"""paddle_tpu — a TPU-native deep learning framework with PaddlePaddle's
capabilities (reference: yangyu18/Paddle), built on JAX/XLA/Pallas.

Not a port: eager tensors are jax.Arrays, autograd is functional
(`paddle_tpu.grad`), compilation is `paddle_tpu.jit.to_static` == jax.jit,
and distribution is GSPMD mesh sharding instead of NCCL process groups.
See SURVEY.md for the subsystem-by-subsystem mapping.
"""
import jax as _jax

from . import dtypes
from .dtypes import (bfloat16, bool_, float16, float32, float64, int8, int16,
                     int32, int64, uint8)
from .tensor import *  # noqa: F401,F403 — paddle flat namespace parity
from .tensor import Tensor
from .utils.rng import get_rng_state, seed, set_rng_state

# functional transforms (TPU-first autograd surface)
grad = _jax.grad
value_and_grad = _jax.value_and_grad
vmap = _jax.vmap
jvp = _jax.jvp
vjp = _jax.vjp


def no_grad(fn=None):
    """paddle.no_grad parity. In a functional-autograd world gradients only
    flow where jax.grad is applied, so this is a stop_gradient marker used
    for API compatibility (usable as decorator or context manager)."""
    import contextlib
    if fn is None:
        return contextlib.nullcontext()
    return fn


def stop_gradient(x):
    return _jax.lax.stop_gradient(x)


from . import amp  # noqa: E402
from . import autograd  # noqa: E402
from . import distribution  # noqa: E402
from . import fft  # noqa: E402
from . import linalg  # noqa: E402
from . import signal  # noqa: E402
from . import tokenizer  # noqa: E402
from . import distributed  # noqa: E402
from . import io  # noqa: E402
from . import jit  # noqa: E402
from . import nn  # noqa: E402
from . import optimizer  # noqa: E402
from . import inference  # noqa: E402
from . import metric  # noqa: E402
from . import peft  # noqa: E402
from . import sparse  # noqa: E402
from . import static  # noqa: E402
from . import trl  # noqa: E402
from . import audio  # noqa: E402
from . import incubate  # noqa: E402
from . import vision  # noqa: E402
from . import quant  # noqa: E402
from . import serving  # noqa: E402
from .checkpoint import load, save  # noqa: E402
from .hapi import Model, summary  # noqa: E402
from . import callbacks  # noqa: E402

__version__ = "0.1.0"


def device_count():
    return len(_jax.devices())


def get_device():
    d = _jax.devices()[0]
    return f"{d.platform}:{d.id}"


def is_compiled_with_cuda():
    return False


def is_compiled_with_xpu():
    return True  # TPU is the accelerator


def set_default_dtype(dtype):
    from .dtypes import to_dtype
    _jax.config.update("jax_default_dtype_bits", "32")
    return to_dtype(dtype)


def get_default_dtype():
    return float32
