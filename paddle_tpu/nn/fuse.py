"""Serving-time projection fusion (reference: PaddleNLP's
``fuse_attention_qkv`` / ``fuse_attention_ffn`` flags on the Llama
family).

Decode is HBM-bound: each token step reads every weight matrix once, and
launching q/k/v (and gate/up) as separate small matmuls leaves MXU tiles
idle while XLA cannot always merge them horizontally. ``fuse_projections``
rewrites a loaded model IN PLACE — concat the q/k/v weights into one
``[h, (nh + 2*kvh) * d]`` matmul and gate/up into one ``[h, 2*ffn]`` —
the attention/MLP forwards detect the fused module and split the single
product.

TP-safe via a RANK-INTERLEAVED column order: with an active ``tp`` mesh
axis of degree T, the fused columns are laid out as
``[q_0 k_0 v_0 | q_1 k_1 v_1 | ...]`` where ``x_t`` is rank t's head
shard of projection x. A (None, "tp") partition then puts exactly
``q_t|k_t|v_t`` on device t — the same columns the unfused layout puts
there — so the split in the forward (a reshape exposing the T axis, a
shard-local slice, a reshape back) never crosses a shard boundary and
no resharding collective is inserted. T is recorded on the module
(``_fused_tp``); T == 1 degenerates to the plain concat.

Apply AFTER from_pretrained / checkpoint load (the pass consumes the
unfused weights), like the quantization pass, and after the serving mesh
is set (the layout bakes in the tp degree).
"""
from __future__ import annotations

import jax.numpy as jnp

from ..parallel.layers import ColumnParallelLinear

__all__ = ["fuse_projections"]


def _tp_degree() -> int:
    from ..distributed.env import get_mesh, has_mesh
    return get_mesh().shape.get("tp", 1) if has_mesh() else 1


def _interleave(ws, tp: int):
    """Concat [h, out_i] weights column-wise, rank-interleaved: reshape
    each to [h, tp, out_i/tp], concat the shard axis, flatten."""
    if tp == 1:
        return jnp.concatenate(ws, axis=1)
    parts = []
    for w in ws:
        if w.shape[1] % tp:
            raise ValueError(
                f"fuse_projections: out dim {w.shape[1]} not divisible "
                f"by tp degree {tp}; keep the unfused layout")
        parts.append(w.reshape(w.shape[0], tp, w.shape[1] // tp))
    return jnp.concatenate(parts, axis=2).reshape(ws[0].shape[0], -1)


def _interleave_bias(bs, tp: int):
    if tp == 1:
        return jnp.concatenate(bs)
    return jnp.concatenate(
        [b.reshape(tp, b.shape[0] // tp) for b in bs], axis=1).reshape(-1)


def _fuse_linears(mods, has_bias: bool, tp: int):
    """Concat N same-input ColumnParallelLinear along the out dim."""
    from . import initializer as I
    w = _interleave([m.weight for m in mods], tp)
    # Constant init: no random matrix materialized, no global RNG key
    # consumed — the fused weight overwrites it immediately
    fused = ColumnParallelLinear(w.shape[0], w.shape[1],
                                 weight_attr=I.Constant(0.0),
                                 has_bias=has_bias, gather_output=False)
    fused.weight = w
    if has_bias:
        fused.bias = _interleave_bias([m.bias for m in mods], tp)
    return fused


def fuse_projections(model, attention: bool = True, mlp: bool = True):
    """Fuse q/k/v (and gate/up) projections of every Llama-family block
    of ``model`` in place; returns the model. Idempotent. The active
    mesh's tp degree is baked into the fused column order (see module
    docstring)."""
    tp = _tp_degree()
    if tp > 1:
        # validate BEFORE mutating: a mid-pass failure would leave the
        # model half-fused with the unfused weights already deleted
        cfg = model.config
        if attention and (cfg.num_attention_heads % tp
                          or cfg.num_key_value_heads % tp):
            raise ValueError(
                f"fuse_projections: heads ({cfg.num_attention_heads}q/"
                f"{cfg.num_key_value_heads}kv) not divisible by tp "
                f"degree {tp}")
        if mlp and cfg.intermediate_size % tp:
            raise ValueError(
                f"fuse_projections: intermediate_size "
                f"{cfg.intermediate_size} not divisible by tp degree {tp}")
    for layer in getattr(model, "model", model).layers:
        attn = getattr(layer, "self_attn", None)
        if attention and attn is not None and \
                hasattr(attn, "q_proj") and not hasattr(attn, "qkv_proj"):
            has_bias = attn.q_proj.bias is not None
            attn.qkv_proj = _fuse_linears(
                [attn.q_proj, attn.k_proj, attn.v_proj], has_bias, tp)
            attn._fused_tp = tp
            del attn.q_proj, attn.k_proj, attn.v_proj
        mlp_mod = getattr(layer, "mlp", None)
        if mlp and mlp_mod is not None and \
                hasattr(mlp_mod, "gate_proj") and \
                not hasattr(mlp_mod, "gate_up_proj"):
            mlp_mod.gate_up_proj = _fuse_linears(
                [mlp_mod.gate_proj, mlp_mod.up_proj], False, tp)
            mlp_mod._fused_tp = tp
            del mlp_mod.gate_proj, mlp_mod.up_proj
    return model
