"""paddle.callbacks parity (reference: python/paddle/hapi/callbacks —
EarlyStopping, ModelCheckpoint, LRScheduler, ProgBarLogger subset).

These target ``paddle_tpu.Model.fit``, which invokes
``on_train_batch_end(step, logs)`` at log points and
``on_epoch_end(epoch, logs)`` per epoch (duck-typed). The low-level
``Trainer`` fires only ``on_step_end``/``on_save``/``on_train_end`` and
has no epoch concept, so the epoch-driven callbacks here (EarlyStopping,
ModelCheckpoint) do NOT function there — use TrainingArguments'
save_steps / the watchdog instead. State is pure-host: the jitted step
never sees callbacks.
"""
from __future__ import annotations

import os
from typing import Optional

__all__ = ["Callback", "EarlyStopping", "ModelCheckpoint", "LRScheduler"]


class Callback:
    """Base: all hooks optional (reference: paddle.callbacks.Callback)."""

    def set_model(self, model):
        self.model = model

    def on_train_batch_end(self, step: int, logs=None):
        pass

    def on_epoch_end(self, epoch: int, logs=None):
        pass

    # Trainer-protocol aliases
    def on_step_end(self, step: int, logs=None):
        self.on_train_batch_end(step, logs)

    def on_save(self, step: int):
        pass

    def on_train_end(self, step: int):
        pass


class EarlyStopping(Callback):
    """Stop when a monitored metric stops improving (reference:
    paddle.callbacks.EarlyStopping). Raising ``StopTraining`` is not an
    option inside a jitted loop, so the callback sets ``stop_training``
    and the host loop (or the user's loop) checks it; with
    ``raise_on_stop=True`` it raises StopIteration, which Model.fit's
    try/finally handles cleanly."""

    def __init__(self, monitor: str = "loss", mode: str = "min",
                 patience: int = 3, min_delta: float = 0.0,
                 baseline: Optional[float] = None,
                 raise_on_stop: bool = True):
        if mode not in ("min", "max"):
            raise ValueError(f"mode must be min|max, got {mode!r}")
        self.monitor, self.mode = monitor, mode
        self.patience, self.min_delta = patience, min_delta
        self.best = baseline if baseline is not None else (
            float("inf") if mode == "min" else -float("inf"))
        self.wait = 0
        self.stop_training = False
        self.raise_on_stop = raise_on_stop
        self.stopped_epoch: Optional[int] = None

    def _improved(self, value: float) -> bool:
        if self.mode == "min":
            return value < self.best - self.min_delta
        return value > self.best + self.min_delta

    def on_epoch_end(self, epoch: int, logs=None):
        logs = logs or {}
        if self.monitor not in logs:
            return
        value = float(logs[self.monitor])
        if self._improved(value):
            self.best = value
            self.wait = 0
            return
        self.wait += 1
        if self.wait >= self.patience:
            self.stop_training = True
            self.stopped_epoch = epoch
            if self.raise_on_stop:
                raise StopIteration(
                    f"EarlyStopping: no {self.monitor} improvement for "
                    f"{self.patience} epochs (best {self.best:.6g})")


class ModelCheckpoint(Callback):
    """Save the model every N epochs / on metric improvement (reference:
    paddle.callbacks.ModelCheckpoint). Works with paddle_tpu.Model (its
    .save) or any Layer (state_dict via paddle_tpu.save)."""

    def __init__(self, save_dir: str, save_freq: int = 1,
                 monitor: Optional[str] = None, mode: str = "min"):
        self.save_dir = save_dir
        self.save_freq = save_freq
        self.monitor = monitor
        self.mode = mode
        self.best = float("inf") if mode == "min" else -float("inf")
        self.saved = []

    def _save(self, tag: str):
        os.makedirs(self.save_dir, exist_ok=True)
        path = os.path.join(self.save_dir, tag)
        model = getattr(self, "model", None)
        if model is None:
            raise RuntimeError(
                "ModelCheckpoint has no model attached — it only works "
                "under Model.fit (which calls set_model); the Trainer "
                "saves via TrainingArguments(save_steps=...) instead")
        if hasattr(model, "save"):          # paddle_tpu.Model
            model.save(path)
        else:                               # bare Layer
            from .checkpoint import save as _save
            _save(model.state_dict(), path + ".pdparams")
        self.saved.append(path)

    def on_epoch_end(self, epoch: int, logs=None):
        logs = logs or {}
        if self.monitor is not None:
            if self.monitor not in logs:
                return
            v = float(logs[self.monitor])
            better = v < self.best if self.mode == "min" else v > self.best
            if not better:
                return
            self.best = v
            self._save("best")
            return
        if (epoch + 1) % self.save_freq == 0:
            self._save(f"epoch_{epoch}")


class LRScheduler(Callback):
    """Step a manually-driven LR scheduler each epoch (reference:
    paddle.callbacks.LRScheduler). ``by_epoch=False`` steps per TRAINING
    step: Model.fit only fires the batch hook every log_freq steps, so
    the callback steps the scheduler by the observed step delta rather
    than once per invocation — the LR trajectory stays correct under any
    logging cadence."""

    def __init__(self, scheduler, by_epoch: bool = True):
        self.scheduler = scheduler
        self.by_epoch = by_epoch
        self._last_step = 0

    def on_epoch_end(self, epoch: int, logs=None):
        if self.by_epoch:
            self.scheduler.step()

    def on_train_batch_end(self, step: int, logs=None):
        if not self.by_epoch:
            for _ in range(step - self._last_step):
                self.scheduler.step()
            self._last_step = step
