"""PP-OCR models (reference: PaddleOCR ppocr/modeling — det_db.py DBNet
{backbone→DBFPN→DBHead}, rec_svtrnet.py SVTR {conv stem, local/global
mixing blocks, CTC head}; losses det_db_loss.py / rec_ctc_loss.py).

TPU-native design: DBNet rides the shared ResNet backbone; its FPN and
head are plain conv stacks (MXU GEMMs). Hard-negative mining in the DB
loss is rewritten shape-statically: instead of a data-dependent top-k
gather, negatives are ranked with a differentiable sort mask so the jit
program has one shape for every batch. SVTR's mixing blocks reuse
``dense_attention``; height is collapsed by strided convs so the CTC time
axis is the image width — all static.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, List

import jax
import jax.numpy as jnp

from .. import nn
from ..nn import functional as F
from ..nn.layer import Layer
from ..ops.attention import dense_attention
from .resnet import ResNet, ResNetConfig


# ------------------------------------------------------------------- DBNet

@dataclass
class DBNetConfig:
    backbone: ResNetConfig = field(
        default_factory=lambda: ResNetConfig(depth=18))
    fpn_channels: int = 256
    head_channels: int = 64
    k: float = 50.0               # differentiable-binarization steepness
    dtype: Any = jnp.float32


def dbnet_tiny(**overrides) -> DBNetConfig:
    base = dict(backbone=ResNetConfig(depth=18, stem_width=8,
                                      layers=[1, 1, 1, 1]),
                fpn_channels=16, head_channels=8)
    base.update(overrides)
    return DBNetConfig(**base)


class DBFPN(Layer):
    """Top-down FPN: lateral 1x1 → upsample-add → per-level 3x3 smooth to
    C/4 channels → upsample all to 1/4 scale and concat."""

    def __init__(self, in_channels: List[int], out_ch: int):
        super().__init__()
        self.lateral = nn.LayerList(
            [nn.Conv2D(c, out_ch, 1, bias_attr=False) for c in in_channels])
        self.smooth = nn.LayerList(
            [nn.Conv2D(out_ch, out_ch // 4, 3, padding=1, bias_attr=False)
             for _ in in_channels])

    def forward(self, feats):
        lat = [conv(f) for conv, f in zip(self.lateral, feats)]
        for i in range(len(lat) - 2, -1, -1):
            lat[i] = lat[i] + F.interpolate(lat[i + 1], scale_factor=2,
                                            mode="nearest")
        outs = []
        for i, (conv, f) in enumerate(zip(self.smooth, lat)):
            o = conv(f)
            if i > 0:
                o = F.interpolate(o, scale_factor=2 ** i, mode="nearest")
            outs.append(o)
        return jnp.concatenate(outs, axis=1)


class DBHead(Layer):
    """conv-BN-relu → 2x deconv → 2x deconv → sigmoid map (shared shape for
    the probability and threshold branches)."""

    def __init__(self, in_ch: int, mid_ch: int):
        super().__init__()
        self.conv = nn.Conv2D(in_ch, mid_ch, 3, padding=1, bias_attr=False)
        self.bn = nn.BatchNorm2D(mid_ch)
        self.up1 = nn.Conv2DTranspose(mid_ch, mid_ch, 2, stride=2)
        self.bn1 = nn.BatchNorm2D(mid_ch)
        self.up2 = nn.Conv2DTranspose(mid_ch, 1, 2, stride=2)

    def forward(self, x):
        x = F.relu(self.bn(self.conv(x)))
        x = F.relu(self.bn1(self.up1(x)))
        return F.sigmoid(self.up2(x))


class DBNet(Layer):
    def __init__(self, config: DBNetConfig):
        super().__init__()
        self.config = config
        self.backbone = ResNet(config.backbone)
        self.fpn = DBFPN(self.backbone.out_channels, config.fpn_channels)
        self.prob_head = DBHead(config.fpn_channels, config.head_channels)
        self.thresh_head = DBHead(config.fpn_channels, config.head_channels)
        if config.dtype != jnp.float32:
            self.to(dtype=config.dtype)

    def forward(self, images):
        feats = self.backbone(images, return_feats=True)
        fused = self.fpn(feats)
        prob = self.prob_head(fused)
        thresh = self.thresh_head(fused)
        # differentiable binarization: B = 1 / (1 + exp(-k (P - T)))
        binary = F.sigmoid(self.config.k * (prob - thresh))
        return {"maps": jnp.concatenate([prob, thresh, binary], axis=1)}


def db_loss(pred, shrink_map, shrink_mask, thresh_map, thresh_mask,
            alpha: float = 5.0, beta: float = 10.0, ohem_ratio: float = 3.0):
    """DB loss = BCE(shrink, hard-negative-mined) + alpha*dice(binary)
    + beta*L1(threshold). The OHEM top-k over negatives is done with a
    static-shape rank mask (sorted losses + cutoff index) instead of a
    dynamic gather (reference: ppocr det_basic_loss BalanceLoss)."""
    maps = pred["maps"].astype(jnp.float32)
    prob, thresh, binary = maps[:, 0], maps[:, 1], maps[:, 2]

    eps = 1e-6
    bce = -(shrink_map * jnp.log(prob + eps)
            + (1 - shrink_map) * jnp.log(1 - prob + eps))
    pos = shrink_map * shrink_mask
    neg = (1 - shrink_map) * shrink_mask
    n_pos = jnp.sum(pos, axis=(1, 2))
    n_neg_keep = jnp.minimum(jnp.sum(neg, axis=(1, 2)),
                             n_pos * ohem_ratio).astype(jnp.int32)
    neg_loss = (bce * neg).reshape(bce.shape[0], -1)
    ranked = jnp.sort(neg_loss, axis=1)[:, ::-1]       # descending
    idx = jnp.arange(ranked.shape[1])[None, :]
    kept = jnp.where(idx < n_neg_keep[:, None], ranked, 0.0)
    balance_bce = (jnp.sum(bce * pos, axis=(1, 2)) + jnp.sum(kept, axis=1)) \
        / (n_pos + n_neg_keep + eps)

    inter = jnp.sum(binary * shrink_map * shrink_mask, axis=(1, 2))
    union = jnp.sum(binary * shrink_mask, axis=(1, 2)) \
        + jnp.sum(shrink_map * shrink_mask, axis=(1, 2))
    dice = 1.0 - 2.0 * inter / (union + eps)

    l1 = jnp.sum(jnp.abs(thresh - thresh_map) * thresh_mask, axis=(1, 2)) \
        / (jnp.sum(thresh_mask, axis=(1, 2)) + eps)

    return jnp.mean(balance_bce + alpha * dice + beta * l1)


# -------------------------------------------------------------------- SVTR

@dataclass
class SVTRConfig:
    img_height: int = 32
    img_width: int = 128
    in_channels: int = 3
    hidden_size: int = 96
    num_hidden_layers: int = 6
    num_attention_heads: int = 3
    mlp_ratio: float = 4.0
    num_classes: int = 6625       # charset + blank at index 0
    local_window: int = 7
    mixer: List[str] = field(default_factory=list)  # per-layer Local/Global
    dtype: Any = jnp.float32

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_attention_heads


def svtr_tiny(**overrides) -> SVTRConfig:
    base = dict(img_height=16, img_width=32, hidden_size=24,
                num_hidden_layers=2, num_attention_heads=2, num_classes=40)
    base.update(overrides)
    return SVTRConfig(**base)


class SVTRMixingBlock(Layer):
    """Pre-LN block; 'Local' mixing restricts attention to a sliding
    window with a static additive mask, 'Global' is full attention."""

    def __init__(self, cfg: SVTRConfig, mixer: str, seq_len: int):
        super().__init__()
        self.cfg, self.mixer = cfg, mixer
        h = cfg.hidden_size
        self.norm1 = nn.LayerNorm(h, epsilon=1e-6)
        self.qkv = nn.Linear(h, 3 * h)
        self.proj = nn.Linear(h, h)
        self.norm2 = nn.LayerNorm(h, epsilon=1e-6)
        mlp = int(h * cfg.mlp_ratio)
        self.fc1 = nn.Linear(h, mlp)
        self.fc2 = nn.Linear(mlp, h)
        if mixer == "Local":
            idx = jnp.arange(seq_len)
            band = jnp.abs(idx[:, None] - idx[None, :]) <= cfg.local_window // 2
            self.register_buffer(
                "local_bias",
                jnp.where(band, 0.0, -1e9)[None, None].astype(jnp.float32),
                persistable=False)

    def forward(self, x):
        cfg = self.cfg
        b, s, _ = x.shape
        nh, d = cfg.num_attention_heads, cfg.head_dim
        qkv = self.qkv(self.norm1(x)).reshape(b, s, 3, nh, d)
        mask = self.local_bias if self.mixer == "Local" else None
        out = dense_attention(qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2],
                              causal=False, attn_mask=mask)
        x = x + self.proj(out.reshape(b, s, nh * d))
        return x + self.fc2(F.gelu(self.fc1(self.norm2(x))))


class SVTRNet(Layer):
    """Recognition backbone + CTC head. The conv stem downsamples H by 4
    and W by 4; tokens are the H/4 x W/4 grid; a final height-collapse
    pooling leaves [b, W/4, C] for CTC over the width axis."""

    def __init__(self, config: SVTRConfig):
        super().__init__()
        self.config = config
        h = config.hidden_size
        self.stem = nn.Sequential(
            nn.Conv2D(config.in_channels, h // 2, 3, stride=2, padding=1),
            nn.GELU(),
            nn.Conv2D(h // 2, h, 3, stride=2, padding=1),
            nn.GELU())
        gh, gw = config.img_height // 4, config.img_width // 4
        self.grid = (gh, gw)
        from ..nn import initializer as I
        from ..nn.layer import Parameter
        from ..utils.rng import next_key
        self.pos_embed = Parameter(
            I.TruncatedNormal(std=0.02)(next_key(), (1, gh * gw, h)))
        mixers = config.mixer or (
            ["Local"] * (config.num_hidden_layers // 2)
            + ["Global"] * (config.num_hidden_layers
                            - config.num_hidden_layers // 2))
        self.blocks = nn.LayerList(
            [SVTRMixingBlock(config, m, gh * gw) for m in mixers])
        self.norm = nn.LayerNorm(h, epsilon=1e-6)
        self.head = nn.Linear(h, config.num_classes)
        if config.dtype != jnp.float32:
            self.to(dtype=config.dtype)

    def forward(self, images):
        x = self.stem(images)                  # [b, h, gh, gw]
        b, c, gh, gw = x.shape
        x = x.reshape(b, c, gh * gw).transpose(0, 2, 1) + \
            self.pos_embed.astype(x.dtype)
        for block in self.blocks:
            x = block(x)
        x = self.norm(x)
        x = x.reshape(b, gh, gw, c).mean(axis=1)   # collapse height
        return self.head(x).astype(jnp.float32)    # [b, gw, num_classes]


def ctc_rec_loss(logits, labels, label_lengths=None):
    """Recognition loss (reference: ppocr rec_ctc_loss)."""
    return F.ctc_loss(logits, labels, label_lengths=label_lengths, blank=0)


def ctc_greedy_decode(logits):
    """Best-path decode: argmax → collapse repeats → drop blanks. Returns
    (ids, mask) with static shapes; mask marks surviving positions."""
    ids = jnp.argmax(logits, axis=-1)
    prev = jnp.pad(ids, ((0, 0), (1, 0)), constant_values=-1)[:, :-1]
    keep = (ids != 0) & (ids != prev)
    return ids, keep
