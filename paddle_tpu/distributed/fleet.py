"""Fleet facade (reference: python/paddle/distributed/fleet/__init__.py —
fleet.init(strategy), DistributedStrategy with hybrid_configs, and the
distributed_model/distributed_optimizer wrappers).

TPU-native: a DistributedStrategy is a declarative mesh recipe. ``init``
builds the global `jax.sharding.Mesh` from the hybrid degrees; there is no
process-group bootstrapping, no NCCL communicators — GSPMD + shard_map use
the mesh directly. `distributed_model` shards a Layer's parameters onto the
mesh (ZeRO via the fsdp axis per sharding stage); `distributed_optimizer`
is an identity that records the strategy (optimizer state inherits param
shardings in the functional core, which is exactly ZeRO stage-1).
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, Optional

import jax

from ..nn.layer import Layer
from . import env


@dataclass
class DistributedStrategy:
    """Reference: paddle.distributed.fleet.DistributedStrategy protobuf.
    hybrid_configs maps fleet's degree names onto mesh axes:
        dp_degree -> "dp", sharding_degree -> "fsdp", mp_degree -> "tp",
        pp_degree -> "pp", sep_degree -> "sp", ep_degree -> "ep".
    sharding_stage: 1 = opt-state sharded, 2 = +grads, 3 = +params
    (all expressed as fsdp-axis shardings; see parallel.sharding).
    """
    hybrid_configs: Dict[str, int] = field(default_factory=dict)
    sharding_stage: int = 1
    amp: bool = False
    amp_level: str = "O1"
    recompute: bool = False
    gradient_merge_steps: int = 1
    find_unused_parameters: bool = False  # accepted for parity; meaningless here

    _DEGREE_TO_AXIS = {
        "dp_degree": "dp", "sharding_degree": "fsdp", "mp_degree": "tp",
        "pp_degree": "pp", "sep_degree": "sp", "ep_degree": "ep",
    }

    def mesh_shape(self) -> Dict[str, int]:
        out = {}
        for k, v in self.hybrid_configs.items():
            axis = self._DEGREE_TO_AXIS.get(k, k)
            if axis not in env.HYBRID_AXES:
                raise ValueError(f"unknown hybrid axis {k!r}")
            if v and v > 1:
                out[axis] = int(v)
        return out


_strategy: Optional[DistributedStrategy] = None


def init(is_collective: bool = True, strategy: Optional[DistributedStrategy] = None):
    """fleet.init parity: install the global mesh from the strategy."""
    global _strategy
    _strategy = strategy or DistributedStrategy()
    env.init_parallel_env(_strategy.mesh_shape())
    return _strategy


def get_strategy() -> DistributedStrategy:
    return _strategy or DistributedStrategy()


def distributed_model(model: Layer, fsdp_min_size: Optional[int] = None) -> Layer:
    """Shard the model's parameters onto the installed mesh. Stage 3 shards
    every eligible param on fsdp; stages 1/2 keep params replicated over
    fsdp (their opt-state/grad sharding happens in the Trainer)."""
    from ..parallel.sharding import shard_layer
    st = get_strategy()
    if fsdp_min_size is None:
        fsdp_min_size = 2 ** 16 if st.sharding_stage >= 3 else (1 << 62)
    shard_layer(model, fsdp_min_size=fsdp_min_size)
    return model


def distributed_optimizer(optimizer, strategy: Optional[DistributedStrategy] = None):
    if strategy is not None:
        global _strategy
        _strategy = strategy
    return optimizer


def worker_num() -> int:
    return jax.process_count()


def worker_index() -> int:
    return jax.process_index()


def is_first_worker() -> bool:
    return jax.process_index() == 0
