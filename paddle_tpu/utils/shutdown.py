"""Graceful preemption shutdown (reference: paddle.distributed.elastic's
signal handling; cloud-TPU preemption notices arrive as SIGTERM with a
~30s grace window).

Contract: the scheduler says "you are going away" (SIGTERM/SIGINT, or a
deterministic ``preempt`` fault injection in tests); the training loop
polls ``requested()`` at step boundaries, checkpoints synchronously,
drains the async writer, and exits with ``PREEMPTED_RC``. The elastic
supervisor (`distributed.elastic.supervise`) recognizes that code as
*always restartable* — a preemption is not a failure and never consumes
a ``max_restarts`` attempt.

Why a distinct exit code: death-by-signal (negative rc) means the grace
window was missed and the latest *periodic* checkpoint stands; rc ==
PREEMPTED_RC means the child checkpointed its exact current step first,
so the relaunch resumes with zero lost work.
"""
from __future__ import annotations

import signal
import sys
import threading
from typing import Optional, Tuple

__all__ = ["GracefulShutdown", "PREEMPTED_RC"]

# Deliberately outside the shell (1/2/126/127) and signal (128+n) ranges
# and distinct from the hang path's default 17.
PREEMPTED_RC = 76


class GracefulShutdown:
    """Latch a shutdown request from SIGTERM/SIGINT (or programmatic
    ``request()``) for a polling loop to observe at a safe boundary.

    The handler only *records* the request — all heavy work (checkpoint,
    drain, exit) happens on the polling thread, where it is safe to call
    into jax/orbax. ``install()`` is a no-op off the main thread (signal
    handlers are main-thread-only in CPython); the fault-injection
    channel still works there.
    """

    def __init__(self, signals: Tuple[int, ...] = (signal.SIGTERM,
                                                   signal.SIGINT)):
        self._signals = signals
        self._event = threading.Event()
        self.reason: Optional[str] = None
        self._prev: dict = {}

    # ------------------------------------------------------------ handlers
    def install(self) -> "GracefulShutdown":
        if self._prev:
            return self
        for sig in self._signals:
            try:
                self._prev[sig] = signal.signal(sig, self._on_signal)
            except ValueError:      # not the main thread: poll-only mode
                self._prev.pop(sig, None)
                break
        return self

    def uninstall(self):
        for sig, prev in self._prev.items():
            try:
                signal.signal(sig, prev)
            except ValueError:
                pass
        self._prev = {}

    def _on_signal(self, signum, frame):  # noqa: ARG002
        if self._event.is_set() and signum == signal.SIGINT:
            # second ^C: the user wants OUT now, not another grace period
            raise KeyboardInterrupt
        self.request(f"signal {signal.Signals(signum).name}")

    # ------------------------------------------------------------- control
    def request(self, reason: str = "requested"):
        """Latch a shutdown request (idempotent; first reason wins)."""
        if not self._event.is_set():
            self.reason = reason
            print(f"[shutdown] graceful shutdown requested ({reason}); "
                  f"will checkpoint and exit at the next step boundary",
                  file=sys.stderr, flush=True)
            try:   # flight recorder: the latch is the postmortem anchor
                from . import observability as obs
                obs.record_event("preempt_latch", reason=reason)
            except Exception:
                pass
        self._event.set()

    def requested(self) -> bool:
        return self._event.is_set()

    def clear(self):
        """Reset the latch (tests / reuse across train() calls)."""
        self._event.clear()
        self.reason = None

    # ------------------------------------------------------ context manager
    def __enter__(self) -> "GracefulShutdown":
        return self.install()

    def __exit__(self, *exc):
        self.uninstall()
        return False
