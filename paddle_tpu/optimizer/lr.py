"""LR schedulers (reference: python/paddle/optimizer/lr.py). Each scheduler
is `sched(step) -> lr` in pure jnp so it traces into the jitted train step
(no host round-trip per step). The stateful paddle API (`.step()`,
`.get_lr()`) is layered on top for parity.
"""
from __future__ import annotations

import math

import jax.numpy as jnp


class LRScheduler:
    def __init__(self, learning_rate=0.1, last_epoch=-1):
        self.base_lr = learning_rate
        self.last_epoch = last_epoch
        self.step()  # paddle semantics: init advances to epoch 0

    # functional core — override this
    def value_at(self, step):
        return jnp.asarray(self.base_lr, dtype=jnp.float32)

    # stateful facade
    def step(self, epoch=None):
        self.last_epoch = epoch if epoch is not None else self.last_epoch + 1

    def get_lr(self):
        return float(self.value_at(jnp.asarray(max(self.last_epoch, 0))))

    def __call__(self, step):
        return self.value_at(step)

    def state_dict(self):
        return {"last_epoch": self.last_epoch}

    def set_state_dict(self, state):
        self.last_epoch = state["last_epoch"]


class NoamDecay(LRScheduler):
    def __init__(self, d_model, warmup_steps, learning_rate=1.0, last_epoch=-1):
        self.d_model, self.warmup_steps = d_model, warmup_steps
        super().__init__(learning_rate, last_epoch)

    def value_at(self, step):
        s = jnp.maximum(step.astype(jnp.float32) if hasattr(step, "astype") else jnp.float32(step), 1.0)
        return self.base_lr * self.d_model ** -0.5 * jnp.minimum(
            s ** -0.5, s * self.warmup_steps ** -1.5)


class PiecewiseDecay(LRScheduler):
    def __init__(self, boundaries, values, last_epoch=-1):
        self.boundaries = jnp.asarray(boundaries)
        self.values = jnp.asarray(values, dtype=jnp.float32)
        super().__init__(float(values[0]), last_epoch)

    def value_at(self, step):
        idx = jnp.searchsorted(self.boundaries, step, side="right")
        return self.values[idx]


class ExponentialDecay(LRScheduler):
    def __init__(self, learning_rate, gamma, last_epoch=-1):
        self.gamma = gamma
        super().__init__(learning_rate, last_epoch)

    def value_at(self, step):
        return self.base_lr * jnp.power(self.gamma, step)


class NaturalExpDecay(LRScheduler):
    def __init__(self, learning_rate, gamma, last_epoch=-1):
        self.gamma = gamma
        super().__init__(learning_rate, last_epoch)

    def value_at(self, step):
        return self.base_lr * jnp.exp(-self.gamma * step)


class InverseTimeDecay(LRScheduler):
    def __init__(self, learning_rate, gamma, last_epoch=-1):
        self.gamma = gamma
        super().__init__(learning_rate, last_epoch)

    def value_at(self, step):
        return self.base_lr / (1 + self.gamma * step)


class PolynomialDecay(LRScheduler):
    def __init__(self, learning_rate, decay_steps, end_lr=0.0001, power=1.0,
                 cycle=False, last_epoch=-1):
        self.decay_steps, self.end_lr, self.power, self.cycle = \
            decay_steps, end_lr, power, cycle
        super().__init__(learning_rate, last_epoch)

    def value_at(self, step):
        step = jnp.asarray(step, dtype=jnp.float32)
        if self.cycle:
            decay_steps = self.decay_steps * jnp.maximum(
                jnp.ceil(step / self.decay_steps), 1.0)
        else:
            decay_steps = self.decay_steps
            step = jnp.minimum(step, decay_steps)
        frac = (1 - step / decay_steps) ** self.power
        return (self.base_lr - self.end_lr) * frac + self.end_lr


class LinearWarmup(LRScheduler):
    def __init__(self, learning_rate, warmup_steps, start_lr=0.0, end_lr=None,
                 last_epoch=-1):
        self.inner = learning_rate if isinstance(learning_rate, LRScheduler) else None
        self.warmup_steps = warmup_steps
        self.start_lr = start_lr
        self.end_lr = end_lr if end_lr is not None else (
            self.inner.base_lr if self.inner else float(learning_rate))
        base = self.inner.base_lr if self.inner else float(learning_rate)
        super().__init__(base, last_epoch)

    def value_at(self, step):
        step = jnp.asarray(step, dtype=jnp.float32)
        warm = self.start_lr + (self.end_lr - self.start_lr) * jnp.minimum(
            step / max(self.warmup_steps, 1), 1.0)
        if self.inner is not None:
            after = self.inner.value_at(jnp.maximum(step - self.warmup_steps, 0))
        else:
            after = jnp.float32(self.end_lr)
        return jnp.where(step < self.warmup_steps, warm, after)


class CosineAnnealingDecay(LRScheduler):
    def __init__(self, learning_rate, T_max, eta_min=0.0, last_epoch=-1):
        self.T_max, self.eta_min = T_max, eta_min
        super().__init__(learning_rate, last_epoch)

    def value_at(self, step):
        step = jnp.asarray(step, dtype=jnp.float32)
        cos = jnp.cos(math.pi * jnp.minimum(step, self.T_max) / self.T_max)
        return self.eta_min + (self.base_lr - self.eta_min) * (1 + cos) / 2


class CosineAnnealingWarmRestarts(LRScheduler):
    def __init__(self, learning_rate, T_0, T_mult=1, eta_min=0.0, last_epoch=-1):
        self.T_0, self.T_mult, self.eta_min = T_0, T_mult, eta_min
        super().__init__(learning_rate, last_epoch)

    def value_at(self, step):
        step = jnp.asarray(step, dtype=jnp.float32)
        if self.T_mult == 1:
            t_cur = jnp.mod(step, self.T_0)
            t_i = self.T_0
        else:
            n = jnp.floor(jnp.log1p(step * (self.T_mult - 1) / self.T_0)
                          / math.log(self.T_mult))
            start = self.T_0 * (jnp.power(self.T_mult, n) - 1) / (self.T_mult - 1)
            t_cur = step - start
            t_i = self.T_0 * jnp.power(self.T_mult, n)
        cos = jnp.cos(math.pi * t_cur / t_i)
        return self.eta_min + (self.base_lr - self.eta_min) * (1 + cos) / 2


class StepDecay(LRScheduler):
    def __init__(self, learning_rate, step_size, gamma=0.1, last_epoch=-1):
        self.step_size, self.gamma = step_size, gamma
        super().__init__(learning_rate, last_epoch)

    def value_at(self, step):
        return self.base_lr * jnp.power(self.gamma, jnp.floor_divide(step, self.step_size))


class MultiStepDecay(LRScheduler):
    def __init__(self, learning_rate, milestones, gamma=0.1, last_epoch=-1):
        self.milestones = jnp.asarray(milestones)
        self.gamma = gamma
        super().__init__(learning_rate, last_epoch)

    def value_at(self, step):
        count = jnp.sum(self.milestones <= step)
        return self.base_lr * jnp.power(self.gamma, count)


class LambdaDecay(LRScheduler):
    def __init__(self, learning_rate, lr_lambda, last_epoch=-1):
        self.lr_lambda = lr_lambda
        super().__init__(learning_rate, last_epoch)

    def value_at(self, step):
        return self.base_lr * self.lr_lambda(step)


class OneCycleLR(LRScheduler):
    def __init__(self, max_learning_rate, total_steps, divide_factor=25.0,
                 end_learning_rate=1e-4, phase_pct=0.3, last_epoch=-1):
        self.total_steps = total_steps
        self.phase_pct = phase_pct
        self.initial_lr = max_learning_rate / divide_factor
        self.end_lr = end_learning_rate
        super().__init__(max_learning_rate, last_epoch)

    def value_at(self, step):
        step = jnp.asarray(step, dtype=jnp.float32)
        up_steps = self.phase_pct * self.total_steps
        down_steps = self.total_steps - up_steps
        up = self.initial_lr + (self.base_lr - self.initial_lr) * (
            1 - jnp.cos(math.pi * jnp.minimum(step, up_steps) / up_steps)) / 2
        t = jnp.clip((step - up_steps) / down_steps, 0, 1)
        down = self.end_lr + (self.base_lr - self.end_lr) * (1 + jnp.cos(math.pi * t)) / 2
        return jnp.where(step < up_steps, up, down)


class ReduceOnPlateau(LRScheduler):
    """Metric-driven (host-side) schedule — inherently stateful; value_at
    returns the current factor-scaled lr."""

    def __init__(self, learning_rate, mode="min", factor=0.1, patience=10,
                 threshold=1e-4, cooldown=0, min_lr=0.0, last_epoch=-1):
        self.mode, self.factor, self.patience = mode, factor, patience
        self.threshold, self.cooldown, self.min_lr = threshold, cooldown, min_lr
        self.best = None
        self.num_bad = 0
        self.cooldown_left = 0
        self.current = learning_rate
        super().__init__(learning_rate, last_epoch)

    def value_at(self, step):
        return jnp.float32(self.current)

    def step(self, metrics=None, epoch=None):
        self.last_epoch += 1
        if metrics is None:
            return
        m = float(metrics)
        better = (self.best is None or
                  (self.mode == "min" and m < self.best - self.threshold) or
                  (self.mode == "max" and m > self.best + self.threshold))
        if better:
            self.best = m
            self.num_bad = 0
        elif self.cooldown_left > 0:
            self.cooldown_left -= 1
        else:
            self.num_bad += 1
            if self.num_bad > self.patience:
                self.current = max(self.current * self.factor, self.min_lr)
                self.cooldown_left = self.cooldown
                self.num_bad = 0
