"""ISSUE 13: multi-host serving fleet — remote replica adapters,
byte-for-byte proxying with cross-process failover, prefix-digest
gossip, closed-loop autoscaling.

Contracts pinned here:

- DIGEST CHAIN: the fleet frontend's standalone
  ``prefix_digest_chain`` equals ``PagedEngine.prefix_digests``
  byte-for-byte (fleet routing keys == engine cache keys).
- GOSSIP: ``GET /debugz/prefix`` exposes the digest-set union with a
  MONOTONIC generation counter; ``?if_gen=N`` answers a tiny
  unchanged-marker when nothing moved (the cheap conditional poll).
- REMOTE SEAM: ``RemoteReplica`` implements the router's duck-typed
  ``healthy``/``load``/``has_prefix`` off cached HTTP probes with a
  STALENESS bound (an unprobed peer goes unhealthy even before the
  failure count evicts it); probe-failure flap evicts and — with a
  breaker attached — rejoin goes through the router's probation
  probe, not merely probes coming back.
- PROXY PARITY: a stream through the FleetFrontend is BYTE-identical
  to a direct connection to the peer gateway (SSE and non-stream).
- REMOTE FAILOVER: a peer dying mid-stream (``peer_conn_drop``)
  resumes on a survivor with tokens BITWISE the uninterrupted run
  (logprobs float-epsilon at the resume boundary — the ISSUE 12
  prefill-vs-decode contract), no duplicated and no missing client
  token; ``failover_budget`` bounds the hops.
- AUTOSCALER: scale-up under sustained pressure, scale-down when
  idle, hysteresis + cooldown mean a flapping signal produces no
  flapping actions; replica-seconds accounting.
- FLEET MERGE: ``trace_report`` joins rings from multiple processes
  by request id and names the hop chain.

Everything tier-1 runs in-process stub gateways as peers (real HTTP
over localhost, no subprocesses); the multi-process loadgen e2e
(spawned ``replica_main`` processes, SIGKILL chaos, autoscaled
diurnal trace) rides behind ``slow`` (``tools/marker_audit.py``
``test_fleet.py.*multiproc``).
"""
import asyncio
import json
import time

import pytest

from paddle_tpu.serving import Gateway, PrefixAffinityRouter
from paddle_tpu.serving.fleet import (FleetAutoscaler, FleetFrontend,
                                      RemoteReplica,
                                      prefix_digest_chain)
from paddle_tpu.serving.supervisor import (BREAKER_CLOSED,
                                           BREAKER_OPEN)
from paddle_tpu.utils import faults

from test_gateway import (_engine, _http, _load_loadgen, _loadgen_ns,
                          _poll, _sse)

PROMPT = list(range(1, 20))          # 2 full chunks + tail at chunk 8


async def _refresh(rep):
    """Synchronous probe off the event loop (the peers serve ON this
    loop; a blocking probe from a coroutine would deadlock them)."""
    return await asyncio.to_thread(rep.refresh)


async def _raw(port, payload, request_id=None):
    """One request, returning the COMPLETE raw response bytes — the
    byte-for-byte proxy-parity probe."""
    reader, writer = await asyncio.open_connection("127.0.0.1", port)
    body = json.dumps(payload).encode()
    rid = f"X-Request-Id: {request_id}\r\n" if request_id else ""
    try:
        writer.write((f"POST /v1/generate HTTP/1.1\r\nHost: t\r\n"
                      f"{rid}Content-Length: {len(body)}\r\n\r\n"
                      ).encode() + body)
        await writer.drain()
        return await asyncio.wait_for(reader.read(), 30)
    finally:
        writer.close()
        try:
            await writer.wait_closed()
        except Exception:
            pass


def _direct(prompt=PROMPT, max_new=12, **kw):
    eng = _engine()
    eng.submit("ref", [prompt], max_new_tokens=max_new, **kw)
    eng.run()
    return eng.results["ref"], eng.logprobs["ref"]


# ============================================================ digest chain
def test_prefix_digest_chain_matches_engine():
    """Fleet routing keys are the engine's cache keys, byte-for-byte —
    computed standalone (the frontend has no engine)."""
    eng = _engine()
    for prompt in (PROMPT, list(range(1, 9)), list(range(1, 45))):
        assert prefix_digest_chain(prompt, 8) \
            == eng.prefix_digests(prompt)
    # cap semantics: at least one live token must remain
    assert prefix_digest_chain(list(range(1, 9)), 8) == []
    assert prefix_digest_chain(PROMPT, 0) == []


# ================================================================== gossip
def test_debugz_prefix_digest_set_and_conditional_fetch():
    """/debugz/prefix: digest-set union + monotonic generation; the
    ``if_gen`` conditional answers the tiny unchanged-marker."""
    async def run():
        eng = _engine()
        gw = Gateway(eng, name="t-gossip")
        await gw.start()
        st, _, toks, fin = await _sse(gw.port,
                                      {"prompt": PROMPT,
                                       "max_new_tokens": 4,
                                       "temperature": 0.0})
        assert st == 200 and fin["finish_reason"] == "stop"
        st, _, doc = await _http(gw.port, "GET", "/debugz/prefix")
        doc = json.loads(doc)
        assert st == 200 and doc["generation"] > 0
        assert doc["entries"] == len(doc["digests"]) > 0
        assert set(doc["digests"]) \
            == {k.hex() for k in eng.prefix_cache}
        gen = doc["generation"]
        # unchanged: the conditional poll skips the digest list
        st, _, doc2 = await _http(gw.port, "GET",
                                  f"/debugz/prefix?if_gen={gen}")
        doc2 = json.loads(doc2)
        assert doc2 == {"generation": gen, "unchanged": True}
        # a different cacheable prompt moves the generation
        await _sse(gw.port, {"prompt": [7] * 30, "max_new_tokens": 4,
                             "temperature": 0.0})
        st, _, doc3 = await _http(gw.port, "GET",
                                  f"/debugz/prefix?if_gen={gen}")
        doc3 = json.loads(doc3)
        assert doc3.get("unchanged") is None
        assert doc3["generation"] > gen
        # the full /debugz carries the same summary
        st, _, dz = await _http(gw.port, "GET", "/debugz")
        assert json.loads(dz)["prefix_digest_set"]["generation"] \
            == doc3["generation"]
        # a supervisor rebuild through engine_factory swaps in a
        # FRESH engine (counter restarts at 0): the gateway's ratchet
        # must keep the exported generation strictly advancing — a
        # regressed-then-recovered sum must never replay an old value
        gw._workers[0].engine.prefix_generation = 0
        st, _, doc4 = await _http(
            gw.port, "GET",
            f"/debugz/prefix?if_gen={doc3['generation']}")
        doc4 = json.loads(doc4)
        assert doc4.get("unchanged") is None
        assert doc4["generation"] > doc3["generation"]
        await gw.drain()
    asyncio.run(run())


def test_remote_replica_probe_gossip_and_warm_routing():
    """The remote seam end-to-end: probes fill the cached snapshot,
    gossip fills the digest set, and the UNMODIFIED router ladder
    places a request on the warm PEER."""
    async def run():
        gws = [Gateway(_engine(), name=f"t-rr{i}") for i in range(2)]
        for gw in gws:
            await gw.start()
        reps = [RemoteReplica(f"p{i}", "127.0.0.1", gw.port)
                for i, gw in enumerate(gws)]
        for r in reps:
            assert await _refresh(r)
            assert r.healthy() and r.load() == 0.0
        # warm ONLY peer 1, then re-gossip
        await _sse(gws[1].port, {"prompt": PROMPT, "max_new_tokens": 4,
                                 "temperature": 0.0})
        for r in reps:
            assert await _refresh(r)
        digest = _engine().prefix_digest(PROMPT)
        assert not reps[0].has_prefix(digest)
        assert reps[1].has_prefix(digest)
        # conditional-fetch accounting: second unchanged poll skipped
        n_unchanged = reps[1].gossip_unchanged_total
        assert await _refresh(reps[1])
        assert reps[1].gossip_unchanged_total == n_unchanged + 1
        router = PrefixAffinityRouter(reps)
        meta = {}
        pick = router.route(
            _engine().prefix_digests(PROMPT)[::-1], meta=meta)
        assert pick is reps[1] and meta["verdict"] == "warm"
        for gw in gws:
            await gw.drain()
    asyncio.run(run())


def test_remote_replica_staleness_bound_and_flap_eviction():
    """A peer whose probes stop landing goes unhealthy two ways:
    consecutive failures flip the latch (opening the breaker), and a
    stale snapshot fails ``healthy()`` on its own."""
    async def run():
        gw = Gateway(_engine(), name="t-stale")
        await gw.start()
        t = [0.0]
        rep = RemoteReplica("p0", "127.0.0.1", gw.port,
                            stale_after_s=2.0, clock=lambda: t[0])
        assert await _refresh(rep)
        assert rep.healthy()
        t[0] = 3.0           # nobody probed for > stale_after_s
        assert not rep.healthy()
        assert rep.signals()["stale"]
        assert not rep.has_prefix("00")   # stale gossip: never warm
        t[0] = 0.0
        assert await _refresh(rep) and rep.healthy()
        await gw.drain()
        # flap: the listener is gone — consecutive failures evict and
        # open the attached breaker exactly once
        from paddle_tpu.serving.supervisor import CircuitBreaker
        rep.breaker = CircuitBreaker(backoff_s=60.0)
        assert not await _refresh(rep)    # 1st failure: still latched
        assert rep._healthy
        assert not await _refresh(rep)    # 2nd: evicted
        assert not rep._healthy
        assert rep.breaker.state == BREAKER_OPEN
        assert rep.breaker.snapshot()["opens"] == 1
        assert not await _refresh(rep)    # more failures don't re-open
        assert rep.breaker.snapshot()["opens"] == 1
    asyncio.run(run())


# ========================================================== proxy parity
def test_fleet_proxy_stream_byte_parity_and_nonstream():
    """A proxied response is BYTE-identical to a direct one — SSE
    head, every token event (token + logprob), the final done event;
    and the non-stream JSON path too."""
    async def run():
        outs = []
        for mode in ("direct", "proxied"):
            gw = Gateway(_engine(), name=f"t-par-{mode}")
            await gw.start()
            port = gw.port
            fe = None
            if mode == "proxied":
                rep = RemoteReplica("p0", "127.0.0.1", gw.port,
                                    probe_interval_s=0.05)
                fe = FleetFrontend([rep], chunk_tokens=8,
                                   name=f"t-flt-{mode}")
                await fe.start()
                await _poll(rep.healthy, 5)
                port = fe.port
            sse = await _raw(port, {"prompt": PROMPT,
                                    "max_new_tokens": 8,
                                    "temperature": 0.0}, "par-1")
            nonstream = await _raw(port, {"prompt": PROMPT,
                                          "max_new_tokens": 8,
                                          "temperature": 0.0,
                                          "stream": False}, "par-2")
            outs.append((sse, nonstream))
            if fe is not None:
                await fe.drain()
            await gw.drain()
        assert outs[0][0] == outs[1][0]      # SSE bytes
        assert b'"lp":' in outs[0][0]        # logprobs ride the events
        assert outs[0][1] == outs[1][1]      # non-stream JSON bytes
    asyncio.run(run())


# ======================================================== remote failover
def test_fleet_midstream_peer_drop_resumes_bitwise():
    """The acceptance pin: a peer severed mid-stream fails over to a
    survivor through the HTTP resume seam — the client sees every
    token exactly once, tokens BITWISE the uninterrupted run, final
    logprobs float-epsilon equal, and the frontend retains the hop
    timeline."""
    ref_toks, ref_lps = _direct()
    async def run():
        gws = [Gateway(_engine(), name=f"t-ko{i}") for i in range(2)]
        for gw in gws:
            await gw.start()
        reps = [RemoteReplica(f"p{i}", "127.0.0.1", gw.port,
                              probe_interval_s=0.05)
                for i, gw in enumerate(gws)]
        fe = FleetFrontend(reps, chunk_tokens=8, name="t-ko",
                           breaker_backoff_s=60.0)
        await fe.start()
        await _poll(lambda: all(r.healthy() for r in reps), 5)
        with faults.scoped("peer_conn_drop@4"):
            st, _, toks, fin = await _sse(
                fe.port, {"prompt": PROMPT, "max_new_tokens": 12,
                          "temperature": 0.0})
        hz = fe.healthz()
        await fe.drain()
        for gw in gws:
            await gw.drain()
        return st, toks, fin, hz, fe
    st, toks, fin, hz, fe = asyncio.run(run())
    assert st == 200
    assert toks == ref_toks                  # no dup, no gap, bitwise
    assert fin["tokens"] == ref_toks
    assert fin["finish_reason"] == "stop"
    assert fin["logprobs"] == pytest.approx(ref_lps)
    assert hz["peer_failovers"] == 1
    assert hz["retry_budget_exhausted"] == 0
    # the dead peer is out, the survivor carried it
    assert sum(v["healthy"] for v in hz["peers"].values()) == 1
    # hop timeline retained on the frontend ring (always, even fast)
    entries = [e for e in fe.ring.snapshot()
               if e["outcome"] == "stop" and e["retained"]]
    assert len(entries) == 1
    kinds = [k for _, k, _ in entries[0]["events"]]
    assert "proxy_to" in kinds and "peer_fail" in kinds \
        and "resume_offset" in kinds
    off = next(f for _, k, f in entries[0]["events"]
               if k == "resume_offset")
    assert off["offset"] == 4                # seen 4, resumed after


def test_fleet_fully_committed_kill_never_errors():
    """A stream severed between its LAST token and the done event is
    complete in the client's hands: the frontend synthesizes the
    final event from the committed prefix BEFORE the budget check —
    even a zero budget never errors a complete result."""
    ref_toks, ref_lps = _direct(max_new=4)
    async def run():
        gws = [Gateway(_engine(), name=f"t-fc{i}") for i in range(2)]
        for gw in gws:
            await gw.start()
        reps = [RemoteReplica(f"p{i}", "127.0.0.1", gw.port,
                              probe_interval_s=0.05)
                for i, gw in enumerate(gws)]
        fe = FleetFrontend(reps, chunk_tokens=8, name="t-fc",
                           failover_budget=0, breaker_backoff_s=60.0)
        await fe.start()
        await _poll(lambda: all(r.healthy() for r in reps), 5)
        # occurrences 0-3 are the 4 token units; @4 severs the done
        with faults.scoped("peer_conn_drop@4"):
            st, _, toks, fin = await _sse(
                fe.port, {"prompt": PROMPT, "max_new_tokens": 4,
                          "temperature": 0.0})
        hz = fe.healthz()
        await fe.drain()
        for gw in gws:
            await gw.drain()
        return st, toks, fin, hz
    st, toks, fin, hz = asyncio.run(run())
    assert st == 200 and toks == ref_toks
    assert fin["finish_reason"] == "stop"
    assert fin["tokens"] == ref_toks
    assert fin["logprobs"] == pytest.approx(ref_lps)
    assert hz["retry_budget_exhausted"] == 0


def test_fleet_failover_budget_exhausted():
    """Every peer keeps dropping: after ``failover_budget`` hops the
    client gets a terminal SSE error event, counted."""
    async def run():
        gws = [Gateway(_engine(), name=f"t-bx{i}") for i in range(2)]
        for gw in gws:
            await gw.start()
        reps = [RemoteReplica(f"p{i}", "127.0.0.1", gw.port,
                              probe_interval_s=0.05)
                for i, gw in enumerate(gws)]
        fe = FleetFrontend(reps, chunk_tokens=8, name="t-bx",
                           failover_budget=1, breaker_backoff_s=60.0)
        await fe.start()
        await _poll(lambda: all(r.healthy() for r in reps), 5)
        with faults.scoped("peer_conn_drop"):     # every occurrence
            st, _, toks, fin = await _sse(
                fe.port, {"prompt": PROMPT, "max_new_tokens": 8,
                          "temperature": 0.0})
        hz = fe.healthz()
        await fe.drain()
        for gw in gws:
            await gw.drain()
        return st, toks, fin, hz
    st, toks, fin, hz = asyncio.run(run())
    assert st == 200 and toks == []          # head sent, then error
    assert fin["done"] and "budget exhausted" in fin["error"]
    assert hz["retry_budget_exhausted"] == 1
    assert hz["peer_failovers"] == 2         # initial + 1 retry


def test_peer_restart_rejoins_through_breaker_probe():
    """Process-restart rejoin: a peer whose port goes dead is evicted
    (breaker OPEN); a new gateway process on the SAME port does NOT
    rejoin by answering probes — the router hands it one probation
    probe, and only the proxied success closes the breaker."""
    async def run():
        gw_a = Gateway(_engine(), name="t-rj-a")
        await gw_a.start()
        port_a = gw_a.port
        gw_b = Gateway(_engine(), name="t-rj-b")
        await gw_b.start()
        reps = [RemoteReplica("pA", "127.0.0.1", port_a,
                              probe_interval_s=0.05,
                              fail_threshold=2),
                RemoteReplica("pB", "127.0.0.1", gw_b.port,
                              probe_interval_s=0.05)]
        fe = FleetFrontend(reps, chunk_tokens=8, name="t-rj",
                           breaker_backoff_s=0.15)
        await fe.start()
        await _poll(lambda: all(r.healthy() for r in reps), 5)
        # kill peer A's process (listener gone, probes fail)
        await gw_a.drain()
        await _poll(lambda: not reps[0].healthy(), 5)
        assert reps[0].breaker.state == BREAKER_OPEN
        payload = {"prompt": PROMPT, "max_new_tokens": 4,
                   "temperature": 0.0}
        st, _, toks, fin = await _sse(fe.port, payload)
        assert st == 200 and fin["finish_reason"] == "stop"
        assert not reps[0].healthy()     # still out: probes dead
        # "restart the process" on the same port
        gw_a2 = Gateway(_engine(), name="t-rj-a2", port=port_a)
        await gw_a2.start()
        await _poll(lambda: reps[0].probe_failures_total > 0
                    and reps[0]._fails == 0, 5)
        assert not reps[0].healthy()     # probes back != rejoined
        # after backoff the next request is peer A's probation probe
        await asyncio.sleep(0.2)
        ok = False
        for _ in range(6):
            st, _, toks, fin = await _sse(fe.port, payload)
            assert st == 200 and fin["finish_reason"] == "stop"
            if reps[0].breaker.state == BREAKER_CLOSED:
                ok = True
                break
            await asyncio.sleep(0.15)   # a doubled backoff may still
        assert ok and reps[0].healthy()  # be running; don't burn all
        # attempts inside one window
        await fe.drain()
        await gw_b.drain()
        await gw_a2.drain()
    asyncio.run(run())


# ============================================================= autoscaler
class _FakeManager:
    def __init__(self, n=1):
        self.reps = [_FakeSignals() for _ in range(n)]
        self._pending = 0
        self.ups = 0
        self.downs = 0

    def replicas(self):
        return list(self.reps)

    def pending(self):
        return self._pending

    def scale_up(self):
        self.ups += 1
        self.reps.append(_FakeSignals())

    def scale_down(self):
        self.downs += 1
        self.reps.pop()


class _FakeSignals:
    def __init__(self):
        self.queue_depth = 0
        self.free_slots = 4
        self.total_slots = 4
        self.up = True        # a SIGKILLed peer: unhealthy AND stale

    def signals(self):
        return {"healthy": self.up, "stale": not self.up,
                "load": self.total_slots - self.free_slots,
                "queue_depth": self.queue_depth,
                "free_slots": self.free_slots,
                "total_slots": self.total_slots,
                "block_pool_free_frac": 1.0, "goodput_frac": 1.0}


def test_autoscaler_hysteresis_cooldown_up_and_down():
    """Sustained pressure scales up ONCE per cooldown window; a
    one-poll blip scales nothing; sustained idleness scales down,
    never below min; flapping signals produce no flapping actions."""
    t = [0.0]
    m = _FakeManager(1)
    # signal_mode="instant": this test pins the hold/cooldown state
    # machine against single-sample transitions; the windowed default
    # (ISSUE 15) smooths those — its semantics (steady-traffic parity,
    # noisy-trace flap reduction) are pinned in test_telemetry.py
    sc = FleetAutoscaler(m, min_replicas=1, max_replicas=3,
                         up_queue_depth=2.0, hold_s=1.0,
                         hold_down_s=2.0, cooldown_s=5.0,
                         signal_mode="instant",
                         clock=lambda: t[0])
    # a blip: pressure seen once, gone before the hold elapses
    m.reps[0].queue_depth = 10
    assert sc.step()["action"] is None
    m.reps[0].queue_depth = 0
    t[0] = 2.0
    assert sc.step()["action"] is None and m.ups == 0
    # sustained pressure: up exactly once at hold_s
    m.reps[0].queue_depth = 10
    assert sc.step()["action"] is None        # hold starts
    t[0] = 2.5
    assert sc.step()["action"] is None
    t[0] = 3.1
    assert sc.step()["action"] == "up" and m.ups == 1
    assert len(m.reps) == 2
    # still under pressure, but the cooldown gates the second up
    t[0] = 4.0
    assert sc.step()["action"] is None        # hold restarts at 4.0
    t[0] = 8.2           # cooldown (5s) passed, hold long satisfied
    assert sc.step()["action"] == "up" and m.ups == 2
    assert len(m.reps) == 3
    t[0] = 14.3          # at max: pressure can't scale further
    assert sc.step()["action"] is None and m.ups == 2
    # idle: down after hold_down_s + cooldown, stopping at min
    for r in m.reps:
        r.queue_depth = 0
    t[0] = 15.0
    assert sc.step()["action"] is None        # down-hold starts
    t[0] = 17.1
    assert sc.step()["action"] == "down" and m.downs == 1
    t[0] = 22.2
    sc.step()
    t[0] = 24.3
    assert sc.step()["action"] == "down" and m.downs == 2
    assert len(m.reps) == 1
    t[0] = 40.0
    sc.step()
    t[0] = 43.0
    assert sc.step()["action"] is None        # never below min
    assert len(sc.events) == 4


def test_autoscaler_pending_spawns_and_replica_seconds():
    """A spawn in flight counts toward the target (no double-fire)
    and replica-seconds integrate live + pending replicas — the
    goodput-per-replica denominator."""
    t = [0.0]
    m = _FakeManager(1)
    m.reps[0].queue_depth = 10
    sc = FleetAutoscaler(m, max_replicas=5, up_queue_depth=2.0,
                         hold_s=0.5, cooldown_s=0.0,
                         clock=lambda: t[0])
    sc.step()
    m._pending = 3       # as if three spawns were already in flight
    t[0] = 1.0
    agg = sc.step()
    assert agg["action"] == "up" and m.ups == 1   # 1+3 < max of 5
    m._pending = 4
    t[0] = 2.0
    assert sc.step()["action"] is None    # 2 live + 4 pending >= max
    # replica-seconds integrate (live + pending) at step boundaries
    assert sc.replica_seconds == pytest.approx(
        (1.0 - 0.0) * (1 + 3) + (2.0 - 1.0) * (2 + 4), abs=1e-6)


def test_autoscaler_mass_outage_freeze_and_thaw():
    """ISSUE 16: a correlated outage takes most peers stale at once —
    the survivors' aggregate (stale peers excluded) reads idle, and
    the classic failure is scaling DOWN during the incident. The loop
    must FREEZE instead (no action either way, one freeze event),
    then thaw and resume normal decisions when peers return."""
    t = [0.0]
    m = _FakeManager(4)
    sc = FleetAutoscaler(m, min_replicas=1, max_replicas=8,
                         hold_s=0.5, hold_down_s=0.5, cooldown_s=0.0,
                         signal_mode="instant",
                         outage_freeze_frac=0.5,
                         clock=lambda: t[0])
    assert sc.step()["action"] is None
    # 3 of 4 peers go dark: live (1) <= replicas (4) * (1 - 0.5)
    for r in m.reps[1:]:
        r.up = False
    t[0] = 1.0
    agg = sc.step()
    assert agg["frozen"] and agg["action"] is None
    assert sc.events[-1]["action"] == "freeze"
    assert sc.events[-1]["stale"] == 3
    # idle survivors held across the whole incident: never a down
    for dt in (1.5, 2.0, 2.5, 3.0):
        t[0] = dt
        assert sc.step()["action"] is None
    assert m.downs == 0 and m.ups == 0
    # recovery thaws the loop; hold windows restart from the thaw
    for r in m.reps:
        r.up = True
    t[0] = 4.0
    agg = sc.step()
    assert not agg.get("frozen") and agg["action"] is None
    assert sc.events[-1]["action"] == "thaw"
    # post-thaw the normal idle scale-down path works again
    t[0] = 5.0
    assert sc.step()["action"] == "down" and m.downs == 1
    assert sc.snapshot()["freezes"] == 1


# ========================================================== tie rotation
def test_router_least_loaded_rotates_ties():
    """Probe-quantized load ties at fleet scale: first-minimum herds
    every miss onto the lowest-index replica. The router must rotate
    among tied minima (the 1000-replica sim measured ~6% of a light
    clean load shed off the herd target before this)."""
    class _R:
        def __init__(self, name):
            self.name = name

        def healthy(self):
            return True

        def has_prefix(self, d):
            return False

        def load(self):
            return 0.0

    reps = [_R(f"r{i}") for i in range(3)]
    router = PrefixAffinityRouter(reps)
    picks = [router.route() for _ in range(6)]
    assert set(p.name for p in picks) == {"r0", "r1", "r2"}
    # a strict minimum still wins outright
    reps[0].load = lambda: 1.0
    reps[1].load = lambda: 1.0
    assert all(router.route() is reps[2] for _ in range(3))


# ====================================================== burn bootstrap
def test_burn_engine_min_window_events_gates_bootstrap():
    """A burn ratio over single-digit samples is noise: with
    ``min_window_events`` set, a hot ratio in an almost-empty
    bootstrap window does NOT page; the same ratio over a populated
    window does. Resolves are never gated."""
    from paddle_tpu.serving import BurnRateEngine
    eng = BurnRateEngine(window_scale=0.2, min_window_events=10,
                         labels={"fleet": "t-minwin"}, clock=lambda: 0)
    # 3 outcomes, all bad: burn is sky-high but the window is empty
    assert eng.observe_many("interactive",
                            [(1.0, False), (1.5, False),
                             (2.0, False)], now=2.0) == []
    assert eng.fires_total == 0
    # the ungated twin pages on exactly that noise
    loose = BurnRateEngine(window_scale=0.2, min_window_events=0,
                           labels={"fleet": "t-minwin0"},
                           clock=lambda: 0)
    evs = loose.observe_many("interactive",
                             [(1.0, False), (1.5, False),
                              (2.0, False)], now=2.0)
    assert any(e["kind"] == "fire" for e in evs)
    # populate past the floor: the gated engine now fires too
    outcomes = [(3.0 + 0.1 * i, False) for i in range(12)]
    evs = eng.observe_many("interactive", outcomes, now=4.2)
    assert any(e["kind"] == "fire" and e["rule"] == "page"
               for e in evs)


# ======================================================= frontend gossip
def test_frontend_gossip_link_merges_digests_and_sticky():
    """One FrontendLink round moves sibling state the right way:
    digest sets adopt only FORWARD by the peer's own generation,
    sticky entries fill only local gaps (resolved through the local
    adapter objects), and a partitioned round changes nothing."""
    from paddle_tpu.serving.fleet import FrontendLink

    def make(name):
        fe = FleetFrontend([], chunk_tokens=None, name=name,
                           trace=False)
        rep = RemoteReplica("p0", "127.0.0.1", 1)
        fe.add_peer(rep)
        return fe, rep

    fe_a, rep_a = make("t-gsp-a")
    fe_b, rep_b = make("t-gsp-b")
    assert rep_b.adopt_digests(["d1", "d2"], 5)
    assert fe_b._router.merge_sticky({"d1": "p0"}, {"p0": rep_b}) == 1
    link = FrontendLink(fe_a, fe_b, seed=3)
    # partition first: the armed fault site severs the round cleanly
    with faults.scoped("gossip_partition"):
        assert not link.exchange()
    assert link.partitioned_total == 1
    assert rep_a.gossip_view()["generation"] == -1   # untouched
    # clean round: digests + sticky cross; generation follows the peer
    assert link.exchange()
    assert link.snapshot()["adopted_digest_sets"] == 1
    assert link.snapshot()["adopted_sticky"] == 1
    view = rep_a.gossip_view()
    assert view["digests"] == ["d1", "d2"] and view["generation"] == 5
    assert fe_a._router.export_sticky() == {"d1": "p0"}
    # idempotent: an unchanged sibling adopts nothing more
    assert link.exchange()
    assert link.snapshot()["adopted_digest_sets"] == 1
    assert link.snapshot()["adopted_sticky"] == 1
    # a STALER sibling view can never roll the local one back
    assert not rep_a.adopt_digests(["old"], 4)
    assert rep_a.gossip_view()["digests"] == ["d1", "d2"]


# ================================================================ diurnal
def test_diurnal_rate_trace_deterministic_and_bounded():
    slg = _load_loadgen()
    vals = [slg.diurnal_rate(i, 100, 10.0, amp=0.8, cycles=1.0,
                             phase=0.3) for i in range(100)]
    vals2 = [slg.diurnal_rate(i, 100, 10.0, amp=0.8, cycles=1.0,
                              phase=0.3) for i in range(100)]
    assert vals == vals2                     # deterministic
    assert max(vals) > 15.0 and min(vals) < 5.0   # actually diurnal
    assert all(v >= 0.5 for v in vals)       # floored at 5% of base
    # amplitude over 1 cannot push the rate negative
    assert all(slg.diurnal_rate(i, 50, 10.0, amp=2.0) > 0
               for i in range(50))


# ============================================================ fleet merge
def test_trace_report_fleet_merge_joins_hops_by_request_id():
    """Synthetic three-process view: the frontend ring + two peer
    rings share one failed-over request id; the merge names the chain
    in accept order and counts the peer failover."""
    import importlib.util
    import os
    from paddle_tpu.serving.reqtrace import (RequestTrace,
                                             RequestTraceRing)
    path = os.path.join(os.path.dirname(__file__), os.pardir,
                        "tools", "trace_report.py")
    spec = importlib.util.spec_from_file_location("trace_report", path)
    tr = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(tr)

    def ring(gateway, replica):
        return RequestTraceRing(
            capacity=16, labels={"gateway": gateway,
                                 "replica": replica})

    rings = {"fe": ring("flt", "frontend"),
             "a": ring("gwA", "r0"), "b": ring("gwB", "r0")}
    t_fe = RequestTrace("req-x")
    t_fe.ev("accept")
    t_fe.ev("proxy_to", replica="pA", attempt=0)
    t_fe.ev("peer_fail", replica="pA", reason="peer_conn_drop")
    t_fe.ev("resubmit", to_replica="", attempt=1)
    t_fe.ev("resume_offset", offset=3, committed=3)
    t_fe.ev("proxy_to", replica="pB", attempt=1)
    t_a = RequestTrace("req-x")
    t_a.ev("queue_enter", slo="interactive")
    t_b = RequestTrace("req-x")
    t_b.ev("queue_enter", slo="interactive")
    t_b.ev("finish", reason="stop")
    # order the accept walls: frontend first, then A, then B
    t_fe.wall0, t_a.wall0, t_b.wall0 = 100.0, 100.001, 100.05
    rings["fe"].finish(t_fe, "stop", tokens=9)
    rings["a"].finish(t_a, "error")
    rings["b"].finish(t_b, "stop", tokens=9)
    solo = RequestTrace("req-solo")
    solo.ev("queue_enter", slo="interactive")
    rings["a"].finish(solo, "stop")
    docs = [dict(r.to_doc(), _file=f"reqtrace_{k}.json")
            for k, r in rings.items()]
    s = tr.summarize(docs)
    fl = s["fleet"]
    assert fl["cross_process_requests"] == 1
    assert fl["with_peer_failover"] == 1
    chain = fl["chains"][0]
    assert chain["request_id"] == "req-x"
    assert chain["chain"] == ["flt/frontend", "gwA/r0", "gwB/r0"]
    assert chain["peer_failovers"] == 1
    assert chain["outcomes"]["gwA/r0"] == "error"
    # merged on one wall-clock axis: the frontend's hop events come
    # before the failed peer's retained timeline (peer B finished
    # clean and fast — retention correctly kept only its summary)
    kinds = [k for _, _, k, _f in chain["events"]]
    assert kinds.index("peer_fail") < kinds.index("queue_enter")
    assert "resubmit" in kinds
    text = tr.render(s)
    assert "flt/frontend -> gwA/r0 -> gwB/r0" in text
    # a single-process view stays in the classic shape
    assert "fleet" not in tr.summarize([docs[1]])


# ============================================================ membership
def test_router_add_remove_replica_drops_sticky():
    class _R:
        def __init__(self, name):
            self.name = name

        def healthy(self):
            return True

        def has_prefix(self, d):
            return False

        def load(self):
            return 0.0

    a, b = _R("a"), _R("b")
    router = PrefixAffinityRouter([a])
    assert router.route(["d1"]) is a          # miss remembered sticky
    router.add_replica(b)
    router.add_replica(b)                     # idempotent
    assert len(router.replicas) == 2
    router.remove_replica(a)
    assert router.replicas == [b]
    assert router.snapshot()["sticky_entries"] == 0
    assert router.route(["d1"]) is b


def test_frontend_healthz_debugz_metrics_endpoints():
    async def run():
        gw = Gateway(_engine(), name="t-fz")
        await gw.start()
        rep = RemoteReplica("p0", "127.0.0.1", gw.port,
                            probe_interval_s=0.05)
        fe = FleetFrontend([rep], chunk_tokens=8, name="t-fz-fe")
        await fe.start()
        await _poll(rep.healthy, 5)
        await _sse(fe.port, {"prompt": PROMPT, "max_new_tokens": 4,
                             "temperature": 0.0})
        st, _, hz = await _http(fe.port, "GET", "/healthz")
        hz = json.loads(hz)
        st2, _, dz = await _http(fe.port, "GET", "/debugz")
        dz = json.loads(dz)
        st3, _, mx = await _http(fe.port, "GET", "/metrics")
        await fe.drain()
        await gw.drain()
        return st, hz, st2, dz, st3, mx.decode()
    st, hz, st2, dz, st3, mx = asyncio.run(run())
    assert st == st2 == st3 == 200
    assert hz["requests"] == 1 and hz["proxied_tokens"] == 4
    assert hz["peers"]["p0"]["healthy"]
    assert hz["router"]["replicas_up"] == 1
    assert dz["autoscaler"] is None
    snap = dz["peers"]["p0"]
    assert snap["gossip"]["generation"] >= 0
    assert snap["probes"] > 0 and snap["breaker"]["state"] == "closed"
    assert dz["trace_ring"]["traced"] == 1
    # the scrape carries the fleet series (same registry objects)
    assert "fleet_requests_total" in mx
    assert "fleet_proxied_tokens_total" in mx


# ===================================================== multi-process e2e
@pytest.mark.slow
def test_fleet_multiproc_loadgen_kill():
    """The ISSUE 13 acceptance harness, small: separate gateway
    PROCESSES behind the frontend, one SIGKILLed mid-run — zero
    corrupted greedy streams (bitwise replay), errors within the
    budget bound, goodput floor cleared."""
    slg = _load_loadgen()
    ns = _loadgen_ns(requests=16, rate=15.0, max_new=8, seed=7,
                     fleet=2, fleet_kill=1, failover_budget=2,
                     goodput_floor=0.95, autoscale=False, diurnal=False)
    rung = asyncio.run(slg.run_loadgen(ns))
    gate = rung["fleet_gate"]
    assert gate["ok"], gate
    assert gate["kills"] == 1 and gate["corrupted_streams"] == 0
    assert rung["completed"] == 16
    assert rung["fleet_tokens_per_sec"] > 0


@pytest.mark.slow
def test_fleet_multiproc_autoscale_diurnal():
    """The closed loop rides a compressed diurnal trace up AND back
    down, with goodput-per-replica in the rung."""
    slg = _load_loadgen()
    ns = _loadgen_ns(requests=150, rate=18.0, max_new=24, seed=5,
                     fleet=1, fleet_kill=0, autoscale=True,
                     autoscale_min=1, autoscale_max=3,
                     autoscale_cooldown_s=2.0, diurnal=True,
                     diurnal_amp=0.8, diurnal_cycles=1.0,
                     failover_budget=2, goodput_floor=0.9)
    rung = asyncio.run(slg.run_loadgen(ns))
    assert rung["fleet_gate"]["ok"], rung["fleet_gate"]
    auto = rung["autoscale"]
    assert auto["scale_ups"] >= 1, auto
    assert auto["scale_downs"] >= 1, auto
    assert rung["goodput_per_replica"] > 0
    assert rung["replica_seconds"] > 0
    assert rung["mean_replicas"] >= 1.0


@pytest.mark.slow
def test_fleet_multiproc_frontend_ha_kill():
    """The ISSUE 16 live acceptance, small: TWO gossip-linked
    frontends over one replica-process fleet, one frontend SIGKILLed
    mid-run — every in-flight client retries against the surviving
    sibling carrying its committed prefix (resume seam, one tier up),
    zero corrupted streams, zero client/server resume mismatches, all
    requests complete."""
    slg = _load_loadgen()
    ns = _loadgen_ns(requests=16, rate=15.0, max_new=8, seed=7,
                     fleet=2, frontends=2, frontend_kill=1,
                     fleet_kill=0, failover_budget=2,
                     goodput_floor=0.95, autoscale=False,
                     diurnal=False)
    rung = asyncio.run(slg.run_loadgen(ns))
    gate = rung["fleet_gate"]
    assert gate["ok"], gate
    assert gate["frontend_kills"] == 1
    assert gate["corrupted_streams"] == 0
    assert gate["resume_mismatches"] == 0
    assert rung["completed"] == 16
    ha = rung["frontend_ha"]
    assert ha["frontends"] == 2 and len(ha["frontend_kills"]) == 1
    assert ha["resumed_failed"] == 0
    # the mesh actually gossiped before (and after) the kill
    assert sum(g["rounds"] for g in ha["gossip"]) > 0
