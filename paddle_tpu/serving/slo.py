"""SLO error budgets & multi-window burn-rate alerting (ISSUE 15
tentpole; reference: the multiwindow, multi-burn-rate alerting recipe
of SRE practice — page when the error budget is burning fast enough
to matter AND has been for long enough to be real, resolve with
hysteresis so a flapping signal doesn't page twice).

The gateway already classifies every request terminal outcome (the
reqtrace ring's ``outcome`` + TTFT attribution, ISSUE 10); what it
could not say is whether the CURRENT error rate is sustainable.
:class:`BurnRateEngine` closes that gap:

- **Error budget** — each SLO class has a success target (e.g.
  interactive 0.99); the budget is ``1 - target``. An observation is
  *bad* when the request failed its class's promise (the gateway
  feeds ``outcome != stop``, and for interactive also a TTFT over the
  SLO threshold — the same rule its goodput gauge uses).
- **Burn rate** — over a window W, ``(bad/n) / budget``: 1.0 means
  "burning exactly the budget", 10 means "the whole budget gone in a
  tenth of the period". No traffic burns nothing.
- **Multi-window rules** — each :class:`BurnRule` pairs a FAST window
  (is it burning *now*?) with a SLOW window (has it been burning long
  enough to be real?) and fires only when BOTH exceed the threshold —
  the classic page/ticket pair, scaled to serving-fleet seconds.
  ``window_scale`` multiplies every window so the same rule table
  runs production-shaped (minutes) or CI-shaped (sub-second,
  ``serve_loadgen --slo-windows``).
- **Hysteresis** — an active alert resolves only when the FAST burn
  falls under ``threshold * resolve_frac`` (default half): between
  the fire and resolve lines the alert holds steady.

Every fire/resolve emits a typed ``alert_fire`` / ``alert_resolve``
event into the flight recorder (the postmortem sees the SLO incident
beside the replica failures that caused it), sets the
``slo_burn_rate{class=,window=}`` gauges, and appends to a bounded
alert log the loadgen rungs bank. Deliberately clock-injectable and
evaluated both on ``observe()`` (prompt fires) and from the metrics
sampler's hook (alerts resolve on wall time even when traffic stops).
"""
from __future__ import annotations

import threading
import time
from collections import deque, namedtuple
from typing import Any, Dict, List, Optional, Tuple

from ..utils import observability as obs

__all__ = ["BurnRule", "BurnRateEngine", "DEFAULT_TARGETS",
           "DEFAULT_RULES"]

BurnRule = namedtuple("BurnRule", ("name", "fast_s", "slow_s",
                                   "threshold"))

# success-fraction targets per SLO class (budget = 1 - target);
# unknown classes auto-register at DEFAULT_TARGET
DEFAULT_TARGETS = {"interactive": 0.99, "batch": 0.95}
DEFAULT_TARGET = 0.99

# the fast/slow pairs, serving-fleet scaled (seconds, not the SRE
# book's hours — window_scale stretches them back out for production):
#   page:   10% of the budget gone in the last minute, confirmed over
#           5 minutes
#   ticket: a slow steady leak over 5/30 minutes
DEFAULT_RULES = (BurnRule("page", 60.0, 300.0, 10.0),
                 BurnRule("ticket", 300.0, 1800.0, 2.0))


class BurnRateEngine:
    """Per-SLO-class error budgets + multi-window burn-rate alerts.

    ``observe(slo, ok)`` feeds one terminal request outcome (the
    gateway wires this to the reqtrace ring's idempotent finish, so a
    disconnect racing a tick finish can never double-count);
    ``evaluate()`` walks the rule table and fires/resolves. Both are
    thread-safe; ``clock`` is injectable for deterministic tests."""

    def __init__(self, targets: Optional[Dict[str, float]] = None,
                 rules=None, *, window_scale: float = 1.0,
                 resolve_frac: float = 0.5,
                 min_window_events: int = 0,
                 max_events: int = 8192, max_alerts: int = 512,
                 labels: Optional[Dict[str, str]] = None,
                 clock=time.monotonic):
        """``min_window_events`` (ISSUE 16): a rule may only FIRE when
        its slow window holds at least this many outcomes — a burn
        ratio over single-digit samples is noise, and the fleet sim's
        cold-start showed exactly that false page (3 sheds in an
        almost-empty bootstrap window pages at burn 18). 0 (the
        default) keeps the historical fire-on-any-traffic behavior;
        resolves are never gated, so an active alert can always
        clear."""
        self.targets = dict(DEFAULT_TARGETS)
        self.targets.update(targets or {})
        self.window_scale = float(window_scale)
        self.rules: Tuple[BurnRule, ...] = tuple(
            BurnRule(r[0], float(r[1]) * self.window_scale,
                     float(r[2]) * self.window_scale, float(r[3]))
            for r in (rules if rules is not None else DEFAULT_RULES))
        if not self.rules:
            raise ValueError("at least one burn rule required")
        self.resolve_frac = float(resolve_frac)
        self.min_window_events = max(int(min_window_events), 0)
        self.max_events = int(max_events)
        self.max_alerts = int(max_alerts)
        self.labels = {k: str(v) for k, v in (labels or {}).items()}
        self._clock = clock
        self._lock = threading.Lock()
        self._events: Dict[str, deque] = {}      # class -> (t, bad)
        self._active: Dict[Tuple[str, str], dict] = {}
        self.alerts: List[dict] = []             # bounded fire/resolve log
        self.fires_total = 0
        self.peak_burn: Dict[str, float] = {}    # class -> max fast burn
        self._horizon = max(r.slow_s for r in self.rules)
        # every distinct rule window, ascending — the one-pass
        # evaluation grid (containment in a window implies containment
        # in every larger one)
        self._windows: Tuple[float, ...] = tuple(sorted(
            {w for r in self.rules for w in (r.fast_s, r.slow_s)}))
        self._gauges: Dict[Tuple[str, str], Any] = {}
        self._c_fires: Dict[str, Any] = {}

    # ------------------------------------------------------------- intake
    def observe(self, slo: str, ok: bool,
                now: Optional[float] = None) -> List[dict]:
        """One terminal request outcome; returns any alert transitions
        this observation triggered."""
        now = self._clock() if now is None else float(now)
        slo = str(slo)
        with self._lock:
            dq = self._events.get(slo)
            if dq is None:
                dq = self._events[slo] = deque(maxlen=self.max_events)
                self.targets.setdefault(slo, DEFAULT_TARGET)
            dq.append((now, not ok))
            while dq and dq[0][0] < now - self._horizon:
                dq.popleft()
        return self.evaluate(now)

    def observe_many(self, slo: str, outcomes,
                     now: Optional[float] = None) -> List[dict]:
        """Batched intake (ISSUE 16: the fleet sim replays thousands
        of trace outcomes per simulated tick): one lock acquisition
        and ONE evaluation for the whole batch. ``outcomes`` is an
        iterable of ``(t, ok)`` pairs, ascending in ``t``; ``now``
        defaults to the last outcome's time. Decision-equivalent to
        per-outcome :meth:`observe` calls evaluated at the batch end —
        only intermediate evaluations (which the sim's tick cadence
        would skip anyway) are elided."""
        slo = str(slo)
        last = None
        with self._lock:
            dq = self._events.get(slo)
            if dq is None:
                dq = self._events[slo] = deque(maxlen=self.max_events)
                self.targets.setdefault(slo, DEFAULT_TARGET)
            for t, ok in outcomes:
                last = float(t)
                dq.append((last, not ok))
            if last is not None:
                while dq and dq[0][0] < last - self._horizon:
                    dq.popleft()
        if now is None:
            now = self._clock() if last is None else last
        return self.evaluate(float(now))

    # ----------------------------------------------------------- the math
    def burn_rate(self, slo: str, window_s: float,
                  now: Optional[float] = None) -> float:
        """``(bad/n) / budget`` over the last ``window_s`` seconds
        (0.0 with no traffic in the window)."""
        now = self._clock() if now is None else float(now)
        with self._lock:
            dq = self._events.get(slo, ())
            lo = now - float(window_s)
            n = bad = 0
            for t, b in dq:
                if t >= lo:
                    n += 1
                    bad += b
        if n == 0:
            return 0.0
        budget = max(1.0 - self.targets.get(slo, DEFAULT_TARGET),
                     1e-9)
        return (bad / n) / budget

    def _class_burns(self, slo: str, now: float
                     ) -> Tuple[Dict[float, float], Dict[float, int]]:
        """Every rule window's (burn, event count) for one class in
        ONE pass — ONE lock acquisition and one event walk, where
        per-window ``burn_rate()`` calls would re-lock and re-scan
        2×rules times. ``evaluate()`` runs on every request finish, so
        this is the hot shape. Same per-event comparison as
        :meth:`burn_rate` (``t >= now - w``), so results are
        bit-identical: each event charges its SMALLEST containing
        window, then a running suffix sum folds it into every larger
        one."""
        windows = self._windows
        with self._lock:
            events = list(self._events.get(slo, ()))
        budget = max(1.0 - self.targets.get(slo, DEFAULT_TARGET),
                     1e-9)
        k = len(windows)
        first_n = [0] * k
        first_bad = [0] * k
        for t, b in events:
            for i in range(k):
                if t >= now - windows[i]:
                    first_n[i] += 1
                    first_bad[i] += b
                    break
        out: Dict[float, float] = {}
        counts: Dict[float, int] = {}
        cn = cb = 0
        for i, w in enumerate(windows):
            cn += first_n[i]
            cb += first_bad[i]
            out[w] = (cb / cn) / budget if cn else 0.0
            counts[w] = cn
        return out, counts

    def _gauge(self, slo: str, window_s: float):
        key = (slo, f"{window_s:g}s")
        g = self._gauges.get(key)
        if g is None:
            g = obs.registry().gauge("slo_burn_rate",
                                     **{"class": slo,
                                        "window": key[1],
                                        **self.labels})
            self._gauges[key] = g
        return g

    # ----------------------------------------------------------- decision
    def evaluate(self, now: Optional[float] = None) -> List[dict]:
        """Walk every (class, rule) pair: fire when BOTH windows burn
        over the threshold, resolve when the fast window falls under
        ``threshold * resolve_frac``. Returns the transitions."""
        now = self._clock() if now is None else float(now)
        with self._lock:
            classes = sorted(set(self._events) | set(self.targets))
        out: List[dict] = []
        for slo in classes:
            budget = max(1.0 - self.targets.get(slo, DEFAULT_TARGET),
                         1e-9)
            burns, counts = self._class_burns(slo, now)
            for w, b in burns.items():
                self._gauge(slo, w).set(b)
            for rule in self.rules:
                bf = burns[rule.fast_s]
                bs = burns[rule.slow_s]
                key = (slo, rule.name)
                with self._lock:
                    if bf > self.peak_burn.get(slo, 0.0):
                        self.peak_burn[slo] = bf
                    active = key in self._active
                ev = None
                if not active and bf >= rule.threshold \
                        and bs >= rule.threshold \
                        and counts[rule.slow_s] \
                        >= self.min_window_events:
                    ev = self._transition(
                        "fire", slo, rule, bf, bs, budget, now)
                elif active and bf <= rule.threshold \
                        * self.resolve_frac:
                    ev = self._transition(
                        "resolve", slo, rule, bf, bs, budget, now)
                if ev is not None:
                    out.append(ev)
        return out

    def _transition(self, kind: str, slo: str, rule: BurnRule,
                    bf: float, bs: float, budget: float,
                    now: float) -> Optional[dict]:
        """Commit one fire/resolve. The state check re-runs UNDER the
        lock (the caller's pre-check was a separate acquisition):
        concurrent evaluators — a request-finish observe() racing the
        sampler-hook heartbeat — must produce exactly one transition,
        never a double fire or an unpaired resolve. Returns None when
        another thread already committed it."""
        ev = {"kind": kind, "slo": slo, "rule": rule.name,
              "t": round(now, 3), "wall": time.time(),
              "fast_s": rule.fast_s, "slow_s": rule.slow_s,
              "threshold": rule.threshold,
              "burn_fast": round(bf, 3), "burn_slow": round(bs, 3),
              "budget": round(budget, 6)}
        with self._lock:
            if kind == "fire":
                if (slo, rule.name) in self._active:
                    return None
                self._active[(slo, rule.name)] = ev
                self.fires_total += 1
            else:
                fired = self._active.pop((slo, rule.name), None)
                if fired is None:
                    return None
                ev["fired_t"] = fired["t"]
            self.alerts.append(ev)
            if len(self.alerts) > self.max_alerts:
                del self.alerts[:len(self.alerts) - self.max_alerts]
        # the flight recorder sees the SLO incident beside the replica
        # failures that caused it (ISSUE 15 acceptance)
        obs.record_event(f"alert_{kind}", slo=slo, rule=rule.name,
                         burn_fast=round(bf, 3),
                         burn_slow=round(bs, 3),
                         threshold=rule.threshold, **self.labels)
        c = self._c_fires.get(slo)
        if c is None:
            c = self._c_fires[slo] = obs.registry().counter(
                "slo_alert_transitions_total",
                **{"class": slo, **self.labels})
        c.inc()
        return ev

    # ------------------------------------------------------------ exports
    def active(self) -> List[dict]:
        with self._lock:
            return list(self._active.values())

    def snapshot(self, now: Optional[float] = None) -> Dict[str, Any]:
        """The ``/metricsz`` / ``/debugz`` SLO block: current burn per
        (class, window), active alerts, the recent alert log, and the
        run's peak burn per class."""
        now = self._clock() if now is None else float(now)
        with self._lock:
            classes = sorted(set(self._events) | set(self.targets))
            peak = {k: round(v, 3) for k, v in self.peak_burn.items()}
        return {
            "targets": dict(self.targets),
            "window_scale": self.window_scale,
            "rules": [r._asdict() for r in self.rules],
            "burn": {slo: {f"{w:g}s": round(b, 3)
                           for w, b in self._class_burns(
                               slo, now)[0].items()}
                     for slo in classes},
            "active": self.active(),
            "fires_total": self.fires_total,
            "peak_burn": peak,
            "alerts": list(self.alerts[-16:]),
        }
