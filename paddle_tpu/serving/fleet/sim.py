"""Trace-driven fleet chaos simulator (ISSUE 16 tentpole; reference:
discrete-event cluster simulators production control planes are
rehearsed in — Borg/Omega trace replay, Jepsen-style fault schedules —
restated in-process over THIS repo's real serving control plane).

Every hardening question at real fleet scale — probe storms at
hundreds of peers, gossip fan-out across N frontends, burn-rate alert
precision during a correlated AZ-style outage, scale-controller
behavior when a majority of signals goes stale — is untestable by a
CPU loadgen run. But the whole frontend stack was built model-free and
clock-injectable, so the sim instantiates the REAL objects:

- :class:`~.frontend.FleetFrontend` (its real
  :class:`~...router.PrefixAffinityRouter` makes every routing
  decision; its real :class:`~...supervisor.CircuitBreaker` instances
  run probation on the simulated clock),
- :class:`~.autoscaler.FleetAutoscaler` (``step(now)`` on the sim
  clock over a :class:`SimManager`),
- :class:`~...slo.BurnRateEngine` (batched outcome intake via
  ``observe_many`` — the alerts scored against injected incidents are
  produced by the production alerting math),
- the real probe schedule (:func:`~.remote.probe_phase` /
  :func:`~.remote.probe_delay` — shared verbatim with the live prober
  thread, so storm behavior measured in-sim IS the production
  schedule).

Only the replica itself is a stub: :class:`SimReplica` duck-types the
RemoteReplica seam (``healthy``/``load``/``has_prefix``/``signals``/
``metricsz``/``note_proxy_failure``/``adopt_digests``/``gossip_view``)
over a scriptable :class:`SimProcess` (latency, slots, prefix
distribution, up/down). ``real_objects()`` asserts the control-plane
classes are the production ones by identity — the sim cannot silently
fork the logic it claims to rehearse.

**Probe capacity model.** Probe rounds draw from a per-time-bin
execution budget (the frontend's finite probe concurrency). A round
that cannot find a free bin within ``probe_timeout_s`` FAILS like a
real timed-out probe — consecutive failures evict and open breakers.
A seeded, jittered schedule spreads demand and fits the budget; the
``peer_storm`` fault site collapses the jitter so every peer's round
fires at once, and the resulting timeout->eviction->page cascade is
exactly what the probe-storm schedule must detect (and what the
jittered clean twin must NOT).

**Scoring.** Chaos schedules carry ground-truth incident windows.
Page-rule fires inside an incident window (+ the slow-window grace)
are true positives; fires outside any window are false pages.
``precision`` / ``recall`` land in the banked rung beside routing
decisions/sec and scale-event counts.

**Frontend HA.** With ``n_frontends >= 2`` each frontend holds its
own adapter views over the shared processes (the real multi-frontend
topology), gossip flows through real :class:`~.ha.FrontendLink`
rounds, and :meth:`FleetSim.kill_frontend` severs one frontend
mid-run: every in-flight stream's client retries against a survivor
carrying its committed prefix through the ``resume_tokens`` seam —
the sim asserts zero lost and zero duplicated committed tokens.
"""
from __future__ import annotations

import heapq
import json
import math
import random
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from ...utils import faults
from ...utils import observability as obs
from ..router import NoReplicaError, PrefixAffinityRouter
from ..slo import BurnRateEngine
from ..supervisor import CircuitBreaker
from .autoscaler import FleetAutoscaler
from .frontend import FleetFrontend
from .ha import FrontendLink
from .remote import probe_delay, probe_phase

__all__ = ["SimClock", "SimProcess", "SimReplica", "SimManager",
           "Incident", "FleetSim", "SCENARIOS", "build_scenario",
           "arrivals_from_series", "arrivals_from_reqtrace"]


class SimClock:
    """Deterministic simulated monotonic clock. Injected everywhere a
    control-plane object accepts ``clock=`` (breakers via the
    frontend, autoscaler, burn engine, series sampler, stubs)."""

    def __init__(self, start: float = 0.0):
        self.now = float(start)

    def __call__(self) -> float:
        return self.now

    def advance(self, to: float):
        if to < self.now:
            raise ValueError(f"clock moved backwards: {to} < {self.now}")
        self.now = float(to)


class SimProcess:
    """The underlying replica gateway process: ground-truth state the
    per-frontend :class:`SimReplica` views observe through probes.
    Scriptable: ``service_s`` (base stream duration), ``slow_mult``
    (brownout), ``up`` (outage), ``slots`` (concurrency before
    queueing/shedding)."""

    def __init__(self, name: str, *, slots: int = 4,
                 service_s: float = 1.0):
        self.name = name
        self.up = True
        self.retired = False
        self.slots = max(int(slots), 1)
        self.service_s = float(service_s)
        self.slow_mult = 1.0
        self.active = 0
        self.completed = 0
        self.tokens = 0
        self.digests: set = set()
        # spilled tier (ISSUE 17): digests surviving only in the
        # process's host-RAM arena — populated by restart(spill=True),
        # promoted back to device-live by the next request that lands
        self.spilled: set = set()
        self.digest_gen = 0
        # probe connections landing on this process (sliding 1s
        # window): health checks run ON the serving process, so a
        # synchronized herd steals decode cycles — the coupling that
        # turns a probe storm into a latency incident
        self._probe_hits: List[float] = []

    def add_digest(self, d: str):
        if d not in self.digests:
            self.digests.add(d)
            self.spilled.discard(d)   # promoted back to device-live
            self.digest_gen += 1

    def restart(self, spill: bool = False):
        """Crash/rebuild the process's engine: device-live digests die
        with the pools. With a spill arena (``spill=True``) they move
        to the spilled tier instead — restorable, still routable —
        which is exactly the warm-restart contract of ISSUE 17."""
        if spill:
            self.spilled |= self.digests
        else:
            self.spilled.clear()
        self.digests.clear()
        self.digest_gen += 1

    def note_probe(self, now: float):
        hits = self._probe_hits
        hits.append(now)
        if len(hits) > 8 and hits[0] < now - 1.0:
            self._probe_hits = [t for t in hits if t >= now - 1.0]

    def probe_rate(self, now: float) -> float:
        return float(sum(1 for t in self._probe_hits
                         if t >= now - 1.0))

    def latency_s(self, rng: random.Random, now: float,
                  probe_load_cost: float = 0.0) -> float:
        """Stream duration for one request admitted NOW: base service
        time x brownout multiplier x a queueing factor once the
        process runs past its slot budget x the probe-pressure tax,
        +-10% seeded noise."""
        queue_factor = 1.0 + max(self.active - self.slots, 0) \
            / self.slots
        probe_factor = 1.0 + probe_load_cost * self.probe_rate(now)
        return self.service_s * self.slow_mult * queue_factor \
            * probe_factor * (0.9 + 0.2 * rng.random())


class SimReplica:
    """Per-frontend adapter view over one :class:`SimProcess`,
    duck-typed to the RemoteReplica seam the router/autoscaler/
    frontend read. Probe rounds (driven by the sim's event loop on
    the REAL seeded schedule) refresh the snapshot; the same
    staleness bound, failure latch, breaker-mediated rejoin and
    generation-guarded gossip adoption semantics as the live
    adapter."""

    def __init__(self, proc: SimProcess, clock: SimClock, *,
                 stale_after_s: float = 2.5, fail_threshold: int = 2):
        self.proc = proc
        self.name = proc.name
        self.host, self.port = "sim", 0
        self._clock = clock
        self.stale_after_s = float(stale_after_s)
        self.fail_threshold = max(int(fail_threshold), 1)
        self.breaker: Optional[CircuitBreaker] = None
        self._healthy = True
        self._fails = 0
        self._snap_t: Optional[float] = None
        self._load = 0.0
        self._queue_depth = 0
        self._free_slots = self._total_slots = 0
        self._digests: frozenset = frozenset()
        self._spilled: frozenset = frozenset()
        self._digest_gen = -1
        self._digest_t: Optional[float] = None
        self.probes_total = 0
        self.probe_failures_total = 0

    # ---------------------------------------------------- probe (sim-driven)
    def probe(self) -> bool:
        """One probe round landing NOW (the sim's stand-in for
        ``RemoteReplica.refresh``): success refreshes the snapshot
        (and, partition permitting, the gossiped digest set); failure
        counts toward the eviction latch exactly like the live
        adapter."""
        self.probes_total += 1
        if not self.proc.up:
            return self.probe_fail("down")
        now = self._clock()
        self._snap_t = now
        self._load = float(self.proc.active)
        self._queue_depth = max(self.proc.active - self.proc.slots, 0)
        self._free_slots = max(self.proc.slots - self.proc.active, 0)
        self._total_slots = self.proc.slots
        if not faults.inject("gossip_partition", replica=self.name):
            if self.proc.digest_gen != self._digest_gen:
                self._digests = frozenset(self.proc.digests)
                self._spilled = frozenset(self.proc.spilled)
                self._digest_gen = self.proc.digest_gen
            self._digest_t = now
        self._fails = 0
        if not self._healthy and self.breaker is None:
            self._healthy = True
        return True

    def probe_fail(self, reason: str) -> bool:
        self.probe_failures_total += 1
        self._fails += 1
        if self._fails >= self.fail_threshold and self._healthy:
            self._healthy = False
            obs.record_event("fleet_peer_down", peer=self.name,
                             fails=self._fails, reason=reason)
            if self.breaker is not None:
                self.breaker.record_failure()
        return False

    # ------------------------------------------------------ the router seam
    def _fresh(self) -> bool:
        return self._snap_t is not None \
            and self._clock() - self._snap_t <= self.stale_after_s

    def healthy(self) -> bool:
        return self._healthy and self._fresh()

    def mark(self, healthy: bool):
        self._healthy = bool(healthy)

    def load(self) -> float:
        return self._load

    def has_prefix(self, digest: str) -> bool:
        if self._digest_t is None \
                or self._clock() - self._digest_t > self.stale_after_s:
            return False
        # spilled tier counts as warm, mirroring the live adapter
        return digest in self._digests or digest in self._spilled

    def note_proxy_failure(self):
        self._healthy = False
        if self.breaker is not None:
            self.breaker.record_failure()

    def start(self):
        pass                    # the sim's event loop IS the prober

    def stop(self, timeout: float = 0.0):
        pass

    def refresh(self) -> bool:
        return self.probe()

    def set_metrics_window(self, window_s: float):
        pass

    def signals(self) -> Dict[str, Any]:
        return {
            "peer": self.name,
            "healthy": self.healthy(),
            "stale": not self._fresh(),
            "load": self._load,
            "queue_depth": self._queue_depth,
            "free_slots": self._free_slots,
            "total_slots": self._total_slots,
            "block_pool_free_frac": 1.0,
            "goodput_frac": 1.0,
        }

    def metricsz(self) -> Dict[str, Any]:
        age = None if self._snap_t is None \
            else self._clock() - self._snap_t
        return {"peer": self.name,
                "age_s": age,
                "stale": age is None or age > self.stale_after_s,
                "doc": {"enabled": True, "gateway": self.name,
                        "metrics": {}, "slo": {}}}

    def snapshot(self) -> Dict[str, Any]:
        out = {"peer": self.name, "healthy": self.healthy(),
               "stale": not self._fresh(),
               "probes": self.probes_total,
               "probe_failures": self.probe_failures_total,
               "gossip": {"digests": len(self._digests),
                          "spilled": len(self._spilled),
                          "generation": self._digest_gen}}
        if self.breaker is not None:
            out["breaker"] = self.breaker.snapshot()
        return out

    # ------------------------------------------------- frontend HA gossip
    def adopt_digests(self, digests, generation: int,
                      spilled=()) -> bool:
        gen = int(generation)
        if gen <= self._digest_gen:
            return False
        self._digests = frozenset(digests or ())
        self._spilled = frozenset(spilled or ())
        self._digest_gen = gen
        self._digest_t = self._clock()
        return True

    def gossip_view(self) -> Dict[str, Any]:
        out = {"digests": sorted(self._digests),
               "spilled": sorted(self._spilled),
               "generation": self._digest_gen,
               "healthy": self.healthy()}
        if self.breaker is not None:
            out["breaker"] = self.breaker.state
        return out


class SimManager:
    """The autoscaler's manager duck type over the sim fleet: spawns
    complete after ``cold_start_s`` of simulated time (a pending spawn
    counts toward the target, like the process manager's)."""

    def __init__(self, sim: "FleetSim", cold_start_s: float = 5.0):
        self.sim = sim
        self.name = "sim"
        self.cold_start_s = float(cold_start_s)
        self._pending = 0
        self.spawns = 0
        self.retires = 0

    def replicas(self) -> List[SimReplica]:
        return list(self.sim.frontends[0].peers)

    def pending(self) -> int:
        return self._pending

    def scale_up(self):
        self._pending += 1
        self.spawns += 1
        sim = self.sim
        name = f"sim{len(sim.procs)}"

        def _spawned():
            self._pending -= 1
            sim.add_process(SimProcess(
                name, slots=sim.slots, service_s=sim.service_s))
        sim.schedule(sim.clock.now + self.cold_start_s, _spawned)

    def scale_down(self):
        sim = self.sim
        for proc in reversed(sim.procs):
            if proc.up and not proc.retired:
                self.retires += 1
                sim.retire_process(proc)
                return


class _FleetRegistryView:
    """Registry facade that exposes only the fleet/SLO/fault metrics
    to the sim's sampler. The frontend registers its counters in the
    PROCESS registry (same code path as live serving), so a sim run
    inside a process that previously served real traffic — one pytest
    session, a notebook — would otherwise sample that unrelated
    history into its ``series`` dump and fleet_dash would classify
    the doc as a gateway doc instead of a sim doc."""

    _PREFIXES = ("fleet_", "slo_", "fault_")

    def _items(self):
        for item in obs.registry()._items():
            if item[0].startswith(self._PREFIXES):
                yield item


class Incident:
    """One ground-truth chaos window: ``apply(sim)`` at ``t0``,
    ``revert(sim)`` at ``t1``. ``page=True`` marks windows the page
    alert MUST detect (recall) — fires outside every window are false
    pages (precision)."""

    def __init__(self, kind: str, t0: float, t1: float, *,
                 page: bool, apply: Callable, revert: Callable):
        self.kind = kind
        self.t0, self.t1 = float(t0), float(t1)
        self.page = bool(page)
        self.apply, self.revert = apply, revert


class FleetSim:
    """Discrete-event fleet simulator over the real control plane.

    ``rate_fn(t) -> requests/s`` drives open-loop arrivals (seeded
    exponential inter-arrivals); ``arrival_times`` replays a recorded
    trace instead. ``incidents`` are ground-truth chaos windows."""

    def __init__(self, *, n_replicas: int = 100, n_frontends: int = 1,
                 duration_s: float = 300.0, seed: int = 0,
                 rate_fn: Optional[Callable[[float], float]] = None,
                 base_rate: float = 20.0, rate_amp: float = 0.0,
                 rate_cycles: float = 1.0,
                 arrival_times: Optional[List[float]] = None,
                 slots: int = 4, service_s: float = 1.0,
                 spill_margin: Optional[float] = None,
                 slo_latency_s: Optional[float] = None,
                 prefix_pool: int = 32, prefix_alpha: float = 1.2,
                 tokens_per_request: int = 32,
                 prompt_tokens: int = 128,
                 probe_interval_s: float = 1.0,
                 stale_after_s: float = 2.5,
                 jitter_frac: float = 0.2,
                 probe_bin_s: float = 0.05,
                 probe_capacity_per_bin: Optional[int] = None,
                 probe_timeout_s: float = 0.3,
                 probe_load_cost: float = 0.15,
                 fe_pressure_cost: float = 0.5,
                 gossip_interval_s: float = 1.0,
                 autoscale: bool = False,
                 scaler_kw: Optional[Dict[str, Any]] = None,
                 window_scale: float = 0.2,
                 failover_budget: int = 2,
                 slo_tick_s: float = 1.0,
                 incidents: Tuple[Incident, ...] = (),
                 kill_frontend_at: Optional[float] = None,
                 sample_interval_s: float = 2.0):
        self.seed = int(seed)
        self.rng = random.Random(f"fleet-sim:{seed}")
        self.clock = SimClock()
        self.duration_s = float(duration_s)
        self.slots, self.service_s = int(slots), float(service_s)
        # spill before the shed cliff: a warm pick running past its
        # slot budget must lose to a cold idle peer (the live margin
        # of 8 is sized for 8-slot gateways; scale it to the stubs')
        self.spill_margin = float(spill_margin) \
            if spill_margin is not None else float(self.slots)
        self.slo_latency_s = float(slo_latency_s) \
            if slo_latency_s is not None else 3.0 * self.service_s
        self.prefix_pool = int(prefix_pool)
        self.prefix_alpha = float(prefix_alpha)
        self.tokens_per_request = int(tokens_per_request)
        self.prompt_tokens = int(prompt_tokens)
        self.probe_interval_s = float(probe_interval_s)
        self.stale_after_s = float(stale_after_s)
        self.jitter_frac = float(jitter_frac)
        self.probe_bin_s = float(probe_bin_s)
        self.probe_timeout_s = float(probe_timeout_s)
        self.probe_load_cost = float(probe_load_cost)
        self.fe_pressure_cost = float(fe_pressure_cost)
        self.gossip_interval_s = float(gossip_interval_s)
        self.failover_budget = int(failover_budget)
        self.slo_tick_s = float(slo_tick_s)
        self.sample_interval_s = float(sample_interval_s)
        self.incidents = tuple(incidents)
        self.kill_frontend_at = kill_frontend_at
        # arrivals: replayed trace or rate-driven open loop
        if arrival_times is not None:
            self._arrivals = sorted(float(t) for t in arrival_times
                                    if 0.0 <= float(t) <= duration_s)
            self.rate_fn = None
        else:
            self._arrivals = None
            self.rate_fn = rate_fn or (
                lambda t: base_rate * (1.0 + rate_amp * math.sin(
                    2.0 * math.pi * rate_cycles * t / duration_s)))
        # probe budget PER FRONTEND (each frontend runs its own
        # prober threads off its own event loop/GIL): sized so the
        # JITTERED schedule fits with ~50% headroom; a storm-collapsed
        # schedule overflows it
        self.probe_capacity_per_bin = int(probe_capacity_per_bin) \
            if probe_capacity_per_bin is not None else max(
                4, int(1.5 * int(n_replicas) * self.probe_bin_s
                       / self.probe_interval_s))
        # ------------------------------------------------------ event loop
        self._heap: List[Tuple[float, int, Callable]] = []
        self._seq = 0
        # (frontend idx, time bin) -> executed / attempted probe counts
        self._bins: Dict[Tuple[int, int], int] = {}
        self._attempts: Dict[Tuple[int, int], int] = {}
        self._inflight: Dict[int, Dict[str, Any]] = {}
        self._req_seq = 0
        self._rr = 0
        self._outcomes: List[Tuple[float, bool]] = []
        self.flight: List[Dict[str, Any]] = []
        # tallies
        self.decisions = 0
        self.verdicts: Dict[str, int] = {}
        self.requests = self.completed = self.failed = 0
        self.shed = self.no_replica = 0
        self.probe_rounds = self.probe_deferred = 0
        self.probe_timeouts = 0
        self.ha = {"severed_streams": 0, "resumed_streams": 0,
                   "synthesized_streams": 0, "corrupted_streams": 0,
                   "committed_tokens_preserved": 0,
                   "tokens_lost": 0, "tokens_duplicated": 0}
        # cross-replica KV transfer tier (ISSUE 18): spans a migrating
        # drain exported become fleet-fetchable (the /kvz wire path);
        # recompute_tokens counts every prefill token a drain forced a
        # survivor to re-run — the quantity migration exists to zero
        self.fleet_spill: set = set()
        self.xfer = {"drained_procs": 0, "migrated_requests": 0,
                     "xfer_hits": 0, "recompute_tokens": 0}
        self._wall_cpu: Optional[float] = None
        # ---------------------------------------------- the REAL objects
        self.procs: List[SimProcess] = []
        self.frontends: List[FleetFrontend] = []
        self.fe_alive: List[bool] = []
        for i in range(int(n_frontends)):
            fe = FleetFrontend(
                [], chunk_tokens=None, routing="prefix",
                spill_margin=self.spill_margin,
                failover_budget=self.failover_budget,
                breaker_backoff_s=1.0,
                name=f"simfe{i}", trace=False, clock=self.clock)
            self.frontends.append(fe)
            self.fe_alive.append(True)
        for i in range(int(n_replicas)):
            self.add_process(SimProcess(f"sim{i}", slots=self.slots,
                                        service_s=self.service_s),
                            initial=True)
        self.links: List[FrontendLink] = []
        for fe in self.frontends:
            for sib in self.frontends:
                if sib is not fe:
                    self.links.append(FrontendLink(
                        fe, sib, interval_s=self.gossip_interval_s,
                        jitter_frac=self.jitter_frac, seed=self.seed))
        self.engine = BurnRateEngine(window_scale=float(window_scale),
                                     min_window_events=24,
                                     max_events=65536,
                                     labels={"fleet": "sim"},
                                     clock=self.clock)
        self.manager = SimManager(self)
        self.scaler = None
        if autoscale:
            kw = dict(min_replicas=1,
                      max_replicas=max(2 * int(n_replicas), 4),
                      interval_s=1.0, clock=self.clock)
            kw.update(scaler_kw or {})
            self.scaler = FleetAutoscaler(self.manager, **kw)
            self.frontends[0].attach_autoscaler(self.scaler)
        self.series = obs.MetricsTimeSeries(
            name=f"sim{self.seed}", registry=_FleetRegistryView(),
            interval_s=self.sample_interval_s,
            capacity=2048, clock=self.clock)

    # ---------------------------------------------------------- membership
    def add_process(self, proc: SimProcess, initial: bool = False):
        self.procs.append(proc)
        for fe in self.frontends:
            view = SimReplica(proc, self.clock,
                              stale_after_s=self.stale_after_s)
            fe.add_peer(view)       # REAL membership path: breaker
            #                         attach (clock-injected) + router
            # first probe lands immediately (the manager's
            # spawn-then-refresh), then the seeded schedule
            view.probe()
            self._schedule_probe_chain(fe, view)

    def retire_process(self, proc: SimProcess):
        """Graceful scale-down: out of rotation everywhere, in-flight
        streams finish on their own (the live manager's drain)."""
        proc.retired = True
        for fe in self.frontends:
            for view in list(fe.peers):
                if view.proc is proc:
                    fe.remove_peer(view)

    def drain_one(self, migrate: bool):
        """One scale-down wave step: retire the MOST-loaded live proc
        — the worst case for a drain, maximum in-flight cut-overs."""
        cands = [p for p in self.procs if p.up and not p.retired]
        if len(cands) <= 1:
            return
        self.drain_process(max(cands, key=lambda p: p.active),
                           migrate=migrate)

    def drain_process(self, proc: SimProcess, migrate: bool):
        """Scale-down drain of ``proc`` (ISSUE 18). With ``migrate``
        the retiring replica exports each live request's KV span to
        the fleet spill tier (the live stack's terminal ``migrated``
        event + ``/kvz`` wire path) and the request cuts over to a
        survivor that RESTORES the span — zero re-prefill. Without,
        the requests resubmit on the classic resume seam and the
        survivor re-prefills prompt+committed: exactly the recompute
        the migration exists to eliminate, scored per token in
        ``xfer["recompute_tokens"]``."""
        self.xfer["drained_procs"] += 1
        live = [rid for rid, req in self._inflight.items()
                if req["proc"] is proc and not req["cancelled"]]
        self._event("drain", proc=proc.name, migrate=migrate,
                    live=len(live))
        self.retire_process(proc)
        if migrate:
            # the replica's whole arena becomes fleet-fetchable —
            # the gossiped spilled tier, now served over the wire
            self.fleet_spill |= proc.digests | proc.spilled
        for rid in live:
            req = self._inflight.pop(rid)
            req["cancelled"] = True
            proc.active = max(proc.active - 1, 0)
            committed = req["resume_from"] + int(
                (self.tokens_per_request - req["resume_from"])
                * min((self.clock.now - req["t_start"])
                      / max(req["latency"], 1e-9), 1.0))
            if migrate:
                self.fleet_spill.add(f"req{rid}")
                self.xfer["migrated_requests"] += 1
                self.xfer["xfer_hits"] += 1
                # one D2H export + one H2D scatter on the survivor;
                # sub-chunk tail recompute is noise, scored as zero
            else:
                self.xfer["recompute_tokens"] += \
                    self.prompt_tokens + committed
            fe = req["fe"]
            if not self.fe_alive[self.frontends.index(fe)]:
                fe = self._live_frontend()
                if fe is None:
                    self._finish_outcome(False)
                    continue
            self._dispatch(rid, fe, req["digests"], hops=0,
                           resume_from=committed,
                           t_accept=req["t_accept"])

    # ------------------------------------------------------------ schedule
    def schedule(self, t: float, fn: Callable):
        self._seq += 1
        heapq.heappush(self._heap, (float(t), self._seq, fn))

    def _event(self, kind: str, **fields):
        self.flight.append({"t": round(self.clock.now, 3),
                            "kind": kind, **fields})

    # -------------------------------------------------------------- probes
    def _schedule_probe_chain(self, fe: FleetFrontend,
                              view: SimReplica):
        key = f"{fe.name}:{view.name}"
        t0 = self.clock.now + probe_phase(
            key, self.probe_interval_s, seed=self.seed)
        self.schedule(t0, lambda: self._probe_round(fe, view, 0, t0))

    def _probe_round(self, fe: FleetFrontend, view: SimReplica,
                     rnd: int, t_req: float):
        fi = self.frontends.index(fe)
        if view not in fe.peers or not self.fe_alive[fi]:
            return                    # retired peer / dead frontend
        self.probe_rounds += 1
        b0 = int(t_req / self.probe_bin_s)
        self._attempts[(fi, b0)] = self._attempts.get((fi, b0), 0) + 1
        # capacity: claim the earliest bin with budget left inside the
        # timeout horizon; none -> the executor rejects the round
        # (fail-fast, never reaches the replica) and it counts as a
        # probe FAILURE
        b = b0
        horizon = b0 + max(int(self.probe_timeout_s
                               / self.probe_bin_s), 1)
        placed = None
        while b <= horizon:
            if self._bins.get((fi, b), 0) \
                    < self.probe_capacity_per_bin:
                self._bins[(fi, b)] = self._bins.get((fi, b), 0) + 1
                placed = b
                break
            b += 1
        if placed is None:
            self.probe_timeouts += 1
            view.probe_fail("probe_timeout")
        else:
            if placed > b0:
                self.probe_deferred += 1
            # an EXECUTED probe opens a connection against the serving
            # process: the probe tax that turns a monopolized storm
            # schedule into a latency incident on the winners' procs
            view.proc.note_probe(t_req)
            view.probe()
        # next round on the REAL seeded schedule (peer_storm collapses
        # the delay to 0 — the synchronized herd); floored at one bin,
        # the live prober's reconnect floor
        key = f"{fe.name}:{view.name}"
        dt = probe_delay(key, self.probe_interval_s, rnd + 1,
                         jitter_frac=self.jitter_frac, seed=self.seed)
        t_next = self.clock.now + max(dt, self.probe_bin_s)
        self.schedule(t_next,
                      lambda: self._probe_round(fe, view, rnd + 1,
                                                t_next))

    # -------------------------------------------------------------- gossip
    def _gossip_round(self, link: FrontendLink, rnd: int):
        i = self.frontends.index(link.frontend)
        j = self.frontends.index(link.sibling) \
            if link.sibling in self.frontends else -1
        if self.fe_alive[i] and (j < 0 or self.fe_alive[j]):
            link.exchange()           # REAL merge path (+ the
            #                           gossip_partition fault site)
        dt = probe_delay(link.name, self.gossip_interval_s, rnd + 1,
                         jitter_frac=self.jitter_frac, seed=self.seed)
        self.schedule(self.clock.now + max(dt, self.probe_bin_s),
                      lambda: self._gossip_round(link, rnd + 1))

    # ------------------------------------------------------------ requests
    def _pick_prefix(self) -> List[str]:
        """Zipf-ish draw over the shared-prefix pool (hot prefixes are
        the affinity routing signal)."""
        u = self.rng.random()
        k = int(self.prefix_pool * (u ** self.prefix_alpha))
        return [f"pfx{min(k, self.prefix_pool - 1)}"]

    def _live_frontend(self) -> Optional[FleetFrontend]:
        """Client-side LB: round-robin over frontends it can reach."""
        n = len(self.frontends)
        for _ in range(n):
            fe = self.frontends[self._rr % n]
            self._rr += 1
            if self.fe_alive[self.frontends.index(fe)]:
                return fe
        return None

    def _arrival(self):
        self.requests += 1
        self._req_seq += 1
        rid = self._req_seq
        fe = self._live_frontend()
        if fe is None:
            self._finish_outcome(False)
            return
        # tick the frontend's REAL request counter (the sim bypasses
        # its HTTP listener): the dumped series doc must show the
        # offered load, and arrivals_from_series must round-trip it
        fe._c_requests.inc()
        self._dispatch(rid, fe, self._pick_prefix(), hops=0,
                       resume_from=0, t_accept=self.clock.now)

    def _dispatch(self, rid: int, fe: FleetFrontend,
                  digests: List[str], *, hops: int, resume_from: int,
                  t_accept: float):
        """Route (REAL router ladder) + admit one stream attempt."""
        meta: Dict[str, Any] = {}
        try:
            view = fe._router.route(digests, allow_probe=hops == 0,
                                    meta=meta)
        except NoReplicaError:
            self.no_replica += 1
            self._finish_outcome(False)
            return
        self.decisions += 1
        v = meta.get("verdict", "?")
        self.verdicts[v] = self.verdicts.get(v, 0) + 1
        proc = view.proc
        probe = v == "probe"
        if not proc.up:
            # routed onto a corpse the staleness bound hasn't caught
            # yet: the proxy fails, the peer is evicted, the failover
            # loop retries — the frontend's own ladder semantics
            view.note_proxy_failure()
            fe._router.evict_unhealthy()
            if probe and view.breaker is not None:
                view.breaker.probe_done(False)
            self._failover(rid, fe, digests, hops, resume_from,
                           t_accept)
            return
        if proc.active >= 2 * proc.slots:
            # overloaded peer sheds (429): terminal, bad for the SLO,
            # no eviction, no budget charge
            if probe and view.breaker is not None:
                view.breaker.probe_done(None)
            self.shed += 1
            self._finish_outcome(False)
            return
        proc.add_digest(digests[0])   # prefill registers the prefix
        proc.active += 1
        latency = proc.latency_s(self.rng, self.clock.now,
                                 self.probe_load_cost) \
            * self._fe_pressure_factor(fe)
        self._inflight[rid] = {
            "fe": fe, "view": view, "proc": proc, "probe": probe,
            "digests": digests, "hops": hops,
            "resume_from": resume_from, "t_start": self.clock.now,
            "t_accept": t_accept, "latency": latency,
            "cancelled": False,
        }
        self.schedule(self.clock.now + latency,
                      lambda: self._complete(rid))

    def _fe_pressure_factor(self, fe: FleetFrontend) -> float:
        """Frontend executor overflow tax on PROXIED STREAMS: probe
        demand past the executor budget starves the same event loop
        that forwards tokens, so every stream through an overloaded
        frontend slows. At or under budget (any jittered schedule)
        the factor is 1.0; a storm-collapsed schedule at N× demand
        inflates fleet-wide latency — the page the storm schedule
        must produce at ANY fleet size."""
        if self.fe_pressure_cost <= 0.0:
            return 1.0
        fi = self.frontends.index(fe)
        b = int(self.clock.now / self.probe_bin_s)
        nb = max(int(1.0 / self.probe_bin_s), 1)
        demand = sum(self._attempts.get((fi, k), 0)
                     for k in range(b - nb, b))
        cap = self.probe_capacity_per_bin * nb
        pressure = demand / max(cap, 1)
        return 1.0 + self.fe_pressure_cost * max(pressure - 1.0, 0.0)

    def _failover(self, rid: int, fe: FleetFrontend,
                  digests: List[str], hops: int, resume_from: int,
                  t_accept: float):
        hops += 1
        if hops > self.failover_budget:
            fe._c_exhausted.inc()
            self.failed += 1
            self._finish_outcome(False)
            return
        fe._c_failovers.inc()
        self._dispatch(rid, fe, digests, hops=hops,
                       resume_from=resume_from, t_accept=t_accept)

    def _complete(self, rid: int):
        req = self._inflight.pop(rid, None)
        if req is None or req["cancelled"]:
            return
        proc, view = req["proc"], req["view"]
        proc.active = max(proc.active - 1, 0)
        if not proc.up:
            # died mid-stream: committed prefix survives with the
            # client; failover resubmits the remainder
            view.note_proxy_failure()
            req["fe"]._router.evict_unhealthy()
            if req["probe"] and view.breaker is not None:
                view.breaker.probe_done(False)
            committed = req["resume_from"] + int(
                (self.tokens_per_request - req["resume_from"])
                * min((self.clock.now - req["t_start"])
                      / max(req["latency"], 1e-9), 1.0))
            self._failover(rid, req["fe"], req["digests"],
                           req["hops"], committed, req["t_accept"])
            return
        proc.completed += 1
        emitted = self.tokens_per_request - req["resume_from"]
        proc.tokens += emitted
        req["fe"]._c_tokens.inc(emitted)
        req["fe"]._h_ttft.observe(
            (req["t_start"] - req["t_accept"]
             + req["latency"] / max(self.tokens_per_request, 1))
            * 1000.0)
        if req["probe"] and view.breaker is not None:
            view.breaker.probe_done(True)
        total_latency = self.clock.now - req["t_accept"]
        self.completed += 1
        self._finish_outcome(total_latency <= self.slo_latency_s)

    def _finish_outcome(self, ok: bool):
        self._outcomes.append((self.clock.now, bool(ok)))

    # ----------------------------------------------------- frontend HA kill
    def kill_frontend(self, idx: int):
        """SIGKILL stand-in for frontend ``idx`` mid-run: the real
        :meth:`FleetFrontend.kill` severs its listener/streams; every
        in-flight request through it loses its uncommitted tail and
        the CLIENT retries against a survivor carrying the committed
        prefix through the resume seam (fully-committed streams are
        synthesized client-side, never retried — the ISSUE 12 rule,
        one tier up)."""
        fe = self.frontends[idx]
        self.fe_alive[idx] = False
        fe.kill()
        self._event("frontend_kill", frontend=fe.name)
        for rid, req in list(self._inflight.items()):
            if req["fe"] is not fe or req["cancelled"]:
                continue
            req["cancelled"] = True
            del self._inflight[rid]
            req["proc"].active = max(req["proc"].active - 1, 0)
            self.ha["severed_streams"] += 1
            committed = req["resume_from"] + int(
                (self.tokens_per_request - req["resume_from"])
                * min((self.clock.now - req["t_start"])
                      / max(req["latency"], 1e-9), 1.0))
            committed_ids = list(range(committed))
            survivor = self._live_frontend()
            if survivor is None:
                self.ha["corrupted_streams"] += 1
                self._finish_outcome(False)
                continue
            if committed >= self.tokens_per_request:
                # client holds every token: synthesize, don't retry
                self.ha["synthesized_streams"] += 1
                self._check_stream(committed_ids, [])
                self._finish_outcome(True)
                continue
            self.ha["resumed_streams"] += 1
            self._resume_on(survivor, req, committed_ids)

    def _resume_on(self, survivor: FleetFrontend,
                   req: Dict[str, Any], committed_ids: List[int]):
        """Client retry against the survivor: resume_tokens carries
        the committed prefix; the survivor's REAL router places the
        remainder (warm/sticky state it gossiped from the dead
        sibling makes this a hit, not a cold miss)."""
        rid = self._req_seq = self._req_seq + 1
        resume_from = len(committed_ids)
        survivor._c_requests.inc()   # the retry is a new request
        self._dispatch(rid, survivor, req["digests"], hops=0,
                       resume_from=resume_from,
                       t_accept=req["t_accept"])
        live = self._inflight.get(rid)
        if live is None:
            self.ha["corrupted_streams"] += 1
            return
        # the remainder the survivor will emit, validated at once (the
        # sim's streams are deterministic ranges — emission content
        # does not depend on which peer serves it, like greedy decode)
        resumed_ids = list(range(resume_from,
                                 self.tokens_per_request))
        self._check_stream(committed_ids, resumed_ids)

    def _check_stream(self, committed_ids: List[int],
                      resumed_ids: List[int]):
        """The client-observed contract: committed + resumed must be
        exactly the uninterrupted stream — zero lost, zero duplicated
        committed tokens."""
        final = committed_ids + resumed_ids
        want = list(range(self.tokens_per_request)) \
            if resumed_ids else committed_ids
        dup = len(final) - len(set(final))
        lost = len(want) - len(final) if not dup else 0
        if final != want:
            self.ha["corrupted_streams"] += 1
            self.ha["tokens_duplicated"] += max(dup, 0)
            self.ha["tokens_lost"] += max(lost, 0)
        else:
            self.ha["committed_tokens_preserved"] += len(committed_ids)

    # ------------------------------------------------------------ main loop
    def _prime(self):
        # arrivals
        if self._arrivals is not None:
            for t in self._arrivals:
                self.schedule(t, self._arrival)
            self.requests_planned = len(self._arrivals)
        else:
            t = 0.0
            n = 0
            while t < self.duration_s:
                rate = max(self.rate_fn(t), 1e-6)
                t += self.rng.expovariate(rate)
                if t < self.duration_s:
                    self.schedule(t, self._arrival)
                    n += 1
            self.requests_planned = n
        # incidents
        for inc in self.incidents:
            self.schedule(inc.t0, lambda inc=inc: (
                self._event("incident_start", incident=inc.kind,
                            page_expected=inc.page),
                inc.apply(self)))
            self.schedule(inc.t1, lambda inc=inc: (
                self._event("incident_end", incident=inc.kind),
                inc.revert(self)))
        # periodic control loops
        if self.scaler is not None:
            def _scale_tick():
                self.scaler.step(self.clock.now)
                self.schedule(self.clock.now + self.scaler.interval_s,
                              _scale_tick)
            self.schedule(self.scaler.interval_s, _scale_tick)

        def _slo_tick():
            if self._outcomes:
                batch, self._outcomes = self._outcomes, []
                for ev in self.engine.observe_many(
                        "interactive", batch, now=self.clock.now):
                    self._event(f"alert_{ev['kind']}",
                                rule=ev["rule"], slo=ev["slo"],
                                burn_fast=ev["burn_fast"])
            self.schedule(self.clock.now + self.slo_tick_s, _slo_tick)
        self.schedule(self.slo_tick_s, _slo_tick)

        def _sample_tick():
            self.series.sample(self.clock.now)
            self.schedule(self.clock.now + self.sample_interval_s,
                          _sample_tick)
        self.schedule(self.sample_interval_s, _sample_tick)
        # gossip links
        for link in self.links:
            self.schedule(
                probe_phase(link.name, self.gossip_interval_s,
                            seed=self.seed),
                lambda link=link: self._gossip_round(link, 0))
        # frontend kill
        if self.kill_frontend_at is not None:
            self.schedule(float(self.kill_frontend_at),
                          lambda: self.kill_frontend(
                              len(self.frontends) - 1))

    def run(self) -> Dict[str, Any]:
        self.real_objects(check=True)
        self._prime()
        cpu0 = time.process_time()
        drain_until = self.duration_s + 10.0 * self.service_s
        while self._heap:
            t, _, fn = heapq.heappop(self._heap)
            if t > drain_until:
                break
            self.clock.advance(max(t, self.clock.now))
            fn()
        # flush the outcome tail through the alert engine
        if self._outcomes:
            batch, self._outcomes = self._outcomes, []
            self.engine.observe_many("interactive", batch,
                                     now=self.clock.now)
        self._wall_cpu = time.process_time() - cpu0
        return self.result()

    # -------------------------------------------------------------- results
    def real_objects(self, check: bool = False) -> Dict[str, str]:
        """Identity report (and assertion): the control plane under
        sim IS the production code, not a fork."""
        fe = self.frontends[0]
        view = fe.peers[0] if fe.peers else None
        objs = {
            "frontend": type(fe),
            "router": type(fe._router),
            "burn_engine": type(self.engine),
            "probe_schedule": probe_delay,
        }
        if self.scaler is not None:
            objs["autoscaler"] = type(self.scaler)
        if view is not None and view.breaker is not None:
            objs["breaker"] = type(view.breaker)
        if check:
            assert objs["frontend"] is FleetFrontend
            assert objs["router"] is PrefixAffinityRouter
            assert objs["burn_engine"] is BurnRateEngine
            if "autoscaler" in objs:
                assert objs["autoscaler"] is FleetAutoscaler
            if "breaker" in objs:
                assert objs["breaker"] is CircuitBreaker
            from . import remote as _remote
            assert probe_delay is _remote.probe_delay
        return {k: f"{v.__module__}.{v.__qualname__}"
                if hasattr(v, "__qualname__")
                else f"{v.__module__}.{type(v).__name__}"
                for k, v in objs.items()}

    def score_alerts(self, grace_s: Optional[float] = None
                     ) -> Dict[str, Any]:
        """Precision/recall of page fires against ground-truth
        incident windows (+ slow-window grace: a burn alert may
        legitimately confirm shortly after the incident clears)."""
        if grace_s is None:
            grace_s = max((r.slow_s for r in self.engine.rules),
                          default=60.0)
        fires = [a for a in self.engine.alerts
                 if a["kind"] == "fire" and a["rule"] == "page"]
        windows = [(i.t0, i.t1 + grace_s) for i in self.incidents
                   if i.page]
        matched = [a for a in fires
                   if any(lo <= a["t"] <= hi for lo, hi in windows)]
        detected = [1 for lo, hi in windows
                    if any(lo <= a["t"] <= hi for a in fires)]
        return {
            "page_fires": len(fires),
            "false_pages": len(fires) - len(matched),
            "incidents_paged_expected": len(windows),
            "incidents_detected": sum(detected),
            "precision": len(matched) / len(fires) if fires else 1.0,
            "recall": sum(detected) / len(windows)
            if windows else 1.0,
            "ticket_fires": sum(
                1 for a in self.engine.alerts
                if a["kind"] == "fire" and a["rule"] == "ticket"),
        }

    def result(self) -> Dict[str, Any]:
        wall = self._wall_cpu or 1e-9
        out = {
            "sim": {
                "replicas": len(self.procs),
                "frontends": len(self.frontends),
                "duration_s": self.duration_s,
                "seed": self.seed,
                "probe_interval_s": self.probe_interval_s,
                "probe_capacity_per_bin":
                    self.probe_capacity_per_bin,
                "incidents": [{"kind": i.kind, "t0": i.t0,
                               "t1": i.t1, "page": i.page}
                              for i in self.incidents],
            },
            "real_objects": self.real_objects(),
            "cpu_s": round(wall, 3),
            "decisions_total": self.decisions,
            "decisions_per_sec": round(self.decisions / wall, 1),
            "verdicts": dict(sorted(self.verdicts.items())),
            "requests": self.requests,
            "completed": self.completed,
            "shed": self.shed,
            "no_replica": self.no_replica,
            "probe": {
                "rounds": self.probe_rounds,
                "deferred": self.probe_deferred,
                "timeouts": self.probe_timeouts,
            },
            "alerts": self.score_alerts(),
            "gossip": [ln.snapshot() for ln in self.links],
        }
        if self.scaler is not None:
            # count from the per-INSTANCE event log (the registry
            # counters are process-global and would leak across sims)
            evs = self.scaler.events
            out["scale"] = {
                "ups": sum(1 for e in evs if e["action"] == "up"),
                "downs": sum(1 for e in evs if e["action"] == "down"),
                "freezes": sum(1 for e in evs
                               if e["action"] == "freeze"),
                "frozen": self.scaler.snapshot()["frozen"],
                "events": evs[-32:],
                "replica_seconds": round(
                    self.scaler.replica_seconds, 3),
            }
        if self.kill_frontend_at is not None \
                or len(self.frontends) > 1:
            out["ha"] = dict(self.ha)
        if self.xfer["drained_procs"]:
            out["xfer"] = dict(self.xfer,
                               fleet_spill_spans=len(self.fleet_spill))
        return out

    # --------------------------------------------------------------- dumps
    def dump_series(self, path: str) -> str:
        """The sim's telemetry history as a standard ``series/1`` doc
        (same writer, same validator, same ``fleet_dash`` renderer as
        live runs) with the alert log attached."""
        return self.series.dump(path, alerts=self.engine.alerts)

    def dump_flight(self, path: str) -> str:
        """The sim's incident/alert/kill timeline as a flight-recorder
        doc. Sim events carry simulated ``t``; ``wall`` is synthesized
        as ``dumped_wall - (clock_now - t)`` so ``fleet_dash`` puts an
        injected incident and its alert on one shared wall axis."""
        dumped_wall = time.time()
        now = self.clock.now
        merged = list(self.flight)
        if self.scaler is not None:
            # the scaler keeps its own per-instance event log; merge
            # it in as the same ``fleet_autoscale`` events a live
            # flight recorder carries, so fleet_dash marks them
            fleet = getattr(self.frontends[0], "name", "fleet")
            merged += [{"kind": "fleet_autoscale", "fleet": fleet,
                        **ev} for ev in self.scaler.events]
        merged.sort(key=lambda ev: ev["t"])
        events = [dict(ev, wall=dumped_wall - (now - ev["t"]))
                  for ev in merged]
        doc = {"run_id": f"fleet_sim_seed{self.seed}", "attempt": 0,
               "reason": "sim_end", "dumped_wall": dumped_wall,
               "clock_now": now, "capacity": len(events),
               "total_events": len(events), "events": events}
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(doc, f)
        import os
        os.replace(tmp, path)
        return path


# ------------------------------------------------------- chaos schedules
def _outage(t0: float, t1: float, frac: float) -> Incident:
    killed: List[SimProcess] = []

    def apply(sim: FleetSim):
        n = max(int(len(sim.procs) * frac), 1)
        for proc in sim.procs[:n]:
            if proc.up:
                proc.up = False
                killed.append(proc)

    def revert(sim: FleetSim):
        for proc in killed:
            proc.up = True
        killed.clear()
    return Incident("correlated_outage", t0, t1, page=True,
                    apply=apply, revert=revert)


def _storm(t0: float, t1: float) -> Incident:
    def apply(sim: FleetSim):
        # arm the REAL fault site probe_delay checks: every armed
        # round's jitter collapses to zero — the synchronized herd
        faults.configure("peer_storm~1.0")

    def revert(sim: FleetSim):
        faults.configure(None)
    return Incident("probe_storm", t0, t1, page=True,
                    apply=apply, revert=revert)


def _partition(t0: float, t1: float) -> Incident:
    def apply(sim: FleetSim):
        faults.configure("gossip_partition~1.0")

    def revert(sim: FleetSim):
        faults.configure(None)
    return Incident("gossip_partition", t0, t1, page=False,
                    apply=apply, revert=revert)


def _brownout(t0: float, t1: float, frac: float,
              mult: float) -> Incident:
    slowed: List[SimProcess] = []

    def apply(sim: FleetSim):
        n = max(int(len(sim.procs) * frac), 1)
        for proc in sim.procs[:n]:
            proc.slow_mult = mult
            slowed.append(proc)

    def revert(sim: FleetSim):
        for proc in slowed:
            proc.slow_mult = 1.0
        slowed.clear()
    return Incident("slow_peer_brownout", t0, t1, page=True,
                    apply=apply, revert=revert)


def _spill_restart(t: float, frac: float, spill: bool) -> Incident:
    """One-shot mass engine rebuild at ``t`` (supervisor rebuild /
    rolling restart across a slice of the fleet): the affected
    processes stay UP but their device-live digests die — with a
    spill arena they move to the spilled tier and stay routable
    (ISSUE 17 warm-restart); without, the fleet re-earns every prefix
    cold."""
    def apply(sim: FleetSim):
        n = max(int(len(sim.procs) * frac), 1)
        for proc in sim.procs[:n]:
            proc.restart(spill=spill)

    def revert(sim: FleetSim):
        pass                      # a restart has no un-restart
    return Incident("spill_restart", t, t + 1e-9, page=False,
                    apply=apply, revert=revert)


def _drain_wave(times: Tuple[float, ...],
                migrate: bool) -> Tuple[Incident, ...]:
    """One scale-down drain per listed time (ISSUE 18): each retires
    the most-loaded live proc, cutting its in-flight requests over
    (``migrate=True``) or resubmitting them cold. page=False — a
    planned drain must never page."""
    kind = "drain_migrate" if migrate else "drain_reprefill"

    def mk(t: float) -> Incident:
        return Incident(kind, t, t + 1e-9, page=False,
                        apply=lambda sim: sim.drain_one(migrate),
                        revert=lambda sim: None)
    return tuple(mk(t) for t in times)


SCENARIOS = ("clean", "outage", "storm", "partition", "brownout",
             "brownout_spill", "diurnal", "ha", "drain_migrate",
             "drain_reprefill")


def build_scenario(name: str, *, n_replicas: int = 100,
                   n_frontends: int = 1, duration_s: float = 300.0,
                   seed: int = 0, base_rate: float = 20.0,
                   **overrides) -> FleetSim:
    """Seeded chaos schedules over a common fleet shape. ``clean`` is
    the incident-free twin every chaos scenario is scored against —
    identical seed, arrivals and fleet, zero injected incidents, so
    any page it raises is a false page by construction."""
    T = float(duration_s)
    kw: Dict[str, Any] = dict(
        n_replicas=n_replicas, n_frontends=n_frontends,
        duration_s=T, seed=seed, base_rate=base_rate)
    if name == "clean":
        pass
    elif name == "outage":
        # kill down to ~half the capacity the offered load needs —
        # a fixed fraction of a lightly-utilized big fleet leaves
        # survivors with headroom and (correctly) no page
        service = float(overrides.get("service_s", 1.0))
        slots = int(overrides.get("slots", 4))
        survivors = max(int(0.4 * base_rate * service / slots), 1)
        frac = 1.0 - min(survivors / max(n_replicas, 1), 0.5)
        kw["incidents"] = (_outage(0.4 * T, 0.7 * T, frac),)
        # pinned floor: the scale story here is the mass-outage FREEZE
        # (survivors' low load must not read as scale-down pressure),
        # not routine capacity tracking
        kw.update(autoscale=True,
                  scaler_kw=dict(min_replicas=n_replicas,
                                 max_replicas=2 * n_replicas))
    elif name == "storm":
        kw["incidents"] = (_storm(0.4 * T, 0.6 * T),)
    elif name == "partition":
        kw["incidents"] = (_partition(0.4 * T, 0.7 * T),)
    elif name == "brownout":
        # fleet-WIDE slowdown (thermal throttle / noisy neighbor
        # across an AZ): a minority brownout is absorbed by the
        # load-aware ladder — measured, not assumed: at frac 0.3 the
        # router routes around it and the fleet stays in SLO
        kw["incidents"] = (_brownout(0.4 * T, 0.7 * T, 0.9, 8.0),)
    elif name == "brownout_spill":
        # brownout + mid-incident mass engine rebuild (ISSUE 17): the
        # throttled slice's supervisors rebuild their engines while the
        # fleet is already degraded. spill=True (the default) keeps the
        # rebuilt processes' digests routable through the spilled tier,
        # so warm routing survives the double hit; spill=False is the
        # cold twin the A/B compares against (override via
        # ``spill_restart=False``).
        spill = bool(overrides.pop("spill_restart", True))
        kw["incidents"] = (_brownout(0.4 * T, 0.7 * T, 0.5, 6.0),
                           _spill_restart(0.55 * T, 0.5, spill))
    elif name == "diurnal":
        # start the fleet at trough size so the peak genuinely forces
        # scale-ups (and the falling edge, scale-downs)
        # fresher probes: at peak, 1s-stale load lets the warm/sticky
        # ladder pile bursts onto one replica past its shed cliff —
        # the small diurnal fleet needs the 0.5s cadence to stay clean
        kw.update(rate_amp=0.8, rate_cycles=1.0, autoscale=True,
                  probe_interval_s=0.5,
                  n_replicas=max(n_replicas // 4, 2),
                  scaler_kw=dict(min_replicas=max(n_replicas // 4, 2),
                                 max_replicas=4 * n_replicas,
                                 hold_s=1.0, hold_down_s=8.0,
                                 cooldown_s=4.0))
    elif name == "ha":
        kw.update(n_frontends=max(n_frontends, 2),
                  kill_frontend_at=0.5 * T)
    elif name in ("drain_migrate", "drain_reprefill"):
        # scale-down wave mid-traffic: ~1/3 of the fleet retires one
        # replica at a time, each drain hitting the busiest survivor
        # candidate. drain_migrate cuts live requests over through
        # the fleet spill tier (recompute ~0); drain_reprefill is the
        # control twin — identical seed/arrivals/wave times, requests
        # resubmit cold and the survivors re-prefill prompt+committed.
        # The recompute-amplification bound (>= 10x) is scored across
        # the pair.
        migrate = bool(overrides.pop("migrate",
                                     name == "drain_migrate"))
        waves = max(int(overrides.pop("drain_waves",
                                      max(n_replicas // 3, 1))), 1)
        kw["incidents"] = _drain_wave(
            tuple(T * (0.3 + 0.5 * k / waves) for k in range(waves)),
            migrate)
    else:
        raise ValueError(f"unknown scenario {name!r}; "
                         f"known: {SCENARIOS}")
    kw.update(overrides)
    return FleetSim(**kw)


# --------------------------------------------------------- trace replay
def arrivals_from_series(doc: Dict[str, Any],
                         metric: str = "gateway_requests_total",
                         scale: float = 1.0) -> List[float]:
    """Recover request arrival times from a recorded ``series_*.json``
    doc: walk the cumulative request-counter samples, spread each
    inter-sample delta uniformly across its interval, shift t to 0.
    ``scale`` multiplies the replayed rate."""
    out: List[float] = []
    for full, view in (doc.get("metrics") or {}).items():
        if full.split("{", 1)[0] != metric:
            continue
        samples = view.get("samples") or []
        prev_t = prev_v = None
        for s in samples:
            t, v = float(s[0]), float(s[1])
            if prev_t is not None and v > prev_v and t > prev_t:
                n = int(round((v - prev_v) * scale))
                for k in range(n):
                    out.append(prev_t + (t - prev_t) * (k + 0.5) / n)
            prev_t, prev_v = t, v
    if not out:
        raise ValueError(f"no {metric!r} rate recoverable from "
                         "series doc")
    t0 = min(out)
    return sorted(t - t0 for t in out)


def arrivals_from_reqtrace(doc: Dict[str, Any],
                           scale: float = 1.0) -> List[float]:
    """Arrival offsets from a dumped reqtrace ring (per-entry
    ``wall_accept``), shifted to 0. ``scale`` compresses (>1) or
    stretches (<1) the replayed timeline."""
    walls = [float(e["wall_accept"])
             for e in (doc.get("entries") or [])
             if e.get("wall_accept") is not None]
    if not walls:
        raise ValueError("no wall_accept entries in reqtrace doc")
    t0 = min(walls)
    return sorted((w - t0) / float(scale) for w in walls)
