"""SLO-aware continuous-batching admission for the serving gateway
(ISSUE 9; reference: vLLM's scheduler policy layer, Orca's iteration-
level scheduling).

``PagedEngine.submit()`` is FIFO: whatever got in the engine queue
first is admitted first, regardless of who is waiting or how urgent
they are. This scheduler is the policy layer the gateway puts in front
of it — requests wait HERE, where they can still be reordered, shed or
expired, and the engine's own queue is kept empty so an admission
happens exactly when a slot frees up (iteration-level continuous
batching, not batch-level):

- **SLO classes** — ``interactive`` requests carry a TTFT deadline
  (enqueue time + ``interactive_ttft_ms``) and are served
  earliest-deadline-first; ``batch`` requests are throughput traffic
  that yields to interactive work.
- **Queue-age promotion** — a batch request queued longer than
  ``promote_after_ms`` joins the interactive pool with an
  already-expired deadline, so EDF serves it next: starvation-free
  without a separate aging thread.
- **Per-tenant fair share** — among the best-class candidates, the
  tenant with the least recently-served debt goes first;
  ``priority`` (higher wins) orders requests within a tenant.
- **Load shedding** — ``enqueue`` raises :class:`ShedError` (the
  gateway maps it to HTTP 429 + ``Retry-After``) when this queue is at
  capacity or when the engine's OWN backpressure signal (the
  ``queued``/``queue_capacity`` fields of ``PagedEngine.health()``)
  says the replica is saturated — no new saturation heuristics, the
  engine's existing ones.
- **Deadline expiry before admission** — a queued request whose hard
  deadline (``timeout_s``) passed is cancelled by ``reap()`` and
  counted in the ``timeouts`` counter BEFORE it ever takes a slot;
  the remaining deadline budget is threaded into
  ``PagedEngine.submit(timeout_s=...)`` by the gateway so in-slot
  expiry still uses the engine's own machinery.

Thread contract: ``enqueue``/``cancel`` run on the gateway's asyncio
thread, ``reap``/``pop`` on the replica's tick thread — every public
method takes the one internal lock.
"""
from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional

from ..utils import observability as obs

__all__ = ["SLO_INTERACTIVE", "SLO_BATCH", "ShedError", "ServeRequest",
           "SLOScheduler"]

SLO_INTERACTIVE = "interactive"
SLO_BATCH = "batch"

# fair-share debt entries kept per scheduler (tenant ids arrive
# verbatim from clients, so the map must be bounded like the router's
# sticky table)
_DEBT_CAP = 1024


class ShedError(RuntimeError):
    """Admission refused under load. ``retry_after_s`` is the backoff
    hint the gateway surfaces as the HTTP ``Retry-After`` header."""

    def __init__(self, msg: str, retry_after_s: float):
        super().__init__(msg)
        self.retry_after_s = retry_after_s


class ServeRequest:
    """One gateway request riding through scheduler -> engine -> stream.

    ``gen`` holds the ``PagedEngine.submit`` kwargs verbatim;
    ``deadline`` is the hard monotonic cutoff (None = no cap);
    ``sink`` is the gateway's per-request asyncio event queue (opaque
    to the scheduler). Timing fields are written by the gateway's
    replica worker as the request advances."""

    __slots__ = ("request_id", "input_ids", "gen", "slo", "tenant",
                 "priority", "deadline", "t_enqueue", "digest", "sink",
                 "stream", "emitted", "t_admit", "t_first", "t_last",
                 "n_out", "promoted", "trace", "failovers", "probe",
                 "resume", "owner")

    def __init__(self, request_id, input_ids, gen: Dict[str, Any],
                 slo: str = SLO_INTERACTIVE, tenant: str = "default",
                 priority: int = 0, deadline: Optional[float] = None,
                 digest: Optional[str] = None, sink=None,
                 stream: bool = True, trace=None):
        if slo not in (SLO_INTERACTIVE, SLO_BATCH):
            raise ValueError(f"unknown SLO class {slo!r}")
        self.request_id = request_id
        self.input_ids = list(input_ids)
        self.gen = dict(gen)
        self.slo = slo
        self.tenant = tenant
        self.priority = int(priority)
        self.deadline = deadline
        self.digest = digest
        self.sink = sink
        self.stream = bool(stream)
        self.trace = trace        # RequestTrace (ISSUE 10) or None
        self.t_enqueue = time.monotonic()
        self.emitted = 0          # tokens already pushed to the sink
        self.t_admit: Optional[float] = None
        self.t_first: Optional[float] = None   # first-token wall (TTFT)
        self.t_last: Optional[float] = None
        self.n_out = 0
        self.promoted = False
        # fleet fault tolerance (ISSUE 12): ``failovers`` counts
        # replica-failure resubmissions against the gateway's retry
        # budget; ``resume`` holds the engine-exported descriptor
        # (prompt + committed tokens) the next _admit submits from;
        # ``probe`` marks the request as a circuit-breaker probation
        # probe; ``owner`` is the worker currently serving it (updated
        # on failover so a disconnect cancels at the RIGHT replica).
        self.failovers = 0
        self.probe = False
        self.resume: Optional[Dict[str, Any]] = None
        self.owner = None


class SLOScheduler:
    """Admission queue for ONE engine replica (the gateway runs one per
    replica, so shedding and fairness see exactly the backlog that
    replica owns)."""

    def __init__(self, max_queue: int = 256,
                 interactive_ttft_ms: float = 500.0,
                 promote_after_ms: float = 2000.0,
                 labels: Optional[Dict[str, str]] = None):
        self.max_queue = int(max_queue)
        self.interactive_ttft_s = float(interactive_ttft_ms) / 1e3
        self.promote_after_s = float(promote_after_ms) / 1e3
        self._lock = threading.Lock()
        self._q: List[ServeRequest] = []
        self._debt: Dict[str, int] = {}
        # EMA of per-request service time: the Retry-After estimate
        self._service_ema_s = 0.25
        labels = labels or {}
        reg = obs.registry()
        self._c_shed = reg.counter("gateway_sched_shed_total", **labels)
        self._c_timeout = reg.counter("gateway_sched_timeouts_total",
                                      **labels)
        self._c_promoted = reg.counter("gateway_sched_promotions_total",
                                       **labels)
        self._g_depth = reg.gauge("gateway_queue_depth", **labels)
        self._h_wait = reg.histogram("gateway_queue_wait_ms",
                                     buckets=obs.SERVING_MS_BUCKETS,
                                     **labels)

    # ------------------------------------------------------------- intake
    def depth(self) -> int:
        with self._lock:
            return len(self._q)

    def enqueue(self, req: ServeRequest,
                engine_health: Optional[Dict[str, Any]] = None):
        """Admit ``req`` to the wait queue, or shed. The engine-side
        saturation check reuses ``PagedEngine.health()`` verbatim: a
        replica whose OWN bounded queue is full is overloaded by the
        engine's definition, not a new one."""
        with self._lock:
            if len(self._q) >= self.max_queue:
                self._c_shed.inc()
                raise ShedError(
                    f"scheduler queue at capacity ({self.max_queue})",
                    self._retry_after_locked())
            if engine_health is not None:
                cap = engine_health.get("queue_capacity")
                if cap is not None and \
                        engine_health.get("queued", 0) >= cap:
                    self._c_shed.inc()
                    raise ShedError(
                        "engine admission queue saturated "
                        f"({engine_health.get('queued')}/{cap})",
                        self._retry_after_locked())
            self._q.append(req)
            self._g_depth.set(len(self._q))
            if req.trace is not None:
                req.trace.ev("queue_enter", slo=req.slo,
                             tenant=req.tenant, depth=len(self._q))

    def cancel(self, request_id) -> bool:
        """Remove a still-queued request (client disconnect before
        admission). Returns False when it already left the queue."""
        with self._lock:
            for r in self._q:
                if r.request_id == request_id:
                    self._q.remove(r)
                    self._g_depth.set(len(self._q))
                    return True
        return False

    # ----------------------------------------------------------- policy
    def _edf_deadline(self, r: ServeRequest) -> float:
        """EDF key: interactive requests are due a first token
        ``interactive_ttft_ms`` after arrival; a batch request becomes
        due at its promotion age, so once promoted it is ALREADY late
        and EDF serves it ahead of fresher interactive work."""
        if r.slo == SLO_INTERACTIVE:
            return r.t_enqueue + self.interactive_ttft_s
        return r.t_enqueue + self.promote_after_s

    def reap(self, now: Optional[float] = None) -> List[ServeRequest]:
        """Remove and return every queued request whose HARD deadline
        passed — the satellite contract: an expired request is counted
        (``timeouts``) and cancelled before it ever takes a slot."""
        now = time.monotonic() if now is None else now
        out: List[ServeRequest] = []
        with self._lock:
            for r in [r for r in self._q
                      if r.deadline is not None and now > r.deadline]:
                self._q.remove(r)
                self._c_timeout.inc()
                if r.trace is not None:
                    r.trace.ev("queue_expire", wait_ms=round(
                        (now - r.t_enqueue) * 1e3, 3))
                out.append(r)
            if out:
                self._g_depth.set(len(self._q))
        return out

    def pop(self, now: Optional[float] = None) -> Optional[ServeRequest]:
        """Next request to admit, or None. Selection: best SLO class
        (interactive, which includes promoted-batch) -> least-debt
        tenant -> highest priority -> earliest deadline."""
        now = time.monotonic() if now is None else now
        with self._lock:
            if not self._q:
                return None
            inter = [r for r in self._q
                     if r.slo == SLO_INTERACTIVE
                     or now - r.t_enqueue >= self.promote_after_s]
            pool = inter or self._q
            tenants: Dict[str, List[ServeRequest]] = {}
            for r in pool:
                tenants.setdefault(r.tenant, []).append(r)
            tenant = min(tenants, key=lambda t: (self._debt.get(t, 0), t))
            pick = min(tenants[tenant],
                       key=lambda r: (-r.priority, self._edf_deadline(r),
                                      r.t_enqueue))
            self._q.remove(pick)
            self._debt[tenant] = self._debt.get(tenant, 0) + 1
            if len(self._debt) > 1 and (m := min(self._debt.values())):
                # keep debt VALUES bounded; only relative order matters
                self._debt = {t: d - m for t, d in self._debt.items()}
            if len(self._debt) > _DEBT_CAP:
                # bound the tenant COUNT too (tenant ids come verbatim
                # from clients): zero-debt entries mean the same as
                # absent ones, and past that the least-indebted go —
                # forgetting a tenant only resets it to most-favored
                self._debt = {t: d for t, d in self._debt.items() if d}
                if len(self._debt) > _DEBT_CAP:
                    keep = sorted(self._debt.items(),
                                  key=lambda kv: -kv[1])[:_DEBT_CAP]
                    self._debt = dict(keep)
            if pick.slo == SLO_BATCH and pick in inter:
                pick.promoted = True
                self._c_promoted.inc()
            self._g_depth.set(len(self._q))
            self._h_wait.observe((now - pick.t_enqueue) * 1e3,
                                 exemplar=pick.request_id)
            if pick.trace is not None:
                pick.trace.ev("queue_leave", promoted=pick.promoted,
                              wait_ms=round(
                                  (now - pick.t_enqueue) * 1e3, 3))
            return pick

    # ------------------------------------------------------------ sizing
    def note_service(self, seconds: float):
        """Fold one completed request's service time into the EMA that
        sizes the Retry-After hint."""
        with self._lock:
            self._service_ema_s = (0.8 * self._service_ema_s
                                   + 0.2 * max(float(seconds), 1e-3))

    def _retry_after_locked(self) -> float:
        est = (len(self._q) + 1) * self._service_ema_s
        return round(min(max(est, 0.1), 30.0), 2)

    def snapshot(self) -> Dict[str, Any]:
        """Health fields, read from the SAME registry objects a
        /metrics scrape exports (the PR-4 pin discipline)."""
        with self._lock:
            depth = len(self._q)
        return {
            "queued": depth,
            "max_queue": self.max_queue,
            "shed": int(self._c_shed.value),
            "timeouts": int(self._c_timeout.value),
            "promotions": int(self._c_promoted.value),
            "queue_wait_ms": self._h_wait.stats(),
        }

    def debug_snapshot(self, max_entries: int = 64) -> Dict[str, Any]:
        """The /debugz view (ISSUE 10): the live queue contents (who is
        waiting, how long, with what deadline) plus the fair-share
        tenant-debt map and the service-time EMA that sizes
        Retry-After — the introspection a "why is this request stuck"
        investigation starts from."""
        now = time.monotonic()
        with self._lock:
            q = [{"request_id": str(r.request_id), "slo": r.slo,
                  "tenant": r.tenant, "priority": r.priority,
                  "age_ms": round((now - r.t_enqueue) * 1e3, 1),
                  "deadline_in_s":
                      round(r.deadline - now, 3)
                      if r.deadline is not None else None}
                 for r in self._q[:max_entries]]
            debt = dict(self._debt)
            ema = self._service_ema_s
        snap = self.snapshot()
        snap.update(queue=q, tenant_debt=debt,
                    service_ema_s=round(ema, 4))
        return snap
