"""paddle_tpu.quant — quantization (reference: PaddleSlim / paddle.nn.quant
weight_only_linear, llm.int8; PaddleNLP quantization configs)."""
from .weight_only import (QuantizedLinear, dequantize_weight,
                          quantize_blockwise, quantize_model,
                          weight_only_linear)
from .qat import FakeQuantLinear, fake_quant
from .ptq import PTQ, AbsMaxObserver, W8A8Linear
from .gptq_awq import (AWQLinear, awq_quantize_model, awq_search_scale,
                       gptq_quantize_model, gptq_quantize_weight)
