"""Llama-3 family (flagship; reference: PaddleNLP
paddlenlp/transformers/llama/modeling.py — LlamaAttention/LlamaMLP/
LlamaDecoderLayer/LlamaForCausalLM, fuse_attention_qkv and the
mp/sp-parallel code paths).

TPU-native design:
- GQA attention over the Pallas flash kernel (training) / dense XLA path
  with a static KV cache (decode) — no per-rank weight slicing: q/k/v/o are
  Column/RowParallelLinear so GSPMD shards heads over ``tp``.
- RoPE computed inline (fp32 angles, cast back) — XLA fuses it into the
  surrounding matmuls; no precomputed position table to keep in HBM.
- Activations sharded batch→("dp","fsdp"), seq→"sp" via constraint hints.
- Per-layer `jax.checkpoint` (remat) when config.recompute is on.
- bf16 params by default (fp32 master weights live in the optimizer).
"""
from __future__ import annotations

import math
from dataclasses import dataclass, field
from functools import partial
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from .. import nn
from ..nn import functional as F
from ..nn.layer import Layer, Parameter
from ..nn.recompute import POLICIES
from ..ops.attention import (decode_attention, dense_attention,
                             flash_attention, use_flash)
from ..parallel.layers import (ColumnParallelLinear, RowParallelLinear,
                               VocabParallelEmbedding, parallel_matmul)
from ..parallel.sharding import constraint
from ..utils.rng import next_key
from .base import CausalLMBase


@dataclass
class LlamaConfig:
    vocab_size: int = 128256
    hidden_size: int = 4096
    intermediate_size: int = 14336
    num_hidden_layers: int = 32
    num_attention_heads: int = 32
    num_key_value_heads: int = 8
    max_position_embeddings: int = 8192
    rms_norm_eps: float = 1e-5
    rope_theta: float = 500000.0
    tie_word_embeddings: bool = False
    attention_bias: bool = False       # Qwen2 uses biased q/k/v projections
    initializer_range: float = 0.02
    recompute: bool = False
    # jax.checkpoint policy name (see nn.recompute.POLICIES): "full"
    # reruns everything; "dots_with_no_batch_dims_saveable" keeps weight
    # matmul outputs in HBM and reruns only the cheap elementwise chains —
    # the usual MFU winner when memory allows.
    recompute_policy: str = "full"
    use_flash_attention: bool = True
    # sliding-window attention (Qwen2/Mistral): each query attends only
    # the trailing `sliding_window` keys; None = full causal. HF-Qwen2
    # gating: only layers with index >= max_window_layers slide (None =
    # every layer slides)
    sliding_window: "Optional[int]" = None
    max_window_layers: "Optional[int]" = None
    # Llama-3.1+ rope_scaling (HF type "llama3": factor,
    # low/high_freq_factor, original_max_position_embeddings); None =
    # plain RoPE
    rope_scaling: "Optional[Dict[str, Any]]" = None
    sequence_parallel: bool = False  # ring attention over the sp axis
    dtype: Any = jnp.bfloat16

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_attention_heads


def llama3_8b(**overrides) -> LlamaConfig:
    return LlamaConfig(**overrides)


def llama3_70b(**overrides) -> LlamaConfig:
    base = dict(hidden_size=8192, intermediate_size=28672,
                num_hidden_layers=80, num_attention_heads=64,
                num_key_value_heads=8)
    base.update(overrides)
    return LlamaConfig(**base)


def llama_tiny(**overrides) -> LlamaConfig:
    """Test-scale config (fits CPU mesh; same code paths as 8B)."""
    base = dict(vocab_size=256, hidden_size=64, intermediate_size=128,
                num_hidden_layers=2, num_attention_heads=4,
                num_key_value_heads=2, max_position_embeddings=128,
                rope_theta=10000.0, dtype=jnp.float32)
    base.update(overrides)
    return LlamaConfig(**base)


# ------------------------------------------------------------------- RoPE
def llama3_inv_freq(head_dim: int, theta: float,
                    rope_scaling: "Dict[str, Any]"):
    """Llama-3.1 frequency remap (matches transformers'
    _compute_llama3_parameters): low-frequency bands divide by `factor`,
    high-frequency bands stay, the middle band interpolates smoothly."""
    import numpy as np
    inv = 1.0 / (theta ** (np.arange(0, head_dim, 2, dtype=np.float32)
                           / head_dim))
    factor = rope_scaling["factor"]
    low_f = rope_scaling["low_freq_factor"]
    high_f = rope_scaling["high_freq_factor"]
    old_ctx = rope_scaling["original_max_position_embeddings"]
    wavelen = 2 * math.pi / inv
    out = np.where(wavelen > old_ctx / low_f, inv / factor, inv)
    smooth = (old_ctx / wavelen - low_f) / (high_f - low_f)
    smoothed = (1 - smooth) * out / factor + smooth * out
    medium = (wavelen >= old_ctx / high_f) & (wavelen <= old_ctx / low_f)
    return jnp.asarray(np.where(medium, smoothed, out))


def yarn_get_mscale(scale: float, mscale: float = 1.0) -> float:
    """YaRN attention magnitude factor (one definition, used by both the
    frequency table and DeepSeek-V3's softmax-scale adjustment)."""
    return 1.0 if scale <= 1 else 0.1 * mscale * math.log(scale) + 1.0


def yarn_params(dim: int, theta: float, rope_scaling: "Dict[str, Any]",
                max_position_embeddings: int):
    """YaRN context extension (Peng et al. 2023; matches transformers'
    _compute_yarn_parameters exactly): per-frequency blend between
    interpolated (factor-divided) and extrapolated frequencies via a
    linear ramp over the correction range, plus the attention factor
    that scales cos/sin magnitudes (HF folds mscale there, which scales
    q . k by attention_factor^2). Convention-agnostic: the returned
    inv_freq table indexes frequency i in [0, dim/2), valid for both
    rotate-half (Llama/Qwen) and interleaved (DeepSeek) RoPE."""
    import numpy as np
    factor = rope_scaling["factor"]
    attention_factor = rope_scaling.get("attention_factor")
    mscale = rope_scaling.get("mscale")
    mscale_all_dim = rope_scaling.get("mscale_all_dim")
    orig = (rope_scaling.get("original_max_position_embeddings")
            or max_position_embeddings)

    if attention_factor is None:
        if mscale and mscale_all_dim:
            attention_factor = float(yarn_get_mscale(factor, mscale)
                                     / yarn_get_mscale(factor,
                                                       mscale_all_dim))
        else:
            attention_factor = yarn_get_mscale(factor)
    beta_fast = rope_scaling.get("beta_fast") or 32
    beta_slow = rope_scaling.get("beta_slow") or 1

    def correction_dim(num_rot):
        return (dim * math.log(orig / (num_rot * 2 * math.pi))
                / (2 * math.log(theta)))

    low, high = correction_dim(beta_fast), correction_dim(beta_slow)
    if rope_scaling.get("truncate", True):
        low, high = math.floor(low), math.ceil(high)
    low, high = max(low, 0), min(high, dim - 1)
    if low == high:
        high += 0.001
    ramp = np.clip((np.arange(dim // 2, dtype=np.float32) - low)
                   / (high - low), 0, 1)
    pos_freqs = theta ** (np.arange(0, dim, 2, dtype=np.float32) / dim)
    inv_extra = 1.0 / pos_freqs
    inv_inter = 1.0 / (factor * pos_freqs)
    extra_factor = 1.0 - ramp
    inv_freq = inv_inter * (1 - extra_factor) + inv_extra * extra_factor
    return jnp.asarray(inv_freq), float(attention_factor)


ROPE_SCALING_TYPES = ("llama3", "yarn", "linear", "default")


def rope_params_from_scaling(head_dim: int, theta: float,
                             rope_scaling: "Optional[Dict[str, Any]]",
                             max_position_embeddings: int):
    """HF ``rope_scaling`` dict -> (inv_freq override or None,
    attention_scaling). Dispatches on type: llama3 (3.1 wavelength
    interpolation), yarn, linear (positional interpolation), default.
    Reference: transformers modeling_rope_utils ROPE_INIT_FUNCTIONS."""
    if not rope_scaling:
        return None, 1.0
    rtype = rope_scaling.get("rope_type", rope_scaling.get("type",
                                                           "default"))
    if rtype == "default":
        return None, 1.0
    if rtype == "llama3":
        return llama3_inv_freq(head_dim, theta, rope_scaling), 1.0
    if rtype == "yarn":
        return yarn_params(head_dim, theta, rope_scaling,
                           max_position_embeddings)
    if rtype == "linear":
        inv = 1.0 / (theta ** (jnp.arange(0, head_dim, 2,
                                          dtype=jnp.float32) / head_dim))
        return inv / rope_scaling["factor"], 1.0
    raise ValueError(f"rope_scaling type {rtype!r} not supported "
                     f"({'/'.join(ROPE_SCALING_TYPES)} are)")


def rotary_cos_sin(positions, head_dim: int, theta: float, dtype,
                   inv_freq=None, attention_scaling: float = 1.0):
    """positions [b, s] -> (cos, sin) [b, s, 1, head_dim/2], fp32 math.
    ``inv_freq`` overrides the plain schedule (Llama-3.1 / yarn / linear
    scaling); ``attention_scaling`` multiplies the magnitudes (YaRN's
    mscale — scales q.k by its square, as transformers does)."""
    if inv_freq is None:
        inv_freq = 1.0 / (theta ** (jnp.arange(0, head_dim, 2,
                                               dtype=jnp.float32)
                                    / head_dim))
    angles = positions.astype(jnp.float32)[..., None] * inv_freq  # [b,s,hd/2]
    cos, sin = jnp.cos(angles), jnp.sin(angles)
    if attention_scaling != 1.0:
        cos, sin = cos * attention_scaling, sin * attention_scaling
    return (cos[:, :, None, :].astype(dtype),
            sin[:, :, None, :].astype(dtype))


def apply_rotary(x, cos, sin):
    """x [b, s, h, d]; rotate-half convention (Llama/GPT-NeoX style)."""
    x1, x2 = jnp.split(x, 2, axis=-1)
    return jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1)


# -------------------------------------------------------------- components
class LlamaAttention(Layer):
    def __init__(self, config: LlamaConfig, layer_idx: int = 0):
        super().__init__()
        self.config = config
        mwl = getattr(config, "max_window_layers", None)
        # HF-Qwen2 semantics: the window applies from max_window_layers on
        self.window = (config.sliding_window
                       if getattr(config, "sliding_window", None) is not None
                       and (mwl is None or layer_idx >= mwl) else None)
        rs = getattr(config, "rope_scaling", None)
        self._inv_freq, self._attn_scaling = rope_params_from_scaling(
            config.head_dim, config.rope_theta, rs,
            config.max_position_embeddings)
        h, kv = config.num_attention_heads, config.num_key_value_heads
        d = config.head_dim
        qkv_bias = config.attention_bias
        self.q_proj = ColumnParallelLinear(config.hidden_size, h * d,
                                           has_bias=qkv_bias, gather_output=False)
        self.k_proj = ColumnParallelLinear(config.hidden_size, kv * d,
                                           has_bias=qkv_bias, gather_output=False)
        self.v_proj = ColumnParallelLinear(config.hidden_size, kv * d,
                                           has_bias=qkv_bias, gather_output=False)
        self.o_proj = RowParallelLinear(h * d, config.hidden_size,
                                        has_bias=False, input_is_parallel=True)

    @staticmethod
    def _sp_degree() -> int:
        from ..distributed.env import get_mesh, has_mesh
        return get_mesh().shape.get("sp", 1) if has_mesh() else 1

    def forward(self, x, positions, kv_cache: Optional[Tuple] = None,
                cache_index=None, attn_mask=None, attn_start=None,
                segment_ids=None, paged_chunk: bool = False,
                paged_decode: bool = False):
        cfg = self.config
        b, s, _ = x.shape
        nh, kvh, d = (cfg.num_attention_heads, cfg.num_key_value_heads,
                      cfg.head_dim)
        if hasattr(self, "qkv_proj"):
            # serving fusion (nn.fuse.fuse_projections): ONE matmul. The
            # fused columns are rank-interleaved [q_t|k_t|v_t per tp rank
            # t] so this split is shard-local under a tp mesh: expose the
            # T axis, slice heads inside each rank's chunk, merge back
            # (T == 1 degenerates to the plain [q|k|v] split).
            qkv = self.qkv_proj(x)
            T = getattr(self, "_fused_tp", 1)
            qkv = qkv.reshape(b, s, T, (nh + 2 * kvh) // T, d)
            q = qkv[:, :, :, :nh // T].reshape(b, s, nh, d)
            k = qkv[:, :, :, nh // T:(nh + kvh) // T].reshape(b, s, kvh, d)
            v = qkv[:, :, :, (nh + kvh) // T:].reshape(b, s, kvh, d)
        else:
            q = self.q_proj(x).reshape(b, s, nh, d)
            k = self.k_proj(x).reshape(b, s, kvh, d)
            v = self.v_proj(x).reshape(b, s, kvh, d)
        cos, sin = rotary_cos_sin(positions, cfg.head_dim, cfg.rope_theta,
                                  q.dtype, inv_freq=self._inv_freq,
                                  attention_scaling=self._attn_scaling)
        q, k = apply_rotary(q, cos, sin), apply_rotary(k, cos, sin)
        # heads sharded over tp
        q = constraint(q, None, None, "tp", None)
        k = constraint(k, None, None, "tp", None)
        v = constraint(v, None, None, "tp", None)

        new_cache = None
        if kv_cache is not None:
            from ..generation.paged import (PagedKV, paged_chunk_attention,
                                            paged_decode_attention,
                                            paged_decode_write,
                                            paged_prefill_write)
        if kv_cache is not None and isinstance(kv_cache, PagedKV):
            # paged serving (generation/paged.py): block-table cache.
            # s == 1 (or paged_decode=True at any s — the speculative
            # verify's multi-query rows, ISSUE 7): scatter-write the
            # tokens at each row's cursor, attend over the row's
            # gathered blocks with per-position causal masking. Other
            # s > 1: prefill — write the prompt's K/V into its blocks;
            # whole-prompt prefill is plain causal attention over the
            # prompt itself (pad tail lands in the garbage block and
            # produces discarded rows), while a CHUNK (paged_chunk=
            # True, positions carry the global offset) must also attend
            # to the earlier chunks already in the row's blocks.
            if s == 1 or paged_decode:
                new_cache = paged_decode_write(kv_cache, k, v)
                out = paged_decode_attention(q, new_cache,
                                             window=self.window)
            elif paged_chunk:
                new_cache = paged_prefill_write(kv_cache, k, v,
                                                positions=positions[0])
                out = paged_chunk_attention(q, new_cache, positions,
                                            window=self.window)
            else:
                new_cache = paged_prefill_write(kv_cache, k, v)
                out = dense_attention(q, k, v, causal=True,
                                      window=self.window)
        elif kv_cache is not None:
            # static-shape decode: write current k/v at cache_index
            ck, cv = kv_cache
            ck = jax.lax.dynamic_update_slice(ck, k.astype(ck.dtype),
                                              (0, cache_index, 0, 0))
            cv = jax.lax.dynamic_update_slice(cv, v.astype(cv.dtype),
                                              (0, cache_index, 0, 0))
            new_cache = (ck, cv)
            if s == 1 and attn_start is None:
                # single-token decode: Pallas masked-MHA kernel (GQA-
                # native, no KV repeat) / grouped-einsum fallback
                out = decode_attention(q, ck, cv, cache_index,
                                       window=self.window)
            elif isinstance(cache_index, int) and cache_index == 0 \
                    and attn_start is None and cfg.use_flash_attention \
                    and use_flash(q, k, None, 0.0):
                # prefill at cache start: nothing earlier in the cache
                # can be attended, so this is plain causal attention
                # over the prompt — take the flash kernel instead of
                # the masked-dense-over-full-cache path (O(s*T) scores
                # and memory for a [s, T] mask). K/V go through the
                # cache dtype so prefill numerics match what decode
                # steps will read back
                out = flash_attention(q, k.astype(ck.dtype),
                                      v.astype(cv.dtype), causal=True,
                                      window=self.window)
            else:
                # prefill-with-cache (and left-padded serving batches):
                # mask positions beyond cache_index+s; with attn_start,
                # also mask each row's pad prefix out of the cache
                total = ck.shape[1]
                kpos = jnp.arange(total)[None, :]           # [1, T]
                qpos = cache_index + jnp.arange(s)[:, None]  # [s, 1]
                mask = (kpos <= qpos)[None, None]           # [1, 1, s, T]
                if self.window is not None:
                    mask = mask & \
                        (qpos - kpos < self.window)[None, None]
                if attn_start is not None:
                    pad_ok = kpos[None] >= attn_start[:, None, None]
                    # pad-prefix queries keep their own position: an
                    # all-masked softmax row is NaN, and that NaN would
                    # re-enter REAL rows in the next layer as 0 * NaN
                    # through masked-out values
                    self_ok = (kpos == qpos)[None]
                    mask = mask & (pad_ok | self_ok)[:, None]  # [b,1,s,T]
                out = dense_attention(q, ck, cv, attn_mask=mask)
        elif cfg.sequence_parallel and attn_mask is None and \
                self._sp_degree() > 1:
            # ring attention: seq stays sp-sharded; KV blocks rotate on
            # ICI. segment_ids (packed SFT) rotate with the KV blocks and
            # a sliding window narrows the causal band with GLOBAL
            # positions — both compose with context parallelism.
            import functools
            from jax.sharding import PartitionSpec as P
            from ..distributed.env import get_mesh
            from ..parallel.ring import ring_attention
            spec = P(("dp", "fsdp"), "sp", "tp", None)
            ring = functools.partial(ring_attention, axis_name="sp",
                                     causal=True, window=self.window)
            from ..utils.jax_compat import shard_map
            if segment_ids is not None:
                sspec = P(("dp", "fsdp"), "sp")
                out = shard_map(
                    lambda q, k, v, seg: ring(q, k, v, segment_ids=seg),
                    mesh=get_mesh(), in_specs=(spec,) * 3 + (sspec,),
                    out_specs=spec, check_vma=False)(q, k, v, segment_ids)
            else:
                out = shard_map(
                    ring, mesh=get_mesh(), in_specs=(spec,) * 3,
                    out_specs=spec, check_vma=False)(q, k, v)
        elif cfg.use_flash_attention and attn_mask is None and use_flash(q, k, None, 0.0):
            # segment_ids ride the flash kernel (packed sequences): the
            # same-segment mask applies inside the online softmax; a
            # sliding window narrows the causal band in-kernel
            out = flash_attention(q, k, v, causal=True,
                                  segment_ids=segment_ids,
                                  window=self.window)
        elif segment_ids is not None and attn_mask is None:
            from ..ops.attention import segment_mask
            out = dense_attention(q, k, v, causal=True,
                                  attn_mask=segment_mask(segment_ids),
                                  window=self.window)
        elif self.window is not None:
            # an explicit mask COMBINES with the window band (HF
            # intersects them); causal-decoder masks are within causal
            # context, so forcing causal=True only narrows
            out = dense_attention(q, k, v, causal=True,
                                  attn_mask=attn_mask, window=self.window)
        else:
            out = dense_attention(q, k, v, causal=attn_mask is None,
                                  attn_mask=attn_mask)
        out = out.reshape(b, s, cfg.num_attention_heads * cfg.head_dim)
        out = self.o_proj(out)
        return (out, new_cache) if kv_cache is not None else out


class LlamaMLP(Layer):
    def __init__(self, config: LlamaConfig):
        super().__init__()
        self.gate_proj = ColumnParallelLinear(config.hidden_size,
                                              config.intermediate_size,
                                              has_bias=False, gather_output=False)
        self.up_proj = ColumnParallelLinear(config.hidden_size,
                                            config.intermediate_size,
                                            has_bias=False, gather_output=False)
        self.down_proj = RowParallelLinear(config.intermediate_size,
                                           config.hidden_size, has_bias=False,
                                           input_is_parallel=True)

    def forward(self, x):
        if hasattr(self, "gate_up_proj"):
            # serving fusion (nn.fuse.fuse_projections): ONE matmul with
            # rank-interleaved [gate_t|up_t] columns — shard-local split
            # under tp, plain halves when T == 1
            gu = self.gate_up_proj(x)
            T = getattr(self, "_fused_tp", 1)
            ffn = gu.shape[-1] // 2
            gu = gu.reshape(*gu.shape[:-1], T, 2, ffn // T)
            gate = gu[..., 0, :].reshape(*gu.shape[:-3], ffn)
            up = gu[..., 1, :].reshape(*gu.shape[:-3], ffn)
            return self.down_proj(F.silu(gate) * up)
        return self.down_proj(F.silu(self.gate_proj(x)) * self.up_proj(x))


class LlamaDecoderLayer(Layer):
    def __init__(self, config: LlamaConfig, layer_idx: int = 0):
        super().__init__()
        self.config = config
        self.input_layernorm = nn.RMSNorm(config.hidden_size, config.rms_norm_eps)
        self.self_attn = LlamaAttention(config, layer_idx=layer_idx)
        self.post_attention_layernorm = nn.RMSNorm(config.hidden_size,
                                                   config.rms_norm_eps)
        self.mlp = LlamaMLP(config)

    def forward(self, x, positions, kv_cache=None, cache_index=None,
                attn_mask=None, attn_start=None, segment_ids=None,
                paged_chunk: bool = False, paged_decode: bool = False):
        attn_out = self.self_attn(self.input_layernorm(x), positions,
                                  kv_cache=kv_cache, cache_index=cache_index,
                                  attn_mask=attn_mask, attn_start=attn_start,
                                  segment_ids=segment_ids,
                                  paged_chunk=paged_chunk,
                                  paged_decode=paged_decode)
        new_cache = None
        if kv_cache is not None:
            attn_out, new_cache = attn_out
        x = x + attn_out
        x = x + self.mlp(self.post_attention_layernorm(x))
        x = constraint(x, ("dp", "fsdp"), "sp", None)
        return (x, new_cache) if kv_cache is not None else x


class LlamaModel(Layer):
    def __init__(self, config: LlamaConfig):
        super().__init__()
        self.config = config
        self.embed_tokens = VocabParallelEmbedding(config.vocab_size,
                                                   config.hidden_size)
        self.embed_tokens.weight = self.embed_tokens.weight.astype(config.dtype) \
            * jnp.asarray(config.initializer_range / 0.02, config.dtype)
        self.layers = nn.LayerList(
            [LlamaDecoderLayer(config, layer_idx=i)
             for i in range(config.num_hidden_layers)])
        self.norm = nn.RMSNorm(config.hidden_size, config.rms_norm_eps)
        if config.dtype != jnp.float32:
            # compute-weight dtype (fp32 masters live in the optimizer)
            self.to(dtype=config.dtype)

    def forward(self, input_ids, positions=None, kv_caches=None,
                cache_index=None, attn_mask=None, attn_start=None,
                segment_ids=None, paged_chunk: bool = False,
                paged_decode: bool = False):
        b, s = input_ids.shape
        if positions is None:
            start = cache_index if cache_index is not None else 0
            positions = start + jnp.arange(s)[None, :].repeat(b, axis=0)
            if attn_start is not None:
                # left-padded rows: RoPE position 0 sits at each row's
                # first REAL token, not at the pad prefix
                positions = jnp.maximum(positions - attn_start[:, None], 0)
        x = self.embed_tokens(input_ids)
        x = constraint(x, ("dp", "fsdp"), "sp", None)
        new_caches = [] if kv_caches is not None else None
        for i, layer in enumerate(self.layers):
            cache_i = kv_caches[i] if kv_caches is not None else None
            if self.config.recompute and kv_caches is None:
                out = jax.checkpoint(
                    lambda h, lyr=layer: lyr(h, positions, attn_mask=attn_mask,
                                             segment_ids=segment_ids),
                    prevent_cse=False,
                    policy=POLICIES[self.config.recompute_policy])(x)
            else:
                out = layer(x, positions, kv_cache=cache_i,
                            cache_index=cache_index, attn_mask=attn_mask,
                            attn_start=attn_start, segment_ids=segment_ids,
                            paged_chunk=paged_chunk,
                            paged_decode=paged_decode)
            if kv_caches is not None:
                x, nc = out
                new_caches.append(nc)
            else:
                x = out
        x = self.norm(x)
        return (x, new_caches) if kv_caches is not None else x


class LlamaForCausalLM(CausalLMBase):
    def __init__(self, config: LlamaConfig):
        super().__init__()
        self.config = config
        self.model = LlamaModel(config)
        if not config.tie_word_embeddings:
            self.lm_head = ColumnParallelLinear(config.hidden_size,
                                                config.vocab_size,
                                                has_bias=False,
                                                gather_output=True)
            if config.dtype != jnp.float32:
                self.lm_head.to(dtype=config.dtype)

    def pipeline_functional(self, pp: int, logits_loss=None, vpp: int = 1):
        """1F1B pipeline train step over ``pp`` stages (Trainer pp path).
        ``logits_loss(logits, labels) -> scalar mean`` swaps the last-stage
        loss head (default: shifted causal-LM cross-entropy). ``vpp`` > 1
        interleaves that many virtual chunks per device (Megatron-style),
        shrinking the pipeline bubble vpp-fold."""
        return llama_pipeline_functional(self, pp, logits_loss=logits_loss,
                                         vpp=vpp)

    def forward(self, input_ids, positions=None, kv_caches=None,
                cache_index=None, attn_mask=None, attn_start=None,
                segment_ids=None, paged_chunk: bool = False,
                paged_decode: bool = False):
        out = self.model(input_ids, positions, kv_caches, cache_index,
                         attn_mask, attn_start, segment_ids=segment_ids,
                         paged_chunk=paged_chunk,
                         paged_decode=paged_decode)
        caches = None
        if kv_caches is not None:
            out, caches = out
        if self.config.tie_word_embeddings:
            logits = parallel_matmul(out, self.model.embed_tokens.weight,
                                     transpose_y=True)
        else:
            logits = self.lm_head(out)
        logits = logits.astype(jnp.float32)  # CE in fp32 for stability
        return (logits, caches) if kv_caches is not None else logits


def causal_lm_loss(logits, labels, ignore_index: int = -100):
    """Shifted next-token CE: logits [b, s, v], labels [b, s]."""
    shift_logits = logits[:, :-1]
    shift_labels = labels[:, 1:]
    return F.cross_entropy(shift_logits, shift_labels,
                           ignore_index=ignore_index, reduction="mean")


# ------------------------------------------------------- pipeline parallel
def llama_pipeline_functional(model: "LlamaForCausalLM", pp: int,
                              logits_loss=None, vpp: int = 1):
    """Wire a LlamaForCausalLM into the 1F1B pipeline (reference:
    fleet.meta_parallel.PipelineLayer's LayerDesc segmentation — embedding
    at stage 0, ``num_hidden_layers/pp`` LlamaDecoderLayers per stage,
    final-norm+lm_head at the last stage).

    Returns ``vag(flat_params, tokens[M, b, s]) -> (loss, flat_grads)``:
    flat params stay the single source of truth (optimizer/checkpoint
    layout unchanged); the stage re-stack to [pp, layers_per_stage, ...]
    happens inside the jitted step, where XLA turns it into resharding.
    """
    from jax import lax as _lax

    from ..parallel.pipeline import pipeline_value_and_grad

    cfg = model.config
    L = cfg.num_hidden_layers
    S = pp * vpp  # global stages (vpp chunks per device when interleaved)
    if L % S != 0:
        raise ValueError(f"num_hidden_layers {L} % (pp*vpp) {S} != 0")
    if cfg.tie_word_embeddings:
        raise ValueError("pipeline requires untied embeddings (the tied "
                         "table would live on two stages)")
    n_per = L // S
    layer_fn, layer_p0 = model.model.layers[0].functional()
    embed_fn, _ = model.model.embed_tokens.functional()
    norm_fn, _ = model.model.norm.functional()
    lm_fn, _ = model.lm_head.functional()
    rel_keys = list(layer_p0)

    def _stage_stack(flat, k, g):
        """One global stage's [n_per, ...] stack for param k."""
        return jnp.stack([flat[f"model.layers.{g * n_per + i}.{k}"]
                          for i in range(n_per)])

    def split(flat):
        if vpp == 1:
            stages = {k: jnp.stack([_stage_stack(flat, k, g)
                                    for g in range(pp)])
                      for k in rel_keys}
        else:
            # [v, pp, n_per, ...]: chunk c on device d is global stage
            # g = c*pp + d (round-robin layout — consecutive stages on
            # consecutive devices so the interleaved ring handoff works)
            stages = {k: jnp.stack([
                jnp.stack([_stage_stack(flat, k, c * pp + d)
                           for d in range(pp)]) for c in range(vpp)])
                for k in rel_keys}
        embed = {k[len("model.embed_tokens."):]: v for k, v in flat.items()
                 if k.startswith("model.embed_tokens.")}
        head = {"norm": {k[len("model.norm."):]: v for k, v in flat.items()
                         if k.startswith("model.norm.")},
                "lm": {k[len("lm_head."):]: v for k, v in flat.items()
                       if k.startswith("lm_head.")}}
        return {"embed": embed, "stages": stages, "head": head}

    def merge(pp_grads):
        flat = {}
        for k, v in pp_grads["stages"].items():
            for g in range(S):
                for i in range(n_per):
                    layer = f"model.layers.{g * n_per + i}.{k}"
                    if vpp == 1:
                        flat[layer] = v[g, i]
                    else:
                        flat[layer] = v[g // pp, g % pp, i]
        flat.update({f"model.embed_tokens.{k}": v
                     for k, v in pp_grads["embed"].items()})
        flat.update({f"model.norm.{k}": v
                     for k, v in pp_grads["head"]["norm"].items()})
        flat.update({f"lm_head.{k}": v
                     for k, v in pp_grads["head"]["lm"].items()})
        return flat

    # MoE decoder layers return (x, aux_loss); the pipeline threads the
    # aux term through each stage's own backward (pp x ep composition)
    probe = jax.eval_shape(
        lambda lp: layer_fn(lp, jnp.zeros((1, 8, cfg.hidden_size)),
                            jnp.zeros((1, 8), jnp.int32)), layer_p0)
    layer_has_aux = isinstance(probe, (tuple, list))

    def stage_fn(sp, x):
        b, sl = x.shape[0], x.shape[1]
        positions = jnp.broadcast_to(jnp.arange(sl)[None, :], (b, sl))

        if layer_has_aux:
            def one(carry, lp):
                xx, aux = carry
                yy, a = layer_fn(lp, xx, positions)
                return (yy, aux + a), None
            (y, aux), _ = _lax.scan(one, (x, jnp.float32(0.0)), sp)
            return y, aux

        def one(xx, lp):
            return layer_fn(lp, xx, positions), None
        y, _ = _lax.scan(one, x, sp)
        return y

    loss_head = logits_loss or causal_lm_loss

    def head_loss_fn(hp, y, labels):
        h = norm_fn(hp["norm"], y)
        logits = lm_fn(hp["lm"], h).astype(jnp.float32)
        return loss_head(logits, labels)

    if vpp == 1:
        run = pipeline_value_and_grad(embed_fn, stage_fn, head_loss_fn, pp)
    else:
        from ..parallel.pipeline_interleaved import \
            interleaved_pipeline_value_and_grad
        run = interleaved_pipeline_value_and_grad(
            embed_fn, stage_fn, head_loss_fn, pp, vpp)

    def vag(flat_params, tokens):
        loss, grads = run(split(flat_params), tokens, tokens)
        return loss, merge(grads)

    return vag
