#!/usr/bin/env python
"""Render a run dir's observability artifacts (ISSUE 5): step-time
p50/p99, MFU/throughput, stall counters, and the fault/rollback/
checkpoint event timeline from the flight recorder — the "what
happened to this run" one-pager.

    python tools/obs_report.py runs/                 # human summary
    python tools/obs_report.py runs/ --json          # machine-readable
    python tools/obs_report.py runs/ --serve 9090    # /metrics scrape
    python tools/obs_report.py --check               # CI self-test

A run dir (``<output_dir>/runs`` for the Trainer) holds:

- ``metrics.jsonl``  — LogWriter scalars + merged registry publishes
- ``metrics.prom``   — Prometheus text snapshot (what ``--serve`` serves)
- ``trace_<k>.json`` — chrome-trace spans per elastic attempt k
                       (load in Perfetto / chrome://tracing)
- ``flight_<k>.json``— flight-recorder dump per attempt (crash /
                       preemption / rollback postmortems)
- ``flight_supervisor.json`` / ``metrics_supervisor.prom`` — the
  elastic supervisor's own child-launch/exit events and
  restart/preemption counters (``supervise(run_dir=…)`` / ``--run-dir``)

``--check`` builds a synthetic run dir with the observability library
itself, re-parses it, and exits nonzero if the schema drifted —
runnable in CI with no devices.
"""
import argparse
import glob
import json
import os
import sys
from typing import Any, Dict, List, Optional

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

# event kinds that belong on the human timeline (per-step step_end
# records feed the latency stats instead — hundreds of them would
# drown the signal)
TIMELINE_KINDS = (
    "train_start", "fault_fire", "divergence", "rollback",
    "preempt_latch", "preempt_exit", "preempt_ckpt_failed", "hang",
    "crash", "prefetch_stall", "ckpt_save", "ckpt_restore",
    "ckpt_committed", "eval", "elastic_child_launch",
    "elastic_child_exit", "serve_reject", "serve_preempt",
    # SLO burn-rate incidents (ISSUE 15): the flight recorder holds
    # them beside the replica failures that caused them
    "alert_fire", "alert_resolve",
)


def _percentile(xs: List[float], q: float) -> float:
    if not xs:
        return 0.0
    xs = sorted(xs)
    idx = q * (len(xs) - 1)
    lo, hi = int(idx), min(int(idx) + 1, len(xs) - 1)
    return xs[lo] + (xs[hi] - xs[lo]) * (idx - lo)


def _load_jsonl(path: str) -> Dict[str, List]:
    """tag -> [(step, value)] series from a LogWriter stream."""
    series: Dict[str, List] = {}
    if not os.path.exists(path):
        return series
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
                series.setdefault(rec["tag"], []).append(
                    (rec["step"], rec["value"]))
            except (ValueError, KeyError):
                continue   # torn tail line from a crash: skip, don't die
    return series


def _load_flights(run_dir: str) -> List[dict]:
    flights = []
    for path in sorted(glob.glob(os.path.join(run_dir, "flight_*.json"))):
        try:
            with open(path) as f:
                doc = json.load(f)
            doc["_file"] = os.path.basename(path)
            flights.append(doc)
        except (OSError, ValueError):
            continue
    return flights


def _load_prom(path: str) -> Dict[str, float]:
    prom: Dict[str, float] = {}
    if not os.path.exists(path):
        return prom
    for line in open(path):
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        try:
            name, value = line.rsplit(" ", 1)
            prom[name] = float(value)
        except ValueError:
            continue
    return prom


def _load_traces(run_dir: str) -> List[dict]:
    traces = []
    for path in sorted(glob.glob(os.path.join(run_dir, "trace_*.json"))):
        try:
            with open(path) as f:
                doc = json.load(f)
            doc["_file"] = os.path.basename(path)
            traces.append(doc)
        except (OSError, ValueError):
            continue
    return traces


def _load_tickphase(run_dir: str) -> List[dict]:
    """Load + schema-validate the ``tickphase_*.json`` phase rings a
    profiled engine (or a gateway drain / ``/profilez`` capture)
    leaves in the run dir (ISSUE 20)."""
    from paddle_tpu.utils.observability import validate_tickphase_doc
    docs = []
    for path in sorted(glob.glob(os.path.join(run_dir,
                                              "tickphase_*.json"))):
        try:
            with open(path) as f:
                doc = json.load(f)
        except (OSError, ValueError):
            continue
        if validate_tickphase_doc(doc):
            continue                 # torn/drifted file: skip, not die
        doc["_file"] = os.path.basename(path)
        docs.append(doc)
    return docs


def phase_decompose(docs: List[dict]) -> Optional[Dict[str, Any]]:
    """The ``phase_decompose`` view (ISSUE 20): split tick wall time —
    and therefore tok/s — into host / h2d / dispatch / device / drain
    SHARES per profiled engine and fleet-wide, and name the dominant
    term. This is the slope-vs-intercept read ROADMAP item 1 needs:
    device share is the slope (model compute), dispatch+host share is
    the intercept (per-tick machinery) — a tok/s gap attributed to the
    intercept is a tick-machinery problem, not a kernel problem."""
    if not docs:
        return None
    per: Dict[str, Any] = {}
    agg_tot: Dict[str, float] = {}
    agg_wall = 0.0
    agg_ticks = 0
    for d in docs:
        wall = float(d.get("wall_total_ms") or 0.0)
        tot = {k: float(v) for k, v in
               (d.get("phase_totals_ms") or {}).items()}
        name = d.get("engine") or d["_file"]
        per[name] = {
            "ticks": int(d.get("ticks") or 0),
            "wall_ms": round(wall, 3),
            "shares": {k: round(v / wall, 4) if wall > 0 else 0.0
                       for k, v in sorted(tot.items())},
        }
        agg_wall += wall
        agg_ticks += int(d.get("ticks") or 0)
        for k, v in tot.items():
            agg_tot[k] = agg_tot.get(k, 0.0) + v
    shares = {k: round(v / agg_wall, 4) if agg_wall > 0 else 0.0
              for k, v in sorted(agg_tot.items())}
    dominant = max(shares, key=shares.get) if shares else None
    return {
        "sources": [d["_file"] for d in docs],
        "ticks": agg_ticks,
        "wall_ms": round(agg_wall, 3),
        "shares": shares,
        "dominant": dominant,
        "per_engine": per,
    }


def summarize(run_dir: str) -> Dict[str, Any]:
    """Parse every artifact in ``run_dir`` into one summary dict (the
    schema ``--check`` pins)."""
    series = _load_jsonl(os.path.join(run_dir, "metrics.jsonl"))
    flights = _load_flights(run_dir)
    traces = _load_traces(run_dir)

    # step latency: flight step_end events are the primary series (they
    # survive crashes); train_step trace spans are the fallback
    step_ms = [ev["ms"] for fl in flights for ev in fl.get("events", ())
               if ev.get("kind") == "step_end" and "ms" in ev]
    span_ms = [ev["dur"] / 1e3 for tr in traces
               for ev in tr.get("traceEvents", ())
               if ev.get("name") == "train_step" and "dur" in ev]
    lat = step_ms or span_ms

    def last(tag: str) -> Optional[float]:
        return series[tag][-1][1] if series.get(tag) else None

    timeline = sorted(
        (ev for fl in flights for ev in fl.get("events", ())
         if ev.get("kind") in TIMELINE_KINDS),
        key=lambda ev: ev.get("wall", 0.0))

    prom = _load_prom(os.path.join(run_dir, "metrics.prom"))
    # the supervisor process keeps its own registry (children can't
    # count their own relaunches): a separate snapshot, merged here
    sup_prom = _load_prom(os.path.join(run_dir,
                                       "metrics_supervisor.prom"))

    def prom_sum(prefix: str, src: Optional[Dict[str, float]] = None
                 ) -> float:
        return sum(v for k, v in (prom if src is None else src).items()
                   if k.split("{")[0] == prefix)

    return {
        "run_dir": os.path.abspath(run_dir),
        # the supervisor's flight doc is not a child attempt
        "attempts": sorted({fl.get("attempt", 0) for fl in flights
                            if fl["_file"] != "flight_supervisor.json"}),
        "flight_reasons": [(fl["_file"], fl.get("reason"))
                           for fl in flights],
        "steps_recorded": len(lat),
        "step_ms": {
            "p50": round(_percentile(lat, 0.5), 3),
            "p99": round(_percentile(lat, 0.99), 3),
            "mean": round(sum(lat) / len(lat), 3) if lat else 0.0,
            "max": round(max(lat), 3) if lat else 0.0,
        },
        "train": {
            "loss": last("loss") if last("loss") is not None
            else last("train_loss"),
            "mfu": last("mfu") if last("mfu") is not None
            else last("train_mfu"),
            "tokens_per_sec": last("tokens_per_sec")
            if last("tokens_per_sec") is not None
            else last("train_tokens_per_sec"),
        },
        "counters": {
            "prefetch_sync_fallbacks":
                prom_sum("prefetch_sync_fallbacks_total"),
            "prefetch_stall_degradations":
                prom_sum("prefetch_stall_degradations_total"),
            "fault_fires": prom_sum("fault_fires_total"),
            "rollbacks": prom_sum("train_rollbacks_total"),
            "train_steps": prom_sum("train_steps_total"),
            "elastic_restarts":
                prom_sum("elastic_restarts_total", sup_prom),
            "elastic_preemptions":
                prom_sum("elastic_preemptions_total", sup_prom),
        },
        "trace_spans": sum(len(tr.get("traceEvents", ()))
                           for tr in traces),
        # tick-phase decomposition (ISSUE 20): present only when a
        # profiled engine left tickphase_*.json rings in the run dir
        "phase_decompose": phase_decompose(_load_tickphase(run_dir)),
        "timeline": timeline,
        "jsonl_tags": sorted(series),
    }


def render(s: Dict[str, Any]) -> str:
    import datetime
    lines = [f"run dir: {s['run_dir']}",
             f"attempts: {s['attempts'] or [0]}   "
             f"trace spans: {s['trace_spans']}   "
             f"steps recorded: {s['steps_recorded']}"]
    st = s["step_ms"]
    lines.append(f"step time  p50 {st['p50']:.1f} ms   "
                 f"p99 {st['p99']:.1f} ms   mean {st['mean']:.1f} ms   "
                 f"max {st['max']:.1f} ms")
    tr = s["train"]
    if tr["loss"] is not None:
        mfu = tr["mfu"] or 0.0
        tps = tr["tokens_per_sec"] or 0.0
        lines.append(f"train      loss {tr['loss']:.4f}   "
                     f"mfu {mfu:.2%}   tokens/s {tps:,.0f}")
    c = s["counters"]
    # metrics.prom is a per-process snapshot: after an elastic run it
    # holds the LAST attempt's registry (the timeline spans them all)
    lines.append(f"counters (last attempt)   "
                 f"steps {c['train_steps']:.0f}   "
                 f"fault fires {c['fault_fires']:.0f}   "
                 f"rollbacks {c['rollbacks']:.0f}   "
                 f"prefetch stalls "
                 f"{c['prefetch_stall_degradations']:.0f} "
                 f"(sync fallbacks {c['prefetch_sync_fallbacks']:.0f})")
    if c["elastic_restarts"] or c["elastic_preemptions"]:
        lines.append(f"supervisor restarts {c['elastic_restarts']:.0f}   "
                     f"preemptions {c['elastic_preemptions']:.0f}")
    pd = s.get("phase_decompose")
    if pd:
        sh = " ".join(f"{k} {v:.1%}" for k, v in pd["shares"].items())
        lines.append(f"tick phases ({pd['ticks']} ticks, "
                     f"{pd['wall_ms']:.0f} ms wall)   {sh}   "
                     f"dominant: {pd['dominant']}")
        for name, p in sorted(pd["per_engine"].items()):
            sh = " ".join(f"{k} {v:.1%}"
                          for k, v in p["shares"].items())
            lines.append(f"  {name}: {p['ticks']} ticks   {sh}")
    for fname, reason in s["flight_reasons"]:
        lines.append(f"flight     {fname}: {reason}")
    if s["timeline"]:
        lines.append("timeline:")
        for ev in s["timeline"][-40:]:
            wall = datetime.datetime.fromtimestamp(
                ev.get("wall", 0.0)).strftime("%H:%M:%S.%f")[:-3]
            extra = " ".join(f"{k}={v}" for k, v in ev.items()
                             if k not in ("wall", "kind"))
            lines.append(f"  {wall}  {ev['kind']:<22s} {extra}")
    return "\n".join(lines)


# ------------------------------------------------------------------ serve
def serve(run_dir: str, port: int) -> int:
    """Serve ``/metrics`` (Prometheus text, re-read per scrape) and
    ``/`` (the JSON summary) with the stdlib http server — a sidecar
    scrape endpoint with zero dependencies."""
    import http.server

    class Handler(http.server.BaseHTTPRequestHandler):
        def do_GET(self):
            if self.path.rstrip("/") == "/metrics":
                path = os.path.join(run_dir, "metrics.prom")
                try:
                    body = open(path, "rb").read()
                except OSError:
                    self.send_error(404, "no metrics.prom yet")
                    return
                self.send_response(200)
                self.send_header("Content-Type",
                                 "text/plain; version=0.0.4")
                self.end_headers()
                self.wfile.write(body)
            else:
                body = json.dumps(summarize(run_dir)).encode()
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.end_headers()
                self.wfile.write(body)

        def log_message(self, *a):   # quiet: scrapes every few seconds
            pass

    httpd = http.server.HTTPServer(("", port), Handler)
    print(f"serving {run_dir} on :{port} (/metrics for Prometheus, "
          f"/ for the JSON summary)", file=sys.stderr, flush=True)
    try:
        httpd.serve_forever()
    except KeyboardInterrupt:
        pass
    return 0


# ------------------------------------------------------------------ check
def self_check() -> int:
    """CI mode: synthesize a run dir with the observability library,
    re-parse it, and verify the summary schema — no devices, no model.
    Nonzero exit = the reader and the writer drifted apart."""
    import tempfile

    from paddle_tpu.utils import observability as obs
    from paddle_tpu.utils.logging import LogWriter

    failures: List[str] = []

    def expect(cond: bool, what: str):
        if not cond:
            failures.append(what)

    obs.reset()
    with tempfile.TemporaryDirectory() as tmp:
        run = os.path.join(tmp, "runs")
        obs.configure(run)
        # a fake 5-step run with one fault fire and a checkpoint
        writer = LogWriter(run)
        for step in range(1, 6):
            with obs.span("train_step", step=step):
                pass
            obs.counter("train_steps_total").inc()
            obs.histogram("train_step_wall_ms").observe(10.0 + step)
            obs.record_event("step_end", step=step, ms=10.0 + step)
        obs.gauge("train_mfu").set(0.41)
        obs.record_event("fault_fire", site="preempt", occurrence=0)
        obs.record_event("ckpt_save", step=5, wait=True, ms=12.5)
        writer.add_scalar("loss", 2.5, 5)
        writer.add_scalar("mfu", 0.41, 5)
        writer.add_scalar("tokens_per_sec", 123456.0, 5)
        obs.publish(writer, 5)
        writer.close()
        obs.dump_flight("preempt")
        # a fake supervisor view (separate recorder/registry — in real
        # runs it's a separate PROCESS writing these two files)
        sup = obs.FlightRecorder()
        sup.record("elastic_child_launch", attempt=0, argv0="python")
        sup.record("elastic_child_exit", attempt=0, rc=76)
        sup.dump(os.path.join(run, "flight_supervisor.json"),
                 "supervise_exit")
        sreg = obs.MetricsRegistry()
        sreg.counter("elastic_preemptions_total").inc()
        with open(os.path.join(run, "metrics_supervisor.prom"), "w") as f:
            f.write(sreg.prometheus_text())

        # request-trace ring (ISSUE 10): write one with the library,
        # re-validate with the same checker trace_report's loader
        # runs — ring writer and report reader must not drift
        from paddle_tpu.serving.reqtrace import (RequestTrace,
                                                 RequestTraceRing,
                                                 validate_ring_doc)
        ring = RequestTraceRing(capacity=8, slow_ttft_ms=50.0,
                                labels={"gateway": "chk",
                                        "replica": "r0"})
        slow = RequestTrace("chk-slow", slo="interactive")
        for t, kind, fields in (
                (0.0, "accept", {}), (0.1, "queue_enter", {}),
                (10.0, "queue_leave", {}), (10.1, "slot_take", {}),
                (40.0, "prefill_done", {}),
                (80.0, "first_token", {}), (90.0, "finish", {})):
            slow.ev(kind, t_ms=t, **fields)
        ring.finish(slow, "stop", tokens=4)
        fast = RequestTrace("chk-fast", slo="interactive")
        for t, kind in ((0.0, "accept"), (0.1, "queue_enter"),
                        (0.5, "slot_take"), (1.0, "prefill_done"),
                        (2.0, "first_token")):
            fast.ev(kind, t_ms=t)
        ring.finish(fast, "stop", tokens=4)
        shed = RequestTrace("chk-shed", slo="batch")
        shed.ev("accept", t_ms=0.0)
        shed.ev("shed", t_ms=0.2)
        ring.finish(shed, "shed")
        ring_path = os.path.join(run, "reqtrace_chk_r0.json")
        ring.dump(ring_path)
        with open(ring_path) as f:
            ring_doc = json.load(f)
        problems = validate_ring_doc(ring_doc)
        expect(not problems,
               f"trace-ring schema drift: {problems[:3]}")
        by_id = {e["request_id"]: e for e in ring_doc["entries"]}
        expect(by_id["chk-slow"]["retained"]
               and by_id["chk-slow"]["events"],
               "slow request's full timeline not retained")
        expect(not by_id["chk-fast"]["retained"]
               and not by_id["chk-fast"]["events"],
               "fast healthy request not tail-dropped")
        expect(by_id["chk-shed"]["retained"],
               "shed request not retained")
        expect(by_id["chk-slow"]["queue_wait_ms"] == 10.0
               and by_id["chk-slow"]["prefill_ms"] == 29.9
               and by_id["chk-slow"]["first_tick_ms"] == 40.0,
               "attribution decomposition wrong")

        # time-series + alert-log document (ISSUE 15): write one with
        # the library (injected clock — rate derivation is PINNED to
        # exact values), round-trip through JSON, re-validate with the
        # same checker fleet_dash's loader runs
        from paddle_tpu.serving.slo import BurnRateEngine, BurnRule
        from paddle_tpu.utils.observability import (MetricsTimeSeries,
                                                    validate_series_doc)
        sreg2 = obs.MetricsRegistry()
        tok = sreg2.counter("toks_total")
        q = sreg2.gauge("queue")
        lat = sreg2.histogram("lat_ms", buckets=(1, 2, 5))
        clk = [0.0]
        ts = MetricsTimeSeries(name="chk", registry=sreg2,
                               interval_s=1.0, capacity=4,
                               clock=lambda: clk[0])
        for i in range(6):
            clk[0] = float(i)
            tok.inc(5)
            q.set(i)
            lat.observe(1.5)
            ts.sample()
        expect(len(ts.series("toks_total")) == 4,
               "series ring bound not enforced")
        w = ts.window(3.0, now=5.0)
        expect(w["toks_total"]["rate_per_s"] == 5.0,
               "counter rate derivation drifted "
               f"(got {w['toks_total']['rate_per_s']})")
        expect(w["queue"]["mean"] == 3.5,
               "gauge window mean drifted")
        expect(w["lat_ms"]["p50"] == 1.5 and w["lat_ms"]["count"] == 3,
               "windowed histogram quantile drifted")
        bclk = [0.0]
        beng = BurnRateEngine(targets={"interactive": 0.9},
                              rules=(BurnRule("page", 5.0, 20.0,
                                              2.0),),
                              clock=lambda: bclk[0])
        for i in range(20):
            bclk[0] = float(i)
            beng.observe("interactive", True)
        for i in range(5):
            bclk[0] = 20.0 + i
            beng.observe("interactive", False)
        for i in range(40):
            bclk[0] = 26.0 + i
            beng.observe("interactive", True)
        kinds_seq = [a["kind"] for a in beng.alerts]
        expect(kinds_seq == ["fire", "resolve"],
               f"burn-rate fire/resolve sequence drifted: {kinds_seq}")
        series_path = os.path.join(run, "series_chk.json")
        ts.dump(series_path, alerts=beng.alerts)
        with open(series_path) as f:
            series_doc = json.load(f)
        problems = validate_series_doc(series_doc)
        expect(not problems,
               f"time-series schema drift: {problems[:3]}")
        expect(series_doc["alerts"][0]["slo"] == "interactive",
               "alert log lost the SLO class")
        broken = json.loads(json.dumps(series_doc))
        broken["metrics"]["toks_total"]["samples"][0][1] = 1e9
        expect(any("regressed" in p
                   for p in validate_series_doc(broken)),
               "counter regression not caught by the validator")

        # tick-phase ring (ISSUE 20): synthesize one with the library's
        # validator vocabulary, re-validate, and pin the decompose math
        from paddle_tpu.utils.observability import (
            TICK_PHASES, validate_tickphase_doc)
        tp_doc = {
            "schema": "tickphase/1", "engine": "chk-e0",
            "dumped_wall": 1000.0, "clock_now": 10.0, "capacity": 8,
            "ticks": 2, "wall_total_ms": 10.0,
            "phase_totals_ms": {"host": 2.0, "h2d": 1.0,
                                "dispatch": 5.0, "device": 1.5,
                                "drain": 0.5},
            "entries": [
                {"tick": k, "t": 9.0 + k, "wall_ms": 5.0,
                 "host_ms": 1.0, "h2d_ms": 0.5, "dispatch_ms": 2.5,
                 "device_ms": 0.75, "drain_ms": 0.25,
                 "dispatches": 1, "uploads": 0, "bytes": 0,
                 "patches": 0, "active": 2} for k in range(2)],
        }
        problems = validate_tickphase_doc(tp_doc)
        expect(not problems,
               f"tickphase schema drift: {problems[:3]}")
        expect(set(tp_doc["phase_totals_ms"]) == set(TICK_PHASES),
               "TICK_PHASES vocabulary drifted")
        broken_tp = json.loads(json.dumps(tp_doc))
        broken_tp["entries"][0]["host_ms"] = 99.0
        expect(any("sum" in p
                   for p in validate_tickphase_doc(broken_tp)),
               "phase-sum != wall not caught by the validator")
        with open(os.path.join(run, "tickphase_chk_r0.json"),
                  "w") as f:
            json.dump(tp_doc, f)

        s = summarize(run)
        pd = s["phase_decompose"]
        expect(pd is not None and pd["dominant"] == "dispatch",
               "phase_decompose missing or dominant term wrong")
        expect(pd is not None
               and pd["shares"].get("dispatch") == 0.5
               and abs(sum(pd["shares"].values()) - 1.0) < 0.01,
               "phase_decompose shares drifted")
        expect(s["steps_recorded"] == 5, "step_end events lost")
        expect(s["step_ms"]["p50"] > 0, "p50 not computed")
        expect(s["step_ms"]["p99"] >= s["step_ms"]["p50"],
               "p99 < p50")
        expect(s["train"]["loss"] == 2.5, "loss not read from jsonl")
        expect(s["train"]["mfu"] == 0.41, "mfu not read from jsonl")
        expect(s["counters"]["train_steps"] == 5,
               "train_steps_total not in metrics.prom")
        kinds = [ev["kind"] for ev in s["timeline"]]
        expect("fault_fire" in kinds, "fault_fire missing from timeline")
        expect("ckpt_save" in kinds, "ckpt_save missing from timeline")
        expect("elastic_child_exit" in kinds,
               "supervisor flight events missing from timeline")
        expect(s["counters"]["elastic_preemptions"] == 1,
               "supervisor counters not read from "
               "metrics_supervisor.prom")
        expect(len(s["attempts"]) == 1,
               "flight_supervisor.json polluted the attempts set")
        expect(s["flight_reasons"] and
               s["flight_reasons"][0][1] == "preempt",
               "flight reason lost")
        expect(s["trace_spans"] >= 5, "train_step spans missing")
        expect(any(t.startswith("train_step_wall_ms")
                   for t in s["jsonl_tags"]),
               "registry publish missing from jsonl")
        # the trace must be chrome-trace shaped (Perfetto-loadable)
        tr = _load_traces(run)[0]
        ev = next(e for e in tr["traceEvents"]
                  if e["name"] == "train_step")
        expect(ev["ph"] == "X" and "ts" in ev and "dur" in ev
               and ev["args"]["step"] in range(1, 6),
               "trace events not chrome-trace shaped")
        expect("run_id" in tr.get("otherData", {}),
               "trace missing run_id metadata")
        render(s)   # rendering must not throw on a well-formed summary
    obs.reset()
    if failures:
        print("obs_report schema drift:", file=sys.stderr)
        for f in failures:
            print(f"  {f}", file=sys.stderr)
        return 1
    print("obs_report --check: schema OK "
          "(writer and reader agree on all artifacts)")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("run_dir", nargs="?", help="run dir (e.g. out/runs)")
    ap.add_argument("--json", action="store_true",
                    help="print the machine-readable summary")
    ap.add_argument("--serve", type=int, metavar="PORT",
                    help="serve /metrics + the JSON summary over HTTP")
    ap.add_argument("--check", action="store_true",
                    help="synthetic self-test (CI; no devices)")
    ns = ap.parse_args(argv)
    if ns.check:
        return self_check()
    if not ns.run_dir:
        ap.error("run_dir required (or --check)")
    if not os.path.isdir(ns.run_dir):
        print(f"not a directory: {ns.run_dir}", file=sys.stderr)
        return 2
    if ns.serve:
        return serve(ns.run_dir, ns.serve)
    s = summarize(ns.run_dir)
    print(json.dumps(s) if ns.json else render(s))
    return 0


if __name__ == "__main__":
    sys.exit(main())
