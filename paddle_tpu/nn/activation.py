"""Activation layers (reference: python/paddle/nn/layer/activation.py)."""
from __future__ import annotations

import jax.numpy as jnp

from . import functional as F
from .layer import Layer, Parameter


def _make(name, fn, **defaults):
    class _Act(Layer):
        def __init__(self, **kwargs):
            super().__init__()
            self._kwargs = {**defaults, **kwargs}

        def forward(self, x):
            return fn(x, **self._kwargs)
    _Act.__name__ = name
    _Act.__qualname__ = name
    return _Act


ReLU = _make("ReLU", F.relu)
ReLU6 = _make("ReLU6", F.relu6)
GELU = _make("GELU", F.gelu)
SiLU = _make("SiLU", F.silu)
Swish = _make("Swish", F.silu)
Mish = _make("Mish", F.mish)
Sigmoid = _make("Sigmoid", F.sigmoid)
LogSigmoid = _make("LogSigmoid", F.log_sigmoid)
Tanh = _make("Tanh", F.tanh)
Tanhshrink = _make("Tanhshrink", F.tanhshrink)
Hardswish = _make("Hardswish", F.hardswish)
Hardsigmoid = _make("Hardsigmoid", F.hardsigmoid)
Hardtanh = _make("Hardtanh", F.hardtanh)
Hardshrink = _make("Hardshrink", F.hardshrink)
Softshrink = _make("Softshrink", F.softshrink)
Softplus = _make("Softplus", F.softplus)
Softsign = _make("Softsign", F.softsign)
ELU = _make("ELU", F.elu)
SELU = _make("SELU", F.selu)
CELU = _make("CELU", F.celu)
LeakyReLU = _make("LeakyReLU", F.leaky_relu)
Softmax = _make("Softmax", F.softmax)
LogSoftmax = _make("LogSoftmax", F.log_softmax)
GLU = _make("GLU", F.glu)


class PReLU(Layer):
    def __init__(self, num_parameters=1, init=0.25, name=None):
        super().__init__(name)
        self.weight = Parameter(jnp.full((num_parameters,), init))

    def forward(self, x):
        w = self.weight
        if w.shape[0] > 1:  # per-channel (NCHW)
            w = w.reshape((1, -1) + (1,) * (x.ndim - 2))
        return F.prelu(x, w)
