"""Native-accelerated batch assembly for paddle_tpu.io.DataLoader
(reference: Paddle's C++ DataLoader worker pool; dataloader_iter.py routes
here when use_native=True).

Python still runs Dataset.__getitem__ (arbitrary user code), but the
byte-moving half of collate — stacking N samples into one contiguous
batch — runs on the native pthread pool, writing into the page-aligned
staging arena that feeds jax.device_put.
"""
from __future__ import annotations

import threading
import weakref

import numpy as np

from . import ThreadPool, StagingArena, available, gather_stack

_state = threading.local()


def _pool() -> ThreadPool:
    if not hasattr(_state, "pool"):
        _state.pool = ThreadPool()
    return _state.pool


def _arena() -> StagingArena:
    if not hasattr(_state, "arena"):
        _state.arena = StagingArena(1 << 28)   # 256 MB staging slab
        _state.live = []                       # weakrefs to handed-out views
    return _state.arena


def _stack(items):
    first = items[0]
    if isinstance(first, np.ndarray) and first.nbytes >= 4096:
        arena = _arena()
        need = first.nbytes * len(items) + 64 * len(items)
        if arena.used() + need > arena.capacity:
            # recycle only when no prior batch view is still alive —
            # prefetch queues may hold views into this slab
            _state.live = [r for r in _state.live if r() is not None]
            if _state.live:
                return None      # plain numpy copy this batch
            arena.reset()
        out = gather_stack(_pool(), items, arena)
        _state.live.append(weakref.ref(out))
        return out
    return None  # too small to win, or not an ndarray


def assemble(dataset, indices, collate_fn):
    """Gather + collate one batch, using native stack for ndarray leaves."""
    batch = [dataset[i] for i in indices]
    sample = batch[0]
    if isinstance(sample, np.ndarray):
        out = _stack(batch)
        if out is not None:
            return out
    elif isinstance(sample, (list, tuple)):
        cols = []
        native_ok = True
        for i in range(len(sample)):
            col = [b[i] for b in batch]
            out = _stack(col) if isinstance(col[0], np.ndarray) else None
            if out is None:
                native_ok = False
                break
            cols.append(out)
        if native_ok:
            return type(sample)(cols)
    return collate_fn(batch)
