"""Multi-host serving fleet (ISSUE 13): the layer that turns N
gateway PROCESSES into one service — the "millions of users" tier the
single-process gateway cannot reach (ROADMAP item 2).

- :mod:`.remote` — :class:`RemoteReplica`: the router's duck-typed
  replica seam (``healthy``/``load``/``has_prefix``) implemented over
  cached HTTP probes of a peer gateway (``/healthz`` + the
  ``/debugz/prefix`` digest gossip), with staleness bounds.
- :mod:`.frontend` — :class:`FleetFrontend`: prefix-affinity routing
  over remote peers, byte-for-byte SSE proxying, and mid-stream peer
  failover through the HTTP face of the ISSUE-12 resume seam (greedy
  streams bitwise identical across a peer death).
- :mod:`.autoscaler` — :class:`FleetAutoscaler`: the closed loop over
  the PR-8 gauges (queue depth, free slots, block pressure, goodput
  fraction) with hysteresis + cooldown, spawning/draining replica
  processes under SIGTERM-drain semantics.
- :mod:`.manager` — :class:`LocalProcessManager`: the process backend
  (spawn ``replica_main`` subprocesses, SIGTERM drains, SIGKILL
  chaos).

See ``docs/SERVING.md`` ("Fleet serving") and
``docs/FAULT_TOLERANCE.md`` §4c (remote failure model).
"""
from .autoscaler import FleetAutoscaler
from .frontend import FleetFrontend
from .manager import LocalProcessManager
from .remote import RemoteReplica, prefix_digest_chain

__all__ = [
    "FleetAutoscaler", "FleetFrontend", "LocalProcessManager",
    "RemoteReplica", "prefix_digest_chain",
]
