"""paddle.audio features (C34) + paddle.vision.datasets (C35): numerics
vs numpy formulas, file-format loaders on synthesized files."""
import gzip
import os
import pickle
import struct

import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu import audio
from paddle_tpu.vision import datasets


class TestAudioFunctional:
    def test_hann_matches_numpy_periodic(self):
        w = np.asarray(audio.get_window("hann", 16))
        np.testing.assert_allclose(w, np.hanning(17)[:-1], atol=1e-6)

    def test_mel_hz_roundtrip(self):
        f = np.array([0.0, 440.0, 1000.0, 4000.0, 8000.0])
        back = np.asarray(audio.mel_to_hz(audio.hz_to_mel(f)))
        np.testing.assert_allclose(back, f, rtol=1e-4, atol=1e-2)
        back_htk = np.asarray(audio.mel_to_hz(audio.hz_to_mel(f, htk=True),
                                              htk=True))
        np.testing.assert_allclose(back_htk, f, rtol=1e-4, atol=1e-2)

    def test_fbank_shape_and_coverage(self):
        fb = np.asarray(audio.compute_fbank_matrix(16000, 512, n_mels=40))
        assert fb.shape == (40, 257)
        assert (fb >= 0).all()
        # every filter has support, and peaks move up in frequency
        peaks = fb.argmax(axis=1)
        assert (np.diff(peaks) >= 0).all() and fb.sum() > 0

    def test_dct_orthonormal(self):
        d = np.asarray(audio.create_dct(13, 40, norm="ortho"))
        gram = d.T @ d
        np.testing.assert_allclose(gram, np.eye(13), atol=1e-5)

    def test_power_to_db_clamp(self):
        s = jnp.asarray([1e-12, 1.0, 100.0])
        db = np.asarray(audio.power_to_db(s, top_db=30.0))
        assert db.max() == pytest.approx(20.0)
        assert db.min() >= db.max() - 30.0


class TestAudioFeatures:
    def test_spectrogram_peak_bin(self):
        sr, n_fft = 8000, 256
        t = np.arange(sr, dtype=np.float32) / sr
        freq = 1000.0
        x = jnp.asarray(np.sin(2 * np.pi * freq * t))[None]  # [1, time]
        spec = audio.Spectrogram(n_fft=n_fft)(x)
        assert spec.shape[1] == n_fft // 2 + 1
        peak = int(np.asarray(spec.mean(axis=-1)).argmax())
        want = round(freq * n_fft / sr)
        assert abs(peak - want) <= 1

    def test_mel_logmel_mfcc_shapes(self):
        x = jnp.asarray(np.random.RandomState(0).randn(2, 4000), jnp.float32)
        mel = audio.MelSpectrogram(sr=8000, n_fft=256, n_mels=32)(x)
        assert mel.shape[:2] == (2, 32)
        logmel = audio.LogMelSpectrogram(sr=8000, n_fft=256, n_mels=32)(x)
        assert logmel.shape == mel.shape
        np.testing.assert_allclose(
            np.asarray(logmel), np.asarray(audio.power_to_db(mel)),
            atol=1e-4)
        mfcc = audio.MFCC(sr=8000, n_mfcc=13, n_mels=32, n_fft=256)(x)
        assert mfcc.shape[:2] == (2, 13)
        assert np.isfinite(np.asarray(mfcc)).all()

    def test_jittable(self):
        import jax
        feat = audio.MelSpectrogram(sr=8000, n_fft=128, n_mels=16)
        fn, params = feat.functional()
        x = jnp.asarray(np.random.RandomState(1).randn(1, 1024), jnp.float32)
        out = jax.jit(lambda p, x: fn(p, x))(params, x)
        assert np.isfinite(np.asarray(out)).all()


class TestFakeData:
    def test_deterministic_and_transform(self):
        ds = datasets.FakeData(num_samples=5, image_shape=(3, 8, 8),
                               num_classes=4, seed=7)
        assert len(ds) == 5
        img1, lab1 = ds[2]
        img2, lab2 = ds[2]
        np.testing.assert_array_equal(img1, img2)
        assert img1.shape == (3, 8, 8) and 0 <= lab1 < 4 and lab1 == lab2
        ds_t = datasets.FakeData(num_samples=5, image_shape=(3, 8, 8),
                                 transform=lambda im: im * 0)
        assert np.asarray(ds_t[0][0]).sum() == 0
        with pytest.raises(IndexError):
            ds[5]


class TestFileDatasets:
    def _write_idx(self, path, arr):
        ndim = arr.ndim
        with gzip.open(path, "wb") as f:
            f.write(struct.pack(">I", (0x08 << 8) | ndim))
            f.write(struct.pack(f">{ndim}I", *arr.shape))
            f.write(arr.astype(np.uint8).tobytes())

    def test_mnist_idx(self, tmp_path):
        rs = np.random.RandomState(0)
        imgs = rs.randint(0, 255, (6, 28, 28), np.uint8)
        labs = rs.randint(0, 10, (6,), np.uint8)
        self._write_idx(tmp_path / "train-images-idx3-ubyte.gz", imgs)
        self._write_idx(tmp_path / "train-labels-idx1-ubyte.gz", labs)
        ds = datasets.MNIST(str(tmp_path), mode="train")
        assert len(ds) == 6
        img, lab = ds[3]
        np.testing.assert_allclose(img, imgs[3] / 255.0, atol=1e-6)
        assert lab == labs[3]
        with pytest.raises(RuntimeError, match="egress"):
            datasets.MNIST(str(tmp_path), download=True)

    def test_cifar10_pickle(self, tmp_path):
        rs = np.random.RandomState(1)
        base = tmp_path / "cifar-10-batches-py"
        os.makedirs(base)
        for n in [f"data_batch_{i}" for i in range(1, 6)]:
            batch = {b"data": rs.randint(0, 255, (4, 3072), np.uint8),
                     b"labels": rs.randint(0, 10, 4).tolist()}
            with open(base / n, "wb") as f:
                pickle.dump(batch, f)
        ds = datasets.Cifar10(str(tmp_path), mode="train")
        assert len(ds) == 20
        img, lab = ds[0]
        assert img.shape == (3, 32, 32) and 0 <= lab < 10

    def test_dataset_folder_npy(self, tmp_path):
        for cls in ("cat", "dog"):
            os.makedirs(tmp_path / cls)
            for i in range(3):
                np.save(tmp_path / cls / f"{i}.npy",
                        np.full((2, 2), ord(cls[0]), np.float32))
        ds = datasets.DatasetFolder(str(tmp_path))
        assert ds.classes == ["cat", "dog"] and len(ds) == 6
        img, lab = ds[0]
        assert lab == 0 and img[0, 0] == ord("c")
        flat = datasets.ImageFolder(str(tmp_path / "cat"))
        assert len(flat) == 3 and flat[1][0].shape == (2, 2)
