"""Preemption-safe elastic training (ISSUE 3 tentpole).

Four-layer contract, pinned end-to-end: (1) graceful shutdown — an
injected ``preempt`` (SIGTERM stand-in) checkpoints the exact step and
exits ``PREEMPTED_RC``; (2) resumable data pipeline — sampler/loader
``state_dict`` restores the exact shuffle position in O(1), no replay;
(3) cross-topology resume — a dp=4 checkpoint restores under dp=2 with
identical numerics, recomputed grad accumulation, and a re-sharded,
non-overlapping sampler index space; (4) supervisor awareness —
``PREEMPTED_RC`` relaunches never consume a ``max_restarts`` attempt.
Every test stays in-process (or spawns only jax-free children) to ride
the tier-1 budget: each is well under 15s on CPU.
"""
import json
import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

import paddle_tpu as pt
from paddle_tpu.io import (DataLoader, DistributedBatchSampler,
                           RandomSampler, SequenceSampler)
from paddle_tpu.utils import faults
from paddle_tpu.utils.shutdown import PREEMPTED_RC, GracefulShutdown

pytestmark = pytest.mark.chaos


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.reset()
    yield
    faults.reset()


# =========================================================== sampler state
class TestSamplerState:
    def test_seeded_sampler_reshuffles_per_epoch(self):
        """Regression (ISSUE 3 satellite): a supplied generator seed
        must not pin every epoch to the identical permutation — the
        epoch counter folds into the seed."""
        s = RandomSampler(list(range(32)), generator=123)
        e0, e1, e2 = list(s), list(s), list(s)
        assert sorted(e0) == sorted(e1) == sorted(e2) == list(range(32))
        assert e0 != e1 and e1 != e2          # epochs differ...
        s2 = RandomSampler(list(range(32)), generator=123)
        assert [list(s2) for _ in range(3)] == [e0, e1, e2]  # ...reproducibly
        # and an unseeded sampler still reshuffles per epoch
        u = RandomSampler(list(range(32)))
        assert list(u) != list(u)

    def test_random_sampler_state_roundtrip_mid_epoch(self):
        src = list(range(20))
        ref = RandomSampler(src, generator=7)
        epoch0, epoch1 = list(ref), list(ref)
        live = RandomSampler(src, generator=7)
        it = iter(live)
        head = [next(it) for _ in range(7)]
        state = live.state_dict()
        assert state == {"epoch": 0, "cursor": 7}
        fresh = RandomSampler(src, generator=7)
        fresh.load_state_dict(state)
        assert head + list(fresh) == epoch0    # exact remaining order
        # the restored sampler's NEXT epoch is epoch 1, not a replay
        assert list(fresh) == epoch1
        # a state taken at an epoch boundary resumes at the next epoch
        boundary = RandomSampler(src, generator=7)
        list(boundary)
        fresh2 = RandomSampler(src, generator=7)
        fresh2.load_state_dict(boundary.state_dict())
        assert list(fresh2) == epoch1

    def test_sequence_sampler_cursor(self):
        s = SequenceSampler(list(range(10)))
        it = iter(s)
        assert [next(it) for _ in range(4)] == [0, 1, 2, 3]
        fresh = SequenceSampler(list(range(10)))
        fresh.load_state_dict(s.state_dict())
        assert list(fresh) == [4, 5, 6, 7, 8, 9]

    def test_dataloader_state_roundtrip(self):
        data = np.arange(24, dtype=np.float32).reshape(12, 2)
        mk = lambda: DataLoader(list(data), batch_size=3,
                                sampler=RandomSampler(data, generator=5))
        ref = [b.copy() for b in mk()]
        live = mk()
        it = iter(live)
        head = [next(it) for _ in range(2)]
        resumed = mk()
        resumed.load_state_dict(live.state_dict())
        tail = list(resumed)
        got = head + tail
        assert len(got) == len(ref)
        for a, b in zip(got, ref):
            np.testing.assert_array_equal(a, b)

    def test_distributed_sampler_reshard_disjoint_and_complete(self):
        """A dp=4 mid-epoch state restores under dp=2 (and dp=3, the
        non-dividing case): the new shards cover exactly the unseen
        remainder of the epoch's global order, with no overlap between
        ranks and no sample double-consumed."""
        N, BS = 64, 2
        mk = lambda nr, r: DistributedBatchSampler(
            list(range(N)), BS, num_replicas=nr, rank=r, shuffle=True)
        olds = [mk(4, r) for r in range(4)]
        its = [iter(s) for s in olds]
        consumed = []
        for _ in range(3):                     # 3 lockstep batches
            for it in its:
                consumed += next(it)
        state = olds[0].state_dict()
        assert state["consumed"] == 3 * BS * 4 == len(consumed)
        global_order = olds[0]._epoch_indices()
        # prefix property: rank-strided sharding makes the lockstep-
        # consumed SET exactly the head of the global order — the
        # invariant that lets a new topology resume from one counter
        assert set(consumed) == set(global_order[:len(consumed)])
        for new_ranks in (2, 3):
            news = [mk(new_ranks, r) for r in range(new_ranks)]
            for s in news:
                s.load_state_dict(state)
            shards = [[i for b in s for i in b] for s in news]
            remainder = set(global_order[len(consumed):])
            seen = [i for sh in shards for i in sh]
            assert set(seen) == remainder      # exactly the unseen rest
            assert set(consumed).isdisjoint(remainder)
            # non-overlapping across ranks up to the even-shard pad
            pad = (-len(remainder)) % new_ranks
            assert len(seen) - len(set(seen)) <= pad
            # every rank got the same number of batches (lockstep safety)
            assert len({len(sh) for sh in shards}) == 1

    def test_generator_object_still_accepted(self):
        """Passing a np.random.Generator OBJECT (torch/paddle-style)
        keeps working: epochs differ via its advancing state; exact
        (epoch, cursor) resume needs an int seed."""
        s = RandomSampler(list(range(16)), generator=np.random.default_rng(0))
        e0, e1 = list(s), list(s)
        assert sorted(e0) == sorted(e1) == list(range(16))
        assert e0 != e1
        s.load_state_dict(s.state_dict())      # degrades, never crashes
        assert sorted(list(s)) == list(range(16))
        # a mid-epoch cursor is NOT reconstructible from a generator
        # object: resume restarts the epoch (full coverage) instead of
        # skipping never-seen samples of a fresh permutation
        s2 = RandomSampler(list(range(16)), generator=np.random.default_rng(0))
        s2.load_state_dict({"epoch": 0, "cursor": 5})
        assert sorted(list(s2)) == list(range(16))

    def test_distributed_sampler_reshuffles_per_epoch(self):
        """Epoch wrap without set_epoch must reshuffle (same bug class
        as the seeded RandomSampler fix); explicit set_epoch still
        pins the order."""
        d = DistributedBatchSampler(list(range(32)), 4, num_replicas=1,
                                    rank=0, shuffle=True)
        e0, e1 = list(d), list(d)
        assert e0 != e1
        d.set_epoch(0)
        assert list(d) == e0

    def test_epoch_tail_resume_onto_more_ranks_keeps_lockstep(self):
        """Resuming with only 2 unseen samples onto 8 ranks: the pad
        must CYCLE the remainder so every rank still gets the same
        batch count (uneven shards would hang SPMD lockstep)."""
        N = 64
        olds = [DistributedBatchSampler(list(range(N)), 1, num_replicas=2,
                                        rank=r, shuffle=True)
                for r in range(2)]
        its = [iter(s) for s in olds]
        for _ in range(31):                    # 62 of 64 consumed
            for it in its:
                next(it)
        state = olds[0].state_dict()
        assert state["consumed"] == 62
        news = [DistributedBatchSampler(list(range(N)), 1, num_replicas=8,
                                        rank=r, shuffle=True)
                for r in range(8)]
        for s in news:
            s.load_state_dict(state)
        shards = [[i for b in s for i in b] for s in news]
        assert len({len(sh) for sh in shards}) == 1   # lockstep preserved
        assert all(len(sh) == 1 for sh in shards)
        remainder = set(olds[0]._epoch_indices()[62:])
        assert {i for sh in shards for i in sh} == remainder

    def test_state_survives_restore_without_iteration(self):
        """Double preemption: a restored-but-never-iterated sampler's
        state_dict must re-report the held position (epoch, cursor, and
        for DBS the ORIGINAL saving nranks), not a zeroed one."""
        s = RandomSampler(list(range(16)), generator=3)
        it = iter(s)
        [next(it) for _ in range(5)]
        state = s.state_dict()
        fresh = RandomSampler(list(range(16)), generator=3)
        fresh.load_state_dict(state)
        assert fresh.state_dict() == state     # no iteration in between
        d = DistributedBatchSampler(list(range(10)), 1, num_replicas=4,
                                    rank=0, shuffle=True)
        dit = iter(d)
        next(dit), next(dit)
        dstate = d.state_dict()
        assert dstate["nranks"] == 4
        d2 = DistributedBatchSampler(list(range(10)), 1, num_replicas=5,
                                     rank=0, shuffle=True)
        d2.load_state_dict(dstate)
        assert d2.state_dict() == dstate       # still the saving topology

    def test_distributed_sampler_same_topology_resume(self):
        N, BS = 32, 4
        ref = [b for b in DistributedBatchSampler(
            list(range(N)), BS, num_replicas=2, rank=0, shuffle=True)]
        live = DistributedBatchSampler(list(range(N)), BS, num_replicas=2,
                                       rank=0, shuffle=True)
        it = iter(live)
        head = [next(it), next(it)]
        fresh = DistributedBatchSampler(list(range(N)), BS, num_replicas=2,
                                        rank=0, shuffle=True)
        fresh.load_state_dict(live.state_dict())
        assert head + list(fresh) == ref


# ======================================================= empty dataloader
def test_empty_train_dataloader_raises_value_error(tmp_path):
    """Regression (ISSUE 3 satellite): the epoch-wrap ``next`` must not
    leak a bare StopIteration out of the training loop."""
    from paddle_tpu.models import LlamaForCausalLM, llama_tiny
    from paddle_tpu.trainer import Trainer, TrainingArguments
    pt.seed(0)
    tr = Trainer(LlamaForCausalLM(llama_tiny()),
                 pt.optimizer.AdamW(learning_rate=1e-3),
                 TrainingArguments(output_dir=str(tmp_path), max_steps=2,
                                   resume_from_checkpoint=False),
                 train_dataloader=[])
    with pytest.raises(ValueError, match="train_dataloader is empty"):
        tr.train()


# ========================================================== preempt e2e
class _RecordingDataset:
    """Token dataset that logs every __getitem__ — replay-based resume
    would re-fetch consumed samples; O(1) sampler restore must not."""

    def __init__(self, n=16, s=16, vocab=256):
        self.data = np.random.RandomState(7).randint(0, vocab, (n, s))
        self.fetches = []

    def __getitem__(self, i):
        self.fetches.append(i)
        return self.data[i]

    def __len__(self):
        return len(self.data)


def _preempt_trainer(out_dir, max_steps=10):
    from paddle_tpu.models import LlamaForCausalLM, llama_tiny
    from paddle_tpu.trainer import Trainer, TrainingArguments
    pt.seed(0)
    ds = _RecordingDataset()
    dl = DataLoader(ds, batch_size=4, sampler=RandomSampler(ds, generator=11))
    args = TrainingArguments(output_dir=str(out_dir), max_steps=max_steps,
                             logging_steps=1, save_steps=4, seed=42)
    tr = Trainer(LlamaForCausalLM(llama_tiny()),
                 pt.optimizer.AdamW(learning_rate=1e-3), args,
                 train_dataloader=dl)
    return tr, ds


class TestPreemptionE2E:
    def test_preempt_checkpoints_exits_and_resumes_exactly(self, tmp_path):
        """ACCEPTANCE: injected preempt mid-run -> checkpoint at the
        exact step + PREEMPTED_RC; relaunch resumes to the SAME final
        loss as an uninterrupted run on the identical data order, via
        sampler-state restore (O(1)), not replay."""
        ref, _ = _preempt_trainer(tmp_path / "ref")
        ref.train()
        ref_final = ref.logger.history["loss"][-1][1]

        tr, _ = _preempt_trainer(tmp_path / "run")
        with faults.scoped("preempt@6"):
            with pytest.raises(SystemExit) as ei:
                tr.train()
        assert ei.value.code == PREEMPTED_RC == tr.args.preempt_exit_code
        assert tr.global_step == 6             # exact step, not save_steps
        ckdir = tmp_path / "run" / "checkpoints"
        steps = sorted(int(d) for d in os.listdir(ckdir) if d.isdigit())
        assert 6 in steps
        meta = json.load(open(ckdir / "meta" / "6.json"))
        assert meta["step"] == 6
        assert meta["sampler"]["batch_sampler"]["sampler"]["cursor"] == 8
        assert meta["topology"]["dp"] == 1

        # relaunch: O(1) sampler restore, identical trajectory
        tr2, ds2 = _preempt_trainer(tmp_path / "run")
        tr2.train()
        assert tr2._sampler_restored
        assert tr2.global_step == 10
        # no replay: the 4 remaining steps' samples, plus at most the
        # device-prefetcher's bounded read-ahead (depth+1 batches drawn
        # but never trained) — a replay-based resume would re-fetch the
        # 6 consumed batches first and blow well past this bound
        depth = tr2.args.prefetch_depth
        assert 4 * 4 <= len(ds2.fetches) <= (4 + depth + 1) * 4
        final = tr2.logger.history["loss"][-1][1]
        assert abs(final - ref_final) < 1e-6, (final, ref_final)

    def test_latch_cleared_on_next_train_call(self, tmp_path):
        """In-process retry after a preemption exit: a latch tripped in
        the previous train() must not make the next call exit before
        its first step."""
        tr, _ = _preempt_trainer(tmp_path / "again", max_steps=4)
        with faults.scoped("preempt@1"):
            with pytest.raises(SystemExit):
                tr.train()
        assert tr.global_step == 1
        tr.train()                             # latch cleared: runs
        assert tr.global_step == 4

    def test_sigterm_latch_requests_graceful_stop(self, tmp_path):
        """The signal channel latches identically to the fault channel
        (handler installed by train(); request observed at the next
        step boundary)."""
        import signal as _signal
        tr, _ = _preempt_trainer(tmp_path / "sig", max_steps=6)

        class Kick:
            def __init__(self):
                self.sent = False

            def on_step_end(self, step, logs):
                if step >= 2 and not self.sent:
                    self.sent = True
                    os.kill(os.getpid(), _signal.SIGTERM)

            def on_save(self, step):
                pass

            def on_train_end(self, step):
                pass

        tr.callbacks.append(Kick())
        before = _signal.getsignal(_signal.SIGTERM)
        with pytest.raises(SystemExit) as ei:
            tr.train()
        assert ei.value.code == PREEMPTED_RC
        assert 2 <= tr.global_step < 6
        # handler uninstalled on the way out (previous handler restored)
        assert _signal.getsignal(_signal.SIGTERM) is before
        assert isinstance(tr._shutdown, GracefulShutdown)
        assert tr._shutdown.requested()


# ================================================= supervisor awareness
_COUNTER_CHILD = r"""
import os, sys
p = sys.argv[1]
n = int(open(p).read()) if os.path.exists(p) else 0
open(p, "w").write(str(n + 1))
codes = [int(c) for c in sys.argv[2].split(",")]
sys.exit(codes[min(n, len(codes) - 1)])
"""


class TestSupervisorPreemption:
    def _run(self, counter, codes, **kw):
        from paddle_tpu.distributed.elastic import supervise
        rc = supervise([sys.executable, "-c", _COUNTER_CHILD, str(counter),
                        ",".join(map(str, codes))], backoff_s=0.01, **kw)
        n = int(open(counter).read()) if os.path.exists(counter) else 0
        return rc, n

    def test_preempted_rc_restarts_without_consuming_attempts(self, tmp_path):
        # two preemptions, then success — with ZERO crash restarts
        # allowed; only works if preemption is a free restart
        rc, n = self._run(tmp_path / "a", [PREEMPTED_RC, PREEMPTED_RC, 0],
                          max_restarts=0)
        assert rc == 0 and n == 3
        # a real crash after a preemption still consumes the budget
        rc, n = self._run(tmp_path / "b", [PREEMPTED_RC, 7, 7],
                          max_restarts=1)
        assert rc == 7 and n == 3              # preempt + crash + retry

    def test_preemption_storm_bounded(self, tmp_path):
        rc, n = self._run(tmp_path / "c", [PREEMPTED_RC], max_restarts=0,
                          max_preemptions=2)
        assert rc == PREEMPTED_RC and n == 3   # initial + 2 free restarts

    def test_topology_change_logged(self, tmp_path, capfd):
        topos = iter(["v4-8", "v4-8", "v4-4", "v4-4"])
        rc, n = self._run(tmp_path / "d", [PREEMPTED_RC, 0], max_restarts=0,
                          probe_topology=lambda: next(topos))
        assert rc == 0 and n == 2
        err = capfd.readouterr().err
        assert "topology changed" in err and "v4-4" in err

    def test_default_probe_reads_mutable_file(self, tmp_path, monkeypatch):
        """The default topology probe must see changes made AFTER the
        supervisor launched — env is frozen, the file channel is not."""
        from paddle_tpu.distributed.elastic import _default_topology
        f = tmp_path / "ws"
        monkeypatch.setenv("PADDLE_TPU_WORLD_SIZE_FILE", str(f))
        assert _default_topology() is None   # not written yet
        f.write_text("8\n")
        assert _default_topology() == "8"
        f.write_text("4")
        assert _default_topology() == "4"    # mutable between relaunches
        monkeypatch.delenv("PADDLE_TPU_WORLD_SIZE_FILE")
        monkeypatch.setenv("PADDLE_TPU_WORLD_SIZE", "16")
        assert _default_topology() == "16"   # static fallback

    def test_fault_sites_tool_check(self):
        """tools/fault_sites.py --check: the inventory (incl. the new
        `preempt` site) matches the wired code."""
        import importlib.util
        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        spec = importlib.util.spec_from_file_location(
            "fault_sites", os.path.join(root, "tools", "fault_sites.py"))
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        assert mod.check_wired() == 0
        assert "preempt" in faults.SITES


# ================================================== cross-topology resume
class TestCrossTopologyResume:
    def test_ckpt_dp4_restores_under_dp2_identical_numerics(self, tmp_path):
        """ACCEPTANCE (checkpoint layer): arrays saved sharded over a
        dp=4 mesh restore onto a dp=2 mesh via orbax target shardings —
        deliberate resharding, identical numerics."""
        from paddle_tpu.checkpoint.distributed_ckpt import \
            DistributedCheckpoint
        from paddle_tpu.distributed import env
        mesh4 = env.init_parallel_env({"dp": 4}, devices=jax.devices()[:4])
        w = np.arange(64, dtype=np.float32).reshape(8, 8)
        m = np.linspace(-1, 1, 32, dtype=np.float32).reshape(8, 4)
        tree4 = {
            "params": {"w": jax.device_put(
                w, NamedSharding(mesh4, P("dp", None)))},
            "opt_state": {"m": jax.device_put(
                m, NamedSharding(mesh4, P("dp", None)))},
        }
        ck = DistributedCheckpoint(str(tmp_path), async_save=False)
        ck.save(1, tree4, wait=True, meta={"topology": {"dp": 4}})
        env.clear_mesh()

        mesh2 = env.init_parallel_env({"dp": 2}, devices=jax.devices()[:2])
        sh2 = NamedSharding(mesh2, P("dp", None))
        like = {"params": {"w": jax.device_put(np.zeros_like(w), sh2)},
                "opt_state": {"m": jax.device_put(np.zeros_like(m), sh2)}}
        out = ck.restore(1, like=like)
        assert out["params"]["w"].sharding.is_equivalent_to(sh2, 2)
        np.testing.assert_array_equal(np.asarray(out["params"]["w"]), w)
        np.testing.assert_array_equal(np.asarray(out["opt_state"]["m"]), m)
        assert ck.load_meta(1) == {"topology": {"dp": 4}}
        ck.close()

    def test_trainer_reconciles_dp4_to_dp2(self, tmp_path):
        """ACCEPTANCE (trainer layer): resume under a halved dp degree
        restores identical params/opt-state, recomputes grad
        accumulation to preserve the effective global batch, and
        re-shards the sampler's remaining index space disjointly."""
        from paddle_tpu.distributed import env
        from paddle_tpu.models import LlamaForCausalLM, llama_tiny
        from paddle_tpu.trainer import Trainer, TrainingArguments

        # 128 samples / (4 per batch * 4 ranks) = 8 lockstep steps per
        # epoch: the step-4 checkpoint lands MID-epoch, so resharding
        # has a real remainder to redistribute
        data = np.random.RandomState(7).randint(0, 256, (128, 16))

        def mk(nranks, rank, max_steps):
            pt.seed(0)
            dl = DataLoader(
                list(data),
                batch_sampler=DistributedBatchSampler(
                    list(data), 4, num_replicas=nranks, rank=rank,
                    shuffle=True))
            args = TrainingArguments(output_dir=str(tmp_path),
                                     max_steps=max_steps, logging_steps=1,
                                     save_steps=4, seed=42)
            return Trainer(LlamaForCausalLM(llama_tiny()),
                           pt.optimizer.AdamW(learning_rate=1e-3), args,
                           train_dataloader=dl)

        env.init_parallel_env({"dp": 4}, devices=jax.devices()[:4])
        tr = mk(4, 0, max_steps=4)
        tr.train()                              # saves at step 4
        tr._ckpt.wait_until_finished()
        saved = {k: np.asarray(v) for k, v in tr._params.items()}
        meta = tr._ckpt.load_meta(4)
        assert meta["topology"]["dp"] == 4
        assert meta["topology"]["mesh"]["dp"] == 4
        consumed_n = 4 * 4 * 4                  # steps * batch * ranks
        assert meta["sampler"]["batch_sampler"]["consumed"] == consumed_n
        env.clear_mesh()

        env.init_parallel_env({"dp": 2}, devices=jax.devices()[:2])
        tr2 = mk(2, 0, max_steps=6)
        tr2._opt_state = tr2.optimizer.init(tr2._params)
        assert tr2._try_resume() == 4
        # identical numerics across the topology change
        for k in saved:
            np.testing.assert_array_equal(saved[k],
                                          np.asarray(tr2._params[k]))
        # per-device share preserved: dp 4->2 doubles accumulation
        assert tr2.args.gradient_accumulation_steps == 2
        assert tr2._step_fn is None             # rebuilt for the new accum
        assert tr2._sampler_restored

        # the two new ranks shard the REMAINING index space disjointly
        def resharded(rank):
            s = DistributedBatchSampler(list(data), 4, num_replicas=2,
                                        rank=rank, shuffle=True)
            s.load_state_dict(meta["sampler"]["batch_sampler"])
            return [i for b in s for i in b]

        shard0, shard1 = resharded(0), resharded(1)
        global_order = DistributedBatchSampler(
            list(data), 4, num_replicas=2, rank=0,
            shuffle=True)._epoch_indices()
        assert set(shard0).isdisjoint(shard1)
        assert set(shard0) | set(shard1) == set(global_order[consumed_n:])
        assert set(shard0 + shard1).isdisjoint(global_order[:consumed_n])

        # and training continues to completion under the new topology
        tr2.train()
        assert tr2.global_step == 6
        assert np.isfinite(tr2.logger.history["loss"][-1][1])


def test_reconcile_clamps_accum_to_loader_batch(tmp_path):
    """dp 4->3 with accum 3 and loader batch 6 would naively pick
    accum=4, which cannot fold a batch of 6 — the reconcile clamps to
    the nearest divisor instead of crashing the first resumed step."""
    from paddle_tpu.models import LlamaForCausalLM, llama_tiny
    from paddle_tpu.trainer import Trainer, TrainingArguments
    pt.seed(0)
    ds = list(np.random.RandomState(7).randint(0, 256, (24, 16)))
    dl = DataLoader(ds, batch_size=6)
    args = TrainingArguments(output_dir=str(tmp_path), max_steps=1,
                             gradient_accumulation_steps=3)
    tr = Trainer(LlamaForCausalLM(llama_tiny()),
                 pt.optimizer.AdamW(learning_rate=1e-3), args,
                 train_dataloader=dl)
    tr._dp_degree = lambda: 3
    tr._reconcile_topology({"dp": 4, "accum": 3})
    assert tr.args.gradient_accumulation_steps == 3   # 4 -> clamp to 3
    tr._dp_degree = lambda: 2
    tr._reconcile_topology({"dp": 4, "accum": 3})
    assert tr.args.gradient_accumulation_steps == 6   # exact: divides 6


# ====================================== rollback keeps poisoned-window skip
def test_divergence_rollback_does_not_rewind_sampler(tmp_path):
    """A divergence rollback restores ARRAYS only: the sampler cursor
    must keep its live position (poisoned-window skip), not rewind to
    the checkpoint's — only a process relaunch restores data state."""
    from paddle_tpu.models import LlamaForCausalLM, llama_tiny
    from paddle_tpu.trainer import Trainer, TrainingArguments
    pt.seed(0)
    ds = _RecordingDataset()
    dl = DataLoader(ds, batch_size=4, sampler=RandomSampler(ds, generator=11))
    args = TrainingArguments(output_dir=str(tmp_path), max_steps=6,
                             logging_steps=1, save_steps=2, nan_patience=1,
                             seed=42)
    tr = Trainer(LlamaForCausalLM(llama_tiny()),
                 pt.optimizer.AdamW(learning_rate=1e-3), args,
                 train_dataloader=dl)
    with faults.scoped("step_nan@2"):      # fires at global step 3
        tr.train()
    assert tr._rollbacks == 1
    assert tr.global_step == 6
    assert not tr._sampler_restored        # rollback didn't touch data
    # steps 1-3 fetched 3 batches, rollback to ckpt@2, steps 3-6 fetch 4
    # more — NO batch re-fetched by a rewind; the device-prefetcher may
    # add its bounded read-ahead (never-trained) on top
    depth = tr.args.prefetch_depth
    assert 7 * 4 <= len(ds.fetches) <= (7 + depth + 1) * 4


# ============================================== concurrent resume safety
def test_resume_waits_for_inflight_async_save(tmp_path):
    """ISSUE 3 satellite: auto-resume racing a still-in-flight async
    save must drain it (wait_until_finished BEFORE latest_complete_step)
    and restore the finalized step — never a torn one."""
    from paddle_tpu.models import LlamaForCausalLM, llama_tiny
    from paddle_tpu.trainer import Trainer, TrainingArguments
    pt.seed(0)
    batch = jnp.asarray(np.random.RandomState(7).randint(0, 256, (4, 16)))
    args = TrainingArguments(output_dir=str(tmp_path), max_steps=3,
                             logging_steps=1, save_steps=0, seed=42,
                             donate_state=False)
    tr = Trainer(LlamaForCausalLM(llama_tiny()),
                 pt.optimizer.AdamW(learning_rate=1e-3), args,
                 train_dataloader=[batch])
    tr.train()
    tr.save_checkpoint(wait=False)             # async save in flight
    params_at_save = {k: np.asarray(v) for k, v in tr._params.items()}

    ckpt = tr._ckpt_manager()
    calls = []
    orig_wait = ckpt.wait_until_finished
    orig_latest = ckpt.latest_complete_step
    ckpt.wait_until_finished = lambda: (calls.append("wait"),
                                        orig_wait())[1]
    ckpt.latest_complete_step = lambda: (calls.append("latest"),
                                         orig_latest())[1]
    try:
        restored = tr._try_resume()
    finally:
        ckpt.wait_until_finished = orig_wait
        ckpt.latest_complete_step = orig_latest
    assert restored == 3
    assert "wait" in calls and "latest" in calls
    assert calls.index("wait") < calls.index("latest")
    for k in params_at_save:
        np.testing.assert_array_equal(params_at_save[k],
                                      np.asarray(tr._params[k]))
