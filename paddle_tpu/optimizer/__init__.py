"""paddle_tpu.optimizer (reference: python/paddle/optimizer/__init__.py)."""
from . import lr
from .clip import (ClipGradByGlobalNorm, ClipGradByNorm, ClipGradByValue,
                   global_norm)
from .optimizers import (SGD, Adadelta, Adafactor, Adagrad, Adam, Adamax,
                         AdamW, Lamb, Momentum, NAdam, Optimizer, RAdam,
                         RMSProp, Rprop)
