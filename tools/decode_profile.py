#!/usr/bin/env python
"""One-window decode-path profiler (round 6; round-5 history below).

BENCH_SELF_r05 raised three decode puzzles the standard queue cannot
answer: the Pallas decode kernel timed 0.61x dense, fused projections
timed SLOWER than unfused, and int8 weight-only decode timed slower
than bf16. Each 'time' there was one whole generate() call over the
tunnel; this script separates compile/dispatch from steady-state
on-device time (long decode runs amortize the tunnel RTT) and times
each lever in isolation (the t64/t256 slope in sections 2-4 IS the
r05 "why is fused/int8 slower" answer: the whole-call numbers were
dispatch-dominated, the slope is the comparable per-token cost).

Round 6 (ISSUE 6): the paged section now profiles all three tick
architectures — per-tick host path (the r05 49 tok/s baseline),
device-resident fused tick, and the multi-tick scan — and splits each
tick into host scheduling vs program (dispatch+compute) vs the
measured per-dispatch floor, so dispatch overhead is a NUMBER, not a
suspicion. It ends with a ``PAGED_JSON`` line that bench.py ingests
as the ``paged_tokens_per_sec`` rung (before/after captured in the
same window). Writes DECODE_PROFILE_r06.json.

Round 7 (ISSUE 11): the paged section runs the async token ring
on/off A/B — ``fused_sync`` (one blocking D2H per dispatch, the r06
architecture) vs ``fused`` (ring drains, pipelined one dispatch
behind) with a ``blocking_d2h_per_tick`` column — and §6b sweeps the
REJECTION-SAMPLED speculative tick on a repetitive sampled stream
(accept rate, tokens/forward, the
``paged_sampled_spec_tokens_per_sec`` rung bench.py auto-ingests
beside the greedy spec rung).

Round 8 (ISSUE 14): §7 churn A/B — short-request traffic with a slot
transition every few ticks, ``delta_transitions`` on vs off (one-row
patch programs vs full mirror rebuild+re-upload per transition), with
uploads/tick, upload BYTES/tick and rebuild/patch counts per row and
the ``paged_churn_tokens_per_sec`` rung bench.py auto-ingests.

Round 9 (ISSUE 19): §7b widens the churn A/B to three modes — fused
(staged patch queue applied by the next tick's program, the engine
default) vs delta vs full rebuild — with a dispatches/tick column
pinning the one-dispatch-per-tick claim and the
``paged_churn_fused_tokens_per_sec`` rung.

Usage: timeout 2100 python tools/decode_profile.py
(budget covers ~20 cold generate compiles across base/fused/int8/int4
plus the attention and paged sections; every subsection banks as it
goes, so even a SIGTERM keeps what was measured)
"""
import json
import os
import sys
import time

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
sys.path.insert(0, REPO)
OUT = os.path.join(REPO, "DECODE_PROFILE_r06.json")

report = {"started": time.strftime("%Y-%m-%d %H:%M:%S")}


def bank():
    with open(OUT, "w") as f:
        json.dump(report, f, indent=1)


def main():
    import numpy as np
    import jax
    import jax.numpy as jnp

    report["device"] = str(jax.devices()[0].device_kind)
    bank()
    import paddle_tpu as pt
    from paddle_tpu.models import LlamaForCausalLM

    import bench

    rs = np.random.RandomState(0)

    # --- 1) raw decode-attention: new kv-folded kernel vs dense, several
    # shapes (the bench shape first). np.asarray forces full execution
    # through the tunnel; iters amortize RTT.
    from paddle_tpu.ops.attention import dense_attention
    from paddle_tpu.ops.pallas.decode_attention import decode_attention_pallas

    def time_it(jfn, *args, iters=100):
        np.asarray(jfn(*args))
        t0 = time.perf_counter()
        for _ in range(iters):
            out = jfn(*args)
        np.asarray(out)
        return round((time.perf_counter() - t0) / iters * 1e3, 4)

    attn = {}
    if jax.devices()[0].platform == "cpu":
        # non-interpret pallas_call cannot lower on CPU, and interpret
        # timings say nothing about the 0.61x-dense hardware question —
        # skip straight to the sections a CPU run CAN answer (the r05
        # crash here used to eat sections 2-5's numbers too)
        report["attn_skipped"] = "cpu backend: kernel timing needs TPU"
        bank()
    else:
        for (b, T, h, kv, d) in ((8, 2048, 16, 8, 128),
                                 (8, 2048, 8, 4, 64),
                                 (1, 4096, 32, 8, 128)):
            try:
                ck = jnp.asarray(rs.randn(b, T, kv, d), jnp.bfloat16)
                cv = jnp.asarray(rs.randn(b, T, kv, d), jnp.bfloat16)
                q1 = jnp.asarray(rs.randn(b, h, d), jnp.bfloat16)
                idx = jnp.int32(T - 2)
                mask = (jnp.arange(T) <= T - 2)[None, None, None, :]
                jd = jax.jit(lambda q, k, v: dense_attention(
                    q[:, None], k, v, attn_mask=mask)[:, 0])
                jp = jax.jit(lambda q, k, v: decode_attention_pallas(
                    q, k, v, idx, d ** -0.5))
                err = float(jnp.max(jnp.abs(
                    jd(q1, ck, cv).astype(jnp.float32)
                    - jp(q1, ck, cv).astype(jnp.float32))))
                key = f"b{b}_T{T}_h{h}_kv{kv}_d{d}"
                attn[key] = {"dense_ms": time_it(jd, q1, ck, cv),
                             "pallas_ms": time_it(jp, q1, ck, cv),
                             "max_err": round(err, 4)}
                # HBM floor: read K+V once
                attn[key]["hbm_floor_ms"] = round(
                    2 * b * T * kv * d * 2 / 819e9 * 1e3, 4)
            except Exception as e:  # one shape must not eat the rest
                attn[f"b{b}_T{T}_h{h}_kv{kv}_d{d}_error"] = repr(e)[:200]
            report["attn"] = attn
            bank()

    # --- 2) end-to-end generate: long decode to amortize dispatch.
    # 256 new tokens vs 64: slope = per-token cost, intercept = overhead.
    pt.seed(0)
    cfg = bench._bench_config("tiny")
    model = LlamaForCausalLM(cfg)
    gen = {}

    def time_generate(m, bs, n_new):
        ids = jnp.asarray(rs.randint(0, m.config.vocab_size, (bs, 32)))
        out = m.generate(ids, max_new_tokens=n_new, temperature=0.0)
        np.asarray(out)      # compile
        t0 = time.perf_counter()
        out = m.generate(ids, max_new_tokens=n_new, temperature=0.0)
        np.asarray(out)
        return time.perf_counter() - t0

    try:
        for bs in (1, 8):
            t64 = time_generate(model, bs, 64)
            t256 = time_generate(model, bs, 256)
            per_tok_ms = (t256 - t64) / 192 * 1e3
            gen[f"bs{bs}"] = {
                "t64_s": round(t64, 4), "t256_s": round(t256, 4),
                "per_token_ms": round(per_tok_ms, 4),
                "dispatch_overhead_ms": round(
                    (t64 * 4 - t256) / 3 * 1e3, 2),
                "tokens_per_sec_steady": round(bs / per_tok_ms * 1e3, 1)}
            report["generate"] = gen
            bank()
    except Exception as e:
        gen["generate_error"] = repr(e)[:200]
        report["generate"] = gen
        bank()

    # weight-read floor for the tiny model: all params once per token
    n_params = sum(int(np.prod(v.shape))
                   for v in model.state_dict().values())
    report["weight_floor_ms_per_tok_bs1"] = round(
        n_params * 2 / 819e9 * 1e3, 4)
    bank()

    # --- 3) fused projections, steady-state
    try:
        from paddle_tpu.nn.fuse import fuse_projections
        pt.seed(0)
        fused = fuse_projections(LlamaForCausalLM(cfg))
        for bs in (1, 8):
            t64 = time_generate(fused, bs, 64)
            t256 = time_generate(fused, bs, 256)
            gen[f"fused_bs{bs}"] = {
                "per_token_ms": round((t256 - t64) / 192 * 1e3, 4)}
            report["generate"] = gen
            bank()
    except Exception as e:
        gen["fused_error"] = repr(e)[:200]
        report["generate"] = gen
        bank()

    # --- 4) int8/int4: kernel route vs forced-XLA-dequant route. Each
    # bits-width guarded on its own so an int4-specific compile failure
    # cannot cost the remaining rungs or section 5 (cf. bench.py).
    from paddle_tpu.quant import quantize_model
    for bits in (8, 4):
        try:
            for tag, disable in ((f"int{bits}_kernel", ""),
                                 (f"int{bits}_xla", "1")):
                os.environ["PADDLE_TPU_DISABLE_QUANT_KERNEL"] = disable
                pt.seed(0)
                qm = LlamaForCausalLM(cfg)
                quantize_model(qm, bits=bits, block_size=128,
                               skip=["lm_head", "embed"])
                for bs in (1, 8):
                    t64 = time_generate(qm, bs, 64)
                    t256 = time_generate(qm, bs, 256)
                    gen[f"{tag}_bs{bs}"] = {
                        "per_token_ms": round((t256 - t64) / 192 * 1e3, 4)}
                    report["generate"] = gen
                    bank()
        except Exception as e:
            gen[f"int{bits}_error"] = repr(e)[:200]
            report["generate"] = gen
            bank()
    os.environ.pop("PADDLE_TPU_DISABLE_QUANT_KERNEL", None)

    # --- 5) paged engine (ISSUE 6): per-tick cost + dispatch-vs-compute
    # split for each tick architecture. Per tick:
    #   tick_ms        = wall around step() (everything)
    #   program_ms     = the engine's decode-step histogram window (the
    #                    jitted call + the (nxt, lps, done) D2H sync)
    #   host_sched_ms  = tick_ms - program_ms (python scheduling,
    #                    mirror bookkeeping, upload staging)
    #   dispatch_floor_ms = a no-op jitted call, fully synced — the
    #                    floor every dispatch pays before any compute
    #   est_compute_ms = program_ms - dispatch_floor_ms
    #   dispatch_overhead_frac = 1 - est_compute_ms / tick_ms
    # The scan row divides its per-dispatch histogram window by K.
    from paddle_tpu.generation.paged import PagedEngine

    # every real tick pays dispatch + a blocking D2H (jax.device_get of
    # the (nxt, lps, done) readback), so the floor must sync EVERY call
    # — an unsynced loop would measure async enqueue throughput on
    # hardware, not the round trip (each np.asarray is that sync)
    noop = jax.jit(lambda x: x + 1)
    z = jnp.zeros((8,), jnp.float32)
    np.asarray(noop(z))
    t0 = time.perf_counter()
    for _ in range(100):
        np.asarray(noop(z))
    floor_ms = (time.perf_counter() - t0) / 100 * 1e3

    # ring on/off A/B (ISSUE 11): "fused_sync" is the r06 architecture
    # (one BLOCKING D2H per dispatch); "fused" is the async token ring
    # (drains ride one dispatch behind — blocking_d2h_per_tick shows
    # the readback amortized away); the scan row composes ring + K=8
    # (<= 1 drain per 8 ticks).
    paged = {"dispatch_floor_ms": round(floor_ms, 4)}
    rs2 = np.random.RandomState(1)
    for tag, kw in (("host_tick", dict(fused_tick=False)),
                    ("fused_sync", dict(ring_mode=False)),
                    ("fused", {}),
                    ("fused_scan8", dict(ticks_per_dispatch=8))):
        K = max(1, kw.get("ticks_per_dispatch", 1))
        eng = PagedEngine(model, max_slots=8, num_blocks=64,
                          block_size=32, max_blocks_per_seq=8,
                          prefill_buckets=(32,), **kw)
        for i in range(8):
            # 8 + 240 = 248 <= max_blocks_per_seq*block_size = 256: the
            # timed ticks never finish a request, so all 8 slots stay
            # busy for the whole window
            eng.submit(f"r{i}", rs2.randint(1, 255, (1, 8)),
                       max_new_tokens=240)
        for _ in range(-(-12 // K)):   # admit + compile
            eng.step()
        _, sum0, cnt0 = eng._h_decode.export()
        d0, u0 = eng.dispatch_count, eng.h2d_uploads
        s0, rd0 = eng.d2h_syncs, eng.ring_drains
        n_steps = max(1, 100 // K)
        t0 = time.perf_counter()
        for _ in range(n_steps):
            eng.step()
        dt = time.perf_counter() - t0
        _, sum1, cnt1 = eng._h_decode.export()
        tick_ms = dt / (n_steps * K) * 1e3
        program_ms = (sum1 - sum0) / max(cnt1 - cnt0, 1) / K
        est_compute = max(program_ms - floor_ms / K, 0.0)
        paged[tag] = {
            "tick_ms": round(tick_ms, 3),
            "program_ms": round(program_ms, 3),
            "host_sched_ms": round(tick_ms - program_ms, 3),
            "est_compute_ms": round(est_compute, 3),
            "dispatch_overhead_frac": round(
                max(1 - est_compute / max(tick_ms, 1e-9), 0.0), 3),
            "tokens_per_sec": round(8 * n_steps * K / dt, 1),
            "dispatches_per_tick": round(
                (eng.dispatch_count - d0) / (n_steps * K), 2),
            "h2d_uploads_per_tick": round(
                (eng.h2d_uploads - u0) / (n_steps * K), 2),
            # the ISSUE 11 acceptance row: blocking readbacks per tick
            # (sync modes pay 1/dispatch; ring drains of ready data
            # count 0 here, ring drains that had to wait count 1)
            "blocking_d2h_per_tick": round(
                (eng.d2h_syncs - s0) / (n_steps * K), 3),
            "ring_drains_per_tick": round(
                (eng.ring_drains - rd0) / (n_steps * K), 3)}
        report["paged"] = paged
        bank()
    base = paged["host_tick"]["tokens_per_sec"]
    for tag in ("fused_sync", "fused", "fused_scan8"):
        paged[tag]["speedup_vs_host_tick"] = round(
            paged[tag]["tokens_per_sec"] / max(base, 1e-9), 2)
    paged["fused"]["speedup_vs_sync"] = round(
        paged["fused"]["tokens_per_sec"]
        / max(paged["fused_sync"]["tokens_per_sec"], 1e-9), 2)
    # headline rung for bench.py ingestion: the best architecture wins
    paged["paged_tokens_per_sec"] = max(
        paged[t]["tokens_per_sec"]
        for t in ("fused_sync", "fused", "fused_scan8"))
    report["paged"] = paged
    bank()

    # --- 6) speculative paged tick (ISSUE 7): accept-rate sweep + the
    # paged_spec_tokens_per_sec rung. A zeroed lm_head makes the tiny
    # model's greedy stream perfectly repetitive (token 0 forever), so
    # prompt-lookup accepts ~every draft — the BEST case; the same
    # spec engine on the random-weight model is the collapse case (the
    # adaptive-k EMA shuts drafting off). Each row carries the same
    # program-vs-host split as section 5 so multi-token commits'
    # dispatch amortization is a number.
    spec = {}
    try:
        pt.seed(0)
        rep_model = LlamaForCausalLM(cfg)
        rep_model.lm_head.weight = rep_model.lm_head.weight * 0.0

        def run_spec(m, new_tok=48, temperature=0.0, **kw):
            eng = PagedEngine(m, max_slots=8, num_blocks=64,
                              block_size=32, max_blocks_per_seq=8,
                              prefill_buckets=(32,), **kw)
            rs4 = np.random.RandomState(3)
            samp = dict(temperature=temperature) if temperature else {}
            eng.submit("warm", rs4.randint(1, 255, (1, 8)),
                       max_new_tokens=2, seed=0, **samp)
            eng.run()          # compile untimed
            for i in range(8):
                eng.submit(i, rs4.randint(1, 255, (1, 8)),
                           max_new_tokens=new_tok, seed=i + 1, **samp)
            # every counter is DELTA'd past the warm-up request, like
            # the _h_decode window — cumulative reads would bias the
            # short spec runs (~6 dispatches) far more than spec-off
            st0 = eng.stats
            _, sum0, cnt0 = eng._h_decode.export()
            _, tpf_sum0, tpf_cnt0 = eng._h_tpf.export()
            t0 = time.perf_counter()
            res = eng.run()
            dt = time.perf_counter() - t0
            _, sum1, cnt1 = eng._h_decode.export()
            _, tpf_sum1, tpf_cnt1 = eng._h_tpf.export()
            n_tok = sum(len(v) for key, v in res.items()
                        if key != "warm")
            st = eng.stats
            nd = max(st["decode_steps"] - st0["decode_steps"], 1)
            prop = st["spec_proposed"] - st0["spec_proposed"]
            # per-slot tokens-per-forward straight from the histogram:
            # one observe per (tick, active slot), value = accepted len
            tpf_sum = tpf_sum1 - tpf_sum0
            tpf_cnt = tpf_cnt1 - tpf_cnt0
            return {
                "tokens_per_sec": round(n_tok / dt, 1),
                "decode_dispatches": nd,
                "tokens_per_forward_per_slot": round(
                    tpf_sum / tpf_cnt, 2) if tpf_cnt else 1.0,
                "tokens_per_dispatch": round(n_tok / nd, 2),
                "program_ms_per_dispatch": round(
                    (sum1 - sum0) / max(cnt1 - cnt0, 1), 3),
                "accept_rate": round(
                    (st["spec_accepted"] - st0["spec_accepted"])
                    / prop, 4) if prop else 0.0,
            }

        spec["spec_off_repetitive"] = run_spec(rep_model)
        for k in (2, 4, 8):
            spec[f"spec_k{k}_repetitive"] = run_spec(rep_model,
                                                     spec_tokens=k)
            report["spec"] = spec
            bank()
        spec["spec_k4_random"] = run_spec(model, spec_tokens=4)
        b0 = spec["spec_off_repetitive"]["tokens_per_sec"]
        for key in spec:
            if key != "spec_off_repetitive":
                spec[key]["speedup_vs_spec_off"] = round(
                    spec[key]["tokens_per_sec"] / max(b0, 1e-9), 2)
        # the rung bench.py ingests alongside paged_tokens_per_sec
        paged["paged_spec_tokens_per_sec"] = max(
            spec[f"spec_k{k}_repetitive"]["tokens_per_sec"]
            for k in (2, 4, 8))
        report["spec"] = spec
        report["paged"] = paged
        bank()
    except Exception as e:
        spec["error"] = repr(e)[:300]
        report["spec"] = spec
        bank()

    # --- 6b) SAMPLED speculative ticks (ISSUE 11): the rejection-
    # sampled verify lets sampled rows ride spec ticks. A decisive
    # TABLE stub (token t argmaxes to (t+1) % 7 with a 12.0 margin —
    # the loadgen-style machinery-not-FLOPs trade) makes the sampled
    # stream repetitive at T=0.7, so accept rates mirror real
    # copy-heavy sampled traffic; spec-off on the same stream is the
    # 1.0 tokens/forward baseline. Rung:
    # paged_sampled_spec_tokens_per_sec (bench.py auto-ingests).
    sspec = {}
    try:
        import jax as _jax
        from paddle_tpu.generation.paged import (paged_chunk_attention,
                                                 paged_decode_attention,
                                                 paged_decode_write,
                                                 paged_prefill_write)

        class _SampCfg:
            vocab_size = 128
            num_hidden_layers = 1
            num_key_value_heads = 1
            head_dim = 8
            dtype = jnp.float32

        class SampStub:
            config = _SampCfg()

            def functional(self):
                d, V = 8, 128
                key = _jax.random.PRNGKey(0)
                params = dict(
                    emb=_jax.random.normal(key, (V, d)),
                    table=_jax.nn.one_hot((jnp.arange(V) + 1) % 7,
                                          V) * 12.0)

                def fn(params, tokens, kv_caches=None, positions=None,
                       paged_chunk=False, paged_decode=False):
                    x = params["emb"][tokens]
                    kv = x[:, :, None, :]
                    pk = kv_caches[0]
                    if tokens.shape[1] == 1 or paged_decode:
                        pk = paged_decode_write(pk, kv, kv)
                        o = paged_decode_attention(
                            x[:, :, None, :], pk)[:, :, 0]
                    else:
                        pk = paged_prefill_write(pk, kv, kv)
                        o = paged_chunk_attention(
                            x[:, :, None, :], pk, positions)[:, :, 0]
                    return (params["table"][tokens]
                            + 0.0 * jnp.sum(o, -1, keepdims=True)), [pk]

                return fn, params

        samp_model = SampStub()
        sspec["sampled_spec_off"] = run_spec(samp_model,
                                             temperature=0.7)
        for k in (2, 4):
            sspec[f"sampled_spec_k{k}"] = run_spec(
                samp_model, temperature=0.7, spec_tokens=k)
            report["sampled_spec"] = sspec
            bank()
        sb = sspec["sampled_spec_off"]["tokens_per_sec"]
        for key in sspec:
            if key != "sampled_spec_off":
                sspec[key]["speedup_vs_spec_off"] = round(
                    sspec[key]["tokens_per_sec"] / max(sb, 1e-9), 2)
        # the rung + its own baseline and tokens/forward: the stub is
        # compute-free, so the ABSOLUTE number only means anything
        # relative to sampled_spec_off on the same stub (on real
        # models the forward dominates and tokens/forward is the
        # transferable win — see docs/PERFORMANCE.md)
        best_k = max((2, 4), key=lambda k: sspec[
            f"sampled_spec_k{k}"]["tokens_per_sec"])
        paged["paged_sampled_spec_tokens_per_sec"] = \
            sspec[f"sampled_spec_k{best_k}"]["tokens_per_sec"]
        paged["paged_sampled_spec_off_tokens_per_sec"] = sb
        paged["paged_sampled_spec_tokens_per_forward"] = \
            sspec[f"sampled_spec_k{best_k}"][
                "tokens_per_forward_per_slot"]
        report["sampled_spec"] = sspec
        report["paged"] = paged
        bank()
    except Exception as e:
        sspec["error"] = repr(e)[:300]
        report["sampled_spec"] = sspec
        bank()
    # --- 7/7b) churn A/B/C (ISSUE 14 + 19): slot transitions under
    # serving-like traffic — short requests queued deep, so a finish +
    # admit lands every few ticks. Three transition modes:
    #   full_rebuild: a FULL host-mirror rebuild + re-upload per churn
    #     tick (the pre-ISSUE-14 path);
    #   delta: one descriptor-sized patch per transition — its own
    #     tiny dispatch (PR 12, kept as an explicit knob);
    #   fused (the engine default): descriptors staged into the
    #     device-resident queue by a plain upload and applied by the
    #     NEXT tick's program — one dispatch per tick, churn or not.
    # Rows report dispatches/tick (the ISSUE 19 claim), uploads/tick,
    # upload BYTES/tick, rebuild/patch/fused counts and tokens/s; the
    # delta row's throughput is the ``paged_churn_tokens_per_sec``
    # rung and the fused row's is ``paged_churn_fused_tokens_per_sec``,
    # both auto-ingested by bench.py beside the other paged rungs.
    # The stub keeps this a TRANSITION-MACHINERY A/B (like §6b's
    # decisive-table stub: the absolute number only means anything
    # relative to the other row on the same stub — on real models the
    # forward dominates and the transferable win is upload bytes +
    # zero rebuild stalls). Budgets are STAGGERED (max_new=4+i%5) so a
    # finish+admit lands every 1-2 ticks instead of 8 at once — the
    # serving churn shape; synchronized batch finishes amortize a full
    # rebuild over 8 transitions and favor the reference.
    churn = {}
    try:
        from paddle_tpu.generation.stub import TickStubModel

        def run_churn(n_req=96, **kw):
            eng = PagedEngine(TickStubModel(), max_slots=8,
                              num_blocks=64, block_size=32,
                              max_blocks_per_seq=8,
                              prefill_buckets=(32,), **kw)
            rs6 = np.random.RandomState(7)
            # two STAGGERED warm requests: the second's admit lands
            # mid-decode of the first, so the transition path (the
            # patch program in delta mode) compiles untimed like the
            # tick/prefill executables — a cold first patch otherwise
            # bills its trace+compile to the measured window
            eng.submit("warm", rs6.randint(1, 120, (1, 8)),
                       max_new_tokens=6)
            eng.step()
            eng.step()
            eng.submit("warm2", rs6.randint(1, 120, (1, 8)),
                       max_new_tokens=4)
            eng.run()
            for i in range(n_req):
                eng.submit(i, rs6.randint(1, 120, (1, 8)),
                           max_new_tokens=4 + i % 5)
            st0 = eng.stats
            u0, b0 = eng.h2d_uploads, eng.h2d_upload_bytes
            fr0, dp0 = eng.full_rebuilds, eng.delta_patches
            pf0, dc0 = eng.patches_fused, eng.dispatch_count
            t0 = time.perf_counter()
            res = eng.run()
            dt = time.perf_counter() - t0
            n_tok = sum(len(v) for key, v in res.items()
                        if key not in ("warm", "warm2"))
            ticks = max(eng.stats["decode_steps"]
                        - st0["decode_steps"], 1)
            return {
                "tokens_per_sec": round(n_tok / dt, 1),
                "decode_ticks": ticks,
                "full_rebuilds": eng.full_rebuilds - fr0,
                "delta_patches": eng.delta_patches - dp0,
                "patches_fused": eng.patches_fused - pf0,
                "dispatches_per_tick": round(
                    (eng.dispatch_count - dc0) / ticks, 3),
                "h2d_uploads_per_tick": round(
                    (eng.h2d_uploads - u0) / ticks, 3),
                "h2d_upload_bytes_per_tick": round(
                    (eng.h2d_upload_bytes - b0) / ticks, 1),
            }

        # best-of-3 per mode: single-core wall clocks on a shared box
        # are noisy and the A/B question is the achievable rate
        def best(**kw):
            rows = [run_churn(**kw) for _ in range(3)]
            return max(rows, key=lambda r: r["tokens_per_sec"])

        churn["full_rebuild"] = best(delta_transitions=False)
        churn["delta"] = best(patch_fuse=False)
        churn["fused"] = best()
        churn["delta"]["speedup_vs_rebuild"] = round(
            churn["delta"]["tokens_per_sec"]
            / max(churn["full_rebuild"]["tokens_per_sec"], 1e-9), 2)
        churn["fused"]["speedup_vs_rebuild"] = round(
            churn["fused"]["tokens_per_sec"]
            / max(churn["full_rebuild"]["tokens_per_sec"], 1e-9), 2)
        churn["fused"]["speedup_vs_delta"] = round(
            churn["fused"]["tokens_per_sec"]
            / max(churn["delta"]["tokens_per_sec"], 1e-9), 2)
        # the ISSUE 14 acceptance row: steady churn, zero full rebuilds
        churn["delta_zero_rebuilds"] = \
            churn["delta"]["full_rebuilds"] == 0
        # the ISSUE 19 acceptance rows: the fused run kept churn to
        # ~one dispatch per tick with zero standalone patch programs
        churn["fused_zero_standalone_patches"] = \
            churn["fused"]["delta_patches"] == 0 \
            and churn["fused"]["full_rebuilds"] == 0
        paged["paged_churn_tokens_per_sec"] = \
            churn["delta"]["tokens_per_sec"]
        paged["paged_churn_fused_tokens_per_sec"] = \
            churn["fused"]["tokens_per_sec"]
        report["churn"] = churn
        report["paged"] = paged
        bank()
    except Exception as e:
        churn["error"] = repr(e)[:300]
        report["churn"] = churn
        bank()

    # machine-ingestible line (bench.py merges DECODE_PROFILE_r06.json's
    # paged section into its decode rung when the file is present)
    print("PAGED_JSON " + json.dumps(paged), flush=True)
    print(json.dumps(report, indent=1))


if __name__ == "__main__":
    try:
        main()
    except Exception as e:  # bank whatever we got plus the failure
        report["error"] = repr(e)[:400]
        bank()
        raise
