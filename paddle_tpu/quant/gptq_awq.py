"""Error-compensating PTQ: GPTQ and AWQ (reference: PaddleNLP llm
quantization recipes — PaddleSlim's GPTQ/AWQ passes; Frantar et al. 2022,
Lin et al. 2023).

Both emit the SAME blockwise (qweight, scales) layout as
``quantize_blockwise``, so the quantized model reuses ``QuantizedLinear``
and the fused Pallas dequant-matmul decode path unchanged — the
algorithms only improve WHICH int codes get stored:

- **GPTQ** quantizes input-channels one at a time and redistributes each
  channel's rounding error onto the not-yet-quantized channels through
  the inverse Hessian of the calibration activations (H = X^T X) — the
  classic OBS update, run offline on host in float64.
- **AWQ** scales salient input channels UP before rounding (s_j =
  act_j^alpha / w_j^(1-alpha), alpha grid-searched per layer against the
  calibration reconstruction error) and folds the inverse scale into the
  activation path at runtime.

Calibration inputs are captured with ``Layer`` forward-pre-hooks — no
graph surgery, works on any model tree.
"""
from __future__ import annotations

from typing import Dict, List, Optional

import jax.numpy as jnp
import numpy as np

from ..nn.layer import Parameter
from .weight_only import (QuantizedLinear, dequantize_weight,
                          linear_quant_meta, pack_int4, quantize_blockwise,
                          quantize_model)

__all__ = ["gptq_quantize_weight", "awq_search_scale",
           "gptq_quantize_model", "awq_quantize_model",
           "capture_linear_inputs"]


# ------------------------------------------------------------------- GPTQ

def gptq_quantize_weight(w, x_cal, bits: int = 4, block_size: int = 128,
                         percdamp: float = 0.01, act_order: bool = False):
    """GPTQ on a [in, out] weight with calibration activations
    [n, in]. Returns (qweight, scales) in quantize_blockwise's layout.

    ``act_order=False``: channels quantized 0..in-1, group scales taken
    from the current (error-compensated) block values at block start.

    ``act_order=True`` (the accuracy-critical reference variant):
    channels are VISITED by descending diag(H) — the most activation-
    salient channels quantize first, while every later channel can still
    absorb their rounding error — but each channel keeps the scale of
    its ORIGINAL contiguous block, and the int codes are permuted back,
    so the emitted (qweight, scales) layout is exactly
    quantize_blockwise's: QuantizedLinear and the Pallas dequant-matmul
    decode path need no g_idx indirection. Scales are fixed up front
    from the uncompensated weights (the visit order no longer walks
    blocks contiguously).

    The damped Cholesky handles rank-deficient H either way.
    """
    w = np.asarray(w, np.float64)                       # [in, out]
    x = np.asarray(x_cal, np.float64).reshape(-1, w.shape[0])
    din, dout = w.shape
    if din % block_size:
        raise ValueError(f"in_features {din} % block {block_size} != 0")
    qmax = 127.0 if bits == 8 else 7.0

    H = x.T @ x                                          # [in, in]
    damp = percdamp * np.mean(np.diag(H))
    H[np.diag_indices(din)] += max(damp, 1e-8)
    Q = np.zeros_like(w)

    if act_order:
        perm = np.argsort(-np.diag(H))                   # salient first
        Hp = H[perm][:, perm]
        # dead channels (no calibration signal): keep H invertible
        Hinv = np.linalg.cholesky(np.linalg.inv(Hp)).T   # upper
        W = w[perm].copy()
        scales = np.maximum(
            np.abs(w).reshape(din // block_size, block_size, dout)
            .max(axis=1) / qmax, 1e-12)
        for i in range(din):
            s = scales[perm[i] // block_size]
            qi = np.clip(np.round(W[i] / s), -qmax, qmax)
            Q[perm[i]] = qi
            err = (W[i] - qi * s) / Hinv[i, i]
            # push the rounding error onto later-visited channels
            W[i + 1:] -= np.outer(Hinv[i, i + 1:], err)
    else:
        Hinv = np.linalg.cholesky(np.linalg.inv(H)).T    # upper
        W = w.copy()
        scales = np.zeros((din // block_size, dout))
        for b0 in range(0, din, block_size):
            b1 = b0 + block_size
            # group scales from the CURRENT (error-compensated) values
            blk = b0 // block_size
            scales[blk] = np.maximum(np.abs(W[b0:b1]).max(axis=0) / qmax,
                                     1e-12)
            for i in range(b0, b1):
                s = scales[blk]
                qi = np.clip(np.round(W[i] / s), -qmax, qmax)
                Q[i] = qi
                err = (W[i] - qi * s) / Hinv[i, i]
                W[i + 1:] -= np.outer(Hinv[i, i + 1:], err)
    q = jnp.asarray(Q.astype(np.int8))
    if bits == 4:
        q = pack_int4(q)
    return q, jnp.asarray(scales, jnp.bfloat16)


# -------------------------------------------------------------------- AWQ

def awq_search_scale(w, x_cal, bits: int = 4, block_size: int = 128,
                     n_grid: int = 20):
    """Per-input-channel AWQ scale for a [in, out] weight: grid-search
    alpha in [0, 1) minimizing || x @ W  -  (x/s) @ RTN(W * s) || on the
    calibration sample. Returns the [in] scale vector (float32)."""
    x = np.asarray(x_cal, np.float32).reshape(-1, w.shape[0])
    wnp = np.asarray(w, np.float32)
    act = np.maximum(np.abs(x).mean(axis=0), 1e-8)       # [in]
    wmax = np.maximum(np.abs(wnp).max(axis=1), 1e-8)     # [in]
    ref = x @ wnp
    best_s, best_err = np.ones_like(act), np.inf
    for g in range(n_grid):
        alpha = g / n_grid
        s = act ** alpha / wmax ** (1 - alpha)
        s = s / np.sqrt(s.max() * s.min())               # center the range
        qw, sc = quantize_blockwise(jnp.asarray(wnp * s[:, None]),
                                    bits, block_size)
        deq = np.asarray(dequantize_weight(qw, sc, bits, block_size,
                                           jnp.float32))
        err = float(np.mean((ref - (x / s) @ deq) ** 2))
        if err < best_err:
            best_err, best_s = err, s
    return jnp.asarray(best_s, jnp.float32)


class AWQLinear(QuantizedLinear):
    """QuantizedLinear whose input is divided by the AWQ channel scale
    (the weight was multiplied by it before rounding — same product,
    int codes spend their range on the salient channels)."""

    def __init__(self, *args, awq_scales=None, **kw):
        super().__init__(*args, **kw)
        self.awq_inv = Parameter(1.0 / awq_scales, trainable=False)

    def forward(self, x):
        return super().forward(x * self.awq_inv.astype(x.dtype))


# ---------------------------------------------------------- model passes

def capture_linear_inputs(model, batches, max_tokens: int = 512,
                          skip: Optional[List[str]] = None
                          ) -> Dict[str, np.ndarray]:
    """Run ``model`` over ``batches`` (list of model-call args tuples or
    arrays) recording up to ``max_tokens`` input rows per eligible
    linear, via forward-pre-hooks. Returns {layer_path: [n, in]}."""
    from ..nn.common import Linear
    from ..parallel.layers import ColumnParallelLinear, RowParallelLinear
    skip = skip or []
    captured: Dict[str, list] = {}
    handles = []

    def make_hook(path):
        def hook(layer, inputs):
            x = np.asarray(inputs[0], np.float32)
            x = x.reshape(-1, x.shape[-1])
            have = sum(a.shape[0] for a in captured[path])
            if have < max_tokens:
                captured[path].append(x[:max_tokens - have])
            return None
        return hook

    for path, sub in model.named_sublayers(include_self=False):
        if isinstance(sub, (Linear, ColumnParallelLinear,
                            RowParallelLinear)) \
                and not any(s in path for s in skip):
            captured[path] = []
            key = sub.register_forward_pre_hook(make_hook(path))
            handles.append((sub, key))
    try:
        for b in batches:
            model(*b) if isinstance(b, tuple) else model(b)
    finally:
        for sub, key in handles:
            sub._forward_pre_hooks.pop(key, None)
    return {p: np.concatenate(a) for p, a in captured.items() if a}


def gptq_quantize_model(model, batches, bits: int = 4,
                        block_size: int = 128,
                        skip: Optional[List[str]] = None,
                        percdamp: float = 0.01,
                        act_order: bool = False) -> int:
    """Calibrate + GPTQ-quantize every eligible linear in place (one
    traversal definition: weight_only.quantize_model drives the swap).
    Returns the number of swapped layers."""
    calib = capture_linear_inputs(model, batches, skip=skip)

    def build(sub, path):
        q, s = gptq_quantize_weight(sub.weight, calib[path], bits,
                                    block_size, percdamp, act_order)
        return QuantizedLinear.from_linear(sub, bits=bits,
                                           block_size=block_size,
                                           qweight=q, scales=s)

    return quantize_model(model, bits, block_size, skip, build=build,
                          extra_filter=lambda p: p in calib)


def awq_quantize_model(model, batches, bits: int = 4,
                       block_size: int = 128,
                       skip: Optional[List[str]] = None,
                       n_grid: int = 20) -> int:
    """Calibrate + AWQ-quantize every eligible linear in place."""
    calib = capture_linear_inputs(model, batches, skip=skip)

    def build(sub, path):
        s = awq_search_scale(sub.weight, calib[path], bits, block_size,
                             n_grid)
        q, sc = quantize_blockwise(sub.weight * s[:, None], bits,
                                   block_size)
        wp, bp, in_axis, out_axis = linear_quant_meta(sub)
        return AWQLinear(q, sc, getattr(sub, "bias", None), bits,
                         block_size, weight_partition=wp,
                         bias_partition=bp, awq_scales=s,
                         input_parallel_axis=in_axis,
                         output_parallel_axis=out_axis)

    return quantize_model(model, bits, block_size, skip, build=build,
                          extra_filter=lambda p: p in calib)
