"""Vision Transformer (reference: PaddleClas ppcls/arch/backbone/
model_zoo/vision_transformer.py and PaddleMIX ViT encoders — patch embed,
class token, learned position embeddings, pre-LN encoder).

TPU-native design: patchify is a strided Conv2D (an implicit GEMM on the
MXU); the encoder reuses the same Column/RowParallel projections as the LLM
stack so a big ViT shards over ``tp`` identically. All shapes static; the
class token is concatenated once at trace time.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Optional

import jax.numpy as jnp

from .. import nn
from ..nn import functional as F
from ..nn import initializer as I
from ..nn.layer import Layer, Parameter
from ..ops.attention import dense_attention
from ..parallel.layers import ColumnParallelLinear, RowParallelLinear
from ..parallel.sharding import constraint
from ..utils.rng import next_key


@dataclass
class ViTConfig:
    image_size: int = 224
    patch_size: int = 16
    in_channels: int = 3
    hidden_size: int = 768
    intermediate_size: int = 3072
    num_hidden_layers: int = 12
    num_attention_heads: int = 12
    num_classes: int = 1000
    dropout_prob: float = 0.0
    layer_norm_eps: float = 1e-6
    use_class_token: bool = True
    global_pool: bool = False      # mean-pool instead of CLS for the head
    # HF-CLIP vision tower compat: LayerNorm after the embeddings
    # (transformers' pre_layrnorm) and OpenAI's quick-gelu activation
    pre_norm: bool = False
    hidden_act: str = "gelu"       # "gelu" (erf) | "quick_gelu"
    dtype: Any = jnp.float32

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_attention_heads

    @property
    def num_patches(self) -> int:
        return (self.image_size // self.patch_size) ** 2


def vit_tiny(**overrides) -> ViTConfig:
    base = dict(image_size=32, patch_size=8, hidden_size=64,
                intermediate_size=128, num_hidden_layers=2,
                num_attention_heads=4, num_classes=10)
    base.update(overrides)
    return ViTConfig(**base)


def vit_base_patch16_224(**overrides) -> ViTConfig:
    return ViTConfig(**overrides)


def vit_large_patch14_224(**overrides) -> ViTConfig:
    base = dict(patch_size=14, hidden_size=1024, intermediate_size=4096,
                num_hidden_layers=24, num_attention_heads=16)
    base.update(overrides)
    return ViTConfig(**base)


class PatchEmbed(Layer):
    def __init__(self, config: ViTConfig):
        super().__init__()
        self.proj = nn.Conv2D(config.in_channels, config.hidden_size,
                              config.patch_size, stride=config.patch_size)

    def forward(self, x):
        x = self.proj(x)                       # [b, h, gh, gw]
        b, c = x.shape[:2]
        return x.reshape(b, c, -1).transpose(0, 2, 1)   # [b, n, h]


class ViTAttention(Layer):
    def __init__(self, config: ViTConfig):
        super().__init__()
        self.config = config
        h = config.hidden_size
        self.qkv = ColumnParallelLinear(h, 3 * h, has_bias=True,
                                        gather_output=False)
        self.proj = RowParallelLinear(h, h, has_bias=True,
                                      input_is_parallel=True)

    def forward(self, x):
        cfg = self.config
        b, s, _ = x.shape
        nh, d = cfg.num_attention_heads, cfg.head_dim
        qkv = self.qkv(x).reshape(b, s, 3, nh, d)
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        out = dense_attention(q, k, v, causal=False)
        return self.proj(out.reshape(b, s, nh * d))


class ViTBlock(Layer):
    """Pre-LN transformer encoder block."""

    def __init__(self, config: ViTConfig):
        super().__init__()
        self.config = config
        eps = config.layer_norm_eps
        self.norm1 = nn.LayerNorm(config.hidden_size, epsilon=eps)
        self.attn = ViTAttention(config)
        self.norm2 = nn.LayerNorm(config.hidden_size, epsilon=eps)
        self.fc1 = ColumnParallelLinear(config.hidden_size,
                                        config.intermediate_size,
                                        has_bias=True, gather_output=False)
        self.fc2 = RowParallelLinear(config.intermediate_size,
                                     config.hidden_size, has_bias=True,
                                     input_is_parallel=True)
        self.dropout = nn.Dropout(config.dropout_prob)

    def forward(self, x):
        x = x + self.dropout(self.attn(self.norm1(x)))
        h = self.fc1(self.norm2(x))
        h = (F.quick_gelu(h) if self.config.hidden_act == "quick_gelu"
             else F.gelu(h))
        x = x + self.dropout(self.fc2(h))
        return constraint(x, ("dp", "fsdp"), None, None)


class ViTModel(Layer):
    def __init__(self, config: ViTConfig):
        super().__init__()
        self.config = config
        self.patch_embed = PatchEmbed(config)
        n_tokens = config.num_patches + int(config.use_class_token)
        init = I.TruncatedNormal(std=0.02)
        self.pos_embed = Parameter(
            init(next_key(), (1, n_tokens, config.hidden_size)))
        if config.use_class_token:
            self.cls_token = Parameter(
                jnp.zeros((1, 1, config.hidden_size)))
        if config.pre_norm:
            self.pre_norm = nn.LayerNorm(config.hidden_size,
                                         epsilon=config.layer_norm_eps)
        self.blocks = nn.LayerList(
            [ViTBlock(config) for _ in range(config.num_hidden_layers)])
        self.norm = nn.LayerNorm(config.hidden_size,
                                 epsilon=config.layer_norm_eps)
        if config.dtype != jnp.float32:
            self.to(dtype=config.dtype)

    def forward(self, pixel_values):
        cfg = self.config
        x = self.patch_embed(pixel_values)
        if cfg.use_class_token:
            cls = jnp.broadcast_to(self.cls_token,
                                   (x.shape[0], 1, x.shape[2]))
            x = jnp.concatenate([cls.astype(x.dtype), x], axis=1)
        x = x + self.pos_embed.astype(x.dtype)
        if cfg.pre_norm:
            x = self.pre_norm(x)
        x = constraint(x, ("dp", "fsdp"), None, None)
        for block in self.blocks:
            x = block(x)
        return self.norm(x)          # [b, n(+1), h]


class ViTForImageClassification(Layer):
    def __init__(self, config: ViTConfig):
        super().__init__()
        self.config = config
        self.vit = ViTModel(config)
        self.head = nn.Linear(config.hidden_size, config.num_classes)

    def forward(self, pixel_values):
        x = self.vit(pixel_values)
        if self.config.global_pool or not self.config.use_class_token:
            pooled = x.mean(axis=1)
        else:
            pooled = x[:, 0]
        return self.head(pooled).astype(jnp.float32)
