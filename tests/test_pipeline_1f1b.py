"""1F1B pipeline correctness (VERDICT r1 item 3): pp=4 tiny-Llama train
step must loss- and grad-match the non-pipelined step on the 8-CPU mesh."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.distributed import env
from paddle_tpu.models import LlamaForCausalLM, causal_lm_loss, llama_tiny
from paddle_tpu.parallel.pipeline import pipeline_value_and_grad, validate_pp_mesh


def _tiny_model(n_layers=4):
    pt.seed(0)
    return LlamaForCausalLM(llama_tiny(
        vocab_size=128, hidden_size=32, intermediate_size=64,
        num_hidden_layers=n_layers, num_attention_heads=4,
        num_key_value_heads=2))


def _reference_loss_grads(model, tokens):
    """Non-pipelined: mean over microbatches of the per-microbatch loss."""
    fn, params = model.functional()

    def loss_of(p):
        losses = [causal_lm_loss(fn(p, tokens[m]), tokens[m])
                  for m in range(tokens.shape[0])]
        return jnp.mean(jnp.stack(losses))
    return jax.value_and_grad(loss_of)(dict(params))


@pytest.mark.parametrize("pp,dp", [(4, 2), (2, 1)])
def test_1f1b_matches_sequential(pp, dp):
    model = _tiny_model(n_layers=4)
    env.init_parallel_env({"pp": pp, "dp": dp},
                          devices=jax.devices()[:pp * dp])
    M, b, s = 3, 2, 16
    tokens = jnp.asarray(np.random.RandomState(1).randint(0, 128, (M, b, s)))

    _, params = model.functional()
    vag = jax.jit(model.pipeline_functional(pp))
    loss_pp, grads_pp = vag(dict(params), tokens)

    loss_ref, grads_ref = _reference_loss_grads(model, tokens)
    np.testing.assert_allclose(float(loss_pp), float(loss_ref), rtol=1e-5)
    assert set(grads_pp) == set(grads_ref)
    for k in grads_ref:
        np.testing.assert_allclose(
            np.asarray(grads_pp[k]), np.asarray(grads_ref[k]),
            rtol=2e-4, atol=2e-5, err_msg=k)


def test_1f1b_single_microbatch():
    model = _tiny_model(n_layers=2)
    env.init_parallel_env({"pp": 2}, devices=jax.devices()[:2])
    tokens = jnp.asarray(np.random.RandomState(2).randint(0, 128, (1, 2, 16)))
    _, params = model.functional()
    loss_pp, _ = jax.jit(model.pipeline_functional(2))(dict(params), tokens)
    loss_ref, _ = _reference_loss_grads(model, tokens)
    np.testing.assert_allclose(float(loss_pp), float(loss_ref), rtol=1e-5)


def _moe_pp_setup(n_layers=2):
    """Tiny uniform-MoE model on a pp=2 x ep=2 x dp=2 mesh + its
    per-microbatch sequential reference (CE + router aux)."""
    from paddle_tpu.models.qwen2_moe import (Qwen2MoeForCausalLM,
                                             qwen2_moe_tiny)
    from paddle_tpu.parallel.sharding import shard_layer
    pt.seed(0)
    model = Qwen2MoeForCausalLM(qwen2_moe_tiny(
        vocab_size=128, hidden_size=32, intermediate_size=64,
        moe_intermediate_size=32, num_experts=4, num_experts_per_tok=2,
        num_hidden_layers=n_layers, num_attention_heads=4,
        num_key_value_heads=2,
        first_k_dense_replace=0, num_shared_experts=0))
    env.init_parallel_env({"pp": 2, "ep": 2, "dp": 2},
                          devices=jax.devices()[:8])
    shard_layer(model, fsdp_min_size=1 << 30)
    fn, params = model.functional()

    def reference(tokens):
        def loss_of(p):
            losses = []
            for m in range(tokens.shape[0]):
                logits, aux = fn(p, tokens[m], return_aux=True)
                losses.append(causal_lm_loss(logits, tokens[m]) + aux)
            return jnp.mean(jnp.stack(losses))
        return jax.value_and_grad(loss_of)(dict(params))

    return model, params, reference


def test_1f1b_composes_with_ep_moe():
    """VERDICT r3 item 4: pp x ep — the MoE aux loss rides each stage's
    own backward, ep stays a GSPMD auto axis inside stages; loss AND
    grads must match the per-microbatch sequential MoE step."""
    model, params, reference = _moe_pp_setup()
    tokens = jnp.asarray(np.random.RandomState(1).randint(0, 128, (2, 2, 16)))

    loss_pp, grads_pp = jax.jit(model.pipeline_functional(2))(
        dict(params), tokens)
    loss_ref, grads_ref = reference(tokens)

    np.testing.assert_allclose(float(loss_pp), float(loss_ref), rtol=1e-5)
    assert set(grads_pp) == set(grads_ref)
    for k in grads_ref:
        np.testing.assert_allclose(
            np.asarray(grads_pp[k]), np.asarray(grads_ref[k]),
            rtol=3e-4, atol=3e-5, err_msg=k)
    env.init_parallel_env({})


def test_interleaved_vpp_composes_with_ep_moe():
    """pp=2 x vpp=2 x ep=2 on the interleaved schedule: MoE chunks'
    aux seeding matches sequential too."""
    model, params, reference = _moe_pp_setup(n_layers=4)  # pp*vpp chunks
    tokens = jnp.asarray(np.random.RandomState(4).randint(0, 128, (2, 2, 16)))

    loss_pp, grads_pp = jax.jit(model.pipeline_functional(2, vpp=2))(
        dict(params), tokens)
    loss_ref, grads_ref = reference(tokens)
    np.testing.assert_allclose(float(loss_pp), float(loss_ref), rtol=1e-5)
    for k in grads_ref:
        np.testing.assert_allclose(
            np.asarray(grads_pp[k]), np.asarray(grads_ref[k]),
            rtol=3e-4, atol=3e-5, err_msg=k)
    env.init_parallel_env({})


def test_pp_mesh_validation_requires_pp_axis():
    from jax.sharding import Mesh
    mesh = Mesh(np.asarray(jax.devices()[:4]).reshape(4), ("x",))
    with pytest.raises(ValueError, match="pp"):
        validate_pp_mesh(mesh)


def test_1f1b_composes_with_tp_dp():
    """VERDICT r2 item 3: true hybrid — pp manual, tp+dp left to GSPMD
    inside the stage fns — must still grad-match the dense step."""
    model = _tiny_model(n_layers=4)
    env.init_parallel_env({"pp": 2, "tp": 2, "dp": 2},
                          devices=jax.devices()[:8])
    from paddle_tpu.parallel.sharding import shard_layer
    shard_layer(model, fsdp_min_size=1 << 30)  # tp rules only
    M, b, s = 3, 2, 16
    tokens = jnp.asarray(np.random.RandomState(5).randint(0, 128, (M, b, s)))

    _, params = model.functional()
    vag = jax.jit(model.pipeline_functional(2))
    loss_pp, grads_pp = vag(dict(params), tokens)

    loss_ref, grads_ref = _reference_loss_grads(model, tokens)
    np.testing.assert_allclose(float(loss_pp), float(loss_ref), rtol=1e-5)
    for k in grads_ref:
        np.testing.assert_allclose(
            np.asarray(grads_pp[k]), np.asarray(grads_ref[k]),
            rtol=2e-4, atol=2e-5, err_msg=k)


@pytest.mark.parametrize("M", [3, 4])
def test_interleaved_vpp_matches_sequential(M):
    """Virtual pipeline stages (Megatron interleaved 1F1B): pp=2 x vpp=2
    over 4 layers must loss- and grad-match the dense step, including a
    microbatch count that is not a multiple of pp."""
    model = _tiny_model(n_layers=4)
    env.init_parallel_env({"pp": 2, "dp": 2}, devices=jax.devices()[:4])
    b, s = 2, 16
    tokens = jnp.asarray(np.random.RandomState(9).randint(0, 128, (M, b, s)))

    _, params = model.functional()
    vag = jax.jit(model.pipeline_functional(2, vpp=2))
    loss_pp, grads_pp = vag(dict(params), tokens)

    loss_ref, grads_ref = _reference_loss_grads(model, tokens)
    np.testing.assert_allclose(float(loss_pp), float(loss_ref), rtol=1e-5)
    assert set(grads_pp) == set(grads_ref)
    for k in grads_ref:
        np.testing.assert_allclose(
            np.asarray(grads_pp[k]), np.asarray(grads_ref[k]),
            rtol=2e-4, atol=2e-5, err_msg=k)


def test_interleaved_vpp_composes_with_tp():
    """Interleaved chunks keep their Column/RowParallel layers: pp=2 x
    vpp=2 with tp=2 on the GSPMD auto axes still grad-matches dense."""
    model = _tiny_model(n_layers=4)
    env.init_parallel_env({"pp": 2, "tp": 2, "dp": 2},
                          devices=jax.devices()[:8])
    from paddle_tpu.parallel.sharding import shard_layer
    shard_layer(model, fsdp_min_size=1 << 30)  # tp rules only
    tokens = jnp.asarray(np.random.RandomState(11).randint(0, 128, (2, 2, 16)))

    _, params = model.functional()
    loss_pp, grads_pp = jax.jit(model.pipeline_functional(2, vpp=2))(
        dict(params), tokens)
    loss_ref, grads_ref = _reference_loss_grads(model, tokens)
    np.testing.assert_allclose(float(loss_pp), float(loss_ref), rtol=1e-5)
    for k in grads_ref:
        np.testing.assert_allclose(
            np.asarray(grads_pp[k]), np.asarray(grads_ref[k]),
            rtol=2e-4, atol=2e-5, err_msg=k)


def test_custom_logits_loss_under_pp():
    """VERDICT r2 weak#8: the pp path accepts a custom loss head via
    logits_loss (it runs at the LAST stage) and matches the dense step."""

    def smoothed_ce(logits, labels, eps=0.1):
        v = logits.shape[-1]
        lp = jax.nn.log_softmax(logits[:, :-1], axis=-1)
        tgt = jax.nn.one_hot(labels[:, 1:], v) * (1 - eps) + eps / v
        return -jnp.mean(jnp.sum(tgt * lp, axis=-1))

    model = _tiny_model(n_layers=2)
    env.init_parallel_env({"pp": 2}, devices=jax.devices()[:2])
    M, b, s = 2, 2, 16
    tokens = jnp.asarray(np.random.RandomState(7).randint(0, 128, (M, b, s)))

    _, params = model.functional()
    vag = jax.jit(model.pipeline_functional(2, logits_loss=smoothed_ce))
    loss_pp, grads_pp = vag(dict(params), tokens)

    fn, _ = model.functional()

    def ref(p):
        return jnp.mean(jnp.stack([smoothed_ce(fn(p, tokens[m]), tokens[m])
                                   for m in range(M)]))
    loss_ref, grads_ref = jax.value_and_grad(ref)(dict(params))
    np.testing.assert_allclose(float(loss_pp), float(loss_ref), rtol=1e-5)
    for k in grads_ref:
        np.testing.assert_allclose(
            np.asarray(grads_pp[k]), np.asarray(grads_ref[k]),
            rtol=2e-4, atol=2e-5, err_msg=k)

    # a whole-model loss_fn still cannot decompose onto stages
    from paddle_tpu.trainer import Trainer, TrainingArguments
    tr = Trainer(model, pt.optimizer.AdamW(learning_rate=1e-3),
                 TrainingArguments(output_dir="/tmp/pt_pp_lossfn"),
                 loss_fn=lambda fn, p, b: 0.0)
    with pytest.raises(ValueError, match="logits_loss"):
        tr._build_step()


def test_trainer_pp_path_runs_and_learns():
    """Trainer auto-selects the pipeline step when the mesh has pp>1."""
    from paddle_tpu.trainer import Trainer, TrainingArguments

    model = _tiny_model(n_layers=4)
    env.init_parallel_env({"pp": 4, "dp": 2})
    data = np.random.RandomState(3).randint(0, 128, (64, 16))

    class Loader:
        def __iter__(self):
            rs = np.random.RandomState(0)
            while True:
                idx = rs.randint(0, 64, 8)
                yield jnp.asarray(data[idx])
    tr = Trainer(model, pt.optimizer.AdamW(learning_rate=5e-3),
                 TrainingArguments(output_dir="/tmp/pt_pp_trainer",
                                   max_steps=12, logging_steps=4,
                                   gradient_accumulation_steps=4),
                 train_dataloader=Loader())
    tr.train()
    losses = tr.logger.history["loss"]
    assert losses[-1][1] < losses[0][1]
