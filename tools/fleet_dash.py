#!/usr/bin/env python
"""Terminal fleet dashboard (ISSUE 15): render the serving fleet's
telemetry as one time-aligned timeline — per-replica tok/s, queue
depth and SLO burn rate as unicode sparklines, with burn-rate alerts
and autoscaler actions marked on a shared axis. Replaces the "run
loadgen, dump rings, join offline" debugging loop with one look.

    python tools/fleet_dash.py RUN_DIR                # dumped series
    python tools/fleet_dash.py series_gw0.json [...]  # specific files
    python tools/fleet_dash.py --url HOST:PORT        # live fleet
    python tools/fleet_dash.py --url HOST:PORT --watch 30
    python tools/fleet_dash.py SIM_DUMP_DIR           # fleet_sim runs

File mode reads the ``series_<name>.json`` documents a drained
gateway (or ``observability.reset()``) flushes — each file becomes
one replica row — plus any ``flight_*.json`` beside them for
``fleet_autoscale`` events. ``tools/fleet_sim.py --dump-dir`` writes
the SAME two document shapes (``sim_*_series.json`` /
``sim_*_flight.json``, frontend-level ``fleet_*`` metrics, injected
incidents and frontend kills in the flight log), so a rehearsed
1000-replica incident renders on the identical timeline axis as a
live run — that is the point of sharing the writer (ISSUE 16). Live mode polls a gateway's or fleet
frontend's ``GET /metricsz`` (the frontend federates every peer's
cached windowed doc, so one URL shows the whole fleet) and redraws
until ``--watch`` seconds elapse.

Stdlib-only, like every serving tool in this repo.
"""
import argparse
import glob
import json
import os
import sys
import time
from typing import Any, Dict, List, Optional, Tuple

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

BLOCKS = "▁▂▃▄▅▆▇█"


def sparkline(vals: List[Optional[float]], lo: float = None,
              hi: float = None) -> str:
    """Unicode sparkline; None renders as a gap (no sample in bin)."""
    present = [v for v in vals if v is not None]
    if not present:
        return " " * len(vals)
    lo = min(present) if lo is None else lo
    hi = max(present) if hi is None else hi
    span = hi - lo
    out = []
    for v in vals:
        if v is None:
            out.append(" ")
        elif span <= 0:
            out.append(BLOCKS[0] if hi <= 0 else BLOCKS[3])
        else:
            i = int((v - lo) / span * (len(BLOCKS) - 1) + 0.5)
            out.append(BLOCKS[max(0, min(i, len(BLOCKS) - 1))])
    return "".join(out)


def counter_rate_points(samples: List[list]) -> List[Tuple[float,
                                                           float]]:
    """(t, rate) from consecutive cumulative samples."""
    out = []
    for a, b in zip(samples, samples[1:]):
        dt = b[0] - a[0]
        if dt > 0:
            out.append((b[0], (b[1] - a[1]) / dt))
    return out


def resample(points: List[Tuple[float, float]], t0: float, t1: float,
             width: int) -> List[Optional[float]]:
    """Mean per fixed-width time bin (None = empty bin) — what maps
    every series onto ONE shared axis regardless of sample cadence."""
    if t1 <= t0:
        t1 = t0 + 1e-9
    bins: List[List[float]] = [[] for _ in range(width)]
    for t, v in points:
        i = int((t - t0) / (t1 - t0) * width)
        if 0 <= i < width:
            bins[i].append(v)
        elif i == width:
            bins[-1].append(v)
    return [sum(b) / len(b) if b else None for b in bins]


def _metric_points(doc: dict, base: str,
                   agg=sum) -> List[Tuple[float, float]]:
    """Merge every label variant of metric ``base`` in a series doc:
    counters become summed rates, gauges/burn series aggregate with
    ``agg`` per timestamp."""
    by_t: Dict[float, List[float]] = {}
    kind = None
    for full, ent in (doc.get("metrics") or {}).items():
        if full.split("{", 1)[0] != base:
            continue
        kind = ent["kind"]
        pts = counter_rate_points(ent["samples"]) \
            if kind == "counter" else \
            [(s[0], s[1]) for s in ent["samples"]]
        for t, v in pts:
            by_t.setdefault(round(t, 6), []).append(v)
    return sorted((t, agg(vs)) for t, vs in by_t.items())


def _label_value(full: str, key: str) -> Optional[str]:
    """Value of label ``key`` in a ``name{k="v",...}`` series key."""
    if "{" not in full:
        return None
    for kv in full.split("{", 1)[1].rstrip("}").split(","):
        k, _, v = kv.partition("=")
        if k.strip() == key:
            return v.strip().strip('"')
    return None


def _phase_share_points(doc: dict) -> Dict[str, List[Tuple[float,
                                                           float]]]:
    """{phase: [(t, phase-ms per wall second)]} derived from the
    ``paged_tick_phase_ms{phase=...}`` histogram SUM deltas a profiled
    engine exports (ISSUE 20) — cumulative sums subtract like counter
    samples, so consecutive samples give the windowed phase-time
    rate."""
    by_phase: Dict[str, Dict[float, float]] = {}
    for full, ent in (doc.get("metrics") or {}).items():
        if full.split("{", 1)[0] != "paged_tick_phase_ms" \
                or ent.get("kind") != "histogram":
            continue
        phase = _label_value(full, "phase")
        if phase is None:
            continue
        merged = by_phase.setdefault(phase, {})
        samples = list(ent["samples"])
        for a, b in zip(samples, samples[1:]):
            dt = b[0] - a[0]
            if dt > 0:
                t = round(b[0], 6)
                merged[t] = merged.get(t, 0.0) \
                    + max(b[2] - a[2], 0.0) / dt
    return {p: sorted(m.items()) for p, m in sorted(by_phase.items())}


# one unambiguous letter per phase (first letters collide:
# host/h2d, dispatch/device/drain)
PHASE_LETTERS = {"host": "H", "h2d": "U", "dispatch": "D",
                 "device": "C", "drain": "R"}


def _phase_row(d: dict, t0: float, t1: float,
               width: int) -> Optional[str]:
    """The stacked phase-share row (ISSUE 20): per time bin, the
    DOMINANT phase's letter (H host, U h2d upload, D dispatch,
    C device compute, R drain readback) — uppercase when it holds a
    majority of the tick wall, lowercase for a mere plurality. One
    glance says "this replica went dispatch-bound at t=40s"."""
    shares = _phase_share_points(d)
    if not shares:
        return None
    binned = {p: resample(pts, t0, t1, width)
              for p, pts in shares.items()}
    out = []
    for i in range(width):
        tot = sum(v[i] for v in binned.values()
                  if v[i] is not None)
        if tot <= 0:
            out.append(" ")
            continue
        p, v = max(((p, v[i] or 0.0) for p, v in binned.items()),
                   key=lambda kv: kv[1])
        ch = PHASE_LETTERS.get(p, p[0].upper())
        out.append(ch if v / tot > 0.5 else ch.lower())
    return "".join(out)


def doc_time_range(docs: Dict[str, dict]) -> Tuple[float, float]:
    ts = [s[0]
          for d in docs.values()
          for ent in (d.get("metrics") or {}).values()
          for s in ent["samples"]]
    if not ts:
        return 0.0, 1.0
    return min(ts), max(ts)


def _flight_event(ev: dict, t: float) -> Optional[dict]:
    """One flight-recorder event → one timeline marker (or None for
    kinds the dashboard doesn't chart). Covers both the live
    recorder's ``fleet_autoscale`` and the simulator's injected
    ``incident_*`` / ``frontend_kill`` chaos events."""
    kind = ev.get("kind")
    if kind == "fleet_autoscale":
        return {"t": t, "kind": f"scale_{ev.get('action')}",
                "who": ev.get("fleet", "fleet"),
                "what": f"replicas_before="
                        f"{ev.get('replicas_before')}"}
    if kind in ("incident_start", "incident_end"):
        return {"t": t, "kind": kind,
                "who": ev.get("incident", "incident"),
                "what": "page expected"
                if ev.get("page_expected") else ""}
    if kind == "frontend_kill":
        return {"t": t, "kind": "frontend_kill",
                "who": ev.get("frontend", "frontend"),
                "what": "SIGKILL (leaderless failover)"}
    if kind == "profilez_capture":
        # an on-demand /profilez capture landed (ISSUE 20) — mark WHEN
        # the phase rings / jax trace were cut so the sparkline shape
        # around the marker is what the capture actually saw
        return {"t": t, "kind": "profilez_capture",
                "who": ev.get("gateway", "gateway"),
                "what": f"duration_s={ev.get('duration_s')} "
                        f"traced={ev.get('traced')}"}
    return None


def collect_events(docs: Dict[str, dict],
                   flights: List[dict]) -> List[dict]:
    """Alerts from the series docs + autoscaler actions / injected
    chaos from flight dumps, mapped onto the series' monotonic axis
    via each doc's ``dumped_wall``/``clock_now`` offset."""
    events = []
    for name, d in docs.items():
        off = None
        if isinstance(d.get("dumped_wall"), (int, float)) \
                and isinstance(d.get("clock_now"), (int, float)):
            off = d["dumped_wall"] - d["clock_now"]
        for a in d.get("alerts") or ():
            events.append({"t": a.get("t"), "kind":
                           f"alert_{a.get('kind')}",
                           "who": name,
                           "what": f"{a.get('slo')}/{a.get('rule')} "
                                   f"burn={a.get('burn_fast')}"})
        for fl in flights:
            for ev in fl.get("events", ()):
                if off is None:
                    continue
                mapped = _flight_event(ev,
                                       ev.get("wall", 0.0) - off)
                if mapped is not None:
                    events.append(mapped)
        flights = []   # flight events mapped once, via the first doc
    seen = set()
    out = []
    for ev in sorted(events, key=lambda e: e.get("t") or 0.0):
        key = (ev["kind"], ev["who"], round(ev.get("t") or 0.0, 3))
        if key not in seen:
            seen.add(key)
            out.append(ev)
    return out


def _doc_rows(d: dict) -> tuple:
    """Pick the three sparkline rows by what the doc actually holds:
    a gateway series doc carries ``gateway_*`` metrics, a fleet_sim
    (or frontend-level) doc carries the frontend's ``fleet_*``
    counters — same renderer either way."""
    bases = {full.split("{", 1)[0]
             for full in (d.get("metrics") or {})}
    if "gateway_tokens_total" not in bases \
            and "fleet_requests_total" in bases:
        return (
            ("req/s", _metric_points(d, "fleet_requests_total")),
            ("tok/s", _metric_points(d,
                                     "fleet_proxied_tokens_total")),
            ("burn", _metric_points(d, "slo_burn_rate", agg=max)),
        )
    rows = (
        ("tok/s", _metric_points(d, "gateway_tokens_total")),
        ("queue", _metric_points(d, "gateway_queue_depth")),
        ("burn", _metric_points(d, "slo_burn_rate", agg=max)),
    )
    if "kv_spill_hits_total" in bases:
        # spill-tier restores (ISSUE 17) — only gateways running with
        # an attached arena export the series, so the row is opt-in
        rows += (("spill", _metric_points(d, "kv_spill_hits_total")),)
    if "kv_xfer_hits_total" in bases:
        # cross-replica KV transfers landed (ISSUE 18) — exported only
        # by gateways that injected at least one migrated/peer span
        rows += (("xfer", _metric_points(d, "kv_xfer_hits_total")),)
    return rows


def render(docs: Dict[str, dict], events: Optional[List[dict]] = None,
           width: int = 60) -> str:
    """One fleet timeline: per replica, tok/s + queue depth + max burn
    sparklines over a shared time axis, then the event markers."""
    t0, t1 = doc_time_range(docs)
    lines = [f"fleet timeline  t=[0 .. {t1 - t0:.1f}s]  "
             f"({len(docs)} replica{'s' if len(docs) != 1 else ''}, "
             f"width {width} bins)"]
    axis = "".join("|" if i % 10 == 0 else "-"
                   for i in range(width))
    lines.append(f"{'':<12s} {axis}")
    for name in sorted(docs):
        d = docs[name]
        rows = _doc_rows(d)
        for label, pts in rows:
            vals = resample(pts, t0, t1, width)
            present = [v for v in vals if v is not None]
            peak = max(present) if present else 0.0
            lines.append(f"{name[:12]:<12s} {sparkline(vals)} "
                         f"{label} peak {peak:.1f}")
        ph = _phase_row(d, t0, t1, width)
        if ph is not None:
            lines.append(f"{name[:12]:<12s} {ph} "
                         f"phase (H host U h2d D dispatch C device "
                         f"R drain; UPPER = majority)")
        lines.append("")
    marks = list(events or ())
    if marks:
        row = [" "] * width
        for ev in marks:
            t = ev.get("t")
            if t is None:
                continue
            i = int((t - t0) / max(t1 - t0, 1e-9) * (width - 1))
            row[max(0, min(i, width - 1))] = \
                "!" if ev["kind"].startswith("alert_fire") else \
                "." if ev["kind"].startswith("alert") else \
                "#" if ev["kind"].startswith("incident") else \
                "x" if ev["kind"] == "frontend_kill" else \
                "P" if ev["kind"] == "profilez_capture" else "^"
        lines.append(f"{'events':<12s} {''.join(row)} "
                     f"(! fire  . resolve  ^ scale  # incident  "
                     f"x fe-kill  P profilez)")
        for ev in marks[-12:]:
            t = ev.get("t")
            lines.append(f"  t={t - t0:7.1f}s  {ev['kind']:<14s} "
                         f"{ev['who']}: {ev['what']}"
                         if t is not None else
                         f"  t=      ?   {ev['kind']} {ev['who']}")
    return "\n".join(lines)


# ------------------------------------------------------------------- live
def _fetch_metricsz(host: str, port: int,
                    window_s: float) -> Optional[dict]:
    import http.client
    conn = http.client.HTTPConnection(host, port, timeout=3.0)
    try:
        conn.request("GET", f"/metricsz?window_s={window_s:g}")
        resp = conn.getresponse()
        if resp.status != 200:
            return None
        return json.loads(resp.read())
    except (OSError, ValueError):
        return None
    finally:
        conn.close()


def _live_rows(doc: dict) -> Dict[str, Dict[str, float]]:
    """One poll → {replica: {tok_s, queue, burn, alerts}} for either a
    single gateway's /metricsz or a frontend's federated one."""
    rows: Dict[str, Dict[str, float]] = {}

    def fold(name: str, mdoc: dict):
        tok = q = burn = 0.0
        ph: Dict[str, float] = {}
        for full, view in (mdoc.get("metrics") or {}).items():
            base = full.split("{", 1)[0]
            if base == "gateway_tokens_total":
                tok += view.get("rate_per_s", 0.0)
            elif base == "gateway_queue_depth":
                q += view.get("last", 0.0)
            elif base == "paged_tick_phase_ms":
                # windowed phase-ms total = count * mean (ISSUE 20)
                p = _label_value(full, "phase")
                if p is not None:
                    ph[p] = ph.get(p, 0.0) + view.get("count", 0) \
                        * view.get("mean", 0.0)
        slo = mdoc.get("slo") or {}
        for by_w in (slo.get("burn") or {}).values():
            burn = max([burn] + list(by_w.values()))
        letter = " "
        tot = sum(ph.values())
        if tot > 0:
            p, v = max(ph.items(), key=lambda kv: kv[1])
            letter = PHASE_LETTERS.get(p, p[0].upper())
            if v / tot <= 0.5:
                letter = letter.lower()
        rows[name] = {"tok_s": tok, "queue": q, "burn": burn,
                      "phase": letter,
                      "alerts": len(slo.get("active") or ())}

    if "replicas" in doc and "totals" in doc:     # federated frontend
        for peer, mz in (doc.get("replicas") or {}).items():
            inner = mz.get("doc")
            if inner and inner.get("enabled"):
                fold(peer, inner)
        rows["(fleet)"] = {
            "tok_s": doc["totals"].get("tokens_per_sec", 0.0),
            "queue": doc["totals"].get("queue_depth", 0.0),
            "burn": max([0.0] + list(
                doc["totals"].get("burn_rate_max", {}).values())),
            "alerts": len(doc["totals"].get("alerts_active", ()))}
    elif doc.get("enabled"):
        fold(doc.get("gateway", "gw"), doc)
    return rows


def live(host: str, port: int, watch_s: float, window_s: float,
         interval_s: float, width: int) -> int:
    hist: Dict[str, Dict[str, list]] = {}
    t_end = time.monotonic() + watch_s
    first = True
    while True:
        now = time.monotonic()
        doc = _fetch_metricsz(host, port, window_s)
        if doc is None:
            print(f"poll failed: {host}:{port} unreachable or no "
                  f"sampler", file=sys.stderr)
        else:
            for name, row in _live_rows(doc).items():
                h = hist.setdefault(name, {"tok_s": [], "queue": [],
                                           "burn": [], "phase": [],
                                           "alerts": 0})
                for k in ("tok_s", "queue", "burn"):
                    h[k].append(row[k])
                    del h[k][:-width]
                h["phase"].append(row.get("phase", " "))
                del h["phase"][:-width]
                h["alerts"] = row["alerts"]
            if not first:
                sys.stdout.write("\x1b[2J\x1b[H")
            first = False
            print(f"{host}:{port}  window={window_s:g}s  "
                  f"poll={interval_s:g}s  "
                  f"{time.strftime('%H:%M:%S')}")
            for name in sorted(hist):
                h = hist[name]
                flag = f"  ALERTS:{h['alerts']}" if h["alerts"] else ""
                print(f"{name[:12]:<12s} tok/s "
                      f"{sparkline(h['tok_s']):<{width}s} "
                      f"{h['tok_s'][-1]:8.1f}{flag}")
                print(f"{'':<12s} queue "
                      f"{sparkline(h['queue']):<{width}s} "
                      f"{h['queue'][-1]:8.1f}")
                print(f"{'':<12s} burn  "
                      f"{sparkline(h['burn']):<{width}s} "
                      f"{h['burn'][-1]:8.2f}")
                if any(c != " " for c in h["phase"]):
                    print(f"{'':<12s} phase "
                          f"{''.join(h['phase']):<{width}s} "
                          f"(H host U h2d D disp C dev R drain)")
            sys.stdout.flush()
        if now >= t_end:
            return 0
        time.sleep(min(interval_s, max(t_end - now, 0.0)))


# ------------------------------------------------------------------- main
def load_docs(paths: List[str]) -> Tuple[Dict[str, dict],
                                         List[dict]]:
    files: List[str] = []
    flights: List[dict] = []
    for p in paths:
        if os.path.isdir(p):
            files += sorted(glob.glob(os.path.join(p,
                                                   "series_*.json")))
            # fleet_sim --dump-dir naming (same document schema)
            files += sorted(glob.glob(os.path.join(
                p, "sim_*_series.json")))
            for fp in sorted(
                    glob.glob(os.path.join(p, "flight_*.json"))
                    + glob.glob(os.path.join(p,
                                             "sim_*_flight.json"))):
                try:
                    with open(fp) as f:
                        flights.append(json.load(f))
                except (OSError, ValueError):
                    pass
        else:
            files.append(p)
    docs = {}
    for fp in files:
        try:
            with open(fp) as f:
                doc = json.load(f)
        except (OSError, ValueError) as e:
            print(f"skipping {fp}: {e}", file=sys.stderr)
            continue
        docs[doc.get("name") or os.path.basename(fp)] = doc
    return docs, flights


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("paths", nargs="*",
                    help="series_*.json files or run dirs")
    ap.add_argument("--url", default=None,
                    help="live mode: poll HOST:PORT/metricsz")
    ap.add_argument("--watch", type=float, default=10.0,
                    help="live mode duration, seconds")
    ap.add_argument("--window-s", type=float, default=5.0,
                    help="windowed-rate horizon per poll")
    ap.add_argument("--interval-s", type=float, default=0.5,
                    help="live poll cadence")
    ap.add_argument("--width", type=int, default=60,
                    help="timeline width, bins")
    ns = ap.parse_args(argv)
    if ns.url:
        h, _, p = ns.url.partition(":")
        return live(h, int(p), ns.watch, ns.window_s, ns.interval_s,
                    ns.width)
    if not ns.paths:
        ap.error("series files / run dir required (or --url)")
    docs, flights = load_docs(ns.paths)
    if not docs:
        print("no series_*.json documents found", file=sys.stderr)
        return 2
    from paddle_tpu.utils.observability import validate_series_doc
    for name, d in docs.items():
        problems = validate_series_doc(d)
        if problems:
            print(f"warning: {name}: {problems[:3]}", file=sys.stderr)
    print(render(docs, collect_events(docs, flights), width=ns.width))
    return 0


if __name__ == "__main__":
    sys.exit(main())
