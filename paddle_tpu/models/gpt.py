"""GPT-2/3 family (reference: PaddleNLP paddlenlp/transformers/gpt/
modeling.py — GPTModel/GPTForCausalLM/GPTLMHeadModel, MultiHeadAttention
with fused qkv, learned positional embeddings, pre-LN blocks).

TPU-native design:
- fused qkv projection as a single ColumnParallelLinear (one big MXU
  matmul, heads sharded over ``tp``), RowParallel output projection.
- learned positional embedding table (GPT convention) added at embed time;
  static-shape KV cache decode identical to the Llama path.
- pre-LN residual blocks, gelu MLP; activations batch-sharded
  over ("dp","fsdp") with sequence on "sp" via constraint hints.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp

from .. import nn
from ..nn import functional as F
from ..nn.layer import Layer, Parameter
from ..nn import initializer as I
from ..ops.attention import dense_attention, flash_attention, use_flash
from ..parallel.layers import (ColumnParallelLinear, RowParallelLinear,
                               VocabParallelEmbedding, parallel_matmul)
from ..parallel.sharding import constraint
from ..utils.rng import next_key
from .base import CausalLMBase


@dataclass
class GPTConfig:
    vocab_size: int = 50304
    hidden_size: int = 2048
    intermediate_size: int = 8192
    num_hidden_layers: int = 24
    num_attention_heads: int = 16
    max_position_embeddings: int = 1024
    layer_norm_epsilon: float = 1e-5
    initializer_range: float = 0.02
    tie_word_embeddings: bool = True
    recompute: bool = False
    use_flash_attention: bool = True
    dtype: Any = jnp.bfloat16

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_attention_heads


def gpt_tiny(**overrides) -> GPTConfig:
    base = dict(vocab_size=256, hidden_size=64, intermediate_size=128,
                num_hidden_layers=2, num_attention_heads=4,
                max_position_embeddings=128, dtype=jnp.float32)
    base.update(overrides)
    return GPTConfig(**base)


class GPTAttention(Layer):
    def __init__(self, config: GPTConfig):
        super().__init__()
        self.config = config
        h = config.hidden_size
        # fused qkv: one column-parallel matmul, split after
        self.qkv_proj = ColumnParallelLinear(h, 3 * h, has_bias=True,
                                             gather_output=False)
        self.out_proj = RowParallelLinear(h, h, has_bias=True,
                                          input_is_parallel=True)

    def forward(self, x, kv_cache: Optional[Tuple] = None, cache_index=None,
                attn_mask=None):
        cfg = self.config
        b, s, _ = x.shape
        nh, d = cfg.num_attention_heads, cfg.head_dim
        qkv = self.qkv_proj(x).reshape(b, s, 3, nh, d)
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        q = constraint(q, None, None, "tp", None)
        k = constraint(k, None, None, "tp", None)
        v = constraint(v, None, None, "tp", None)

        new_cache = None
        if kv_cache is not None:
            ck, cv = kv_cache
            ck = jax.lax.dynamic_update_slice(ck, k.astype(ck.dtype),
                                              (0, cache_index, 0, 0))
            cv = jax.lax.dynamic_update_slice(cv, v.astype(cv.dtype),
                                              (0, cache_index, 0, 0))
            new_cache = (ck, cv)
            total = ck.shape[1]
            kpos = jnp.arange(total)[None, :]
            qpos = cache_index + jnp.arange(s)[:, None]
            mask = (kpos <= qpos)[None, None]
            out = dense_attention(q, ck, cv, attn_mask=mask)
        elif cfg.use_flash_attention and attn_mask is None and use_flash(q, k, None, 0.0):
            out = flash_attention(q, k, v, causal=True)
        else:
            out = dense_attention(q, k, v, causal=attn_mask is None,
                                  attn_mask=attn_mask)
        out = self.out_proj(out.reshape(b, s, nh * d))
        return (out, new_cache) if kv_cache is not None else out


class GPTMLP(Layer):
    def __init__(self, config: GPTConfig):
        super().__init__()
        self.fc_in = ColumnParallelLinear(config.hidden_size,
                                          config.intermediate_size,
                                          has_bias=True, gather_output=False)
        self.fc_out = RowParallelLinear(config.intermediate_size,
                                        config.hidden_size, has_bias=True,
                                        input_is_parallel=True)

    def forward(self, x):
        return self.fc_out(F.gelu(self.fc_in(x), approximate=True))


class GPTDecoderLayer(Layer):
    def __init__(self, config: GPTConfig):
        super().__init__()
        eps = config.layer_norm_epsilon
        self.ln_1 = nn.LayerNorm(config.hidden_size, epsilon=eps)
        self.attn = GPTAttention(config)
        self.ln_2 = nn.LayerNorm(config.hidden_size, epsilon=eps)
        self.mlp = GPTMLP(config)

    def forward(self, x, kv_cache=None, cache_index=None, attn_mask=None):
        attn_out = self.attn(self.ln_1(x), kv_cache=kv_cache,
                             cache_index=cache_index, attn_mask=attn_mask)
        new_cache = None
        if kv_cache is not None:
            attn_out, new_cache = attn_out
        x = x + attn_out
        x = x + self.mlp(self.ln_2(x))
        x = constraint(x, ("dp", "fsdp"), "sp", None)
        return (x, new_cache) if kv_cache is not None else x


class GPTModel(Layer):
    def __init__(self, config: GPTConfig):
        super().__init__()
        self.config = config
        self.embed_tokens = VocabParallelEmbedding(config.vocab_size,
                                                   config.hidden_size)
        init = I.Normal(std=config.initializer_range)
        self.embed_positions = Parameter(
            init(next_key(), (config.max_position_embeddings,
                              config.hidden_size)))
        self.layers = nn.LayerList(
            [GPTDecoderLayer(config) for _ in range(config.num_hidden_layers)])
        self.ln_f = nn.LayerNorm(config.hidden_size,
                                 epsilon=config.layer_norm_epsilon)
        if config.dtype != jnp.float32:
            self.to(dtype=config.dtype)

    def forward(self, input_ids, positions=None, kv_caches=None,
                cache_index=None, attn_mask=None):
        b, s = input_ids.shape
        if positions is None:
            start = cache_index if cache_index is not None else 0
            positions = start + jnp.arange(s)[None, :].repeat(b, axis=0)
        x = self.embed_tokens(input_ids) + self.embed_positions[positions]
        x = constraint(x, ("dp", "fsdp"), "sp", None)
        new_caches = [] if kv_caches is not None else None
        for i, layer in enumerate(self.layers):
            cache_i = kv_caches[i] if kv_caches is not None else None
            if self.config.recompute and kv_caches is None:
                x = jax.checkpoint(
                    lambda h, lyr=layer: lyr(h, attn_mask=attn_mask),
                    prevent_cse=False)(x)
            elif kv_caches is not None:
                x, nc = layer(x, kv_cache=cache_i, cache_index=cache_index,
                              attn_mask=attn_mask)
                new_caches.append(nc)
            else:
                x = layer(x, attn_mask=attn_mask)
        x = self.ln_f(x)
        return (x, new_caches) if kv_caches is not None else x


class GPTForCausalLM(CausalLMBase):
    def __init__(self, config: GPTConfig):
        super().__init__()
        self.config = config
        self.model = GPTModel(config)
        if not config.tie_word_embeddings:
            self.lm_head = ColumnParallelLinear(config.hidden_size,
                                                config.vocab_size,
                                                has_bias=False,
                                                gather_output=True)
            if config.dtype != jnp.float32:
                self.lm_head.to(dtype=config.dtype)

    def forward(self, input_ids, positions=None, kv_caches=None,
                cache_index=None, attn_mask=None):
        out = self.model(input_ids, positions, kv_caches, cache_index,
                         attn_mask)
        caches = None
        if kv_caches is not None:
            out, caches = out
        if self.config.tie_word_embeddings:
            logits = parallel_matmul(out, self.model.embed_tokens.weight,
                                     transpose_y=True)
        else:
            logits = self.lm_head(out)
        logits = logits.astype(jnp.float32)
        return (logits, caches) if kv_caches is not None else logits
