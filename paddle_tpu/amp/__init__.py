"""Automatic mixed precision (reference: python/paddle/amp/*.py).

TPU-first AMP: bfloat16 has fp32's exponent range, so the default TPU
policy needs **no loss scaling** — `amp.auto_cast(dtype="bfloat16")` casts
layer compute to bf16 and keeps normalization/softmax/reductions in fp32
(our F.* norms already accumulate in fp32). GradScaler exists for fp16
parity and is an identity when scaling is unnecessary.

Levels (paddle parity):
- O1: per-op cast — matmul/conv inputs to low precision, fp32 elsewhere.
- O2: model weights in low precision + fp32 master weights in the optimizer
  (optimizer(multi_precision=True)).
"""
from __future__ import annotations

import contextlib
import threading

import jax
import jax.numpy as jnp

from ..dtypes import to_dtype

_amp_state = threading.local()


def _dtype():
    return getattr(_amp_state, "dtype", None)


@contextlib.contextmanager
def auto_cast(enable=True, dtype="bfloat16", level="O1", custom_white_list=None,
              custom_black_list=None):
    """Context that makes Linear/Conv/Attention cast inputs to `dtype`."""
    prev = _dtype()
    _amp_state.dtype = to_dtype(dtype) if enable else None
    _amp_state.level = level
    try:
        yield
    finally:
        _amp_state.dtype = prev


amp_guard = auto_cast


def amp_dtype():
    """Queried by compute layers; None when AMP is off."""
    return _dtype()


def maybe_cast(x):
    dt = _dtype()
    if dt is not None and hasattr(x, "dtype") and jnp.issubdtype(x.dtype, jnp.floating):
        return x.astype(dt)
    return x


def decorate(models=None, optimizers=None, level="O2", dtype="bfloat16",
             master_weight=None):
    """paddle.amp.decorate parity: cast model params to `dtype`; the
    optimizer keeps fp32 masters (multi_precision)."""
    dt = to_dtype(dtype)
    single = False
    if models is not None and not isinstance(models, (list, tuple)):
        models, single = [models], True
    for m in models or []:
        m.to(dtype=dt)
    if optimizers is not None:
        opts = optimizers if isinstance(optimizers, (list, tuple)) else [optimizers]
        for o in opts:
            o.multi_precision = True if master_weight is None else master_weight
    if models is None:
        return optimizers
    out_models = models[0] if single else models
    if optimizers is None:
        return out_models
    return out_models, optimizers


class GradScaler:
    """Loss scaling for fp16 (reference: python/paddle/amp/grad_scaler.py).
    With bf16 (TPU default) scaling is unnecessary; enable=False makes all
    methods identity passthroughs.

    Functional usage inside a jitted step:
        scaled = scaler.scale(loss)
        ... grads of scaled loss ...
        grads, found_inf = scaler.unscale(grads)
        new_scale_state = scaler.update_state(found_inf)
    """

    def __init__(self, enable=True, init_loss_scaling=2.0 ** 15,
                 incr_ratio=2.0, decr_ratio=0.5, incr_every_n_steps=2000,
                 decr_every_n_nan_or_inf=1, use_dynamic_loss_scaling=True):
        self._enable = enable
        self.incr_ratio = incr_ratio
        self.decr_ratio = decr_ratio
        self.incr_every_n_steps = incr_every_n_steps
        self.decr_every_n = decr_every_n_nan_or_inf
        self.dynamic = use_dynamic_loss_scaling
        self._scale = jnp.float32(init_loss_scaling if enable else 1.0)
        self._growth_tracker = jnp.int32(0)
        self._nan_tracker = jnp.int32(0)

    def is_enable(self):
        return self._enable

    def scale(self, loss):
        if not self._enable:
            return loss
        return loss * self._scale

    def unscale(self, grads):
        """Returns (unscaled_grads, found_inf[bool])."""
        if not self._enable:
            return grads, jnp.bool_(False)
        inv = 1.0 / self._scale
        unscaled = jax.tree.map(lambda g: g * inv, grads)
        found_inf = jnp.any(jnp.stack([
            jnp.any(~jnp.isfinite(g.astype(jnp.float32))) for g in jax.tree.leaves(unscaled)
        ]))
        return unscaled, found_inf

    def update(self, found_inf=None):
        """paddle update_loss_scaling semantics: a bad step zeroes the good
        counter; scale shrinks only after decr_every_n accumulated bad steps;
        a good step zeroes the bad counter."""
        if not (self._enable and self.dynamic) or found_inf is None:
            return
        if bool(found_inf):
            self._growth_tracker = jnp.int32(0)
            self._nan_tracker = self._nan_tracker + 1
            if int(self._nan_tracker) >= self.decr_every_n:
                self._scale = self._scale * self.decr_ratio
                self._nan_tracker = jnp.int32(0)
        else:
            self._nan_tracker = jnp.int32(0)
            self._growth_tracker = self._growth_tracker + 1
            if int(self._growth_tracker) >= self.incr_every_n_steps:
                self._scale = self._scale * self.incr_ratio
                self._growth_tracker = jnp.int32(0)

    # paddle flow: scaler.step(optimizer) + scaler.update()
    def step(self, optimizer, layer=None, grads=None):
        grads, found_inf = self.unscale(grads)
        if not bool(found_inf):
            optimizer.step(grads=grads, layer=layer)
        self.update(found_inf)

    def state_dict(self):
        return {"scale": self._scale, "growth_tracker": self._growth_tracker}

    def load_state_dict(self, sd):
        self._scale = jnp.float32(sd["scale"])
        self._growth_tracker = jnp.int32(sd["growth_tracker"])
