"""Unified observability layer (ISSUE 5): metrics registry, span
tracing, flight recorder, the trainer/serving wiring, and the
obs_report tool."""
import json
import os
import sys
import threading
import time

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.utils import observability as obs
from paddle_tpu.utils.observability import (FlightRecorder,
                                            MetricsRegistry, SpanTracer)

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ================================================================ registry
class TestMetricsRegistry:
    def test_counter_gauge_histogram(self):
        reg = MetricsRegistry()
        c = reg.counter("req_total", engine="e0")
        c.inc()
        c.inc(2)
        assert reg.counter("req_total", engine="e0") is c  # get-or-create
        assert c.value == 3
        with pytest.raises(ValueError):
            c.inc(-1)                       # counters only go up
        g = reg.gauge("depth")
        g.set(4)
        g.dec()
        assert g.value == 3
        h = reg.histogram("lat_ms")
        for v in (1, 2, 3, 4, 100):
            h.observe(v)
        s = h.stats()
        assert s["count"] == 5 and s["sum"] == 110
        assert s["min"] == 1 and s["max"] == 100
        assert s["p50"] <= s["p99"] <= 100

    def test_kind_collision_raises(self):
        reg = MetricsRegistry()
        reg.counter("x")
        with pytest.raises(ValueError):
            reg.gauge("x")

    def test_snapshot_and_prometheus_text(self):
        reg = MetricsRegistry()
        reg.counter("served_total", engine="a").inc(7)
        reg.gauge("queue_depth").set(2)
        reg.histogram("wait_ms").observe(3.0)
        snap = reg.snapshot()
        assert snap['served_total{engine="a"}'] == 7
        assert snap["queue_depth"] == 2
        assert snap["wait_ms"]["count"] == 1
        text = reg.prometheus_text()
        assert "# TYPE served_total counter" in text
        assert 'served_total{engine="a"} 7' in text
        assert "# TYPE wait_ms histogram" in text
        assert 'wait_ms_bucket{le="+Inf"} 1' in text
        assert "wait_ms_count 1" in text

    def test_thread_safety(self):
        reg = MetricsRegistry()
        c = reg.counter("n")
        h = reg.histogram("h")

        def work():
            for _ in range(1000):
                c.inc()
                h.observe(1.0)

        ts = [threading.Thread(target=work) for _ in range(4)]
        [t.start() for t in ts]
        [t.join() for t in ts]
        assert c.value == 4000
        assert h.stats()["count"] == 4000

    def test_publish_merges_into_logwriter(self, tmp_path):
        from paddle_tpu.utils.logging import LogWriter
        reg = MetricsRegistry()
        reg.counter("steps_total").inc(5)
        reg.histogram("step_ms").observe(8.0)
        with LogWriter(str(tmp_path)) as w:
            reg.publish(w, step=5)
        tags = {json.loads(l)["tag"]
                for l in open(w.path).read().splitlines()}
        assert "steps_total" in tags
        assert "step_ms:p50" in tags and "step_ms:p99" in tags


# ================================================================== spans
class TestSpanTracer:
    def test_spans_are_chrome_trace_shaped(self, tmp_path):
        tr = SpanTracer()
        with tr.span("train_step", step=7):
            time.sleep(0.002)
        tr.instant("fault_fire", site="preempt")
        path = tr.flush(str(tmp_path / "trace.json"))
        doc = json.load(open(path))          # Perfetto-loadable JSON
        assert "traceEvents" in doc and "run_id" in doc["otherData"]
        ev = next(e for e in doc["traceEvents"]
                  if e["name"] == "train_step")
        assert ev["ph"] == "X" and ev["dur"] >= 2000  # us
        assert ev["args"]["step"] == 7
        mark = next(e for e in doc["traceEvents"]
                    if e["name"] == "fault_fire")
        assert mark["ph"] == "i"

    def test_span_ring_keeps_recent_window(self, tmp_path):
        tr = SpanTracer(max_events=3)
        for i in range(5):
            with tr.span("s", i=i):
                pass
        evs = tr.snapshot()
        assert len(evs) == 3 and tr.dropped == 2
        # ring semantics: a crash-time flush needs the RECENT window
        assert [e["args"]["i"] for e in evs] == [2, 3, 4]

    def test_run_and_attempt_ids(self, monkeypatch):
        monkeypatch.delenv(obs.ENV_RUN_ID, raising=False)
        rid = obs.run_id()
        assert rid and os.environ[obs.ENV_RUN_ID] == rid
        assert obs.run_id() == rid           # stable once minted
        monkeypatch.setenv(obs.ENV_ATTEMPT, "3")
        assert obs.attempt_id() == 3
        monkeypatch.setenv(obs.ENV_ATTEMPT, "junk")
        assert obs.attempt_id() == 0


# ======================================================== flight recorder
class TestFlightRecorder:
    def test_ring_bounded_and_dump_schema(self, tmp_path):
        fr = FlightRecorder(capacity=4)
        for i in range(10):
            fr.record("step_end", step=i, ms=1.0)
        evs = fr.snapshot()
        assert len(evs) == 4                     # ring dropped the old
        assert [e["step"] for e in evs] == [6, 7, 8, 9]
        path = fr.dump(str(tmp_path / "flight.json"), reason="crash")
        doc = json.load(open(path))
        assert doc["reason"] == "crash" and doc["total_events"] == 10
        assert doc["events"][-1]["kind"] == "step_end"
        assert "run_id" in doc and "attempt" in doc

    def test_values_coerced_jsonable(self, tmp_path):
        fr = FlightRecorder()
        fr.record("x", arr=np.float32(1.5), obj=object(), ok="s")
        json.dumps(fr.snapshot())                # must not raise


# ============================================================= satellites
class TestSatellites:
    def test_get_logger_per_logdir(self, tmp_path):
        """REGRESSION: the old singleton ignored logdir after the first
        call, silently writing every stream into one directory."""
        from paddle_tpu.utils.logging import get_logger
        a = get_logger(str(tmp_path / "a"))
        b = get_logger(str(tmp_path / "b"))
        assert a is not b
        assert a is get_logger(str(tmp_path / "a"))   # cached per dir
        a.add_scalar("x", 1.0, 0)
        b.add_scalar("y", 2.0, 0)
        assert "x" in open(a.path).read()
        assert "y" in open(b.path).read()
        assert a.path != b.path

    def test_profiler_start_idempotent(self, monkeypatch, capsys):
        from paddle_tpu.utils import profiler as prof
        calls = []
        monkeypatch.setattr(prof.jax.profiler, "start_trace",
                            lambda d: calls.append(("start", d)))
        monkeypatch.setattr(prof.jax.profiler, "stop_trace",
                            lambda: calls.append(("stop", None)))
        p = prof.Profiler(logdir="x")
        p.start()
        p.start()                       # second start: warn, don't crash
        assert len([c for c in calls if c[0] == "start"]) == 1
        assert "already-active" in capsys.readouterr().err
        q = prof.Profiler(logdir="y")
        q.start()                       # other trace still open: degrade
        assert len([c for c in calls if c[0] == "start"]) == 1
        assert "already running" in capsys.readouterr().err
        q.stop()                        # q never owned the trace
        assert not [c for c in calls if c[0] == "stop"]
        p.stop()
        assert [c for c in calls if c[0] == "stop"]

    def test_steptimer_stop_without_start_raises(self):
        from paddle_tpu.utils.profiler import StepTimer
        t = StepTimer(flops_per_token=1.0, peak_flops=1.0)
        with pytest.raises(RuntimeError, match="no open window"):
            t.stop(tokens=1)
        t.start()
        t.stop(tokens=1)                # normal path unaffected
        with pytest.raises(RuntimeError):
            t.stop(tokens=1)            # window already closed


# ==================================================== serving == registry
def _mlp():
    from paddle_tpu import nn
    pt.seed(0)
    return nn.Sequential(nn.Linear(16, 32), nn.GELU(), nn.Linear(32, 4))


class TestServingRegistryMigration:
    def test_batching_health_matches_registry_concurrent(self):
        """ACCEPTANCE + satellite: counter semantics identical to the
        pre-migration dicts under concurrent submit/cancel, and
        health() reads the same objects a registry snapshot exports."""
        from paddle_tpu.inference import BackpressureError, \
            BatchingPredictor
        bp = BatchingPredictor(_mlp(), max_batch=2, max_delay_ms=1,
                               max_queue=4)
        orig = bp.predictor.run

        def slow(*a):
            time.sleep(0.05)
            return orig(*a)
        bp.predictor.run = slow
        x = np.zeros((16,), np.float32)
        futs, rejected, attempts = [], 0, 24
        lock = threading.Lock()

        def submit_some():
            nonlocal rejected
            for _ in range(attempts // 4):
                try:
                    f = bp.submit(x)
                    with lock:
                        futs.append(f)
                except BackpressureError:
                    with lock:
                        rejected += 1
                time.sleep(0.001)

        ts = [threading.Thread(target=submit_some) for _ in range(4)]
        [t.start() for t in ts]
        [t.join() for t in ts]
        cancelled = sum(f.cancel() for f in futs[-3:])  # race the worker
        bp.close()                                      # drain the rest
        h = bp.health()
        # conservation: every submitted request resolved exactly once
        assert h["submitted"] == len(futs)
        assert h["submitted"] + h["rejected"] == attempts
        assert h["rejected"] == rejected >= 1
        assert h["cancelled"] == cancelled
        assert h["served"] + h["cancelled"] + h["timeouts"] \
            + h["errors"] == h["submitted"]
        assert h["queued"] == 0
        # health() IS the registry: same numbers under the engine label
        snap = obs.registry().snapshot()
        eng = bp._obs_labels["engine"]
        for key in BatchingPredictor._STAT_KEYS:
            assert snap[f'serving_{key}_total{{engine="{eng}"}}'] \
                == h[key], key
        assert snap[f'serving_queue_wait_ms{{engine="{eng}"}}'][
            "count"] >= h["served"]

    def test_paged_health_matches_registry(self):
        from paddle_tpu.models import LlamaForCausalLM, llama_tiny
        from paddle_tpu.generation.paged import PagedEngine
        pt.seed(0)
        eng = PagedEngine(LlamaForCausalLM(llama_tiny()), max_slots=2,
                          num_blocks=16, block_size=8,
                          max_blocks_per_seq=4, prefill_buckets=(16,),
                          max_queue=2)
        ids = np.arange(1, 5)[None]
        eng.submit("a", ids, max_new_tokens=2)
        eng.submit("b", ids, max_new_tokens=2)
        with pytest.raises(Exception):      # BackpressureError
            eng.submit("c", ids, max_new_tokens=2)
        out = eng.run()
        assert set(out) == {"a", "b"}
        eng.submit("gone", ids, max_new_tokens=2)
        assert eng.cancel("gone")
        # pre-migration dict semantics survive the registry move
        assert eng.stats["prefills"] == 2
        assert eng.stats["rejected"] == 1
        assert eng.stats["cancellations"] == 1
        assert eng.stats["decode_steps"] >= 1
        h = eng.health()
        snap = obs.registry().snapshot()
        label = eng._obs_labels["engine"]
        for key, v in eng.stats.items():
            assert snap[f'paged_{key}_total{{engine="{label}"}}'] == v
            assert h[key] == v
        assert snap[f'paged_decode_step_ms{{engine="{label}"}}'][
            "count"] == eng.stats["decode_steps"]


# ================================================= trainer e2e artifacts
class TestTrainerArtifacts:
    def test_preempt_run_produces_artifacts(self, tmp_path):
        """ACCEPTANCE: one toy run under an injected preempt yields,
        from a single run dir: a Prometheus snapshot, a
        Perfetto-loadable trace with step-numbered train_step spans,
        and a flight record whose tail holds the fault fire and the
        checkpoint-on-shutdown; obs_report renders p50/p99 + timeline
        from it."""
        import jax.numpy as jnp
        from paddle_tpu.models import LlamaForCausalLM, llama_tiny
        from paddle_tpu.trainer import Trainer, TrainingArguments
        from paddle_tpu.utils import faults
        sys.path.insert(0, os.path.join(ROOT, "tools"))
        import obs_report

        # fresh global registry/recorder: the ring and counters are
        # process-wide and earlier tests in this process have trained
        # and fired faults — the assertions below pin EXACT values
        obs.reset()
        rng = np.random.RandomState(0)
        batches = [jnp.asarray(rng.randint(0, 256, (4, 16)))
                   for _ in range(8)]
        args = TrainingArguments(output_dir=str(tmp_path), max_steps=20,
                                 logging_steps=2, save_steps=4,
                                 resume_from_checkpoint=False,
                                 prefetch_depth=0)
        tr = Trainer(LlamaForCausalLM(llama_tiny()),
                     pt.optimizer.AdamW(learning_rate=1e-4), args,
                     train_dataloader=batches)
        with faults.scoped("preempt@6"):
            with pytest.raises(SystemExit) as exc:
                tr.train()
        assert exc.value.code == args.preempt_exit_code
        run = os.path.join(str(tmp_path), "runs")

        # prometheus snapshot
        prom = open(os.path.join(run, "metrics.prom")).read()
        assert "train_steps_total" in prom
        assert "train_step_wall_ms_bucket" in prom
        assert 'fault_fires_total{site="preempt"} 1' in prom

        # perfetto trace: train_step spans carry step numbers
        trace = json.load(open(os.path.join(run, "trace_0.json")))
        steps = [e["args"]["step"] for e in trace["traceEvents"]
                 if e["name"] == "train_step"]
        assert steps and steps == sorted(steps)
        assert any(e["name"] == "checkpoint_save"
                   for e in trace["traceEvents"])

        # flight record: the tail shows fault fire -> latch -> exit ->
        # checkpoint-on-shutdown
        flight = json.load(open(os.path.join(run, "flight_0.json")))
        assert flight["reason"] == "preempt"
        kinds = [e["kind"] for e in flight["events"]]
        for kind in ("fault_fire", "preempt_latch", "preempt_exit",
                     "ckpt_save", "step_end"):
            assert kind in kinds, kind
        assert kinds.index("fault_fire") < kinds.index("preempt_exit")
        tail = kinds[kinds.index("preempt_exit"):]
        assert "ckpt_save" in tail     # the shutdown checkpoint

        # obs_report renders it
        s = obs_report.summarize(run)
        assert s["steps_recorded"] == 6
        assert s["step_ms"]["p99"] >= s["step_ms"]["p50"] > 0
        assert s["train"]["loss"] is not None
        assert s["counters"]["fault_fires"] >= 1
        timeline_kinds = {e["kind"] for e in s["timeline"]}
        assert {"fault_fire", "preempt_exit"} <= timeline_kinds
        text = obs_report.render(s)
        assert "p50" in text and "fault_fire" in text

    def test_crash_dumps_flight(self, tmp_path):
        """An exception escaping the train loop writes the postmortem
        window before propagating."""
        from paddle_tpu.trainer import Trainer, TrainingArguments
        from paddle_tpu import nn

        class Boom:
            """Raises INSIDE the loop (iter() itself succeeding), so
            the crash unwinds out of _train_loop."""

            def __iter__(self):
                return self

            def __next__(self):
                raise RuntimeError("feed exploded")

        pt.seed(0)
        model = nn.Linear(4, 4)
        args = TrainingArguments(output_dir=str(tmp_path), max_steps=3,
                                 resume_from_checkpoint=False,
                                 prefetch_depth=0, graceful_shutdown=False)
        tr = Trainer(model, pt.optimizer.SGD(learning_rate=0.1), args,
                     train_dataloader=Boom())
        with pytest.raises(RuntimeError, match="feed exploded"):
            tr.train()
        flight = json.load(open(
            os.path.join(str(tmp_path), "runs", "flight_0.json")))
        assert flight["reason"] == "crash:RuntimeError"
        assert any(e["kind"] == "crash" for e in flight["events"])


# =================================================================== elastic
def test_supervise_propagates_run_and_attempt_ids(tmp_path):
    """Children see $PADDLE_TPU_RUN_ID (stable) and $PADDLE_TPU_ATTEMPT
    (incremented per launch, preemption relaunches included) — the env
    contract that lets an elastic run's trace/flight files stitch."""
    from paddle_tpu.distributed.elastic import supervise
    from paddle_tpu.utils.shutdown import PREEMPTED_RC
    out = tmp_path / "attempts.txt"
    script = (
        "import os, sys\n"
        f"open({str(out)!r}, 'a').write(\n"
        "    os.environ['PADDLE_TPU_ATTEMPT'] + ' ' +\n"
        "    os.environ['PADDLE_TPU_RUN_ID'] + '\\n')\n"
        # first launch simulates a preemption; the relaunch succeeds
        f"sys.exit({PREEMPTED_RC} "
        "if os.environ['PADDLE_TPU_ATTEMPT'] == '0' else 0)\n")
    rc = supervise([sys.executable, "-c", script], max_restarts=0,
                   backoff_s=0.01)
    assert rc == 0
    lines = [l.split() for l in out.read_text().splitlines()]
    assert [l[0] for l in lines] == ["0", "1"]       # attempt ids
    assert lines[0][1] == lines[1][1]                # run id stable


def test_supervise_flushes_supervisor_telemetry(tmp_path):
    """REGRESSION: the supervisor's own registry/recorder — the only
    place the cross-attempt child launch/exit/rc story lives — must
    reach disk (flight_supervisor.json + metrics_supervisor.prom in the
    shared run dir), not die write-only with the process."""
    from paddle_tpu.distributed.elastic import supervise
    from paddle_tpu.utils.shutdown import PREEMPTED_RC
    sys.path.insert(0, os.path.join(ROOT, "tools"))
    import obs_report
    run = tmp_path / "runs"
    script = (
        "import os, sys\n"
        f"sys.exit({PREEMPTED_RC} "
        "if os.environ['PADDLE_TPU_ATTEMPT'] == '0' else 0)\n")
    rc = supervise([sys.executable, "-c", script], max_restarts=0,
                   backoff_s=0.01, run_dir=str(run))
    assert rc == 0
    flight = json.load(open(run / "flight_supervisor.json"))
    assert flight["reason"] == "supervise_exit"
    kinds = [e["kind"] for e in flight["events"]]
    assert kinds.count("elastic_child_launch") == 2
    exits = [e for e in flight["events"]
             if e["kind"] == "elastic_child_exit"]
    assert [e["rc"] for e in exits] == [PREEMPTED_RC, 0]
    prom = open(run / "metrics_supervisor.prom").read()
    assert "elastic_preemptions_total 1" in prom
    # and obs_report surfaces the supervisor's view
    s = obs_report.summarize(str(run))
    assert s["counters"]["elastic_preemptions"] == 1
    assert any(e["kind"] == "elastic_child_exit" for e in s["timeline"])
    # per-call isolation: a second supervise() in this process starts
    # from zero — no phantom counters/events from the first job
    run2 = tmp_path / "runs2"
    rc = supervise([sys.executable, "-c", "import sys; sys.exit(0)"],
                   max_restarts=0, backoff_s=0.01, run_dir=str(run2))
    assert rc == 0
    f2 = json.load(open(run2 / "flight_supervisor.json"))
    assert [e["kind"] for e in f2["events"]] == [
        "elastic_child_launch", "elastic_child_exit"]
    assert "elastic_preemptions_total 0" in \
        open(run2 / "metrics_supervisor.prom").read()


# ==================================================================== tool
def test_obs_report_check_mode():
    """CI self-test: schema drift between writer and reader fails."""
    sys.path.insert(0, os.path.join(ROOT, "tools"))
    import obs_report
    assert obs_report.self_check() == 0
