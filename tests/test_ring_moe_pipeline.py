"""SURVEY.md §4 parallel correctness: ring attention == full attention,
ulysses == full attention, MoE dispatch conservation, pipeline == sequential."""
import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

import paddle_tpu as pt
from paddle_tpu.distributed import env
from paddle_tpu.ops.attention import dense_attention
from paddle_tpu.parallel import (MoEMLP, pipeline_apply, ring_attention,
                                 stack_stage_params, top_k_routing,
                                 ulysses_attention)
from paddle_tpu.utils.jax_compat import shard_map


@pytest.fixture
def sp_mesh():
    mesh = env.init_parallel_env({"sp": 4, "dp": 2})
    yield mesh
    env.init_parallel_env({})


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_matches_dense(sp_mesh, causal):
    b, s, h, d = 2, 64, 4, 16
    kvh = 2  # GQA
    q = jnp.asarray(np.random.randn(b, s, h, d), jnp.float32)
    k = jnp.asarray(np.random.randn(b, s, kvh, d), jnp.float32)
    v = jnp.asarray(np.random.randn(b, s, kvh, d), jnp.float32)
    ref = dense_attention(q, k, v, causal=causal)

    ring = shard_map(
        functools.partial(ring_attention, axis_name="sp", causal=causal),
        mesh=sp_mesh, in_specs=(P(None, "sp"),) * 3, out_specs=P(None, "sp"),
        check_vma=False)
    out = jax.jit(ring)(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)


def test_ring_attention_grads_match(sp_mesh):
    b, s, h, d = 1, 32, 2, 8
    q = jnp.asarray(np.random.randn(b, s, h, d), jnp.float32)
    k = jnp.asarray(np.random.randn(b, s, h, d), jnp.float32)
    v = jnp.asarray(np.random.randn(b, s, h, d), jnp.float32)

    ring = shard_map(
        functools.partial(ring_attention, axis_name="sp", causal=True),
        mesh=sp_mesh, in_specs=(P(None, "sp"),) * 3, out_specs=P(None, "sp"),
        check_vma=False)
    g_ring = jax.jit(jax.grad(lambda q, k, v: ring(q, k, v).sum(),
                              argnums=(0, 1, 2)))(q, k, v)
    g_ref = jax.grad(lambda q, k, v: dense_attention(q, k, v, causal=True).sum(),
                     argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(g_ring, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   rtol=1e-3, atol=1e-4)


@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_matches_dense(sp_mesh, causal):
    b, s, h, d = 2, 64, 8, 16
    q = jnp.asarray(np.random.randn(b, s, h, d), jnp.float32)
    k = jnp.asarray(np.random.randn(b, s, h, d), jnp.float32)
    v = jnp.asarray(np.random.randn(b, s, h, d), jnp.float32)
    ref = dense_attention(q, k, v, causal=causal)
    uly = shard_map(
        functools.partial(ulysses_attention, axis_name="sp", causal=causal),
        mesh=sp_mesh, in_specs=(P(None, "sp"),) * 3, out_specs=P(None, "sp"),
        check_vma=False)
    out = jax.jit(uly)(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)


def test_topk_routing_conservation():
    T, E, k = 64, 8, 2
    logits = jnp.asarray(np.random.randn(T, E), jnp.float32)
    C = 32  # ample capacity: nothing dropped
    dispatch, combine, aux = top_k_routing(logits, k, C)
    # each token dispatched exactly k times
    np.testing.assert_allclose(np.asarray(dispatch.sum(axis=(1, 2))), k)
    # no slot double-booked
    assert float(dispatch.sum(axis=0).max()) <= 1.0 + 1e-6
    # combine weights = the top-k softmax probs
    probs = jax.nn.softmax(logits, axis=-1)
    topk = jnp.sort(probs, axis=-1)[:, -k:].sum(-1)
    np.testing.assert_allclose(np.asarray(combine.sum(axis=(1, 2))),
                               np.asarray(topk), rtol=1e-5)
    assert np.isfinite(float(aux))


def test_moe_mlp_forward_and_ep_sharding():
    env.init_parallel_env({"ep": 4, "dp": 2})
    try:
        pt.seed(0)
        moe = MoEMLP(hidden_size=32, intermediate_size=64, num_experts=8,
                     top_k=2, num_shared_experts=1)
        from paddle_tpu.parallel.sharding import shard_layer
        sh = shard_layer(moe)
        assert "ep" in str(sh["w_gate"].spec)
        x = jnp.asarray(np.random.randn(4, 16, 32), jnp.float32)
        fn, params = moe.functional()
        y, aux = jax.jit(lambda p, x: fn(p, x, return_aux=True))(params, x)
        assert y.shape == x.shape
        assert np.isfinite(np.asarray(y)).all()
        assert float(aux) > 0
        # gradients flow to expert weights
        g = jax.grad(lambda p: fn(p, x).sum())(params)
        assert float(jnp.abs(g["w_down"]).sum()) > 0
    finally:
        env.init_parallel_env({})


def test_moe_matches_dense_single_expert():
    """E=1, k=1, ample capacity: MoE == its one expert's SwiGLU."""
    pt.seed(1)
    moe = MoEMLP(hidden_size=16, intermediate_size=32, num_experts=1,
                 top_k=1, capacity_factor=2.0)
    x = jnp.asarray(np.random.randn(2, 8, 16), jnp.float32)
    y = moe(x)
    import paddle_tpu.nn.functional as F
    w_g, w_u, w_d = moe.w_gate[0], moe.w_up[0], moe.w_down[0]
    ref = (F.silu(x @ w_g) * (x @ w_u)) @ w_d  # gate prob == 1 when E==1
    np.testing.assert_allclose(np.asarray(y), np.asarray(ref), rtol=1e-4,
                               atol=1e-5)


def test_pipeline_matches_sequential():
    mesh = env.init_parallel_env({"pp": 4, "dp": 2})
    try:
        pt.seed(0)
        dim, n_micro, mb = 16, 8, 4
        stages = [{"w": jnp.asarray(np.random.randn(dim, dim) * 0.3, jnp.float32),
                   "b": jnp.zeros((dim,))} for _ in range(4)]

        def stage_fn(params, x):
            return jnp.tanh(x @ params["w"] + params["b"])

        stacked = stack_stage_params(stages)
        microbatches = jnp.asarray(np.random.randn(n_micro, mb, dim), jnp.float32)

        out = jax.jit(lambda sp, m: pipeline_apply(stage_fn, sp, m))(
            stacked, microbatches)

        ref = microbatches
        for p in stages:
            ref = jax.vmap(lambda x, p=p: stage_fn(p, x))(ref)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-4, atol=1e-5)
    finally:
        env.init_parallel_env({})


def test_pipeline_differentiable():
    mesh = env.init_parallel_env({"pp": 4, "dp": 2})
    try:
        dim = 8
        stages = [{"w": jnp.asarray(np.random.randn(dim, dim) * 0.3, jnp.float32)}
                  for _ in range(4)]

        def stage_fn(params, x):
            return jnp.tanh(x @ params["w"])

        stacked = stack_stage_params(stages)
        mbs = jnp.asarray(np.random.randn(4, 2, dim), jnp.float32)

        def loss_pp(sp):
            return jnp.sum(pipeline_apply(stage_fn, sp, mbs) ** 2)

        def loss_seq(stages_list):
            x = mbs
            for p in stages_list:
                x = jax.vmap(lambda xx, p=p: stage_fn(p, xx))(x)
            return jnp.sum(x ** 2)

        g_pp = jax.jit(jax.grad(loss_pp))(stacked)
        g_seq = jax.grad(loss_seq)(stages)
        for i in range(4):
            np.testing.assert_allclose(np.asarray(g_pp["w"][i]),
                                       np.asarray(g_seq[i]["w"]),
                                       rtol=1e-3, atol=1e-4)
    finally:
        env.init_parallel_env({})


class TestRingFlash:
    def test_matches_full_attention(self, monkeypatch):
        """ring_flash == single-device full attention (8-way sp mesh,
        pallas kernels in interpret mode on CPU)."""
        monkeypatch.setenv("PADDLE_TPU_PALLAS_INTERPRET", "1")
        from jax.sharding import Mesh, PartitionSpec as P
        from paddle_tpu.utils.jax_compat import shard_map
        from paddle_tpu.parallel.ring import ring_flash_attention
        from paddle_tpu.ops.attention import dense_attention

        # interpret-mode pallas is slow: 4 shards x 128 is the smallest
        # shape that still tiles the kernel and rotates a real ring
        n = 4
        B, S, H, D = 1, 4 * 128, 1, 32
        q = jax.random.normal(jax.random.PRNGKey(0), (B, S, H, D))
        k = jax.random.normal(jax.random.PRNGKey(1), (B, S, H, D))
        v = jax.random.normal(jax.random.PRNGKey(2), (B, S, H, D))
        mesh = Mesh(np.array(jax.devices()[:n]), ("sp",))
        for causal in (False, True):
            ring = shard_map(
                lambda q, k, v: ring_flash_attention(q, k, v, "sp",
                                                     causal=causal),
                mesh=mesh,
                in_specs=(P(None, "sp"), P(None, "sp"), P(None, "sp")),
                out_specs=P(None, "sp"), check_vma=False)
            out = ring(q, k, v)
            ref = dense_attention(q, k, v, causal=causal)
            np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                       atol=2e-5, rtol=1e-4)

    def test_gradients_flow(self, monkeypatch):
        monkeypatch.setenv("PADDLE_TPU_PALLAS_INTERPRET", "1")
        from jax.sharding import Mesh, PartitionSpec as P
        from paddle_tpu.utils.jax_compat import shard_map
        from paddle_tpu.parallel.ring import ring_flash_attention
        from paddle_tpu.ops.attention import dense_attention

        n = 2
        B, S, H, D = 1, 2 * 128, 1, 32
        q = jax.random.normal(jax.random.PRNGKey(3), (B, S, H, D))
        k = jax.random.normal(jax.random.PRNGKey(4), (B, S, H, D))
        v = jax.random.normal(jax.random.PRNGKey(5), (B, S, H, D))
        mesh = Mesh(np.array(jax.devices()[:n]), ("sp",))
        ring = shard_map(
            lambda q, k, v: ring_flash_attention(q, k, v, "sp", causal=True),
            mesh=mesh,
            in_specs=(P(None, "sp"), P(None, "sp"), P(None, "sp")),
            out_specs=P(None, "sp"), check_vma=False)
        g1 = jax.grad(lambda q, k, v: jnp.sum(ring(q, k, v) ** 2),
                      argnums=(0, 1, 2))(q, k, v)
        g2 = jax.grad(
            lambda q, k, v: jnp.sum(
                dense_attention(q, k, v, causal=True) ** 2),
            argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g1, g2):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=5e-4, rtol=1e-3)


def test_norm_topk_prob_routing():
    """norm_topk_prob renormalizes the selected gates to sum to 1 per
    token (Qwen2-57B-A14B semantics); combine weights prove it."""
    import numpy as np
    from paddle_tpu.parallel.moe import top_k_routing

    rs = np.random.RandomState(0)
    logits = jnp.asarray(rs.randn(16, 8), jnp.float32)
    _, combine_raw, _ = top_k_routing(logits, 2, capacity=16)
    _, combine_norm, _ = top_k_routing(logits, 2, capacity=16,
                                       norm_topk_prob=True)
    raw_sums = np.asarray(combine_raw.sum(axis=(1, 2)))
    norm_sums = np.asarray(combine_norm.sum(axis=(1, 2)))
    assert (raw_sums < 0.999).any()       # raw softmax mass < 1 over top-k
    np.testing.assert_allclose(norm_sums, 1.0, atol=1e-5)


def _mk_segments(rng, b, s, n_seg=3):
    """Random packed-sequence ids: contiguous runs 1..n_seg then 0-pad."""
    import numpy as _np
    out = _np.zeros((b, s), _np.int32)
    for r in range(b):
        cuts = sorted(rng.choice(_np.arange(4, s - 4), n_seg - 1,
                                 replace=False))
        bounds = [0] + list(cuts) + [s - 4]  # last 4 positions = pad (0)
        for i in range(n_seg):
            out[r, bounds[i]:bounds[i + 1]] = i + 1
    return out


def test_ring_attention_segments_match_dense(sp_mesh):
    """Packed SFT under context parallelism (VERDICT r3 weak #4): the
    segment ids rotate with the KV blocks; result must equal dense
    block-causal attention over the full sequence."""
    from paddle_tpu.ops.attention import segment_mask
    b, s, h, d = 2, 64, 4, 16
    rng = np.random.RandomState(0)
    q = jnp.asarray(rng.randn(b, s, h, d), jnp.float32)
    k = jnp.asarray(rng.randn(b, s, 2, d), jnp.float32)  # GQA
    v = jnp.asarray(rng.randn(b, s, 2, d), jnp.float32)
    seg = jnp.asarray(_mk_segments(rng, b, s))
    ref = dense_attention(q, k, v, causal=True, attn_mask=segment_mask(seg))

    ring = shard_map(
        lambda q, k, v, sg: ring_attention(q, k, v, axis_name="sp",
                                           causal=True, segment_ids=sg),
        mesh=sp_mesh, in_specs=(P(None, "sp"),) * 3 + (P(None, "sp"),),
        out_specs=P(None, "sp"), check_vma=False)
    out = jax.jit(ring)(q, k, v, seg)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)


@pytest.mark.parametrize("window", [8, 24])
def test_ring_attention_window_matches_dense(sp_mesh, window):
    """Sliding-window attention under sp: global positions make the band
    exact across shard boundaries."""
    b, s, h, d = 2, 64, 4, 16
    rng = np.random.RandomState(1)
    q = jnp.asarray(rng.randn(b, s, h, d), jnp.float32)
    k = jnp.asarray(rng.randn(b, s, h, d), jnp.float32)
    v = jnp.asarray(rng.randn(b, s, h, d), jnp.float32)
    ref = dense_attention(q, k, v, causal=True, window=window)

    ring = shard_map(
        functools.partial(ring_attention, axis_name="sp", causal=True,
                          window=window),
        mesh=sp_mesh, in_specs=(P(None, "sp"),) * 3, out_specs=P(None, "sp"),
        check_vma=False)
    out = jax.jit(ring)(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)


def test_ring_attention_segments_window_grads(sp_mesh):
    """Both masks at once, and grads flow (packed + SWA under sp)."""
    from paddle_tpu.ops.attention import segment_mask
    b, s, h, d = 1, 32, 2, 8
    rng = np.random.RandomState(2)
    q = jnp.asarray(rng.randn(b, s, h, d), jnp.float32)
    k = jnp.asarray(rng.randn(b, s, h, d), jnp.float32)
    v = jnp.asarray(rng.randn(b, s, h, d), jnp.float32)
    seg = jnp.asarray(_mk_segments(rng, b, s, n_seg=2))
    window = 6

    ring = shard_map(
        lambda q, k, v, sg: ring_attention(q, k, v, axis_name="sp",
                                           causal=True, segment_ids=sg,
                                           window=window),
        mesh=sp_mesh, in_specs=(P(None, "sp"),) * 3 + (P(None, "sp"),),
        out_specs=P(None, "sp"), check_vma=False)
    out = jax.jit(ring)(q, k, v, seg)
    ref = dense_attention(q, k, v, causal=True, window=window,
                          attn_mask=segment_mask(seg))
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)
    g_ring = jax.jit(jax.grad(lambda q, k, v: ring(q, k, v, seg).sum(),
                              argnums=(0, 1, 2)))(q, k, v)
    g_ref = jax.grad(
        lambda q, k, v: dense_attention(
            q, k, v, causal=True, window=window,
            attn_mask=segment_mask(seg)).sum(), argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(g_ring, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   rtol=1e-3, atol=1e-4)


def test_ulysses_segments_window_match_dense(sp_mesh):
    """Ulysses path: local segment shard all-gathers to the full mask."""
    from paddle_tpu.ops.attention import segment_mask
    b, s, h, d = 2, 64, 4, 16
    rng = np.random.RandomState(3)
    q = jnp.asarray(rng.randn(b, s, h, d), jnp.float32)
    k = jnp.asarray(rng.randn(b, s, h, d), jnp.float32)
    v = jnp.asarray(rng.randn(b, s, h, d), jnp.float32)
    seg = jnp.asarray(_mk_segments(rng, b, s))
    window = 16
    ref = dense_attention(q, k, v, causal=True, window=window,
                          attn_mask=segment_mask(seg))

    uly = shard_map(
        lambda q, k, v, sg: ulysses_attention(q, k, v, axis_name="sp",
                                              causal=True, segment_ids=sg,
                                              window=window),
        mesh=sp_mesh, in_specs=(P(None, "sp"),) * 3 + (P(None, "sp"),),
        out_specs=P(None, "sp"), check_vma=False)
    out = jax.jit(uly)(q, k, v, seg)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)


def test_ring_flash_masked_delegates(sp_mesh):
    """ring_flash_attention with masks routes to the exact block path."""
    from paddle_tpu.parallel.ring import ring_flash_attention
    b, s, h, d = 1, 64, 2, 16
    rng = np.random.RandomState(4)
    q = jnp.asarray(rng.randn(b, s, h, d), jnp.float32)
    k = jnp.asarray(rng.randn(b, s, h, d), jnp.float32)
    v = jnp.asarray(rng.randn(b, s, h, d), jnp.float32)
    ref = dense_attention(q, k, v, causal=True, window=12)
    ring = shard_map(
        functools.partial(ring_flash_attention, axis_name="sp",
                          causal=True, window=12),
        mesh=sp_mesh, in_specs=(P(None, "sp"),) * 3, out_specs=P(None, "sp"),
        check_vma=False)
    out = jax.jit(ring)(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)
