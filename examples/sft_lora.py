"""Supervised fine-tuning with LoRA adapters + packed batches:
chat-template rendering -> packing collator -> SFTTrainer training only
the adapters -> merged export.

  python examples/sft_lora.py
"""
import numpy as np

import paddle_tpu as pt
from paddle_tpu.models import LlamaForCausalLM, llama_tiny
from paddle_tpu.peft import LoRAConfig, LoRAModel
from paddle_tpu.tokenizer import render_chat_template
from paddle_tpu.trainer import TrainingArguments
from paddle_tpu.trl import DataCollatorForSFT, SFTTrainer


def main():
    pt.seed(0)
    base = LlamaForCausalLM(llama_tiny())
    lora = LoRAModel(base, LoRAConfig(
        r=8, lora_alpha=16, target_modules=[".*q_proj", ".*v_proj"]))

    # toy "tokenizer": bytes of the rendered chat template
    def encode(text):
        return [b % 255 + 1 for b in text.encode()][:48]

    rs = np.random.RandomState(0)
    examples = []
    for i in range(16):
        prompt = render_chat_template(
            [{"role": "user", "content": f"question {i}"}], "llama3")
        examples.append({"prompt_ids": encode(prompt),
                         "response_ids": encode(f"answer {i}")})

    coll = DataCollatorForSFT(max_length=128, packing=True, pack_rows=8)
    tr = SFTTrainer(base, pt.optimizer.AdamW(learning_rate=1e-3),
                    TrainingArguments(output_dir="output/sft_lora",
                                      max_steps=30, logging_steps=10),
                    train_dataloader=[coll(examples)])
    tr.train()

    lora.save_pretrained("output/sft_lora/adapter")  # adapter-only ckpt
    lora.merge()  # fold adapters into the base weights for serving
    print("saved adapter + merged model")


if __name__ == "__main__":
    main()
