"""Ring attention — sequence/context parallelism for long sequences
(reference: fleet's sep/context-parallel path in
paddle/distributed/fleet/meta_parallel/, which shards the sequence over
ranks and exchanges KV with NCCL send/recv).

TPU-native: inside `shard_map` over the ``sp`` mesh axis, each device holds
one sequence block of Q/K/V. KV blocks rotate around the ring with
`lax.ppermute` (ICI neighbor exchange — bandwidth-optimal on a TPU torus)
while each device accumulates its Q block's attention with an *online
softmax* (running max + denominator), exactly the flash-attention
recurrence across devices. Causality is enforced per (q-block, kv-block)
pair, so blocks strictly in the future contribute nothing (their compute is
masked; the rotation still happens to keep the schedule static).

Differentiable end-to-end: ppermute has a transpose rule, so `jax.grad`
through ring_attention yields the reverse ring — no hand-written backward.
"""
from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from ..utils.jax_compat import axis_size as _axis_size

NEG_INF = -1e30


def _block_scores(q, k, scale):
    """q [b,sq,h,d], k [b,sk,kvh,d] -> scores [b,h,sq,sk] (fp32), GQA-aware."""
    h, kvh = q.shape[2], k.shape[2]
    if kvh != h:
        k = jnp.repeat(k, h // kvh, axis=2)
    return jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * scale


def _block_pv(p, v, h):
    kvh = v.shape[2]
    if kvh != h:
        v = jnp.repeat(v, h // kvh, axis=2)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v)


def ring_attention(q, k, v, axis_name: str = "sp", causal: bool = False,
                   scale: Optional[float] = None, segment_ids=None,
                   window: Optional[int] = None):
    """Blockwise ring attention. Call inside shard_map with q/k/v
    [b, s_local, h|kvh, d] sharded on the sequence dim over `axis_name`.
    Returns [b, s_local, h, d] (the local Q block's full attention).

    ``segment_ids`` [b, s_local] (the LOCAL shard of the packed-sequence
    ids, same convention as the flash kernel: attention only within equal
    ids) rotates around the ring alongside K/V, so packed SFT composes
    with context parallelism. ``window`` (requires causal) keeps only the
    trailing ``window`` keys per query — sliding-window attention under
    sp. Positions are global (block index * s_local + offset), so both
    masks are exact across shard boundaries."""
    if window is not None and not causal:
        raise ValueError("window requires causal=True (sliding-window "
                         "attention narrows the causal band)")
    n = _axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    b, s, h, d = q.shape
    scale = scale if scale is not None else 1.0 / math.sqrt(d)
    perm = [(i, (i + 1) % n) for i in range(n)]
    has_seg = segment_ids is not None
    sc0 = (jnp.asarray(segment_ids, jnp.int32) if has_seg
           else jnp.zeros((b, 0), jnp.int32))  # empty: nothing to rotate

    def tick(carry, step):
        o, m, l, kc, vc, sc = carry
        kv_idx = (idx - step) % n  # whose sequence block we currently hold
        s_scores = _block_scores(q, kc, scale)  # [b,h,sq,sk]
        if causal or has_seg:
            qpos = idx * s + jnp.arange(s)[:, None]
            kpos = kv_idx * s + jnp.arange(s)[None, :]
            if causal:
                keep = kpos <= qpos
                if window is not None:
                    keep &= qpos - kpos < window
            else:
                keep = jnp.ones((s, s), bool)
            keep = keep[None, None]                      # [1,1,sq,sk]
            if has_seg:
                keep = keep & (segment_ids[:, None, :, None]
                               == sc[:, None, None, :])  # [b,1,sq,sk]
            s_scores = jnp.where(keep, s_scores, NEG_INF)
        m_new = jnp.maximum(m, s_scores.max(axis=-1))
        alpha = jnp.exp(m - m_new)
        p = jnp.exp(s_scores - m_new[..., None])
        l_new = l * alpha + p.sum(axis=-1)
        pv = _block_pv(p.astype(q.dtype), vc, h)  # [b,sq,h,d]
        o_new = o * jnp.swapaxes(alpha, 1, 2)[..., None].astype(o.dtype) \
            + pv.astype(o.dtype)
        kc = lax.ppermute(kc, axis_name, perm)
        vc = lax.ppermute(vc, axis_name, perm)
        if has_seg:
            sc = lax.ppermute(sc, axis_name, perm)
        return (o_new, m_new, l_new, kc, vc, sc), None

    o0 = jnp.zeros((b, s, h, d), jnp.float32)
    m0 = jnp.full((b, h, s), NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, h, s), jnp.float32)
    (o, m, l, _, _, _), _ = lax.scan(tick, (o0, m0, l0, k, v, sc0),
                                     jnp.arange(n))
    denom = jnp.swapaxes(l, 1, 2)[..., None]  # [b,sq,h,1]
    return (o / jnp.maximum(denom, 1e-20)).astype(q.dtype)


def ulysses_attention(q, k, v, axis_name: str = "sp", causal: bool = False,
                      scale: Optional[float] = None, attn_fn=None,
                      segment_ids=None, window: Optional[int] = None):
    """DeepSpeed-Ulysses sequence parallelism (reference: sep_degree path):
    all_to_all trades the sequence shard for a head shard, runs ordinary
    (full-sequence) attention on h/n heads, and trades back. Cheaper than
    ring when heads >= sp degree; requires num_heads % sp == 0.

    ``segment_ids`` is the LOCAL [b, s/n] shard (all-gathered to the full
    sequence, since each device sees every position after the swap);
    ``window`` narrows the causal band (sliding-window attention)."""
    from ..ops.attention import dense_attention, segment_mask
    n = _axis_size(axis_name)

    def swap_in(x):   # [b, s/n, h, d] -> [b, s, h/n, d]
        return lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                              tiled=True)

    def swap_out(x):  # [b, s, h/n, d] -> [b, s/n, h, d]
        return lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                              tiled=True)

    kw = {}
    if segment_ids is not None:
        seg_full = lax.all_gather(jnp.asarray(segment_ids, jnp.int32),
                                  axis_name, axis=1, tiled=True)
        kw["attn_mask"] = segment_mask(seg_full)
    if window is not None:
        kw["window"] = window
    if attn_fn is not None and kw:
        # contract: a custom attn_fn must accept (q, k, v, causal=...,
        # **kw) for whichever of attn_mask/window the caller sets here.
        # Fail with the contract spelled out instead of a TypeError from
        # deep inside the wrapped function.
        import inspect
        try:
            sig = inspect.signature(attn_fn)
            has_var_kw = any(p.kind is inspect.Parameter.VAR_KEYWORD
                             for p in sig.parameters.values())
            missing = [k for k in kw if k not in sig.parameters] \
                if not has_var_kw else []
        except (TypeError, ValueError):   # builtins/partials w/o signature
            missing = []
        if missing:
            cause = "/".join(
                n for n, set_ in (("segment_ids", segment_ids is not None),
                                  ("window", window is not None)) if set_)
            raise TypeError(
                f"ulysses_attention: custom attn_fn {attn_fn!r} does not "
                f"accept {missing} — required because {cause} was set. "
                "attn_fn must take (q, k, v, *, causal, attn_mask, "
                "window) like ops.attention.dense_attention.")
    attn_fn = attn_fn or functools.partial(dense_attention, scale=scale)
    kvh = k.shape[2]
    if kvh < n:  # too few KV heads to split: replicate them up to sp degree
        k = jnp.repeat(k, n // math.gcd(n, kvh), axis=2)
        v = jnp.repeat(v, n // math.gcd(n, kvh), axis=2)
    out = attn_fn(swap_in(q), swap_in(k), swap_in(v), causal=causal, **kw)
    return swap_out(out)


def ring_flash_attention(q, k, v, axis_name: str = "sp",
                         causal: bool = False, scale: Optional[float] = None,
                         segment_ids=None, window: Optional[int] = None):
    """Ring attention with the Pallas flash kernel doing each block pair
    (reference semantics identical to `ring_attention`; this is the fast
    path for long sequences on TPU).

    Per-device blocks merge across ring steps by logsumexp reweighting —
    the same recurrence flash uses internally, lifted to the ring level.
    The ring is unrolled in Python (n is static): step 0 is the diagonal
    (causal within the block); later steps are full block attention taken
    only by devices whose block is in the past (`lax.cond` per device).
    Differentiable end-to-end: flash exposes lse with a custom VJP and
    ppermute transposes to the reverse rotation.

    Note: call inside `shard_map(..., check_vma=False)` — pallas_call
    does not yet declare varying-across-mesh info for its outputs.

    ``segment_ids``/``window`` route to the online-softmax block path
    (`ring_attention`): the per-block flash kernel has no cross-shard
    position offset, so the masked variants use the dense block pairs —
    per-device blocks are modest (s/n) and XLA fuses them; the flash
    fast path covers the plain/causal long-context case.
    """
    if segment_ids is not None or window is not None:
        return ring_attention(q, k, v, axis_name=axis_name, causal=causal,
                              scale=scale, segment_ids=segment_ids,
                              window=window)
    from ..ops.pallas.flash_attention import flash_attention_with_lse
    n = _axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    b, s, h, d = q.shape
    scale = scale if scale is not None else 1.0 / math.sqrt(d)
    perm = [(i, (i + 1) % n) for i in range(n)]

    def merge(o, lse, o_i, lse_i):
        # o, o_i are each NORMALIZED softmax outputs of their blocks;
        # reweight by each block's probability mass and renormalize
        m = jnp.maximum(lse, lse_i)
        w = jnp.exp(lse - m)                    # [b,h,s]
        w_i = jnp.exp(lse_i - m)
        wq = jnp.swapaxes(w, 1, 2)[..., None]   # [b,s,h,1]
        wq_i = jnp.swapaxes(w_i, 1, 2)[..., None]
        o_new = (o * wq + o_i.astype(jnp.float32) * wq_i) / (wq + wq_i)
        lse_new = m + jnp.log(w + w_i)
        return o_new, lse_new

    # step 0: own block, causal if requested
    o_i, lse_i = flash_attention_with_lse(q, k, v, causal=causal,
                                          scale=scale)
    o = o_i.astype(jnp.float32)
    lse = lse_i
    kc, vc = k, v
    for step in range(1, n):
        kc = lax.ppermute(kc, axis_name, perm)
        vc = lax.ppermute(vc, axis_name, perm)
        if causal:
            # kv block is in this device's past iff idx >= step
            def take(q=q, kc=kc, vc=vc, o=o, lse=lse):
                o_b, lse_b = flash_attention_with_lse(q, kc, vc,
                                                      causal=False,
                                                      scale=scale)
                return merge(o, lse, o_b, lse_b)

            def skip(o=o, lse=lse):
                return o, lse

            o, lse = lax.cond(idx >= step, take, skip)
        else:
            o_b, lse_b = flash_attention_with_lse(q, kc, vc, causal=False,
                                                  scale=scale)
            o, lse = merge(o, lse, o_b, lse_b)
    return o.astype(q.dtype)
