"""Elastic relaunch supervisor (reference: paddle.distributed.elastic /
fleet elastic launch — the agent that restarts failed trainers so a
preemption costs a resume, not the run).

TPU-native shape: on TPU pods the scheduler preempts whole workers; the
recovery contract is (1) trainers checkpoint periodically and on hang
(Trainer.hang_timeout_s), (2) this supervisor relaunches the training
process, (3) Trainer auto-resume restores the latest COMPLETE checkpoint
(checkpoint.distributed_ckpt manifests make half-written saves
invisible). Loss trajectory continuity across kill/restart is asserted
end-to-end in tests/test_elastic.py.
"""
from __future__ import annotations

import os
import subprocess
import sys
import time
from typing import Any, Callable, List, Optional, Sequence

from ..utils import compile_cache
from ..utils import observability as obs
from ..utils.faults import retry_with_backoff
from ..utils.shutdown import PREEMPTED_RC

__all__ = ["supervise", "PREEMPTED_RC"]


def _default_topology() -> Optional[Any]:
    """Cheap world-size probe for the relaunch log. The supervisor must
    not import jax (the child owns the accelerator). Prefers a FILE
    (``$PADDLE_TPU_WORLD_SIZE_FILE``) the scheduler/launcher can rewrite
    between relaunches — the supervisor's own env is frozen at launch,
    so a bare env var can only describe the initial topology."""
    path = os.environ.get("PADDLE_TPU_WORLD_SIZE_FILE")
    if path:
        try:
            with open(path) as f:
                return f.read().strip()
        except OSError:
            return None
    return os.environ.get("PADDLE_TPU_WORLD_SIZE")


class _RestartableExit(RuntimeError):
    """Child exited with a relaunch-worthy code (retry_with_backoff's
    retryable filter keys on this)."""

    def __init__(self, rc: int):
        super().__init__(f"restartable child exit rc={rc}")
        self.rc = rc


def supervise(argv: Sequence[str], max_restarts: int = 3,
              backoff_s: float = 1.0,
              restart_codes: Optional[Sequence[int]] = None,
              timeout_s: Optional[float] = None,
              preempt_rc: Optional[int] = PREEMPTED_RC,
              max_preemptions: Optional[int] = None,
              probe_topology: Optional[Callable[[], Any]]
              = _default_topology,
              compile_cache_dir: Optional[str] = None,
              run_dir: Optional[str] = None) -> int:
    """Run ``argv`` as a subprocess; relaunch on failure with jittered
    exponential backoff (the shared utils.faults.retry_with_backoff —
    ``backoff_s`` seeds the base delay, doubling per consecutive
    failure so a crash-looping job doesn't hammer the scheduler).

    restart_codes: exit codes that trigger a relaunch (None = any
    non-zero, plus death-by-signal). Returns the final exit code (0 on
    eventual success). Each relaunch resumes from the latest complete
    checkpoint via the Trainer's own auto-resume — the supervisor carries
    no training state.

    preempt_rc: the graceful-shutdown exit code (Trainer's
    ``preempt_exit_code``, default utils.shutdown.PREEMPTED_RC). A child
    exiting with it was *preempted, not broken* — it already checkpointed
    its exact step — so it is ALWAYS relaunched and never consumes a
    ``max_restarts`` attempt (``max_preemptions`` bounds a pathological
    preemption storm; None = unlimited, preemption is the steady state
    on spot/preemptible pods). ``probe_topology`` is sampled before each
    launch and changes are logged — the job may come back with a
    different world size, which the Trainer reconciles from its
    topology manifest on resume.

    compile_cache_dir: persistent XLA compilation cache shared by every
    (re)launch — injected into children as
    ``$PADDLE_TPU_COMPILE_CACHE_DIR`` (the child's ``Trainer.train``
    resolves it via ``utils.compile_cache.enable``), so a
    preempted-and-relaunched worker restores its step executable from
    disk instead of paying full recompilation. None inherits the
    supervisor's env (which may itself carry the var); the supervisor
    never imports jax — the child owns the accelerator.

    run_dir: where to land the SUPERVISOR'S OWN telemetry on exit —
    ``flight_supervisor.json`` (child launch/exit events with rcs) and
    ``metrics_supervisor.prom`` (restart/preemption counters). Children
    write their attempt-numbered ``flight_<k>``/``trace_<k>`` files
    themselves; without this the supervisor's view — the only place the
    cross-attempt launch/exit/rc story lives — is write-only and dies
    with the process. Pass the child's ``<output_dir>/runs`` so one dir
    holds both sides. None (default) keeps the old behavior.
    """
    # every (re)launch gets an explicit environment: the compile-cache
    # dir (when configured), the shared run id, and a per-launch attempt
    # number — the child's observability names its artifacts
    # flight_<attempt>.json / trace_<attempt>.json, so an elastic run's
    # attempts sit side by side in one run dir and stitch into one
    # timeline (epoch-microsecond trace timestamps).
    base_env = compile_cache.child_env(compile_cache_dir) \
        if compile_cache.resolve_dir(compile_cache_dir) \
        else dict(os.environ)
    base_env[obs.ENV_RUN_ID] = obs.run_id()
    launches = [0]
    preemptions = [0]
    # PER-CALL recorder/registry, not the process globals: a driver
    # supervising two jobs back-to-back must not report job A's
    # preemption counters and launch events in job B's artifacts
    recorder = obs.FlightRecorder()
    registry = obs.MetricsRegistry()
    c_restarts = registry.counter("elastic_restarts_total")
    c_preempts = registry.counter("elastic_preemptions_total")
    last_topo: List[Any] = [probe_topology() if probe_topology else None]

    def check_topology():
        if probe_topology is None:
            return
        topo = probe_topology()
        if topo != last_topo[0]:
            print(f"[elastic] topology changed between attempts: "
                  f"{last_topo[0]!r} -> {topo!r} (the trainer reconciles "
                  f"sampler shards and grad accumulation on resume)",
                  file=sys.stderr, flush=True)
            last_topo[0] = topo

    def attempt() -> int:
        while True:
            check_topology()
            env = dict(base_env)
            env[obs.ENV_ATTEMPT] = str(launches[0])
            recorder.record("elastic_child_launch", attempt=launches[0],
                            argv0=argv[0])
            launches[0] += 1
            try:
                proc = subprocess.run(list(argv), timeout=timeout_s,
                                      env=env)
                rc = proc.returncode
            except subprocess.TimeoutExpired:
                # a child hung before its own watchdog could fire (e.g.
                # stuck in startup): that IS the case this supervisor
                # exists for
                rc = 124
            recorder.record("elastic_child_exit",
                            attempt=launches[0] - 1, rc=rc)
            if rc == 0:
                return 0
            if preempt_rc is not None and rc == preempt_rc:
                preemptions[0] += 1
                c_preempts.inc()
                if max_preemptions is not None and \
                        preemptions[0] > max_preemptions:
                    print(f"[elastic] preemption budget exhausted "
                          f"({max_preemptions}); giving up",
                          file=sys.stderr, flush=True)
                    return rc
                print(f"[elastic] child preempted (rc={rc}, preemption "
                      f"{preemptions[0]}): it checkpointed before "
                      f"exiting; relaunching WITHOUT consuming a "
                      f"restart attempt", file=sys.stderr, flush=True)
                time.sleep(min(backoff_s, 1.0))
                continue
            restartable = (restart_codes is None) or (rc in restart_codes) \
                or rc < 0 or rc == 124  # negative = killed by signal
            if restartable:
                raise _RestartableExit(rc)
            return rc

    def on_retry(exc, attempt_no, delay):
        c_restarts.inc()
        print(f"[elastic] attempt {attempt_no}/{max_restarts + 1}: "
              f"rc={exc.rc}; relaunching in {delay:.1f}s",
              file=sys.stderr, flush=True)

    def flush_supervisor_telemetry():
        if run_dir is None:
            return
        try:
            os.makedirs(run_dir, exist_ok=True)
            recorder.dump(
                os.path.join(run_dir, "flight_supervisor.json"),
                "supervise_exit")
            prom = os.path.join(run_dir, "metrics_supervisor.prom")
            with open(prom + ".tmp", "w") as f:
                f.write(registry.prometheus_text())
            os.replace(prom + ".tmp", prom)
        except OSError:
            pass   # telemetry must never mask the child's exit code

    try:
        return retry_with_backoff(attempt, max_attempts=max_restarts + 1,
                                  base_delay=backoff_s, factor=2.0,
                                  max_delay=max(backoff_s, 60.0),
                                  retryable=(_RestartableExit,),
                                  on_retry=on_retry)
    except _RestartableExit as e:
        return e.rc
    finally:
        flush_supervisor_telemetry()


def main(args: Optional[List[str]] = None) -> int:
    """CLI: ``python -m paddle_tpu.distributed.elastic [--max-restarts N]
    -- cmd args...``"""
    args = list(sys.argv[1:] if args is None else args)
    max_restarts = 3
    cache_dir = None
    run_dir = None
    while args and args[0] in ("--max-restarts", "--compile-cache-dir",
                               "--run-dir"):
        if len(args) < 2 or args[1] == "--":
            # flag without a value: fall through to the usage message
            # instead of an IndexError (or eating the -- separator)
            args = []
            break
        if args[0] == "--max-restarts":
            max_restarts = int(args[1])
        elif args[0] == "--compile-cache-dir":
            cache_dir = args[1]
        else:
            run_dir = args[1]
        args = args[2:]
    if args and args[0] == "--":
        args = args[1:]
    if not args:
        print("usage: python -m paddle_tpu.distributed.elastic "
              "[--max-restarts N] [--compile-cache-dir DIR] "
              "[--run-dir DIR] -- cmd ...",
              file=sys.stderr)
        return 2
    return supervise(args, max_restarts=max_restarts,
                     compile_cache_dir=cache_dir, run_dir=run_dir)


if __name__ == "__main__":
    sys.exit(main())
