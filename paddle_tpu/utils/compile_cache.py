"""Persistent XLA compilation cache wiring (ISSUE 4 tentpole).

PR 3 made preemption restarts free at the supervisor level
(``PREEMPTED_RC`` never consumes a restart attempt) — but each relaunch
still paid a full XLA recompilation of the train step before its first
post-resume step. This module points jax's persistent compilation cache
(``jax_compilation_cache_dir``) at a directory that survives the
process, so a preempted-and-relaunched worker compiles the
byte-identical step program once and restores it from disk thereafter.

Two enablement channels, one resolver:

- ``TrainingArguments.compile_cache_dir`` → ``Trainer.train`` calls
  ``enable()`` before building the step;
- ``$PADDLE_TPU_COMPILE_CACHE_DIR`` — picked up by ``enable()`` when no
  explicit dir is given, and injected into relaunched children by
  ``distributed.elastic.supervise`` via ``child_env()`` so the whole
  supervise/preempt/relaunch loop shares one cache without any trainer
  code changes.

``entries()`` lists the cache's program keys (the ``*-cache`` payload
files, not the ``-atime`` access-time markers) so tests and tools can
assert "the second startup hit the cache" by set equality on keys —
population, not wall time.
"""
from __future__ import annotations

import os
import sys
import threading
from typing import Dict, List, Optional

__all__ = ["ENV_VAR", "MIN_COMPILE_ENV_VAR", "enable", "enabled",
           "active_dir", "resolve_dir", "entries", "child_env"]

ENV_VAR = "PADDLE_TPU_COMPILE_CACHE_DIR"
MIN_COMPILE_ENV_VAR = "PADDLE_TPU_COMPILE_CACHE_MIN_S"

_lock = threading.Lock()
_dir: Optional[str] = None


def resolve_dir(cache_dir: Optional[str] = None) -> Optional[str]:
    """Explicit dir wins; falls back to ``$PADDLE_TPU_COMPILE_CACHE_DIR``;
    None means "leave whatever jax config is already active alone"."""
    return cache_dir or os.environ.get(ENV_VAR) or None


def enable(cache_dir: Optional[str] = None,
           min_compile_time_s: Optional[float] = None) -> Optional[str]:
    """Point jax at a persistent compilation cache directory.

    No-op (returns None) when neither ``cache_dir`` nor the env var is
    set — an already-configured cache (e.g. the test suite's) is left
    untouched. Idempotent and cheap; safe to call every ``train()``.
    ``min_compile_time_s`` gates trivial programs out of the cache
    (default ``$PADDLE_TPU_COMPILE_CACHE_MIN_S`` or 1.0s — the train
    step is far above it, per-op jits mostly below)."""
    global _dir
    cache_dir = resolve_dir(cache_dir)
    if not cache_dir:
        return None
    if min_compile_time_s is None:
        min_compile_time_s = float(
            os.environ.get(MIN_COMPILE_ENV_VAR, "1.0"))
    import jax
    with _lock:
        try:
            os.makedirs(cache_dir, exist_ok=True)
            jax.config.update("jax_compilation_cache_dir", cache_dir)
            jax.config.update("jax_persistent_cache_min_compile_time_secs",
                              float(min_compile_time_s))
            _reset_latched_cache(cache_dir)
        except Exception as e:   # config drift across jax versions
            print(f"[compile_cache] could not enable persistent cache at "
                  f"{cache_dir}: {e}", file=sys.stderr, flush=True)
            return None
        _dir = cache_dir
    return cache_dir


def _reset_latched_cache(cache_dir: str) -> None:
    """jax initializes its cache object AT MOST ONCE, on the first XLA
    compile — and model/optimizer init usually compiles something long
    before ``Trainer.train`` calls ``enable()``, latching "no cache"
    for the whole process. If the latched cache doesn't point at
    ``cache_dir``, reset it so the next compile re-initializes against
    the directory just configured."""
    try:
        # the _src module, not the jax.experimental re-export: the
        # latter's module-level ints/bools are frozen at its import
        from jax._src import compilation_cache as cc
        latched = getattr(cc, "_cache", None)
        if getattr(cc, "_cache_initialized", False) and \
                str(getattr(latched, "_path", None)) != cache_dir:
            cc.reset_cache()
    except Exception:
        pass     # private latch moved (newer jax): dir config still set


def enabled() -> bool:
    return active_dir() is not None


def active_dir() -> Optional[str]:
    """The directory jax is currently caching into (None if disabled)."""
    try:
        import jax
        return jax.config.jax_compilation_cache_dir or None
    except Exception:
        return _dir


def entries(cache_dir: Optional[str] = None) -> List[str]:
    """Sorted program keys currently in the cache (payload files only;
    ``-atime`` access markers are bookkeeping, not programs)."""
    d = resolve_dir(cache_dir) or active_dir()
    if not d or not os.path.isdir(d):
        return []
    return sorted(f for f in os.listdir(d) if not f.endswith("-atime"))


def child_env(cache_dir: Optional[str] = None,
              base: Optional[Dict[str, str]] = None) -> Dict[str, str]:
    """Environment for a relaunched worker: the cache dir propagates via
    ``$PADDLE_TPU_COMPILE_CACHE_DIR``, which ``enable()`` inside the
    child's ``Trainer.train`` resolves — the supervisor never imports
    jax (the child owns the accelerator)."""
    env = dict(os.environ if base is None else base)
    d = resolve_dir(cache_dir)
    if d:
        env[ENV_VAR] = d
    return env
