"""DataLoader (reference: python/paddle/io/dataloader/dataloader_iter.py —
C++ BlockingQueue + worker pool).

TPU-native: the accelerator is fed from the host over PCIe/ICI, so the
loader's job is (1) overlap host batch assembly with device compute, and
(2) pin a steady static batch shape. Default path: background prefetch
threads (numpy collate releases the GIL for the heavy copies). When the
native C++ pipeline (paddle_tpu/native) is built, `use_native=True` routes
batch assembly through the C ring buffer; the Python fallback is always
available.
"""
from __future__ import annotations

import queue
import threading
from typing import Callable, Optional

import numpy as np

from .dataset import Dataset, IterableDataset
from .sampler import BatchSampler


def bounded_put(q: "queue.Queue", item, stop: threading.Event,
                poll_s: float = 0.05) -> bool:
    """Bounded producer put that re-checks ``stop`` while the queue is
    full, so an abandoned consumer (early break, preemption exit) can't
    leave the producer thread parked forever. Returns False when stopped
    before the item fit. Shared by the DataLoader prefetch threads and
    io.device_prefetch's producer — one copy of the shutdown race."""
    while not stop.is_set():
        try:
            q.put(item, timeout=poll_s)
            return True
        except queue.Full:
            continue
    return False


def default_collate_fn(batch):
    """Stack samples into batch arrays (mirrors paddle's default_collate_fn)."""
    sample = batch[0]
    if isinstance(sample, np.ndarray):
        return np.stack(batch)
    if isinstance(sample, (int, float, np.number)):
        return np.asarray(batch)
    if isinstance(sample, (list, tuple)):
        return type(sample)(default_collate_fn([b[i] for b in batch])
                            for i in range(len(sample)))
    if isinstance(sample, dict):
        return {k: default_collate_fn([b[k] for b in batch]) for k in sample}
    if hasattr(sample, "__array__"):
        return np.stack([np.asarray(b) for b in batch])
    return batch


class DataLoader:
    def __init__(self, dataset: Dataset, batch_size=1, shuffle=False,
                 sampler=None, batch_sampler=None, num_workers=0,
                 collate_fn: Optional[Callable] = None, drop_last=False,
                 prefetch_factor=2, use_native=False, return_list=True,  # noqa: ARG002
                 worker_init_fn=None, persistent_workers=False):
        self.dataset = dataset
        self.collate_fn = collate_fn or default_collate_fn
        self.num_workers = num_workers
        self.prefetch_factor = max(prefetch_factor, 1)
        self.use_native = use_native
        self.worker_init_fn = worker_init_fn
        self.persistent_workers = persistent_workers
        self._pool = None
        self._iterable = isinstance(dataset, IterableDataset)
        if self._iterable:
            self.batch_size = batch_size
            self.drop_last = drop_last
            self.batch_sampler = None
        elif batch_sampler is not None:
            self.batch_sampler = batch_sampler
        else:
            self.batch_sampler = BatchSampler(dataset, sampler=sampler,
                                              shuffle=shuffle,
                                              batch_size=batch_size,
                                              drop_last=drop_last)

    def __len__(self):
        if self._iterable:
            raise TypeError("IterableDataset has no length")
        return len(self.batch_sampler)

    # ------------------------------------------------------------ iteration
    def _assemble(self, indices):
        if self.use_native:
            from ..native import loader as native_loader
            if native_loader.available():
                return native_loader.assemble(self.dataset, indices, self.collate_fn)
        return self.collate_fn([self.dataset[i] for i in indices])

    def _iter_sync(self):
        if self._iterable:
            batch = []
            for item in self.dataset:
                batch.append(item)
                if len(batch) == self.batch_size:
                    yield self.collate_fn(batch)
                    batch = []
            if batch and not self.drop_last:
                yield self.collate_fn(batch)
        else:
            for indices in self.batch_sampler:
                yield self._assemble(indices)

    def _iter_prefetch(self):
        """Background thread pool keeps `num_workers * prefetch_factor`
        batches in flight ahead of the consumer."""
        depth = self.num_workers * self.prefetch_factor
        q: queue.Queue = queue.Queue(maxsize=depth)
        sentinel = object()
        stop = threading.Event()

        class _WorkerError:
            def __init__(self, exc):
                self.exc = exc

        def put(item):
            return bounded_put(q, item, stop)

        def producer():
            try:
                for batch in self._iter_sync():
                    if not put(batch):
                        return
            except BaseException as e:  # propagate into consumer
                put(_WorkerError(e))
            finally:
                # bounded put: waits for space while the consumer drains;
                # bails out via `stop` if the consumer abandoned the
                # iterator. Never discards a queued batch.
                put(sentinel)

        t = threading.Thread(target=producer, daemon=True)
        t.start()
        try:
            while True:
                item = q.get()
                if item is sentinel:
                    break
                if isinstance(item, _WorkerError):
                    raise item.exc
                yield item
        finally:
            stop.set()

    def _iter_multiprocess(self):
        """True multiprocess workers (io/worker.py): spawned processes,
        ordered results, persistent across epochs when asked."""
        from .worker import WorkerPool
        pool = self._pool
        if pool is None:
            # fresh base seed per pool (drawn from the ambient numpy RNG,
            # so pt.seed/np.random.seed still gives reproducible runs):
            # respawned workers must NOT replay epoch 1's augmentations
            pool = WorkerPool(self.dataset, self.collate_fn,
                              self.num_workers, self.prefetch_factor,
                              self.worker_init_fn,
                              seed=int(np.random.randint(0, 2 ** 31 - 1)))
            if self.persistent_workers:
                self._pool = pool
        try:
            yield from pool.run_epoch(iter(self.batch_sampler))
        finally:
            if not self.persistent_workers:
                pool.shutdown()

    # -------------------------------------------------- resumable state
    def state_dict(self):
        """Sampler position for preemption-safe resume (delegated to the
        batch sampler's (epoch, cursor) state). O(1) to capture and to
        restore — no batch replay. Caveat: with ``num_workers > 0`` the
        sampler runs ahead of the consumer by up to the prefetch depth,
        so a checkpoint taken mid-epoch counts in-flight batches as
        consumed (they are skipped on resume, never double-trained); the
        synchronous path is exact. IterableDataset has no index space to
        cursor — returns {} (resume falls back to the trainer's legacy
        skip-replay)."""
        bs = self.batch_sampler
        if bs is None or not hasattr(bs, "state_dict"):
            return {}
        sd = bs.state_dict()
        return {"batch_sampler": sd} if sd else {}

    def load_state_dict(self, state):
        inner = (state or {}).get("batch_sampler")
        if inner is not None and self.batch_sampler is not None \
                and hasattr(self.batch_sampler, "load_state_dict"):
            self.batch_sampler.load_state_dict(inner)

    def shutdown(self):
        """Tear down persistent workers (no-op otherwise)."""
        if self._pool is not None:
            self._pool.shutdown()
            self._pool = None

    def __del__(self):
        try:
            self.shutdown()
        except Exception:
            pass

    def __iter__(self):
        if self.num_workers > 0:
            # map-style -> real worker processes; iterable/native keep the
            # thread prefetcher (the native path's C++ ring buffer IS its
            # worker pool; an IterableDataset shards via get_worker_info
            # only when the user opts in, so default to single-stream)
            if not self._iterable and not self.use_native:
                return self._iter_multiprocess()
            return self._iter_prefetch()
        return self._iter_sync()
