"""Convolution & pooling layers (reference: python/paddle/nn/layer/conv.py,
pooling.py). NCHW API surface; lowering through lax.conv_general_dilated
lets XLA choose TPU-optimal layouts (convs run on the MXU as implicit
GEMMs)."""
from __future__ import annotations

import math

import jax.numpy as jnp

from ..utils.rng import next_key
from . import functional as F
from . import initializer as I
from .layer import Layer, Parameter


def _ntuple(v, n):
    return (v,) * n if isinstance(v, int) else tuple(v)


class _ConvNd(Layer):
    def __init__(self, in_channels, out_channels, kernel_size, stride, padding,
                 dilation, groups, bias_attr, ndim, weight_attr=None, name=None):
        super().__init__(name)
        self.in_channels = in_channels
        self.out_channels = out_channels
        self.kernel_size = _ntuple(kernel_size, ndim)
        self.stride = stride
        self.padding = padding
        self.dilation = dilation
        self.groups = groups
        self._ndim = ndim
        shape = (out_channels, in_channels // groups) + self.kernel_size
        fan_in = (in_channels // groups) * math.prod(self.kernel_size)
        init = weight_attr if isinstance(weight_attr, I.Initializer) else \
            I.KaimingUniform(fan_in=fan_in)
        self.weight = Parameter(init(next_key(), shape))
        if bias_attr is not False:
            bound = 1 / math.sqrt(fan_in)
            self.bias = Parameter(I.Uniform(-bound, bound)(next_key(), (out_channels,)))
        else:
            self.bias = None

    def extra_repr(self):
        return (f"{self.in_channels}, {self.out_channels}, k={self.kernel_size}, "
                f"s={self.stride}, p={self.padding}, g={self.groups}")


class Conv1D(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, weight_attr=None,
                 bias_attr=None, name=None):
        super().__init__(in_channels, out_channels, kernel_size, stride,
                         padding, dilation, groups, bias_attr, 1, weight_attr, name)

    def forward(self, x):
        return F.conv1d(x, self.weight, getattr(self, "bias", None),
                        self.stride, self.padding, self.dilation, self.groups)


class Conv2D(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, weight_attr=None,
                 bias_attr=None, name=None):
        super().__init__(in_channels, out_channels, kernel_size, stride,
                         padding, dilation, groups, bias_attr, 2, weight_attr, name)

    def forward(self, x):
        return F.conv2d(x, self.weight, getattr(self, "bias", None),
                        self.stride, self.padding, self.dilation, self.groups)


class Conv3D(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, weight_attr=None,
                 bias_attr=None, name=None):
        super().__init__(in_channels, out_channels, kernel_size, stride,
                         padding, dilation, groups, bias_attr, 3, weight_attr, name)

    def forward(self, x):
        return F.conv3d(x, self.weight, getattr(self, "bias", None),
                        self.stride, self.padding, self.dilation, self.groups)


class Conv2DTranspose(Layer):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, output_padding=0, dilation=1, groups=1,
                 weight_attr=None, bias_attr=None, name=None):
        super().__init__(name)
        self.in_channels, self.out_channels = in_channels, out_channels
        self.kernel_size = _ntuple(kernel_size, 2)
        self.stride, self.padding = stride, padding
        self.output_padding, self.dilation, self.groups = output_padding, dilation, groups
        shape = (in_channels, out_channels // groups) + self.kernel_size
        fan_in = in_channels * math.prod(self.kernel_size) // groups
        init = weight_attr if isinstance(weight_attr, I.Initializer) else \
            I.KaimingUniform(fan_in=fan_in)
        self.weight = Parameter(init(next_key(), shape))
        if bias_attr is not False:
            self.bias = Parameter(jnp.zeros((out_channels,)))
        else:
            self.bias = None

    def forward(self, x):
        return F.conv2d_transpose(x, self.weight, getattr(self, "bias", None),
                                  self.stride, self.padding,
                                  self.output_padding, self.dilation, self.groups)


class MaxPool2D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0, name=None):
        super().__init__(name)
        self.kernel_size, self.stride, self.padding = kernel_size, stride, padding

    def forward(self, x):
        return F.max_pool2d(x, self.kernel_size, self.stride, self.padding)


class AvgPool2D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0, name=None):
        super().__init__(name)
        self.kernel_size, self.stride, self.padding = kernel_size, stride, padding

    def forward(self, x):
        return F.avg_pool2d(x, self.kernel_size, self.stride, self.padding)


class AdaptiveAvgPool2D(Layer):
    def __init__(self, output_size, name=None):
        super().__init__(name)
        self.output_size = output_size

    def forward(self, x):
        return F.adaptive_avg_pool2d(x, self.output_size)


class AdaptiveMaxPool2D(Layer):
    def __init__(self, output_size, name=None):
        super().__init__(name)
        self.output_size = output_size

    def forward(self, x):
        return F.adaptive_max_pool2d(x, self.output_size)
