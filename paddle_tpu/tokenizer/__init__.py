"""paddle_tpu.tokenizer — real tokenization (reference: PaddleNLP
``paddlenlp/transformers/*/tokenizer.py``).

- ``BPETokenizer``: merges-based byte-level BPE, loads HF tokenizer.json
  or GPT-2 vocab.json+merges.txt — reproduces GPT-2/Llama-3/Qwen2
  tokenizations exactly (parity-tested vs the ``tokenizers`` library).
- ``TrieTokenizer``: C++ greedy longest-match trie (vocab-only models /
  fast data prep), re-exported from ``paddle_tpu.native``.
"""
from ..native import Tokenizer as TrieTokenizer
from .bpe import (GPT2_SPLIT, LLAMA3_SPLIT, BPETokenizer, bytes_to_unicode)
from .chat import (CHAT_TEMPLATES, apply_chat_template,
                   render_chat_template)

__all__ = ["BPETokenizer", "TrieTokenizer", "bytes_to_unicode",
           "GPT2_SPLIT", "LLAMA3_SPLIT", "CHAT_TEMPLATES",
           "apply_chat_template", "render_chat_template"]
