"""Samplers (reference: python/paddle/io/dataloader/sampler.py,
batch_sampler.py). DistributedBatchSampler shards the *index space* per dp
rank; on a single-controller TPU runtime the loader usually feeds the global
batch and GSPMD shards it, but per-host sharding is needed for multi-host
input pipelines."""
from __future__ import annotations

import math
from typing import Iterator, Optional, Sequence

import numpy as np


class Sampler:
    def __init__(self, data_source=None):
        self.data_source = data_source

    def __iter__(self) -> Iterator[int]:
        raise NotImplementedError

    def __len__(self):
        return len(self.data_source)


class SequenceSampler(Sampler):
    def __iter__(self):
        return iter(range(len(self.data_source)))


class RandomSampler(Sampler):
    def __init__(self, data_source, replacement=False, num_samples=None,
                 generator=None):
        super().__init__(data_source)
        self.replacement = replacement
        self._num_samples = num_samples
        self.generator = generator
        self._epoch_seed = 0

    @property
    def num_samples(self):
        return self._num_samples or len(self.data_source)

    def __iter__(self):
        n = len(self.data_source)
        rng = np.random.default_rng(self.generator if self.generator is not None
                                    else self._epoch_seed)
        self._epoch_seed += 1
        if self.replacement:
            yield from rng.integers(0, n, size=self.num_samples).tolist()
        else:
            yield from rng.permutation(n)[:self.num_samples].tolist()

    def __len__(self):
        return self.num_samples


class SubsetRandomSampler(Sampler):
    def __init__(self, indices: Sequence[int], generator=None):
        super().__init__(indices)
        self.indices = list(indices)
        self.generator = generator

    def __iter__(self):
        rng = np.random.default_rng(self.generator)
        yield from (self.indices[i] for i in rng.permutation(len(self.indices)))

    def __len__(self):
        return len(self.indices)


class WeightedRandomSampler(Sampler):
    def __init__(self, weights: Sequence[float], num_samples: int,
                 replacement=True, generator=None):
        super().__init__(None)
        self.weights = np.asarray(weights, dtype=np.float64)
        self.num_samples = num_samples
        self.replacement = replacement
        self.generator = generator

    def __iter__(self):
        rng = np.random.default_rng(self.generator)
        p = self.weights / self.weights.sum()
        yield from rng.choice(len(self.weights), size=self.num_samples,
                              replace=self.replacement, p=p).tolist()

    def __len__(self):
        return self.num_samples


class BatchSampler(Sampler):
    def __init__(self, dataset=None, sampler: Optional[Sampler] = None,
                 shuffle=False, batch_size=1, drop_last=False):
        super().__init__(dataset)
        if sampler is None:
            assert dataset is not None
            sampler = RandomSampler(dataset) if shuffle else SequenceSampler(dataset)
        self.sampler = sampler
        self.batch_size = batch_size
        self.drop_last = drop_last

    def __iter__(self):
        batch = []
        for idx in self.sampler:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        n = len(self.sampler)
        if self.drop_last:
            return n // self.batch_size
        return math.ceil(n / self.batch_size)


class DistributedBatchSampler(BatchSampler):
    """Index-sharded batch sampler (reference:
    python/paddle/io/dataloader/batch_sampler.py DistributedBatchSampler)."""

    def __init__(self, dataset, batch_size, num_replicas=None, rank=None,
                 shuffle=False, drop_last=False):
        import jax
        self.dataset = dataset
        self.batch_size = batch_size
        self.nranks = num_replicas if num_replicas is not None else jax.process_count()
        self.local_rank = rank if rank is not None else jax.process_index()
        self.shuffle = shuffle
        self.drop_last = drop_last
        self.epoch = 0
        self.num_samples = int(math.ceil(len(dataset) / self.nranks))
        self.total_size = self.num_samples * self.nranks

    def __iter__(self):
        n = len(self.dataset)
        if self.shuffle:
            rng = np.random.default_rng(self.epoch)
            indices = rng.permutation(n).tolist()
        else:
            indices = list(range(n))
        indices += indices[: (self.total_size - n)]  # pad to even shards
        indices = indices[self.local_rank::self.nranks]
        batch = []
        for idx in indices:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        if self.drop_last:
            return self.num_samples // self.batch_size
        return math.ceil(self.num_samples / self.batch_size)

    def set_epoch(self, epoch: int):
        self.epoch = epoch
