"""paddle_tpu.io (reference: python/paddle/io/__init__.py)."""
from .dataset import (ChainDataset, ConcatDataset, Dataset, IterableDataset,
                      Subset, TensorDataset, random_split)
from .dataloader import DataLoader, default_collate_fn
from .sampler import (BatchSampler, DistributedBatchSampler, RandomSampler,
                      Sampler, SequenceSampler, SubsetRandomSampler,
                      WeightedRandomSampler)
