"""Multi-host serving fleet (ISSUE 13): the layer that turns N
gateway PROCESSES into one service — the "millions of users" tier the
single-process gateway cannot reach (ROADMAP item 2).

- :mod:`.remote` — :class:`RemoteReplica`: the router's duck-typed
  replica seam (``healthy``/``load``/``has_prefix``) implemented over
  cached HTTP probes of a peer gateway (``/healthz`` + the
  ``/debugz/prefix`` digest gossip), with staleness bounds.
- :mod:`.frontend` — :class:`FleetFrontend`: prefix-affinity routing
  over remote peers, byte-for-byte SSE proxying, and mid-stream peer
  failover through the HTTP face of the ISSUE-12 resume seam (greedy
  streams bitwise identical across a peer death).
- :mod:`.autoscaler` — :class:`FleetAutoscaler`: the closed loop over
  the PR-8 gauges (queue depth, free slots, block pressure, goodput
  fraction) with hysteresis + cooldown, spawning/draining replica
  processes under SIGTERM-drain semantics.
- :mod:`.manager` — :class:`LocalProcessManager`: the process backend
  (spawn ``replica_main`` subprocesses, SIGTERM drains, SIGKILL
  chaos); accepts a LIST of frontends (ISSUE 16 HA) — every sibling
  gets its own adapter per spawned process.
- :mod:`.ha` — :class:`FrontendLink`/:func:`link_frontends`:
  leaderless frontend-to-frontend gossip (prefix digests, breaker
  states, sticky assignments) so a frontend death loses no routing
  state and a client retry against the survivor resumes mid-stream.
- :mod:`.sim` — :class:`FleetSim`: the trace-driven chaos simulator
  that runs THESE real objects (frontend, router, autoscaler, burn
  engine, breakers) against thousands of in-process replica stubs on
  a simulated clock (``tools/fleet_sim.py``).

See ``docs/SERVING.md`` ("Fleet serving") and
``docs/FAULT_TOLERANCE.md`` (remote + frontend failure models).
"""
from .autoscaler import FleetAutoscaler
from .frontend import FleetFrontend
from .ha import FrontendLink, link_frontends
from .manager import LocalProcessManager
from .remote import RemoteReplica, prefix_digest_chain
from .sim import (SCENARIOS, FleetSim, SimClock, SimProcess,
                  SimReplica, build_scenario)

__all__ = [
    "FleetAutoscaler", "FleetFrontend", "LocalProcessManager",
    "RemoteReplica", "prefix_digest_chain",
    "FrontendLink", "link_frontends",
    "FleetSim", "SimClock", "SimProcess", "SimReplica",
    "SCENARIOS", "build_scenario",
]
