"""paddle_tpu.models — model zoo (reference: PaddleNLP/PaddleMIX recipes)."""
from .llama import (LlamaConfig, LlamaForCausalLM, LlamaModel, causal_lm_loss,
                    llama3_8b, llama3_70b, llama_tiny)
from .gpt import GPTConfig, GPTForCausalLM, GPTModel, gpt_tiny
from .bert import (BertConfig, BertForPretraining,
                   BertForSequenceClassification, BertModel, bert_tiny,
                   pretraining_loss)
from .ernie import (Ernie45MoeConfig, Ernie45MoeForCausalLM, ErnieConfig,
                    ErnieForMaskedLM, ErnieForSequenceClassification,
                    ErnieModel, ernie45_moe_tiny, ernie_tiny)
from .qwen2 import (Qwen2Config, Qwen2ForCausalLM, Qwen2Model, qwen2_7b,
                    qwen2_tiny)
from .deepseek_v2 import (DeepseekV2Config, DeepseekV2ForCausalLM,
                          DeepseekV2Model, deepseek_v2_tiny)
from .qwen2_moe import (DeepseekMoeConfig, DeepseekMoeForCausalLM,
                        Qwen2MoeConfig, Qwen2MoeForCausalLM, Qwen2MoeModel,
                        deepseek_moe_tiny, moe_lm_loss, qwen2_moe_tiny)
from .resnet import (ResNet, ResNetConfig, resnet18, resnet34, resnet50,
                     resnet50_vd, resnet_tiny)
from .vit import (ViTConfig, ViTForImageClassification, ViTModel, vit_tiny,
                  vit_base_patch16_224, vit_large_patch14_224)
from .clip import (CLIPConfig, CLIPModel, CLIPTextConfig, CLIPTextModel,
                   clip_contrastive_loss, clip_tiny, gather_features)
from .dit import (DiT, DiTConfig, MMDiT, MMDiTConfig, dit_tiny, dit_xl_2,
                  mmdit_tiny)
from .vae import (AutoencoderKL, DiagonalGaussian, VAEConfig, vae_loss,
                  vae_tiny)
from .ppocr import (DBNet, DBNetConfig, SVTRConfig, SVTRNet, ctc_greedy_decode,
                    ctc_rec_loss, db_loss, dbnet_tiny, svtr_tiny)
from .hf_interop import (config_from_hf, convert_hf_state_dict,
                         from_pretrained, load_hf_checkpoint,
                         to_hf_state_dict)
