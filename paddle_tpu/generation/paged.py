"""Paged KV cache + continuous batching (reference: PaddleNLP llm
predictor's block attention / paged KV serving path, vLLM's PagedAttention
scheduling).

TPU-native design — everything the XLA program sees is STATIC:

- The KV cache is a fixed pool of ``num_blocks`` physical blocks of
  ``block_size`` tokens per layer (``[P, B, kvh, d]``). A request owns a
  row of the ``[R, M]`` block table mapping its logical blocks to
  physical ones. Memory per request grows in block quanta, so one long
  request no longer pins a whole max-length buffer and the pool holds
  as many mixed-length requests as actually fit.
- One jitted ``decode_step`` advances EVERY active slot one token:
  per-row scatter-write of the new K/V into the row's current block,
  gather of the row's blocks ``kp[block_tables]``, masked attention up
  to each row's length. One jitted ``prefill`` per bucket writes a new
  request's prompt K/V into its blocks. Shapes never change, so both
  executables compile once per bucket.
- Scheduling (admission, block allocation, eviction) is HOST-side
  bookkeeping between jitted calls — numpy lists, no recompiles. New
  requests are admitted mid-decode the moment a slot and blocks free
  up: the bucketed Predictor's whole-batch barrier is gone.

Padded prompt positions scatter into a reserved GARBAGE block (physical
block 0) so they can never corrupt a live block; it is never allocated.
"""
from __future__ import annotations

from typing import Any, Dict, List, NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["PagedKV", "PagedEngine"]


class PagedKV(NamedTuple):
    """Per-layer paged cache view handed to the attention modules.

    kp/vp: [P, B, kvh, d] physical block pools (this layer's).
    block_tables: [R, M] physical block id per (slot, logical block).
    seq_lens: [R] tokens already cached per slot == this step's write
    position. Shared across layers; XLA dedups the copies.
    """
    kp: Any
    vp: Any
    block_tables: Any
    seq_lens: Any

    @property
    def block_size(self) -> int:
        return self.kp.shape[1]


def paged_decode_write(pk: PagedKV, k, v):
    """Scatter each row's single new K/V (k [R, 1, kvh, d]) into its
    current block at (seq_len // B, seq_len % B)."""
    B = pk.block_size
    r = jnp.arange(k.shape[0])
    bidx = pk.block_tables[r, pk.seq_lens // B]          # [R]
    boff = pk.seq_lens % B
    kp = pk.kp.at[bidx, boff].set(k[:, 0].astype(pk.kp.dtype))
    vp = pk.vp.at[bidx, boff].set(v[:, 0].astype(pk.vp.dtype))
    return pk._replace(kp=kp, vp=vp)


def paged_prefill_write(pk: PagedKV, k, v, garbage_block: int = 0):
    """Scatter a [1, s, kvh, d] prompt's K/V into row 0's blocks; pad
    positions (>= seq_lens[0]) go to the garbage block."""
    B = pk.block_size
    s = k.shape[1]
    pos = jnp.arange(s)
    live = pos < pk.seq_lens[0]
    bidx = jnp.where(live, pk.block_tables[0, pos // B], garbage_block)
    boff = pos % B
    kp = pk.kp.at[bidx, boff].set(k[0].astype(pk.kp.dtype))
    vp = pk.vp.at[bidx, boff].set(v[0].astype(pk.vp.dtype))
    return pk._replace(kp=kp, vp=vp)


def paged_decode_attention(q, pk: PagedKV, scale: Optional[float] = None,
                           window: Optional[int] = None):
    """q [R, 1, h, d] against each row's gathered blocks, masked to the
    row's length (inclusive of the token written this step). The math is
    dense_attention's — only the block gather and per-row length mask
    live here."""
    from ..ops.attention import dense_attention
    R = q.shape[0]
    kvh, d = pk.kp.shape[2], pk.kp.shape[3]
    ks = pk.kp[pk.block_tables]                  # [R, M, B, kvh, d]
    vs = pk.vp[pk.block_tables]
    T = ks.shape[1] * ks.shape[2]
    ks = ks.reshape(R, T, kvh, d)
    vs = vs.reshape(R, T, kvh, d)
    kpos = jnp.arange(T)[None, :]
    keep = kpos <= pk.seq_lens[:, None]
    if window is not None:
        keep &= kpos > pk.seq_lens[:, None] - window
    return dense_attention(q, ks, vs, attn_mask=keep[:, None, None, :],
                           scale=scale)


class _Slot:
    __slots__ = ("request_id", "prompt", "max_new", "eos", "tokens",
                 "blocks", "prefix", "admit_seq")

    def __init__(self, request_id, prompt, max_new, eos, prefix,
                 admit_seq):
        self.request_id = request_id
        self.prompt = prompt            # ids the prefill ran over
        self.max_new = max_new          # tokens still to emit
        self.eos = eos
        self.prefix = prefix            # tokens emitted before preemption
        self.admit_seq = admit_seq      # preemption picks the youngest
        self.tokens: List[int] = []
        self.blocks: List[int] = []


class PagedEngine:
    """Continuous-batching serving engine for Llama-family CausalLMs.

    submit() enqueues requests at any time; each step() admits what
    fits (slot + blocks), prefills at most one queued request, and
    advances every active slot one greedy token. Finished requests free
    their blocks immediately, so capacity recycles mid-stream instead
    of at batch boundaries (reference: PaddleNLP block-attention
    predictor; the bucketed ``Predictor`` keeps whole-batch semantics).
    """

    def __init__(self, model, max_slots: int = 8, num_blocks: int = 128,
                 block_size: int = 16, max_blocks_per_seq: int = 16,
                 prefill_buckets=(32, 64, 128)):
        cfg = model.config
        self.model = model
        self.fn, self.params = model.functional()
        self.R, self.P, self.B, self.M = (max_slots, num_blocks,
                                          block_size, max_blocks_per_seq)
        self.prefill_buckets = sorted(prefill_buckets)
        L = cfg.num_hidden_layers
        kvh, d = cfg.num_key_value_heads, cfg.head_dim
        self.pools = [(jnp.zeros((self.P, self.B, kvh, d), cfg.dtype),
                       jnp.zeros((self.P, self.B, kvh, d), cfg.dtype))
                      for _ in range(L)]
        # block 0 is the garbage block: pad scatter lands there
        self.free_blocks = list(range(1, self.P))
        self.block_tables = np.zeros((self.R, self.M), np.int32)
        self.seq_lens = np.zeros((self.R,), np.int32)
        self.slots: List[Optional[_Slot]] = [None] * self.R
        self.queue: List[tuple] = []
        self.results: Dict[Any, List[int]] = {}
        self._admit_counter = 0
        self.stats = {"decode_steps": 0, "prefills": 0, "preemptions": 0,
                      "slot_steps": 0, "active_slot_steps": 0}
        # pools are donated: XLA aliases input to output so a decode
        # step costs one scatter, not a full pool copy
        self._decode_jit = jax.jit(self._decode_step, donate_argnums=(1,))
        self._prefill_jit = jax.jit(self._prefill, donate_argnums=(1,),
                                    static_argnames=("bucket",))

    # ------------------------------------------------------------ jitted
    def _paged_caches(self, pools, tables, lens):
        return [PagedKV(kp, vp, tables, lens) for kp, vp in pools]

    def _decode_step(self, params, pools, tables, lens, last_tokens):
        caches = self._paged_caches(pools, tables, lens)
        logits, new_caches = self.fn(params, last_tokens[:, None],
                                     kv_caches=caches,
                                     positions=lens[:, None])
        nxt = jnp.argmax(logits[:, -1].astype(jnp.float32), axis=-1)
        return nxt.astype(jnp.int32), [(c.kp, c.vp) for c in new_caches]

    def _prefill(self, params, pools, table_row, ids, length, *,
                 bucket: int):
        tables = jnp.broadcast_to(table_row[None], (1, self.M))
        lens = jnp.asarray([length], jnp.int32)
        caches = self._paged_caches(pools, tables, lens)
        positions = jnp.arange(bucket)[None, :]
        logits, new_caches = self.fn(params, ids, kv_caches=caches,
                                     positions=positions)
        nxt = jnp.argmax(logits[0, length - 1].astype(jnp.float32))
        return nxt.astype(jnp.int32), [(c.kp, c.vp) for c in new_caches]

    # ------------------------------------------------------------- host
    def submit(self, request_id, input_ids, max_new_tokens: int = 32,
               eos_token_id: Optional[int] = None):
        if max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        ids = list(np.asarray(input_ids).reshape(-1))
        total = len(ids) + max_new_tokens
        if total > self.M * self.B:
            raise ValueError(f"request needs {total} tokens > "
                             f"max_blocks_per_seq*block_size "
                             f"{self.M * self.B}")
        if self._blocks_needed(total) > self.P - 1:
            raise ValueError("request alone exceeds the block pool")
        self.queue.append((request_id, ids, max_new_tokens, eos_token_id,
                           []))

    def _blocks_needed(self, n_tokens: int) -> int:
        return (n_tokens + self.B - 1) // self.B

    def _try_admit(self) -> bool:
        """Prefill ONE queued request into a free slot if blocks allow."""
        if not self.queue:
            return False
        rid, ids, max_new, eos, prefix = self.queue[0]
        try:
            slot_id = self.slots.index(None)
        except ValueError:
            return False
        need = self._blocks_needed(len(ids) + 1)
        if len(self.free_blocks) < need:
            return False
        self.queue.pop(0)
        self._admit_counter += 1
        slot = _Slot(rid, ids, max_new, eos, prefix, self._admit_counter)
        slot.blocks = [self.free_blocks.pop() for _ in range(need)]
        self.slots[slot_id] = slot
        row = np.zeros((self.M,), np.int32)
        row[:need] = slot.blocks
        self.block_tables[slot_id] = row

        bucket = next((b for b in self.prefill_buckets if b >= len(ids)),
                      None)
        if bucket is None:
            bucket = self.prefill_buckets[-1]
            while bucket < len(ids):
                bucket *= 2
        padded = np.zeros((1, bucket), np.int32)
        padded[0, :len(ids)] = ids
        nxt, self.pools = self._prefill_jit(
            self.params, self.pools, jnp.asarray(row),
            jnp.asarray(padded), np.int32(len(ids)), bucket=bucket)
        self.stats["prefills"] += 1
        first = int(nxt)
        slot.tokens.append(first)
        self.seq_lens[slot_id] = len(ids)
        if slot.max_new <= 1 or (slot.eos is not None
                                 and first == slot.eos):
            self._finish(slot_id)
        return True

    def _ensure_block(self, slot_id: int) -> bool:
        """The next decode writes at seq_lens[slot_id]; allocate the
        covering block if the row hasn't got it yet."""
        slot = self.slots[slot_id]
        need = self._blocks_needed(int(self.seq_lens[slot_id]) + 1)
        while len(slot.blocks) < need:
            if not self.free_blocks:
                return False
            b = self.free_blocks.pop()
            slot.blocks.append(b)
            self.block_tables[slot_id, len(slot.blocks) - 1] = b
        return True

    def _finish(self, slot_id: int):
        slot = self.slots[slot_id]
        self.results[slot.request_id] = slot.prefix + slot.tokens
        self._release(slot_id)

    def _release(self, slot_id: int):
        self.free_blocks.extend(self.slots[slot_id].blocks)
        self.block_tables[slot_id] = 0
        self.seq_lens[slot_id] = 0
        self.slots[slot_id] = None

    def _preempt_youngest(self, exclude: int) -> bool:
        """Memory pressure: requeue the most recently admitted OTHER
        request (vLLM's recompute-mode preemption — its emitted tokens
        fold into the prompt, so the re-prefill rebuilds the same KV
        deterministically and the output stays exact)."""
        cands = [i for i, s in enumerate(self.slots)
                 if s is not None and i != exclude]
        if not cands:
            return False
        victim = max(cands, key=lambda i: self.slots[i].admit_seq)
        s = self.slots[victim]
        self.queue.insert(0, (
            s.request_id, s.prompt + s.tokens,
            s.max_new - len(s.tokens), s.eos,
            s.prefix + s.tokens))
        self._release(victim)
        self.stats["preemptions"] += 1
        return True

    def step(self):
        """One scheduler tick: admit, then one decode for all slots."""
        self._try_admit()
        for i in range(self.R):
            if self.slots[i] is None:
                continue
            while not self._ensure_block(i):
                if not self._preempt_youngest(exclude=i):
                    raise RuntimeError(
                        "paged KV pool cannot hold even one request; "
                        "raise num_blocks")
        active = [i for i, s in enumerate(self.slots) if s is not None]
        if not active:
            return
        last = np.zeros((self.R,), np.int32)
        for i in active:
            last[i] = self.slots[i].tokens[-1]
        nxt, self.pools = self._decode_jit(
            self.params, self.pools, jnp.asarray(self.block_tables),
            jnp.asarray(self.seq_lens), jnp.asarray(last))
        nxt = np.asarray(nxt)
        self.stats["decode_steps"] += 1
        self.stats["slot_steps"] += self.R
        self.stats["active_slot_steps"] += len(active)
        for i in active:
            slot = self.slots[i]
            self.seq_lens[i] += 1   # the decode wrote last token's K/V
            tok = int(nxt[i])
            slot.tokens.append(tok)
            done = len(slot.tokens) >= slot.max_new or \
                (slot.eos is not None and tok == slot.eos)
            if done:
                # the final token's K/V was never written - fine, it is
                # never attended to
                self._finish(i)
        return True

    def run(self) -> Dict[Any, List[int]]:
        """Drive until queue and slots drain; returns request_id ->
        generated token list (prompt excluded)."""
        while self.queue or any(s is not None for s in self.slots):
            self.step()
        return dict(self.results)
