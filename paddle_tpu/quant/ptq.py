"""Post-training quantization with activation calibration (reference:
python/paddle/quantization — PTQ with AbsmaxObserver/HistObserver +
paddlenlp llm PTQ recipes: A8W8 smooth/static quantization).

TPU-native: calibration is a host-side pass (forward hooks record
activation statistics over calibration batches — nothing enters the
jitted graph), then ``convert`` swaps each observed Linear for a
``W8A8Linear`` whose forward fake-quantizes activations with the FROZEN
calibrated scale and runs the int8-weight matmul. The resulting model is
still a pure jnp program: XLA folds the static scales into the
surrounding ops, and bf16/int8 tensors stream at half/quarter HBM cost.
"""
from __future__ import annotations

from typing import Dict, List, Optional

import jax.numpy as jnp

from ..nn.layer import Layer
from .weight_only import QuantizedLinear

__all__ = ["AbsMaxObserver", "PTQ", "W8A8Linear"]


class AbsMaxObserver:
    """Running abs-max over calibration batches (reference:
    paddle.quantization.observers.AbsmaxObserver). ``ema`` smooths
    outliers the way the reference's EMA observer does."""

    def __init__(self, ema: float = 0.0):
        self.ema = ema
        self.stat: Optional[float] = None

    def update(self, x):
        cur = float(jnp.max(jnp.abs(x.astype(jnp.float32))))
        if self.stat is None or self.ema == 0.0:
            self.stat = cur if self.stat is None else max(self.stat, cur)
        else:
            self.stat = self.ema * self.stat + (1 - self.ema) * cur

    def scale(self) -> float:
        return max(self.stat or 0.0, 1e-8) / 127.0


class W8A8Linear(QuantizedLinear):
    """int8 weights + int8-fake-quantized activations with a frozen
    calibrated scale (reference: paddlenlp llm A8W8). Subclasses
    QuantizedLinear, so the TP contracts (qweight/scales partitions,
    Column/Row activation constraints) and frozen-bias semantics carry
    over unchanged."""

    def __init__(self, *args, act_scale: float = 1.0, **kw):
        super().__init__(*args, **kw)
        self.act_scale = float(act_scale)

    def forward(self, x):
        # activation fake-quant with the FROZEN calibration scale: the
        # rounding happens at trace time as pure ops, so serving keeps
        # one static program
        s = jnp.asarray(self.act_scale, jnp.float32)
        xq = (jnp.clip(jnp.round(x.astype(jnp.float32) / s), -127, 127)
              * s).astype(x.dtype)
        return super().forward(xq)

    def extra_repr(self):
        return (f"{super().extra_repr()}, "
                f"A8 act_scale={self.act_scale:.3g}")


class PTQ:
    """Calibrate-then-convert driver (reference: paddle.quantization.PTQ).

    ptq = PTQ(model)                      # hooks every Linear-family layer
    for batch in calib_data: model(batch) # observers record abs-max
    ptq.convert()                         # swap in W8A8Linear, drop hooks
    """

    def __init__(self, model: Layer, ema: float = 0.0,
                 skip: Optional[List[str]] = None):
        from ..nn.common import Linear
        from ..parallel.layers import ColumnParallelLinear, RowParallelLinear
        self.model = model
        self.observers: Dict[str, AbsMaxObserver] = {}
        self._hooked = []
        skip = tuple(skip or ())
        for path, sub in model.named_sublayers():
            if not isinstance(sub, (Linear, ColumnParallelLinear,
                                    RowParallelLinear)):
                continue
            if path.startswith(skip) or any(s in path for s in skip):
                continue
            obs = AbsMaxObserver(ema=ema)
            self.observers[path] = obs
            hid = sub.register_forward_pre_hook(
                lambda layer, args, _obs=obs: _obs.update(args[0]) or None)
            self._hooked.append((path, sub, hid))
        if not self.observers:
            raise ValueError("no Linear-family layers to calibrate")

    def convert(self, bits: int = 8, block_size: int = 128) -> Layer:
        """Swap calibrated layers for W8A8Linear in place; remove hooks."""
        uncalibrated = [p for p, o in self.observers.items()
                        if o.stat is None]
        if uncalibrated:
            raise RuntimeError(
                f"run calibration batches first; no activations seen for "
                f"{uncalibrated[:4]}")
        for path, sub, hid in self._hooked:
            del sub._forward_pre_hooks[hid]
            parent = self.model
            parts = path.split(".")
            for p in parts[:-1]:
                parent = parent._sub_layers[p]
            din = sub.weight.shape[0]
            bs = block_size if din % block_size == 0 else din
            lay = W8A8Linear.from_linear(sub, bits=bits, block_size=bs)
            lay.act_scale = self.observers[path].scale()
            parent._sub_layers[parts[-1]] = lay
        self._hooked = []
        return self.model
