"""Diffusion pipelines (reference: PaddleMIX ppdiffusers/pipelines —
pipeline_dit.py DiTPipeline, pipeline_stable_diffusion_3.py
StableDiffusion3Pipeline).

TPU-native design: a pipeline is a thin orchestrator whose entire
denoising loop is ONE jitted program — `schedulers.sample_loop`'s
`lax.scan` is the single implementation of the reverse process, and
classifier-free guidance is a model_fn wrapper that doubles the batch so
the conditional/unconditional passes share every matmul. No per-step host
round trips.

Jit engines are cached per pipeline INSTANCE (keyed by step count and the
current scheduler), so dropping a pipeline frees its weights and swapping
`pipe.scheduler` takes effect on the next call.
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from ..models.dit import DiT, MMDiT
from ..models.vae import AutoencoderKL
from .schedulers import DDIMScheduler, FlowMatchScheduler, sample_loop


class _PipelineBase:
    def __init__(self, backbone, vae):
        self.vae = vae
        self._fn, self._params = backbone.functional()
        self._engines = {}
        if vae is not None:
            vae.eval()

    def _engine(self, num_steps: int, build):
        key = (num_steps, id(self.scheduler))
        if key not in self._engines:
            self._engines[key] = jax.jit(build(num_steps))
        return self._engines[key]

    def _decode(self, latents):
        if self.vae is None:
            return latents
        return self.vae.decode(latents / self.vae.config.scaling_factor)


class DiTPipeline(_PipelineBase):
    """Class-conditional latent diffusion with a DiT backbone
    (reference: ppdiffusers DiTPipeline: DiT + AutoencoderKL + DDIM)."""

    def __init__(self, dit: DiT, vae: Optional[AutoencoderKL] = None,
                 scheduler: Optional[DDIMScheduler] = None):
        super().__init__(dit, vae)
        self.dit = dit
        self.scheduler = scheduler or DDIMScheduler(num_train_timesteps=1000)

    def __call__(self, class_labels, num_inference_steps: int = 50,
                 guidance_scale: float = 4.0, key=None):
        """Returns decoded images [b, c, h, w] (latents if no VAE)."""
        key = key if key is not None else jax.random.PRNGKey(0)
        labels = jnp.asarray(class_labels)

        def build(n_steps):
            def sampler(params, labels, cfg_scale, key):
                cfg = self.dit.config
                b = labels.shape[0]
                shape = (b, cfg.in_channels, cfg.input_size, cfg.input_size)
                labels2 = jnp.concatenate([labels, labels])
                null_mask = jnp.concatenate(
                    [jnp.zeros(b, bool), jnp.ones(b, bool)])

                def model_fn(x, t):
                    out = self._fn(params, jnp.concatenate([x, x]),
                                   jnp.concatenate([t, t]), labels2,
                                   null_mask)
                    eps = out[:, :cfg.in_channels]   # drop learned sigma
                    cond, uncond = eps[:b], eps[b:]
                    return uncond + cfg_scale * (cond - uncond)

                return sample_loop(self.scheduler, model_fn, shape,
                                   n_steps, key)
            return sampler

        latents = self._engine(num_inference_steps, build)(
            self._params, labels, jnp.float32(guidance_scale), key)
        return self._decode(latents)


class StableDiffusion3Pipeline(_PipelineBase):
    """SD3-style text-to-image: MMDiT + flow matching + VAE (reference:
    ppdiffusers StableDiffusion3Pipeline). Text encoders are pluggable —
    pass precomputed (context, pooled) embeddings, the way the reference's
    pipeline separates encode_prompt from the denoise loop."""

    def __init__(self, mmdit: MMDiT, vae: Optional[AutoencoderKL] = None,
                 scheduler: Optional[FlowMatchScheduler] = None):
        super().__init__(mmdit, vae)
        self.mmdit = mmdit
        self.scheduler = scheduler or FlowMatchScheduler(shift=3.0)

    def __call__(self, context, pooled, neg_context=None, neg_pooled=None,
                 num_inference_steps: int = 28, guidance_scale: float = 7.0,
                 key=None):
        key = key if key is not None else jax.random.PRNGKey(0)
        if neg_context is None:
            neg_context = jnp.zeros_like(context)
        if neg_pooled is None:
            neg_pooled = jnp.zeros_like(pooled)

        def build(n_steps):
            def sampler(params, ctx, pool, nctx, npool, cfg_scale, key):
                cfg = self.mmdit.config
                b = ctx.shape[0]
                shape = (b, cfg.in_channels, cfg.input_size, cfg.input_size)
                ctx2 = jnp.concatenate([ctx, nctx])
                pool2 = jnp.concatenate([pool, npool])

                def model_fn(x, t):
                    v = self._fn(params, jnp.concatenate([x, x]),
                                 jnp.concatenate([t, t]), ctx2, pool2)
                    cond, uncond = v[:b], v[b:]
                    return uncond + cfg_scale * (cond - uncond)

                return sample_loop(self.scheduler, model_fn, shape,
                                   n_steps, key)
            return sampler

        latents = self._engine(num_inference_steps, build)(
            self._params, context, pooled, neg_context, neg_pooled,
            jnp.float32(guidance_scale), key)
        return self._decode(latents)
