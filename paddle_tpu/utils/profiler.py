"""Profiling (reference: paddle.profiler.Profiler — scheduler, timer_only
mode, chrome-trace export).

TPU-native: wraps `jax.profiler` (perfetto/xplane traces viewable in
tensorboard or perfetto.dev) and adds the numbers people actually watch in
training loops: step time, tokens/sec, and MFU against the chip's peak."""
from __future__ import annotations

import contextlib
import sys
import time
from dataclasses import dataclass, field
from typing import Optional

import jax

PEAK_BF16_FLOPS = {
    "TPU v4": 275e12,
    "TPU v5 lite": 197e12,
    "TPU v5e": 197e12,
    "TPU v5p": 459e12,
    "TPU v6 lite": 918e12,
}


def device_peak_flops(device=None) -> float:
    device = device or jax.devices()[0]
    return PEAK_BF16_FLOPS.get(getattr(device, "device_kind", ""), 197e12)


def local_peak_flops() -> float:
    """Aggregate peak of every local chip. The trainer's token counts
    span the whole per-process batch (all local mesh devices), so MFU
    must divide by the matching aggregate peak — a single chip's peak
    would overstate it by the local device count."""
    return sum(device_peak_flops(d) for d in jax.local_devices())


# jax.profiler supports ONE live trace per process; the owner lets
# stop() know whether this instance actually holds it
_trace_owner: Optional["Profiler"] = None


class Profiler:
    """paddle.profiler.Profiler-shaped facade over jax.profiler."""

    def __init__(self, logdir: str = "runs/profile", timer_only: bool = False):
        self.logdir = logdir
        self.timer_only = timer_only
        self._active = False

    def start(self):
        """Idempotent: a second ``start()`` on a live profiler — or a
        ``start()`` while ANOTHER profiler's trace is still open — warns
        and returns instead of surfacing jax.profiler's raw "trace
        already started" error mid-run."""
        global _trace_owner
        if self._active:
            print("[profiler] start() called on an already-active "
                  "profiler; ignoring", file=sys.stderr, flush=True)
            return
        if not self.timer_only:
            if _trace_owner is not None:
                print(f"[profiler] a trace is already running "
                      f"(logdir={_trace_owner.logdir}); start() falls "
                      f"back to timer-only for this profiler",
                      file=sys.stderr, flush=True)
            else:
                jax.profiler.start_trace(self.logdir)
                _trace_owner = self
        self._active = True

    def stop(self):
        global _trace_owner
        if self._active and _trace_owner is self:
            try:
                jax.profiler.stop_trace()
            finally:
                # release the latch even when stop_trace() raises: the
                # jax trace is in an unknown state either way, but a
                # held latch would wedge every future profiler in this
                # process into timer-only fallback
                _trace_owner = None
        self._active = False

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.stop()


@contextlib.contextmanager
def annotate(name: str):
    """Trace annotation visible in the profile (reference:
    paddle.profiler.RecordEvent)."""
    with jax.profiler.TraceAnnotation(name):
        yield


@dataclass
class StepTimer:
    """Running step-time / throughput / MFU meter."""
    flops_per_token: float = 0.0
    peak_flops: float = field(default_factory=local_peak_flops)
    _t0: Optional[float] = None
    steps: int = 0
    total_s: float = 0.0
    total_tokens: int = 0

    def start(self):
        self._t0 = time.perf_counter()

    def stop(self, tokens: int = 0, steps: int = 1):
        """Close a timing window covering ``steps`` training steps (the
        trainer logs once per ``logging_steps`` window, so per-step
        averages need the real step count, not the window count)."""
        if self._t0 is None:
            raise RuntimeError(
                "StepTimer.stop() called with no open window; call "
                "start() first")
        dt = time.perf_counter() - self._t0
        self._t0 = None          # window closed; a second stop() raises
        self.steps += steps
        self.total_s += dt
        self.total_tokens += tokens
        return dt

    @property
    def avg_step_s(self) -> float:
        return self.total_s / max(self.steps, 1)

    @property
    def tokens_per_sec(self) -> float:
        return self.total_tokens / max(self.total_s, 1e-9)

    @property
    def mfu(self) -> float:
        if not self.flops_per_token:
            return 0.0
        return self.flops_per_token * self.tokens_per_sec / self.peak_flops


def llama_flops_per_token(n_params: int, num_layers: int, seq_len: int,
                          hidden: int) -> float:
    """6N matmul + causal-attention term (fwd+bwd)."""
    return 6.0 * n_params + 6.0 * num_layers * seq_len * hidden
