"""Logits processors for autoregressive decoding (reference: PaddleNLP
paddlenlp/generation/logits_process.py — TopKProcess, TopPProcess,
temperature, repetition penalty).

All processors are pure jnp on static shapes so the whole decode loop
compiles into one XLA program (`lax.while_loop`), never re-tracing per
token. Filtering uses mask-to--inf (no dynamic shapes from sorting)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

NEG_INF = -1e30


def apply_temperature(logits, temperature):
    t = jnp.maximum(jnp.asarray(temperature, jnp.float32), 1e-6)
    return logits / t


def top_k_filter(logits, k: int):
    """Keep the k highest logits per row; mask the rest to -inf. Static k."""
    if k <= 0:
        return logits
    kth = jax.lax.top_k(logits, k)[0][..., -1:]
    return jnp.where(logits < kth, NEG_INF, logits)


def top_p_filter(logits, p: float):
    """Nucleus sampling: keep the smallest prefix of the sorted distribution
    with cumulative prob >= p (always keeps the argmax)."""
    sorted_logits = jnp.sort(logits, axis=-1)[..., ::-1]
    probs = jax.nn.softmax(sorted_logits, axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    # mask sorted positions whose *previous* cumulative already reached p
    keep_sorted = (cum - probs) < p
    # threshold = smallest kept logit
    thresh = jnp.min(jnp.where(keep_sorted, sorted_logits, jnp.inf),
                     axis=-1, keepdims=True)
    return jnp.where(logits < thresh, NEG_INF, logits)


def repetition_penalty(logits, generated_mask, penalty: float):
    """Divide (positive) / multiply (negative) logits of seen tokens
    (generated_mask [b, vocab] counts>0)."""
    if penalty == 1.0:
        return logits
    seen = generated_mask > 0
    penalized = jnp.where(logits > 0, logits / penalty, logits * penalty)
    return jnp.where(seen, penalized, logits)


def filter_logits_rows(logits, temperature, top_k, top_p):
    """Per-row temperature / top-k / top-p filtering on [R, V] fp32
    logits with TRACED per-row params (k <= 0 / p >= 1 disable) —
    the processor half of :func:`sample_token_rows`, factored out so
    the rejection-sampled speculative verify
    (:func:`residual_resample_rows`) filters each verify position with
    EXACTLY the ops the plain sampled tick uses. Returns the filtered
    logits (kept entries divided by temperature, rest NEG_INF)."""
    raw = logits.astype(jnp.float32)
    V = raw.shape[-1]
    temperature = jnp.asarray(temperature, jnp.float32)
    top_k = jnp.asarray(top_k, jnp.int32)
    top_p = jnp.asarray(top_p, jnp.float32)
    lt = raw / jnp.maximum(temperature, 1e-6)[:, None]
    # per-row top-k: k-th largest value as threshold (k <= 0: keep all)
    sd = jnp.sort(lt, axis=-1)[..., ::-1]
    kth = jnp.take_along_axis(
        sd, jnp.clip(top_k - 1, 0, V - 1)[:, None], axis=-1)
    lt = jnp.where((top_k[:, None] > 0) & (lt < kth), NEG_INF, lt)
    # the top-k-filtered logits in sorted order, derived from the ONE
    # sort: rank >= k is masked (ties at the k-th value are all kept by
    # the filter above but counted once in the top-p cumsum)
    rank = jnp.arange(V)[None, :]
    sd2 = jnp.where((top_k[:, None] <= 0) | (rank < top_k[:, None]),
                    sd, NEG_INF)
    probs = jax.nn.softmax(sd2, axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    keep_sorted = (cum - probs) < top_p[:, None]   # always keeps argmax
    thresh = jnp.min(jnp.where(keep_sorted, sd2, jnp.inf), axis=-1,
                     keepdims=True)
    return jnp.where((top_p[:, None] < 1.0) & (lt < thresh), NEG_INF, lt)


def sample_token_rows(logits, keys, temperature, top_k, top_p):
    """Per-ROW sampling for continuous batching: every parameter is an
    array over rows, so one jitted decode step serves a mixed stream of
    greedy and sampled requests (reference: PaddleNLP llm predictor's
    per-request sampling config).

    logits [R, V] (raw); keys [R, 2] uint32 per-row PRNG states;
    temperature [R] f32 (<= 0 means greedy — BIT-exact argmax of the raw
    fp32 logits, the same op the all-greedy step used); top_k [R] i32
    (<= 0 disables); top_p [R] f32 (>= 1 disables). Unlike the static
    processors above, k and p are traced values: top-k thresholds via
    take_along_axis on the sorted row, not lax.top_k.

    Returns (tokens [R] i32, logprobs [R] f32, new_keys [R, 2]).
    Logprobs are of the CHOSEN token under the unfiltered softmax (what
    serving APIs report), greedy rows included."""
    raw = logits.astype(jnp.float32)
    temperature = jnp.asarray(temperature, jnp.float32)
    lt = filter_logits_rows(raw, temperature, top_k, top_p)

    keys = jnp.asarray(keys, jnp.uint32)
    pairs = jax.vmap(lambda k: jax.random.split(
        jax.random.wrap_key_data(k, impl="threefry2x32")))(keys)
    carry = jax.vmap(jax.random.key_data)(pairs[:, 0])
    sampled = jax.vmap(
        lambda k, l: jax.random.categorical(k, l))(pairs[:, 1], lt)
    tokens = jnp.where(temperature <= 0.0,
                       jnp.argmax(raw, axis=-1), sampled).astype(jnp.int32)
    logprobs = jnp.take_along_axis(jax.nn.log_softmax(raw, axis=-1),
                                   tokens[:, None].astype(jnp.int32),
                                   axis=-1)[:, 0]
    return tokens, logprobs, carry


def seed_key_row(seed: int):
    """The [2] uint32 raw key data for ONE row's PRNG stream, seeded by
    ``seed`` — the row-scoped key init shared by ``PagedEngine.submit``
    and the delta-transition descriptor packing (ISSUE 14): an admitted
    row's device key is byte-identical whether it rides a full mirror
    rebuild or a one-row patch, because both start from this value."""
    import numpy as np
    return np.asarray(jax.random.key_data(jax.random.PRNGKey(seed)),
                      np.uint32)


def override_key_rows(keys, rows, new_keys, flags):
    """Scatter per-row PRNG key OVERRIDES into the [R, 2] uint32 key
    state: row ``rows[j]`` takes ``new_keys[j]`` iff ``flags[j] != 0``;
    every other row keeps its current (device) stream untouched. The
    key-override rule of the delta-transition descriptors (ISSUE 14),
    shared by the one-row patch program and the fused patch-queue
    scatter (ISSUE 19) so the two transition paths cannot drift: a key
    is authoritative only when the HOST re-keyed the row (fresh admit,
    chunk-final) — for every other descriptor the device key stream,
    possibly advanced by sampled ticks since the last upload, must
    survive the patch. Non-override (and out-of-range padding) rows
    are routed to the out-of-bounds index R and dropped by the
    scatter, which also makes the all-masked case a bitwise no-op —
    the property that lets the fused scatter ride EVERY tick."""
    keys = jnp.asarray(keys, jnp.uint32)
    R = keys.shape[0]
    target = jnp.where(jnp.asarray(flags) != 0,
                       jnp.asarray(rows, jnp.int32), R)
    return keys.at[target].set(jnp.asarray(new_keys, jnp.uint32),
                               mode="drop")


def split_key_rows(keys):
    """Advance [R, 2] uint32 per-row PRNG states one split: returns
    (carry [R, 2], sub [R, 2]) raw key data. The carry chain is the
    same one :func:`sample_token_rows` advances — one split per tick —
    so a rejection-sampled speculative tick consumes the row stream at
    the same rate as the plain sampled tick."""
    pairs = jax.vmap(lambda k: jax.random.split(
        jax.random.wrap_key_data(k, impl="threefry2x32")))(
        jnp.asarray(keys, jnp.uint32))
    carry = jax.vmap(jax.random.key_data)(pairs[:, 0])
    sub = jax.vmap(jax.random.key_data)(pairs[:, 1])
    return carry, sub


def fold_in_rows(keys, j):
    """fold_in over [R, 2] raw key data: the per-position subkey
    derivation of the rejection-sampled verify (position j of a tick's
    sub key)."""
    return jax.vmap(lambda k: jax.random.key_data(jax.random.fold_in(
        jax.random.wrap_key_data(k, impl="threefry2x32"), j)))(
        jnp.asarray(keys, jnp.uint32))


def residual_resample_rows(logits, draft, keys, temperature, top_k,
                           top_p):
    """ONE verify position of rejection-sampled speculative decoding
    with a DETERMINISTIC (one-hot) draft distribution, row-batched
    (Leviathan et al. 2023, specialized: the draft proposes token d
    with probability 1, so accept happens with prob p(d) and the
    residual norm(max(0, p - q)) is p with d removed, renormalized).

    logits [R, V] fp32 — the SAME (penalty-applied, unfiltered) logits
    the plain tick would hand to :func:`sample_token_rows`; draft [R]
    i32 proposed token ids (< 0 = no draft for this row/position: the
    accept test always fails and the residual is the full filtered
    distribution — i.e. a plain sample); keys [R, 2] uint32
    PER-POSITION subkeys (callers fold the row's tick key by position,
    :func:`fold_in_rows`); temperature/top_k/top_p as
    :func:`sample_token_rows`. Rows with temperature <= 0 are greedy:
    token = argmax(logits), accepted = (token == draft) — exactly the
    longest-argmax-prefix rule the greedy speculative tick pins
    bitwise, no RNG consumed.

    Returns (tokens [R] i32, accepted [R] bool, logprobs [R] f32 of
    the chosen token under the unfiltered softmax of ``logits``).

    Distribution preservation (the reason sampled rows may ride
    speculative ticks at all): with p the filtered per-row
    distribution and q = onehot(d),
    P(emit y) = p(d)·[y==d] + (1-p(d)) · p(y)·[y!=d] / (1-p(d)) = p(y)
    — every position's marginal equals the plain tick's, whatever the
    drafter proposed (pinned statistically in tests/test_ring_spec.py).
    """
    raw = logits.astype(jnp.float32)
    R, V = raw.shape
    temperature = jnp.asarray(temperature, jnp.float32)
    d = jnp.asarray(draft, jnp.int32)
    dc = jnp.clip(d, 0, V - 1)
    has = d >= 0
    lt = filter_logits_rows(raw, temperature, top_k, top_p)
    keys = jnp.asarray(keys, jnp.uint32)
    pairs = jax.vmap(lambda k: jax.random.split(
        jax.random.wrap_key_data(k, impl="threefry2x32")))(keys)
    # accept test: u < p(draft) under the FILTERED distribution
    u = jax.vmap(lambda k: jax.random.uniform(k))(pairs[:, 0])
    p_d = jnp.take_along_axis(jax.nn.softmax(lt, axis=-1),
                              dc[:, None], axis=-1)[:, 0]
    acc_s = has & (u < p_d)
    # residual: mask the draft token to -inf; categorical renormalizes
    lt_res = jnp.where((jnp.arange(V)[None, :] == dc[:, None])
                       & has[:, None], NEG_INF, lt)
    res = jax.vmap(lambda k, l: jax.random.categorical(k, l))(
        pairs[:, 1], lt_res)
    samp = jnp.where(acc_s, dc, res).astype(jnp.int32)
    g = jnp.argmax(raw, axis=-1).astype(jnp.int32)
    greedy = temperature <= 0.0
    tokens = jnp.where(greedy, g, samp)
    accepted = jnp.where(greedy, has & (g == d), acc_s)
    logprobs = jnp.take_along_axis(jax.nn.log_softmax(raw, axis=-1),
                                   tokens[:, None], axis=-1)[:, 0]
    return tokens, accepted, logprobs


def sample_token(logits, key, temperature=1.0, top_k=0, top_p=1.0,
                 do_sample=True):
    """logits [b, vocab] -> token ids [b]."""
    logits = logits.astype(jnp.float32)
    if not do_sample:
        return jnp.argmax(logits, axis=-1)
    logits = apply_temperature(logits, temperature)
    if top_k and top_k > 0:
        logits = top_k_filter(logits, top_k)
    if top_p < 1.0:
        logits = top_p_filter(logits, top_p)
    return jax.random.categorical(key, logits, axis=-1)


def suffix_window_hits(seq, cur, g):
    """[L] bool: window ``seq[p : p+g]`` equals the last ``g`` committed
    tokens ``seq[cur-g : cur]``, restricted to windows STRICTLY earlier
    than that suffix. Shared match kernel for n-gram drafting
    (speculative prompt-lookup) and no-repeat-ngram banning — O(L*g)
    integer compares on static shapes. ``g == 0`` matches every
    committed position (the degenerate 1-gram case)."""
    L = seq.shape[0]
    last = jax.lax.dynamic_slice(seq, (jnp.maximum(cur - g, 0),), (g,))
    starts = jnp.arange(L)
    win = seq[jnp.clip(starts[:, None] + jnp.arange(g)[None, :],
                       0, L - 1)]                           # [L, g]
    hit = jnp.all(win == last[None, :], axis=1)
    return hit & (starts <= cur - g - 1) & (cur >= g)


def repetition_penalty_rows(logits, seen, penalties):
    """Per-ROW repetition penalty for continuous batching: logits
    [R, V], seen [R, V] bool membership of each row's running sequence,
    penalties [R] (1.0 = off). Rows at 1.0 pass through BIT-exactly
    (jnp.where with a false mask), preserving the engine's greedy
    exactness guarantee."""
    p = jnp.asarray(penalties, jnp.float32)[:, None]
    pen = jnp.where(logits > 0, logits / p, logits * p)
    return jnp.where(seen & (p != 1.0), pen, logits)
