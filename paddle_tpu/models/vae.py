"""AutoencoderKL (reference: PaddleMIX ppdiffusers/models/autoencoder_kl.py
— the SD/SD3 latent VAE: GroupNorm+SiLU resnet stacks, spatial attention
mid-block, diagonal-Gaussian posterior).

TPU-native design: NCHW convs lowered via lax (implicit MXU GEMMs); the
spatial attention block flattens H*W into a token axis and calls the same
``dense_attention`` primitive as the transformers, so XLA fuses QKV into
one matmul. Sampling uses an explicit key (no global RNG state under jit).
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, List, Optional

import jax
import jax.numpy as jnp

from .. import nn
from ..nn import functional as F
from ..nn.layer import Layer
from ..ops.attention import dense_attention


@dataclass
class VAEConfig:
    in_channels: int = 3
    latent_channels: int = 4
    base_channels: int = 128
    channel_multipliers: List[int] = field(default_factory=lambda: [1, 2, 4, 4])
    layers_per_block: int = 2
    norm_groups: int = 32
    scaling_factor: float = 0.18215   # SD1/2 latent scale
    dtype: Any = jnp.float32


def vae_tiny(**overrides) -> VAEConfig:
    base = dict(base_channels=16, channel_multipliers=[1, 2],
                layers_per_block=1, norm_groups=4, latent_channels=4)
    base.update(overrides)
    return VAEConfig(**base)


class ResnetBlock(Layer):
    def __init__(self, in_ch: int, out_ch: int, groups: int):
        super().__init__()
        self.norm1 = nn.GroupNorm(groups, in_ch)
        self.conv1 = nn.Conv2D(in_ch, out_ch, 3, padding=1)
        self.norm2 = nn.GroupNorm(groups, out_ch)
        self.conv2 = nn.Conv2D(out_ch, out_ch, 3, padding=1)
        self.short = nn.Conv2D(in_ch, out_ch, 1) if in_ch != out_ch else None

    def forward(self, x):
        h = self.conv1(F.silu(self.norm1(x)))
        h = self.conv2(F.silu(self.norm2(h)))
        s = self.short(x) if self.short is not None else x
        return s + h


class AttnBlock(Layer):
    """Single-head spatial self-attention over flattened H*W tokens."""

    def __init__(self, channels: int, groups: int):
        super().__init__()
        self.norm = nn.GroupNorm(groups, channels)
        self.qkv = nn.Linear(channels, 3 * channels)
        self.proj = nn.Linear(channels, channels)

    def forward(self, x):
        b, c, h, w = x.shape
        t = self.norm(x).reshape(b, c, h * w).transpose(0, 2, 1)
        qkv = self.qkv(t).reshape(b, h * w, 3, 1, c)
        out = dense_attention(qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2],
                              causal=False)
        out = self.proj(out.reshape(b, h * w, c))
        return x + out.transpose(0, 2, 1).reshape(b, c, h, w)


class Downsample(Layer):
    def __init__(self, channels: int):
        super().__init__()
        self.conv = nn.Conv2D(channels, channels, 3, stride=2, padding=1)

    def forward(self, x):
        return self.conv(x)


class Upsample(Layer):
    def __init__(self, channels: int):
        super().__init__()
        self.conv = nn.Conv2D(channels, channels, 3, padding=1)

    def forward(self, x):
        x = F.interpolate(x, scale_factor=2, mode="nearest")
        return self.conv(x)


class Encoder(Layer):
    def __init__(self, cfg: VAEConfig):
        super().__init__()
        g = cfg.norm_groups
        ch = cfg.base_channels
        self.conv_in = nn.Conv2D(cfg.in_channels, ch, 3, padding=1)
        downs = []
        in_ch = ch
        for i, mult in enumerate(cfg.channel_multipliers):
            out_ch = ch * mult
            for _ in range(cfg.layers_per_block):
                downs.append(ResnetBlock(in_ch, out_ch, g))
                in_ch = out_ch
            if i != len(cfg.channel_multipliers) - 1:
                downs.append(Downsample(in_ch))
        self.down = nn.Sequential(*downs)
        self.mid = nn.Sequential(ResnetBlock(in_ch, in_ch, g),
                                 AttnBlock(in_ch, g),
                                 ResnetBlock(in_ch, in_ch, g))
        self.norm_out = nn.GroupNorm(g, in_ch)
        self.conv_out = nn.Conv2D(in_ch, 2 * cfg.latent_channels, 3, padding=1)

    def forward(self, x):
        h = self.mid(self.down(self.conv_in(x)))
        return self.conv_out(F.silu(self.norm_out(h)))  # [b, 2*zc, h', w']


class Decoder(Layer):
    def __init__(self, cfg: VAEConfig):
        super().__init__()
        g = cfg.norm_groups
        ch = cfg.base_channels
        in_ch = ch * cfg.channel_multipliers[-1]
        self.conv_in = nn.Conv2D(cfg.latent_channels, in_ch, 3, padding=1)
        self.mid = nn.Sequential(ResnetBlock(in_ch, in_ch, g),
                                 AttnBlock(in_ch, g),
                                 ResnetBlock(in_ch, in_ch, g))
        ups = []
        for i, mult in enumerate(reversed(cfg.channel_multipliers)):
            out_ch = ch * mult
            for _ in range(cfg.layers_per_block + 1):
                ups.append(ResnetBlock(in_ch, out_ch, g))
                in_ch = out_ch
            if i != len(cfg.channel_multipliers) - 1:
                ups.append(Upsample(in_ch))
        self.up = nn.Sequential(*ups)
        self.norm_out = nn.GroupNorm(g, in_ch)
        self.conv_out = nn.Conv2D(in_ch, cfg.in_channels, 3, padding=1)

    def forward(self, z):
        h = self.up(self.mid(self.conv_in(z)))
        return self.conv_out(F.silu(self.norm_out(h)))


class DiagonalGaussian:
    """Posterior q(z|x); moments split from the encoder output."""

    def __init__(self, moments):
        self.mean, logvar = jnp.split(moments, 2, axis=1)
        self.logvar = jnp.clip(logvar, -30.0, 20.0)
        self.std = jnp.exp(0.5 * self.logvar)

    def sample(self, key):
        return self.mean + self.std * jax.random.normal(
            key, self.mean.shape, self.mean.dtype)

    def kl(self):
        return 0.5 * jnp.sum(
            jnp.square(self.mean) + jnp.exp(self.logvar) - 1.0 - self.logvar,
            axis=(1, 2, 3))

    def mode(self):
        return self.mean


class AutoencoderKL(Layer):
    def __init__(self, config: VAEConfig):
        super().__init__()
        self.config = config
        self.encoder = Encoder(config)
        self.decoder = Decoder(config)
        zc = config.latent_channels
        self.quant_conv = nn.Conv2D(2 * zc, 2 * zc, 1)
        self.post_quant_conv = nn.Conv2D(zc, zc, 1)
        if config.dtype != jnp.float32:
            self.to(dtype=config.dtype)

    def encode(self, x) -> DiagonalGaussian:
        return DiagonalGaussian(self.quant_conv(self.encoder(x)))

    def decode(self, z):
        return self.decoder(self.post_quant_conv(z))

    def forward(self, x, key: Optional[jax.Array] = None):
        posterior = self.encode(x)
        z = posterior.sample(key) if key is not None else posterior.mode()
        return self.decode(z), posterior


def vae_loss(recon, x, posterior: DiagonalGaussian, kl_weight: float = 1e-6):
    rec = jnp.mean(jnp.abs(recon.astype(jnp.float32) - x.astype(jnp.float32)))
    return rec + kl_weight * jnp.mean(posterior.kl())
