"""Attention kernels (reference: PHI fused attention kernels,
paddle/phi/kernels/fusion/*flash_attn*). TPU path: a Pallas flash-attention
kernel (online softmax, blocked over KV) used when shapes tile cleanly onto
the MXU; otherwise an XLA-fused dense path.

The Pallas kernel lands in `paddle_tpu/ops/pallas/flash_attention.py`;
this module is the dispatch layer.
"""
from __future__ import annotations

import math
import os

import jax
import jax.numpy as jnp


def _platform() -> str:
    try:
        return jax.devices()[0].platform
    except Exception:  # pragma: no cover
        return "cpu"


def _flash_enabled() -> bool:
    # NOT cached: both terms (env toggles in tests, platform) must be
    # re-read so interpret-mode coverage is real
    if os.environ.get("PADDLE_TPU_DISABLE_FLASH"):
        return False
    # interpret mode counts: CPU tests must be able to exercise every
    # branch that will select the kernel on hardware
    return _platform() == "tpu" or \
        bool(os.environ.get("PADDLE_TPU_PALLAS_INTERPRET"))


def use_flash(query, key, attn_mask, dropout_p) -> bool:
    if not _flash_enabled() or attn_mask is not None or dropout_p > 0.0:
        return False
    b, sq, h, d = query.shape
    sk = key.shape[1]
    # kernel tiles: seq multiples of 128, head_dim in {64, 128, 256}
    return sq % 128 == 0 and sk % 128 == 0 and d in (64, 128, 256)


def flash_attention(query, key, value, causal=False, scale=None,
                    segment_ids=None, window=None):
    """[b, s, h, d] flash attention; grouped-query aware. The Pallas kernel
    is TPU-only; on other backends (CPU mesh tests, dryruns) this routes to
    the numerically-identical dense XLA path. ``segment_ids`` [b, s]
    (0 = pad) restricts attention to same-segment pairs (packed
    sequences)."""
    from .pallas import kernels_enabled
    if not kernels_enabled():
        return dense_attention(query, key, value, causal=causal, scale=scale,
                               window=window,
                               attn_mask=segment_mask(segment_ids)
                               if segment_ids is not None else None)
    from .pallas.flash_attention import flash_attention_bshd
    return flash_attention_bshd(query, key, value, causal=causal,
                                scale=scale, segment_ids=segment_ids,
                                window=window)


def segment_mask(segment_ids):
    """[b, s] segment ids -> [b, 1, s, s] same-segment boolean mask with
    pads (seg 0) attending only pads (flash-kernel semantics; combined
    with `causal=` by dense_attention)."""
    seg = jnp.asarray(segment_ids)
    return (seg[:, :, None] == seg[:, None, :])[:, None]


def dense_attention(query, key, value, attn_mask=None, dropout_p=0.0,
                    causal=False, scale=None, dropout_key=None,
                    window=None):
    """XLA-fused dense path, [b, s, h, d]; fp32 softmax; GQA-aware.
    Single source of truth for the non-flash math (nn.functional's
    scaled_dot_product_attention fallback routes here). ``window``
    (with causal) keeps only the trailing ``window`` keys per query —
    sliding-window attention (Qwen2/Mistral)."""
    b, sq, h, d = query.shape
    scale = scale if scale is not None else 1.0 / math.sqrt(d)
    q = jnp.swapaxes(query, 1, 2)
    k = jnp.swapaxes(key, 1, 2)
    v = jnp.swapaxes(value, 1, 2)
    if k.shape[1] != h:
        rep = h // k.shape[1]
        k = jnp.repeat(k, rep, axis=1)
        v = jnp.repeat(v, rep, axis=1)
    scores = jnp.einsum("bhqd,bhkd->bhqk", q, k).astype(jnp.float32) * scale
    if causal:
        sk = k.shape[2]
        mask = jnp.tril(jnp.ones((sq, sk), dtype=bool), k=sk - sq)
        if window is not None:
            # bottom-right aligned: query i sits at absolute sk - sq + i
            qpos = jnp.arange(sq)[:, None] + (sk - sq)
            mask = mask & (qpos - jnp.arange(sk)[None, :] < window)
        scores = jnp.where(mask, scores, -jnp.inf)
    elif window is not None:
        raise ValueError("window requires causal=True (sliding-window "
                         "attention is causal)")
    if attn_mask is not None:
        if attn_mask.dtype == jnp.bool_:
            scores = jnp.where(attn_mask, scores, -jnp.inf)
        else:
            scores = scores + attn_mask.astype(scores.dtype)
    probs = jax.nn.softmax(scores, axis=-1).astype(query.dtype)
    if dropout_p > 0.0 and dropout_key is not None:
        keep = 1.0 - dropout_p
        dmask = jax.random.bernoulli(dropout_key, keep, probs.shape)
        probs = jnp.where(dmask, probs / keep, 0).astype(probs.dtype)
    out = jnp.einsum("bhqk,bhkd->bhqd", probs, v)
    return jnp.swapaxes(out, 1, 2)


def naive_attention(query, key, value, causal=False, scale=None):
    return dense_attention(query, key, value, causal=causal, scale=scale)


def use_decode_kernel(q, k_cache) -> bool:
    """Pallas decode kernel wants a TPU backend (or interpret mode, so CI
    exercises the same dispatch glue), MXU-friendly head_dim, a cache
    length with a 128-multiple tile, and a whole number of query heads
    per kv head."""
    from .pallas import interpret_enabled, kernels_enabled
    b, s, h, d = q.shape
    T, kv = k_cache.shape[1], k_cache.shape[2]
    if s != 1 or h % kv:
        return False
    if not (interpret_enabled()
            or (_flash_enabled() and kernels_enabled())):
        return False
    if interpret_enabled():
        # interpret mode skips Mosaic's tiling checks; any shape the
        # python emulation can run keeps CI coverage of the dispatch glue
        return d in (64, 128, 256) and T % 128 == 0
    # hardware: the kernel's K/V column blocks are [bt, cw] over the
    # folded [b, T, kv*d] view and must be STRICTLY (8, 128)-tiled (the
    # r05 window refused the equal-to-array-dims escape hatch for
    # (kv, d) = (4, 64)). cw is d when d % 128 == 0 and a head PAIR
    # (128) when d == 64 with an even kv; d=64 with odd kv has no
    # 128-multiple column block and takes the grouped-einsum fallback.
    return T % 128 == 0 and (d in (128, 256)
                             or (d == 64 and kv % 2 == 0))


def decode_attention(q, k_cache, v_cache, cache_index, scale=None,
                     window=None):
    """Single-token decode over a static KV cache (reference: PHI
    fusion/gpu/masked_multihead_attention). q [b, 1, h, d];
    k/v_cache [b, T, kv, d]; positions <= cache_index attend.

    Both paths are GQA-native — no `jnp.repeat` of K/V anywhere, so HBM
    traffic is the cache read itself (the decode bottleneck), not
    h/kv copies of it."""
    b, s, h, d = q.shape
    assert s == 1, f"decode_attention is for q_len=1, got {s}"
    kv, T = k_cache.shape[2], k_cache.shape[1]
    scale = scale if scale is not None else 1.0 / math.sqrt(d)

    if use_decode_kernel(q, k_cache):
        from .pallas.decode_attention import decode_attention_pallas
        out = decode_attention_pallas(q[:, 0], k_cache, v_cache,
                                      cache_index, scale, window=window)
        return out[:, None]

    # grouped einsum fallback (CPU mesh tests / odd shapes): same layout,
    # XLA contracts per kv head without materializing the repeat
    g = h // kv
    qg = q[:, 0].reshape(b, kv, g, d)
    scores = jnp.einsum("bkgd,btkd->bkgt", qg, k_cache,
                        preferred_element_type=jnp.float32) * scale
    kpos = jnp.arange(T)[None, None, None, :]
    mask = kpos <= cache_index
    if window is not None:  # sliding window: only the trailing keys
        mask = mask & (kpos > cache_index - window)
    scores = jnp.where(mask, scores, -jnp.inf)
    probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    out = jnp.einsum("bkgt,btkd->bkgd", probs, v_cache)
    return out.reshape(b, 1, h, d)
