"""ResNet family (reference: PaddleClas ppcls/arch/backbone/
legendary_models/resnet.py — ResNet vB/vD variants with BasicBlock /
BottleneckBlock, and paddle.vision.models.resnet).

TPU-native design: NCHW API surface lowered through
``lax.conv_general_dilated`` so XLA picks MXU-friendly layouts (convs are
implicit GEMMs on TPU). The "vD" trick (stride on the 3x3, avg-pool in the
shortcut) is kept because it is numerics, not a device detail. BatchNorm
uses the functional buffer path so the whole net stays jit-pure.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, List

import jax.numpy as jnp

from .. import nn
from ..nn import functional as F
from ..nn.layer import Layer


class ConvBNLayer(Layer):
    """conv → BN → optional act; the universal PP-ResNet building unit."""

    def __init__(self, in_ch, out_ch, kernel_size, stride=1, groups=1,
                 act=None, avg_first=False):
        super().__init__()
        self.avg_first = avg_first  # ResNet-vD downsample shortcut
        if avg_first:
            self.pool = nn.AvgPool2D(2, stride=2, padding=0)
            stride = 1
        self.conv = nn.Conv2D(in_ch, out_ch, kernel_size, stride=stride,
                              padding=(kernel_size - 1) // 2, groups=groups,
                              bias_attr=False)
        self.bn = nn.BatchNorm2D(out_ch)
        self.act = act

    def forward(self, x):
        if self.avg_first:
            x = self.pool(x)
        x = self.bn(self.conv(x))
        return F.relu(x) if self.act == "relu" else x


class BasicBlock(Layer):
    expansion = 1

    def __init__(self, in_ch, out_ch, stride=1, shortcut=True, variant="b"):
        super().__init__()
        self.conv0 = ConvBNLayer(in_ch, out_ch, 3, stride=stride, act="relu")
        self.conv1 = ConvBNLayer(out_ch, out_ch, 3, act=None)
        self.shortcut = shortcut
        if not shortcut:
            self.short = ConvBNLayer(in_ch, out_ch, 1, stride=stride,
                                     avg_first=(variant == "d" and stride > 1))

    def forward(self, x):
        y = self.conv1(self.conv0(x))
        s = x if self.shortcut else self.short(x)
        return F.relu(y + s)


class BottleneckBlock(Layer):
    expansion = 4

    def __init__(self, in_ch, out_ch, stride=1, shortcut=True, variant="b"):
        super().__init__()
        # vB puts the stride on the 3x3 (not the 1x1) — standard since
        # ResNet-B; vD additionally avg-pools in the projection shortcut.
        self.conv0 = ConvBNLayer(in_ch, out_ch, 1, act="relu")
        self.conv1 = ConvBNLayer(out_ch, out_ch, 3, stride=stride, act="relu")
        self.conv2 = ConvBNLayer(out_ch, out_ch * 4, 1, act=None)
        self.shortcut = shortcut
        if not shortcut:
            self.short = ConvBNLayer(in_ch, out_ch * 4, 1, stride=stride,
                                     avg_first=(variant == "d" and stride > 1))

    def forward(self, x):
        y = self.conv2(self.conv1(self.conv0(x)))
        s = x if self.shortcut else self.short(x)
        return F.relu(y + s)


@dataclass
class ResNetConfig:
    depth: int = 50
    num_classes: int = 1000
    variant: str = "b"           # "b" classic, "d" PP-ResNet-vD
    in_channels: int = 3
    stem_width: int = 64
    dtype: Any = jnp.float32
    layers: List[int] = field(default_factory=list)

    _DEPTH_CFG = {18: ([2, 2, 2, 2], BasicBlock),
                  34: ([3, 4, 6, 3], BasicBlock),
                  50: ([3, 4, 6, 3], BottleneckBlock),
                  101: ([3, 4, 23, 3], BottleneckBlock),
                  152: ([3, 8, 36, 3], BottleneckBlock)}

    def block_plan(self):
        blocks, cls = self._DEPTH_CFG[self.depth]
        return (self.layers or blocks), cls


class ResNet(Layer):
    """Backbone + classifier head. ``forward(x, return_feats=True)`` yields
    the four stage feature maps (what DBNet's FPN consumes)."""

    def __init__(self, config: ResNetConfig):
        super().__init__()
        self.config = config
        blocks, block_cls = config.block_plan()
        w = config.stem_width
        if config.variant == "d":  # deep stem: three 3x3s
            self.stem = nn.Sequential(
                ConvBNLayer(config.in_channels, w // 2, 3, stride=2, act="relu"),
                ConvBNLayer(w // 2, w // 2, 3, act="relu"),
                ConvBNLayer(w // 2, w, 3, act="relu"))
        else:
            self.stem = ConvBNLayer(config.in_channels, w, 7, stride=2,
                                    act="relu")
        self.pool = nn.MaxPool2D(3, stride=2, padding=1)

        stages = []
        in_ch = w
        for stage_idx, num_blocks in enumerate(blocks):
            out_ch = w * (2 ** stage_idx)
            stage = []
            for i in range(num_blocks):
                stride = 2 if stage_idx > 0 and i == 0 else 1
                # identity shortcut iff shapes already line up — the
                # canonical rule (torch/paddle ResNet): basic stage 0
                # block 0 is identity, bottleneck stage 0 needs the
                # 1x1 expand
                shortcut = (in_ch == out_ch * block_cls.expansion
                            and stride == 1)
                stage.append(block_cls(in_ch, out_ch, stride=stride,
                                       shortcut=shortcut,
                                       variant=config.variant))
                in_ch = out_ch * block_cls.expansion
            stages.append(nn.Sequential(*stage))
        self.stages = nn.LayerList(stages)
        self.out_channels = [w * (2 ** i) * block_cls.expansion
                             for i in range(len(blocks))]
        self.head = nn.Linear(in_ch, config.num_classes)
        if config.dtype != jnp.float32:
            self.to(dtype=config.dtype)

    def forward(self, x, return_feats: bool = False):
        x = self.pool(self.stem(x))
        feats = []
        for stage in self.stages:
            x = stage(x)
            feats.append(x)
        if return_feats:
            return feats
        x = F.global_avg_pool2d(x).reshape(x.shape[0], -1)
        return self.head(x).astype(jnp.float32)


def resnet18(**kw) -> ResNet:
    return ResNet(ResNetConfig(depth=18, **kw))


def resnet34(**kw) -> ResNet:
    return ResNet(ResNetConfig(depth=34, **kw))


def resnet50(**kw) -> ResNet:
    return ResNet(ResNetConfig(depth=50, **kw))


def resnet50_vd(**kw) -> ResNet:
    return ResNet(ResNetConfig(depth=50, variant="d", **kw))


def resnet_tiny(**overrides) -> ResNetConfig:
    base = dict(depth=18, num_classes=10, stem_width=16,
                layers=[1, 1, 1, 1], dtype=jnp.float32)
    base.update(overrides)
    return ResNetConfig(**base)
