"""paddle.incubate.nn parity."""
from . import functional

__all__ = ["functional"]
