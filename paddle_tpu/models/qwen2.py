"""Qwen2 family (reference: PaddleNLP paddlenlp/transformers/qwen2/
modeling.py — Qwen2Attention with q/k/v biases, Qwen2MLP, GQA,
Qwen2ForCausalLM).

Architecturally Qwen2 is the Llama backbone with biased q/k/v projections
and (for the small variants) tied embeddings, so the TPU-native build
reuses the Llama decoder wholesale — same flash-attention Pallas kernel,
same GSPMD sharding over ("dp","fsdp","tp","sp"), same static KV cache.
"""
from __future__ import annotations

from dataclasses import dataclass

import jax.numpy as jnp

from .llama import LlamaConfig, LlamaForCausalLM, LlamaModel


@dataclass
class Qwen2Config(LlamaConfig):
    vocab_size: int = 151936
    hidden_size: int = 3584
    intermediate_size: int = 18944
    num_hidden_layers: int = 28
    num_attention_heads: int = 28
    num_key_value_heads: int = 4
    max_position_embeddings: int = 32768
    rms_norm_eps: float = 1e-6
    rope_theta: float = 1000000.0
    attention_bias: bool = True        # the Qwen2 signature difference


def qwen2_7b(**overrides) -> Qwen2Config:
    return Qwen2Config(**overrides)


def qwen2_tiny(**overrides) -> Qwen2Config:
    base = dict(vocab_size=256, hidden_size=64, intermediate_size=128,
                num_hidden_layers=2, num_attention_heads=4,
                num_key_value_heads=2, max_position_embeddings=128,
                rope_theta=10000.0, dtype=jnp.float32)
    base.update(overrides)
    return Qwen2Config(**base)


class Qwen2Model(LlamaModel):
    pass


class Qwen2ForCausalLM(LlamaForCausalLM):
    pass
