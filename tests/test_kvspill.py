"""ISSUE 17: checksummed host-RAM KV spill tier (KVSpillArena).

Contracts pinned here:

- ARENA: the take-side validation ladder — crc32 mismatch, truncated
  record, geometry skew, capacity refusal — drops the record, counts
  it, and NEVER returns bytes; chain spans dedup into one payload
  record (longest digest) with every shorter span an index alias
  returning the FULL record payload.
- PARITY: greedy streams are bitwise identical (tokens AND logprobs)
  spill-on vs spill-off under eviction pressure — restored KV is
  byte-for-byte what re-prefill would have computed.
- CORRUPTION: a span stored with ``spill_corrupt`` armed (byte flip
  AFTER the crc is banked) is caught by the checksum on the way back;
  the engine falls back to re-prefill and the stream stays bitwise
  the reference — a corrupted span may cost a prefill, never a token.
- WARM RESTART: a fresh engine re-attached to the arena (the
  supervisor-rebuild path) advertises the spilled tier through
  ``has_prefix`` and serves the spilled prefix with
  ``prefix_hit_tokens > 0`` — no re-prefill across the crash.
- CHAOS (slow): the ``serve_loadgen --chaos --spill on`` harness —
  seeded mid-run kills with the shared arena attached — finishes with
  zero corrupted streams, zero checksum surprises, and at least one
  arena restore on a rebuilt replica (``tools/marker_audit.py`` chaos
  patterns).
"""
import asyncio

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.generation.paged import PagedEngine
from paddle_tpu.models import LlamaForCausalLM
from paddle_tpu.models.llama import llama_tiny
from paddle_tpu.serving.kvspill import KVSpillArena
from paddle_tpu.utils import faults

from test_gateway import _load_loadgen


@pytest.fixture(scope="module")
def model():
    pt.seed(0)
    return LlamaForCausalLM(llama_tiny())


def _engine(model, arena=None, **kw):
    base = dict(max_slots=2, num_blocks=16, block_size=8,
                max_blocks_per_seq=8, prefill_buckets=(16, 32),
                chunk_prefill_tokens=16, enable_prefix_cache=True)
    base.update(kw)
    eng = PagedEngine(model, **base)
    if arena is not None:
        eng.attach_spill(arena)
    return eng


def _greedy_new(model, ids, n):
    import jax.numpy as jnp
    out = model.generate(jnp.asarray(ids), max_new_tokens=n,
                         temperature=0.0)
    return np.asarray(out)[0, ids.shape[1]:]


# ================================================================== arena
GEO = (2, 8, 1, 4, "float32", 16)   # (L, B, kvh, d, dtype, chunk)


def _payload(n_blocks, fill=7.0):
    L, B, kvh, d = GEO[0], GEO[1], GEO[2], GEO[3]
    return np.full((2 * L, n_blocks, B, kvh, d), fill,
                   np.float32).tobytes()


class TestArena:
    def test_spill_take_roundtrip(self):
        arena = KVSpillArena(1 << 20, name="u_rt")
        pay = _payload(2)
        assert arena.spill([(b"d2", (1, 2))],
                           lambda e: pay, GEO, 5) == 1
        assert len(arena) == 1
        assert arena.probe(b"d2") == 16        # 2 blocks x B=8
        assert arena.take(b"d2", GEO) == (pay, 16)
        snap = arena.snapshot()
        assert snap["hits"] == 1 and snap["records"] == 1
        assert snap["occupancy_bytes"] == len(pay)

    def test_chain_dedup_one_gather_aliases_full_payload(self):
        """One D2H per chain: the longest span is the payload record;
        a shorter span in the same call is an index alias whose take
        returns the FULL record bytes + the RECORD's token count (the
        caller slices the leading blocks it needs)."""
        arena = KVSpillArena(1 << 20, name="u_alias")
        pay = _payload(4)
        gathers = []

        def fetch(entry):
            gathers.append(entry)
            return pay
        assert arena.spill([(b"d4", (1, 2, 3, 4)), (b"d2", (1, 2))],
                           fetch, GEO) == 1
        assert gathers == [(1, 2, 3, 4)]       # single gather
        assert arena.probe(b"d2") == 16        # alias advertises OWN span
        assert arena.take(b"d2", GEO) == (pay, 32)  # record's payload
        assert arena.snapshot()["digests"] == 2

    def test_capacity_refusal_and_lru_eviction(self):
        one = len(_payload(2))
        arena = KVSpillArena(2 * one, name="u_cap")
        # can never fit -> refused and counted, nothing stored
        assert arena.spill([(b"big", tuple(range(1, 9)))],
                           lambda e: _payload(8), GEO) == 0
        assert arena.snapshot()["drops"] == 1 and len(arena) == 0
        for i in range(3):                     # 3 spans into a 2-span cap
            arena.spill([(bytes([i]) * 4, (1, 2))],
                        lambda e: _payload(2, fill=float(i)), GEO)
        assert len(arena) == 2
        assert arena.lru_evictions == 1
        assert arena.probe(b"\x00" * 4) is None   # oldest evicted
        assert arena.probe(b"\x02" * 4) == 16

    def test_geometry_skew_drops_record(self):
        arena = KVSpillArena(1 << 20, name="u_geo")
        arena.spill([(b"dg", (1, 2))], lambda e: _payload(2), GEO)
        other = (4,) + GEO[1:]                 # different layer count
        assert arena.take(b"dg", other) is None
        assert arena.snapshot()["drops"] == 1
        assert arena.probe(b"dg") is None      # evicted, not retried

    def test_truncated_record_drops(self):
        arena = KVSpillArena(1 << 20, name="u_trunc")
        arena.spill([(b"dt", (1, 2))], lambda e: _payload(2), GEO)
        rec = arena._records[b"dt"]
        rec.payload = rec.payload[:-4]         # torn host buffer
        assert arena.take(b"dt", GEO) is None
        assert arena.snapshot()["drops"] == 1
        assert arena.probe(b"dt") is None

    def test_corrupt_fault_caught_by_checksum(self):
        """``spill_corrupt`` flips a byte AFTER the crc is banked: the
        probe still advertises the span, but take must catch the rot,
        count it, and evict — bytes never reach the caller."""
        arena = KVSpillArena(1 << 20, name="u_crc")
        with faults.scoped("spill_corrupt"):
            arena.spill([(b"dc", (1, 2))], lambda e: _payload(2), GEO)
        assert arena.probe(b"dc") == 16
        assert arena.take(b"dc", GEO) is None
        snap = arena.snapshot()
        assert snap["checksum_failures"] == 1 and snap["drops"] == 0
        assert arena.probe(b"dc") is None

    def test_drop_fault_refuses_store(self):
        arena = KVSpillArena(1 << 20, name="u_drop")
        with faults.scoped("spill_drop"):
            assert arena.spill([(b"dd", (1, 2))],
                               lambda e: _payload(2), GEO) == 0
        assert arena.snapshot()["drops"] == 1
        assert arena.probe(b"dd") is None

    def test_generation_advances_on_mutation(self):
        arena = KVSpillArena(1 << 20, name="u_gen")
        g0 = arena.generation
        arena.spill([(b"dgn", (1, 2))], lambda e: _payload(2), GEO)
        assert arena.generation > g0           # gossip sees the store
        g1 = arena.generation
        arena.take(b"dgn", (9,) + GEO[1:])     # skew -> eviction
        assert arena.generation > g1           # ...and the eviction


# ================================================================= engine
class TestSpillParity:
    def test_eviction_pressure_bitwise_spill_on_vs_off(self, model):
        """Five distinct 33-token prompts through a 15-block pool:
        spill-on evicts THROUGH the arena, spill-off discards — every
        stream (tokens and logprobs) must be bitwise identical."""
        def run(arena):
            rs = np.random.RandomState(50)
            prompts = {f"r{i}": np.asarray([rs.randint(1, 256, 33)])
                       for i in range(5)}
            eng = _engine(model, arena)
            for rid, ids in prompts.items():
                eng.submit(rid, ids, max_new_tokens=4)
            return eng, eng.run(), prompts
        eng_off, out_off, prompts = run(None)
        eng_on, out_on, _ = run(KVSpillArena(64 << 20, name="parity"))
        for rid in prompts:
            np.testing.assert_array_equal(
                np.asarray(out_on[rid]), np.asarray(out_off[rid]),
                err_msg=rid)
            np.testing.assert_array_equal(
                np.asarray(eng_on.logprobs[rid]),
                np.asarray(eng_off.logprobs[rid]), err_msg=rid)
        assert eng_on.stats["spill_spans"] > 0     # pressure spilled
        assert eng_off.stats["spill_spans"] == 0

    def test_evicted_span_restores_from_arena_and_stays_exact(
            self, model):
        """After a span is evicted D2H, resubmitting its prompt must
        restore it (one H2D scatter, no re-prefill of the span) and
        the stream must equal the model's own greedy decode."""
        arena = KVSpillArena(64 << 20, name="restore")
        eng = _engine(model, arena)
        rs = np.random.RandomState(51)
        first = np.asarray([rs.randint(1, 256, 33)])
        eng.submit("a", first, max_new_tokens=4)
        eng.run()
        for i in range(6):                     # flood the 15-block pool
            eng.submit(f"f{i}",
                       np.asarray([rs.randint(1, 256, 33)]),
                       max_new_tokens=4)
        eng.run()
        digest = eng.prefix_digest(first)
        assert bytes.fromhex(digest) not in eng.prefix_cache
        assert eng.has_prefix(digest)          # spilled tier advertises
        hit0 = eng.stats["prefix_hit_tokens"]
        eng.submit("a2", first, max_new_tokens=4)
        out = eng.run()
        assert eng.stats["spill_restores"] >= 1, eng.stats
        assert eng.stats["prefix_hit_tokens"] > hit0
        np.testing.assert_array_equal(np.asarray(out["a2"]),
                                      _greedy_new(model, first, 4))

    def test_corrupted_span_never_emits_a_token(self, model):
        """Every record stored under ``spill_corrupt`` carries silent
        bit rot. The warm resubmit must catch it at the checksum,
        count a restore failure, fall back to re-prefill, and emit a
        stream bitwise identical to the uncorrupted reference."""
        arena = KVSpillArena(64 << 20, name="corrupt")
        eng = _engine(model, arena)
        rs = np.random.RandomState(52)
        first = np.asarray([rs.randint(1, 256, 33)])
        ref = _greedy_new(model, first, 4)
        eng.submit("a", first, max_new_tokens=4)
        eng.run()
        with faults.scoped("spill_corrupt"):
            for i in range(6):                 # evict a's spans rotten
                eng.submit(f"f{i}",
                           np.asarray([rs.randint(1, 256, 33)]),
                           max_new_tokens=4)
            eng.run()
        digest = eng.prefix_digest(first)
        assert eng.has_prefix(digest)          # still advertised...
        eng.submit("a2", first, max_new_tokens=4)
        out = eng.run()
        np.testing.assert_array_equal(np.asarray(out["a2"]), ref)
        assert eng.stats["spill_restores"] == 0
        assert eng.stats["spill_restore_failures"] >= 1, eng.stats
        assert arena.snapshot()["checksum_failures"] >= 1


class TestWarmRestart:
    def test_rebuild_recovers_warm_from_arena(self, model):
        """The supervisor-rebuild contract: drain-spill on the dying
        engine, then a FRESH engine re-attached to the same arena
        advertises the span, restores it at admission, and serves it
        with prefix-hit tokens — bitwise the original stream."""
        arena = KVSpillArena(64 << 20, name="warm")
        e0 = _engine(model, arena, num_blocks=32)
        rs = np.random.RandomState(53)
        prompt = np.asarray([rs.randint(1, 256, 33)])
        e0.submit("a", prompt, max_new_tokens=4)
        ref = np.asarray(e0.run()["a"])
        lp_ref = np.asarray(e0.logprobs["a"])
        assert e0.spill_parked() > 0           # SIGTERM drain banks
        e1 = _engine(model, arena, num_blocks=32)   # rebuilt replica
        digest = e1.prefix_digest(prompt)
        assert e1.has_prefix(digest)           # warm BEFORE any traffic
        e1.submit("b", prompt, max_new_tokens=4)
        out = e1.run()
        assert e1.stats["spill_restores"] >= 1, e1.stats
        assert e1.stats["prefix_hit_tokens"] >= 16, e1.stats
        np.testing.assert_array_equal(np.asarray(out["b"]), ref)
        np.testing.assert_array_equal(np.asarray(e1.logprobs["b"]),
                                      lp_ref)

    def test_geometry_skew_falls_back_to_prefill(self, model):
        """An arena fed by one block geometry attached to an engine
        with another: the take-side geometry check refuses the
        payload, the restore counts a failure, and the stream is
        still exact via re-prefill."""
        arena = KVSpillArena(64 << 20, name="skew")
        e0 = _engine(model, arena, num_blocks=32)
        rs = np.random.RandomState(54)
        prompt = np.asarray([rs.randint(1, 256, 33)])
        e0.submit("a", prompt, max_new_tokens=4)
        ref = np.asarray(e0.run()["a"])
        assert e0.spill_parked() > 0
        e1 = _engine(model, arena, num_blocks=32, block_size=4,
                     max_blocks_per_seq=16)    # skewed geometry
        e1.submit("b", prompt, max_new_tokens=4)
        out = e1.run()
        np.testing.assert_array_equal(np.asarray(out["b"]), ref)
        assert e1.stats["spill_restores"] == 0
        assert arena.snapshot()["drops"] >= 1


# ================================================================== chaos
def _chaos_spill_ns(**kw):
    import types
    base = dict(requests=400, rate=50.0, share_frac=0.9, sys_tokens=16,
                tail_tokens=24, max_new=16, interactive_frac=0.7,
                ttft_slo_ms=5000.0, timeout_s=60.0, tenants=2,
                replicas=3, policy="prefix", max_queue=256,
                model="stub", seed=0, url=None, out="",
                chaos=True, chaos_kills=3, chaos_mode="kill",
                failover_budget=3, watchdog_timeout_s=0.5,
                goodput_floor=0.95, spill="on", spill_mb=64)
    base.update(kw)
    return types.SimpleNamespace(**base)


@pytest.mark.slow
@pytest.mark.chaos
def test_spill_chaos_kill_replay_clean():
    """The ISSUE 17 acceptance run: 3-replica gateway, 3 seeded
    mid-run SIGKILL-style crashes, the shared host-RAM arena attached.
    Eviction pressure banks spans (the shared sys prefix rides along
    as an alias of its dying descendants), a rebuilt replica
    advertises the spilled tier, restores at least one span, and
    EVERY completed greedy stream replays bitwise — zero corrupted
    streams, zero checksum failures, errors within the budget bound."""
    slg = _load_loadgen()
    rung = asyncio.run(slg.run_loadgen(_chaos_spill_ns()))
    ch = rung["chaos"]
    assert ch["corrupted_streams"] == 0, ch
    assert ch["errors_5xx"] == 0, ch
    assert ch["completed_frac"] >= 0.95, ch
    assert ch["ok"], ch
    arena = rung["kv_spill_arena"]
    assert arena["spans"] > 0, arena           # pressure spilled
    assert arena["checksum_failures"] == 0, arena
    assert rung["kv_spill_restores"] >= 1, rung
    assert rung["kv_spill_restored_tokens"] > 0, rung
