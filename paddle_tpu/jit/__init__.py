"""Dynamic-to-static + compiled execution (reference: python/paddle/jit/*,
the static Program/Executor and the CINN compiler).

TPU-native mapping:
- `to_static(fn)` == trace-and-compile with jax.jit. XLA *is* the fusion
  compiler (what CINN does for the reference, XLA does here, better, for
  TPU).
- A paddle `Program` == a captured ClosedJaxpr; `ProgramHolder` exposes it
  for inspection/serialization.
- `save`/`load` == AOT-compiled executable export via jax.export.
- Layers: `to_static(layer)` wraps forward through the functional bridge so
  the module tree stays out of the traced graph.
"""
from __future__ import annotations

import functools
from typing import Any, Optional

import jax
import jax.numpy as jnp

from ..nn.layer import Layer


class StaticFunction:
    """Compiled callable with paddle.jit surface (concrete_program etc.)."""

    def __init__(self, fn, static_argnums=(), donate_argnums=(), backend=None):
        self._raw = fn
        self._jitted = jax.jit(fn, static_argnums=static_argnums,
                               donate_argnums=donate_argnums, backend=backend)

    def __call__(self, *args, **kwargs):
        return self._jitted(*args, **kwargs)

    def concrete_program(self, *args, **kwargs):
        """Return the captured jaxpr (the 'static Program')."""
        return jax.make_jaxpr(self._raw)(*args, **kwargs)

    def lowered(self, *args, **kwargs):
        return self._jitted.lower(*args, **kwargs)

    def compiled_ir(self, *args, **kwargs):
        return self._jitted.lower(*args, **kwargs).compile()

    def cost_analysis(self, *args, **kwargs):
        return self._jitted.lower(*args, **kwargs).compile().cost_analysis()


def to_static(fn_or_layer=None, input_spec=None, static_argnums=(),
              donate_argnums=(), full_graph=True, backend=None):  # noqa: ARG001
    """paddle.jit.to_static parity. Use as decorator or call."""
    def wrap(obj):
        if isinstance(obj, Layer):
            orig_forward = obj.forward  # capture before we shadow it

            def pure(p, *args, rng=None, **kwargs):
                # rng: traced key threaded to Dropout etc. — without it a
                # host key would bake into the program as a constant
                # (next_key warns in that case).
                import contextlib
                from ..utils.rng import key_context
                ctx = key_context(rng) if rng is not None else contextlib.nullcontext()
                with ctx, obj.bound(p):
                    return orig_forward(*args, **kwargs)
            jitted = jax.jit(pure, static_argnums=static_argnums)

            @functools.wraps(orig_forward)
            def layer_call(*args, rng=None, **kwargs):
                return jitted(dict(obj.named_parameters()), *args, rng=rng, **kwargs)
            # shadow the instance forward so obj(x) runs the compiled program
            object.__setattr__(obj, "forward", layer_call)
            object.__setattr__(obj, "_static_fn", layer_call)
            return obj
        return StaticFunction(obj, static_argnums=static_argnums,
                              donate_argnums=donate_argnums, backend=backend)
    if fn_or_layer is None:
        return wrap
    return wrap(fn_or_layer)


def not_to_static(fn):
    fn.__not_to_static__ = True
    return fn


def save(static_fn, path: str, *example_args, **example_kwargs):
    """AOT-export a compiled function (paddle.jit.save parity)."""
    from jax import export as jax_export
    fn = static_fn._jitted if isinstance(static_fn, StaticFunction) else jax.jit(static_fn)
    exported = jax_export.export(fn)(*example_args, **example_kwargs)
    data = exported.serialize()
    with open(path if path.endswith(".jaxir") else path + ".jaxir", "wb") as f:
        f.write(data)
    return path


def load(path: str):
    """Load an AOT-exported function (paddle.jit.load parity)."""
    from jax import export as jax_export
    with open(path if path.endswith(".jaxir") else path + ".jaxir", "rb") as f:
        data = f.read()
    exported = jax_export.deserialize(data)
    return exported.call


def ignore_module(modules):  # paddle API parity; nothing to ignore under jax
    return None


def save_inference_model(path_prefix: str, layer, *example_inputs):
    """Deployable bundle = serialized StableHLO program + weights
    (reference: paddle.static.save_inference_model — program .pdmodel +
    params .pdiparams). The exported artifact replays WITHOUT the model
    class: ``load_inference_model`` returns a plain callable.

    Layout: ``<prefix>.jaxir`` (jax.export serialization of
    fn(params, *inputs)) + ``<prefix>.pdiparams`` (npz state_dict).
    Buffers (e.g. BatchNorm running stats) are traced as constants —
    frozen into the program, exactly the inference semantics.
    """
    import numpy as np

    from jax import export as jax_export

    fn, params = layer.functional()
    # export records the exact pytree type of args[0]; serialize a plain
    # dict so load-time invocation (which builds a dict from npz) matches
    exported = jax_export.export(jax.jit(fn))(dict(params), *example_inputs)
    with open(path_prefix + ".jaxir", "wb") as f:
        f.write(exported.serialize())
    host = {k: np.asarray(v) for k, v in params.items()}
    np.savez(path_prefix + ".pdiparams", **host)
    return path_prefix


def load_inference_model(path_prefix: str):
    """Load a save_inference_model bundle -> ``predict(*inputs)`` with the
    weights baked in (params re-materialized on device at first call)."""
    import numpy as np

    from jax import export as jax_export

    with open(path_prefix + ".jaxir", "rb") as f:
        exported = jax_export.deserialize(f.read())
    with np.load(path_prefix + ".pdiparams.npz") as z:
        params = {k: jnp.asarray(z[k]) for k in z.files}

    def predict(*inputs):
        return exported.call(params, *inputs)

    return predict
