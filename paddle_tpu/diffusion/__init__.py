"""paddle_tpu.diffusion — schedulers + training/sampling loops
(reference: PaddleMIX ppdiffusers/schedulers)."""
from .schedulers import (DDIMScheduler, DDPMScheduler, FlowMatchScheduler,
                         diffusion_loss, make_betas, sample_loop)
from .pipelines import DiTPipeline, StableDiffusion3Pipeline
