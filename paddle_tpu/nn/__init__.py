"""paddle_tpu.nn — layer library (reference: python/paddle/nn/__init__.py)."""
from . import functional
from . import initializer
from .activation import (CELU, ELU, GELU, GLU, SELU, Hardshrink, Hardsigmoid,
                         Hardswish, Hardtanh, LeakyReLU, LogSigmoid,
                         LogSoftmax, Mish, PReLU, ReLU, ReLU6, Sigmoid, SiLU,
                         Softmax, Softplus, Softshrink, Softsign, Swish, Tanh,
                         Tanhshrink)
from .common import (AdaptiveAvgPool1D,
                     AlphaDropout,
                     AvgPool1D,
                     AvgPool3D,
                     Bilinear,
                     ChannelShuffle,
                     CosineSimilarity,
                     Dropout,
                     Dropout2D,
                     Dropout3D,
                     Embedding,
                     Flatten,
                     Fold,
                     Identity,
                     Linear,
                     LocalResponseNorm,
                     MaxPool1D,
                     MaxPool3D,
                     MaxUnPool2D,
                     Maxout,
                     Pad1D,
                     Pad2D,
                     PairwiseDistance,
                     PixelShuffle,
                     PixelUnshuffle,
                     RReLU,
                     ThresholdedReLU,
                     Unfold,
                     Upsample,
                     UpsamplingBilinear2D,
                     ZeroPad2D)
from .container import LayerDict, LayerList, ParameterList, Sequential
from .conv import (AdaptiveAvgPool2D, AdaptiveMaxPool2D, AvgPool2D, Conv1D,
                   Conv2D, Conv2DTranspose, Conv3D, MaxPool2D)
from .layer import Buffer, Layer, Parameter, ParamMeta
from .loss import (BCELoss,
                   BCEWithLogitsLoss,
                   CTCLoss,
                   CosineEmbeddingLoss,
                   CrossEntropyLoss,
                   GaussianNLLLoss,
                   HingeEmbeddingLoss,
                   KLDivLoss,
                   L1Loss,
                   MSELoss,
                   MarginRankingLoss,
                   MultiLabelSoftMarginLoss,
                   NLLLoss,
                   PoissonNLLLoss,
                   SmoothL1Loss,
                   SoftMarginLoss,
                   TripletMarginLoss)
from .norm import (BatchNorm, BatchNorm1D, BatchNorm2D, BatchNorm3D,
                   GroupNorm, InstanceNorm2D, LayerNorm, RMSNorm,
                   SyncBatchNorm)
from .recompute import checkpoint_wrapper, recompute
from .transformer import (MultiHeadAttention, Transformer, TransformerDecoder,
                          TransformerDecoderLayer, TransformerEncoder,
                          TransformerEncoderLayer)

F = functional
