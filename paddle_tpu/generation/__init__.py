"""Autoregressive generation (reference: PaddleNLP
paddlenlp/generation/utils.py GenerationMixin.generate — greedy/sampling/
beam search over a KV cache).

TPU-native: ONE compiled program per (batch, prompt_len, max_len) bucket —
prefill + a `lax.while_loop` decode over a static-shape KV cache. No
per-token retracing, no dynamic shapes. Sampling params are traced scalars
where possible so changing temperature does not recompile.
"""
from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import Optional

import jax
import jax.numpy as jnp

from .sampling import (repetition_penalty, sample_token,
                       suffix_window_hits)

__all__ = ["GenerationConfig", "generate", "beam_search"]

# Per-model executable cache {static-shape/config key -> compiled run},
# hung off the model object itself. Without it every generate() call
# would build a fresh closure and jax.jit would retrace + recompile the
# whole prefill+decode program per request — the pipeline's bucket
# ladder only pays off if the executable is actually reused. NOT a
# module-global registry: the compiled run closes over the model, so a
# global (even weak-keyed — its values would pin their own keys) would
# leak every model ever generated with; model -> cache -> run -> model
# is a plain cycle the gc collects when the caller drops the model.


def _gen_cache_for(model):
    cache = getattr(model, "_gen_exec_cache", None)
    if cache is None:
        cache = {}
        object.__setattr__(model, "_gen_exec_cache", cache)
    return cache


@dataclass
class GenerationConfig:
    max_new_tokens: int = 64
    do_sample: bool = False
    temperature: float = 1.0
    top_k: int = 0
    top_p: float = 1.0
    eos_token_id: Optional[int] = None
    pad_token_id: int = 0
    num_beams: int = 1
    length_penalty: float = 1.0
    # penalize tokens already in the running sequence (prompt +
    # generated), HF/Paddle semantics: divide positive logits, multiply
    # negative ones. 1.0 = off.
    repetition_penalty: float = 1.0
    # suppress eos until this many tokens have been generated
    min_new_tokens: int = 0
    # ban any token that would complete an n-gram already present in
    # the running sequence (HF semantics). 0 = off.
    no_repeat_ngram_size: int = 0


def generate(model, input_ids, config: Optional[GenerationConfig] = None,
             key=None, params=None, prompt_start=None, **kwargs):
    """Greedy/sampled decoding. `model` is a Layer with `init_kv_caches` and
    forward(ids, kv_caches=, cache_index=) (the CausalLM contract).

    prompt_start: optional [b] index of each row's first REAL token for
    left-padded serving batches (reference: PaddleNLP llm predictor's
    padded batch layout) — pad prefixes are masked out of attention and
    RoPE positions start at each row's real start.

    Returns [b, prompt_len + max_new_tokens] token ids (right-padded with
    pad_token_id after eos)."""
    cfg = config or GenerationConfig(**kwargs)
    if config is not None and kwargs:
        # per-call overrides on top of a base config (the pipeline path):
        # silently dropping them would be wrong-output, not an error
        import dataclasses
        cfg = dataclasses.replace(cfg, **kwargs)
    if cfg.no_repeat_ngram_size < 0:
        raise ValueError("no_repeat_ngram_size must be >= 0")
    if cfg.repetition_penalty <= 0:
        # mirrors PagedEngine.submit: a zero/negative penalty silently
        # divides by zero or flips the penalty's sign semantics
        raise ValueError("repetition_penalty must be > 0")
    if cfg.num_beams > 1:
        if prompt_start is not None:
            # beam_search neither masks pad-prefix attention (attn_start)
            # nor excludes pads from the processors' seen/ngram windows;
            # running it on a left-padded batch would be silently wrong
            raise NotImplementedError(
                "beam search does not support left-padded prompt_start "
                "batches; pass right-aligned prompts (per row) instead")
        return beam_search(model, input_ids, cfg, params=params)
    key = key if key is not None else jax.random.key(0)
    fn, model_params = model.functional()
    params = params if params is not None else model_params
    b, prompt_len = input_ids.shape
    has_start = prompt_start is not None

    cache_key = (b, prompt_len, cfg.max_new_tokens, cfg.do_sample,
                 cfg.top_k, cfg.top_p, cfg.eos_token_id, cfg.pad_token_id,
                 cfg.repetition_penalty, cfg.min_new_tokens,
                 cfg.no_repeat_ngram_size, has_start,
                 # model surgery (e.g. quantize_model) changes the param
                 # tree; a stale compiled fn must not be reused
                 hash(tuple(model_params)))
    per_model = _gen_cache_for(model)
    run = per_model.get(cache_key)
    if run is None:
        run = _build_generate_fn(model, fn, cfg, b, prompt_len, has_start)
        per_model[cache_key] = run
    args = [params, input_ids, key, jnp.float32(cfg.temperature)]
    if has_start:
        args.append(jnp.asarray(prompt_start, jnp.int32))
    return run(*args)


def _logits_processors(cfg, vocab):
    """ONE implementation of the decode-time logits processors
    (repetition penalty, no-repeat-ngram bans, min-new-tokens eos
    suppression), shared by the greedy while_loop and beam search.
    Returns ``process(raw, seen, n_generated, tokens, cur, row_starts)``
    operating on [N, V] fp32 logits for N rows (batch rows or b*beams);
    every knob compiles away when off (static flags)."""
    eos = cfg.eos_token_id
    use_rep = cfg.repetition_penalty != 1.0
    ngram = int(cfg.no_repeat_ngram_size)

    def banned_ngram(tokens_row, cur, row_start):
        """[V] mask of tokens that would complete an ``ngram``-gram
        already present in the row's sequence (HF semantics): match the
        last ngram-1 committed tokens against every earlier window
        (shared kernel with speculative prompt-lookup) and ban each
        window's follower."""
        g = ngram - 1
        L = tokens_row.shape[0]
        starts = jnp.arange(L)
        hit = suffix_window_hits(tokens_row, cur, g)
        if row_start is not None:        # left-pad prefix is not content
            hit &= starts >= row_start
        follow = tokens_row[jnp.clip(starts + g, 0, L - 1)]
        return jnp.zeros((vocab,), bool).at[follow].max(hit)

    def process(raw, seen, n_generated, tokens=None, cur=None,
                row_starts=None):
        if use_rep:
            raw = repetition_penalty(raw, seen, cfg.repetition_penalty)
        if ngram:
            ban = jax.vmap(
                banned_ngram,
                in_axes=(0, None, 0 if row_starts is not None else None))(
                tokens, cur, row_starts)
            raw = jnp.where(ban, -1e30, raw)
        if eos is not None and cfg.min_new_tokens > 0:
            suppress = n_generated < cfg.min_new_tokens
            is_eos = (jnp.arange(raw.shape[-1]) == eos)[None, :]
            raw = jnp.where(is_eos & suppress, -1e30, raw)
        return raw

    return process


def _build_generate_fn(model, fn, cfg, b, prompt_len, has_start):
    total = prompt_len + cfg.max_new_tokens
    eos = cfg.eos_token_id
    use_rep = cfg.repetition_penalty != 1.0
    ngram = int(cfg.no_repeat_ngram_size)
    if use_rep or ngram:  # only these paths need a vocab size off the
        # config — the plain contract (init_kv_caches + forward) stays
        # sufficient otherwise
        vocab = model.config.vocab_size
        _process = _logits_processors(cfg, vocab)
    elif eos is not None and cfg.min_new_tokens > 0:
        _process = _logits_processors(cfg, None)
    else:
        _process = None

    def adjust(row_logits, seen, n_generated, tokens=None, cur=None,
               row_starts=None):
        if _process is None:
            return row_logits
        return _process(row_logits, seen, n_generated, tokens=tokens,
                        cur=cur, row_starts=row_starts)

    @jax.jit
    def run(params, input_ids, key, temperature, *start):
        extra = {"attn_start": start[0]} if has_start else {}
        caches = model.init_kv_caches(b, total)
        # prefill
        logits, caches = fn(params, input_ids, kv_caches=caches,
                            cache_index=0, **extra)
        tokens = jnp.concatenate(
            [input_ids,
             jnp.full((b, cfg.max_new_tokens), cfg.pad_token_id,
                      input_ids.dtype)], axis=1)
        rows = jnp.arange(b)
        if use_rep:
            # bool membership mask (the penalty only tests seen-ness);
            # left-pad prefixes excluded: not part of the real sequence
            valid = jnp.ones((b, prompt_len), bool) if not has_start \
                else jnp.arange(prompt_len)[None, :] >= start[0][:, None]
            seen = jnp.zeros((b, vocab), bool) \
                .at[rows[:, None], input_ids].max(valid)
        else:
            seen = jnp.zeros((b, 1), bool)        # unused placeholder
        row0 = adjust(logits[:, -1], seen, jnp.int32(0), tokens=tokens,
                      cur=jnp.int32(prompt_len),
                      row_starts=start[0] if has_start else None)
        next_tok = sample_token(row0, key,
                                temperature=temperature, top_k=cfg.top_k,
                                top_p=cfg.top_p, do_sample=cfg.do_sample)
        tokens = tokens.at[:, prompt_len].set(next_tok)
        if use_rep:
            seen = seen.at[rows, next_tok].set(True)
        done = jnp.zeros((b,), bool) if eos is None else (next_tok == eos)

        def step(state, cur):
            tokens, caches, key, done, seen = state
            ids = jax.lax.dynamic_slice_in_dim(tokens, cur - 1, 1, axis=1)
            logits, caches = fn(params, ids, kv_caches=caches,
                                cache_index=cur - 1, **extra)
            key, sub = jax.random.split(key)
            row = adjust(logits[:, 0], seen, cur - prompt_len,
                         tokens=tokens, cur=cur,
                         row_starts=start[0] if has_start else None)
            nxt = sample_token(row, sub, temperature=temperature,
                               top_k=cfg.top_k, top_p=cfg.top_p,
                               do_sample=cfg.do_sample)
            nxt = jnp.where(done, jnp.asarray(cfg.pad_token_id, nxt.dtype), nxt)
            if use_rep:  # finished rows emit pad — don't count it
                seen = seen.at[rows, nxt].max(~done)
            tokens = jax.lax.dynamic_update_slice(tokens, nxt[:, None], (0, cur))
            if eos is not None:
                done = done | (nxt == eos)
            return (tokens, caches, key, done, seen)

        state = (tokens, caches, key, done, seen)
        if eos is None:
            # static trip count: fori lowers without a dynamic predicate,
            # letting XLA pipeline iterations (while_loop can't)
            state = jax.lax.fori_loop(
                prompt_len + 1, total, lambda c, s: step(s, c), state)
        else:
            def cond(s):
                done = s[0][3]
                return (s[1] < total) & ~jnp.all(done)

            def body(s):
                return (step(s[0], s[1]), s[1] + 1)

            (state, _) = jax.lax.while_loop(
                cond, body, (state, jnp.asarray(prompt_len + 1)))
        tokens = state[0]
        return tokens

    return run


def beam_search(model, input_ids, config: GenerationConfig, params=None):
    """Beam search (reference: PaddleNLP BeamSearchScorer). Beams live as an
    expanded batch [b*beams]; the KV cache is gathered per step with the
    beam indices — static shapes throughout. The logits processors
    (repetition_penalty / min_new_tokens / no_repeat_ngram_size) run on
    each beam's raw logits before log_softmax, and the final beam is
    picked by ``score / length**length_penalty`` (HF convention; with
    no eos all beams share one length, so the default is unchanged)."""
    cfg = config
    k = cfg.num_beams
    fn, model_params = model.functional()
    params = params if params is not None else model_params
    b, prompt_len = input_ids.shape
    total = prompt_len + cfg.max_new_tokens
    eos = cfg.eos_token_id
    vocab = model.config.vocab_size
    use_rep = cfg.repetition_penalty != 1.0
    _proc = _logits_processors(cfg, vocab)

    def process(raw, tokens, cur, seen):
        """Per-beam logits processors on [b*k, V] raw fp32 logits (the
        shared _logits_processors implementation; beams are right-
        aligned — generate() rejects prompt_start for beams)."""
        return _proc(raw, seen, cur - prompt_len, tokens=tokens, cur=cur)

    @jax.jit
    def run(params, input_ids):
        # expand prompts to beams
        ids = jnp.repeat(input_ids, k, axis=0)              # [b*k, L]
        rows = jnp.arange(b * k)
        caches = model.init_kv_caches(b * k, total)
        logits, caches = fn(params, ids, kv_caches=caches, cache_index=0)
        tokens = jnp.concatenate(
            [ids, jnp.full((b * k, cfg.max_new_tokens), cfg.pad_token_id,
                           ids.dtype)], axis=1)
        if use_rep:
            seen = jnp.zeros((b * k, vocab), bool) \
                .at[rows[:, None], ids].set(True)
        else:
            seen = jnp.zeros((b * k, 1), bool)    # unused placeholder
        raw = process(logits[:, -1].astype(jnp.float32), tokens,
                      jnp.int32(prompt_len), seen)
        logp = jax.nn.log_softmax(raw, -1).reshape(b, k, vocab)
        # first step: only beam 0 is live (identical beams would collapse)
        first_mask = jnp.where(jnp.arange(k)[None, :, None] == 0, 0.0, -jnp.inf)
        scores, idx = jax.lax.top_k((logp + first_mask).reshape(b, -1), k)
        beam_src, next_tok = idx // vocab, idx % vocab      # [b, k]

        def gather_beams(tree, src):
            flat_src = (src + jnp.arange(b)[:, None] * k).reshape(-1)
            return jax.tree.map(lambda x: x[flat_src], tree)

        seen = gather_beams(seen, beam_src)
        tokens = tokens.at[:, prompt_len].set(next_tok.reshape(-1))
        if use_rep:
            seen = seen.at[rows, next_tok.reshape(-1)].set(True)
        done = jnp.zeros((b, k), bool) if eos is None else (next_tok == eos)
        # generated length per beam EXCLUDING the terminating eos — HF's
        # BeamHypotheses.add ranks by generated_len, which does not count
        # the eos being processed (an eos-first beam would be length 0;
        # the final ranking clamps to 1 to keep the score finite)
        n_gen = jnp.ones((b, k), jnp.int32) if eos is None \
            else (~done).astype(jnp.int32)

        def body(cur, state):
            tokens, caches, scores, done, seen, n_gen = state
            ids_t = jax.lax.dynamic_slice_in_dim(tokens, cur - 1, 1, axis=1)
            logits, new_caches = fn(params, ids_t, kv_caches=caches,
                                    cache_index=cur - 1)
            raw = process(logits[:, 0].astype(jnp.float32), tokens, cur,
                          seen)
            logp = jax.nn.log_softmax(raw, -1).reshape(b, k, vocab)
            # finished beams: freeze score, only pad continues
            pad_only = jnp.full((vocab,), -jnp.inf).at[cfg.pad_token_id].set(0.0)
            logp = jnp.where(done[..., None], pad_only[None, None], logp)
            cand = scores[..., None] + logp                 # [b, k, v]
            scores, idx = jax.lax.top_k(cand.reshape(b, -1), k)
            beam_src, next_tok = idx // vocab, idx % vocab
            tokens = gather_beams(tokens, beam_src)
            caches = gather_beams(new_caches, beam_src)
            seen = gather_beams(seen, beam_src)
            done = jnp.take_along_axis(done, beam_src, axis=1)
            n_gen = jnp.take_along_axis(n_gen, beam_src, axis=1)
            # count live continuations only; the step a beam emits eos
            # adds nothing (HF's generated_len excludes that eos)
            live = ~done if eos is None else ~done & (next_tok != eos)
            n_gen = n_gen + live.astype(jnp.int32)
            nxt = jnp.where(done, cfg.pad_token_id, next_tok)
            if use_rep:
                seen = seen.at[rows, nxt.reshape(-1)] \
                    .max(~done.reshape(-1))
            tokens = jax.lax.dynamic_update_slice(
                tokens, nxt.reshape(-1, 1), (0, cur))
            if eos is not None:
                done = done | (nxt == eos)
            return (tokens, caches, scores, done, seen, n_gen)

        state = (tokens, caches, scores, done, seen, n_gen)
        state = jax.lax.fori_loop(prompt_len + 1, total,
                                  lambda c, s: body(c, s), state)
        tokens, _, scores, _, _, n_gen = state
        # HF-convention final ranking: sum-logprob / generated_len^penalty
        # (eos excluded from the length; clamped to 1 for the degenerate
        # eos-as-first-token beam)
        ranked = scores / (jnp.maximum(n_gen, 1).astype(jnp.float32)
                           ** jnp.float32(cfg.length_penalty))
        best = jnp.argmax(ranked, axis=1)
        return tokens.reshape(b, k, total)[jnp.arange(b), best]

    return run(params, input_ids)


from .pipeline import TextGenerationPipeline  # noqa: E402
from .paged import PagedEngine, PagedKV  # noqa: E402
from .prompt_lookup import (propose_ngram,  # noqa: E402
                            propose_ngram_rows)
from .speculative import (speculative_generate,  # noqa: E402
                          mtp_speculative_generate,
                          ngram_speculative_generate)

__all__ += ["TextGenerationPipeline", "speculative_generate",
            "mtp_speculative_generate", "ngram_speculative_generate",
            "PagedEngine", "PagedKV", "propose_ngram",
            "propose_ngram_rows"]
