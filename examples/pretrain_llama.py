"""Pretrain a Llama on synthetic tokens with the Trainer — the flagship
recipe (hybrid-parallel-ready: install a mesh and it runs SPMD).

  python examples/pretrain_llama.py               # tiny config, any backend
  python examples/pretrain_llama.py --preset 8b   # the real recipe shape

With a mesh (e.g. on a pod slice):
  from paddle_tpu.distributed import env
  env.init_parallel_env({"dp": 2, "fsdp": 2, "tp": 2})
and the same script runs 4D-hybrid-parallel (add pp via
TrainingArguments(virtual_pp_degree=...) for interleaved pipelining).
"""
import argparse

import jax.numpy as jnp
import numpy as np

import paddle_tpu as pt
from paddle_tpu.models import LlamaConfig, LlamaForCausalLM, llama_tiny
from paddle_tpu.parallel.sharding import shard_layer
from paddle_tpu.trainer import Trainer, TrainingArguments


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--preset", default="tiny", choices=["tiny", "8b"])
    ap.add_argument("--steps", type=int, default=50)
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--seq", type=int, default=128)
    ap.add_argument("--out", default="output/pretrain")
    args = ap.parse_args()

    pt.seed(0)
    cfg = llama_tiny() if args.preset == "tiny" else \
        LlamaConfig(recompute=True)  # Llama-3-8B shape, bf16, remat
    model = LlamaForCausalLM(cfg)
    shard_layer(model)  # no-op without a mesh; SPMD with one

    rs = np.random.RandomState(0)

    class Synthetic:
        def __iter__(self):
            while True:
                yield jnp.asarray(
                    rs.randint(0, cfg.vocab_size, (args.batch, args.seq)))

    tr = Trainer(
        model,
        pt.optimizer.AdamW(learning_rate=3e-4, weight_decay=0.1,
                           grad_clip=pt.optimizer.ClipGradByGlobalNorm(1.0)),
        TrainingArguments(output_dir=args.out, max_steps=args.steps,
                          logging_steps=10, save_steps=0),
        train_dataloader=Synthetic())
    tr.train()


if __name__ == "__main__":
    main()
