"""Logits processors for autoregressive decoding (reference: PaddleNLP
paddlenlp/generation/logits_process.py — TopKProcess, TopPProcess,
temperature, repetition penalty).

All processors are pure jnp on static shapes so the whole decode loop
compiles into one XLA program (`lax.while_loop`), never re-tracing per
token. Filtering uses mask-to--inf (no dynamic shapes from sorting)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

NEG_INF = -1e30


def apply_temperature(logits, temperature):
    t = jnp.maximum(jnp.asarray(temperature, jnp.float32), 1e-6)
    return logits / t


def top_k_filter(logits, k: int):
    """Keep the k highest logits per row; mask the rest to -inf. Static k."""
    if k <= 0:
        return logits
    kth = jax.lax.top_k(logits, k)[0][..., -1:]
    return jnp.where(logits < kth, NEG_INF, logits)


def top_p_filter(logits, p: float):
    """Nucleus sampling: keep the smallest prefix of the sorted distribution
    with cumulative prob >= p (always keeps the argmax)."""
    sorted_logits = jnp.sort(logits, axis=-1)[..., ::-1]
    probs = jax.nn.softmax(sorted_logits, axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    # mask sorted positions whose *previous* cumulative already reached p
    keep_sorted = (cum - probs) < p
    # threshold = smallest kept logit
    thresh = jnp.min(jnp.where(keep_sorted, sorted_logits, jnp.inf),
                     axis=-1, keepdims=True)
    return jnp.where(logits < thresh, NEG_INF, logits)


def repetition_penalty(logits, generated_mask, penalty: float):
    """Divide (positive) / multiply (negative) logits of seen tokens
    (generated_mask [b, vocab] counts>0)."""
    if penalty == 1.0:
        return logits
    seen = generated_mask > 0
    penalized = jnp.where(logits > 0, logits / penalty, logits * penalty)
    return jnp.where(seen, penalized, logits)


def sample_token(logits, key, temperature=1.0, top_k=0, top_p=1.0,
                 do_sample=True):
    """logits [b, vocab] -> token ids [b]."""
    logits = logits.astype(jnp.float32)
    if not do_sample:
        return jnp.argmax(logits, axis=-1)
    logits = apply_temperature(logits, temperature)
    if top_k and top_k > 0:
        logits = top_k_filter(logits, top_k)
    if top_p < 1.0:
        logits = top_p_filter(logits, top_p)
    return jax.random.categorical(key, logits, axis=-1)
