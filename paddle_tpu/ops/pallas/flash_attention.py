"""Pallas TPU flash attention (reference: PHI flash_attn kernels,
paddle/phi/kernels/gpu/flash_attn_kernel.cu — reimagined for TPU).

Online-softmax blocked attention, FlashAttention-2 style, forward AND
backward as Pallas kernels:

- forward: grid (bh, q_blocks, kv_blocks), KV innermost so the fp32
  accumulator scratch carries across KV steps of one Q block; saves only
  out + logsumexp.
- backward dq: grid (bh, q_blocks, kv_blocks) — recompute p from (q,k,lse),
  accumulate dq across KV blocks.
- backward dk/dv: grid (bh_kv, kv_blocks, group, q_blocks) — the GQA group
  is an explicit grid dim so all query heads of a group accumulate into one
  (dk, dv) scratch; no materialized head repeat anywhere.

Block sizes: 1024x1024 measured 3.5ms vs XLA-dense 10.3ms on a v5e at
[8,2048,16/8,128] causal (the Llama bench shape); `pick_block` chooses the
largest tile that divides the sequence. Causal blocks strictly above the
diagonal are predicated off with @pl.when (their DMA still lands, compute
is skipped); partially-masked diagonal blocks mask inside the kernel.
"""
from __future__ import annotations

import functools
import math
import os

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

DEFAULT_BLOCK_Q = 1024
DEFAULT_BLOCK_K = 1024
NEG_INF = -1e30


def _interpret() -> bool:
    """Run the kernels in Pallas interpret mode (CPU testing)."""
    return bool(os.environ.get("PADDLE_TPU_PALLAS_INTERPRET"))


def pick_block(seq: int, preferred: int) -> int:
    """Largest MXU-friendly tile that divides seq; the kernels tile the
    sequence exactly, so a non-dividing block would silently drop the
    tail — fail loudly instead."""
    b = min(preferred, seq)
    while b > 128 and seq % b:
        b //= 2
    if seq % b:
        raise ValueError(
            f"flash attention needs seq divisible by a {{128..{preferred}}} "
            f"tile; got seq={seq} (pad the sequence or use dense_attention)")
    return b


def _scores(q, k, qi, ki, *, scale, causal, block_q, block_k,
            causal_offset, qs=None, ks=None, window=None):
    """q@k^T with the shared bottom-right causal mask — the ONE definition
    of the masking convention, inlined into fwd and both bwd kernels.
    qs [block_q, 128] / ks [1, block_k] (lane/sublane-broadcast segment-id
    tiles, the jax TPU flash layout) additionally mask cross-segment
    pairs — the packed-sequence case."""
    s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * scale
    if causal:
        q_ids = lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0) \
            + qi * block_q
        k_ids = lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1) \
            + ki * block_k
        keep = q_ids + causal_offset >= k_ids
        if window is not None:  # sliding window: trailing `window` keys
            keep &= (q_ids + causal_offset) - k_ids < window
        s = jnp.where(keep, s, NEG_INF)
    if qs is not None:
        qs_full = jnp.tile(qs, (1, block_k // 128))   # [block_q, block_k]
        s = jnp.where(qs_full == ks, s, NEG_INF)
    return s


# ----------------------------------------------------------------- forward
def _fwd_kernel(*refs, scale, causal, block_q, block_k, kv_blocks,
                causal_offset, has_seg, window=None):
    """causal_offset = sk - sq: bottom-right-aligned causal mask (matches
    the naive path and the backward), so query i attends keys <= i+offset."""
    if has_seg:
        (q_ref, k_ref, v_ref, qs_ref, ks_ref,
         o_ref, lse_ref, acc, m_scr, l_scr) = refs
    else:
        q_ref, k_ref, v_ref, o_ref, lse_ref, acc, m_scr, l_scr = refs
        qs_ref = ks_ref = None
    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        acc[:] = jnp.zeros_like(acc)
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)

    run = True
    if causal:
        # block [qi] attends kv blocks whose start <= last query's diag pos
        run = ki * block_k <= (qi + 1) * block_q - 1 + causal_offset
        if window is not None:  # ...and whose end reaches the window band
            run &= (ki + 1) * block_k - 1 >= \
                qi * block_q + causal_offset - (window - 1)

    @pl.when(run)
    def _compute():
        s = _scores(q_ref[0, :, :], k_ref[0, :, :], qi, ki, scale=scale,
                    causal=causal, block_q=block_q, block_k=block_k,
                    causal_offset=causal_offset, window=window,
                    qs=qs_ref[0] if has_seg else None,
                    ks=ks_ref[0, :1, :] if has_seg else None)
        m_prev = m_scr[:, :1]
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m_prev - m_new)
        l_new = alpha * l_scr[:, :1] + jnp.sum(p, axis=-1, keepdims=True)
        acc[:] = acc[:] * alpha + jax.lax.dot_general(
            p.astype(v_ref.dtype), v_ref[0, :, :], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)

    @pl.when(ki == kv_blocks - 1)
    def _finalize():
        safe_l = jnp.maximum(l_scr[:, :1], 1e-30)
        o_ref[0, :, :] = (acc[:] / safe_l).astype(o_ref.dtype)
        lse_ref[0, :, :] = jnp.broadcast_to(
            m_scr[:, :1] + jnp.log(safe_l), (acc.shape[0], 128))


def _seg_operands(segment_ids, heads):
    """[b, s] int32 -> the jax-TPU-flash layout: q ids broadcast into the
    128-lane dim, kv ids into an 8-sublane dim, so every block is
    (8,128)-tiled. ``heads`` lets the bh-flattened grids index batch as
    bh // heads."""
    seg = jnp.asarray(segment_ids, jnp.int32)
    b, s = seg.shape
    qs = jnp.broadcast_to(seg[:, :, None], (b, s, 128))
    ks = jnp.broadcast_to(seg[:, None, :], (b, 8, s))
    return qs, ks


def _flash_fwd(q, k, v, scale, causal, block_q, block_k,
               segment_ids=None, heads=1, window=None):
    """q: [bh, sq, d]; k/v: [bh_kv, sk, d] with bh % bh_kv == 0."""
    bh, sq, d = q.shape
    bh_kv, sk, _ = k.shape
    group = bh // bh_kv
    q_blocks = sq // block_q
    kv_blocks = sk // block_k
    has_seg = segment_ids is not None

    kernel = functools.partial(
        _fwd_kernel, scale=scale, causal=causal, block_q=block_q,
        block_k=block_k, kv_blocks=kv_blocks, causal_offset=sk - sq,
        has_seg=has_seg, window=window)

    in_specs = [
        pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((1, block_k, d), lambda b, i, j: (b // group, j, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((1, block_k, d), lambda b, i, j: (b // group, j, 0),
                     memory_space=pltpu.VMEM),
    ]
    operands = [q, k, v]
    if has_seg:
        h = heads
        in_specs += [
            pl.BlockSpec((1, block_q, 128), lambda b, i, j: (b // h, i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 8, block_k), lambda b, i, j: (b // h, 0, j),
                         memory_space=pltpu.VMEM),
        ]
        operands += list(_seg_operands(segment_ids, heads))

    out, lse = pl.pallas_call(
        kernel,
        grid=(bh, q_blocks, kv_blocks),
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_q, 128), lambda b, i, j: (b, i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, sq, d), q.dtype),
            jax.ShapeDtypeStruct((bh, sq, 128), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
        ],
        interpret=_interpret(),
    )(*operands)
    return out, lse[:, :, :1]   # [bh, sq, 1]


# ---------------------------------------------------------------- backward
def _bwd_dq_kernel(*refs, scale, causal, block_q, block_k, kv_blocks,
                   causal_offset, has_seg, window=None):
    if has_seg:
        (q_ref, k_ref, v_ref, g_ref, lse_ref, delta_ref, qs_ref, ks_ref,
         dq_ref, acc) = refs
    else:
        (q_ref, k_ref, v_ref, g_ref, lse_ref, delta_ref,
         dq_ref, acc) = refs
        qs_ref = ks_ref = None
    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        acc[:] = jnp.zeros_like(acc)

    run = True
    if causal:
        run = ki * block_k <= (qi + 1) * block_q - 1 + causal_offset
        if window is not None:
            run &= (ki + 1) * block_k - 1 >= \
                qi * block_q + causal_offset - (window - 1)

    @pl.when(run)
    def _compute():
        k = k_ref[0, :, :]
        s = _scores(q_ref[0, :, :], k, qi, ki, scale=scale, causal=causal,
                    block_q=block_q, block_k=block_k,
                    causal_offset=causal_offset, window=window,
                    qs=qs_ref[0] if has_seg else None,
                    ks=ks_ref[0, :1, :] if has_seg else None)
        p = jnp.exp(s - lse_ref[0, :, :1])            # exact probs via lse
        dp = jax.lax.dot_general(
            g_ref[0, :, :], v_ref[0, :, :], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta_ref[0, :, :1]) * scale
        acc[:] += jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(ki == kv_blocks - 1)
    def _finalize():
        dq_ref[0, :, :] = acc[:].astype(dq_ref.dtype)


def _bwd_dkv_kernel(*refs, scale, causal, block_q, block_k, group,
                    q_blocks, causal_offset, has_seg, window=None):
    if has_seg:
        (q_ref, k_ref, v_ref, g_ref, lse_ref, delta_ref, qs_ref, ks_ref,
         dk_ref, dv_ref, dk_acc, dv_acc) = refs
    else:
        (q_ref, k_ref, v_ref, g_ref, lse_ref, delta_ref,
         dk_ref, dv_ref, dk_acc, dv_acc) = refs
        qs_ref = ks_ref = None
    kj = pl.program_id(1)
    gi = pl.program_id(2)
    qi = pl.program_id(3)

    @pl.when((gi == 0) & (qi == 0))
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    run = True
    if causal:
        run = kj * block_k <= (qi + 1) * block_q - 1 + causal_offset
        if window is not None:
            run &= (kj + 1) * block_k - 1 >= \
                qi * block_q + causal_offset - (window - 1)

    @pl.when(run)
    def _compute():
        q = q_ref[0, :, :]
        s = _scores(q, k_ref[0, :, :], qi, kj, scale=scale, causal=causal,
                    block_q=block_q, block_k=block_k,
                    causal_offset=causal_offset, window=window,
                    qs=qs_ref[0] if has_seg else None,
                    ks=ks_ref[0, :1, :] if has_seg else None)
        p = jnp.exp(s - lse_ref[0, :, :1])
        g = g_ref[0, :, :]
        # dv += p^T g
        dv_acc[:] += jax.lax.dot_general(
            p.astype(g.dtype), g, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(
            g, v_ref[0, :, :], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta_ref[0, :, :1]) * scale
        # dk += ds^T q
        dk_acc[:] += jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when((gi == group - 1) & (qi == q_blocks - 1))
    def _finalize():
        dk_ref[0, :, :] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0, :, :] = dv_acc[:].astype(dv_ref.dtype)


def _flash_bwd(q, k, v, out, lse, g, scale, causal, block_q, block_k,
               segment_ids=None, heads=1, window=None):
    bh, sq, d = q.shape
    bh_kv, sk, _ = k.shape
    group = bh // bh_kv
    q_blocks = sq // block_q
    kv_blocks = sk // block_k
    offset = sk - sq
    has_seg = segment_ids is not None

    # delta_i = rowsum(dout * out): cheap XLA reduction, fp32
    delta = jnp.sum(g.astype(jnp.float32) * out.astype(jnp.float32),
                    axis=-1, keepdims=True)                  # [bh, sq, 1]

    dq_in_specs = [
        pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((1, block_k, d), lambda b, i, j: (b // group, j, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((1, block_k, d), lambda b, i, j: (b // group, j, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, 0),
                     memory_space=pltpu.VMEM),
    ]
    dq_operands = [q, k, v, g, lse, delta]
    dkv_in_specs = [
        pl.BlockSpec((1, block_q, d),
                     lambda b, j, gidx, i: (b * group + gidx, i, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((1, block_k, d), lambda b, j, gidx, i: (b, j, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((1, block_k, d), lambda b, j, gidx, i: (b, j, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((1, block_q, d),
                     lambda b, j, gidx, i: (b * group + gidx, i, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((1, block_q, 1),
                     lambda b, j, gidx, i: (b * group + gidx, i, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((1, block_q, 1),
                     lambda b, j, gidx, i: (b * group + gidx, i, 0),
                     memory_space=pltpu.VMEM),
    ]
    dkv_operands = [q, k, v, g, lse, delta]
    if has_seg:
        h, hk = heads, heads // group
        qs3, ks3 = _seg_operands(segment_ids, heads)
        dq_in_specs += [
            pl.BlockSpec((1, block_q, 128), lambda b, i, j: (b // h, i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 8, block_k), lambda b, i, j: (b // h, 0, j),
                         memory_space=pltpu.VMEM),
        ]
        dq_operands += [qs3, ks3]
        dkv_in_specs += [
            pl.BlockSpec((1, block_q, 128),
                         lambda b, j, gidx, i: (b // hk, i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 8, block_k),
                         lambda b, j, gidx, i: (b // hk, 0, j),
                         memory_space=pltpu.VMEM),
        ]
        dkv_operands += [qs3, ks3]

    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, scale=scale, causal=causal,
                          block_q=block_q, block_k=block_k,
                          kv_blocks=kv_blocks, causal_offset=offset,
                          has_seg=has_seg, window=window),
        grid=(bh, q_blocks, kv_blocks),
        in_specs=dq_in_specs,
        out_specs=pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((bh, sq, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        interpret=_interpret(),
    )(*dq_operands)

    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, scale=scale, causal=causal,
                          block_q=block_q, block_k=block_k, group=group,
                          q_blocks=q_blocks, causal_offset=offset,
                          has_seg=has_seg, window=window),
        grid=(bh_kv, kv_blocks, group, q_blocks),
        in_specs=dkv_in_specs,
        out_specs=[
            pl.BlockSpec((1, block_k, d), lambda b, j, gidx, i: (b, j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_k, d), lambda b, j, gidx, i: (b, j, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh_kv, sk, d), k.dtype),
            jax.ShapeDtypeStruct((bh_kv, sk, d), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
        interpret=_interpret(),
    )(*dkv_operands)
    return dq, dk, dv


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash(q, k, v, scale, causal, block_q, block_k, window=None):
    out, _ = _flash_fwd(q, k, v, scale, causal, block_q, block_k,
                        window=window)
    return out


def _flash_vjp_fwd(q, k, v, scale, causal, block_q, block_k, window=None):
    out, lse = _flash_fwd(q, k, v, scale, causal, block_q, block_k,
                          window=window)
    return out, (q, k, v, out, lse)


def _flash_vjp_bwd(scale, causal, block_q, block_k, window, res, g):
    q, k, v, out, lse = res
    return _flash_bwd(q, k, v, out, lse, g, scale, causal, block_q, block_k,
                      window=window)


_flash.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)


# -------------------------------------------------- flash with segment ids
@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8, 9))
def _flash_seg(q, k, v, seg, scale, causal, block_q, block_k, heads,
               window=None):
    out, _ = _flash_fwd(q, k, v, scale, causal, block_q, block_k,
                        segment_ids=seg, heads=heads, window=window)
    return out


def _flash_seg_vjp_fwd(q, k, v, seg, scale, causal, block_q, block_k,
                       heads, window=None):
    out, lse = _flash_fwd(q, k, v, scale, causal, block_q, block_k,
                          segment_ids=seg, heads=heads, window=window)
    return out, (q, k, v, seg, out, lse)


def _flash_seg_vjp_bwd(scale, causal, block_q, block_k, heads, window,
                       res, g):
    q, k, v, seg, out, lse = res
    dq, dk, dv = _flash_bwd(q, k, v, out, lse, g, scale, causal,
                            block_q, block_k, segment_ids=seg, heads=heads,
                            window=window)
    return dq, dk, dv, None  # int segment ids carry no cotangent


_flash_seg.defvjp(_flash_seg_vjp_fwd, _flash_seg_vjp_bwd)


def flash_attention_bshd(query, key, value, causal=False, scale=None,
                         block_q=DEFAULT_BLOCK_Q, block_k=DEFAULT_BLOCK_K,
                         segment_ids=None, window=None):
    """Flash attention on [batch, seq, heads, head_dim] (paddle layout).
    ``segment_ids`` [b, s] (0 = pad) restricts attention to same-segment
    pairs — packed-sequence training on the flash path. ``window`` (with
    causal) is sliding-window attention: only the trailing ``window``
    keys per query."""
    if window is not None and not causal:
        raise ValueError("window requires causal=True")
    b, sq, h, d = query.shape
    _, sk, hk, _ = key.shape
    scale = scale if scale is not None else 1.0 / math.sqrt(d)
    block_q = pick_block(sq, block_q)
    block_k = pick_block(sk, block_k)
    q = jnp.swapaxes(query, 1, 2).reshape(b * h, sq, d)
    k = jnp.swapaxes(key, 1, 2).reshape(b * hk, sk, d)
    v = jnp.swapaxes(value, 1, 2).reshape(b * hk, sk, d)
    if segment_ids is not None:
        out = _flash_seg(q, k, v, jnp.asarray(segment_ids, jnp.int32),
                         scale, causal, block_q, block_k, h, window)
    else:
        out = _flash(q, k, v, scale, causal, block_q, block_k, window)
    return jnp.swapaxes(out.reshape(b, h, sq, d), 1, 2)


# --------------------------------------------------- flash with exposed lse
@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash_lse(q, k, v, scale, causal, block_q, block_k):
    out, lse = _flash_fwd(q, k, v, scale, causal, block_q, block_k)
    return out, lse[:, :, 0]


def _flash_lse_vjp_fwd(q, k, v, scale, causal, block_q, block_k):
    out, lse = _flash_fwd(q, k, v, scale, causal, block_q, block_k)
    return (out, lse[:, :, 0]), (q, k, v, out, lse)


def _flash_lse_vjp_bwd(scale, causal, block_q, block_k, res, gs):
    """Backward with cotangents for BOTH outputs. d lse_i / d s_ij = p_ij,
    so the lse cotangent folds into delta: ds = p (dp - (delta - g_lse))."""
    q, k, v, out, lse = res
    g_out, g_lse = gs
    dq, dk, dv = _flash_bwd(q, k, v, out, lse, g_out, scale, causal,
                            block_q, block_k)
    # lse cotangent: d lse_i / d s_ij = p_ij, so
    # d/dq sum_i g_lse_i lse_i = g_lse_i * p_ij * k_j * scale (and sym. dk)
    dq2, dk2 = _lse_grad_terms(q, k, lse[:, :, 0], g_lse, scale, causal)
    dq = (dq.astype(jnp.float32) + dq2).astype(q.dtype)
    dk = (dk.astype(jnp.float32) + dk2).astype(k.dtype)
    return dq, dk, dv


def _lse_grad_terms(q, k, lse, g_lse, scale, causal):
    """Dense fallback for the lse-cotangent term (used only by ring
    attention's combine, where per-shard sequences are modest)."""
    bh, sq, d = q.shape
    bh_kv, sk, _ = k.shape
    group = bh // bh_kv
    kr = jnp.repeat(k, group, axis=0) if group > 1 else k
    s = jnp.einsum("bqd,bkd->bqk", q.astype(jnp.float32),
                   kr.astype(jnp.float32)) * scale
    if causal:
        mask = jnp.tril(jnp.ones((sq, sk), bool), k=sk - sq)
        s = jnp.where(mask, s, NEG_INF)
    p = jnp.exp(s - lse[:, :, None])
    w = p * g_lse[:, :, None] * scale
    dq = jnp.einsum("bqk,bkd->bqd", w, kr.astype(jnp.float32))
    dk = jnp.einsum("bqk,bqd->bkd", w, q.astype(jnp.float32))
    if group > 1:
        dk = dk.reshape(bh_kv, group, sk, d).sum(axis=1)
    return dq, dk


_flash_lse.defvjp(_flash_lse_vjp_fwd, _flash_lse_vjp_bwd)


def flash_attention_with_lse(query, key, value, causal=False, scale=None,
                             block_q=DEFAULT_BLOCK_Q,
                             block_k=DEFAULT_BLOCK_K):
    """[b, s, h, d] flash attention returning (out, lse[b, h, s]) — the
    building block for cross-device softmax merging (ring attention)."""
    b, sq, h, d = query.shape
    _, sk, hk, _ = key.shape
    scale = scale if scale is not None else 1.0 / math.sqrt(d)
    block_q = pick_block(sq, block_q)
    block_k = pick_block(sk, block_k)
    q = jnp.swapaxes(query, 1, 2).reshape(b * h, sq, d)
    k = jnp.swapaxes(key, 1, 2).reshape(b * hk, sk, d)
    v = jnp.swapaxes(value, 1, 2).reshape(b * hk, sk, d)
    out, lse = _flash_lse(q, k, v, scale, causal, block_q, block_k)
    out = jnp.swapaxes(out.reshape(b, h, sq, d), 1, 2)
    return out, lse.reshape(b, h, sq)
