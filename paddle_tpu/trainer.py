"""Trainer (reference: PaddleNLP paddlenlp/trainer/trainer.py — the
train loop with gradient accumulation, hybrid-parallel awareness, AMP,
checkpointing/auto-resume, callbacks, and eval).

TPU-native: ONE jitted train step (loss -> grads -> clip -> optimizer)
with donated (params, opt_state) so the update is in-place in HBM.
Gradient accumulation folds into the same program via `lax.scan` over the
microbatch dim — not N python-side steps. Hybrid parallelism is ambient:
if a mesh is installed, params are sharded by their partition metadata
(fleet.distributed_model) and the step compiles to SPMD; the loop itself
is identical single-chip vs pod. Aux wiring: JSONL metrics (C21), NaN
watchdog (C20), orbax auto-resume (C14)."""
from __future__ import annotations

import functools
import os
import sys
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Iterable, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from .models.llama import causal_lm_loss
from .nn.layer import Layer
from .optimizer.optimizers import Optimizer
from .utils import compile_cache, faults
from .utils import observability as obs
from .utils.logging import LogWriter
from .utils.profiler import StepTimer, llama_flops_per_token
from .utils.shutdown import PREEMPTED_RC, GracefulShutdown
from .utils.watchdog import DivergenceError, StepWatchdog


@dataclass
class TrainingArguments:
    """Reference: paddlenlp.trainer.TrainingArguments (subset that matters)."""
    output_dir: str = "output"
    max_steps: int = 1000
    gradient_accumulation_steps: int = 1
    logging_steps: int = 10
    save_steps: int = 0              # 0 = no periodic ckpt
    eval_steps: int = 0
    resume_from_checkpoint: bool = True
    max_grad_norm: float = 1.0
    seed: int = 42
    nan_patience: int = 3
    donate_state: bool = True
    # elastic training (reference: paddle.distributed.elastic): a step
    # that exceeds hang_timeout_s triggers best-effort checkpoint +
    # process exit with hang_exit_code; a supervisor
    # (distributed.elastic.supervise) relaunches and auto-resume picks
    # up from the latest complete checkpoint.
    hang_timeout_s: Optional[float] = None
    hang_exit_code: int = 17
    # on resume, fast-forward the data stream past the batches the
    # checkpointed steps already consumed (reference: PaddleNLP Trainer's
    # skip_first_batches / consumed_samples accounting) so the loss
    # trajectory continues instead of re-seeing epoch-start data
    skip_data_on_resume: bool = True
    # interleaved pipeline: virtual chunks per pp device (Megatron-style
    # virtual_pp_degree); >1 shrinks the pipeline bubble that many times
    virtual_pp_degree: int = 1
    # divergence recovery (chaos hardening): on DivergenceError reload
    # the latest complete checkpoint and continue — the data iterator is
    # NOT rewound, so the poisoned window (batches between checkpoint
    # and divergence) is skipped rather than replayed. After this many
    # rollbacks in one train() call the error propagates (a persistent
    # NaN is a bug or a bad lr, not a transient).
    max_divergence_rollbacks: int = 2
    # preemption safety: install a SIGTERM/SIGINT GracefulShutdown
    # handler for the duration of train(); the loop polls it at step
    # boundaries and, when tripped (scheduler preemption notice, ^C, or
    # the seeded `preempt` fault site), checkpoints the exact current
    # step, drains the async writer, and exits preempt_exit_code — which
    # distributed.elastic.supervise restarts for free (a preemption is
    # not a failure and never consumes a max_restarts attempt).
    graceful_shutdown: bool = True
    preempt_exit_code: int = PREEMPTED_RC
    # async input pipeline (perf): wrap the dataloader in a
    # DevicePrefetcher so batch prep + the H2D copy of step N+1 overlap
    # step N's compute instead of serializing with it. 0 disables
    # (synchronous feeding, the pre-ISSUE-4 behavior). Checkpoint meta
    # always records the CONSUMER position, so preemption/resume is
    # bit-identical with or without prefetch.
    prefetch_depth: int = 2
    # a prefetch producer that delivers nothing for this long (wedged
    # host pipeline, the seeded `prefetch_stall` fault) degrades the
    # loop to synchronous feeding instead of deadlocking it
    prefetch_stall_timeout_s: float = 5.0
    # persistent XLA compilation cache: a preempted-and-relaunched
    # worker restores the step executable from disk instead of paying
    # full recompilation. None falls back to
    # $PADDLE_TPU_COMPILE_CACHE_DIR (which elastic.supervise propagates
    # to relaunched children); unset entirely = no-op.
    compile_cache_dir: Optional[str] = None
    # compile the train step ahead-of-time on the first batch (before
    # step 0 "runs"), so compile time never counts against the first
    # checkpoint/logging interval
    aot_warmup: bool = False
    # per-token model FLOPs for the in-loop MFU log; 0 derives it from
    # the model config (llama-family) on the first batch
    flops_per_token: float = 0.0


class TrainerCallback:
    def on_step_end(self, step: int, logs: Dict[str, float]):  # noqa: D401
        pass

    def on_save(self, step: int):
        pass

    def on_train_end(self, step: int):
        pass


class Trainer:
    def __init__(self, model: Layer, optimizer: Optimizer,
                 args: Optional[TrainingArguments] = None,
                 loss_fn: Optional[Callable] = None,
                 train_dataloader: Optional[Iterable] = None,
                 eval_dataloader: Optional[Iterable] = None,
                 callbacks: Optional[List[TrainerCallback]] = None,
                 scaler=None, logits_loss: Optional[Callable] = None):
        self.model = model
        self.optimizer = optimizer
        self.args = args or TrainingArguments()
        # loss_fn(pure_fn, params, batch) -> scalar; default: causal LM on
        # a batch of token ids (the flagship recipe). logits_loss(logits,
        # labels) -> scalar swaps just the loss head while keeping the
        # token-ids recipe — unlike loss_fn it also works under pipeline
        # parallelism, where the loss must live at the LAST stage and a
        # whole-model loss_fn cannot be decomposed.
        if loss_fn is not None and logits_loss is not None:
            raise ValueError("pass loss_fn OR logits_loss, not both")
        self._default_loss = loss_fn is None
        self._logits_loss = logits_loss
        if loss_fn is not None:
            self.loss_fn = loss_fn
        elif logits_loss is not None:
            self.loss_fn = (
                lambda fn, p, batch: logits_loss(fn(p, batch), batch))
        else:
            self.loss_fn = (
                lambda fn, p, batch: causal_lm_loss(fn(p, batch), batch))
        self.train_dataloader = train_dataloader
        self.eval_dataloader = eval_dataloader
        self.callbacks = callbacks or []
        self.logger = LogWriter(os.path.join(self.args.output_dir, "runs"))
        self.watchdog = StepWatchdog(
            nan_patience=self.args.nan_patience,
            hang_timeout_s=self.args.hang_timeout_s,
            on_hang=self._on_hang if self.args.hang_timeout_s else None)
        # plain dict, NOT the OrderedDict functional() hands back: the
        # jitted step returns plain-dict params, and dict/OrderedDict are
        # DIFFERENT pytree node types — an OrderedDict here means step 2
        # silently retraces+recompiles the whole step (and permanently
        # invalidates the AOT-warmed executable)
        pure_fn, params = model.functional()
        self._pure_fn, self._params = pure_fn, dict(params)
        # PEFT/LoRA: parameters whose ParamMeta says trainable=False are
        # frozen — grads are taken only w.r.t. the trainable subset and
        # the optimizer holds state only for it (frozen weights never get
        # Adam moments). Empty tuple = everything trains (the usual case).
        meta = model.param_meta()
        self._trainable_keys = tuple(
            k for k in self._params if meta[k].trainable)
        self._has_frozen = len(self._trainable_keys) < len(self._params)
        self._opt_state = None
        self._step_fn = None
        self._eval_fn = None
        # fp16 loss scaling (amp.GradScaler); scaler state lives INSIDE the
        # jitted step — inf steps skip the update branchlessly (C6).
        self.scaler = scaler if (scaler is not None and scaler.is_enable()) \
            else None
        self._scaler_state = (self.scaler.init_state() if self.scaler
                              else None)
        self.global_step = 0
        self._rollbacks = 0
        self._in_recovery = False
        self._shutdown: Optional[GracefulShutdown] = None
        self._sampler_restored = False
        # live feed for the current/most-recent train(): the raw
        # dataloader, or the DevicePrefetcher wrapping it — checkpoint
        # meta must read sampler state from HERE (consumer position),
        # never from a loader the prefetcher has run ahead on
        self._data_feed = None
        self.step_timer: Optional[StepTimer] = None
        self._aot_done = False
        self._derived_flops: Optional[float] = None

    # ------------------------------------------------------------ jit step
    def _pp_degree(self) -> int:
        from .distributed import env
        return env.get_mesh().shape.get("pp", 1) if env.has_mesh() else 1

    def _build_step(self):
        fn, opt, args = self._pure_fn, self.optimizer, self.args
        scaler = self.scaler
        accum = args.gradient_accumulation_steps

        pp = self._pp_degree()
        if pp > 1 and hasattr(self.model, "pipeline_functional"):
            # 1F1B pipeline path: the schedule computes loss AND grads in
            # one manual-SPMD program (microbatches = grad-accum steps).
            if self._has_frozen:
                raise ValueError(
                    "frozen parameters (PEFT/LoRA) are not supported on "
                    "the pipeline-parallel path: the 1F1B schedule "
                    "differentiates the full stage stack; run LoRA under "
                    "tp/fsdp/dp instead")
            if scaler is not None:
                raise ValueError("fp16 GradScaler is not supported with "
                                 "pipeline parallelism (use bf16)")
            if not self._default_loss:
                raise ValueError(
                    "a whole-model loss_fn cannot be decomposed onto "
                    "pipeline stages; pass logits_loss=(logits, labels) -> "
                    "scalar instead — it runs at the last stage")
            vag = self.model.pipeline_functional(
                pp, logits_loss=self._logits_loss,
                vpp=args.virtual_pp_degree)

            def pp_step(params, state, sstate, stepno, batch):
                if not hasattr(batch, "ndim"):
                    raise TypeError(
                        "pipeline path expects a token-id array batch "
                        f"[n_micro, b, s] or [b, s], got {type(batch)}")
                if batch.ndim == 2:  # [b, s] -> single microbatch
                    batch = batch[None]
                loss, grads = vag(params, batch)
                params, state = opt.apply(params, grads, state, stepno)
                return params, state, sstate, loss

            donate = (0, 1) if args.donate_state else ()
            return jax.jit(pp_step, donate_argnums=donate)

        # One unified step: differentiate w.r.t. the TRAINABLE subset only
        # (PEFT/LoRA freezes the rest; the all-trainable case is simply
        # frozen = {}). Frozen params ride along as (donated) jit inputs,
        # not constants, and the optimizer sees only the trainable subset.
        tkeys = frozenset(self._trainable_keys)

        def loss_of(p, batch, stepno, mbidx):
            # route next_key() through a per-step traced key so dropout
            # masks change every step (a bare next_key() during tracing
            # would bake ONE host key in as a constant); fold the
            # microbatch index in too so grad-accum microbatches don't
            # share one dropout mask
            from .utils.rng import key_context
            key = jax.random.fold_in(jax.random.PRNGKey(args.seed), stepno)
            key = jax.random.fold_in(key, mbidx)
            with key_context(key):
                return self.loss_fn(fn, p, batch)

        def scaled_loss(p, mb, sstate, stepno, mbidx):
            loss = loss_of(p, mb, stepno, mbidx)
            scaled = scaler.scale(loss, sstate) if scaler else loss
            return scaled, loss

        def step(params, state, sstate, stepno, batch):
            frozen = {k: v for k, v in params.items() if k not in tkeys}
            tp = {k: v for k, v in params.items() if k in tkeys}
            vg = jax.value_and_grad(
                lambda t, b, ss, mi: scaled_loss({**frozen, **t}, b, ss,
                                                 stepno, mi),
                has_aux=True)
            if accum == 1:
                (_, loss), grads = vg(tp, batch, sstate, jnp.int32(0))
            else:
                # batch leading dim = accum: scan microbatches, mean grads
                # (dropout masks vary per step via stepno AND per
                # microbatch via the scanned index)
                def micro(carry, xs):
                    mi, mb = xs
                    gsum, lsum = carry
                    (_, l), g = vg(tp, mb, sstate, mi)
                    return (jax.tree.map(jnp.add, gsum, g), lsum + l), None
                zeros = jax.tree.map(jnp.zeros_like, tp)
                (gsum, lsum), _ = jax.lax.scan(
                    micro, (zeros, 0.0), (jnp.arange(accum), batch))
                grads = jax.tree.map(lambda g: g / accum, gsum)
                loss = lsum / accum
            if scaler is None:
                new_tp, state = opt.apply(tp, grads, state, stepno)
            else:
                # fp16: unscale, branchlessly skip the update on inf/nan
                # grads, and advance the dynamic loss scale — in this jit.
                grads, found_inf = scaler.unscale(grads, sstate)
                cand_tp, cand_state = opt.apply(tp, grads, state, stepno)
                new_tp = scaler.select(found_inf, tp, cand_tp)
                state = scaler.select(found_inf, state, cand_state)
                sstate = scaler.update_state(sstate, found_inf)
            params = {**params, **new_tp}
            return params, state, sstate, loss

        donate = (0, 1) if args.donate_state else ()
        return jax.jit(step, donate_argnums=donate)

    # ------------------------------------------------------------- train
    def train(self, max_steps: Optional[int] = None):
        args = self.args
        max_steps = max_steps or args.max_steps
        # persistent compilation cache BEFORE anything traces: a
        # relaunched (e.g. preempted) worker restores the byte-identical
        # step executable from disk instead of recompiling. No-op when
        # neither args nor $PADDLE_TPU_COMPILE_CACHE_DIR is set.
        compile_cache.enable(args.compile_cache_dir)
        # observability artifacts (trace_<attempt>.json,
        # flight_<attempt>.json, metrics.prom) land in the SAME run dir
        # as the JSONL metrics — one dir answers "what happened"
        obs.configure(os.path.join(args.output_dir, "runs"))
        obs.record_event("train_start", step=self.global_step,
                         max_steps=max_steps, run_id=obs.run_id(),
                         attempt=obs.attempt_id())
        if self._opt_state is None:
            self._opt_state = self.optimizer.init(
                {k: self._params[k] for k in self._trainable_keys}
                if self._has_frozen else self._params)
        if args.resume_from_checkpoint and args.save_steps:
            self._try_resume()
        if self._step_fn is None:
            self._step_fn = self._build_step()

        assert self.train_dataloader is not None, "pass train_dataloader"
        # async feed (AFTER _try_resume restored the sampler position):
        # prep + device placement of batch N+1 overlap step N's compute
        feed = self.train_dataloader
        # legacy fallback: no sampler state in the checkpoint (plain
        # iterables, pre-meta checkpoints) — blind O(global_step) replay
        # of the stream. Loaders with state_dict support are restored in
        # O(1) by _try_resume instead.
        legacy_skip = bool(self.global_step and args.skip_data_on_resume
                           and not self._sampler_restored)
        if args.prefetch_depth > 0:
            initial_iter = None
            if legacy_skip:
                # skip on the RAW loader: discarded batches must not pay
                # accum-fold prep + an H2D copy in the producer thread
                initial_iter = self._skip_consumed(
                    iter(self.train_dataloader), self.global_step,
                    source=self.train_dataloader)
            from .io.device_prefetch import DevicePrefetcher
            feed = DevicePrefetcher(
                self.train_dataloader, prep=self._prep_batch,
                depth=args.prefetch_depth,
                stall_timeout_s=args.prefetch_stall_timeout_s,
                initial_iter=initial_iter)
        self._data_feed = feed
        data = iter(feed)
        if legacy_skip and feed is self.train_dataloader:
            data = self._skip_consumed(data, self.global_step)
        self._rollbacks = 0
        if self._shutdown is not None:
            # a latch tripped in a PREVIOUS train() call must not make
            # this one exit before its first step
            self._shutdown.clear()
        if args.graceful_shutdown:
            if self._shutdown is None:
                self._shutdown = GracefulShutdown()
            self._shutdown.install()
        try:
            return self._train_loop(data, max_steps)
        except SystemExit:
            raise      # preempt/hang exits dump their own flight record
        except BaseException as e:
            # crash postmortem: the last ring-buffer window (recent
            # steps, fault fires, rollbacks, ckpt events) hits disk
            # BEFORE the exception unwinds out of the trainer
            obs.record_event("crash", step=self.global_step,
                             error=repr(e))
            obs.dump_flight(f"crash:{type(e).__name__}")
            raise
        finally:
            # the trace + Prometheus snapshot are written on EVERY exit
            # path (normal completion included)
            obs.flush()
            if feed is not self.train_dataloader:
                # tears the producer thread down; the prefetcher retains
                # the consumer position so a post-train save_checkpoint
                # still records truthful sampler state
                feed.close()
            if self._shutdown is not None:
                self._shutdown.uninstall()

    def _train_loop(self, data, max_steps: int):
        args = self.args
        prefetching = self._data_feed is not self.train_dataloader
        # windowed throughput meter: totals accumulate only while the
        # loop is actually stepping — save/eval wall time is stopped out
        # of the window, so tokens_per_sec/mfu measure the step loop,
        # not checkpoint I/O
        timer = self.step_timer = StepTimer(
            flops_per_token=args.flops_per_token)
        # registry handles cached outside the loop: the per-step cost is
        # an inc/observe (one small lock), not a registry lookup
        m_steps = obs.counter("train_steps_total")
        h_step = obs.histogram("train_step_wall_ms")
        win_tokens = 0
        win_steps = 0
        t_last = time.perf_counter()
        timer.start()
        while self.global_step < max_steps:
            t_step = time.perf_counter()
            if faults.inject("preempt", step=self.global_step):
                # chaos: deterministic stand-in for a scheduler
                # preemption notice (SIGTERM) landing between steps
                sd = self._shutdown or GracefulShutdown()
                self._shutdown = sd
                sd.request("injected preempt")
            if self._shutdown is not None and self._shutdown.requested():
                self._preempt_exit()
            if faults.inject("hang", step=self.global_step):
                # chaos: simulated stuck step (preempted chip) — the
                # StepWatchdog hang path must checkpoint and exit
                time.sleep(faults.hang_seconds())
            try:
                batch = next(data)
            except StopIteration:
                data = iter(self._data_feed)
                try:
                    batch = next(data)
                except StopIteration:
                    # a bare StopIteration from the second next() would
                    # leak out of the loop as a silent early return
                    raise ValueError("train_dataloader is empty") from None
            if not prefetching:
                # the prefetcher already prepped + placed in its thread
                batch = self._prep_batch(batch)
            if timer.flops_per_token == 0.0:
                if self._derived_flops is None:
                    self._derived_flops = self._derive_flops_per_token(batch)
                timer.flops_per_token = self._derived_flops
            if args.aot_warmup and not self._aot_done:
                self._aot_warmup(batch)
                # compile happened before "step 0"; don't bill it to the
                # first throughput window
                timer.start()
                t_last = time.perf_counter()
            stepno = self.global_step
            with obs.span("train_step", step=stepno):
                self._params, self._opt_state, self._scaler_state, loss = \
                    self._step_fn(self._params, self._opt_state,
                                  self._scaler_state,
                                  jnp.int32(stepno), batch)
            self.global_step += 1
            # host-side step wall (data wait + dispatch; device compute
            # overlaps asynchronously and is amortized into the window
            # by the logging-step sync) — the per-step series behind
            # obs_report's p50/p99 and the flight record's recent
            # window. step= matches the train_step span's number (the
            # step just executed), so trace and flight cross-reference.
            step_ms = (time.perf_counter() - t_step) * 1e3
            h_step.observe(step_ms)
            m_steps.inc()
            obs.record_event("step_end", step=stepno,
                             ms=round(step_ms, 3))
            win_tokens += self._batch_tokens(batch)
            win_steps += 1
            self.watchdog.beat()
            if faults.inject("step_nan", step=self.global_step):
                # chaos: numeric divergence — NaN the float params (as a
                # real NaN-grad step would) and the reported loss, then
                # let the watchdog + rollback loop recover
                self._params = jax.tree.map(
                    lambda x: x * float("nan")
                    if jnp.issubdtype(jnp.asarray(x).dtype, jnp.floating)
                    else x, self._params)
                loss = jnp.float32(float("nan"))
            if self.global_step % args.logging_steps == 0 or \
                    self.global_step == max_steps:
                loss_val = float(loss)   # host sync: closes the window
                try:
                    self.watchdog.check_loss(loss_val, self.global_step)
                except DivergenceError:
                    obs.record_event("divergence", step=self.global_step,
                                     loss=loss_val)
                    if not self._maybe_rollback():
                        raise
                    # rollback time (restore I/O) is not step time
                    t_last = time.perf_counter()
                    timer.start()
                    win_tokens = 0
                    win_steps = 0
                    continue
                now = time.perf_counter()
                dt = timer.stop(win_tokens, win_steps)
                tps = win_tokens / max(dt, 1e-9)
                logs = {"loss": loss_val,
                        # win_steps, not args.logging_steps: a save/eval
                        # (or resume) landing mid-window resets t_last,
                        # so the denominator only spans the steps since —
                        # the numerator must match
                        "steps_per_sec": win_steps / (now - t_last),
                        "tokens_per_sec": tps,
                        "mfu": timer.flops_per_token * tps /
                        timer.peak_flops if timer.flops_per_token else 0.0}
                win_tokens = 0
                win_steps = 0
                t_last = now
                timer.start()
                self.logger.add_scalars(logs, self.global_step)
                # mirror the window metrics into registry gauges and
                # merge the WHOLE registry (serving counters, prefetch
                # gauges, ckpt histograms included) into the same JSONL
                # stream the dashboards already tail
                for k, v in logs.items():
                    obs.gauge(f"train_{k}").set(v)
                try:
                    obs.gauge("train_lr").set(self.optimizer.get_lr())
                except Exception:
                    pass       # exotic schedules: lr gauge is optional
                obs.publish(self.logger, self.global_step)
                for cb in self.callbacks:
                    cb.on_step_end(self.global_step, logs)
            due_save = args.save_steps and \
                self.global_step % args.save_steps == 0
            due_eval = args.eval_steps and self.eval_dataloader is not None \
                and self.global_step % args.eval_steps == 0
            if due_save or due_eval:
                # close the throughput window BEFORE the save/eval (and
                # drain in-flight compute so it isn't silently credited
                # to the excluded span); the timer restart + t_last
                # reset below keep save/eval wall time out of both the
                # StepTimer totals and the next steps_per_sec window.
                # Skipped when the logging branch just closed it —
                # stopping an empty window would pad StepTimer.steps
                # with a zero-length entry and skew avg_step_s.
                if win_steps:
                    jax.block_until_ready(loss)
                    timer.stop(win_tokens, win_steps)
                    win_tokens = 0
                    win_steps = 0
                if due_save:
                    self.save_checkpoint()
                    self.watchdog.beat()  # a long save is not a hung step
                if due_eval:
                    self.evaluate()
                    self.watchdog.beat()  # ditto a long eval
                timer.start()
                t_last = time.perf_counter()
        for cb in self.callbacks:
            cb.on_train_end(self.global_step)
        if getattr(self, "_ckpt", None) is not None:
            # drain the cached manager's async write + manifest so a
            # finished run's last checkpoint is durable
            self._ckpt.wait_until_finished()
        # leave the module tree holding the trained weights
        self.model.bind(self._params)
        return self

    def _skip_consumed(self, data, n: int, source=None):
        """Advance the data iterator past ``n`` already-trained batches,
        re-iterating ``source`` (default: the live feed) at epoch
        boundaries."""
        if source is None:
            source = self._data_feed
        skip = n
        while skip > 0:
            got_any = False
            try:
                next(data)
                got_any = True
                skip -= 1
            except StopIteration:
                data = iter(source)
                try:
                    next(data)
                    skip -= 1
                except StopIteration:
                    if not got_any:
                        raise RuntimeError("train_dataloader is empty; "
                                           "cannot skip consumed batches")
        return data

    def _prep_batch(self, batch):
        accum = self.args.gradient_accumulation_steps
        if accum > 1:
            def fold(x):
                b = x.shape[0]
                assert b % accum == 0, f"batch {b} % accum {accum} != 0"
                return x.reshape((accum, b // accum) + x.shape[1:])
            if hasattr(batch, "shape"):
                batch = fold(batch)
            elif isinstance(batch, dict):  # SFT/DPO dict batches
                batch = {k: fold(v) for k, v in batch.items()}
        return batch

    # ------------------------------------------------------- perf meters
    @staticmethod
    def _token_array(batch):
        """The token-id array of a batch ([b, s] or the accum-folded
        [accum, b, s]): dict batches by ``input_ids``, tuple batches by
        first element. None when the batch carries no shaped array —
        the one unwrap heuristic shared by token counting and FLOPs
        derivation, so the mfu ratio can't silently diverge."""
        x = batch
        if isinstance(x, dict):
            x = x.get("input_ids", next(iter(x.values())))
        elif isinstance(x, (list, tuple)) and x:
            x = x[0]
        return x if getattr(x, "shape", None) else None

    @classmethod
    def _batch_tokens(cls, batch) -> int:
        """Token count of a step's batch for the throughput log."""
        x = cls._token_array(batch)
        return int(np.prod(x.shape)) if x is not None else 0

    def _derive_flops_per_token(self, batch) -> float:
        """Per-token train FLOPs for the MFU log when args.flops_per_token
        is unset: the 6N + attention estimate from the model config
        (llama-family shape); 0.0 when the config doesn't expose the
        needed fields (mfu then logs as 0)."""
        cfg = getattr(self.model, "config", None)
        layers = getattr(cfg, "num_hidden_layers", None)
        hidden = getattr(cfg, "hidden_size", None)
        if not layers or not hidden:
            return 0.0
        x = self._token_array(batch)
        if x is None:
            return 0.0
        seq = int(x.shape[-1])
        n_params = sum(int(np.prod(v.shape)) for v in self._params.values()
                       if hasattr(v, "shape"))
        # honest 6N, matching bench.py's headline formula: the input
        # embedding is a gather, not a matmul, so its params don't
        # belong in 6N (lm_head does — it IS a matmul)
        vocab = getattr(cfg, "vocab_size", None)
        if vocab:
            n_params -= vocab * hidden
        return llama_flops_per_token(n_params, layers, seq, hidden)

    def _aot_warmup(self, batch):
        """Compile the train step ahead of the first dispatch
        (jit(...).lower().compile()), so XLA compile time lands before
        step 0 instead of inside the first checkpoint interval. The
        compiled executable is shape-pinned; if a later batch drifts
        (e.g. a ragged epoch tail) the wrapper falls back to the
        original jit, which recompiles for the new shape as before."""
        self._aot_done = True
        jitted = self._step_fn
        if not hasattr(jitted, "lower"):   # already warmed/wrapped
            return
        t0 = time.perf_counter()
        try:
            compiled = jitted.lower(
                self._params, self._opt_state, self._scaler_state,
                jnp.int32(self.global_step), batch).compile()
        except Exception as e:
            print(f"[trainer] AOT warmup failed ({e}); falling back to "
                  f"on-demand jit", file=sys.stderr, flush=True)
            return
        print(f"[trainer] AOT warmup: train step compiled in "
              f"{time.perf_counter() - t0:.1f}s before step 0",
              file=sys.stderr, flush=True)
        self.watchdog.beat()               # a long compile is not a hang

        def stepper(*a):
            try:
                return compiled(*a)
            except (TypeError, ValueError):
                # shape drift: the AOT executable rejects BEFORE running
                # (donated buffers untouched); jit handles it
                return jitted(*a)

        self._step_fn = stepper

    # ------------------------------------------------------------- eval
    def evaluate(self) -> float:
        assert self.eval_dataloader is not None
        fn = self._pure_fn
        losses = []
        # trace the eval program with the module tree in eval mode so
        # dropout (incl. LoRA adapter dropout) is OFF — training flags are
        # trace-time constants, so flipping them here bakes eval semantics
        # into this executable without touching the jitted train step
        was_training = self.model.training
        self.model.eval()
        try:
            with obs.span("evaluate", step=self.global_step):
                if self._eval_fn is None:  # built once; jit caches/shape
                    self._eval_fn = jax.jit(
                        lambda p, b: self.loss_fn(fn, p, b))
                for batch in self.eval_dataloader:
                    # collect DEVICE scalars: each float() here would
                    # block the host once per batch, serializing dispatch
                    # with compute — one device_get at the end syncs once
                    losses.append(self._eval_fn(self._params, batch))
                losses = jax.device_get(losses) if losses else []
        finally:
            if was_training:
                self.model.train()
        mean = float(np.mean(losses)) if len(losses) else float("nan")
        self.logger.add_scalar("eval_loss", mean, self.global_step)
        obs.record_event("eval", step=self.global_step, loss=mean)
        return mean

    # --------------------------------------------------------- checkpoint
    def _ckpt_dir(self):
        return os.path.join(self.args.output_dir, "checkpoints")

    def _ckpt_manager(self):
        """ONE long-lived DistributedCheckpoint across the run: per-save
        create/close would force every periodic save to drain the async
        write AND hash the integrity manifest synchronously in the train
        loop — the cached manager keeps both in the background."""
        if getattr(self, "_ckpt", None) is None:
            from .checkpoint.distributed_ckpt import DistributedCheckpoint
            self._ckpt = DistributedCheckpoint(self._ckpt_dir())
        return self._ckpt

    def save_checkpoint(self, wait: bool = False):
        ckpt = self._ckpt_manager()
        tree = {"params": dict(self._params), "opt_state": self._opt_state}
        if self._scaler_state is not None:
            tree["scaler"] = self._scaler_state
        if self.args.donate_state and not wait:
            # the async write drains AFTER the next step DONATES these
            # exact buffers — hand orbax its own device-side copy or the
            # checkpoint bytes become whatever the reused buffers hold
            tree = jax.tree.map(
                lambda x: jnp.copy(x) if hasattr(x, "dtype") else x, tree)
        with obs.span("checkpoint_save", step=self.global_step,
                      wait=wait):
            ckpt.save(self.global_step, tree, wait=wait,
                      meta=self._checkpoint_meta())
        for cb in self.callbacks:
            cb.on_save(self.global_step)

    def _dp_degree(self) -> int:
        """Batch-sharding degree of the live mesh (dp and fsdp both
        split the batch; 1 with no mesh installed)."""
        from .distributed import env
        if not env.has_mesh():
            return 1
        shape = env.get_mesh().shape
        return int(shape.get("dp", 1)) * int(shape.get("fsdp", 1))

    def _checkpoint_meta(self) -> Dict[str, Any]:
        """Host-side sidecar for the step: sampler position (O(1)
        resume) + the topology manifest (cross-topology reconcile)."""
        topo: Dict[str, Any] = {
            "device_count": jax.device_count(),
            "dp": self._dp_degree(),
            "accum": self.args.gradient_accumulation_steps,
        }
        mesh_shape = self._live_mesh_shape()
        if mesh_shape is not None:
            topo["mesh"] = mesh_shape
        meta: Dict[str, Any] = {"step": self.global_step,
                                "topology": topo}
        # read sampler state from the live feed: with prefetch active
        # the raw loader has run AHEAD by the buffer depth, and saving
        # its cursor would skip buffered-but-untrained batches on
        # resume; the DevicePrefetcher reports the consumer position
        dl = self._data_feed if self._data_feed is not None \
            else self.train_dataloader
        if dl is not None and hasattr(dl, "state_dict"):
            try:
                sd = dl.state_dict()
                if sd:
                    meta["sampler"] = sd
            except Exception as e:  # sampler state is best-effort
                print(f"[trainer] sampler state_dict failed: {e}",
                      file=sys.stderr, flush=True)
        return meta

    def _preempt_exit(self):
        """Graceful-shutdown path: checkpoint the EXACT current step
        (sampler cursor included), drain the async writer so the save is
        durable, and exit with the preemption code the elastic
        supervisor restarts for free. SystemExit (not os._exit): the
        main thread is healthy here and should unwind cleanly."""
        reason = (self._shutdown.reason if self._shutdown else None) \
            or "requested"
        print(f"[trainer] preemption ({reason}) at global_step="
              f"{self.global_step}: checkpointing and exiting "
              f"rc={self.args.preempt_exit_code}",
              file=sys.stderr, flush=True)
        obs.record_event("preempt_exit", step=self.global_step,
                         reason=reason, rc=self.args.preempt_exit_code)
        try:
            self.save_checkpoint(wait=True)
        except Exception as e:
            # the grace window beats a perfect save: the latest periodic
            # checkpoint stands and the relaunch resumes from it
            print(f"[trainer] checkpoint during preemption failed: {e}; "
                  f"exiting anyway", file=sys.stderr, flush=True)
            obs.record_event("preempt_ckpt_failed", error=repr(e))
        # the flight dump happens AFTER the shutdown checkpoint so the
        # record's tail shows the fault/latch AND the save that answered
        # it — the acceptance shape of a clean preemption postmortem
        obs.dump_flight("preempt")
        raise SystemExit(self.args.preempt_exit_code)

    def _on_hang(self):
        """Monitor-thread path for a hung step (preempted chip, stuck
        host callback): best-effort checkpoint, then hard-exit so the
        elastic supervisor can relaunch. os._exit, not sys.exit — the
        main thread is stuck and would never unwind."""
        import sys
        print(f"[watchdog] step hung > {self.args.hang_timeout_s}s at "
              f"global_step={self.global_step}; checkpointing and exiting "
              f"rc={self.args.hang_exit_code}", file=sys.stderr, flush=True)
        obs.record_event("hang", step=self.global_step,
                         timeout_s=self.args.hang_timeout_s)
        obs.dump_flight("hang")
        if self._in_recovery:
            # wedged INSIDE a divergence rollback: params are NaN — a
            # snapshot now would become the latest checkpoint and poison
            # every future auto-resume. Exit without saving; the last
            # complete checkpoint stands and the supervisor relaunches.
            print("[watchdog] hang during divergence recovery; exiting "
                  "WITHOUT checkpointing (params are diverged)",
                  file=sys.stderr, flush=True)
            os._exit(self.args.hang_exit_code)
        # the save itself can wedge if the device is gone (device->host
        # copies blocking, not raising) — give it a bounded side thread
        # and exit regardless, or the detected hang becomes permanent
        import threading

        def _save():
            try:
                self.save_checkpoint(wait=True)
            except Exception as e:
                print(f"[watchdog] checkpoint during hang failed: {e}",
                      file=sys.stderr, flush=True)

        t = threading.Thread(target=_save, daemon=True)
        t.start()
        t.join(timeout=max(30.0, 2 * self.args.hang_timeout_s))
        if t.is_alive():
            print("[watchdog] checkpoint did not finish in time; exiting "
                  "anyway (latest periodic checkpoint stands)",
                  file=sys.stderr, flush=True)
        os._exit(self.args.hang_exit_code)

    def _maybe_rollback(self) -> bool:
        """Bounded divergence recovery: reload the latest complete (and
        checksum-verified) checkpoint and continue training. The data
        iterator is deliberately NOT rewound — the poisoned window
        (batches consumed between the checkpoint and the divergence) is
        skipped, not replayed into the restored params. Returns False
        (caller re-raises) when rollbacks are exhausted or there is no
        checkpoint to return to."""
        import sys
        if self._rollbacks >= self.args.max_divergence_rollbacks:
            print(f"[trainer] divergence persists after {self._rollbacks} "
                  f"rollback(s); giving up", file=sys.stderr, flush=True)
            return False
        diverged_at = self.global_step
        # a long restore must not trip the hang watchdog: params are NaN
        # right now, and _on_hang would checkpoint them as the new
        # latest (a permanent NaN resume loop). Flag the recovery so the
        # hang path skips its snapshot, and beat around the restore.
        self._in_recovery = True
        self.watchdog.beat()
        try:
            # restore_data=False: the live iterator is deliberately NOT
            # rewound (poisoned-window skip) — restoring the sampler
            # cursor here would replay checkpointed-epoch data at the
            # next epoch wrap
            restored = self._try_resume(restore_data=False)
        finally:
            self._in_recovery = False
            self.watchdog.beat()
        if restored is None:
            print("[trainer] divergence with no complete checkpoint to "
                  "roll back to", file=sys.stderr, flush=True)
            return False
        self._rollbacks += 1
        self.watchdog.reset_nan()
        print(f"[trainer] divergence at step {diverged_at}: rolled back "
              f"to checkpoint step {restored} "
              f"(rollback {self._rollbacks}/"
              f"{self.args.max_divergence_rollbacks}); skipping the "
              f"poisoned data window", file=sys.stderr, flush=True)
        obs.counter("train_rollbacks_total").inc()
        obs.record_event("rollback", diverged_at=diverged_at,
                         restored_step=restored,
                         rollback=self._rollbacks)
        obs.dump_flight("divergence_rollback")
        return True

    def _try_resume(self, restore_data: bool = True) -> Optional[int]:
        """Restore the latest complete checkpoint if one exists; returns
        the restored step (None if there was nothing to restore).
        ``restore_data=False`` (divergence rollback) restores arrays
        only, leaving the live data iterator's position untouched."""
        if not os.path.isdir(self._ckpt_dir()):
            return None
        ckpt = self._ckpt_manager()
        # rollback can race an in-flight async save: make it durable
        # (and its manifest written) before choosing the restore step
        ckpt.wait_until_finished()
        step = ckpt.latest_complete_step()
        if step is not None:
            base = {"params": dict(self._params),
                    "opt_state": self._opt_state}
            # the checkpoint may or may not contain scaler state (run
            # restarted with/without fp16): try the matching tree first,
            # fall back to the other shape rather than aborting resume.
            likes = [base]
            if self._scaler_state is not None:
                likes.insert(0, {**base, "scaler": self._scaler_state})
            else:
                from .amp import GradScaler
                likes.append({**base, "scaler": GradScaler().init_state()})
            restored = None
            first_err = None
            for like in likes:
                try:
                    restored = ckpt.restore(step, like=like)
                    break
                except Exception as e:
                    first_err = first_err or e
            if restored is None:
                # every tree shape failed: report the PRIMARY error (the
                # fallback's mismatch error would mislead diagnosis)
                raise first_err
            # Two placement fixups in one pass:
            # - defensive copy (donate_state): the jitted step DONATES
            #   params/opt state, but orbax-restored arrays can share
            #   internal buffers with the restore machinery — donating
            #   those double-frees and corrupts the heap (observed on
            #   XLA:CPU). A fresh copy owns its buffers.
            # - mesh re-placement (cross-topology resume): orbax commits
            #   restored arrays to the devices of the restore target; if
            #   that target was not laid out on the LIVE mesh (plain
            #   host params as `like`, or a checkpoint from a different
            #   topology), the committed placement conflicts with the
            #   step's mesh sharding constraints — replicate such arrays
            #   onto the current mesh (arrays already spanning the mesh
            #   keep their sharding).
            from .distributed import env as denv
            mesh = denv.get_mesh() if denv.has_mesh() else None
            mesh_devs = set(mesh.devices.flat) if mesh is not None else None

            def _fix(x):
                if not hasattr(x, "dtype"):
                    return x
                sh = getattr(x, "sharding", None)
                if mesh is not None and (
                        sh is None or set(sh.device_set) != mesh_devs):
                    from jax.sharding import NamedSharding, PartitionSpec
                    return jax.device_put(
                        x, NamedSharding(mesh, PartitionSpec()))
                return jnp.copy(x) if self.args.donate_state else x

            restored = jax.tree.map(_fix, restored)
            self._params = restored["params"]
            self._opt_state = restored["opt_state"]
            if self._scaler_state is not None and "scaler" in restored:
                self._scaler_state = restored["scaler"]
            # restore() may have fallen back past a corrupt latest step;
            # global_step must track what was actually loaded
            step = ckpt.last_restored_step
            self.global_step = step
            if restore_data:
                self._restore_meta(ckpt, step)
        return step

    def _restore_meta(self, ckpt, step: int):
        """Apply the step's meta sidecar: O(1) sampler-position restore
        (replacing _skip_consumed's blind replay) and cross-topology
        reconciliation when the checkpoint was written under a different
        mesh."""
        self._sampler_restored = False
        meta = ckpt.load_meta(step)
        if not meta:
            return
        self._reconcile_topology(meta.get("topology"))
        sd = meta.get("sampler")
        dl = self.train_dataloader
        if sd and dl is not None and hasattr(dl, "load_state_dict"):
            try:
                dl.load_state_dict(sd)
                self._sampler_restored = True
            except Exception as e:
                print(f"[trainer] sampler state restore failed ({e}); "
                      f"falling back to data replay",
                      file=sys.stderr, flush=True)

    def _reconcile_topology(self, saved: Optional[Dict[str, Any]]):
        """The job may come back with a different world size (preemptible
        pods): keep the EFFECTIVE global batch constant by recomputing
        gradient accumulation from the saved dp degree, and log the
        change. The per-rank index space re-shards inside
        DistributedBatchSampler.load_state_dict (its consumed counter is
        topology-independent), and orbax re-shards the arrays onto the
        live mesh via the restore target shardings."""
        if not saved:
            return
        cur_dp = self._dp_degree()
        old_dp = int(saved.get("dp", cur_dp) or cur_dp)
        if old_dp == cur_dp:
            return
        old_accum = int(saved.get("accum",
                                  self.args.gradient_accumulation_steps))
        effective = old_dp * old_accum
        new_accum = max(1, effective // cur_dp)
        # the accum factor must divide the loader batch (the jitted step
        # folds the batch into accum microbatches) — clamp down to the
        # nearest divisor rather than crashing the first resumed step
        batch = self._loader_batch_size()
        if batch:
            while batch % new_accum:
                new_accum -= 1
        if new_accum * cur_dp != effective:
            print(f"[trainer] effective global batch not exactly "
                  f"preservable: dp {old_dp}->{cur_dp} with accum "
                  f"{old_accum}, loader batch {batch} "
                  f"(using accum={new_accum})",
                  file=sys.stderr, flush=True)
        print(f"[trainer] topology change on resume: dp {old_dp} -> "
              f"{cur_dp} (mesh {saved.get('mesh')} -> now "
              f"{self._live_mesh_shape()}); gradient accumulation "
              f"{old_accum} -> {new_accum} to preserve the effective "
              f"global batch", file=sys.stderr, flush=True)
        if new_accum != self.args.gradient_accumulation_steps:
            self.args.gradient_accumulation_steps = new_accum
            self._step_fn = None   # rebuilt with the new accum factor

    def _live_mesh_shape(self) -> Optional[Dict[str, int]]:
        from .distributed import env
        if not env.has_mesh():
            return None
        return {a: int(d) for a, d in env.get_mesh().shape.items()}

    def _loader_batch_size(self) -> Optional[int]:
        """The per-step batch the dataloader feeds, when discoverable
        (None for plain iterables)."""
        dl = self.train_dataloader
        bs = getattr(getattr(dl, "batch_sampler", None), "batch_size",
                     None) or getattr(dl, "batch_size", None)
        try:
            return int(bs) if bs else None
        except (TypeError, ValueError):
            return None
