"""Sharding infrastructure: the GSPMD replacement for fleet's process-group
topology (reference: paddle/distributed/fleet/base/topology.py and
meta_parallel/* — which shard by slicing weights per-rank and inserting NCCL
calls by hand).

TPU-native: parameters stay *logically full-size*; each carries a
`ParamMeta.partition` tuple of mesh-axis names (e.g. ``("tp", None)``).
`shard_layer` device_puts every param with the NamedSharding its partition
resolves to, and the jitted step's in_shardings keep it there. XLA/GSPMD
then inserts the collectives the reference writes by hand. ZeRO stages 1-3
(reference: fleet sharding stage1/2/3) are not separate codepaths: sharding
optimizer state / grads / params over the ``fsdp`` axis IS stages 1/2/3.

Also hosts the trace-time mesh-axis validator — the TPU analogue of the
reference's NCCL race detection (SURVEY.md §5): it rejects partitions that
name axes missing from the mesh or that don't divide the dim size, at
sharding-resolution time rather than at runtime.
"""
from __future__ import annotations

from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..distributed.env import get_mesh, has_mesh
from ..nn.layer import Layer


class ShardingError(ValueError):
    """Invalid partition: unknown mesh axis or non-divisible dimension."""


def validate_partition(shape: Tuple[int, ...], partition, mesh: Mesh,
                       name: str = "<param>") -> None:
    """Trace-time validation (SURVEY.md §5 'race detection' analogue)."""
    if partition is None:
        return
    if len(partition) > len(shape):
        raise ShardingError(
            f"{name}: partition {partition} has more entries than shape {shape}")
    for dim, axes in enumerate(partition):
        if axes is None:
            continue
        axes = (axes,) if isinstance(axes, str) else tuple(axes)
        degree = 1
        for ax in axes:
            if ax not in mesh.shape:
                raise ShardingError(
                    f"{name}: unknown mesh axis {ax!r}; mesh has {tuple(mesh.shape)}")
            degree *= mesh.shape[ax]
        if shape[dim] % degree != 0:
            raise ShardingError(
                f"{name}: dim {dim} of shape {shape} not divisible by "
                f"{axes} degree {degree}")


def partition_to_sharding(partition, mesh: Optional[Mesh] = None) -> NamedSharding:
    mesh = mesh or get_mesh()
    spec = P(*partition) if partition else P()
    return NamedSharding(mesh, spec)


def _drop_dead_axes(partition, mesh: Mesh):
    """Drop axes of degree 1 (or absent) so specs stay minimal."""
    if partition is None:
        return None
    out = []
    for axes in partition:
        if axes is None:
            out.append(None)
            continue
        tup = (axes,) if isinstance(axes, str) else tuple(axes)
        kept = tuple(a for a in tup if mesh.shape.get(a, 1) > 1)
        out.append(None if not kept else (kept[0] if len(kept) == 1 else kept))
    while out and out[-1] is None:
        out.pop()
    return tuple(out)


def param_shardings(layer: Layer, mesh: Optional[Mesh] = None,
                    fsdp_axis: Optional[str] = "fsdp",
                    fsdp_min_size: int = 2 ** 16
                    ) -> Dict[str, NamedSharding]:
    """Resolve every parameter's partition into a NamedSharding.

    If the mesh has a non-trivial ``fsdp_axis``, parameters above
    ``fsdp_min_size`` elements additionally get fsdp sharding on their
    largest still-unsharded divisible dim (ZeRO-3 == fsdp param sharding;
    stages 1/2 reuse these specs for opt-state/grads only).
    """
    mesh = mesh or get_mesh()
    metas = layer.param_meta()
    out: Dict[str, NamedSharding] = {}
    fsdp_n = mesh.shape.get(fsdp_axis, 1) if fsdp_axis else 1
    for name, value in layer.named_parameters():
        part = _drop_dead_axes(metas[name].partition, mesh)
        part = list(part) if part else []
        part += [None] * (value.ndim - len(part))
        if fsdp_n > 1 and value.size >= fsdp_min_size:
            # choose largest unsharded dim divisible by fsdp degree
            cand = [(value.shape[d], d) for d in range(value.ndim)
                    if part[d] is None and value.shape[d] % fsdp_n == 0]
            if cand:
                _, d = max(cand)
                part[d] = fsdp_axis
        part = tuple(part)
        validate_partition(value.shape, part, mesh, name)
        out[name] = partition_to_sharding(part, mesh)
    return out


def shard_layer(layer: Layer, mesh: Optional[Mesh] = None, **kw) -> Dict[str, NamedSharding]:
    """device_put every parameter according to param_shardings; returns the
    sharding dict (feed it to jit in_shardings so params stay put)."""
    mesh = mesh or get_mesh()
    shardings = param_shardings(layer, mesh, **kw)
    for name, value in list(layer.named_parameters()):
        layer._set_by_path(name, jax.device_put(value, shardings[name]))
    return shardings


def constraint(x, *spec):
    """`lax.with_sharding_constraint` against the global mesh; no-op when no
    mesh is installed or it is single-device (keeps layers usable eagerly).
    Axes that don't evenly divide their dim are dropped (a hint must never
    make a program invalid — e.g. a debug batch of 2 on an 8-way dp mesh)."""
    if not has_mesh():
        return x
    mesh = get_mesh()
    if mesh.size == 1:
        return x
    cleaned = _drop_dead_axes(tuple(spec), mesh)
    if not cleaned:
        return x
    fitted = []
    for dim, axes in enumerate(cleaned):
        if axes is None:
            fitted.append(None)
            continue
        tup = (axes,) if isinstance(axes, str) else tuple(axes)
        degree = 1
        for a in tup:
            degree *= mesh.shape.get(a, 1)
        fitted.append(axes if x.shape[dim] % degree == 0 else None)
    while fitted and fitted[-1] is None:
        fitted.pop()
    if not fitted:
        return x
    get_abstract = getattr(jax.sharding, "get_abstract_mesh", None)
    if get_abstract is not None:
        abstract = get_abstract()
        if not abstract.empty:
            # inside a mesh context — e.g. the partial-manual 1F1B body
            # (shard_map axis_names={'pp'}): a NamedSharding built on the
            # outer all-Auto mesh would clash with the context mesh's axis
            # types, so hand over a bare PartitionSpec (manual axes in the
            # hint would be invalid; drop them)
            fitted = [None if _mentions_manual(a, abstract) else a
                      for a in fitted]
            while fitted and fitted[-1] is None:
                fitted.pop()
            if not any(a is not None for a in fitted):
                return x
            return jax.lax.with_sharding_constraint(x, P(*fitted))
        return jax.lax.with_sharding_constraint(
            x, NamedSharding(mesh, P(*fitted)))
    # old jax (<= 0.4.x): no abstract-mesh introspection, and a
    # NamedSharding hint inside a (partial-)manual shard_map region
    # lowers to an XLA PartitionId op SPMD can't partition — drop the
    # hint there (it is an optimization hint, never load-bearing) and
    # keep it everywhere else.
    from ..utils.jax_compat import inside_manual_region
    if inside_manual_region():
        return x
    return jax.lax.with_sharding_constraint(
        x, NamedSharding(mesh, P(*fitted)))


def _mentions_manual(axes, abstract_mesh) -> bool:
    if axes is None:
        return False
    tup = (axes,) if isinstance(axes, str) else tuple(axes)
    manual_t = jax.sharding.AxisType.Manual
    manual = {n for n, t in zip(abstract_mesh.axis_names,
                                abstract_mesh.axis_types)
              if t == manual_t}
    return any(a in manual for a in tup)


def tree_shardings(tree, like: Dict[str, NamedSharding], default=None):
    """Map a flat {name: Array} tree to its shardings, falling back to
    `default` (replicated if None) for names absent from `like`."""
    mesh = get_mesh()
    default = default or NamedSharding(mesh, P())
    return {k: like.get(k, default) for k in tree}
