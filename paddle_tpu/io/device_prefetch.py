"""Asynchronous device-prefetch input pipeline (ISSUE 4 tentpole).

The trainer used to dispatch each jitted step against a host-resident
numpy batch, so the H2D transfer serialized with the previous step's
compute. ``DevicePrefetcher`` wraps any dataloader/iterable and runs
batch prep (``Trainer._prep_batch``-style reshaping) plus
``jax.device_put`` — replicated onto the live global mesh when one is
installed — in a background thread with a BOUNDED double/triple buffer,
so the next batch's host assembly and device copy overlap the current
step's compute (the "keep the SPMD program fed" half of GSPMD's MFU
story; see PAPERS.md).

Preemption safety (composes with the PR 3 graceful-shutdown latch and
sampler-state checkpointing): the wrapped sampler runs AHEAD of the
consumer by up to the buffer depth, so exposing the producer's live
cursor would make a checkpoint skip buffered-but-untrained batches on
resume. Instead every buffered batch carries the loader's
``state_dict()`` snapshot taken right after it was drawn, and
``state_dict()`` reports the snapshot of the last batch actually
YIELDED — exactly the consumer position. Nothing is double-trained or
silently skipped, and the bit-identical-trajectory preemption tests
hold with prefetch enabled.

Robustness: a wedged producer (the seeded ``prefetch_stall`` fault, or
a genuinely hung host input pipeline) must degrade, not deadlock — when
the buffer stays empty past ``stall_timeout_s`` the consumer takes the
fetch lock and feeds itself synchronously from the wrapped iterator
(``sync_fallbacks`` counts these). The lock serializes every access to
the inner iterator, so producer and degraded consumer never interleave
a fetch.
"""
from __future__ import annotations

import queue
import sys
import threading
import time
from typing import Callable, Iterable, Optional

import jax

from ..utils import faults
from ..utils import observability as obs

__all__ = ["DevicePrefetcher", "default_device_put"]

_BATCH, _ERROR, _END = "batch", "error", "end"


def default_device_put(batch):
    """Place a host batch (array or pytree) onto the accelerator:
    replicated onto the live global mesh when one is installed (the
    jitted step's sharding constraints re-shard it on-device), plain
    ``device_put`` on a single local device, and a host pass-through
    when placement is ambiguous (several devices, no mesh — jit's own
    placement logic wins, as before)."""
    from ..distributed import env as denv
    if denv.has_mesh():
        return jax.device_put(batch, denv.replicated())
    if len(jax.local_devices()) == 1:
        return jax.device_put(batch)
    return batch


class _PrefetchIterator:
    """One epoch's background feed; created by ``iter(DevicePrefetcher)``."""

    def __init__(self, loader, prep, place, depth, stall_timeout_s,
                 inner=None):
        self._prep = prep
        self._place = place
        self._stall_timeout_s = stall_timeout_s
        self._inner = iter(loader) if inner is None else inner
        self._snapshot = getattr(loader, "state_dict", None)
        self._q: queue.Queue = queue.Queue(maxsize=max(1, depth))
        self._lock = threading.Lock()          # serializes self._inner
        self._stop = threading.Event()
        self._exhausted = False                # inner raised StopIteration
        self._finished = False                 # consumer saw the end
        self._degraded = False                 # stall latch: sync feeding
        self.state = self._snap()              # last-YIELDED position
        self.sync_fallbacks = 0
        self._warned_stall = False
        # observability: live buffer depth + stall accounting (the
        # "why was the feed slow" half of the step-time postmortem)
        self._g_depth = obs.gauge("prefetch_buffer_depth")
        self._c_sync = obs.counter("prefetch_sync_fallbacks_total")
        self._c_stall = obs.counter("prefetch_stall_degradations_total")
        self._thread = threading.Thread(
            target=self._produce, name="device-prefetch", daemon=True)
        self._thread.start()

    # ------------------------------------------------------------ producer
    def _snap(self) -> dict:
        if self._snapshot is None:
            return {}
        try:
            return self._snapshot() or {}
        except Exception as e:     # state is best-effort; feeding is not
            print(f"[prefetch] loader state_dict failed: {e}",
                  file=sys.stderr, flush=True)
            return {}

    def _fetch_locked(self):
        """next(inner) + state snapshot + prep + device_put. Caller must
        hold the lock: the snapshot only means "position after this
        batch" if no other fetch is in flight."""
        batch = next(self._inner)              # may raise StopIteration
        snap = self._snap()
        if self._prep is not None:
            batch = self._prep(batch)
        return self._place(batch), snap        # device_put dispatch is async

    def _put(self, item) -> bool:
        from .dataloader import bounded_put
        ok = bounded_put(self._q, item, self._stop)
        self._g_depth.set(self._q.qsize())
        return ok

    def _produce(self):
        try:
            while not self._stop.is_set():
                if faults.inject("prefetch_stall"):
                    # OUTSIDE the lock: the consumer's degraded
                    # synchronous path must be able to feed itself while
                    # this thread is wedged
                    time.sleep(faults.prefetch_stall_seconds())
                with self._lock:
                    if self._stop.is_set() or self._exhausted:
                        break
                    try:
                        item = self._fetch_locked()
                    except StopIteration:
                        self._exhausted = True
                        break
                    # still under the lock: a bypassing consumer must
                    # find either this batch already queued or a free
                    # lock and an empty queue — never a batch in limbo
                    if not self._put((_BATCH, item)):
                        return
        except BaseException as e:             # propagate into the consumer
            self._put((_ERROR, e))
            return
        self._put((_END, None))

    # ------------------------------------------------------------ consumer
    def __iter__(self):
        return self

    def __next__(self):
        if self._finished:
            raise StopIteration
        while True:
            if self._degraded:
                # latched: don't pay the full stall timeout on the empty
                # queue every batch — go straight to the sync path, which
                # drains (and un-latches on) a recovered producer's
                # deliveries before falling back to the fetch lock
                kind, payload = self._degraded_fetch()
                if kind is None:
                    continue                   # producer holds the lock
            else:
                try:
                    kind, payload = self._q.get(
                        timeout=self._stall_timeout_s)
                except queue.Empty:
                    kind, payload = self._degraded_fetch()
                    if kind is None:
                        continue               # producer mid-cycle: wait on
            if kind == _BATCH:
                self._g_depth.set(self._q.qsize())
                batch, snap = payload
                if snap:
                    self.state = snap
                return batch
            if kind == _ERROR:
                self._finished = True
                self.close()
                raise payload
            self._finished = True              # _END
            self.close()
            raise StopIteration

    def _degraded_fetch(self):
        """Stall path: the producer delivered nothing for a full
        timeout. Take the fetch lock and feed synchronously — training
        degrades to the old serial feed instead of deadlocking."""
        try:
            # Drain the buffer BEFORE taking the lock. Queue order is
            # fetch order, so a lock-free get is always consistent — and
            # it is what unwedges a RECOVERED producer that filled the
            # bounded queue and is now blocked in its put while holding
            # the fetch lock (which this path would otherwise wait on
            # forever: latched consumer needs the lock, producer needs a
            # queue slot).
            item = self._q.get_nowait()
            self._degraded = False             # producer is feeding again
            return item
        except queue.Empty:
            pass
        if not self._lock.acquire(timeout=self._stall_timeout_s):
            return None, None                  # producer holds the lock
        try:
            try:
                item = self._q.get_nowait()    # raced a late delivery
                self._degraded = False         # producer is feeding again
                return item
            except queue.Empty:
                pass
            if self._exhausted:
                return _END, None
            if not self._warned_stall:
                self._warned_stall = True
                print(f"[prefetch] no batch for {self._stall_timeout_s:.1f}s "
                      f"(stalled prefetch thread); degrading to synchronous "
                      f"feeding", file=sys.stderr, flush=True)
                self._c_stall.inc()
                obs.record_event("prefetch_stall",
                                 timeout_s=self._stall_timeout_s)
            try:
                item = self._fetch_locked()
            except StopIteration:
                self._exhausted = True
                return _END, None
            self.sync_fallbacks += 1
            self._c_sync.inc()
            self._degraded = True              # stay synchronous until the
            return _BATCH, item                # producer delivers again
        finally:
            self._lock.release()

    def close(self, join_timeout_s: float = 5.0):
        """Idempotent teardown: stop the producer, discard buffered
        batches (the consumer-position ``state`` is unaffected — that is
        the whole point), and join the thread."""
        self._stop.set()
        try:
            while True:                        # unblock a producer in put()
                self._q.get_nowait()
        except queue.Empty:
            pass
        if self._thread.is_alive() and \
                threading.current_thread() is not self._thread:
            self._thread.join(timeout=join_timeout_s)


class DevicePrefetcher:
    """Iterable wrapper: each ``iter()`` starts a fresh background-fed
    epoch (tearing down the previous epoch's thread first), matching the
    trainer's epoch-wrap contract.

    ``state_dict()`` reports the CONSUMER position (module docstring) in
    exactly the wrapped loader's schema, so checkpoint meta sidecars are
    byte-compatible with the synchronous path; ``load_state_dict``
    delegates to the wrapped loader (call it before iterating, as the
    trainer's resume path does)."""

    def __init__(self, loader: Iterable, prep: Optional[Callable] = None,
                 depth: int = 2, place: Optional[Callable] = None,
                 stall_timeout_s: float = 5.0,
                 initial_iter: Optional[Iterable] = None):
        if depth < 1:
            raise ValueError(f"prefetch depth must be >= 1, got {depth}")
        self.loader = loader
        self.prep = prep
        self.depth = depth
        self.place = place if place is not None else default_device_put
        self.stall_timeout_s = stall_timeout_s
        # a partially-consumed iterator for the FIRST epoch only (the
        # trainer's legacy resume skip advances the raw loader before
        # wrapping, so discarded batches never pay prep + H2D); later
        # epochs re-iterate the loader as usual
        self._initial_iter = initial_iter
        self._it: Optional[_PrefetchIterator] = None
        self._last_state: Optional[dict] = None
        self._closed_fallbacks = 0

    def __iter__(self):
        self.close()
        inner, self._initial_iter = self._initial_iter, None
        self._it = _PrefetchIterator(self.loader, self.prep, self.place,
                                     self.depth, self.stall_timeout_s,
                                     inner=inner)
        return self._it

    def __len__(self):
        return len(self.loader)  # type: ignore[arg-type]

    # ---------------------------------------------------- resumable state
    def state_dict(self) -> dict:
        if self._it is not None:
            return dict(self._it.state)
        if self._last_state is not None:
            # closed epoch: the wrapped loader ran AHEAD by the buffer
            # depth, so its live state_dict would over-report; the
            # retained consumer position is the truthful one
            return dict(self._last_state)
        sd = getattr(self.loader, "state_dict", None)
        return sd() if sd is not None else {}

    def load_state_dict(self, state):
        self._last_state = None
        lsd = getattr(self.loader, "load_state_dict", None)
        if lsd is not None:
            lsd(state)

    @property
    def sync_fallbacks(self) -> int:
        """Degraded synchronous fetches taken (stall fallback), summed
        across closed epochs so the trainer can report it post-train."""
        live = self._it.sync_fallbacks if self._it is not None else 0
        return self._closed_fallbacks + live

    def close(self):
        if self._it is not None:
            self._last_state = dict(self._it.state)
            self._closed_fallbacks += self._it.sync_fallbacks
            self._it.close()
            self._it = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
