"""BPE tokenizer parity vs the HF ``tokenizers`` library (VERDICT r2
item 3; reference: PaddleNLP gpt/tokenizer.py + llama/tokenizer_fast.py).
A byte-level BPE is trained locally (zero network), saved as
tokenizer.json, and our merges-based implementation must reproduce the
library's encodings token-for-token."""
import json

import pytest

tokenizers = pytest.importorskip("tokenizers")

from paddle_tpu.tokenizer import BPETokenizer, LLAMA3_SPLIT  # noqa: E402

CORPUS = [
    "The quick brown fox jumps over the lazy dog.",
    "TPUs multiply matrices in bfloat16 on a 128x128 systolic array.",
    "def train_step(params, batch):\n    return loss, grads\n",
    "Unicode: café naïve über 中文分词 🚀🤖",
    "   leading spaces\tand\ttabs\nnewlines\r\nwindows",
    "don't can't won't it's we're I'll they'd you've",
    "numbers 123 4567 3.14159 0x1F large 1234567890",
]

TRICKY = [
    "Hello, world!",
    "  double  spaces  ",
    "café 🚀 rocket",
    "don't stop",
    "tabs\tnewlines\nmixed \r\n end",
    "123abc 456 def789",
    "",
    "a",
    "中文 mixed English 中文",
]


@pytest.fixture(scope="module")
def trained(tmp_path_factory):
    d = tmp_path_factory.mktemp("tok")
    tok = tokenizers.ByteLevelBPETokenizer()
    tok.train_from_iterator(CORPUS, vocab_size=400, min_frequency=1,
                            special_tokens=["<|endoftext|>", "<pad>"])
    path = str(d / "tokenizer.json")
    tok.save(path)
    return tok, path


def test_encode_parity(trained):
    ref, path = trained
    ours = BPETokenizer.from_tokenizer_json(path)
    for s in CORPUS + TRICKY:
        assert ours.encode(s) == ref.encode(s).ids, f"mismatch on {s!r}"


def test_decode_round_trip(trained):
    ref, path = trained
    ours = BPETokenizer.from_tokenizer_json(path)
    for s in CORPUS + TRICKY:
        ids = ours.encode(s)
        assert ours.decode(ids) == s, f"round-trip failed on {s!r}"


def test_special_tokens(trained):
    _, path = trained
    ours = BPETokenizer.from_tokenizer_json(path)
    eot = ours.special_tokens["<|endoftext|>"]
    ids = ours.encode("Hello<|endoftext|>world")
    assert eot in ids
    assert ours.decode(ids) == "Hello<|endoftext|>world"
    assert "<|endoftext|>" not in ours.decode(ids, skip_special_tokens=True)


def test_vocab_merges_files(trained, tmp_path):
    """GPT-2 style vocab.json + merges.txt loading path."""
    ref, path = trained
    with open(path, encoding="utf-8") as f:
        data = json.load(f)
    with open(tmp_path / "vocab.json", "w", encoding="utf-8") as f:
        json.dump(data["model"]["vocab"], f, ensure_ascii=False)
    with open(tmp_path / "merges.txt", "w", encoding="utf-8") as f:
        f.write("#version: 0.2\n")
        for m in data["model"]["merges"]:
            pair = m if isinstance(m, str) else " ".join(m)
            f.write(pair + "\n")
    ours = BPETokenizer.from_pretrained(str(tmp_path))
    for s in TRICKY:
        assert ours.encode(s) == ref.encode(s).ids


def test_llama3_style_split_pattern(tmp_path):
    """Llama-3 tokenizer.json shape: Sequence[Split(Regex), ByteLevel
    (use_regex=false)] — the Split regex must be honored."""
    tok = tokenizers.Tokenizer(tokenizers.models.BPE())
    trainer = tokenizers.trainers.BpeTrainer(
        vocab_size=400, min_frequency=1, special_tokens=["<|eot|>"],
        initial_alphabet=tokenizers.pre_tokenizers.ByteLevel.alphabet())
    tok.pre_tokenizer = tokenizers.pre_tokenizers.Sequence([
        tokenizers.pre_tokenizers.Split(
            tokenizers.Regex(LLAMA3_SPLIT), behavior="isolated"),
        tokenizers.pre_tokenizers.ByteLevel(add_prefix_space=False,
                                            use_regex=False),
    ])
    tok.decoder = tokenizers.decoders.ByteLevel()
    tok.train_from_iterator(CORPUS, trainer)
    path = str(tmp_path / "tokenizer.json")
    tok.save(path)
    ours = BPETokenizer.from_tokenizer_json(path)
    assert ours._split_re.pattern == LLAMA3_SPLIT
    for s in CORPUS + TRICKY:
        assert ours.encode(s) == tok.encode(s).ids, f"mismatch on {s!r}"
        assert ours.decode(ours.encode(s)) == s


def test_real_gpt2_known_tokenization():
    """Spot-check against GPT-2's published tokenization using a minimal
    hand-built vocab (no network): 'low lower lowest' with merges l+o,
    lo+w, Ġ+l (space-l)."""
    b2u = __import__("paddle_tpu.tokenizer", fromlist=["bytes_to_unicode"])
    table = b2u.bytes_to_unicode()
    sp = table[ord(" ")]
    vocab = {c: i for i, c in enumerate(sorted(set(table.values())))}
    for extra in ["lo", "low", sp + "l", sp + "lo", sp + "low"]:
        vocab[extra] = len(vocab)
    merges = [(sp, "l"), (sp + "l", "o"), (sp + "lo", "w"), ("l", "o"),
              ("lo", "w")]
    tok = BPETokenizer(vocab, merges)
    toks = tok.tokenize("low lower lowest")
    assert toks[0] == "low"
    assert sp + "low" in toks
    assert tok.decode(tok.encode("low lower lowest")) == "low lower lowest"


def test_sentencepiece_style_rejected(tmp_path):
    """Llama-2-style (sentencepiece-converted) BPE must be refused, not
    silently mis-tokenized through the byte alphabet."""
    data = {"model": {"type": "BPE", "vocab": {"▁the": 0}, "merges": []},
            "pre_tokenizer": None, "decoder": {"type": "Sequence"}}
    p = tmp_path / "tokenizer.json"
    p.write_text(json.dumps(data), encoding="utf-8")
    with pytest.raises(ValueError, match="byte-level"):
        BPETokenizer.from_tokenizer_json(str(p))


def test_native_bpe_matches_python(trained):
    """The C++ merge loop (native/src/bpe.cc) must produce exactly the
    Python loop's ids on the same tokenizer."""
    _, path = trained
    ours = BPETokenizer.from_tokenizer_json(path)
    if ours._native is None:
        pytest.skip("native library unavailable")
    for s in CORPUS + TRICKY:
        native_ids = ours.encode(s)
        ours_py = BPETokenizer.from_tokenizer_json(path)
        ours_py._native = None
        assert native_ids == ours_py.encode(s), f"mismatch on {s!r}"
