"""Speculative decoding (C38): greedy exactness regardless of draft
quality, fewer target forwards with a good draft, eos handling."""
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.generation import speculative_generate
from paddle_tpu.models import LlamaForCausalLM, llama_tiny


def _models():
    pt.seed(0)
    target = LlamaForCausalLM(llama_tiny())
    # decisive logits: random-init outputs are near-uniform, and the
    # decode (q_len=1) vs verify (q_len=k+1) paths differ by float
    # epsilon — enough to flip coin-toss argmaxes and make exactness
    # seed-lottery. Scaling the head widens every gap 10x.
    target.lm_head.weight = target.lm_head.weight * 10.0
    pt.seed(99)  # a DIFFERENT (bad) draft: random init, half the size
    draft = LlamaForCausalLM(llama_tiny(hidden_size=32, intermediate_size=64,
                                        num_hidden_layers=1))
    return target, draft


def _prompt(seed=0, n=8):
    return jnp.asarray(np.random.RandomState(seed).randint(1, 256, (1, n)))


class TestSpeculative:
    def test_exactness_with_bad_draft(self):
        """The defining property: a random draft changes SPEED only —
        the output equals the target's own greedy decode token-for-token."""
        target, draft = _models()
        ids = _prompt()
        want = target.generate(ids, max_new_tokens=24, temperature=0.0)
        got = speculative_generate(target, draft, ids, max_new_tokens=24,
                                   num_draft_tokens=4)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))

    def test_perfect_draft_cuts_target_forwards(self):
        """Draft == target: every proposal accepted, so ~(k+1) tokens per
        target forward instead of 1."""
        target, _ = _models()
        ids = _prompt(seed=1)
        got, stats = speculative_generate(
            target, target, ids, max_new_tokens=24, num_draft_tokens=4,
            return_stats=True)
        want = target.generate(ids, max_new_tokens=24, temperature=0.0)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
        # 1 prefill + ceil(23 / 5) = 6 verify calls; plain greedy uses 24
        assert stats["target_forwards"] <= 8, stats
        assert stats["tokens_per_forward"] > 2.5

    def test_eos_stops_and_pads(self):
        target, draft = _models()
        ids = _prompt(seed=2)
        want = target.generate(ids, max_new_tokens=24, temperature=0.0,
                               eos_token_id=7)
        got = speculative_generate(target, draft, ids, max_new_tokens=24,
                                   num_draft_tokens=3, eos_token_id=7)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))

    def test_batched_exactness_with_bad_draft(self):
        """VERDICT r3 weak #5: rows accept independently (per-row cursors
        via the vmapped loop) and each row equals its own greedy decode."""
        target, draft = _models()
        ids = jnp.asarray(
            np.random.RandomState(8).randint(1, 256, (3, 8)))
        want = target.generate(ids, max_new_tokens=16, temperature=0.0)
        got = speculative_generate(target, draft, ids, max_new_tokens=16,
                                   num_draft_tokens=4)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))

    def test_batched_eos_rows_stop_independently(self):
        """A row hitting EOS freezes while the others keep decoding."""
        target, draft = _models()
        ids = jnp.asarray(
            np.random.RandomState(9).randint(1, 256, (4, 8)))
        want = target.generate(ids, max_new_tokens=20, temperature=0.0,
                               eos_token_id=7)
        got, stats = speculative_generate(
            target, draft, ids, max_new_tokens=20, num_draft_tokens=3,
            eos_token_id=7, return_stats=True)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
        assert len(stats["target_forwards"]) == 4
        assert len(stats["tokens_per_forward"]) == 4

    def test_batched_perfect_draft_speedup(self):
        target, _ = _models()
        ids = jnp.asarray(
            np.random.RandomState(10).randint(1, 256, (2, 8)))
        got, stats = speculative_generate(
            target, target, ids, max_new_tokens=24, num_draft_tokens=4,
            return_stats=True)
        want = target.generate(ids, max_new_tokens=24, temperature=0.0)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
        assert all(f <= 8 for f in stats["target_forwards"]), stats

    @pytest.mark.parametrize("k", [1, 2, 6])
    def test_various_draft_lengths(self, k):
        target, draft = _models()
        ids = _prompt(seed=3)
        want = target.generate(ids, max_new_tokens=16, temperature=0.0)
        got = speculative_generate(target, draft, ids, max_new_tokens=16,
                                   num_draft_tokens=k)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_executable_cached_across_calls():
    """Same (target, draft, shapes): the second call reuses the compiled
    run instead of retracing (serving latency)."""
    target, draft = _models()
    ids = _prompt(seed=5)
    out1 = speculative_generate(target, draft, ids, max_new_tokens=8,
                                num_draft_tokens=2)
    cache = target._spec_exec_cache[id(draft)]
    assert len(cache) == 1
    out2 = speculative_generate(target, draft, ids, max_new_tokens=8,
                                num_draft_tokens=2)
    assert len(cache) == 1  # no new entry
    np.testing.assert_array_equal(np.asarray(out1), np.asarray(out2))
    # leak check (review r5): the cache hangs off the model, so dropping
    # the models leaves only a reference cycle the gc can collect — a
    # global registry whose values close over the models could not
    import gc
    import weakref
    wr = weakref.ref(target)
    del target, draft, out1, out2, cache
    gc.collect()
    assert wr() is None


class TestMTPSpeculative:
    """MTP-as-draft self-speculation (VERDICT r4 item 5): the model's own
    depth-0 MTP module drafts; no second model."""

    def _model(self):
        import paddle_tpu as pt
        from paddle_tpu.models.deepseek_v2 import (DeepseekV2ForCausalLM,
                                                   deepseek_v2_tiny)
        from paddle_tpu.generation import mtp_speculative_generate  # noqa
        pt.seed(0)
        model = DeepseekV2ForCausalLM(deepseek_v2_tiny(
            num_nextn_predict_layers=1))
        # decisive logits (see _models above): widen argmax gaps so the
        # q_len=1 vs q_len=k+1 float-epsilon difference can't flip them
        model.lm_head.weight = model.lm_head.weight * 10.0
        return model

    def test_exactness_vs_greedy(self):
        """Self-drafting changes SPEED only — output equals the model's
        own greedy decode token-for-token."""
        from paddle_tpu.generation import mtp_speculative_generate
        model = self._model()
        ids = _prompt(seed=21)
        want = model.generate(ids, max_new_tokens=20, temperature=0.0)
        got = mtp_speculative_generate(model, ids, max_new_tokens=20,
                                       num_draft_tokens=3)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))

    def test_forced_full_accept_cuts_forwards(self):
        """Zeroed lm_head -> every logit row is identical, so target and
        MTP draft both argmax to token 0: all k drafts accepted every
        round, ~(k+1) tokens per target forward."""
        from paddle_tpu.generation import mtp_speculative_generate
        model = self._model()
        model.lm_head.weight = model.lm_head.weight * 0.0
        ids = _prompt(seed=22)
        got, stats = mtp_speculative_generate(
            model, ids, max_new_tokens=24, num_draft_tokens=4,
            return_stats=True)
        want = model.generate(ids, max_new_tokens=24, temperature=0.0)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
        # 1 prefill + ceil(23/5) = 6 target forwards vs 24 plain greedy
        assert stats["target_forwards"] <= 6, stats
        assert stats["tokens_per_forward"] > 3.5, stats

    def test_eos_stops_and_pads(self):
        from paddle_tpu.generation import mtp_speculative_generate
        model = self._model()
        ids = _prompt(seed=23)
        want = model.generate(ids, max_new_tokens=20, temperature=0.0,
                              eos_token_id=7)
        got = mtp_speculative_generate(model, ids, max_new_tokens=20,
                                       num_draft_tokens=3, eos_token_id=7)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))

    def test_batched_exactness(self):
        from paddle_tpu.generation import mtp_speculative_generate
        model = self._model()
        ids = jnp.asarray(
            np.random.RandomState(24).randint(1, 256, (2, 8)))
        want = model.generate(ids, max_new_tokens=16, temperature=0.0)
        got, stats = mtp_speculative_generate(
            model, ids, max_new_tokens=16, num_draft_tokens=2,
            return_stats=True)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
        assert len(stats["target_forwards"]) == 2

    def test_no_mtp_module_raises(self):
        import paddle_tpu as pt
        from paddle_tpu.models.deepseek_v2 import (DeepseekV2ForCausalLM,
                                                   deepseek_v2_tiny)
        from paddle_tpu.generation import mtp_speculative_generate
        pt.seed(0)
        model = DeepseekV2ForCausalLM(deepseek_v2_tiny())
        with pytest.raises(ValueError, match="num_nextn"):
            mtp_speculative_generate(model, _prompt(), max_new_tokens=4)


class TestNgramSpeculative:
    """Prompt-lookup drafting (round 5): no draft model — the sequence's
    own repeated n-grams propose the draft."""

    def test_exactness_vs_greedy(self):
        from paddle_tpu.generation import ngram_speculative_generate
        target, _ = _models()
        ids = _prompt(seed=31)
        want = target.generate(ids, max_new_tokens=20, temperature=0.0)
        got = ngram_speculative_generate(target, ids, max_new_tokens=20,
                                         num_draft_tokens=3, ngram=2)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))

    def test_repetitive_output_cuts_forwards(self):
        """Zeroed lm_head -> the model emits token 0 forever; the n-gram
        lookup finds the repetition and every draft is accepted."""
        from paddle_tpu.generation import ngram_speculative_generate
        target, _ = _models()
        target.lm_head.weight = target.lm_head.weight * 0.0
        ids = _prompt(seed=32)
        got, stats = ngram_speculative_generate(
            target, ids, max_new_tokens=24, num_draft_tokens=4, ngram=2,
            return_stats=True)
        want = target.generate(ids, max_new_tokens=24, temperature=0.0)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
        # the loop repeats after ~2 tokens; nearly every round then
        # commits k+1 tokens: far fewer than 24 forwards
        assert stats["target_forwards"] <= 8, stats
        assert stats["tokens_per_forward"] >= 2.5, stats

    def test_exactness_with_eos(self):
        from paddle_tpu.generation import ngram_speculative_generate
        target, _ = _models()
        ids = _prompt(seed=33)
        want = target.generate(ids, max_new_tokens=20, temperature=0.0,
                               eos_token_id=7)
        got = ngram_speculative_generate(target, ids, max_new_tokens=20,
                                         num_draft_tokens=3, ngram=2,
                                         eos_token_id=7)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))

    def test_batched_exactness(self):
        from paddle_tpu.generation import ngram_speculative_generate
        target, _ = _models()
        ids = jnp.asarray(
            np.random.RandomState(34).randint(1, 256, (2, 8)))
        want = target.generate(ids, max_new_tokens=16, temperature=0.0)
        got, stats = ngram_speculative_generate(
            target, ids, max_new_tokens=16, num_draft_tokens=2, ngram=2,
            return_stats=True)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
        assert len(stats["target_forwards"]) == 2

    def test_prompt_with_repeats_drafts_from_prompt(self):
        """A prompt that is itself periodic seeds matches immediately —
        stats confirm multi-token commits on a NON-degenerate model as
        long as the model actually continues the pattern."""
        from paddle_tpu.generation import ngram_speculative_generate
        target, _ = _models()
        target.lm_head.weight = target.lm_head.weight * 0.0  # copies 0s
        pat = [5, 9, 5, 9, 5, 9, 5, 9]
        ids = jnp.asarray([pat])
        got, stats = ngram_speculative_generate(
            target, ids, max_new_tokens=12, num_draft_tokens=3,
            return_stats=True)
        want = target.generate(ids, max_new_tokens=12, temperature=0.0)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
        # the property this test exists for: the periodic prompt seeds
        # matches from round one, so commits are multi-token
        assert stats["target_forwards"] < 12, stats
        assert stats["tokens_per_forward"] > 1.5, stats
