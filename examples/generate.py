"""Text generation + serving: greedy/sampling decode over the static KV
cache, then the batched serving pipeline.

  python examples/generate.py
  python examples/generate.py --hf /path/to/llama-checkpoint  # real weights
"""
import argparse

import jax.numpy as jnp
import numpy as np

import paddle_tpu as pt
from paddle_tpu.models import LlamaForCausalLM, from_pretrained, llama_tiny


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--hf", default=None,
                    help="HF/safetensors checkpoint dir (Llama/Qwen2 family)")
    args = ap.parse_args()

    pt.seed(0)
    if args.hf:
        model = from_pretrained(args.hf)  # real weights + config
    else:
        model = LlamaForCausalLM(llama_tiny(vocab_size=512))

    prompts = jnp.asarray(
        np.random.RandomState(0).randint(0, 256, (2, 16)))
    out = model.generate(prompts, max_new_tokens=32, temperature=0.8,
                         top_p=0.95)
    print("sampled:", np.asarray(out)[:, -8:])

    greedy = model.generate(prompts, max_new_tokens=32, temperature=0.0)
    print("greedy: ", np.asarray(greedy)[:, -8:])


if __name__ == "__main__":
    main()
