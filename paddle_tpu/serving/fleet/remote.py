"""Remote replica adapter + prefix-digest gossip (ISSUE 13 tentpole;
reference: the control-plane side of multi-host LLM serving fleets —
envoy/k8s-style health probing + SGLang's cache-aware routing lifted
from one process to N — restated stdlib-only over the gateway's
existing HTTP surface).

The router's replica seam is duck-typed on purpose
(``healthy``/``load``/``has_prefix`` — see ``serving/router.py``):
:class:`RemoteReplica` implements those three methods over HTTP probes
of a PEER GATEWAY PROCESS, so the same
:class:`~paddle_tpu.serving.router.PrefixAffinityRouter` ladder
(warm -> sticky -> least-loaded, circuit-breaker probation) that
places requests on local tick threads places them on remote gateways
— without touching routing policy. The fleet frontend
(:mod:`.frontend`) then proxies ``/v1/generate`` streams to the chosen
peer byte-for-byte.

Probing is CACHED with a staleness bound: the router calls
``healthy()``/``load()``/``has_prefix()`` synchronously on the serving
path, so those reads must never block on the network. A background
prober refreshes two snapshots per peer:

- ``GET /healthz`` — draining flag, per-replica slot/queue occupancy
  (the ``load()`` the ladder sorts by) and the autoscaler signal
  quartet (queue depth, free slots, block-pool free fraction, goodput
  fraction — the PR-8 gauges, read remotely in one fetch).
- ``GET /debugz/prefix?if_gen=N`` — the peer's prefix-digest set
  (ISSUE 13 satellite). The monotonic ``generation`` counter makes the
  poll conditional: an unchanged set answers a tiny marker instead of
  re-shipping the digest list, so sub-second gossip stays cheap. The
  gossiped set is what turns the prefix cache into a FLEET asset: the
  router can place a request on ANY warm peer, not just the one an
  earlier request happened to land on.
- ``GET /metricsz?window_s=N`` — the peer's WINDOWED telemetry view
  (ISSUE 15): counter rates, gauge means, windowed histogram
  quantiles and the SLO burn/alert block, cached per probe round so
  the frontend's federated ``/metricsz`` is an O(peers) cache walk.
  Best-effort: a peer without the endpoint stays healthy — live
  metrics are a lens, not a liveness signal.

A peer whose probes stop landing is evicted two ways: consecutive
probe failures flip the health latch (and open the breaker when one is
attached), and a snapshot older than ``stale_after_s`` fails
``healthy()`` even before the failure count does — a wedged prober or
a silently black-holed peer can never keep serving stale "healthy"
answers to the router. The ``peer_slow`` fault site injects probe
latency to exercise exactly that bound.
"""
from __future__ import annotations

import hashlib
import http.client
import json
import random
import threading
import time
from typing import Any, Dict, List, Optional

import numpy as np

from ...utils import faults
from ...utils import observability as obs

__all__ = ["RemoteReplica", "prefix_digest_chain", "probe_phase",
           "probe_delay"]


# -------------------------------------------------- probe round scheduling
# ISSUE 16 satellite: every peer used to probe on the same fixed
# interval, so N frontends x M peers synchronize into one thundering
# herd of /healthz+/debugz+/metricsz rounds (the storm the fleet sim
# flags). The schedule is now seeded per peer: a start PHASE spreads
# round 0 across the interval, and per-round JITTER keeps rounds from
# re-synchronizing over time. Both are pure functions of
# (seed, name, round) — deterministic across runs, shared verbatim by
# the live prober thread AND the simulator's probe events, so what the
# sim measures about storm behavior is the schedule production runs.

def probe_phase(name: str, interval_s: float, seed: int = 0) -> float:
    """Deterministic per-peer start offset in ``[0, interval_s)``."""
    u = random.Random(f"probe-phase:{seed}:{name}").random()
    return float(interval_s) * u


def probe_delay(name: str, interval_s: float, round_idx: int, *,
                jitter_frac: float = 0.2, seed: int = 0) -> float:
    """Wait before probe round ``round_idx``: ``interval * (1 +-
    jitter_frac)``, seeded per (peer, round). The ``peer_storm`` fault
    site collapses the delay to 0 — every armed peer's next round
    fires NOW, re-creating the synchronized herd on purpose (what the
    sim's probe-storm schedule and the storm tests arm)."""
    if faults.inject("peer_storm", replica=name, round=round_idx):
        return 0.0
    if jitter_frac <= 0.0:
        return float(interval_s)
    u = random.Random(f"probe:{seed}:{name}:{round_idx}").random()
    return float(interval_s) * (1.0 + float(jitter_frac)
                                * (2.0 * u - 1.0))


def prefix_digest_chain(input_ids, chunk_tokens: int,
                        max_tokens: Optional[int] = None) -> List[str]:
    """The chunk-grid digest chain of a prompt, shortest span first —
    byte-for-byte the keys ``PagedEngine.prefix_digests`` returns for
    the same ``chunk_prefill_tokens`` (pinned by test). The fleet
    frontend has no engine, so it computes routing keys standalone:
    digest_k = SHA256(digest_{k-1} || int64 tokens of chunk k), for
    every span k*C <= cap (default cap ``len(ids) - 1`` — at least one
    live token must remain to prefill, the engine's own rule)."""
    C = int(chunk_tokens)
    if C <= 0:
        return []
    ids = [int(t) for t in np.asarray(input_ids).reshape(-1)]
    cap = len(ids) - 1 if max_tokens is None \
        else min(int(max_tokens), len(ids))
    digests: List[str] = []
    d = b""
    k = 1
    while k * C <= cap:
        h = hashlib.sha256(d)
        h.update(np.asarray(ids[(k - 1) * C:k * C], np.int64).tobytes())
        d = h.digest()
        digests.append(d.hex())
        k += 1
    return digests


class RemoteReplica:
    """One peer gateway process, adapted to the router's replica seam.

    ``healthy()``/``load()``/``has_prefix()`` read the cached probe
    snapshots only (never the network); :meth:`refresh` runs one
    synchronous probe round (what the background prober loops, and
    what deterministic tests call directly). ``breaker`` is attached
    by the fleet frontend — while present, a peer evicted by probe
    failures rejoins through the router's probation-probe ladder, not
    by its probes merely coming back (a peer that answers /healthz but
    drops every proxied stream must not re-enter rotation for free).
    """

    def __init__(self, name: str, host: str, port: int, *,
                 probe_interval_s: float = 0.2,
                 probe_timeout_s: float = 1.0,
                 stale_after_s: float = 2.0,
                 fail_threshold: int = 2,
                 metrics_window_s: float = 5.0,
                 jitter_frac: float = 0.2,
                 metrics_every_rounds: int = 1,
                 seed: int = 0,
                 clock=time.monotonic):
        self.name = name
        self.host = host
        self.port = int(port)
        self.probe_interval_s = float(probe_interval_s)
        self.probe_timeout_s = float(probe_timeout_s)
        self.stale_after_s = float(stale_after_s)
        self.fail_threshold = max(int(fail_threshold), 1)
        self.metrics_window_s = float(metrics_window_s)
        # ISSUE 16: seeded probe-schedule decorrelation (phase +
        # per-round jitter) and optional round-batching of the
        # best-effort /metricsz fetch (every k-th round; the health +
        # gossip legs run every round — they are the liveness and
        # routing signal, metrics are a lens)
        self.jitter_frac = float(jitter_frac)
        self.metrics_every_rounds = max(int(metrics_every_rounds), 1)
        self.seed = int(seed)
        self._round = 0
        self._clock = clock
        self.breaker = None           # attached by the fleet frontend
        self._lock = threading.Lock()
        self._healthy = True
        self._fails = 0
        self._snap: Dict[str, Any] = {}
        self._snap_t: Optional[float] = None
        # gossiped digest set (ISSUE 13): hex digests + the peer's
        # generation counter the conditional fetch keys on
        self._digests: frozenset = frozenset()
        # spilled tier (ISSUE 17): digests the peer holds only in its
        # host-RAM spill arena — cheaper than device-live (a restore
        # beats a re-prefill, a live hit beats both) but still warm
        # for routing purposes
        self._spilled: frozenset = frozenset()
        self._digest_gen = -1
        self._digest_t: Optional[float] = None
        self.probes_total = 0
        self.probe_failures_total = 0
        self.gossip_fetches_total = 0
        self.gossip_unchanged_total = 0
        # federated live metrics (ISSUE 15): the peer's windowed
        # /metricsz doc, cached per probe round like the health snap —
        # the frontend's fleet view reads only these caches, never the
        # network
        self._metricsz: Dict[str, Any] = {}
        self._metricsz_t: Optional[float] = None
        self.metricsz_failures_total = 0
        self._halt = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # ------------------------------------------------------------- probing
    def _get_json(self, path: str) -> Dict[str, Any]:
        conn = http.client.HTTPConnection(
            self.host, self.port, timeout=self.probe_timeout_s)
        try:
            conn.request("GET", path)
            resp = conn.getresponse()
            payload = resp.read()
            if resp.status != 200:
                raise ConnectionError(
                    f"{path} answered {resp.status}")
            return json.loads(payload)
        finally:
            conn.close()

    def fetch_kv(self, digest_hex: str,
                 timeout_s: Optional[float] = None) -> Optional[bytes]:
        """``GET /kvz?digest=`` on this peer: one spill-arena span as
        a kvxfer wire record, on the same bounded transport the probes
        use (ISSUE 18 peer fetch). Returns the raw blob — the CALLER
        runs the decode ladder against its own geometry — or None on
        any miss/timeout/error; never raises. ``timeout_s`` overrides
        the probe timeout (the fetch side's ``xfer_timeout_s`` bound:
        a slow transfer is a counted re-prefill fallback, not a stall).
        """
        conn = http.client.HTTPConnection(
            self.host, self.port,
            timeout=float(timeout_s) if timeout_s is not None
            else self.probe_timeout_s)
        try:
            conn.request("GET", f"/kvz?digest={digest_hex}")
            resp = conn.getresponse()
            payload = resp.read()
            if resp.status != 200:
                return None
            return payload
        except (OSError, http.client.HTTPException):
            return None
        finally:
            conn.close()

    def fetch_profilez(self, duration_s: float,
                       timeout_s: Optional[float] = None
                       ) -> Optional[Dict[str, Any]]:
        """``GET /profilez?duration_s=`` on this peer (ISSUE 20
        federated capture): trigger a bounded tick-phase + jax-trace
        capture on the peer gateway and return its report dict, or
        None on any error — never raises. The default timeout covers
        the capture window plus transport slack (the peer holds the
        response open for ``duration_s`` wall seconds)."""
        conn = http.client.HTTPConnection(
            self.host, self.port,
            timeout=float(timeout_s) if timeout_s is not None
            else float(duration_s) + max(self.probe_timeout_s, 5.0))
        try:
            conn.request("GET", f"/profilez?duration_s={duration_s}")
            resp = conn.getresponse()
            payload = resp.read()
            if resp.status != 200:
                return None
            return json.loads(payload)
        except (OSError, ValueError, http.client.HTTPException):
            return None
        finally:
            conn.close()

    @staticmethod
    def _fold_health(doc: Dict[str, Any]) -> Dict[str, Any]:
        """Collapse a peer /healthz doc into the numbers the router and
        autoscaler read: load units (live slots + engine queue +
        scheduler queue), free/total slots, mean block-pool free
        fraction, scheduler queue depth, goodput fraction, draining."""
        load = 0.0
        free_slots = total_slots = queue_depth = 0
        block_free = []
        for rep in (doc.get("replicas") or {}).values():
            eng = rep.get("engine") or {}
            sched = rep.get("scheduler") or {}
            active = int(eng.get("active_slots", 0))
            queued = int(eng.get("queued", 0))
            sq = int(sched.get("queued", 0))
            load += active + queued + sq
            total = int(eng.get("max_slots", 0))
            total_slots += total
            free_slots += max(total - active, 0)
            queue_depth += sq
            tb = int(eng.get("total_blocks", 0))
            if tb:
                block_free.append(
                    (int(eng.get("free_blocks", 0))
                     + int(eng.get("cached_free_blocks", 0))) / tb)
        return {
            "draining": bool(doc.get("draining", False)),
            "load": load,
            "free_slots": free_slots,
            "total_slots": total_slots,
            "queue_depth": queue_depth,
            "block_pool_free_frac": round(
                sum(block_free) / len(block_free), 4)
            if block_free else 1.0,
            "goodput_frac": float(doc.get("goodput_frac", 1.0)),
            "completed": int(doc.get("completed", 0)),
            "tokens": int(doc.get("tokens", 0)),
        }

    def _probe_once(self):
        """One probe round: /healthz, then the conditional gossip
        fetch. Raises on any failure (the caller counts)."""
        if faults.inject("peer_slow", replica=self.name):
            time.sleep(faults.peer_slow_seconds())
        snap = self._fold_health(self._get_json("/healthz"))
        now = self._clock()
        with self._lock:
            self._snap = snap
            self._snap_t = now
            self._round += 1
            rnd = self._round
        if faults.inject("gossip_partition", replica=self.name):
            # a partition of the GOSSIP channel only (ISSUE 16): the
            # peer stays healthy and routable, but its digest set and
            # metrics caches age toward the staleness bound — warm
            # routing degrades to least-loaded, never to an eviction
            return
        # gossip: skip the digest list when the peer's generation
        # still matches what we hold (the cheap-poll satellite)
        doc = self._get_json(
            f"/debugz/prefix?if_gen={self._digest_gen}")
        self.gossip_fetches_total += 1
        with self._lock:
            if doc.get("unchanged"):
                self.gossip_unchanged_total += 1
            else:
                self._digests = frozenset(doc.get("digests") or ())
                self._spilled = frozenset(doc.get("spilled") or ())
                self._digest_gen = int(doc.get("generation", -1))
            self._digest_t = self._clock()
        # federated metrics (ISSUE 15): cache the peer's windowed view
        # on the SAME probe round — no new connections beyond the
        # round's, and the frontend's fleet /metricsz reads the cache.
        # Best-effort: a peer without the endpoint (older build) or
        # with its sampler off must not read as unhealthy — health is
        # /healthz's verdict alone. ISSUE 16 batches the fetch to
        # every k-th round (metrics_every_rounds) — at 1000 peers the
        # metrics leg is the expensive one, and a k-round-old window
        # is still a window.
        if (rnd - 1) % self.metrics_every_rounds != 0:
            return
        try:
            mz = self._get_json(
                f"/metricsz?window_s={self.metrics_window_s:g}")
            with self._lock:
                self._metricsz = mz
                self._metricsz_t = self._clock()
        except (OSError, ValueError, ConnectionError,
                http.client.HTTPException):
            self.metricsz_failures_total += 1

    def refresh(self) -> bool:
        """One synchronous probe round; returns success. Updates the
        health latch: ``fail_threshold`` consecutive failures evict
        (opening the breaker when one is attached); a success clears
        the failure count and — breakerless only — re-admits."""
        self.probes_total += 1
        try:
            self._probe_once()
        except (OSError, ValueError, ConnectionError,
                http.client.HTTPException):
            self.probe_failures_total += 1
            with self._lock:
                self._fails += 1
                evict = self._fails >= self.fail_threshold \
                    and self._healthy
                if evict:
                    self._healthy = False
            if evict:
                obs.record_event("fleet_peer_down", peer=self.name,
                                 fails=self._fails)
                if self.breaker is not None:
                    self.breaker.record_failure()
            return False
        with self._lock:
            self._fails = 0
            rejoin = not self._healthy and self.breaker is None
            if rejoin:
                # no breaker: probes coming back IS the rejoin. With a
                # breaker, rejoin goes through the router's probation
                # probe (frontend closes it -> on_state marks healthy).
                self._healthy = True
        if rejoin:
            obs.record_event("fleet_peer_up", peer=self.name)
        return True

    # ---------------------------------------------------- background prober
    def start(self):
        if self._thread is not None and self._thread.is_alive():
            return
        self._halt.clear()
        self._thread = threading.Thread(
            target=self._probe_loop, daemon=True,
            name=f"fleet-probe-{self.name}")
        self._thread.start()

    def stop(self, timeout: float = 2.0):
        self._halt.set()
        t = self._thread
        if t is not None and t.is_alive():
            t.join(timeout)

    def _probe_loop(self):
        # seeded phase + per-round jitter (ISSUE 16): the same
        # schedule functions the fleet sim replays, so decorrelation
        # behavior measured in-sim is the live thread's behavior
        if self._halt.wait(probe_phase(self.name,
                                       self.probe_interval_s,
                                       seed=self.seed)):
            return
        rnd = 0
        while True:
            try:
                self.refresh()
            except Exception as e:  # the prober must outlive any bug
                obs.record_event("fleet_probe_error", peer=self.name,
                                 err=repr(e))
            rnd += 1
            if self._halt.wait(probe_delay(
                    self.name, self.probe_interval_s, rnd,
                    jitter_frac=self.jitter_frac, seed=self.seed)):
                return

    # ------------------------------------------------------ the router seam
    def _fresh(self) -> bool:
        t = self._snap_t
        return t is not None \
            and self._clock() - t <= self.stale_after_s

    def healthy(self) -> bool:
        """Staleness-bounded: a peer whose last good probe is older
        than ``stale_after_s`` is unhealthy even before the failure
        count evicts it — the router must never trust an answer nobody
        has verified recently."""
        with self._lock:
            return self._healthy and self._fresh() \
                and not self._snap.get("draining", False)

    def mark(self, healthy: bool):
        with self._lock:
            self._healthy = bool(healthy)

    def load(self) -> float:
        with self._lock:
            return float(self._snap.get("load", 0.0))

    def has_prefix(self, digest: str) -> bool:
        """Fleet-wide prefix awareness: True when the peer's GOSSIPED
        digest set holds ``digest`` and the set is fresh. A stale set
        answers False — a wrong warm-verdict only costs one prefill,
        but the bound keeps the error window explicit."""
        with self._lock:
            if self._digest_t is None \
                    or self._clock() - self._digest_t \
                    > self.stale_after_s:
                return False
            # the spilled tier counts as warm: a restore on the peer
            # still skips the span's prefill (ISSUE 17)
            return digest in self._digests or digest in self._spilled

    def set_metrics_window(self, window_s: float):
        """Change the window the NEXT probe rounds fetch (the
        frontend's ``?window_s=N`` pass-through — cached federation
        converges to the new window within one probe interval)."""
        self.metrics_window_s = float(window_s)

    def metricsz(self) -> Dict[str, Any]:
        """The cached windowed-metrics doc (ISSUE 15), staleness-
        tagged: a peer nobody probed within ``stale_after_s`` reports
        ``stale`` and the frontend excludes it from fleet totals —
        the same freshness bound ``healthy()`` applies."""
        with self._lock:
            age = None if self._metricsz_t is None \
                else self._clock() - self._metricsz_t
            return {
                "peer": self.name,
                "age_s": round(age, 3) if age is not None else None,
                "stale": age is None or age > self.stale_after_s,
                "doc": dict(self._metricsz) if self._metricsz
                else None,
            }

    def note_proxy_failure(self):
        """The frontend observed this peer fail an in-flight proxied
        stream (conn drop / 5xx): evict immediately — stronger
        evidence than a missed health probe."""
        with self._lock:
            self._healthy = False
        if self.breaker is not None:
            self.breaker.record_failure()

    # ------------------------------------------------- frontend HA gossip
    def adopt_digests(self, digests, generation: int,
                      spilled=()) -> bool:
        """Adopt a SIBLING FRONTEND's fresher view of this peer's
        prefix-digest set (ISSUE 16 HA gossip). Generation-guarded:
        only a strictly newer generation wins — our own probe loop is
        the authority whenever it is at least as current, so gossip can
        only ever move a frontend FORWARD in time, never roll it back.
        Returns True when adopted."""
        gen = int(generation)
        with self._lock:
            if gen <= self._digest_gen:
                return False
            self._digests = frozenset(digests or ())
            self._spilled = frozenset(spilled or ())
            self._digest_gen = gen
            self._digest_t = self._clock()
            return True

    def gossip_view(self) -> Dict[str, Any]:
        """What a sibling frontend may adopt about this peer: the
        gossiped digest set + its generation (authoritative: the PEER's
        own counter, comparable across frontends), plus health and
        breaker state as HINTS (each frontend re-derives those from its
        own probes; hints only pre-warm a cold sibling)."""
        with self._lock:
            out = {
                "digests": sorted(self._digests),
                "spilled": sorted(self._spilled),
                "generation": self._digest_gen,
                "healthy": self._healthy and self._fresh()
                and not self._snap.get("draining", False),
            }
        b = self.breaker
        if b is not None:
            out["breaker"] = b.snapshot().get("state")
        return out

    # ------------------------------------------------------------- exports
    def signals(self) -> Dict[str, Any]:
        """The autoscaler's per-peer signal read (cached, O(1))."""
        with self._lock:
            return {
                "peer": self.name,
                "healthy": self._healthy and self._fresh()
                and not self._snap.get("draining", False),
                "stale": not self._fresh(),
                "load": float(self._snap.get("load", 0.0)),
                "queue_depth": int(self._snap.get("queue_depth", 0)),
                "free_slots": int(self._snap.get("free_slots", 0)),
                "total_slots": int(self._snap.get("total_slots", 0)),
                "block_pool_free_frac": float(
                    self._snap.get("block_pool_free_frac", 1.0)),
                "goodput_frac": float(
                    self._snap.get("goodput_frac", 1.0)),
            }

    def snapshot(self) -> Dict[str, Any]:
        """/debugz view of this peer's adapter state."""
        with self._lock:
            snap = dict(self._snap)
            out = {
                "peer": self.name,
                "url": f"{self.host}:{self.port}",
                "healthy_latch": self._healthy,
                "healthy": self._healthy and self._fresh()
                and not snap.get("draining", False),
                "stale": not self._fresh(),
                "consecutive_probe_failures": self._fails,
                "probes": self.probes_total,
                "probe_failures": self.probe_failures_total,
                "snap": snap,
                "gossip": {
                    "digests": len(self._digests),
                    "spilled": len(self._spilled),
                    "generation": self._digest_gen,
                    "fetches": self.gossip_fetches_total,
                    "unchanged_skips": self.gossip_unchanged_total,
                },
                "metricsz": {
                    "window_s": self.metrics_window_s,
                    "cached": bool(self._metricsz),
                    "age_s": round(self._clock() - self._metricsz_t,
                                   3)
                    if self._metricsz_t is not None else None,
                    "failures": self.metricsz_failures_total,
                },
            }
        b = self.breaker
        if b is not None:
            out["breaker"] = b.snapshot()
        return out
