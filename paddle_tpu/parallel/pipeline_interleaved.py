"""Interleaved 1F1B pipeline — virtual pipeline stages (reference:
paddle/distributed/fleet/meta_parallel/pipeline_parallel.py, the
``virtual_pp_degree`` interleaved schedule; Megatron-LM's
"interleaved 1F1B").

Each device holds ``v`` model chunks instead of one contiguous stage:
global stage ``g`` (of S = v*pp) lives on device ``g % pp``, chunk
``g // pp``. A microbatch therefore visits every device v times, and the
pipeline bubble shrinks from (pp-1) full-stage units to (pp-1)
chunk-units — v times smaller, the whole point of interleaving.

TPU-native realisation: like the non-interleaved 1F1B in
``pipeline.py``, this is ONE SPMD program inside `shard_map` manual over
``pp`` (tp/fsdp/dp stay GSPMD-auto inside the chunk fns). What is new:

- Consecutive global stages sit on consecutive devices, so EVERY tick's
  handoff is the same ring `lax.ppermute` (+1 forward, -1 backward) —
  the interleaving needs no special routing, just more ticks.
- The who-does-what-when problem is solved OUTSIDE the program: the
  schedule (microbatch m, chunk c, live?) per (tick, device) is computed
  on the host as static int32 tables and streamed through the
  `lax.scan` as xs; each device picks its row with `lax.axis_index`.
  Collision-freedom is *asserted* during table construction, not hoped
  for: the tick formula
      fwd(m, g)  = (m // pp) * S + (m % pp) + g
      bwd(m, g)  = S + (m // pp) * S + (m % pp) + (S - 1 - g)
  assigns each device at most one forward and one backward per tick
  (unique (m, c) recovery mod pp — see _build_schedule), and
  bwd(m, S-1) = fwd(m, S-1) + 1: the backward chases the forward at the
  1F1B distance, so saved activations stay O(pp), not O(M).
- Per-chunk state: chunk params are stacked on a local leading [v] dim
  (dynamic-indexed by the scheduled chunk), activations live in a
  [v, K] ring whose K is the exact max-in-flight computed from the
  tables, and chunk grads scatter-add into [v, ...] accumulators.

Embedding and loss head run only where they live (device 0 chunk 0 /
device pp-1 chunk v-1) behind device-varying `lax.cond`s, as in
pipeline.py.
"""
from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec as P

from ..distributed.env import get_mesh
from .pipeline import _tree_add_where, validate_pp_mesh


def _build_schedule(pp: int, v: int, M: int):
    """Static (tick, device) -> (m, chunk, live) tables for fwd and bwd.

    Returns (fwd_m, fwd_c, fwd_live, bwd_m, bwd_c, bwd_live) as [T, pp]
    int32/bool arrays, plus K, the max activations in flight per chunk.
    """
    S = v * pp

    def fwd_tick(m, g):
        return (m // pp) * S + (m % pp) + g

    def bwd_tick(m, g):
        return S + (m // pp) * S + (m % pp) + (S - 1 - g)

    T = bwd_tick(M - 1, 0) + 1
    shape = (T, pp)
    fwd_m = np.zeros(shape, np.int32)
    fwd_c = np.zeros(shape, np.int32)
    fwd_live = np.zeros(shape, bool)
    bwd_m = np.zeros(shape, np.int32)
    bwd_c = np.zeros(shape, np.int32)
    bwd_live = np.zeros(shape, bool)
    for m in range(M):
        for g in range(S):
            d, c = g % pp, g // pp
            t = fwd_tick(m, g)
            assert not fwd_live[t, d], "fwd schedule collision"
            fwd_m[t, d], fwd_c[t, d], fwd_live[t, d] = m, c, True
            t = bwd_tick(m, g)
            assert not bwd_live[t, d], "bwd schedule collision"
            bwd_m[t, d], bwd_c[t, d], bwd_live[t, d] = m, c, True

    # exact ring size: max, over (device, chunk), of activations whose
    # forward has run but whose backward has not
    K = 1
    for g in range(S):
        events = [(fwd_tick(m, g), 1) for m in range(M)] + \
                 [(bwd_tick(m, g), -1) for m in range(M)]
        live = peak = 0
        for _, delta in sorted(events):
            live += delta
            peak = max(peak, live)
        K = max(K, peak)
    return (fwd_m, fwd_c, fwd_live, bwd_m, bwd_c, bwd_live), T, K


def interleaved_pipeline_value_and_grad(
        embed_fn: Callable, chunk_fn: Callable, head_loss_fn: Callable,
        n_stages: int, v: int, axis_name: str = "pp", mesh=None):
    """Interleaved-1F1B analogue of ``pipeline_value_and_grad``.

    Args:
      embed_fn(embed_params, tokens[mb, s]) -> x [mb, s, h]
      chunk_fn(chunk_params, x) -> y (same shape; one chunk = L/(v*pp)
        decoder layers; called with the scheduled chunk's params)
      head_loss_fn(head_params, y, labels[mb, s]) -> scalar mean loss
      n_stages: pp degree; v: virtual chunks per device (v=1 degenerates
        to the plain schedule — use pipeline.py then, it is cheaper).

    Returns fn(params, tokens, labels) -> (loss, grads) with
      params = {"embed":…, "stages": pytree with leading [v, pp, …],
                "head":…};  tokens/labels [n_micro, micro_b, seq].
    """

    def run(params, tokens, labels):
        m = mesh or get_mesh()
        validate_pp_mesh(m, axis_name)
        pp = n_stages
        stage_specs = jax.tree.map(lambda _: P(None, axis_name),
                                   params["stages"])
        in_specs = ({"embed": jax.tree.map(lambda _: P(), params["embed"]),
                     "stages": stage_specs,
                     "head": jax.tree.map(lambda _: P(), params["head"])},
                    P(), P())
        out_specs = (P(), in_specs[0])

        M = tokens.shape[0]
        tables, T, K = _build_schedule(pp, v, M)
        xs = tuple(jnp.asarray(t) for t in tables)

        def body(prm, toks, labs, *sched):
            # local chunk params: [v, 1, ...] -> [v, ...]
            cparams = jax.tree.map(lambda p: p[:, 0], prm["stages"])
            eparams, hparams = prm["embed"], prm["head"]
            d = lax.axis_index(axis_name)
            is_dev0, is_last_dev = d == 0, d == pp - 1

            x_sd = jax.eval_shape(embed_fn, eparams, toks[0])
            xdt = x_sd.dtype
            # MoE chunks return (y, aux): same per-stage aux seeding as
            # pipeline_value_and_grad (pp x ep composition)
            out_sd = jax.eval_shape(
                chunk_fn, jax.tree.map(lambda p: p[0], cparams),
                jax.ShapeDtypeStruct(x_sd.shape, xdt))
            has_aux = isinstance(out_sd, (tuple, list))
            zeros_h = jax.tree.map(jnp.zeros_like, hparams)
            zeros_e = jax.tree.map(jnp.zeros_like, eparams)

            def chunk_at(c):
                return jax.tree.map(
                    lambda p: lax.dynamic_index_in_dim(p, c, 0,
                                                       keepdims=False),
                    cparams)

            def tick(c, row):
                fm, fc, flive, bm, bc, blive = (r[d] for r in row)
                # ---------------------------------------------- forward
                fm_c = jnp.clip(fm, 0, M - 1)
                tok_f = lax.dynamic_index_in_dim(toks, fm_c, 0,
                                                 keepdims=False)
                first_stage = is_dev0 & (fc == 0)
                x0 = lax.cond(
                    first_stage,
                    lambda: embed_fn(eparams, tok_f).astype(xdt),
                    lambda: jnp.zeros(x_sd.shape, xdt))
                x_in = jnp.where(first_stage, x0, c["recv_f"])
                y = chunk_fn(chunk_at(fc), x_in)
                if has_aux:
                    y = y[0]
                y = jnp.where(flive, y, jnp.zeros_like(y))
                slot_f = fm_c % K
                old = c["xbuf"][fc, slot_f]
                xbuf = c["xbuf"].at[fc, slot_f].set(
                    jnp.where(flive, x_in, old))

                # ---------------------------------------------- backward
                bm_c = jnp.clip(bm, 0, M - 1)
                x_sv = xbuf[bc, bm_c % K]
                lab_b = lax.dynamic_index_in_dim(labs, bm_c, 0,
                                                 keepdims=False)
                if has_aux:
                    (y_b, aux_b), chunk_vjp = jax.vjp(chunk_fn,
                                                      chunk_at(bc), x_sv)
                else:
                    y_b, chunk_vjp = jax.vjp(chunk_fn, chunk_at(bc), x_sv)
                    aux_b = jnp.float32(0.0)

                last_stage = is_last_dev & (bc == v - 1)

                def head_branch():
                    loss_m, head_vjp = jax.vjp(
                        lambda hp, yy: head_loss_fn(hp, yy, lab_b),
                        hparams, y_b)
                    g_h_m, dy_head = head_vjp(jnp.ones((), loss_m.dtype))
                    return loss_m.astype(jnp.float32), g_h_m, \
                        dy_head.astype(xdt)

                loss_m, g_h_m, dy_head = lax.cond(
                    last_stage, head_branch,
                    lambda: (jnp.float32(0.0), zeros_h,
                             jnp.zeros(x_sd.shape, xdt)))
                dy = jnp.where(last_stage, dy_head, c["recv_b"])
                if has_aux:
                    g_ch_m, dx = chunk_vjp((dy, jnp.ones((), aux_b.dtype)))
                else:
                    g_ch_m, dx = chunk_vjp(dy)

                first_bwd = is_dev0 & (bc == 0)

                def embed_branch():
                    tok_b = lax.dynamic_index_in_dim(toks, bm_c, 0,
                                                     keepdims=False)
                    _, embed_vjp = jax.vjp(embed_fn, eparams, tok_b)
                    return embed_vjp(dx.astype(x_sd.dtype))[0]

                g_e_m = lax.cond(first_bwd, embed_branch, lambda: zeros_e)

                g_st = jax.tree.map(
                    lambda acc, g: acc.at[bc].add(
                        jnp.where(blive, g, jnp.zeros_like(g)).astype(
                            acc.dtype)),
                    c["g_st"], g_ch_m)
                c = dict(
                    xbuf=xbuf,
                    g_st=g_st,
                    g_h=_tree_add_where(blive & last_stage, c["g_h"], g_h_m),
                    g_e=_tree_add_where(blive & first_bwd, c["g_e"], g_e_m),
                    loss=c["loss"] + jnp.where(blive & last_stage, loss_m,
                                               0.0)
                    + jnp.where(blive, aux_b.astype(jnp.float32), 0.0),
                    recv_f=lax.ppermute(
                        y, axis_name,
                        [(i, (i + 1) % pp) for i in range(pp)]),
                    recv_b=lax.ppermute(
                        jnp.where(blive, dx, jnp.zeros_like(dx)),
                        axis_name,
                        [(i, (i - 1) % pp) for i in range(pp)]),
                )
                return c, None

            carry0 = dict(
                xbuf=jnp.zeros((v, K) + x_sd.shape, xdt),
                g_st=jax.tree.map(jnp.zeros_like, cparams),
                g_h=zeros_h,
                g_e=zeros_e,
                loss=jnp.float32(0.0),
                recv_f=jnp.zeros(x_sd.shape, xdt),
                recv_b=jnp.zeros(x_sd.shape, xdt),
            )
            c, _ = lax.scan(tick, carry0, sched)

            grads = {
                "stages": jax.tree.map(lambda g: (g / M)[:, None],
                                       c["g_st"]),
                "head": jax.tree.map(
                    lambda g: lax.psum(g, axis_name) / M, c["g_h"]),
                "embed": jax.tree.map(
                    lambda g: lax.psum(g, axis_name) / M, c["g_e"]),
            }
            loss = lax.psum(c["loss"], axis_name) / M
            return loss, grads

        from ..utils.jax_compat import shard_map
        return shard_map(body, mesh=m, in_specs=in_specs + (P(),) * 6,
                         out_specs=out_specs, axis_names={axis_name},
                         check_vma=False)(params, tokens, labels, *xs)

    return run
