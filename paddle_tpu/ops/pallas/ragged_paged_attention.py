"""Ragged paged-attention Pallas kernel (ISSUE 6 tentpole; reference:
PAPERS.md "Ragged Paged Attention" — ONE kernel over variable-length
requests with no per-request padding in the work schedule).

The grid-per-row kernel (`paged_attention.py`) runs a fixed ``(R, kvh,
M)`` grid: every row pays M grid steps whether it holds 1 live block or
M. Dead steps clamp their index maps (no copy, no compute), but they
still occupy the scalar core and fragment Mosaic's pipeline R times per
kv head. This kernel flattens the work into a single SCHEDULE of (row,
logical block) pairs, packed live-first:

- the schedule is computed from ``seq_lens``/``block_tables`` with jnp
  ops (cumsum + searchsorted over per-row live-block counts) INSIDE the
  caller's jit — in the fused decode tick it is traced once per program
  and XLA CSE-dedups it across layers. No host round-trip per tick.
- schedule capacity ``S`` is static ``R*M`` (every row's table can be
  fully live; a physical-pool bound would under-count when prefix
  caching shares blocks across rows — see ``schedule_capacity``). The
  live work is packed contiguous at the front, so the dead tail is ONE
  run of clamped (copy-free, predicated-off) steps instead of R of
  them.
- grid ``(kvh, S)``; the fp32 accumulator scratch carries the online
  softmax across a row's consecutive schedule steps; `first`/`last`
  steps of each row's run are detected from the prefetched schedule
  (init / finalize). The output index map repeats a row's index across
  its run, so Mosaic flushes each row's output exactly once.
- dead steps (s >= total live) clamp row/block to the last live step:
  the repeated index skips the HBM→VMEM copy and `@pl.when` skips the
  compute, so the tail costs only scalar-core index math.
- GQA rides the matmul M dim exactly like `paged_attention.py`: q is
  viewed [R, kvh, group(padded to 8), d], each KV block is read once
  per KV head. The pool is viewed [P, B, kvh*d] so KV blocks are
  (B, d) with the column block selecting the head — (8, 128)-tilable
  for the gated shapes.

Sliding windows schedule only the in-band blocks per row (the front
clamp moves into the schedule itself instead of the index map).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from . import interpret_enabled as _interpret

NEG_INF = -1e30


def schedule_capacity(R: int, M: int, P: int) -> int:
    """Static schedule length: every row can contribute up to M live
    LOGICAL blocks, so the schedule must hold R*M. A pool-derived bound
    (P-1 allocatable + one write block per row) would be tighter for
    block-constrained configs but is WRONG under prefix caching: shared
    physical blocks count once against the pool yet appear in every
    borrowing row's table, so the sum of logical live blocks can exceed
    any physical-pool bound — a truncated schedule cuts a row's run
    mid-stride and its output block is never finalized (garbage
    attention for that row and every row after it). The dead tail is
    copy-free and predicated off, so the R*M worst case costs only
    scalar-core index math per unused step."""
    del P
    return R * M


def build_schedule(block_tables, seq_lens, S: int, block_size: int,
                   window=None, q_len: int = 1):
    """Flattened live-first schedule. Returns int32 arrays
    (row[S], blk[S], live[S]) where (row, blk) index ``block_tables``
    and live flags steps < total. Dead steps repeat the LAST live step's
    (row, blk) so their block indices never change (copy-free). All jnp
    — traceable inside the decode tick's jit.

    ``q_len`` > 1 (ISSUE 7 multi-query verify rows): each row carries
    q_len query positions seq_len .. seq_len+q_len-1, so live blocks
    must cover the LAST query's window (lens + q_len attendable tokens)
    while a sliding window's front clamp follows the FIRST query."""
    R, M = block_tables.shape
    B = block_size
    lens = jnp.asarray(seq_lens, jnp.int32)
    valid = lens + q_len                              # attendable tokens
    nb = jnp.clip((valid + B - 1) // B, 1, M)         # last live block + 1
    if window is None:
        lo = jnp.zeros((R,), jnp.int32)
    else:
        lo = jnp.maximum(lens + 1 - window, 0) // B   # first in-band block
    cnt = nb - lo                                     # >= 1 per row
    cum = jnp.cumsum(cnt)
    total = cum[-1]
    starts = cum - cnt
    s = jnp.arange(S, dtype=jnp.int32)
    row = jnp.searchsorted(cum, s, side="right").astype(jnp.int32)
    rowc = jnp.clip(row, 0, R - 1)
    blk = lo[rowc] + (s - starts[rowc])
    live = s < total
    li = jnp.clip(total - 1, 0, S - 1)
    row_s = jnp.where(live, rowc, rowc[li])
    blk_s = jnp.where(live, blk, blk[li])
    return row_s, blk_s, live.astype(jnp.int32)


def _ragged_kernel(tbl_ref, len_ref, row_ref, blk_ref, live_ref,
                   q_ref, k_ref, v_ref, o_ref, acc, m_scr, l_scr, *,
                   scale, bs, S, window, group):
    si = pl.program_id(1)
    r = row_ref[si]
    b = blk_ref[si]
    live = live_ref[si] == 1
    prv = jnp.maximum(si - 1, 0)
    nxt = jnp.minimum(si + 1, S - 1)
    prev_same = (si > 0) & (row_ref[prv] == r) & (live_ref[prv] == 1)
    next_same = (si < S - 1) & (row_ref[nxt] == r) & (live_ref[nxt] == 1)
    first = live & jnp.logical_not(prev_same)
    last = live & jnp.logical_not(next_same)

    @pl.when(first)
    def _init():
        acc[:] = jnp.zeros_like(acc)
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)

    @pl.when(live)
    def _compute():
        valid = len_ref[r] + 1          # tokens [0, seq_len] attendable
        q = q_ref[0, 0, :, :]                        # [gp, d]
        k = k_ref[0, :, :]                           # [bs, d]
        v = v_ref[0, :, :]
        s = lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * scale
        gp = q.shape[0]
        k_ids = lax.broadcasted_iota(jnp.int32, (gp, bs), 1) + b * bs
        # multi-query rows (ISSUE 7): the q tile packs q_len positions x
        # `group` query heads, so sublane j belongs to verify position
        # t = j // group and attends causally up to seq_len + t. Single-
        # query calls have every real sublane at t == 0 — the original
        # mask; padded sublanes see a wider mask but their rows are
        # sliced off by the caller.
        t_of = lax.broadcasted_iota(jnp.int32, (gp, bs), 0) // group
        keep = k_ids < valid + t_of
        if window is not None:
            keep &= k_ids >= valid + t_of - window
        s = jnp.where(keep, s, NEG_INF)
        m_prev = m_scr[:, :1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m_prev - m_new)
        l_scr[:, :1] = alpha * l_scr[:, :1] + jnp.sum(p, axis=-1,
                                                      keepdims=True)
        acc[:] = acc[:] * alpha + lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_scr[:, :1] = m_new

    @pl.when(last)
    def _finalize():
        safe_l = jnp.maximum(l_scr[:, :1], 1e-30)
        o_ref[0, 0, :, :] = (acc[:] / safe_l).astype(o_ref.dtype)


def ragged_paged_attention_pallas(q, kp, vp, block_tables, seq_lens,
                                  scale, window=None):
    """q [R, h, d] (single-query decode) OR [R, T, h, d] (multi-query
    speculative verify rows, ISSUE 7: query t of row r sits at position
    seq_lens[r] + t and attends tokens 0..seq_lens[r]+t); kp/vp
    [P, B, kvh, d] physical pools; block_tables [R, M]; seq_lens [R].
    Returns q's shape.

    Multi-query rides the SAME (kvh, S) schedule grid: the q tile packs
    T positions x `group` heads into the sublane dim (padded to 8), so
    each KV block is still read once per kv head per row — the verify's
    extra queries are matmul rows, not extra HBM traffic."""
    multi = q.ndim == 4
    if multi:
        R, T, h, d = q.shape
    else:
        R, h, d = q.shape
        T = 1
    P, B, kvh, _ = kp.shape
    M = block_tables.shape[1]
    group = h // kvh
    rows = T * group
    gp = max(8, -(-rows // 8) * 8)
    S = schedule_capacity(R, M, P)

    if multi:
        # [R, T, kvh, group, d] -> [R, kvh, T*group, d]: position-major
        # sublanes so the kernel's t = sublane // group mapping holds
        qg = q.reshape(R, T, kvh, group, d).transpose(0, 2, 1, 3, 4) \
             .reshape(R, kvh, rows, d)
    else:
        qg = q.reshape(R, kvh, group, d)
    if gp != rows:
        qg = jnp.pad(qg, ((0, 0), (0, 0), (0, gp - rows), (0, 0)))

    tbl = jnp.asarray(block_tables, jnp.int32)
    lens = jnp.asarray(seq_lens, jnp.int32)
    row_s, blk_s, live = build_schedule(tbl, lens, S, B, window=window,
                                        q_len=T)

    def q_index(ki, si, tbl, lens, row, blk, live):
        return (row[si], ki, 0, 0)

    def kv_index(ki, si, tbl, lens, row, blk, live):
        # dead steps carry the last live step's (row, blk): the repeated
        # physical index skips the copy
        return (tbl[row[si], blk[si]], 0, ki)

    kernel = functools.partial(_ragged_kernel, scale=scale, bs=B, S=S,
                               window=window, group=group)
    kc = kp.reshape(P, B, kvh * d)
    vc = vp.reshape(P, B, kvh * d)
    out = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=5,
            grid=(kvh, S),
            in_specs=[
                pl.BlockSpec((1, 1, gp, d), q_index),
                pl.BlockSpec((1, B, d), kv_index),
                pl.BlockSpec((1, B, d), kv_index),
            ],
            out_specs=pl.BlockSpec((1, 1, gp, d), q_index),
            scratch_shapes=[
                pltpu.VMEM((gp, d), jnp.float32),
                pltpu.VMEM((gp, 128), jnp.float32),
                pltpu.VMEM((gp, 128), jnp.float32),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((R, kvh, gp, d), q.dtype),
        interpret=_interpret(),
    )(tbl, lens, row_s, blk_s, live, qg, kc, vc)
    out = out[:, :, :rows, :]
    if not multi:
        return out.reshape(R, h, d)
    return out.reshape(R, kvh, T, group, d).transpose(0, 2, 1, 3, 4) \
              .reshape(R, T, h, d)
