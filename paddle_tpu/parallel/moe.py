"""Mixture-of-Experts with expert parallelism (reference: Paddle's
incubate.distributed.models.moe + PaddleNLP Qwen2-MoE/DeepSeekMoE recipes —
top-k gating, capacity dispatch, NCCL all_to_all over the expert group).

TPU-native (GShard-style): experts live as *stacked* weights
[E, in, out] sharded over the ``ep`` mesh axis; dispatch/combine are
einsums against a capacity-bucketed one-hot, so XLA lowers the routing to
all_to_all collectives over ICI — no hand-written NCCL plumbing, fully
static shapes (dropped tokens beyond capacity, GShard semantics).

Balancing: switch-style aux loss (mean router prob x mean token fraction
x E) plus optional router z-loss; or "loss-free" bias balancing
(DeepSeek-V3 style) via `update_loss_free_bias`.
"""
from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from ..nn import functional as F
from ..nn import initializer as I
from ..nn.layer import Layer, Parameter
from ..utils.rng import next_key
from .sharding import constraint


def _select_topk(router_logits, k, bias, n_group, topk_group, scoring,
                 group_score_mode):
    """The ONE definition of DeepSeek-family expert selection (scores,
    bias correction, group limiting, top-k) — shared by the dispatch and
    by ``update_loss_free_bias`` so the bias is always updated against
    the loads the real router produces."""
    T, E = router_logits.shape
    if scoring == "sigmoid":   # DeepSeek-V3: independent expert scores
        probs = jax.nn.sigmoid(router_logits.astype(jnp.float32))
    else:
        probs = jax.nn.softmax(router_logits.astype(jnp.float32), axis=-1)
    select_scores = probs if bias is None else probs + bias[None, :]
    if n_group > 1:
        g = select_scores.reshape(T, n_group, E // n_group)
        if group_score_mode == "top2_sum":   # DeepSeek-V3 group score
            top2, _ = jax.lax.top_k(g, 2)
            group_scores = jnp.sum(top2, axis=-1)             # [T, G]
        else:
            group_scores = jnp.max(g, axis=-1)                # [T, G]
        _, top_groups = jax.lax.top_k(group_scores, topk_group)
        group_ok = jnp.any(
            jnp.arange(n_group)[None, :, None] == top_groups[:, None, :],
            axis=-1)                                          # [T, G]
        # -inf, not 0: a loss-free-balancing bias can push eligible
        # scores negative, and a 0-masked ineligible expert must never
        # outrank them in top_k (gates come from the unmasked probs, so
        # -inf never reaches the combine weights)
        select_scores = jnp.where(
            jnp.repeat(group_ok, E // n_group, axis=1), select_scores,
            -jnp.inf)
    _, expert_ids = jax.lax.top_k(select_scores, k)          # [T, k]
    return probs, expert_ids


def top_k_routing(router_logits, k: int, capacity: int,
                  bias: Optional[jax.Array] = None,
                  norm_topk_prob: bool = False,
                  n_group: int = 1, topk_group: int = 1,
                  scoring: str = "softmax",
                  group_score_mode: str = "max"):
    """router_logits [T, E] -> (dispatch [T, E, C] bool, combine [T, E, C],
    aux_loss scalar). GShard top-k with per-expert capacity C.
    ``norm_topk_prob`` renormalizes the selected gates to sum to 1
    (Qwen2-57B-A14B-style); False keeps raw softmax-over-all probs.
    ``n_group > 1`` is DeepSeek's group-limited-greedy: experts split
    into n_group groups, only the top ``topk_group`` groups (by max
    member prob) stay eligible before the per-token top-k."""
    T, E = router_logits.shape
    probs, expert_ids = _select_topk(router_logits, k, bias, n_group,
                                     topk_group, scoring,
                                     group_score_mode)
    onehot = jax.nn.one_hot(expert_ids, E, dtype=jnp.float32)  # [T, k, E]
    gates = probs[:, None, :] * onehot                        # gate per choice
    if norm_topk_prob:
        total = jnp.sum(gates, axis=(1, 2), keepdims=True)
        gates = gates / jnp.maximum(total, 1e-9)
    # position of each token within its expert's bucket (over T*k choices,
    # priority by choice rank then token order — GShard's policy)
    flat = onehot.transpose(1, 0, 2).reshape(k * T, E)        # choice-major
    pos = (jnp.cumsum(flat, axis=0) - flat)                   # [kT, E]
    pos = pos.reshape(k, T, E).transpose(1, 0, 2)             # [T, k, E]
    keep = (pos < capacity) * onehot                          # drop overflow
    pos = jnp.minimum(pos, capacity - 1).astype(jnp.int32)
    pos_onehot = jax.nn.one_hot(pos, capacity, dtype=jnp.float32)  # [T,k,E,C]
    dispatch = jnp.einsum("tke,tkec->tec", keep, pos_onehot)
    combine = jnp.einsum("tke,tkec->tec", gates * keep, pos_onehot)
    # switch aux loss: E * sum_e mean_prob_e * mean_frac_e. Sigmoid
    # scores normalize first (DeepSeek's seq-aux does the same) — the raw
    # product would be minimized by driving EVERY score to 0, collapsing
    # the router instead of balancing it.
    frac = jnp.mean(onehot[:, 0, :], axis=0)   # fraction routed (top-1 choice)
    pn = probs / jnp.maximum(jnp.sum(probs, axis=-1, keepdims=True), 1e-9) \
        if scoring == "sigmoid" else probs
    mean_prob = jnp.mean(pn, axis=0)
    aux = E * jnp.sum(frac * mean_prob)
    return dispatch, combine, aux


class MoEMLP(Layer):
    """Drop-in replacement for a dense FFN: k-of-E expert SwiGLU MLPs with
    optional always-on shared experts (Qwen2-MoE/DeepSeekMoE pattern)."""

    def __init__(self, hidden_size: int, intermediate_size: int,
                 num_experts: int, top_k: int = 2,
                 capacity_factor: float = 1.25,
                 num_shared_experts: int = 0,
                 shared_intermediate_size: Optional[int] = None,
                 aux_loss_weight: float = 0.01,
                 use_shared_expert_gate: bool = False,
                 norm_topk_prob: bool = False,
                 routed_scaling_factor: float = 1.0,
                 n_group: int = 1, topk_group: int = 1,
                 scoring: str = "softmax",
                 group_score_mode: str = "max", name=None):
        super().__init__(name)
        self.hidden_size = hidden_size
        self.intermediate_size = intermediate_size
        self.num_experts = num_experts
        self.top_k = top_k
        self.capacity_factor = capacity_factor
        self.aux_loss_weight = aux_loss_weight
        self.norm_topk_prob = norm_topk_prob
        # DeepSeek-V2/V3: the routed (not shared) output is scaled
        self.routed_scaling_factor = routed_scaling_factor
        self.n_group, self.topk_group = n_group, topk_group
        self.scoring, self.group_score_mode = scoring, group_score_mode
        E, h, m = num_experts, hidden_size, intermediate_size
        init = I.XavierNormal()
        self.gate = Parameter(init(next_key(), (h, E)))  # router, replicated
        self.w_gate = Parameter(init(next_key(), (E, h, m)),
                                partition=("ep", None, None))
        self.w_up = Parameter(init(next_key(), (E, h, m)),
                              partition=("ep", None, None))
        self.w_down = Parameter(init(next_key(), (E, m, h)),
                                partition=("ep", None, None))
        # loss-free balancing bias (buffer: updated outside the grad path)
        self.register_buffer("expert_bias", jnp.zeros((E,)), persistable=True)
        self.shared = None
        self.has_shared_gate = False
        if num_shared_experts:
            sm = shared_intermediate_size or m * num_shared_experts
            self.shared_gate_proj = Parameter(init(next_key(), (h, sm)))
            self.shared_up_proj = Parameter(init(next_key(), (h, sm)))
            self.shared_down_proj = Parameter(init(next_key(), (sm, h)))
            self.shared = True
            if use_shared_expert_gate:
                # Qwen2-MoE: the shared expert's output is scaled by a
                # learned sigmoid gate on the token
                self.shared_expert_gate = Parameter(
                    init(next_key(), (h, 1)))
                self.has_shared_gate = True

    def capacity(self, tokens: int) -> int:
        c = int(math.ceil(self.capacity_factor * tokens * self.top_k
                          / self.num_experts))
        return max(c, 4)

    def forward(self, x, return_aux: bool = False):
        orig_shape = x.shape
        h = self.hidden_size
        xt = x.reshape(-1, h)                          # [T, h]
        T = xt.shape[0]
        C = self.capacity(T)
        logits = xt.astype(jnp.float32) @ self.gate.astype(jnp.float32)
        dispatch, combine, aux = top_k_routing(
            logits, self.top_k, C, bias=self.expert_bias,
            norm_topk_prob=self.norm_topk_prob,
            n_group=self.n_group, topk_group=self.topk_group,
            scoring=self.scoring, group_score_mode=self.group_score_mode)
        # dispatch to expert buckets: [E, C, h], sharded over ep
        xe = jnp.einsum("tec,th->ech", dispatch.astype(x.dtype), xt)
        xe = constraint(xe, "ep", None, None)
        # per-expert SwiGLU, batched over E on the MXU
        g = jnp.einsum("ech,ehm->ecm", xe, self.w_gate)
        u = jnp.einsum("ech,ehm->ecm", xe, self.w_up)
        ye = jnp.einsum("ecm,emh->ech", F.silu(g) * u, self.w_down)
        ye = constraint(ye, "ep", None, None)
        y = jnp.einsum("tec,ech->th", combine.astype(x.dtype), ye)
        if self.routed_scaling_factor != 1.0:
            y = y * self.routed_scaling_factor
        if self.shared:
            sg = F.silu(xt @ self.shared_gate_proj) * (xt @ self.shared_up_proj)
            so = sg @ self.shared_down_proj
            if self.has_shared_gate:
                so = jax.nn.sigmoid(
                    xt.astype(jnp.float32) @
                    self.shared_expert_gate.astype(jnp.float32)
                ).astype(so.dtype) * so
            y = y + so
        y = y.reshape(orig_shape)
        if return_aux:
            return y, self.aux_loss_weight * aux
        return y

    def update_loss_free_bias(self, router_logits, lr: float = 1e-3):
        """DeepSeek-V3 loss-free balancing: nudge per-expert bias opposite
        to its load error (host-side, outside the gradient path). Uses
        the SAME selection path as dispatch (scoring/group limiting), so
        the measured load is the load the router actually produces."""
        _, ids = _select_topk(router_logits, self.top_k, self.expert_bias,
                              self.n_group, self.topk_group, self.scoring,
                              self.group_score_mode)
        load = jnp.mean(jax.nn.one_hot(ids, self.num_experts).sum(1), axis=0)
        err = load - self.top_k / self.num_experts
        self._buffers["expert_bias"] = self.expert_bias - lr * jnp.sign(err)
        return self.expert_bias
