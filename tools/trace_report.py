#!/usr/bin/env python
"""SLO-attribution report over request-trace rings (ISSUE 10): ingest
the per-replica ``reqtrace_*.json`` ring dumps the gateway writes (on
drain, via ``Gateway.dump_traces``, or ``serve_loadgen --trace-dir``)
plus, optionally, the loadgen's client-side per-request JSONL, and
print the p50/p99 TTFT decomposition per component and per SLO class:

    python tools/trace_report.py RUNDIR_OR_FILES...        # human
    python tools/trace_report.py DIR --jsonl lg.jsonl      # + client join
    python tools/trace_report.py DIR --json                # machine

The decomposition is the tentpole formula (docs/OBSERVABILITY.md):

    ttft = queue_wait + prefill + first_tick   (+ accept residual)

so a bad p99 TTFT is attributed to the admission queue, the prefill
chunking, or the decode/dispatch path — per SLO class, with the exact
p99 request id named (percentiles here are EXACT order statistics over
the ring entries, not bucket interpolations). The client join matches
server rings against client-minted ``X-Request-Id``s: the TTFT delta
is the wire + gateway parse overhead, and client-only outcomes (shed
before a ring existed, connection errors) are counted separately.

Fleet merge (ISSUE 13): point the CLI at SEVERAL gateway run dirs (or
one shared ``--trace-dir`` a fleet loadgen run filled) and rings from
different PROCESSES merge into one timeline. A request that crossed
processes — proxied by the fleet frontend, or failed over to a
surviving peer mid-stream — is followed by request id: the report
counts cross-process requests, names the hop chain
(``fleet/frontend -> gwA/r0 -> gwB/r0``), and prints the merged
event-by-event timeline on one wall-clock axis (entries carry
``wall_accept``; event times are offsets from it).
"""
import argparse
import glob
import json
import os
import sys
from typing import Any, Dict, List, Optional

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

COMPONENTS = ("queue_wait_ms", "prefill_ms", "first_tick_ms")


def _pct(pairs: List[tuple], q: float) -> tuple:
    """Exact order-statistic percentile over (value, request_id) pairs
    — returns (value, exemplar_request_id)."""
    if not pairs:
        return 0.0, None
    pairs = sorted(pairs)
    i = min(int(q * (len(pairs) - 1) + 0.5), len(pairs) - 1)
    return pairs[i]


def load_rings(paths: List[str]) -> List[dict]:
    """Expand dirs to reqtrace_*.json, load and schema-validate each
    doc (invalid docs are skipped with a warning — one torn file must
    not kill the report)."""
    from paddle_tpu.serving.reqtrace import validate_ring_doc
    files: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            files.extend(sorted(glob.glob(
                os.path.join(p, "reqtrace_*.json"))))
        else:
            files.append(p)
    docs = []
    for f in files:
        try:
            with open(f) as fh:
                doc = json.load(fh)
        except (OSError, ValueError) as e:
            print(f"warning: skipping {f}: {e}", file=sys.stderr)
            continue
        problems = validate_ring_doc(doc)
        if problems:
            print(f"warning: {f} failed schema check "
                  f"({problems[0]}; {len(problems)} total) — skipped",
                  file=sys.stderr)
            continue
        doc["_file"] = os.path.basename(f)
        docs.append(doc)
    return docs


def load_client_jsonl(path: str) -> Dict[str, dict]:
    recs: Dict[str, dict] = {}
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
                recs[str(rec["request_id"])] = rec
            except (ValueError, KeyError):
                continue   # torn tail line: skip, don't die
    return recs


def fleet_merge(docs: List[dict], top: int = 5) -> Optional[dict]:
    """Join entries across rings from different PROCESSES by request
    id (ISSUE 13). A request is cross-process when it has ring entries
    in more than one dump, or its timeline carries fleet hop events
    (``proxy_to``/``peer_fail``/``resubmit``). Returns None when the
    input is a single-process view with no hops — the report then
    stays in its classic shape."""
    by_rid: Dict[str, List[tuple]] = {}
    for d in docs:
        lbl = d.get("labels") or {}
        where = (f"{lbl.get('gateway', '?')}/"
                 f"{lbl.get('replica', '?')}")
        for e in d["entries"]:
            by_rid.setdefault(str(e["request_id"]),
                              []).append((where, e))

    def _hops(entries):
        return sum(1 for _, e in entries
                   for _, k, _f in e.get("events", ())
                   if k == "peer_fail")

    cross = {rid: hops for rid, hops in by_rid.items()
             if len(hops) > 1
             or any(k in ("proxy_to", "peer_fail")
                    for _, e in hops
                    for _, k, _f in e.get("events", ()))}
    if not cross:
        return None
    chains = []
    for rid, hops in cross.items():
        # order hops by accept wall time: the frontend accepts first,
        # then each peer the request touched in failover order
        hops = sorted(hops, key=lambda we: we[1].get("wall_accept", 0))
        merged = []
        for where, e in hops:
            w0 = e.get("wall_accept") or 0.0
            for t, kind, fields in e.get("events", ()):
                merged.append((w0 + t / 1e3, where, kind, fields))
        merged.sort(key=lambda ev: ev[0])
        chains.append({
            "request_id": rid,
            "chain": [where for where, _ in hops],
            "outcomes": {where: e["outcome"] for where, e in hops},
            "peer_failovers": _hops(hops),
            "events": merged,
        })
    chains.sort(key=lambda c: (-c["peer_failovers"],
                               -len(c["chain"]), c["request_id"]))
    return {
        "cross_process_requests": len(cross),
        "with_peer_failover": sum(1 for c in chains
                                  if c["peer_failovers"]),
        "chains": chains[:top],
    }


def summarize(docs: List[dict],
              client: Optional[Dict[str, dict]] = None,
              top: int = 5) -> Dict[str, Any]:
    entries = [e for d in docs for e in d["entries"]]
    by_slo: Dict[str, List[dict]] = {}
    for e in entries:
        by_slo.setdefault(e["slo"], []).append(e)

    classes: Dict[str, Any] = {}
    for slo, es in sorted(by_slo.items()):
        outcomes: Dict[str, int] = {}
        for e in es:
            outcomes[e["outcome"]] = outcomes.get(e["outcome"], 0) + 1
        comps: Dict[str, Any] = {}
        for key in ("ttft_ms",) + COMPONENTS + ("tpot_ms",):
            pairs = [(e[key], e["request_id"]) for e in es
                     if e.get(key) is not None]
            p50, _ = _pct(pairs, 0.50)
            p99, rid99 = _pct(pairs, 0.99)
            comps[key] = {"n": len(pairs),
                          "p50": round(p50, 2), "p99": round(p99, 2),
                          "p99_request_id": rid99}
        # failover hops (ISSUE 12): how many requests in this class
        # rode a replica failure, and the total resubmission count —
        # failed-over timelines are always retained, so the hops are
        # printed event-by-event below
        fo = [e.get("failovers", 0) or 0 for e in es]
        classes[slo] = {"requests": len(es), "outcomes": outcomes,
                        "failed_over": sum(1 for n in fo if n),
                        "failover_hops": sum(fo),
                        "components": comps}

    slowest = sorted((e for e in entries if e.get("retained")
                      and e.get("ttft_ms") is not None),
                     key=lambda e: -e["ttft_ms"])[:top]

    out: Dict[str, Any] = {
        "rings": [d["_file"] for d in docs],
        "requests": len(entries),
        "retained": sum(bool(e.get("retained")) for e in entries),
        "classes": classes,
        "slowest_retained": slowest,
    }
    fleet = fleet_merge(docs, top=top)
    if fleet is not None:
        out["fleet"] = fleet
    if client is not None:
        server_ids = {e["request_id"] for e in entries}
        matched = [(client[e["request_id"]], e) for e in entries
                   if e["request_id"] in client]
        deltas = [(c["ttft_ms"] - e["ttft_ms"], e["request_id"])
                  for c, e in matched
                  if c.get("ttft_ms") is not None
                  and e.get("ttft_ms") is not None]
        client_only = {rid: rec.get("outcome")
                       for rid, rec in client.items()
                       if rid not in server_ids}
        d50, _ = _pct(deltas, 0.50)
        d99, rid = _pct(deltas, 0.99)
        out["client_join"] = {
            "client_records": len(client),
            "matched": len(matched),
            "client_only": len(client_only),
            "client_only_outcomes": sorted(
                {str(v) for v in client_only.values()})[:8],
            "wire_overhead_ms": {"n": len(deltas),
                                 "p50": round(d50, 2),
                                 "p99": round(d99, 2),
                                 "p99_request_id": rid},
        }
    return out


def render(s: Dict[str, Any]) -> str:
    lines = [f"rings: {', '.join(s['rings']) or '(none)'}",
             f"requests: {s['requests']}   retained timelines: "
             f"{s['retained']}"]
    for slo, cls in s["classes"].items():
        oc = " ".join(f"{k}={v}" for k, v in
                      sorted(cls["outcomes"].items()))
        fo = ""
        if cls.get("failed_over"):
            fo = (f"   failed-over {cls['failed_over']} "
                  f"({cls['failover_hops']} hops)")
        lines.append(f"class {slo}: n={cls['requests']}   {oc}{fo}")
        for key in ("ttft_ms",) + COMPONENTS + ("tpot_ms",):
            c = cls["components"][key]
            if not c["n"]:
                continue
            tail = f"   p99-req {c['p99_request_id']}" \
                if key == "ttft_ms" else ""
            lines.append(f"  {key:<14s} p50 {c['p50']:>9.2f}   "
                         f"p99 {c['p99']:>9.2f}   (n={c['n']}){tail}")
    if s["slowest_retained"]:
        lines.append("slowest retained timelines:")
        for e in s["slowest_retained"]:
            hop = (f" failovers={e['failovers']}"
                   if e.get("failovers") else "")
            lines.append(
                f"  {e['request_id']}  slo={e['slo']} "
                f"outcome={e['outcome']}{hop} ttft={e['ttft_ms']}ms "
                f"(queue {e.get('queue_wait_ms')} / prefill "
                f"{e.get('prefill_ms')} / first-tick "
                f"{e.get('first_tick_ms')})")
            for t, kind, fields in e.get("events", [])[:24]:
                extra = " ".join(f"{k}={v}" for k, v in fields.items())
                lines.append(f"    {t:>10.3f}ms  {kind:<14s} {extra}")
    fl = s.get("fleet")
    if fl:
        lines.append(
            f"fleet: {fl['cross_process_requests']} cross-process "
            f"requests ({fl['with_peer_failover']} rode a peer "
            f"failover)")
        for c in fl["chains"]:
            oc = " ".join(f"{w}={o}" for w, o in
                          sorted(c["outcomes"].items()))
            lines.append(f"  {c['request_id']}  "
                         f"{' -> '.join(c['chain'])}  "
                         f"peer_failovers={c['peer_failovers']}  {oc}")
            for t, where, kind, fields in c["events"][:32]:
                extra = " ".join(f"{k}={v}"
                                 for k, v in fields.items())
                lines.append(f"    {t:.3f}  {where:<24s} "
                             f"{kind:<14s} {extra}")
    cj = s.get("client_join")
    if cj:
        w = cj["wire_overhead_ms"]
        lines.append(
            f"client join: {cj['matched']}/{cj['client_records']} "
            f"matched ({cj['client_only']} client-only: "
            f"{cj['client_only_outcomes']})   wire overhead "
            f"p50 {w['p50']:.2f}ms p99 {w['p99']:.2f}ms")
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("rings", nargs="+",
                    help="reqtrace_*.json files or dirs holding them")
    ap.add_argument("--jsonl", default=None,
                    help="loadgen per-request JSONL to join "
                         "(tools/serve_loadgen.py --jsonl)")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable summary")
    ap.add_argument("--top", type=int, default=5,
                    help="slowest retained timelines to print")
    ns = ap.parse_args(argv)
    docs = load_rings(ns.rings)
    if not docs:
        print("no valid trace rings found", file=sys.stderr)
        return 2
    client = load_client_jsonl(ns.jsonl) if ns.jsonl else None
    s = summarize(docs, client=client, top=ns.top)
    print(json.dumps(s) if ns.json else render(s))
    return 0


if __name__ == "__main__":
    sys.exit(main())
