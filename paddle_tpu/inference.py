"""Inference predictor (reference: paddle.inference.Predictor /
paddle/fluid/inference/api — config + predictor over an optimized program;
PaddleNLP's llm/predict/predictor.py for the LLM path).

TPU-native: the "optimized program" is a cached jax.jit of the model's
functional form with donated weights left on device; optional weight-only
quantization at load (C17). XLA compiles one engine per input shape, so
serving discipline is SHAPE discipline:

- batch-dim bucketing: requests pad up to a fixed bucket ladder, bounding
  the number of compiled engines at len(buckets) per rank profile (the
  reference's shape-bucketed engine cache); padding rows are cropped
  before returning, so results are exact.
- `BatchingPredictor` adds the server-side micro-batching policy: concurrent
  `submit()` calls coalesce (up to max_batch, bounded by max_delay_ms)
  into one engine call — the TPU sees few, large, fixed-shape batches.
"""
from __future__ import annotations

import queue
import threading
import time
from concurrent.futures import Future
from typing import Any, Callable, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

DEFAULT_BUCKETS = (1, 2, 4, 8, 16, 32)


class Config:
    """paddle.inference.Config parity surface (the knobs that matter on
    TPU: dtype, quantization, shape buckets)."""

    def __init__(self, model_path: Optional[str] = None):
        self.model_path = model_path
        self.dtype = None                         # None = keep model dtype
        self.quant_bits: Optional[int] = None     # 8 / 4 / None
        self.quant_skip = ["lm_head", "embed"]
        self.batch_buckets: Optional[Tuple[int, ...]] = DEFAULT_BUCKETS

    def enable_weight_only_quant(self, bits: int = 8):
        self.quant_bits = bits
        return self

    def set_dtype(self, dtype):
        self.dtype = dtype
        return self

    def set_batch_buckets(self, buckets: Optional[Sequence[int]]):
        """None disables bucketing (one engine per exact batch size)."""
        self.batch_buckets = tuple(sorted(buckets)) if buckets else None
        return self


class Predictor:
    """Wraps a Layer for serving: jitted engines cached per shape bucket,
    optional dtype cast + PTQ at load, state kept on device."""

    def __init__(self, model, config: Optional[Config] = None):
        self.config = config or Config()
        self.model = model
        if self.config.dtype is not None:
            model.to(dtype=self.config.dtype)
        if self.config.quant_bits:
            from .quant import quantize_model
            quantize_model(model, bits=self.config.quant_bits,
                           skip=self.config.quant_skip)
        model.eval()
        self.last_serve_stats = {}
        self._paged_engines = {}
        self._fn, self._params = model.functional()
        # weights live on device once; every run reuses them
        self._params = jax.device_put(self._params)
        self._engine = jax.jit(self._fn)

    def _bucket(self, b: int) -> int:
        buckets = self.config.batch_buckets
        if not buckets:
            return b
        for cap in buckets:
            if b <= cap:
                return cap
        return b  # beyond the ladder: exact-shape engine

    def run(self, *inputs):
        """Eager-looking predict: inputs are host arrays; returns device
        outputs (np.asarray them for host use). The batch dim pads up to
        the bucket (edge-replicated rows, cropped from every output), so
        a b=3 request reuses the b=4 engine instead of compiling."""
        args = tuple(jnp.asarray(x) for x in inputs)
        b = args[0].shape[0] if args[0].ndim else 1
        cap = self._bucket(b)
        if cap != b:
            # pad only the inputs that actually carry the batch dim —
            # scalars / shared side inputs pass through untouched
            args = tuple(
                jnp.concatenate(
                    [a, jnp.broadcast_to(a[-1:], (cap - b,) + a.shape[1:])])
                if a.ndim and a.shape[0] == b else a
                for a in args)
        out = self._engine(self._params, *args)
        if cap != b:
            out = jax.tree.map(
                lambda o: o[:b]
                if hasattr(o, "ndim") and o.ndim and o.shape[0] == cap
                else o, out)
        return out

    __call__ = run

    def generate(self, input_ids, **kwargs):
        """Autoregressive generation with the model's KV cache path."""
        return self.model.generate(jnp.asarray(input_ids), **kwargs)

    def serve_stream(self, requests, max_new_tokens: int = 64,
                     eos_token_id=None, sampling=None, **engine_kw):
        """Continuous-batching service for a mixed-length request
        stream (reference: PaddleNLP llm predictor's block-attention
        path): ``requests`` maps request_id -> input_ids. Admission is
        FIFO: a request enters the moment a slot AND its blocks free
        up, backfilling slots that finished mid-decode (a large
        request at the queue head can delay the ones behind it — size
        the pool for the large case). Greedy by default — exact per
        request vs ``generate``; ``sampling`` maps request_id -> dict
        of per-request overrides (temperature / top_k / top_p / seed /
        repetition_penalty / stop_sequences), and chosen-token logprobs
        land in ``self.last_logprobs``. Returns request_id ->
        generated ids.

        The engine (pools + compiled prefill/decode executables) is
        cached per ``engine_kw`` shape, so repeated calls pay no
        recompile and no pool re-allocation."""
        from .generation.paged import PagedEngine
        key = tuple(sorted(engine_kw.items()))
        eng = self._paged_engines.get(key)
        if eng is None:
            eng = PagedEngine(self.model, **engine_kw)
            self._paged_engines[key] = eng
        for rid, ids in requests.items():
            eng.submit(rid, ids, max_new_tokens=max_new_tokens,
                       eos_token_id=eos_token_id,
                       **((sampling or {}).get(rid, {})))
        out = eng.run()
        eng.results.clear()  # the caller owns them now
        self.last_logprobs = dict(eng.logprobs)
        eng.logprobs.clear()
        self.last_serve_stats = dict(eng.stats)
        return out

    @classmethod
    def from_checkpoint(cls, model_factory: Callable[[], Any], path: str,
                        config: Optional[Config] = None):
        """Build model, load weights (paddle_tpu.load), wrap."""
        from .checkpoint import load
        model = model_factory()
        model.set_state_dict(load(path))
        return cls(model, config)


class BatchingPredictor:
    """Server-side micro-batching over a Predictor (reference: the
    batching policy in PaddleNLP's serving predictor / fastdeploy).

    Concurrent `submit()` calls enqueue single requests; a collector
    thread coalesces up to ``max_batch`` of them (waiting at most
    ``max_delay_ms`` once one is pending), stacks them into one bucketed
    engine call, and resolves each request's Future with its own row.
    """

    def __init__(self, model, config: Optional[Config] = None,
                 max_batch: int = 8, max_delay_ms: float = 2.0):
        self.predictor = Predictor(model, config)
        self.max_batch = max_batch
        self.max_delay = max_delay_ms / 1e3
        self._q: "queue.Queue" = queue.Queue()
        self._closed = False
        self._worker = threading.Thread(target=self._loop, daemon=True)
        self._worker.start()

    def submit(self, *inputs) -> Future:
        """One request (no batch dim on the inputs) -> Future of its
        outputs (batch dim stripped)."""
        if self._closed:
            raise RuntimeError("BatchingPredictor is closed")
        fut: Future = Future()
        self._q.put((tuple(np.asarray(x) for x in inputs), fut))
        return fut

    def run(self, *inputs):
        return self.submit(*inputs).result()

    def _loop(self):
        while True:
            item = self._q.get()
            if item is None:
                return
            batch = [item]
            deadline = time.monotonic() + self.max_delay
            while len(batch) < self.max_batch:
                left = deadline - time.monotonic()
                if left <= 0:
                    break
                try:
                    nxt = self._q.get(timeout=left)
                except queue.Empty:
                    break
                if nxt is None:
                    self._flush(batch)
                    return
                batch.append(nxt)
            self._flush(batch)

    def _flush(self, batch):
        reqs = [r for r, _ in batch]
        futs = [f for _, f in batch]
        try:
            stacked = tuple(np.stack([r[i] for r in reqs])
                            for i in range(len(reqs[0])))
            out = self.predictor.run(*stacked)
            for i, fut in enumerate(futs):
                fut.set_result(jax.tree.map(
                    lambda o: o[i] if hasattr(o, "ndim") and o.ndim else o,
                    out))
        except BaseException as e:
            for fut in futs:
                if not fut.done():
                    fut.set_exception(e)

    def close(self):
        self._closed = True
        self._q.put(None)
        self._worker.join(timeout=5)
        # a submit() racing past the _closed check may have enqueued
        # after the sentinel; its Future must fail, not hang forever
        while True:
            try:
                item = self._q.get_nowait()
            except queue.Empty:
                break
            if item is not None and not item[1].done():
                item[1].set_exception(
                    RuntimeError("BatchingPredictor closed before the "
                                 "request was served"))


def create_predictor(config: Config, model=None):
    """paddle.inference.create_predictor parity."""
    if model is None:
        raise ValueError("paddle_tpu predictor needs the model object "
                         "(graph serialization comes via jit.to_static AOT)")
    return Predictor(model, config)
