"""Shared causal-LM plumbing (reference: PaddleNLP's GenerationMixin on
PretrainedModel — every *ForCausalLM gains generate() and cache setup).

One implementation of the generation entry point and the static-shape KV
cache allocator; models only differ in their KV head count, read off the
config (GQA models set num_key_value_heads, MHA models fall back to
num_attention_heads).
"""
from __future__ import annotations

import jax.numpy as jnp

from ..nn.layer import Layer


class CausalLMBase(Layer):
    """Base for *ForCausalLM heads: generation + KV-cache allocation."""

    def generate(self, input_ids, config=None, key=None, **kwargs):
        from ..generation import generate as _generate
        return _generate(self, input_ids, config=config, key=key, **kwargs)

    def init_kv_caches(self, batch_size: int, max_len: int, dtype=None):
        cfg = self.config
        dtype = dtype or cfg.dtype
        kv_heads = getattr(cfg, "num_key_value_heads", None) \
            or cfg.num_attention_heads
        shape = (batch_size, max_len, kv_heads, cfg.head_dim)
        return [(jnp.zeros(shape, dtype), jnp.zeros(shape, dtype))
                for _ in range(cfg.num_hidden_layers)]
