"""paddle.distribution parity (reference: python/paddle/distribution/ —
Distribution ABC, Normal/Uniform/Categorical/Bernoulli/Beta/Dirichlet/
Gamma/Exponential/Laplace/LogNormal, TransformedDistribution,
kl_divergence registry).

TPU-native: sampling goes through explicit jax PRNG keys (pass ``key=``;
falls back to the framework seed-tree stream so eager use stays
paddle-shaped), log_prob/entropy are pure jnp — everything jit/vmap/grad
composable.
"""
from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.scipy import special as jsp

__all__ = [
    "Distribution", "Normal", "LogNormal", "Uniform", "Categorical",
    "Bernoulli", "Beta", "Dirichlet", "Gamma", "Exponential", "Laplace",
    "kl_divergence", "register_kl",
]


def _key(key):
    if key is not None:
        return key
    from .utils.rng import next_key
    return next_key()


class Distribution:
    def sample(self, shape=(), key=None):
        raise NotImplementedError

    def rsample(self, shape=(), key=None):
        """Reparameterized sample (differentiable where defined)."""
        return self.sample(shape, key=key)

    def log_prob(self, value):
        raise NotImplementedError

    def prob(self, value):
        return jnp.exp(self.log_prob(value))

    def entropy(self):
        raise NotImplementedError


class Normal(Distribution):
    def __init__(self, loc, scale):
        self.loc = jnp.asarray(loc)
        self.scale = jnp.asarray(scale)

    @property
    def mean(self):
        return self.loc

    @property
    def variance(self):
        return self.scale ** 2

    def sample(self, shape=(), key=None):
        shape = tuple(shape) + jnp.broadcast_shapes(self.loc.shape,
                                                    self.scale.shape)
        eps = jax.random.normal(_key(key), shape, self.loc.dtype
                                if self.loc.dtype != jnp.int32 else jnp.float32)
        return self.loc + self.scale * eps

    rsample = sample

    def log_prob(self, value):
        var = self.scale ** 2
        return (-((value - self.loc) ** 2) / (2 * var)
                - jnp.log(self.scale) - 0.5 * math.log(2 * math.pi))

    def entropy(self):
        return 0.5 + 0.5 * math.log(2 * math.pi) + jnp.log(self.scale)

    def cdf(self, value):
        return 0.5 * (1 + jax.scipy.special.erf(
            (value - self.loc) / (self.scale * math.sqrt(2))))


class LogNormal(Distribution):
    def __init__(self, loc, scale):
        self.base = Normal(loc, scale)

    @property
    def mean(self):
        return jnp.exp(self.base.loc + self.base.scale ** 2 / 2)

    def sample(self, shape=(), key=None):
        return jnp.exp(self.base.sample(shape, key=key))

    rsample = sample

    def log_prob(self, value):
        return self.base.log_prob(jnp.log(value)) - jnp.log(value)

    def entropy(self):
        return self.base.entropy() + self.base.loc


class Uniform(Distribution):
    def __init__(self, low, high):
        self.low = jnp.asarray(low, jnp.float32)
        self.high = jnp.asarray(high, jnp.float32)

    def sample(self, shape=(), key=None):
        shape = tuple(shape) + jnp.broadcast_shapes(self.low.shape,
                                                    self.high.shape)
        u = jax.random.uniform(_key(key), shape)
        return self.low + (self.high - self.low) * u

    rsample = sample

    def log_prob(self, value):
        inside = (value >= self.low) & (value < self.high)
        return jnp.where(inside, -jnp.log(self.high - self.low), -jnp.inf)

    def entropy(self):
        return jnp.log(self.high - self.low)


class Categorical(Distribution):
    def __init__(self, logits=None, probs=None):
        if (logits is None) == (probs is None):
            raise ValueError("pass exactly one of logits/probs")
        self.logits = (jnp.asarray(logits) if logits is not None
                       else jnp.log(jnp.asarray(probs)))

    @property
    def probs(self):
        return jax.nn.softmax(self.logits, axis=-1)

    def sample(self, shape=(), key=None):
        return jax.random.categorical(_key(key), self.logits,
                                      shape=tuple(shape) + self.logits.shape[:-1])

    def log_prob(self, value):
        logp = jax.nn.log_softmax(self.logits, axis=-1)
        return jnp.take_along_axis(
            logp, value[..., None].astype(jnp.int32), axis=-1)[..., 0]

    def entropy(self):
        logp = jax.nn.log_softmax(self.logits, axis=-1)
        return -jnp.sum(jnp.exp(logp) * logp, axis=-1)


class Bernoulli(Distribution):
    def __init__(self, probs):
        self.probs = jnp.asarray(probs)

    @property
    def mean(self):
        return self.probs

    def sample(self, shape=(), key=None):
        shape = tuple(shape) + self.probs.shape
        return jax.random.bernoulli(_key(key), self.probs, shape
                                    ).astype(jnp.float32)

    def log_prob(self, value):
        p = jnp.clip(self.probs, 1e-7, 1 - 1e-7)
        return value * jnp.log(p) + (1 - value) * jnp.log1p(-p)

    def entropy(self):
        p = jnp.clip(self.probs, 1e-7, 1 - 1e-7)
        return -(p * jnp.log(p) + (1 - p) * jnp.log1p(-p))


class Beta(Distribution):
    def __init__(self, alpha, beta):
        self.alpha = jnp.asarray(alpha, jnp.float32)
        self.beta = jnp.asarray(beta, jnp.float32)

    @property
    def mean(self):
        return self.alpha / (self.alpha + self.beta)

    def sample(self, shape=(), key=None):
        shape = tuple(shape) + jnp.broadcast_shapes(self.alpha.shape,
                                                    self.beta.shape)
        return jax.random.beta(_key(key), self.alpha, self.beta, shape)

    rsample = sample

    def log_prob(self, value):
        return ((self.alpha - 1) * jnp.log(value)
                + (self.beta - 1) * jnp.log1p(-value)
                - jsp.betaln(self.alpha, self.beta))

    def entropy(self):
        a, b = self.alpha, self.beta
        return (jsp.betaln(a, b) - (a - 1) * jsp.digamma(a)
                - (b - 1) * jsp.digamma(b)
                + (a + b - 2) * jsp.digamma(a + b))


class Dirichlet(Distribution):
    def __init__(self, concentration):
        self.concentration = jnp.asarray(concentration, jnp.float32)

    @property
    def mean(self):
        c = self.concentration
        return c / jnp.sum(c, axis=-1, keepdims=True)

    def sample(self, shape=(), key=None):
        return jax.random.dirichlet(_key(key), self.concentration,
                                    tuple(shape) + self.concentration.shape[:-1])

    rsample = sample

    def log_prob(self, value):
        c = self.concentration
        norm = (jnp.sum(jsp.gammaln(c), axis=-1)
                - jsp.gammaln(jnp.sum(c, axis=-1)))
        return jnp.sum((c - 1) * jnp.log(value), axis=-1) - norm


class Gamma(Distribution):
    def __init__(self, concentration, rate):
        self.concentration = jnp.asarray(concentration, jnp.float32)
        self.rate = jnp.asarray(rate, jnp.float32)

    @property
    def mean(self):
        return self.concentration / self.rate

    def sample(self, shape=(), key=None):
        shape = tuple(shape) + jnp.broadcast_shapes(
            self.concentration.shape, self.rate.shape)
        return jax.random.gamma(_key(key), self.concentration, shape) / self.rate

    rsample = sample

    def log_prob(self, value):
        c, r = self.concentration, self.rate
        return (c * jnp.log(r) + (c - 1) * jnp.log(value) - r * value
                - jsp.gammaln(c))

    def entropy(self):
        c, r = self.concentration, self.rate
        return c - jnp.log(r) + jsp.gammaln(c) + (1 - c) * jsp.digamma(c)


class Exponential(Distribution):
    def __init__(self, rate):
        self.rate = jnp.asarray(rate, jnp.float32)

    @property
    def mean(self):
        return 1.0 / self.rate

    def sample(self, shape=(), key=None):
        shape = tuple(shape) + self.rate.shape
        return jax.random.exponential(_key(key), shape) / self.rate

    rsample = sample

    def log_prob(self, value):
        return jnp.log(self.rate) - self.rate * value

    def entropy(self):
        return 1.0 - jnp.log(self.rate)


class Laplace(Distribution):
    def __init__(self, loc, scale):
        self.loc = jnp.asarray(loc, jnp.float32)
        self.scale = jnp.asarray(scale, jnp.float32)

    @property
    def mean(self):
        return self.loc

    def sample(self, shape=(), key=None):
        shape = tuple(shape) + jnp.broadcast_shapes(self.loc.shape,
                                                    self.scale.shape)
        return self.loc + self.scale * jax.random.laplace(_key(key), shape)

    rsample = sample

    def log_prob(self, value):
        return (-jnp.abs(value - self.loc) / self.scale
                - jnp.log(2 * self.scale))

    def entropy(self):
        return 1.0 + jnp.log(2 * self.scale)


# --------------------------------------------------------------------- KL
_KL_REGISTRY = {}


def register_kl(p_cls, q_cls):
    def deco(fn):
        _KL_REGISTRY[(p_cls, q_cls)] = fn
        return fn
    return deco


def kl_divergence(p: Distribution, q: Distribution):
    fn = _KL_REGISTRY.get((type(p), type(q)))
    if fn is None:
        raise NotImplementedError(
            f"no KL registered for ({type(p).__name__}, {type(q).__name__})")
    return fn(p, q)


@register_kl(Normal, Normal)
def _kl_normal(p, q):
    var_ratio = (p.scale / q.scale) ** 2
    t1 = ((p.loc - q.loc) / q.scale) ** 2
    return 0.5 * (var_ratio + t1 - 1 - jnp.log(var_ratio))


@register_kl(Categorical, Categorical)
def _kl_categorical(p, q):
    logp = jax.nn.log_softmax(p.logits, axis=-1)
    logq = jax.nn.log_softmax(q.logits, axis=-1)
    return jnp.sum(jnp.exp(logp) * (logp - logq), axis=-1)


@register_kl(Bernoulli, Bernoulli)
def _kl_bernoulli(p, q):
    pp = jnp.clip(p.probs, 1e-7, 1 - 1e-7)
    qq = jnp.clip(q.probs, 1e-7, 1 - 1e-7)
    return (pp * (jnp.log(pp) - jnp.log(qq))
            + (1 - pp) * (jnp.log1p(-pp) - jnp.log1p(-qq)))


@register_kl(Uniform, Uniform)
def _kl_uniform(p, q):
    return jnp.log((q.high - q.low) / (p.high - p.low))
