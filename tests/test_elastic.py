"""Elastic preemption end-to-end (VERDICT r2 item 7; reference:
paddle.distributed.elastic). A real training subprocess is SIGKILLed
mid-run, restarted, and the loss trajectory must continue from the
latest complete checkpoint — plus the watchdog hang path: a stuck step
checkpoints and exits with the elastic code, and the supervisor's
relaunch finishes the run."""
import json
import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

TRAIN_SCRIPT = r"""
import os, sys
import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_compilation_cache_dir", os.environ["PT_CACHE"])
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.1)
import numpy as np
import jax.numpy as jnp
sys.path.insert(0, os.environ["PT_REPO"])
import paddle_tpu as pt
from paddle_tpu.models import LlamaForCausalLM, llama_tiny
from paddle_tpu.trainer import Trainer, TrainingArguments

pt.seed(0)
model = LlamaForCausalLM(llama_tiny(
    vocab_size=64, hidden_size=32, intermediate_size=64,
    num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2))
data = np.random.RandomState(7).randint(0, 64, (8, 4, 16))  # fixed batches

class Loader:
    def __iter__(self):
        i = 0
        while True:
            if os.environ.get("PT_STEP_DELAY"):
                import time
                time.sleep(float(os.environ["PT_STEP_DELAY"]))
            if os.environ.get("PT_HANG_AT") and \
                    i == int(os.environ["PT_HANG_AT"]) and \
                    not os.path.exists(os.environ["PT_HANG_FLAG"]):
                open(os.environ["PT_HANG_FLAG"], "w").write("x")
                import time
                time.sleep(3600)  # simulated stuck step (preempted chip)
            yield jnp.asarray(data[i % 8])
            i += 1

args = TrainingArguments(
    output_dir=os.environ["PT_OUT"], max_steps=20, logging_steps=1,
    save_steps=5, donate_state=False,
    hang_timeout_s=float(os.environ.get("PT_HANG_TIMEOUT", 0)) or None)
tr = Trainer(model, pt.optimizer.AdamW(learning_rate=1e-3), args,
             train_dataloader=Loader())
tr.train()
print("FINAL", tr.global_step, flush=True)
"""


def _losses(out_dir):
    path = os.path.join(out_dir, "runs", "metrics.jsonl")
    if not os.path.exists(path):
        return {}
    out = {}
    with open(path) as f:
        for line in f:
            r = json.loads(line)
            if r["tag"] == "loss":
                out[r["step"]] = r["value"]
    return out


def _env(out, **extra):
    env = dict(os.environ)
    env.update(PT_REPO=os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), PT_OUT=str(out),
        # the suite-wide persistent cache (conftest): children of BOTH
        # tests then compile the identical train program exactly once
        PT_CACHE="/tmp/paddle_tpu_test_cache", JAX_PLATFORMS="cpu",
        **{k: str(v) for k, v in extra.items()})
    return env


def test_kill_mid_run_then_resume_continues_trajectory(tmp_path):
    out_killed = tmp_path / "killed"
    # reference: uninterrupted run (also warms the compile cache)
    out_ref = tmp_path / "ref"
    subprocess.run([sys.executable, "-c", TRAIN_SCRIPT],
                   env=_env(out_ref), check=True, timeout=90)
    ref_losses = _losses(out_ref)
    assert len(ref_losses) == 20

    # run 1: SIGKILL once it logs step >= 8 (so ckpt@5 is complete).
    # PT_STEP_DELAY keeps the run slow enough that (with the compile
    # cache warm from the reference run) it cannot race to step 20
    # before the kill lands — the resume assertions must not pass
    # vacuously against a completed run.
    proc = subprocess.Popen([sys.executable, "-c", TRAIN_SCRIPT],
                            env=_env(out_killed, PT_STEP_DELAY="0.25"))
    deadline = time.time() + 80
    try:
        while time.time() < deadline:
            if max(_losses(out_killed), default=0) >= 8:
                break
            time.sleep(0.3)
        else:
            pytest.fail("run never reached step 8")
    finally:
        os.kill(proc.pid, signal.SIGKILL)
        proc.wait()
    assert proc.returncode == -signal.SIGKILL
    killed_at = max(_losses(out_killed), default=0)
    assert killed_at < 20, "run finished before the kill; nothing resumed"

    # run 2: restart; must RESUME (first logged step > 10), not restart
    before = set(_losses(out_killed))
    subprocess.run([sys.executable, "-c", TRAIN_SCRIPT],
                   env=_env(out_killed), check=True, timeout=90)
    after = _losses(out_killed)
    resumed_steps = sorted(set(after) - before | {s for s in after if s > 8})
    assert min(s for s in resumed_steps) > 5   # continued from ckpt@5
    assert max(after) == 20

    # trajectory continuity: deterministic data + same seed -> the
    # resumed run's tail must match the uninterrupted reference closely
    assert abs(after[20] - ref_losses[20]) < 1e-3, (after[20], ref_losses[20])


def test_hang_checkpoints_exits_and_supervisor_finishes(tmp_path):
    """Watchdog hang -> checkpoint + exit(hang_exit_code); elastic
    supervisor relaunches; second attempt completes with continuity."""
    from paddle_tpu.distributed.elastic import supervise
    out = tmp_path / "hang"
    flag = tmp_path / "hung_once"
    env = _env(out, PT_HANG_AT=12, PT_HANG_FLAG=str(flag),
               PT_HANG_TIMEOUT=3)

    t0 = time.time()
    import paddle_tpu.distributed.elastic as el
    # drive subprocesses with the test env (supervise passes env through
    # os.environ by default; use explicit Popen wrapper)
    attempts = []
    orig_run = el.subprocess.run

    def run_with_env(argv, timeout=None):
        attempts.append(1)
        return orig_run(argv, env=env, timeout=timeout)
    el.subprocess.run = run_with_env
    try:
        rc = supervise([sys.executable, "-c", TRAIN_SCRIPT],
                       max_restarts=2, backoff_s=0.1, timeout_s=100)
    finally:
        el.subprocess.run = orig_run
    assert rc == 0
    assert len(attempts) == 2          # hung once, finished on relaunch
    assert flag.exists()
    losses = _losses(out)
    assert max(losses) == 20
    # the hang fired at data batch 12 (>= step 12): a checkpoint at or
    # after step 12 must exist from the on-hang save
    ckpts = os.listdir(os.path.join(out, "checkpoints"))
    steps = [int(d) for d in ckpts if d.isdigit()]
    assert steps and max(steps) >= 12, ckpts
    assert time.time() - t0 < 110
