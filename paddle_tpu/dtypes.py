"""Dtype aliases mirroring paddle's dtype surface (reference: paddle/phi/common/data_type.h).

TPU-first defaults: bfloat16 is the preferred compute dtype, float32 the
accumulation/master dtype.
"""
import jax.numpy as jnp

float16 = jnp.float16
bfloat16 = jnp.bfloat16
float32 = jnp.float32
float64 = jnp.float64
int8 = jnp.int8
int16 = jnp.int16
int32 = jnp.int32
int64 = jnp.int64
uint8 = jnp.uint8
bool_ = jnp.bool_
complex64 = jnp.complex64

_DTYPE_ALIASES = {
    "float16": float16, "fp16": float16, "half": float16,
    "bfloat16": bfloat16, "bf16": bfloat16,
    "float32": float32, "fp32": float32, "float": float32,
    "float64": float64, "fp64": float64, "double": float64,
    "int8": int8, "int16": int16, "int32": int32, "int64": int64,
    "uint8": uint8, "bool": bool_, "complex64": complex64,
}


def to_dtype(dtype):
    """Normalize a paddle-style dtype spec (str or jnp dtype) to a jnp dtype."""
    if dtype is None:
        return None
    if isinstance(dtype, str):
        try:
            return _DTYPE_ALIASES[dtype]
        except KeyError:
            raise ValueError(f"unknown dtype {dtype!r}") from None
    return jnp.dtype(dtype)


def default_float():
    return float32
