"""Metrics (reference: python/paddle/metric/metrics.py — Metric base with
update/accumulate/reset, Accuracy, Precision, Recall, Auc).

TPU-native: update() takes device arrays and does one small reduction on
device; the running counters are plain Python floats on host (metrics are
epoch-scale state, not step-scale compute — keeping them out of jit avoids
recompiles)."""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np


class Metric:
    def reset(self):
        raise NotImplementedError

    def update(self, *args):
        raise NotImplementedError

    def accumulate(self):
        raise NotImplementedError

    def name(self):
        return type(self).__name__.lower()


class Accuracy(Metric):
    """Top-k accuracy (reference: metrics.Accuracy)."""

    def __init__(self, topk=(1,)):
        self.topk = (topk,) if isinstance(topk, int) else tuple(topk)
        self.reset()

    def reset(self):
        self._correct = np.zeros(len(self.topk))
        self._total = 0

    def compute(self, pred, label):
        """Returns per-sample correctness for each k (paddle's compute).
        Accepts class-index labels [n] / [n, 1] or one-hot [n, classes]."""
        maxk = max(self.topk)
        _, top = jax.lax.top_k(pred, maxk)
        label = label.reshape(label.shape[0], -1)
        if label.shape[1] > 1:                  # one-hot / soft labels
            label = jnp.argmax(label, axis=-1, keepdims=True)
        hits = top == label
        return jnp.stack([hits[..., :k].any(axis=-1) for k in self.topk],
                         axis=-1)

    def update(self, correct):
        c = np.asarray(correct)
        if c.ndim == 1:
            c = c[:, None]
        self._correct += c.sum(axis=0)
        self._total += c.shape[0]
        return self.accumulate()

    def accumulate(self):
        acc = self._correct / max(self._total, 1)
        return float(acc[0]) if len(self.topk) == 1 else [float(a) for a in acc]

    def name(self):
        return "acc"


class Precision(Metric):
    """Binary precision over thresholded predictions."""

    def __init__(self, threshold: float = 0.5):
        self.threshold = threshold
        self.reset()

    def reset(self):
        self.tp = self.fp = 0

    def update(self, preds, labels):
        p = np.asarray(preds).ravel() > self.threshold
        l = np.asarray(labels).ravel().astype(bool)
        self.tp += int((p & l).sum())
        self.fp += int((p & ~l).sum())

    def accumulate(self):
        denom = self.tp + self.fp
        return self.tp / denom if denom else 0.0


class Recall(Metric):
    def __init__(self, threshold: float = 0.5):
        self.threshold = threshold
        self.reset()

    def reset(self):
        self.tp = self.fn = 0

    def update(self, preds, labels):
        p = np.asarray(preds).ravel() > self.threshold
        l = np.asarray(labels).ravel().astype(bool)
        self.tp += int((p & l).sum())
        self.fn += int((~p & l).sum())

    def accumulate(self):
        denom = self.tp + self.fn
        return self.tp / denom if denom else 0.0


class Auc(Metric):
    """ROC-AUC via fixed-bin histogram accumulation (reference:
    metrics.Auc with num_thresholds buckets — streaming-friendly, so
    epoch-scale eval never stores raw scores)."""

    def __init__(self, num_thresholds: int = 4095):
        self.num_thresholds = num_thresholds
        self.reset()

    def reset(self):
        self._pos = np.zeros(self.num_thresholds + 1)
        self._neg = np.zeros(self.num_thresholds + 1)

    def update(self, preds, labels):
        scores = np.asarray(preds)
        if scores.ndim == 2 and scores.shape[1] == 2:
            scores = scores[:, 1]               # paddle passes [n, 2] probs
        scores = scores.ravel()
        labels_ = np.asarray(labels).ravel().astype(bool)
        idx = np.clip((scores * self.num_thresholds).astype(int), 0,
                      self.num_thresholds)
        np.add.at(self._pos, idx[labels_], 1)
        np.add.at(self._neg, idx[~labels_], 1)

    def accumulate(self):
        # integrate TPR over FPR from the histogram (trapezoid)
        pos = self._pos[::-1].cumsum()
        neg = self._neg[::-1].cumsum()
        tot_pos, tot_neg = pos[-1], neg[-1]
        if tot_pos == 0 or tot_neg == 0:
            return 0.0
        tpr = np.concatenate([[0.0], pos / tot_pos])
        fpr = np.concatenate([[0.0], neg / tot_neg])
        return float(np.trapezoid(tpr, fpr))
