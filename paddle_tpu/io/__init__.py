"""paddle_tpu.io (reference: python/paddle/io/__init__.py)."""
from .dataset import (ChainDataset, ConcatDataset, Dataset, IterableDataset,
                      Subset, TensorDataset, random_split)
from .dataloader import DataLoader, default_collate_fn
from .device_prefetch import DevicePrefetcher, default_device_put
from .worker import WorkerError, WorkerInfo, get_worker_info
from .sampler import (BatchSampler, DistributedBatchSampler, RandomSampler,
                      Sampler, SequenceSampler, SubsetRandomSampler,
                      WeightedRandomSampler)
