"""Weight initializers mirroring paddle.nn.initializer (reference:
python/paddle/nn/initializer/*.py). Each initializer is a callable
`init(key, shape, dtype) -> Array`, matching jax convention so they can be
used inside jitted init functions too.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from ..dtypes import to_dtype


def _fans(shape):
    if len(shape) == 0:
        return 1, 1
    if len(shape) == 1:
        return shape[0], shape[0]
    if len(shape) == 2:
        return shape[0], shape[1]
    # conv kernels (paddle NCHW layout: [out_c, in_c, *spatial])
    receptive = math.prod(shape[2:])
    return shape[1] * receptive, shape[0] * receptive


class Initializer:
    def __call__(self, key, shape, dtype="float32"):
        raise NotImplementedError


class Constant(Initializer):
    def __init__(self, value=0.0):
        self.value = value

    def __call__(self, key, shape, dtype="float32"):
        return jnp.full(shape, self.value, dtype=to_dtype(dtype))


class Normal(Initializer):
    def __init__(self, mean=0.0, std=1.0):
        self.mean, self.std = mean, std

    def __call__(self, key, shape, dtype="float32"):
        dt = to_dtype(dtype)
        return (self.mean + self.std * jax.random.normal(key, shape)).astype(dt)


class TruncatedNormal(Initializer):
    def __init__(self, mean=0.0, std=1.0, a=-2.0, b=2.0):
        self.mean, self.std, self.a, self.b = mean, std, a, b

    def __call__(self, key, shape, dtype="float32"):
        dt = to_dtype(dtype)
        x = jax.random.truncated_normal(key, self.a, self.b, shape)
        return (self.mean + self.std * x).astype(dt)


class Uniform(Initializer):
    def __init__(self, low=-1.0, high=1.0):
        self.low, self.high = low, high

    def __call__(self, key, shape, dtype="float32"):
        dt = to_dtype(dtype)
        return jax.random.uniform(key, shape, minval=self.low, maxval=self.high).astype(dt)


class XavierNormal(Initializer):
    def __init__(self, gain=1.0):
        self.gain = gain

    def __call__(self, key, shape, dtype="float32"):
        fan_in, fan_out = _fans(shape)
        std = self.gain * math.sqrt(2.0 / (fan_in + fan_out))
        return (std * jax.random.normal(key, shape)).astype(to_dtype(dtype))


class XavierUniform(Initializer):
    def __init__(self, gain=1.0):
        self.gain = gain

    def __call__(self, key, shape, dtype="float32"):
        fan_in, fan_out = _fans(shape)
        limit = self.gain * math.sqrt(6.0 / (fan_in + fan_out))
        return jax.random.uniform(key, shape, minval=-limit, maxval=limit).astype(to_dtype(dtype))


class KaimingNormal(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="relu"):
        self.fan_in, self.slope, self.nonlinearity = fan_in, negative_slope, nonlinearity

    def __call__(self, key, shape, dtype="float32"):
        fan_in = self.fan_in or _fans(shape)[0]
        gain = math.sqrt(2.0 / (1 + self.slope ** 2)) if self.nonlinearity == "leaky_relu" else math.sqrt(2.0)
        std = gain / math.sqrt(fan_in)
        return (std * jax.random.normal(key, shape)).astype(to_dtype(dtype))


class KaimingUniform(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="relu"):
        self.fan_in, self.slope, self.nonlinearity = fan_in, negative_slope, nonlinearity

    def __call__(self, key, shape, dtype="float32"):
        fan_in = self.fan_in or _fans(shape)[0]
        gain = math.sqrt(2.0 / (1 + self.slope ** 2)) if self.nonlinearity == "leaky_relu" else math.sqrt(2.0)
        limit = gain * math.sqrt(3.0 / fan_in)
        return jax.random.uniform(key, shape, minval=-limit, maxval=limit).astype(to_dtype(dtype))


class Orthogonal(Initializer):
    def __init__(self, gain=1.0):
        self.gain = gain

    def __call__(self, key, shape, dtype="float32"):
        return (self.gain * jax.nn.initializers.orthogonal()(key, shape)).astype(to_dtype(dtype))


class Assign(Initializer):
    def __init__(self, value):
        self.value = value

    def __call__(self, key, shape, dtype="float32"):
        arr = jnp.asarray(self.value, dtype=to_dtype(dtype))
        assert tuple(arr.shape) == tuple(shape), (arr.shape, shape)
        return arr


# paddle-style short aliases
constant = Constant
normal = Normal
uniform = Uniform
