#!/usr/bin/env python
"""Fleet-scale chaos simulator CLI (ISSUE 16) — rehearse the
1000-replica incidents without 1000 processes.

Thin driver over :mod:`paddle_tpu.serving.fleet.sim`: instantiates the
REAL control plane (FleetFrontend + PrefixAffinityRouter +
FleetAutoscaler + BurnRateEngine + CircuitBreaker — ``run()`` asserts
their identity) against in-process SimReplica stubs on a simulated
clock, replays seeded chaos schedules (``--scenario``) or recorded
traces (``--replay-series`` / ``--replay-reqtrace``), and scores the
alerting plane against the injected ground truth.

Outputs:

- one ``SIM_JSON {...}`` line per run (full ``FleetSim.result()``);
- the ``FLEET_SIM_r16.json`` rung next to ``bench.py`` (decisions/s,
  aggregate alert precision/recall over the chaos scenarios, scale
  events, HA stream accounting) — auto-ingested by bench.py with the
  same device+freshness gate as the loadgen rungs;
- with ``--dump-dir``: a ``series/1`` telemetry doc + flight-recorder
  doc per run, rendered by ``tools/fleet_dash.py`` on the same
  timeline axis as live runs.

``--check`` runs a small pinned matrix (clean twin must stay silent,
outage + storm must each page exactly once, the frontend-kill drill
must lose zero committed tokens) and exits nonzero on any violation —
cheap enough for tier-1.
"""
import argparse
import json
import os
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

from paddle_tpu.serving.fleet.sim import (  # noqa: E402
    SCENARIOS, arrivals_from_reqtrace, arrivals_from_series,
    build_scenario)
from paddle_tpu.utils import faults  # noqa: E402

OUT_RUNG = os.path.join(ROOT, "FLEET_SIM_r16.json")


def _device_kind() -> str:
    """Same provenance field as the loadgen rungs so bench.py's
    same-device promote gate treats sim numbers consistently; the sim
    itself never touches an accelerator."""
    try:
        import jax
        return jax.devices()[0].device_kind
    except Exception:
        return "cpu"


def _run_one(name, ns, seed, arrivals=None):
    """One scenario run: fresh fault plan, build, run, optional dumps.
    Returns the full result dict (plus scenario/seed tags)."""
    faults.reset()
    overrides = {}
    for flag, key in (("slots", "slots"), ("service_s", "service_s"),
                      ("tokens", "tokens_per_request"),
                      ("probe_interval_s", "probe_interval_s"),
                      ("gossip_interval_s", "gossip_interval_s")):
        v = getattr(ns, flag)
        if v is not None:
            overrides[key] = v
    if arrivals is not None:
        overrides["arrival_times"] = arrivals
    try:
        sim = build_scenario(name, n_replicas=ns.replicas,
                             n_frontends=ns.frontends,
                             duration_s=ns.duration, seed=seed,
                             base_rate=ns.rate, **overrides)
        res = sim.run()
        res["scenario"], res["seed"] = name, seed
        if ns.dump_dir:
            os.makedirs(ns.dump_dir, exist_ok=True)
            stem = os.path.join(ns.dump_dir, f"sim_{name}_s{seed}")
            res["dumps"] = {
                "series": sim.dump_series(stem + "_series.json"),
                "flight": sim.dump_flight(stem + "_flight.json"),
            }
        return res
    finally:
        faults.reset()


def _aggregate(results):
    """Micro-aggregate alert quality over every run that HAD injected
    incidents (the clean twin contributes its false-page count only)
    — one precision/recall pair for the rung, not a per-scenario
    forest bench.py would have to interpret."""
    fires = false = expected = detected = 0
    for r in results:
        a = r["alerts"]
        fires += a["page_fires"]
        false += a["false_pages"]
        expected += a["incidents_paged_expected"]
        detected += a["incidents_detected"]
    return {
        "page_fires": fires, "false_pages": false,
        "incidents_expected": expected, "incidents_detected": detected,
        "alert_precision": (fires - false) / fires if fires else 1.0,
        "alert_recall": detected / expected if expected else 1.0,
    }


def _write_rung(results, ns):
    import time
    agg = _aggregate(results)
    section = {
        # headline: routing throughput of the REAL ladder under sim
        # load — max over runs (the biggest fleet dominates)
        "sim_decisions_per_sec": max(r["decisions_per_sec"]
                                     for r in results),
        "sim_replicas": max(r["sim"]["replicas"] for r in results),
        "sim_frontends": max(r["sim"]["frontends"] for r in results),
        "sim_cpu_s": round(sum(r["cpu_s"] for r in results), 3),
        "scenarios": sorted({r["scenario"] for r in results}),
        "seeds": sorted({r["seed"] for r in results}),
        **agg,
        "scale_ups": sum(r.get("scale", {}).get("ups", 0)
                         for r in results),
        "scale_downs": sum(r.get("scale", {}).get("downs", 0)
                           for r in results),
        "scale_freezes": sum(r.get("scale", {}).get("freezes", 0)
                             for r in results),
    }
    ha_runs = [r for r in results if "ha" in r]
    if ha_runs:
        ha = {k: sum(r["ha"][k] for r in ha_runs)
              for k in ha_runs[0]["ha"]}
        section["ha"] = ha
    xfer_runs = [r for r in results if "xfer" in r]
    if xfer_runs:
        section["xfer"] = {k: sum(r["xfer"][k] for r in xfer_runs)
                           for k in xfer_runs[0]["xfer"]}
        mig = sum(r["xfer"]["recompute_tokens"] for r in xfer_runs
                  if r["scenario"] == "drain_migrate")
        ctl = sum(r["xfer"]["recompute_tokens"] for r in xfer_runs
                  if r["scenario"] == "drain_reprefill")
        if ctl:
            # the recompute-amplification bound (ISSUE 18): prefill
            # tokens the re-prefill control twin burned per token the
            # migrating drain burned (same seed, arrivals, wave times)
            section["xfer"]["recompute_amplification"] = round(
                ctl / max(mig, 1), 2)
    doc = {"started": time.strftime("%Y-%m-%d %H:%M:%S"),
           "device": _device_kind(), "argv": sys.argv[1:],
           "fleet_sim": section}
    tmp = ns.out + ".tmp"
    with open(tmp, "w") as f:
        json.dump(doc, f, indent=1)
    os.replace(tmp, ns.out)
    return doc


# ------------------------------------------------------------------ check
def check(ns) -> int:
    """Pinned self-test: tiny fleet, fixed seed, four scenarios, hard
    assertions on alert precision/recall and HA stream accounting.
    This is the tier-1 gate for the whole sim stack — it exercises
    probe scheduling, routing, breakers, burn-rate paging, the
    autoscaler freeze and the leaderless frontend failover in ~2s."""
    kw = dict(replicas=16, frontends=1, duration=80.0, rate=8.0,
              slots=None, service_s=None, tokens=None,
              probe_interval_s=None, gossip_interval_s=None,
              dump_dir=None)
    ns2 = argparse.Namespace(**kw)
    bad = []

    def expect(cond, what):
        if not cond:
            bad.append(what)

    r = _run_one("clean", ns2, 1)
    a = r["alerts"]
    expect(a["page_fires"] == 0, f"clean twin paged: {a}")
    expect(r["shed"] == 0, f"clean twin shed {r['shed']}")
    expect(r["completed"] == r["requests"],
           f"clean twin dropped requests: {r['completed']}"
           f"/{r['requests']}")

    r = _run_one("outage", ns2, 1)
    a = r["alerts"]
    expect(a["recall"] >= 1.0 and a["false_pages"] == 0,
           f"outage alert quality: {a}")
    expect(r["scale"]["freezes"] >= 1,
           f"mass outage did not freeze the autoscaler: {r['scale']}")

    r = _run_one("storm", ns2, 1)
    a = r["alerts"]
    expect(a["recall"] >= 1.0 and a["false_pages"] == 0,
           f"storm alert quality: {a}")
    expect(r["probe"]["timeouts"] > 0,
           "storm produced no probe-capacity overflow")

    r = _run_one("brownout_spill", ns2, 1)
    a = r["alerts"]
    expect(a["false_pages"] == 0,
           f"brownout_spill false-paged: {a}")
    expect(r["completed"] + r["shed"] == r["requests"],
           f"brownout_spill dropped requests: {r}")

    rm = _run_one("drain_migrate", ns2, 1)
    rc = _run_one("drain_reprefill", ns2, 1)
    xm, xc = rm["xfer"], rc["xfer"]
    expect(rm["alerts"]["page_fires"] == 0,
           f"planned drain paged: {rm['alerts']}")
    expect(xm["migrated_requests"] >= 1,
           f"drain wave cut no live requests over: {xm}")
    expect(xm["recompute_tokens"] == 0,
           f"migrating drain recomputed prefill: {xm}")
    expect(xc["recompute_tokens"] > 0,
           f"re-prefill control twin recomputed nothing: {xc}")
    expect(rm["requests"] == rc["requests"],
           f"drain twins diverged: {rm['requests']} != "
           f"{rc['requests']}")
    amp = xc["recompute_tokens"] / max(xm["recompute_tokens"], 1)
    expect(amp >= 10.0,
           f"recompute amplification {amp:.1f}x < 10x bound "
           f"(migrate={xm}, control={xc})")
    expect(rm["completed"] + rm["shed"] == rm["requests"],
           f"drain_migrate dropped requests: {rm['completed']}"
           f"/{rm['requests']} shed={rm['shed']}")

    ns2.frontends = 2
    r = _run_one("ha", ns2, 1)
    ha, a = r["ha"], r["alerts"]
    expect(a["false_pages"] == 0, f"ha drill paged: {a}")
    expect(ha["severed_streams"] >= 1,
           f"frontend kill severed no streams: {ha}")
    expect(ha["severed_streams"] == ha["resumed_streams"]
           + ha["synthesized_streams"],
           f"severed streams unaccounted for: {ha}")
    expect(ha["corrupted_streams"] == 0 and ha["tokens_lost"] == 0
           and ha["tokens_duplicated"] == 0,
           f"frontend kill corrupted streams: {ha}")

    if bad:
        for line in bad:
            print(f"FLEET_SIM CHECK FAIL: {line}", file=sys.stderr)
        return 1
    print("fleet_sim check ok: clean twin silent, outage+storm each "
          "paged with freeze, frontend kill lost zero committed "
          "tokens")
    return 0


# ------------------------------------------------------------------- main
def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--check", action="store_true",
                    help="pinned self-test matrix; nonzero exit on "
                         "any alert/HA violation")
    ap.add_argument("--scenario", action="append", default=None,
                    choices=SCENARIOS + ("all",),
                    help="repeatable; default: all seeded schedules")
    ap.add_argument("--replicas", type=int, default=100)
    ap.add_argument("--frontends", type=int, default=1,
                    help="HA: >=2 shares routing state via gossip; "
                         "the ha scenario forces 2")
    ap.add_argument("--duration", type=float, default=None,
                    help="simulated seconds (not wall time); default "
                         "300, or the replayed trace's span — chaos "
                         "windows are placed relative to this, so it "
                         "must cover the arrivals")
    ap.add_argument("--rate", type=float, default=20.0,
                    help="offered load, requests/s")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--seeds", type=int, default=1,
                    help="run seeds seed..seed+N-1")
    ap.add_argument("--slots", type=int, default=None)
    ap.add_argument("--service-s", type=float, default=None,
                    dest="service_s")
    ap.add_argument("--tokens", type=int, default=None,
                    help="tokens per request")
    ap.add_argument("--probe-interval-s", type=float, default=None,
                    dest="probe_interval_s")
    ap.add_argument("--gossip-interval-s", type=float, default=None,
                    dest="gossip_interval_s")
    ap.add_argument("--replay-series", default=None, metavar="PATH",
                    help="replay arrivals from a series_*.json doc "
                         "instead of the seeded open loop")
    ap.add_argument("--replay-reqtrace", default=None, metavar="PATH",
                    help="replay arrivals from a dumped reqtrace ring")
    ap.add_argument("--replay-scale", type=float, default=1.0,
                    help="rate multiplier applied to the replayed "
                         "trace")
    ap.add_argument("--replay-metric",
                    default="gateway_requests_total",
                    help="request counter to recover arrivals from "
                         "(a sim-produced series doc uses "
                         "fleet_requests_total)")
    ap.add_argument("--dump-dir", default=None,
                    help="write per-run series + flight docs here "
                         "(fleet_dash renders them)")
    ap.add_argument("--out", default=OUT_RUNG,
                    help="rung JSON path (bench.py ingests the "
                         "default)")
    ap.add_argument("--no-rung", action="store_true",
                    help="skip writing the rung file")
    ns = ap.parse_args(argv)

    if ns.check:
        return check(ns)

    arrivals = None
    if ns.replay_series and ns.replay_reqtrace:
        ap.error("--replay-series and --replay-reqtrace are "
                 "exclusive")
    if ns.replay_series:
        with open(ns.replay_series) as f:
            arrivals = arrivals_from_series(json.load(f),
                                            metric=ns.replay_metric,
                                            scale=ns.replay_scale)
    elif ns.replay_reqtrace:
        with open(ns.replay_reqtrace) as f:
            arrivals = arrivals_from_reqtrace(json.load(f),
                                              scale=ns.replay_scale)
    if ns.duration is None:
        # the replayed trace defines the timeline (chaos windows are
        # fractions of it); a hair past the last arrival so every
        # replayed request drains
        ns.duration = arrivals[-1] + 1.0 if arrivals is not None \
            else 300.0

    names = ns.scenario or ["all"]
    if "all" in names:
        names = list(SCENARIOS)
    results = []
    for name in names:
        for seed in range(ns.seed, ns.seed + max(ns.seeds, 1)):
            res = _run_one(name, ns, seed, arrivals=arrivals)
            results.append(res)
            a = res["alerts"]
            print(f"# {name} seed={seed}: "
                  f"decisions/s={res['decisions_per_sec']} "
                  f"completed={res['completed']}/{res['requests']} "
                  f"shed={res['shed']} pages={a['page_fires']} "
                  f"false={a['false_pages']} "
                  f"recall={a['recall']:.2f} cpu={res['cpu_s']}s",
                  file=sys.stderr)
            print("SIM_JSON " + json.dumps(res))
    if not ns.no_rung:
        doc = _write_rung(results, ns)
        print(f"# rung -> {ns.out}: "
              + json.dumps({k: doc["fleet_sim"][k] for k in
                            ("sim_decisions_per_sec",
                             "alert_precision", "alert_recall")}),
          file=sys.stderr)
    agg = _aggregate(results)
    return 0 if agg["false_pages"] == 0 \
        and agg["alert_recall"] >= 1.0 else 2


if __name__ == "__main__":
    sys.exit(main())
