"""Local replica-process manager (ISSUE 13): the process backend the
autoscaler and the fleet loadgen drive — spawn a gateway PROCESS
(:mod:`.replica_main`), wait for its readiness line, wrap it in a
:class:`~.remote.RemoteReplica` and join it to the frontend; drain one
back out under the gateway's existing SIGTERM semantics.

One machine, N processes is the honest local shape of the multi-host
fleet (each process owns its engines, its port and its prefix cache;
nothing is shared but HTTP) — pointing ``spawn_cmd`` at ssh/k8s is the
only change a real multi-host deployment needs, which is why the
manager speaks only argv + readiness line + SIGTERM.
"""
from __future__ import annotations

import os
import signal
import subprocess
import sys
import threading
import time
from typing import Any, Dict, List, Optional

from ...utils import observability as obs
from .remote import RemoteReplica

__all__ = ["LocalProcessManager"]

READY_PREFIX = "FLEET_REPLICA_READY"


class LocalProcessManager:
    """Spawn/drain gateway subprocesses for a
    :class:`~.frontend.FleetFrontend`.

    Implements the autoscaler's manager duck type (``replicas`` /
    ``pending`` / ``scale_up`` / ``scale_down``) plus the chaos hook
    ``kill`` (SIGKILL — the real process-death the remote failover
    path must survive)."""

    def __init__(self, frontend, *, model: str = "stub",
                 chunk_tokens: int = 8,
                 engines_per_replica: int = 1,
                 spawn_timeout_s: float = 120.0,
                 probe_interval_s: float = 0.1,
                 stale_after_s: float = 1.5,
                 extra_args: Optional[List[str]] = None,
                 env: Optional[Dict[str, str]] = None,
                 log_dir: Optional[str] = None):
        # ISSUE 16 frontend HA: ``frontend`` may be a LIST — every
        # frontend gets its OWN RemoteReplica adapter per spawned
        # process (own probe thread, own breaker, own staleness
        # clock), under the SAME peer name so gossiped sticky/digest
        # state resolves across siblings. The first frontend is the
        # primary: the autoscaler duck type reads its peer list.
        self.frontends = list(frontend) if isinstance(
            frontend, (list, tuple)) else [frontend]
        self.frontend = self.frontends[0]
        self.name = getattr(self.frontend, "name", "fleet")
        self.model = model
        self.chunk_tokens = int(chunk_tokens)
        self.engines_per_replica = int(engines_per_replica)
        self.spawn_timeout_s = float(spawn_timeout_s)
        self.probe_interval_s = float(probe_interval_s)
        self.stale_after_s = float(stale_after_s)
        self.extra_args = list(extra_args or ())
        self.env = dict(env or {})
        self.log_dir = log_dir
        self._counter = 0
        self._pending = 0
        self._lock = threading.Lock()
        self.procs: Dict[str, subprocess.Popen] = {}

    # ----------------------------------------------------- the duck type
    def replicas(self) -> List[RemoteReplica]:
        return list(self.frontend.peers)

    def pending(self) -> int:
        with self._lock:
            return self._pending

    def scale_up(self):
        """Asynchronous spawn (a cold start takes seconds; the
        autoscaler counts the pending spawn toward the target so it
        never double-fires)."""
        with self._lock:
            self._pending += 1
        threading.Thread(target=self._spawn_bg, daemon=True,
                         name=f"fleet-spawn-{self.name}").start()

    def _spawn_bg(self):
        try:
            self.spawn()
        except Exception as e:
            obs.record_event("fleet_spawn_failed", fleet=self.name,
                             err=repr(e))
        finally:
            with self._lock:
                self._pending -= 1

    def scale_down(self, migrate: bool = False):
        """Drain the least-loaded live peer: leave rotation first (no
        new traffic), then SIGTERM — ``run_until_shutdown`` finishes
        in-flight work and exits. A reaper escalates to SIGKILL only
        past the drain grace. ``migrate`` records the autoscaler's
        intent in the scale-down event; whether SIGTERM actually cuts
        live requests over is the replica's own ``--migrate`` flag
        (argv is the only channel the manager speaks, and migration
        semantics belong to the process being drained)."""
        peers = [p for p in self.frontend.peers if p.name in self.procs]
        if not peers:
            return
        peer = min(peers, key=lambda p: p.load())
        self._remove_everywhere(peer.name)
        proc = self.procs.pop(peer.name, None)
        obs.record_event("fleet_scale_down", fleet=self.name,
                         peer=peer.name, migrate=bool(migrate))
        if proc is not None:
            threading.Thread(target=self._reap, args=(proc,),
                             daemon=True).start()

    def _remove_everywhere(self, peer_name: str):
        """Drop the named peer's adapter from EVERY frontend (each
        holds its own object for the same process)."""
        for fe in self.frontends:
            for p in list(fe.peers):
                if p.name == peer_name:
                    fe.remove_peer(p)

    @staticmethod
    def _reap(proc: subprocess.Popen, grace_s: float = 30.0):
        try:
            proc.send_signal(signal.SIGTERM)
        except OSError:
            return
        try:
            proc.wait(grace_s)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.wait(5)

    # -------------------------------------------------------------- spawn
    def spawn(self) -> RemoteReplica:
        """Start one gateway process, wait for readiness, join it."""
        with self._lock:
            idx = self._counter
            self._counter += 1
        name = f"peer{idx}"
        cmd = [sys.executable, "-m",
               "paddle_tpu.serving.fleet.replica_main",
               "--port", "0", "--model", self.model,
               "--chunk-tokens", str(self.chunk_tokens),
               "--engines", str(self.engines_per_replica),
               "--name", f"{self.name}-{name}"] + self.extra_args
        env = {**os.environ, **self.env}
        # children share one persistent compile cache: a scale-up's
        # cold start deserializes executables instead of recompiling
        env.setdefault("PADDLE_TPU_COMPILE_CACHE_DIR",
                       "/tmp/paddle_tpu_fleet_cache")
        stderr = subprocess.DEVNULL
        if self.log_dir:
            os.makedirs(self.log_dir, exist_ok=True)
            stderr = open(os.path.join(
                self.log_dir, f"{name}.stderr.log"), "w")
        proc = subprocess.Popen(cmd, stdout=subprocess.PIPE,
                                stderr=stderr, text=True, env=env,
                                cwd=os.path.dirname(os.path.dirname(
                                    os.path.dirname(os.path.dirname(
                                        os.path.abspath(__file__))))))
        deadline = time.monotonic() + self.spawn_timeout_s
        port = None
        while time.monotonic() < deadline:
            line = proc.stdout.readline()
            if not line:
                break
            if line.startswith(READY_PREFIX):
                for part in line.split():
                    k, _, v = part.partition("=")
                    if k == "port":
                        port = int(v)
                break
        if port is None:
            proc.kill()
            raise RuntimeError(
                f"replica process never reported ready "
                f"(rc={proc.poll()})")
        # keep draining the child's stdout so its pipe never fills
        threading.Thread(target=self._drain_stdout, args=(proc,),
                         daemon=True).start()
        self.procs[name] = proc
        first = None
        for fe in self.frontends:
            peer = RemoteReplica(
                name, "127.0.0.1", port,
                probe_interval_s=self.probe_interval_s,
                stale_after_s=self.stale_after_s)
            peer.refresh()        # first snapshot before rotation
            fe.add_peer(peer)
            if first is None:
                first = peer
        obs.record_event("fleet_spawn", fleet=self.name, peer=name,
                         port=port)
        return first

    @staticmethod
    def _drain_stdout(proc: subprocess.Popen):
        try:
            for _ in proc.stdout:
                pass
        except Exception:
            pass

    # -------------------------------------------------------------- chaos
    def kill(self, peer_name: Optional[str] = None) -> Optional[str]:
        """SIGKILL one replica PROCESS (the chaos harness's mid-run
        kill): no drain, no cleanup — in-flight proxied streams fail
        over through the frontend, probes evict the corpse. Returns
        the killed peer's name."""
        names = [p.name for p in self.frontend.peers
                 if p.name in self.procs]
        if peer_name is None:
            if not names:
                return None
            peer_name = names[0]
        proc = self.procs.pop(peer_name, None)
        if proc is None:
            return None
        # the corpse leaves the MANAGER's books (later kills and
        # scale-downs must target live processes) but its peer adapter
        # stays in rotation: the fleet must DISCOVER the death through
        # failed probes and dropped streams — that's the chaos
        proc.kill()
        threading.Thread(target=proc.wait, daemon=True).start()
        obs.record_event("fleet_chaos_kill", fleet=self.name,
                         peer=peer_name)
        return peer_name

    def stop_all(self, grace_s: float = 10.0):
        for name, proc in list(self.procs.items()):
            try:
                proc.send_signal(signal.SIGTERM)
            except OSError:
                pass
        deadline = time.monotonic() + grace_s
        for proc in self.procs.values():
            try:
                proc.wait(max(deadline - time.monotonic(), 0.1))
            except subprocess.TimeoutExpired:
                proc.kill()
        self.procs.clear()
