"""Quantization-aware training stubs (reference: PaddleSlim QAT —
fake-quant observers inserted around matmuls, straight-through gradients).

TPU-native: fake_quant is a pure function with a straight-through
estimator, so it rides inside the normal jitted train step; no observer
state machinery — scale is computed from the current tensor (dynamic) the
way PaddleSlim's moving-average observers converge to.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..nn.layer import Layer
from ..nn import functional as F


def fake_quant(x, bits: int = 8, axis=None):
    """Simulated symmetric quantization with straight-through gradient."""
    qmax = 2.0 ** (bits - 1) - 1
    if axis is None:
        scale = jnp.max(jnp.abs(x)) / qmax
    else:
        scale = jnp.max(jnp.abs(x), axis=axis, keepdims=True) / qmax
    scale = jnp.where(scale == 0, 1.0, scale)
    q = jnp.clip(jnp.round(x / scale), -qmax, qmax) * scale
    return x + jax.lax.stop_gradient(q - x)   # STE


class FakeQuantLinear(Layer):
    """Linear with fake-quantized weights (+ optionally activations) for
    QAT fine-tuning; export via quant.quantize_blockwise afterwards."""

    def __init__(self, linear, bits: int = 8, quant_activations: bool = False):
        super().__init__()
        self.inner = linear
        self.bits = bits
        self.quant_activations = quant_activations

    def forward(self, x):
        if self.quant_activations:
            x = fake_quant(x, self.bits)
        w = fake_quant(self.inner.weight, self.bits, axis=0)
        return F.linear(x, w, getattr(self.inner, "bias", None))
