"""Multiprocess DataLoader workers (reference:
python/paddle/io/dataloader/dataloader_iter.py — the C++ BlockingQueue +
_worker_loop process pool; also worker.py's WorkerInfo).

TPU-native notes:
- Workers are SPAWNED, not forked: a forked child inherits an initialized
  XLA runtime and can deadlock in it. Spawn gives each worker a clean
  interpreter; the dataset/collate_fn travel by pickle.
- A worker that ends up importing jax (e.g. the dataset holds jax arrays)
  pins itself to the CPU backend *before* unpickling anything — data
  assembly is host-side work, and letting a worker touch the TPU backend
  would both fight the trainer for the chip and (over the axon tunnel)
  risk hanging in backend init.
- Each worker gets an ordered index stream (round-robin) and results are
  re-sequenced in the parent, so output order matches num_workers=0
  exactly regardless of per-worker timing.
"""
from __future__ import annotations

import multiprocessing as mp
import os
import queue as _queue
import threading
import traceback
from dataclasses import dataclass
from typing import Callable, Optional

__all__ = ["WorkerInfo", "get_worker_info", "WorkerPool", "WorkerError"]

_worker_info: Optional["WorkerInfo"] = None


@dataclass
class WorkerInfo:
    id: int
    num_workers: int
    seed: int
    dataset: object = None


def get_worker_info() -> Optional[WorkerInfo]:
    """Inside a worker process: this worker's (id, num_workers, seed,
    dataset); None in the main process. Mirrors paddle.io.get_worker_info
    — IterableDataset shards itself with this."""
    return _worker_info


class WorkerError(RuntimeError):
    """A dataset/collate exception inside a worker, with its traceback."""


def _worker_loop(dataset, index_q, result_q, collate_fn, init_fn,
                 worker_id: int, num_workers: int, seed: int):
    # Pin jax (if anything imports it) to CPU before the first unpickle.
    # Env var: free, takes effect iff the dataset later imports jax. The
    # config.update handles images whose sitecustomize both pre-imports
    # jax AND re-selects its platform over the env var — without paying
    # a jax import in workers that never need it.
    import sys
    os.environ["JAX_PLATFORMS"] = "cpu"
    if "jax" in sys.modules:
        try:
            sys.modules["jax"].config.update("jax_platforms", "cpu")
        except Exception:
            pass
    global _worker_info
    _worker_info = WorkerInfo(id=worker_id, num_workers=num_workers,
                              seed=seed + worker_id, dataset=dataset)
    import numpy as np
    np.random.seed((seed + worker_id) % (2 ** 31))
    try:
        if init_fn is not None:
            init_fn(worker_id)
    except BaseException as e:
        result_q.put((-1, None, (type(e).__name__, str(e),
                                 traceback.format_exc())))
        return
    from ..utils import faults
    while True:
        item = index_q.get()
        if item is None:
            break
        seq, indices = item
        # chaos: OOM-kill stand-in — die hard with this batch
        # outstanding, so the parent's dead-worker detection (not an
        # eternal queue.get) is what ends the epoch. Spawned workers
        # inherit os.environ, so the PADDLE_TPU_FAULTS arming channel
        # reaches them for free.
        if faults.inject("worker_crash", worker_id=worker_id, seq=seq):
            os._exit(1)
        try:
            batch = collate_fn([dataset[i] for i in indices])
            result_q.put((seq, batch, None))
        except BaseException as e:
            result_q.put((seq, None, (type(e).__name__, str(e),
                                      traceback.format_exc())))


class WorkerPool:
    """Spawned worker pool shared across epochs (persistent_workers) or
    torn down per-iterator. The parent pumps `prefetch_factor` batches per
    worker ahead of the consumer and re-orders results by sequence id."""

    def __init__(self, dataset, collate_fn: Callable, num_workers: int,
                 prefetch_factor: int = 2,
                 worker_init_fn: Optional[Callable] = None, seed: int = 0):
        ctx = mp.get_context("spawn")
        self.num_workers = num_workers
        self.prefetch = max(prefetch_factor, 1)
        self._index_queues = [ctx.Queue() for _ in range(num_workers)]
        self._result_q = ctx.Queue()
        self._seq = 0  # monotonic across epochs: no stale-result collisions
        self._epoch_running = False
        self._alive = True
        self._workers = []
        for wid in range(num_workers):
            p = ctx.Process(
                target=_worker_loop,
                args=(dataset, self._index_queues[wid], self._result_q,
                      collate_fn, worker_init_fn, wid, num_workers, seed),
                daemon=True)
            p.start()
            self._workers.append(p)

    # ------------------------------------------------------------- epoch run
    def run_epoch(self, batch_iter):
        """Yield collated batches for one pass over ``batch_iter`` (an
        iterator of index lists), in order."""
        assert self._alive, "pool already shut down"
        if self._epoch_running:
            # two live iterators would cross-consume one result queue and
            # deadlock; fail fast instead (matches the reference loader's
            # single-iterator contract for persistent workers)
            raise RuntimeError(
                "this DataLoader's persistent worker pool already has an "
                "active iterator; exhaust or close it first")
        self._epoch_running = True
        pending = {}          # seq -> batch
        epoch_start = self._seq
        next_out = epoch_start
        in_flight = 0
        exhausted = False

        def dispatch():
            nonlocal in_flight, exhausted
            while not exhausted and in_flight < self.num_workers * self.prefetch:
                try:
                    indices = next(batch_iter)
                except StopIteration:
                    exhausted = True
                    return
                wid = self._seq % self.num_workers
                self._index_queues[wid].put((self._seq, list(indices)))
                self._seq += 1
                in_flight += 1

        try:
            dispatch()
            while in_flight > 0:
                seq, batch, err = self._get_result()
                if seq != -1 and seq < epoch_start:
                    continue  # stale result from an aborted prior epoch
                if err is not None:
                    name, msg, tb = err
                    raise WorkerError(
                        f"DataLoader worker raised {name}: {msg}\n{tb}")
                pending[seq] = batch
                in_flight -= 1
                dispatch()
                while next_out in pending:
                    yield pending.pop(next_out)
                    next_out += 1
        except BaseException:
            # consumer broke / worker raised: the epoch's remaining results
            # are stale; drain them lazily on shutdown or next epoch
            self._drain_stale()
            raise
        finally:
            self._epoch_running = False
        assert not pending

    def _get_result(self):
        """Blocking result read that notices dead workers: a worker killed
        by the OOM killer — or crashed during spawn bootstrap because the
        user's __main__ lacks an ``if __name__ == '__main__'`` guard —
        must surface as an error, not an eternal queue.get()."""
        while True:
            try:
                return self._result_q.get(timeout=2.0)
            except _queue.Empty:
                for wid, p in enumerate(self._workers):
                    # ANY dead worker while results are outstanding is
                    # fatal — including exitcode 0 (e.g. a dataset that
                    # calls sys.exit()): its batches will never arrive.
                    if not p.is_alive():
                        raise WorkerError(
                            f"DataLoader worker {wid} died "
                            f"(exitcode {p.exitcode}). With spawned workers "
                            "the launching script must guard its entry "
                            "point with `if __name__ == '__main__':`")

    def _drain_stale(self):
        try:
            while True:
                self._result_q.get_nowait()
        except _queue.Empty:
            pass

    # -------------------------------------------------------------- shutdown
    def shutdown(self, timeout: float = 5.0):
        if not self._alive:
            return
        self._alive = False
        for q in self._index_queues:
            try:
                q.put(None)
            except Exception:
                pass
        deadline = timeout
        for p in self._workers:
            p.join(timeout=deadline)
            if p.is_alive():
                p.terminate()
        self._drain_stale()
        for q in self._index_queues + [self._result_q]:
            q.close()
            q.cancel_join_thread()

    def __del__(self):
        try:
            self.shutdown(timeout=0.5)
        except Exception:
            pass
