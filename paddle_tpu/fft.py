"""paddle.fft parity (reference: python/paddle/fft.py — PHI
fft_c2c/r2c/c2r kernels). Thin delegates to jnp.fft with paddle's
norm-mode names; complex transforms run where XLA's FFT lowering does.
"""
from __future__ import annotations

import jax.numpy as jnp

__all__ = [
    "fft", "ifft", "fft2", "ifft2", "fftn", "ifftn",
    "rfft", "irfft", "rfft2", "irfft2", "rfftn", "irfftn",
    "hfft", "ihfft",
    "fftfreq", "rfftfreq", "fftshift", "ifftshift",
]


def _norm(norm):
    return norm or "backward"


def fft(x, n=None, axis=-1, norm="backward"):
    return jnp.fft.fft(x, n=n, axis=axis, norm=_norm(norm))


def ifft(x, n=None, axis=-1, norm="backward"):
    return jnp.fft.ifft(x, n=n, axis=axis, norm=_norm(norm))


def fft2(x, s=None, axes=(-2, -1), norm="backward"):
    return jnp.fft.fft2(x, s=s, axes=axes, norm=_norm(norm))


def ifft2(x, s=None, axes=(-2, -1), norm="backward"):
    return jnp.fft.ifft2(x, s=s, axes=axes, norm=_norm(norm))


def fftn(x, s=None, axes=None, norm="backward"):
    return jnp.fft.fftn(x, s=s, axes=axes, norm=_norm(norm))


def ifftn(x, s=None, axes=None, norm="backward"):
    return jnp.fft.ifftn(x, s=s, axes=axes, norm=_norm(norm))


def rfft(x, n=None, axis=-1, norm="backward"):
    return jnp.fft.rfft(x, n=n, axis=axis, norm=_norm(norm))


def irfft(x, n=None, axis=-1, norm="backward"):
    return jnp.fft.irfft(x, n=n, axis=axis, norm=_norm(norm))


def rfft2(x, s=None, axes=(-2, -1), norm="backward"):
    return jnp.fft.rfft2(x, s=s, axes=axes, norm=_norm(norm))


def irfft2(x, s=None, axes=(-2, -1), norm="backward"):
    return jnp.fft.irfft2(x, s=s, axes=axes, norm=_norm(norm))


def rfftn(x, s=None, axes=None, norm="backward"):
    return jnp.fft.rfftn(x, s=s, axes=axes, norm=_norm(norm))


def irfftn(x, s=None, axes=None, norm="backward"):
    return jnp.fft.irfftn(x, s=s, axes=axes, norm=_norm(norm))


def hfft(x, n=None, axis=-1, norm="backward"):
    return jnp.fft.hfft(x, n=n, axis=axis, norm=_norm(norm))


def ihfft(x, n=None, axis=-1, norm="backward"):
    return jnp.fft.ihfft(x, n=n, axis=axis, norm=_norm(norm))


def fftfreq(n, d=1.0, dtype=None):
    out = jnp.fft.fftfreq(n, d=d)
    return out.astype(dtype) if dtype is not None else out


def rfftfreq(n, d=1.0, dtype=None):
    out = jnp.fft.rfftfreq(n, d=d)
    return out.astype(dtype) if dtype is not None else out


def fftshift(x, axes=None):
    return jnp.fft.fftshift(x, axes=axes)


def ifftshift(x, axes=None):
    return jnp.fft.ifftshift(x, axes=axes)
