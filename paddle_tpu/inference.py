"""Inference predictor (reference: paddle.inference.Predictor /
paddle/fluid/inference/api — config + predictor over an optimized program;
PaddleNLP's llm/predict/predictor.py for the LLM path).

TPU-native: the "optimized program" is a cached jax.jit of the model's
functional form with donated weights left on device; optional weight-only
quantization at load (C17). XLA compiles one engine per input shape, so
serving discipline is SHAPE discipline:

- batch-dim bucketing: requests pad up to a fixed bucket ladder, bounding
  the number of compiled engines at len(buckets) per rank profile (the
  reference's shape-bucketed engine cache); padding rows are cropped
  before returning, so results are exact.
- `BatchingPredictor` adds the server-side micro-batching policy: concurrent
  `submit()` calls coalesce (up to max_batch, bounded by max_delay_ms)
  into one engine call — the TPU sees few, large, fixed-shape batches.
"""
from __future__ import annotations

import itertools
import queue
import threading
import time
from concurrent.futures import Future
from typing import Any, Callable, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .utils import observability as obs
from .utils.faults import BackpressureError, RequestTimeoutError

DEFAULT_BUCKETS = (1, 2, 4, 8, 16, 32)

# per-process instance ids: every BatchingPredictor's counters live in
# the GLOBAL metrics registry under a unique engine label, so health()
# and a /metrics scrape read the same numbers
_batcher_ids = itertools.count()


class Config:
    """paddle.inference.Config parity surface (the knobs that matter on
    TPU: dtype, quantization, shape buckets)."""

    def __init__(self, model_path: Optional[str] = None):
        self.model_path = model_path
        self.dtype = None                         # None = keep model dtype
        self.quant_bits: Optional[int] = None     # 8 / 4 / None
        self.quant_skip = ["lm_head", "embed"]
        self.batch_buckets: Optional[Tuple[int, ...]] = DEFAULT_BUCKETS

    def enable_weight_only_quant(self, bits: int = 8):
        self.quant_bits = bits
        return self

    def set_dtype(self, dtype):
        self.dtype = dtype
        return self

    def set_batch_buckets(self, buckets: Optional[Sequence[int]]):
        """None disables bucketing (one engine per exact batch size)."""
        self.batch_buckets = tuple(sorted(buckets)) if buckets else None
        return self


class Predictor:
    """Wraps a Layer for serving: jitted engines cached per shape bucket,
    optional dtype cast + PTQ at load, state kept on device."""

    def __init__(self, model, config: Optional[Config] = None):
        self.config = config or Config()
        self.model = model
        if self.config.dtype is not None:
            model.to(dtype=self.config.dtype)
        if self.config.quant_bits:
            from .quant import quantize_model
            quantize_model(model, bits=self.config.quant_bits,
                           skip=self.config.quant_skip)
        model.eval()
        self.last_serve_stats = {}
        self._paged_engines = {}
        self._fn, self._params = model.functional()
        # weights live on device once; every run reuses them
        self._params = jax.device_put(self._params)
        self._engine = jax.jit(self._fn)

    def _bucket(self, b: int) -> int:
        buckets = self.config.batch_buckets
        if not buckets:
            return b
        for cap in buckets:
            if b <= cap:
                return cap
        return b  # beyond the ladder: exact-shape engine

    def run(self, *inputs):
        """Eager-looking predict: inputs are host arrays; returns device
        outputs (np.asarray them for host use). The batch dim pads up to
        the bucket (edge-replicated rows, cropped from every output), so
        a b=3 request reuses the b=4 engine instead of compiling."""
        args = tuple(jnp.asarray(x) for x in inputs)
        b = args[0].shape[0] if args[0].ndim else 1
        cap = self._bucket(b)
        if cap != b:
            # pad only the inputs that actually carry the batch dim —
            # scalars / shared side inputs pass through untouched
            args = tuple(
                jnp.concatenate(
                    [a, jnp.broadcast_to(a[-1:], (cap - b,) + a.shape[1:])])
                if a.ndim and a.shape[0] == b else a
                for a in args)
        out = self._engine(self._params, *args)
        if cap != b:
            out = jax.tree.map(
                lambda o: o[:b]
                if hasattr(o, "ndim") and o.ndim and o.shape[0] == cap
                else o, out)
        return out

    __call__ = run

    def generate(self, input_ids, **kwargs):
        """Autoregressive generation with the model's KV cache path."""
        return self.model.generate(jnp.asarray(input_ids), **kwargs)

    def serve_stream(self, requests, max_new_tokens: int = 64,
                     eos_token_id=None, sampling=None, **engine_kw):
        """Continuous-batching service for a mixed-length request
        stream (reference: PaddleNLP llm predictor's block-attention
        path): ``requests`` maps request_id -> input_ids. Admission is
        FIFO: a request enters the moment a slot AND its blocks free
        up, backfilling slots that finished mid-decode (a large
        request at the queue head can delay the ones behind it — size
        the pool for the large case). Greedy by default — exact per
        request vs ``generate``; ``sampling`` maps request_id -> dict
        of per-request overrides (temperature / top_k / top_p / seed /
        repetition_penalty / stop_sequences), and chosen-token logprobs
        land in ``self.last_logprobs``. Returns request_id ->
        generated ids.

        The engine (pools + compiled prefill/decode executables) is
        cached per ``engine_kw`` shape, so repeated calls pay no
        recompile and no pool re-allocation."""
        from .generation.paged import PagedEngine
        key = tuple(sorted(engine_kw.items()))
        eng = self._paged_engines.get(key)
        if eng is None:
            eng = PagedEngine(self.model, **engine_kw)
            self._paged_engines[key] = eng
        for rid, ids in requests.items():
            eng.submit(rid, ids, max_new_tokens=max_new_tokens,
                       eos_token_id=eos_token_id,
                       **((sampling or {}).get(rid, {})))
        out = eng.run()
        eng.results.clear()  # the caller owns them now
        self.last_logprobs = dict(eng.logprobs)
        eng.logprobs.clear()
        self.last_serve_stats = dict(eng.stats)
        return out

    @classmethod
    def from_checkpoint(cls, model_factory: Callable[[], Any], path: str,
                        config: Optional[Config] = None):
        """Build model, load weights (paddle_tpu.load), wrap."""
        from .checkpoint import load
        model = model_factory()
        model.set_state_dict(load(path))
        return cls(model, config)


class BatchingPredictor:
    """Server-side micro-batching over a Predictor (reference: the
    batching policy in PaddleNLP's serving predictor / fastdeploy).

    Concurrent `submit()` calls enqueue single requests; a collector
    thread coalesces up to ``max_batch`` of them (waiting at most
    ``max_delay_ms`` once one is pending), stacks them into one bucketed
    engine call, and resolves each request's Future with its own row.

    Overload protection (chaos hardening): ``max_queue`` bounds the
    admission queue — past capacity ``submit()`` raises
    BackpressureError IMMEDIATELY (shed at the door, don't buffer an
    unbounded backlog while the engine falls behind). Per-request
    ``timeout_s`` bounds the time a request may wait for dispatch; an
    expired request's Future fails with RequestTimeoutError instead of
    occupying a batch slot (the engine call itself is not interruptible
    — the deadline governs queueing, where overload actually bites).
    Futures support standard cancellation while queued. ``close()``
    drains gracefully by default; ``health()`` snapshots the counters a
    load balancer needs.

    Observability (ISSUE 5): the counters live in the global
    ``utils.observability`` MetricsRegistry under a unique
    ``engine=batcherN`` label — ``health()`` reads the SAME objects a
    Prometheus scrape exports, so the two can never disagree. A
    queue-wait histogram (``serving_queue_wait_ms``) tracks dispatch
    latency per admitted request.
    """

    _STAT_KEYS = ("submitted", "served", "rejected", "timeouts",
                  "cancelled", "errors", "batches")

    def __init__(self, model, config: Optional[Config] = None,
                 max_batch: int = 8, max_delay_ms: float = 2.0,
                 max_queue: Optional[int] = None,
                 default_timeout_s: Optional[float] = None):
        self.predictor = Predictor(model, config)
        self.max_batch = max_batch
        self.max_delay = max_delay_ms / 1e3
        self.max_queue = max_queue
        self.default_timeout_s = default_timeout_s
        self._q: "queue.Queue" = queue.Queue()
        self._closed = False
        self._aborting = False
        self._lock = threading.Lock()
        self._pending = 0
        labels = {"engine": f"batcher{next(_batcher_ids)}"}
        self._obs_labels = labels
        reg = obs.registry()
        self._stats = {k: reg.counter(f"serving_{k}_total", **labels)
                       for k in self._STAT_KEYS}
        self._g_queued = reg.gauge("serving_queue_depth", **labels)
        self._h_wait = reg.histogram("serving_queue_wait_ms", **labels)
        self._worker = threading.Thread(target=self._loop, daemon=True)
        self._worker.start()

    def submit(self, *inputs, timeout_s: Optional[float] = None) -> Future:
        """One request (no batch dim on the inputs) -> Future of its
        outputs (batch dim stripped). Raises BackpressureError when the
        admission queue is at ``max_queue``."""
        if self._closed:
            raise RuntimeError("BatchingPredictor is closed")
        timeout_s = timeout_s if timeout_s is not None \
            else self.default_timeout_s
        deadline = (time.monotonic() + timeout_s) \
            if timeout_s is not None else None
        # convert BEFORE claiming queue capacity: a bad input that
        # raises here must not leak a _pending slot forever
        req = tuple(np.asarray(x) for x in inputs)
        with self._lock:
            if self.max_queue is not None and \
                    self._pending >= self.max_queue:
                self._stats["rejected"].inc()
                obs.record_event("serve_reject",
                                 engine=self._obs_labels["engine"],
                                 pending=self._pending)
                raise BackpressureError(
                    f"admission queue at capacity ({self.max_queue} "
                    f"pending); shed load or retry with backoff")
            self._pending += 1
            self._g_queued.set(self._pending)
            self._stats["submitted"].inc()
        fut: Future = Future()
        self._q.put((req, fut, deadline, time.monotonic()))
        return fut

    def run(self, *inputs):
        return self.submit(*inputs).result()

    def health(self) -> dict:
        """Stats snapshot for load balancers / probes — read straight
        off the registry counters, so it matches a concurrent
        ``MetricsRegistry.snapshot()`` / Prometheus scrape exactly."""
        with self._lock:
            snap = {k: int(c.value) for k, c in self._stats.items()}
            snap["queued"] = self._pending
        snap.update(capacity=self.max_queue, max_batch=self.max_batch,
                    closed=self._closed,
                    worker_alive=self._worker.is_alive())
        return snap

    def _count(self, key: str):
        self._stats[key].inc()

    def _admit(self, item) -> bool:
        """Dequeue-side gate: False when the request must not enter a
        batch (cancelled, expired, or the predictor is aborting)."""
        _, fut, deadline, t_enq = item
        with self._lock:
            self._pending -= 1
            self._g_queued.set(self._pending)
        if self._aborting:
            fut.cancel()  # pending -> CancelledError for the caller
            self._count("cancelled")
            return False
        if not fut.set_running_or_notify_cancel():
            self._count("cancelled")
            return False
        if deadline is not None and time.monotonic() > deadline:
            fut.set_exception(RequestTimeoutError(
                "request expired while queued for dispatch"))
            self._count("timeouts")
            return False
        # observed only for ADMITTED requests: expired/cancelled items
        # would pollute the dispatch-latency histogram with the (often
        # maximal) wait of work that was never served
        self._h_wait.observe((time.monotonic() - t_enq) * 1e3)
        return True

    def _loop(self):
        while True:
            item = self._q.get()
            if item is None:
                return
            if not self._admit(item):
                continue
            batch = [item]
            deadline = time.monotonic() + self.max_delay
            while len(batch) < self.max_batch:
                left = deadline - time.monotonic()
                if left <= 0:
                    break
                try:
                    nxt = self._q.get(timeout=left)
                except queue.Empty:
                    break
                if nxt is None:
                    self._flush(batch)
                    return
                if self._admit(nxt):
                    batch.append(nxt)
            self._flush(batch)

    def _flush(self, batch):
        reqs = [r for r, _, _, _ in batch]
        futs = [f for _, f, _, _ in batch]
        self._count("batches")
        try:
            stacked = tuple(np.stack([r[i] for r in reqs])
                            for i in range(len(reqs[0])))
            out = self.predictor.run(*stacked)
            for i, fut in enumerate(futs):
                fut.set_result(jax.tree.map(
                    lambda o: o[i] if hasattr(o, "ndim") and o.ndim else o,
                    out))
                self._count("served")
        except BaseException as e:
            for fut in futs:
                if not fut.done():
                    fut.set_exception(e)
                    self._count("errors")

    def close(self, drain: bool = True, timeout: Optional[float] = None):
        """Stop accepting work. ``drain=True`` (default) serves every
        already-queued request before shutting the collector down —
        the join is unbounded unless ``timeout`` is given, because a
        bounded join would race the live worker for queued items and
        nondeterministically fail requests it promised to serve;
        ``drain=False`` fails queued requests immediately (emergency
        stop — in-flight engine calls still finish)."""
        self._closed = True
        if not drain:
            self._aborting = True  # _admit fails queued items fast
        self._q.put(None)
        self._worker.join(timeout=timeout)
        # a submit() racing past the _closed check may have enqueued
        # after the sentinel; its Future must fail, not hang forever
        while True:
            try:
                item = self._q.get_nowait()
            except queue.Empty:
                break
            if item is None:
                continue
            with self._lock:   # keep health()'s queued count honest
                self._pending -= 1
                self._g_queued.set(self._pending)
            self._stats["cancelled"].inc()
            if not item[1].done():
                item[1].set_exception(
                    RuntimeError("BatchingPredictor closed before the "
                                 "request was served"))


def create_predictor(config: Config, model=None):
    """paddle.inference.create_predictor parity."""
    if model is None:
        raise ValueError("paddle_tpu predictor needs the model object "
                         "(graph serialization comes via jit.to_static AOT)")
    return Predictor(model, config)
