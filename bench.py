#!/usr/bin/env python
"""Headline bench (SURVEY.md §6): Llama train-step tokens/sec/chip + MFU on
the local chip. Prints ONE JSON line; vs_baseline = achieved MFU / 0.40
(the reference's Llama-3 pretraining MFU target in BASELINE.json).

Environment-proof (VERDICT r1 weak#2): TPU backend init over the axon
tunnel can fail transiently with UNAVAILABLE; a failed init is sticky
within a jax process, so the retry re-execs the bench in a fresh child
process (3x, backoff) rather than retrying in-process."""
import functools
import json
import os
import subprocess
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, ".")
import paddle_tpu as pt  # noqa: E402
from paddle_tpu.models import LlamaForCausalLM, LlamaConfig, causal_lm_loss  # noqa: E402

# peak bf16 FLOP/s per chip by device kind
PEAK_FLOPS = {
    "TPU v5 lite": 197e12,   # v5e
    "TPU v5e": 197e12,
    "TPU v5p": 459e12,
    "TPU v4": 275e12,
    "TPU v6 lite": 918e12,   # trillium
}

BATCH, SEQ = 8, 2048


def bench_config() -> LlamaConfig:
    """~470M-param Llama shaped to saturate a single v5e (16G HBM) with
    remat; same code path as the 8B recipe."""
    return LlamaConfig(
        vocab_size=32768, hidden_size=2048, intermediate_size=5632,
        num_hidden_layers=8, num_attention_heads=16, num_key_value_heads=8,
        max_position_embeddings=SEQ, rope_theta=500000.0,
        recompute=True, dtype=jnp.bfloat16)


def main():
    # persistent compilation cache: the ~470M-model compile is the slow part
    # over the axon tunnel; cache it across bench attempts/processes.
    try:
        jax.config.update("jax_compilation_cache_dir", "/tmp/jax_bench_cache")
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception:
        pass
    dev = jax.devices()[0]
    peak = PEAK_FLOPS.get(dev.device_kind, 197e12)
    pt.seed(0)
    cfg = bench_config()
    model = LlamaForCausalLM(cfg)
    fn, params = model.functional()
    n_params = sum(int(np.prod(v.shape)) for v in params.values())

    opt = pt.optimizer.AdamW(learning_rate=1e-4, multi_precision=True,
                             grad_clip=pt.optimizer.ClipGradByGlobalNorm(1.0))
    state = opt.init(params)
    ids = jnp.asarray(np.random.randint(0, cfg.vocab_size, (BATCH, SEQ)))

    @functools.partial(jax.jit, donate_argnums=(0, 1))
    def train_step(params, state, step, ids):
        def loss_fn(p):
            return causal_lm_loss(fn(p, ids), ids)
        loss, grads = jax.value_and_grad(loss_fn)(params)
        params, state = opt.apply(params, grads, state, step)
        return params, state, loss

    # warmup/compile (float() forces a device->host transfer: on the axon
    # tunnel block_until_ready alone returns before execution completes)
    params, state, loss = train_step(params, state, jnp.int32(0), ids)
    float(loss)

    steps = 10
    t0 = time.perf_counter()
    for i in range(1, steps + 1):
        params, state, loss = train_step(params, state, jnp.int32(i), ids)
    float(loss)
    dt = (time.perf_counter() - t0) / steps

    tokens_per_sec = BATCH * SEQ / dt
    # Honest 6N (VERDICT r1 weak#3): the input-embedding forward is a
    # gather, not a matmul, so its params don't belong in 6N; lm_head does
    # (it IS a matmul). mfu_legacy keeps round 1's all-params formula once
    # for continuity.
    embed_params = cfg.vocab_size * cfg.hidden_size
    matmul_params = n_params - embed_params
    attn_flops = 6 * cfg.num_hidden_layers * SEQ * cfg.hidden_size
    flops_per_token = 6 * matmul_params + attn_flops
    mfu = flops_per_token * tokens_per_sec / peak
    mfu_legacy = (6 * n_params + attn_flops) * tokens_per_sec / peak
    print(json.dumps({
        "metric": "llama_train_tokens_per_sec_per_chip",
        "value": round(tokens_per_sec, 1),
        "unit": "tokens/s/chip",
        "vs_baseline": round(mfu / 0.40, 3),
        "mfu": round(mfu, 4),
        "mfu_legacy": round(mfu_legacy, 4),
        "params": n_params,
        "step_ms": round(dt * 1e3, 2),
        "device": dev.device_kind,
        "loss": round(float(loss), 4),
    }))


if __name__ == "__main__":
    if os.environ.get("_PADDLE_TPU_BENCH_CHILD") == "1":
        main()
        sys.exit(0)
    # parent: run the bench in a fresh process; retry transient backend
    # failures with backoff (child inherits stdout so the JSON line flows).
    # Each attempt is time-bounded: backend init over the axon tunnel can
    # HANG (observed r1/r2), not just fail, and a hung attempt must not eat
    # the driver's whole budget.
    rc = 1
    for attempt in range(3):
        transient = False
        try:
            proc = subprocess.run(
                [sys.executable, os.path.abspath(__file__)],
                env={**os.environ, "_PADDLE_TPU_BENCH_CHILD": "1"},
                stderr=subprocess.PIPE,
                timeout=float(os.environ.get("PADDLE_TPU_BENCH_TIMEOUT",
                                             420)))
            rc = proc.returncode
            err = proc.stderr.decode(errors="replace")
            sys.stderr.write(err)
            transient = any(sig in err for sig in
                            ("UNAVAILABLE", "DEADLINE_EXCEEDED", "RESOURCE_EXHAUSTED",
                             "failed to connect", "Socket closed"))
        except subprocess.TimeoutExpired as e:
            rc, transient = 124, True  # hung backend init
            if e.stderr:
                sys.stderr.write(e.stderr.decode(errors="replace"))
        if rc == 0:
            break
        print(f"bench attempt {attempt + 1} failed rc={rc}", file=sys.stderr)
        if not transient:
            break  # deterministic failure: retrying wastes driver budget
        if attempt < 2:
            wait = 15 * (attempt + 1)
            print(f"retrying in {wait}s", file=sys.stderr)
            time.sleep(wait)
    sys.exit(rc)
