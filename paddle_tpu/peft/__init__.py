"""Parameter-efficient fine-tuning (reference: PaddleNLP paddlenlp/peft)."""
from .lora import (LoRAConfig, LoRAModel, apply_lora, inject_lora,
                   lora_state_dict, mark_only_lora_as_trainable, merge_lora,
                   unmerge_lora)

__all__ = ["LoRAConfig", "LoRAModel", "apply_lora", "inject_lora",
           "lora_state_dict", "mark_only_lora_as_trainable", "merge_lora",
           "unmerge_lora"]
