"""Serving gateway subsystem (ISSUE 9): the async HTTP/SSE front door
over :class:`~paddle_tpu.generation.paged.PagedEngine` — SLO-aware
continuous-batching admission (:mod:`.scheduler`), prefix-cache-aware
multi-replica routing (:mod:`.router`), and the stdlib-only gateway
server with graceful SIGTERM drain (:mod:`.gateway`).

The multi-host fleet layer (ISSUE 13) lives in :mod:`.fleet`: remote
replica adapters over peer-gateway HTTP probes, the byte-for-byte
proxying frontend with cross-process failover, prefix-digest gossip
and the closed-loop autoscaler. Import it explicitly
(``from paddle_tpu.serving.fleet import FleetFrontend, ...``) — the
gateway itself stays importable without the fleet machinery.

See ``docs/SERVING.md`` for the API schema, SLO classes, drain
semantics and the load-generator reading guide.
"""
from .gateway import Gateway
from .kvspill import KVSpillArena
from .reqtrace import RequestTrace, RequestTraceRing
from .router import EngineReplica, NoReplicaError, PrefixAffinityRouter
from .scheduler import (SLO_BATCH, SLO_INTERACTIVE, ServeRequest,
                        ShedError, SLOScheduler)
from .slo import BurnRateEngine, BurnRule
from .supervisor import CircuitBreaker, ReplicaSupervisor

__all__ = [
    "Gateway", "KVSpillArena",
    "BurnRateEngine", "BurnRule",
    "CircuitBreaker", "ReplicaSupervisor",
    "EngineReplica", "NoReplicaError", "PrefixAffinityRouter",
    "RequestTrace", "RequestTraceRing",
    "SLO_BATCH", "SLO_INTERACTIVE", "ServeRequest", "ShedError",
    "SLOScheduler",
]
