"""Pretrained-weight interop: HF/safetensors checkpoints -> paddle_tpu.

Reference: PaddleNLP ``paddlenlp/transformers/auto/modeling.py`` (AutoModel
dispatch by config) and the per-model ``modeling.py`` converters
(``convert_hf_state_dict`` name maps, e.g. llama/modeling.py).

TPU-native design notes:
- Our Linear weights are ``[in, out]`` (jax matmul convention; activations
  are row-major [b, s, in] @ [in, out] feeds the MXU without a transpose).
  HF torch stores ``[out, in]`` — every 2-D linear weight is transposed
  once on load, on host, before device placement.
- Weights are placed as a whole ``state_dict`` via ``Layer.set_state_dict``;
  under a mesh, GSPMD resharding happens at first use — no per-rank
  slicing code (the reference slices tensors per-mp-rank by hand in
  ``convert_tensor_parallel``).
- Index-sharded Llama-family checkpoints are converted and placed one
  shard at a time (``iter_hf_checkpoint_shards``) so host peak memory is
  one shard, not the whole model.
"""
from __future__ import annotations

import json
import os
import re
import warnings
from typing import Any, Callable, Dict, Iterator, Optional

import numpy as np

__all__ = [
    "load_hf_checkpoint", "iter_hf_checkpoint_shards",
    "convert_hf_state_dict", "to_hf_state_dict",
    "from_pretrained", "config_from_hf",
]


# ----------------------------------------------------------- tensor loading

def _load_safetensors_file(path: str) -> Dict[str, np.ndarray]:
    from safetensors.numpy import load_file
    try:
        return load_file(path)
    except (TypeError, ValueError):
        # bf16 safetensors can't land in numpy directly on some versions;
        # go through torch (cpu) and cast to fp32.
        from safetensors.torch import load_file as tload
        return {k: v.float().numpy() for k, v in tload(path).items()}


def iter_hf_checkpoint_shards(model_dir: str) -> Iterator[Dict[str, np.ndarray]]:
    """Yield tensors shard-by-shard so the caller can convert + place each
    shard and let it go before the next loads (host peak = one shard)."""
    idx = os.path.join(model_dir, "model.safetensors.index.json")
    if os.path.exists(idx):
        with open(idx) as f:
            weight_map = json.load(f)["weight_map"]
        for shard in sorted(set(weight_map.values())):
            yield _load_safetensors_file(os.path.join(model_dir, shard))
        return
    for name in ("model.safetensors",
                 "diffusion_pytorch_model.safetensors"):  # diffusers
        single = os.path.join(model_dir, name)
        if os.path.exists(single):
            yield _load_safetensors_file(single)
            return
    binp = os.path.join(model_dir, "pytorch_model.bin")
    if os.path.exists(binp):
        import torch
        sd = torch.load(binp, map_location="cpu", weights_only=True)
        yield {k: v.float().numpy() for k, v in sd.items()}
        return
    raise FileNotFoundError(f"no safetensors/bin checkpoint in {model_dir}")


def load_hf_checkpoint(model_dir: str) -> Dict[str, np.ndarray]:
    """Read ALL tensors into one dict (convenience; for big sharded
    checkpoints prefer ``iter_hf_checkpoint_shards``)."""
    out: Dict[str, np.ndarray] = {}
    for shard in iter_hf_checkpoint_shards(model_dir):
        out.update(shard)
    return out


# ------------------------------------------------------------- name mapping

_LLAMA_LINEAR = re.compile(
    r"(self_attn\.(q|k|v|o)_proj|mlp\.(gate|up|down)_proj)\.weight$")


def _convert_llama(hf: Dict[str, np.ndarray], cfg) -> Dict[str, np.ndarray]:
    """Llama/Qwen2/ERNIE-4.5 family: names already match
    (model.layers.N.self_attn.q_proj...), only linear layout differs.
    Per-key, so it works one shard at a time."""
    out = {}
    for k, v in hf.items():
        if k.endswith("rotary_emb.inv_freq"):
            continue  # we compute RoPE inline (llama.py:rotary_cos_sin)
        if k == "lm_head.weight" or _LLAMA_LINEAR.search(k):
            v = v.T  # [out, in] -> [in, out]
        if k == "lm_head.weight" and getattr(cfg, "tie_word_embeddings", False):
            continue
        out[k] = v
    return out


def _revert_llama(sd: Dict[str, np.ndarray], cfg) -> Dict[str, np.ndarray]:
    out = {}
    for k, v in sd.items():
        if k == "lm_head.weight" or _LLAMA_LINEAR.search(k):
            v = v.T
        out[k] = np.asarray(v)
    return out


_MOE_EXPERT = re.compile(
    r"^(model\.layers\.\d+\.mlp)\.experts\.(\d+)\."
    r"(gate_proj|up_proj|down_proj)\.weight$")
_MOE_SHARED = re.compile(
    r"^(model\.layers\.\d+\.mlp)\.shared_experts?\."
    r"(gate_proj|up_proj|down_proj)\.weight$")


def _convert_qwen2_moe(hf: Dict[str, np.ndarray], cfg) -> Dict[str, np.ndarray]:
    """Qwen2-MoE / ERNIE-4.5-MoE family: Llama rules for attention/norms,
    plus per-layer stacking of the HF per-expert weights into our batched
    [E, ...] expert tensors (reference: PaddleNLP qwen2_moe/modeling.py).
    Needs the WHOLE checkpoint (experts may span shards), so from_pretrained
    routes these model types through the full-merge loader."""
    out = {}
    experts: Dict[str, Dict[int, np.ndarray]] = {}
    for k, v in hf.items():
        m = _MOE_EXPERT.match(k)
        if m:
            layer, eid, proj = m.group(1), int(m.group(2)), m.group(3)
            name = {"gate_proj": "w_gate", "up_proj": "w_up",
                    "down_proj": "w_down"}[proj]
            experts.setdefault(f"{layer}.{name}", {})[eid] = v.T
            continue
        m = _MOE_SHARED.match(k)
        if m:
            out[f"{m.group(1)}.shared_{m.group(2)}"] = v.T
            continue
        if k.endswith(".mlp.gate.weight"):            # router [E, h] -> [h, E]
            out[k[:-len(".weight")]] = v.T
            continue
        if k.endswith(".mlp.shared_expert_gate.weight"):  # [1, h] -> [h, 1]
            out[k[:-len(".weight")]] = v.T
            continue
        if k.endswith(".mlp.moe_statics.e_score_correction_bias") or \
                k.endswith(".mlp.gate.e_score_correction_bias"):
            # ERNIE-4.5 / DeepSeek-V3 aux-free routing correction == our
            # loss-free balancing buffer (selection-only bias)
            out[k.rsplit(".mlp.", 1)[0] + ".mlp.expert_bias"] = \
                v.reshape(-1)
            continue
        out.update(_convert_llama({k: v}, cfg))
    for name, by_id in experts.items():
        E = len(by_id)
        assert sorted(by_id) == list(range(E)), f"missing experts in {name}"
        out[name] = np.stack([by_id[e] for e in range(E)])
    return out


_DSV2_LINEAR = re.compile(
    r"self_attn\.(q_a_proj|q_b_proj|kv_a_proj_with_mqa|kv_b_proj)"
    r"\.weight$")


def _convert_deepseek_v2(hf: Dict[str, np.ndarray], cfg) -> Dict[str, np.ndarray]:
    """DeepSeek-V2: the Qwen2-MoE expert stacking plus the MLA projection
    transposes (q_a/q_b/kv_a/kv_b; q_proj/o_proj ride the Llama rule)."""
    pre = {}
    for k, v in hf.items():
        if _DSV2_LINEAR.search(k):
            pre[k] = v.T
        else:
            pre[k] = v
    out = _convert_qwen2_moe(pre, cfg)
    # the MLA weights were already transposed above; _convert_llama inside
    # only touches its own regex, so no double-transpose
    return out


def _src_prefix(hf: Dict[str, np.ndarray]) -> str:
    for p in ("bert.", "ernie."):
        if any(k.startswith(p) for k in hf):
            return p
    return ""


def _convert_bert_encoder(hf: Dict[str, np.ndarray], cfg,
                          dst_prefix: str) -> Dict[str, np.ndarray]:
    """HF BERT-family encoder -> our fused-qkv layout (models/bert.py):
    per layer, the three [h, h] q/k/v projections fuse into one [h, 3h]
    qkv_proj so the MXU sees one big matmul instead of three."""
    out: Dict[str, np.ndarray] = {}
    g = lambda k: hf[k]  # noqa: E731
    p = _src_prefix(hf)
    emb = f"{p}embeddings."
    dp = dst_prefix
    out[dp + "embeddings.word_embeddings.weight"] = g(emb + "word_embeddings.weight")
    out[dp + "embeddings.position_embeddings"] = g(emb + "position_embeddings.weight")
    out[dp + "embeddings.token_type_embeddings"] = g(emb + "token_type_embeddings.weight")
    out[dp + "embeddings.layer_norm.weight"] = g(emb + "LayerNorm.weight")
    out[dp + "embeddings.layer_norm.bias"] = g(emb + "LayerNorm.bias")
    for i in range(cfg.num_hidden_layers):
        src = f"{p}encoder.layer.{i}."
        dst = f"{dp}layers.{i}."
        qw, kw, vw = (g(src + f"attention.self.{n}.weight") for n in
                      ("query", "key", "value"))
        qb, kb, vb = (g(src + f"attention.self.{n}.bias") for n in
                      ("query", "key", "value"))
        out[dst + "attention.qkv_proj.weight"] = np.concatenate(
            [qw.T, kw.T, vw.T], axis=1)
        out[dst + "attention.qkv_proj.bias"] = np.concatenate([qb, kb, vb])
        out[dst + "attention.out_proj.weight"] = g(src + "attention.output.dense.weight").T
        out[dst + "attention.out_proj.bias"] = g(src + "attention.output.dense.bias")
        out[dst + "attn_norm.weight"] = g(src + "attention.output.LayerNorm.weight")
        out[dst + "attn_norm.bias"] = g(src + "attention.output.LayerNorm.bias")
        out[dst + "fc_in.weight"] = g(src + "intermediate.dense.weight").T
        out[dst + "fc_in.bias"] = g(src + "intermediate.dense.bias")
        out[dst + "fc_out.weight"] = g(src + "output.dense.weight").T
        out[dst + "fc_out.bias"] = g(src + "output.dense.bias")
        out[dst + "out_norm.weight"] = g(src + "output.LayerNorm.weight")
        out[dst + "out_norm.bias"] = g(src + "output.LayerNorm.bias")
    if p + "pooler.dense.weight" in hf:
        out[dp + "pooler.dense.weight"] = g(p + "pooler.dense.weight").T
        out[dp + "pooler.dense.bias"] = g(p + "pooler.dense.bias")
    return out


def _convert_mlm_head(hf: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
    """HF ``cls.predictions.*`` / ``cls.seq_relationship.*`` ->
    our TiedMLMHead / nsp head (models/bert.py TiedMLMHead; the decoder
    weight itself is tied to word embeddings on both sides, so only the
    transform + biases transfer)."""
    out: Dict[str, np.ndarray] = {}
    cp = "cls.predictions."
    if cp + "transform.dense.weight" in hf:
        out["mlm_head.transform.weight"] = hf[cp + "transform.dense.weight"].T
        out["mlm_head.transform.bias"] = hf[cp + "transform.dense.bias"]
        out["mlm_head.transform_norm.weight"] = hf[cp + "transform.LayerNorm.weight"]
        out["mlm_head.transform_norm.bias"] = hf[cp + "transform.LayerNorm.bias"]
        out["mlm_head.mlm_bias"] = hf[cp + "bias"]
    if "cls.seq_relationship.weight" in hf:
        out["nsp_head.weight"] = hf["cls.seq_relationship.weight"].T
        out["nsp_head.bias"] = hf["cls.seq_relationship.bias"]
    return out


def _convert_bert(hf: Dict[str, np.ndarray], cfg) -> Dict[str, np.ndarray]:
    out = _convert_bert_encoder(hf, cfg, "bert.")
    out.update(_convert_mlm_head(hf))
    return out


def _convert_ernie(hf: Dict[str, np.ndarray], cfg) -> Dict[str, np.ndarray]:
    """HF model_type 'ernie' (BERT-family encoder + task-type embeddings,
    transformers ErnieModel) -> our ErnieModel (models/ernie.py)."""
    out = _convert_bert_encoder(hf, cfg, "ernie.encoder.")
    p = _src_prefix(hf)
    tt = p + "embeddings.task_type_embeddings.weight"
    if tt in hf:
        out["ernie.task_type_embeddings"] = hf[tt]
    head = _convert_mlm_head(hf)
    head.pop("nsp_head.weight", None)  # ErnieForMaskedLM has no NSP head
    head.pop("nsp_head.bias", None)
    out.update(head)
    return out


# --------------------------------------------------- GPT-2 / ViT / CLIP

_GPT2_LAYER = {
    "ln_1": "ln_1", "ln_2": "ln_2",
    "attn.c_attn": "attn.qkv_proj", "attn.c_proj": "attn.out_proj",
    "mlp.c_fc": "mlp.fc_in", "mlp.c_proj": "mlp.fc_out",
}


def _convert_gpt2(hf: Dict[str, np.ndarray], cfg) -> Dict[str, np.ndarray]:
    """HF GPT2LMHeadModel -> our GPT (models/gpt.py). GPT-2's Conv1D
    already stores weights [in, out] (the jax matmul layout), so unlike
    every torch nn.Linear family NO transpose is needed; the fused c_attn
    q|k|v column order matches our qkv_proj reshape [3, nh, d]."""
    out = {}
    for k, v in hf.items():
        if k.endswith((".attn.bias", ".attn.masked_bias")):
            continue  # causal-mask buffers
        if k == "lm_head.weight":
            continue  # GPT-2 always ties; our tied path reuses embeddings
        if k.startswith("transformer."):
            k = k[len("transformer."):]
        if k == "wte.weight":
            out["model.embed_tokens.weight"] = v
        elif k == "wpe.weight":
            out["model.embed_positions"] = v
        elif k.startswith("ln_f."):
            out["model.ln_f." + k[len("ln_f."):]] = v
        else:
            m = re.match(r"h\.(\d+)\.(.+)\.(weight|bias)$", k)
            if m is None:
                raise KeyError(f"unmapped GPT-2 key {k!r}")
            n, sub, wb = m.groups()
            out[f"model.layers.{n}.{_GPT2_LAYER[sub]}.{wb}"] = v
    return out


def _fuse_qkv(hf: Dict[str, np.ndarray], q: str, k: str, v: str):
    """Three torch [out, in] projections -> one fused [in, 3*out] weight
    + [3*out] bias (our qkv reshape order is [3, heads, head_dim])."""
    w = np.concatenate([hf[q + ".weight"].T, hf[k + ".weight"].T,
                        hf[v + ".weight"].T], axis=1)
    b = np.concatenate([hf[q + ".bias"], hf[k + ".bias"], hf[v + ".bias"]])
    return w, b


def _convert_vit(hf: Dict[str, np.ndarray], cfg) -> Dict[str, np.ndarray]:
    """HF ViTModel / ViTForImageClassification -> our ViT
    (models/vit.py). Separate q/k/v fuse into our single qkv matmul;
    conv patch embedding stays OIHW (both torch layout)."""
    src = {k[4:] if k.startswith("vit.") else k: v for k, v in hf.items()}
    out = {}
    n_layers = cfg.num_hidden_layers
    out["vit.cls_token"] = src["embeddings.cls_token"]
    out["vit.pos_embed"] = src["embeddings.position_embeddings"]
    out["vit.patch_embed.proj.weight"] = \
        src["embeddings.patch_embeddings.projection.weight"]
    out["vit.patch_embed.proj.bias"] = \
        src["embeddings.patch_embeddings.projection.bias"]
    for i in range(n_layers):
        p = f"encoder.layer.{i}."
        o = f"vit.blocks.{i}."
        at = p + "attention.attention."
        w, b = _fuse_qkv(src, at + "query", at + "key", at + "value")
        out[o + "attn.qkv.weight"], out[o + "attn.qkv.bias"] = w, b
        out[o + "attn.proj.weight"] = \
            src[p + "attention.output.dense.weight"].T
        out[o + "attn.proj.bias"] = src[p + "attention.output.dense.bias"]
        out[o + "fc1.weight"] = src[p + "intermediate.dense.weight"].T
        out[o + "fc1.bias"] = src[p + "intermediate.dense.bias"]
        out[o + "fc2.weight"] = src[p + "output.dense.weight"].T
        out[o + "fc2.bias"] = src[p + "output.dense.bias"]
        for hf_ln, ours in (("layernorm_before", "norm1"),
                            ("layernorm_after", "norm2")):
            out[o + ours + ".weight"] = src[p + hf_ln + ".weight"]
            out[o + ours + ".bias"] = src[p + hf_ln + ".bias"]
    out["vit.norm.weight"] = src["layernorm.weight"]
    out["vit.norm.bias"] = src["layernorm.bias"]
    if "classifier.weight" in src:
        out["head.weight"] = src["classifier.weight"].T
        out["head.bias"] = src["classifier.bias"]
    return out


def _convert_clip_tower(src: Dict[str, np.ndarray], hp: str, op: str,
                        n_layers: int, out: Dict[str, np.ndarray]):
    """One CLIP transformer tower's blocks (text or vision share the
    encoder.layers layout)."""
    for i in range(n_layers):
        p = f"{hp}encoder.layers.{i}."
        o = f"{op}{i}."
        at = p + "self_attn."
        w, b = _fuse_qkv(src, at + "q_proj", at + "k_proj", at + "v_proj")
        out[o + "qkv.weight"], out[o + "qkv.bias"] = w, b
        out[o + "proj.weight"] = src[at + "out_proj.weight"].T
        out[o + "proj.bias"] = src[at + "out_proj.bias"]
        out[o + "fc1.weight"] = src[p + "mlp.fc1.weight"].T
        out[o + "fc1.bias"] = src[p + "mlp.fc1.bias"]
        out[o + "fc2.weight"] = src[p + "mlp.fc2.weight"].T
        out[o + "fc2.bias"] = src[p + "mlp.fc2.bias"]
        for hf_ln, ours in (("layer_norm1", "norm1"),
                            ("layer_norm2", "norm2")):
            out[o + ours + ".weight"] = src[p + hf_ln + ".weight"]
            out[o + ours + ".bias"] = src[p + hf_ln + ".bias"]


def _convert_clip(hf: Dict[str, np.ndarray], cfg) -> Dict[str, np.ndarray]:
    """HF CLIPModel -> our CLIP (models/clip.py): both towers' separate
    q/k/v fuse; the vision class embedding becomes the [1,1,h] cls
    token; HF's bias-free patch conv gets explicit zero bias (identical
    math); vision attn.qkv names differ from the text tower (ViT blocks
    nest attention under .attn)."""
    src = dict(hf)
    out = {}
    out["logit_scale"] = src["logit_scale"].reshape(())
    out["text_projection"] = src["text_projection.weight"].T
    out["visual_projection"] = src["visual_projection.weight"].T
    # text tower
    tp = "text_model."
    out["text_model.token_embedding.weight"] = \
        src[tp + "embeddings.token_embedding.weight"]
    out["text_model.position_embedding"] = \
        src[tp + "embeddings.position_embedding.weight"]
    _convert_clip_tower(src, tp, "text_model.blocks.",
                        cfg.text.num_hidden_layers, out)
    out["text_model.final_norm.weight"] = \
        src[tp + "final_layer_norm.weight"]
    out["text_model.final_norm.bias"] = src[tp + "final_layer_norm.bias"]
    # the text tower writes flat qkv/proj/fc names (CLIPTextBlock);
    # _convert_clip_tower emitted them correctly already
    # vision tower
    vp = "vision_model."
    h = cfg.vision.hidden_size
    out["vision_model.cls_token"] = \
        src[vp + "embeddings.class_embedding"].reshape(1, 1, h)
    out["vision_model.pos_embed"] = \
        src[vp + "embeddings.position_embedding.weight"][None]
    out["vision_model.patch_embed.proj.weight"] = \
        src[vp + "embeddings.patch_embedding.weight"]
    out["vision_model.patch_embed.proj.bias"] = np.zeros((h,), np.float32)
    out["vision_model.pre_norm.weight"] = src[vp + "pre_layrnorm.weight"]
    out["vision_model.pre_norm.bias"] = src[vp + "pre_layrnorm.bias"]
    vtmp: Dict[str, np.ndarray] = {}
    _convert_clip_tower(src, vp, "vision_model.blocks.",
                        cfg.vision.num_hidden_layers, vtmp)
    for k, v in vtmp.items():
        # ViT blocks nest attention params under .attn
        k = k.replace(".qkv.", ".attn.qkv.").replace(".proj.",
                                                     ".attn.proj.")
        out[k] = v
    out["vision_model.norm.weight"] = src[vp + "post_layernorm.weight"]
    out["vision_model.norm.bias"] = src[vp + "post_layernorm.bias"]
    return out




# ------------------------------------------- diffusers AutoencoderKL (VAE)

def _vae_name_map(cfg):
    """Deterministic (diffusers_name -> our_name) prefix pairs, built by
    replaying Encoder/Decoder's construction loops (models/vae.py). The
    diffusers layout nests resnets/downsamplers per block; ours is a
    flat Sequential index."""
    pairs = []
    n_blocks = len(cfg.channel_multipliers)

    def resnet(dst, src_p, in_ch, out_ch):
        for a, b in (("norm1", "norm1"), ("conv1", "conv1"),
                     ("norm2", "norm2"), ("conv2", "conv2")):
            pairs.append((f"{src_p}.{a}", f"{dst}.{b}"))
        if in_ch != out_ch:
            pairs.append((f"{src_p}.conv_shortcut", f"{dst}.short"))

    ch = cfg.base_channels
    # encoder
    k, in_ch = 0, ch
    for b, mult in enumerate(cfg.channel_multipliers):
        out_ch = ch * mult
        for r in range(cfg.layers_per_block):
            resnet(f"encoder.down.{k}",
                   f"encoder.down_blocks.{b}.resnets.{r}", in_ch, out_ch)
            in_ch = out_ch
            k += 1
        if b != n_blocks - 1:
            pairs.append((f"encoder.down_blocks.{b}.downsamplers.0.conv",
                          f"encoder.down.{k}.conv"))
            k += 1
    resnet("encoder.mid.0", "encoder.mid_block.resnets.0", in_ch, in_ch)
    pairs.append(("encoder.mid_block.attentions.0", "encoder.mid.1"))
    resnet("encoder.mid.2", "encoder.mid_block.resnets.1", in_ch, in_ch)
    pairs.append(("encoder.conv_norm_out", "encoder.norm_out"))
    for n in ("encoder.conv_in", "encoder.conv_out", "quant_conv",
              "post_quant_conv", "decoder.conv_in", "decoder.conv_out"):
        pairs.append((n, n))
    # decoder (diffusers up_blocks[0] = deepest, same order as our loop)
    in_ch = ch * cfg.channel_multipliers[-1]
    resnet("decoder.mid.0", "decoder.mid_block.resnets.0", in_ch, in_ch)
    pairs.append(("decoder.mid_block.attentions.0", "decoder.mid.1"))
    resnet("decoder.mid.2", "decoder.mid_block.resnets.1", in_ch, in_ch)
    k = 0
    for b, mult in enumerate(reversed(cfg.channel_multipliers)):
        out_ch = ch * mult
        for r in range(cfg.layers_per_block + 1):
            resnet(f"decoder.up.{k}",
                   f"decoder.up_blocks.{b}.resnets.{r}", in_ch, out_ch)
            in_ch = out_ch
            k += 1
        if b != n_blocks - 1:
            pairs.append((f"decoder.up_blocks.{b}.upsamplers.0.conv",
                          f"decoder.up.{k}.conv"))
            k += 1
    pairs.append(("decoder.conv_norm_out", "decoder.norm_out"))
    return pairs


def _vae_attn(hf, src_p, dst_p, out):
    """Diffusers spatial attention (group_norm + to_q/k/v + to_out.0;
    1x1-conv weights in old CompVis exports squeeze to linear) -> our
    fused AttnBlock (norm + qkv + proj)."""
    def lin(name):
        w = hf[f"{src_p}.{name}.weight"]
        if w.ndim == 4:                  # [c, c, 1, 1] conv form
            w = w[..., 0, 0]
        return w.T, hf[f"{src_p}.{name}.bias"]
    gname = ("group_norm" if f"{src_p}.group_norm.weight" in hf
             else "norm")
    out[f"{dst_p}.norm.weight"] = hf[f"{src_p}.{gname}.weight"]
    out[f"{dst_p}.norm.bias"] = hf[f"{src_p}.{gname}.bias"]
    ws, bs = zip(lin("to_q"), lin("to_k"), lin("to_v"))
    out[f"{dst_p}.qkv.weight"] = np.concatenate(ws, axis=1)
    out[f"{dst_p}.qkv.bias"] = np.concatenate(bs)
    pw, pb = lin("to_out.0")
    out[f"{dst_p}.proj.weight"] = pw
    out[f"{dst_p}.proj.bias"] = pb


def _convert_vae(hf: Dict[str, np.ndarray], cfg) -> Dict[str, np.ndarray]:
    """diffusers AutoencoderKL checkpoint -> our AutoencoderKL
    (models/vae.py). Convs stay OIHW; attention linears fuse. NOTE:
    verified by construction + round-trip (diffusers itself is not in
    this image for a numerics-parity test)."""
    out: Dict[str, np.ndarray] = {}
    for src_p, dst_p in _vae_name_map(cfg):
        if src_p.endswith("attentions.0"):
            _vae_attn(hf, src_p, dst_p, out)
            continue
        for suf in ("weight", "bias"):
            out[f"{dst_p}.{suf}"] = hf[f"{src_p}.{suf}"]
    return out


def _revert_vae(sd: Dict[str, np.ndarray], cfg) -> Dict[str, np.ndarray]:
    """Inverse of _convert_vae (to_hf export + the round-trip test)."""
    out: Dict[str, np.ndarray] = {}
    for src_p, dst_p in _vae_name_map(cfg):
        if src_p.endswith("attentions.0"):
            qkv = np.asarray(sd[f"{dst_p}.qkv.weight"])
            qb = np.asarray(sd[f"{dst_p}.qkv.bias"])
            c = qkv.shape[0]
            for i, n in enumerate(("to_q", "to_k", "to_v")):
                out[f"{src_p}.{n}.weight"] = qkv[:, i * c:(i + 1) * c].T
                out[f"{src_p}.{n}.bias"] = qb[i * c:(i + 1) * c]
            out[f"{src_p}.group_norm.weight"] = sd[f"{dst_p}.norm.weight"]
            out[f"{src_p}.group_norm.bias"] = sd[f"{dst_p}.norm.bias"]
            out[f"{src_p}.to_out.0.weight"] = \
                np.asarray(sd[f"{dst_p}.proj.weight"]).T
            out[f"{src_p}.to_out.0.bias"] = sd[f"{dst_p}.proj.bias"]
            continue
        for suf in ("weight", "bias"):
            out[f"{src_p}.{suf}"] = np.asarray(sd[f"{dst_p}.{suf}"])
    return out




# ------------------------------------- diffusers DiT / SD3 transformers

def _fuse_qkv_named(hf, src_p, names, dst_p, out):
    """torch to_q/to_k/to_v linears -> our fused qkv, written into
    ``out`` (thin naming wrapper over _fuse_qkv's transpose+concat)."""
    out[f"{dst_p}.weight"], out[f"{dst_p}.bias"] = _fuse_qkv(
        hf, *(f"{src_p}.{n}" for n in names))


def _split_qkv(sd, dst_p, src_p, names, out):
    w = np.asarray(sd[f"{dst_p}.weight"])
    b = np.asarray(sd[f"{dst_p}.bias"])
    h = w.shape[0]
    for i, n in enumerate(names):
        out[f"{src_p}.{n}.weight"] = w[:, i * h:(i + 1) * h].T
        out[f"{src_p}.{n}.bias"] = b[i * h:(i + 1) * h]


def _lin(hf, src, dst, out):
    out[f"{dst}.weight"] = np.asarray(hf[f"{src}.weight"]).T
    out[f"{dst}.bias"] = np.asarray(hf[f"{src}.bias"])


def _lin_rev(sd, dst, src, out):
    out[f"{src}.weight"] = np.asarray(sd[f"{dst}.weight"]).T
    out[f"{src}.bias"] = np.asarray(sd[f"{dst}.bias"])


def _convert_dit(hf: Dict[str, np.ndarray], cfg) -> Dict[str, np.ndarray]:
    """diffusers DiTTransformer2DModel -> our DiT (models/dit.py).

    The diffusers layout duplicates the timestep/label embedder inside
    EVERY block's AdaLayerNormZero (norm1.emb.*, identical weights); we
    read block 0's copy into the single shared embedder. The sin-cos
    pos table is a non-persistent buffer there, so we emit ours
    deterministically from the config. Verified by construction +
    round-trip (diffusers is not in this image — same protocol as
    _convert_vae)."""
    out: Dict[str, np.ndarray] = {}
    out["patch_embed.weight"] = hf["pos_embed.proj.weight"]
    out["patch_embed.bias"] = hf["pos_embed.proj.bias"]
    emb = "transformer_blocks.0.norm1.emb"
    _lin(hf, f"{emb}.timestep_embedder.linear_1", "t_embedder.fc1", out)
    _lin(hf, f"{emb}.timestep_embedder.linear_2", "t_embedder.fc2", out)
    out["y_embedder.table.weight"] = \
        hf[f"{emb}.class_embedder.embedding_table.weight"]
    for i in range(cfg.num_hidden_layers):
        s, d = f"transformer_blocks.{i}", f"blocks.{i}"
        _lin(hf, f"{s}.norm1.linear", f"{d}.ada", out)
        _fuse_qkv_named(hf, f"{s}.attn1", ("to_q", "to_k", "to_v"),
                  f"{d}.qkv", out)
        _lin(hf, f"{s}.attn1.to_out.0", f"{d}.proj", out)
        _lin(hf, f"{s}.ff.net.0.proj", f"{d}.fc1", out)
        _lin(hf, f"{s}.ff.net.2", f"{d}.fc2", out)
    _lin(hf, "proj_out_1", "final_ada", out)
    _lin(hf, "proj_out_2", "final_proj", out)
    from .dit import sincos_pos_embed_2d
    grid = cfg.input_size // cfg.patch_size
    out["pos_embed"] = np.asarray(
        sincos_pos_embed_2d(grid, cfg.hidden_size), np.float32)
    return out


def _revert_dit(sd: Dict[str, np.ndarray], cfg) -> Dict[str, np.ndarray]:
    """Inverse of _convert_dit: the shared embedder is written into every
    block's norm1.emb (the diffusers layout); pos_embed is dropped
    (non-persistent buffer there)."""
    out: Dict[str, np.ndarray] = {}
    out["pos_embed.proj.weight"] = np.asarray(sd["patch_embed.weight"])
    out["pos_embed.proj.bias"] = np.asarray(sd["patch_embed.bias"])
    for i in range(cfg.num_hidden_layers):
        s, d = f"transformer_blocks.{i}", f"blocks.{i}"
        emb = f"{s}.norm1.emb"
        _lin_rev(sd, "t_embedder.fc1", f"{emb}.timestep_embedder.linear_1",
                 out)
        _lin_rev(sd, "t_embedder.fc2", f"{emb}.timestep_embedder.linear_2",
                 out)
        out[f"{emb}.class_embedder.embedding_table.weight"] = \
            np.asarray(sd["y_embedder.table.weight"])
        _lin_rev(sd, f"{d}.ada", f"{s}.norm1.linear", out)
        _split_qkv(sd, f"{d}.qkv", f"{s}.attn1",
                   ("to_q", "to_k", "to_v"), out)
        _lin_rev(sd, f"{d}.proj", f"{s}.attn1.to_out.0", out)
        _lin_rev(sd, f"{d}.fc1", f"{s}.ff.net.0.proj", out)
        _lin_rev(sd, f"{d}.fc2", f"{s}.ff.net.2", out)
    _lin_rev(sd, "final_ada", "proj_out_1", out)
    _lin_rev(sd, "final_proj", "proj_out_2", out)
    return out


def _swap_halves(w_t: np.ndarray, b: np.ndarray):
    """AdaLayerNormContinuous emits (scale, shift); our final/context
    modulation splits (shift, scale). Swap the output halves — weights
    here are already in our [in, out] layout, so split axis=1."""
    h = w_t.shape[1] // 2
    return (np.concatenate([w_t[:, h:], w_t[:, :h]], axis=1),
            np.concatenate([b[h:], b[:h]]))


def _convert_sd3(hf: Dict[str, np.ndarray], cfg) -> Dict[str, np.ndarray]:
    """diffusers SD3Transformer2DModel -> our MMDiT (models/dit.py).

    Stream mapping: attn.to_q/k/v + to_out.0 + ff.* is the image
    stream; attn.add_*_proj + to_add_out + ff_context.* the text
    stream. Concat order inside joint attention differs (img-first
    there, txt-first here) but attention without positional terms is
    permutation-equivariant in key order, so no weight change is
    needed. AdaLayerNormContinuous (final norm_out + last block's
    norm1_context) chunks (scale, shift) — swapped into our
    shift-first layout. The persistent pos_embed table (max-size grid)
    is center-cropped to our static grid, exactly what the diffusers
    forward does per call. Verified by construction + round-trip."""
    out: Dict[str, np.ndarray] = {}
    out["patch_embed.weight"] = hf["pos_embed.proj.weight"]
    out["patch_embed.bias"] = hf["pos_embed.proj.bias"]
    table = np.asarray(hf["pos_embed.pos_embed"])  # [1, max*max, h]
    max_g = int(round(table.shape[1] ** 0.5))
    grid = cfg.input_size // cfg.patch_size
    if max_g < grid:
        raise ValueError(f"checkpoint pos_embed grid {max_g} smaller "
                         f"than model grid {grid}")
    top = (max_g - grid) // 2
    out["pos_embed"] = table.reshape(1, max_g, max_g, -1)[
        :, top:top + grid, top:top + grid].reshape(1, grid * grid, -1)
    _lin(hf, "time_text_embed.timestep_embedder.linear_1",
         "t_embedder.fc1", out)
    _lin(hf, "time_text_embed.timestep_embedder.linear_2",
         "t_embedder.fc2", out)
    _lin(hf, "time_text_embed.text_embedder.linear_1",
         "pooled_proj.0", out)
    _lin(hf, "time_text_embed.text_embedder.linear_2",
         "pooled_proj.2", out)
    _lin(hf, "context_embedder", "context_proj", out)
    last = cfg.num_hidden_layers - 1
    for i in range(cfg.num_hidden_layers):
        s, d = f"transformer_blocks.{i}", f"blocks.{i}"
        _lin(hf, f"{s}.norm1.linear", f"{d}.img.ada", out)
        _fuse_qkv_named(hf, f"{s}.attn", ("to_q", "to_k", "to_v"),
                  f"{d}.img.qkv", out)
        _lin(hf, f"{s}.attn.to_out.0", f"{d}.img.proj", out)
        _lin(hf, f"{s}.ff.net.0.proj", f"{d}.img.fc1", out)
        _lin(hf, f"{s}.ff.net.2", f"{d}.img.fc2", out)
        _lin(hf, f"{s}.norm1_context.linear", f"{d}.txt.ada", out)
        if i == last:  # AdaLayerNormContinuous: scale-first there
            out[f"{d}.txt.ada.weight"], out[f"{d}.txt.ada.bias"] = \
                _swap_halves(out[f"{d}.txt.ada.weight"],
                             out[f"{d}.txt.ada.bias"])
        _fuse_qkv_named(hf, f"{s}.attn",
                  ("add_q_proj", "add_k_proj", "add_v_proj"),
                  f"{d}.txt.qkv", out)
        if i != last:
            out[f"{d}.txt.proj.weight"] = \
                np.asarray(hf[f"{s}.attn.to_add_out.weight"]).T
            out[f"{d}.txt.proj.bias"] = hf[f"{s}.attn.to_add_out.bias"]
            _lin(hf, f"{s}.ff_context.net.0.proj", f"{d}.txt.fc1", out)
            _lin(hf, f"{s}.ff_context.net.2", f"{d}.txt.fc2", out)
    _lin(hf, "norm_out.linear", "final_ada", out)
    out["final_ada.weight"], out["final_ada.bias"] = \
        _swap_halves(out["final_ada.weight"], out["final_ada.bias"])
    _lin(hf, "proj_out", "final_proj", out)
    return out


def _revert_sd3(sd: Dict[str, np.ndarray], cfg) -> Dict[str, np.ndarray]:
    """Inverse of _convert_sd3 (export + round-trip test); the exported
    pos_embed table's max size equals our grid."""
    out: Dict[str, np.ndarray] = {}
    out["pos_embed.proj.weight"] = np.asarray(sd["patch_embed.weight"])
    out["pos_embed.proj.bias"] = np.asarray(sd["patch_embed.bias"])
    out["pos_embed.pos_embed"] = np.asarray(sd["pos_embed"])
    _lin_rev(sd, "t_embedder.fc1",
             "time_text_embed.timestep_embedder.linear_1", out)
    _lin_rev(sd, "t_embedder.fc2",
             "time_text_embed.timestep_embedder.linear_2", out)
    _lin_rev(sd, "pooled_proj.0",
             "time_text_embed.text_embedder.linear_1", out)
    _lin_rev(sd, "pooled_proj.2",
             "time_text_embed.text_embedder.linear_2", out)
    _lin_rev(sd, "context_proj", "context_embedder", out)
    last = cfg.num_hidden_layers - 1
    for i in range(cfg.num_hidden_layers):
        s, d = f"transformer_blocks.{i}", f"blocks.{i}"
        _lin_rev(sd, f"{d}.img.ada", f"{s}.norm1.linear", out)
        _split_qkv(sd, f"{d}.img.qkv", f"{s}.attn",
                   ("to_q", "to_k", "to_v"), out)
        _lin_rev(sd, f"{d}.img.proj", f"{s}.attn.to_out.0", out)
        _lin_rev(sd, f"{d}.img.fc1", f"{s}.ff.net.0.proj", out)
        _lin_rev(sd, f"{d}.img.fc2", f"{s}.ff.net.2", out)
        tw = np.asarray(sd[f"{d}.txt.ada.weight"])
        tb = np.asarray(sd[f"{d}.txt.ada.bias"])
        if i == last:
            tw, tb = _swap_halves(tw, tb)
        out[f"{s}.norm1_context.linear.weight"] = tw.T
        out[f"{s}.norm1_context.linear.bias"] = tb
        _split_qkv(sd, f"{d}.txt.qkv", f"{s}.attn",
                   ("add_q_proj", "add_k_proj", "add_v_proj"), out)
        if i != last:
            out[f"{s}.attn.to_add_out.weight"] = \
                np.asarray(sd[f"{d}.txt.proj.weight"]).T
            out[f"{s}.attn.to_add_out.bias"] = \
                np.asarray(sd[f"{d}.txt.proj.bias"])
            _lin_rev(sd, f"{d}.txt.fc1", f"{s}.ff_context.net.0.proj", out)
            _lin_rev(sd, f"{d}.txt.fc2", f"{s}.ff_context.net.2", out)
    w, b = _swap_halves(np.asarray(sd["final_ada.weight"]),
                        np.asarray(sd["final_ada.bias"]))
    out["norm_out.linear.weight"] = w.T
    out["norm_out.linear.bias"] = b
    _lin_rev(sd, "final_proj", "proj_out", out)
    return out


def _convert_resnet(hf: Dict[str, np.ndarray], cfg) -> Dict[str, np.ndarray]:
    """HF ResNetForImageClassification / ResNetModel (v1.5: stride on
    the 3x3 middle conv, first stage unstrided — exactly our "b"
    variant) -> our ResNet (models/resnet.py)."""
    p = "resnet." if any(k.startswith("resnet.") for k in hf) else ""
    out: Dict[str, np.ndarray] = {}

    def convbn(src, dst):
        out[dst + ".conv.weight"] = hf[src + ".convolution.weight"]
        for a, b in (("weight", "weight"), ("bias", "bias"),
                     ("running_mean", "_mean"),
                     ("running_var", "_variance")):
            out[f"{dst}.bn.{b}"] = hf[f"{src}.normalization.{a}"]

    convbn(p + "embedder.embedder", "stem")
    blocks, block_cls = cfg.block_plan()
    names = ("conv0", "conv1", "conv2")[:3 if block_cls.expansion == 4
                                        else 2]
    for s, nb in enumerate(blocks):
        for i in range(nb):
            base = f"{p}encoder.stages.{s}.layers.{i}"
            for j, nm in enumerate(names):
                convbn(f"{base}.layer.{j}", f"stages.{s}.{i}.{nm}")
            if f"{base}.shortcut.convolution.weight" in hf:
                convbn(f"{base}.shortcut", f"stages.{s}.{i}.short")
    if "classifier.1.weight" in hf:
        out["head.weight"] = hf["classifier.1.weight"].T
        out["head.bias"] = hf["classifier.1.bias"]
    return out


_CONVERTERS: Dict[str, Callable] = {
    "llama": _convert_llama,
    "qwen2": _convert_llama,   # Llama backbone + qkv bias (qwen2.py)
    "ernie4_5": _convert_llama,
    "qwen2_moe": _convert_qwen2_moe,
    "ernie4_5_moe": _convert_qwen2_moe,
    "deepseek_v2": _convert_deepseek_v2,
    "deepseek_v3": _convert_deepseek_v2,
    "bert": _convert_bert,
    "ernie": _convert_ernie,
    "gpt2": _convert_gpt2,
    "vit": _convert_vit,
    "clip": _convert_clip,
    "autoencoder_kl": _convert_vae,
    "dit": _convert_dit,
    "sd3_transformer": _convert_sd3,
    "resnet": _convert_resnet,
}

# missing keys under these prefixes are heads a bare encoder checkpoint
# legitimately lacks; they stay at init and we warn instead of raising.
_OPTIONAL_HEAD_PREFIXES = ("mlm_head.", "nsp_head.", "bert.pooler.",
                           "ernie.encoder.pooler.",
                           "ernie.task_type_embeddings",
                           "head.")  # bare ViTModel has no classifier


def convert_hf_state_dict(hf_sd: Dict[str, np.ndarray], cfg,
                          model_type: str) -> Dict[str, np.ndarray]:
    if model_type not in _CONVERTERS:
        raise ValueError(f"no converter for model_type={model_type!r}; "
                         f"have {sorted(_CONVERTERS)}")
    return _CONVERTERS[model_type](hf_sd, cfg)


def to_hf_state_dict(model) -> Dict[str, np.ndarray]:
    """Export back to HF layout (Llama-family only) — enables round-trip
    tests and serving our checkpoints from HF-based stacks."""
    sd = {k: np.asarray(v) for k, v in model.state_dict().items()}
    return _revert_llama(sd, model.config)


# ------------------------------------------------------------ construction

def _jax_dtype(hf: Dict[str, Any]):
    import jax.numpy as jnp
    # transformers >= 4.56 writes "dtype"; older wrote "torch_dtype"
    dt = hf.get("dtype", hf.get("torch_dtype"))
    if dt == "float32":
        return jnp.float32
    if dt == "float16":
        # fp16 has no TPU fast path; bf16 keeps the exponent range but
        # drops mantissa bits vs the checkpoint's training dtype
        warnings.warn("checkpoint dtype float16 mapped to bfloat16 "
                      "(TPU-native); pass dtype explicitly to override",
                      stacklevel=3)
    return jnp.bfloat16


def _rope_scaling_cfg(hf, mt):
    """Validate/normalize HF rope_scaling for the Llama-family loaders;
    the model side dispatches via llama.rope_params_from_scaling."""
    rs_cfg = hf.get("rope_scaling")
    if not rs_cfg:
        return None
    from .llama import ROPE_SCALING_TYPES
    rtype = rs_cfg.get("rope_type", rs_cfg.get("type"))
    if rtype not in ROPE_SCALING_TYPES:
        raise ValueError(f"rope_scaling type {rtype!r} not supported "
                         f"for {mt} ({'/'.join(ROPE_SCALING_TYPES)} are)")
    return None if rtype == "default" else rs_cfg


def config_from_hf(model_dir: str):
    """Map an HF ``config.json`` to our config dataclass + model class."""
    with open(os.path.join(model_dir, "config.json")) as f:
        hf = json.load(f)
    mt = hf.get("model_type", "")
    if not mt and hf.get("_class_name") == "AutoencoderKL":
        from .vae import AutoencoderKL, VAEConfig
        if hf.get("use_quant_conv") is False or \
                hf.get("use_post_quant_conv") is False or \
                hf.get("shift_factor"):
            raise ValueError(
                "AutoencoderKL variant without quant convs / with "
                "shift_factor (SD3/FLUX VAE) is not supported yet; "
                "the SD1/2-family layout is")
        bout = hf.get("block_out_channels", [128, 256, 512, 512])
        cfg = VAEConfig(
            in_channels=hf.get("in_channels", 3),
            latent_channels=hf.get("latent_channels", 4),
            base_channels=bout[0],
            channel_multipliers=[c // bout[0] for c in bout],
            layers_per_block=hf.get("layers_per_block", 2),
            norm_groups=hf.get("norm_num_groups", 32),
            scaling_factor=hf.get("scaling_factor", 0.18215),
        )
        return AutoencoderKL, cfg, "autoencoder_kl"
    if not mt and hf.get("_class_name") in ("DiTTransformer2DModel",
                                            "Transformer2DModel"):
        from .dit import DiT, DiTConfig
        if hf.get("norm_type", "ada_norm_zero") != "ada_norm_zero":
            raise ValueError("only adaLN-Zero DiT transformers are "
                             "supported")
        nheads = hf.get("num_attention_heads", 16)
        in_c = hf.get("in_channels", 4)
        # diffusers serializes out_channels: null to mean == in_channels
        # (no learned sigma); DiT checkpoints set it to 2*in explicitly
        out_c = hf.get("out_channels")
        if out_c is None:
            out_c = in_c
        cfg = DiTConfig(
            input_size=hf.get("sample_size", 32),
            patch_size=hf.get("patch_size", 2),
            in_channels=in_c,
            hidden_size=nheads * hf.get("attention_head_dim", 72),
            num_hidden_layers=hf.get("num_layers", 28),
            num_attention_heads=nheads,
            num_classes=hf.get("num_embeds_ada_norm", 1000),
            learn_sigma=out_c == 2 * in_c,
        )
        return DiT, cfg, "dit"
    if not mt and hf.get("_class_name") == "SD3Transformer2DModel":
        from .dit import MMDiT, MMDiTConfig
        if hf.get("qk_norm"):
            raise ValueError("SD3.5-style qk_norm is not supported "
                             "(our MMDiT matches the SD3-medium layout)")
        if hf.get("dual_attention_layers"):
            raise ValueError("dual_attention_layers (SD3.5-medium) not "
                             "supported")
        nheads = hf["num_attention_heads"]
        h = nheads * hf.get("attention_head_dim", 64)
        if hf.get("caption_projection_dim", h) != h:
            raise ValueError("caption_projection_dim != hidden size")
        cfg = MMDiTConfig(
            input_size=hf.get("sample_size", 128),
            patch_size=hf.get("patch_size", 2),
            in_channels=hf.get("in_channels", 16),
            hidden_size=h,
            num_hidden_layers=hf["num_layers"],
            num_attention_heads=nheads,
            context_dim=hf.get("joint_attention_dim", 4096),
            pooled_dim=hf.get("pooled_projection_dim", 2048),
        )
        return MMDiT, cfg, "sd3_transformer"
    if mt == "gpt2":
        from .gpt import GPTConfig, GPTForCausalLM
        cfg = GPTConfig(
            vocab_size=hf["vocab_size"],
            hidden_size=hf["n_embd"],
            intermediate_size=hf.get("n_inner") or 4 * hf["n_embd"],
            num_hidden_layers=hf["n_layer"],
            num_attention_heads=hf["n_head"],
            max_position_embeddings=hf.get("n_positions", 1024),
            layer_norm_epsilon=hf.get("layer_norm_epsilon", 1e-5),
            tie_word_embeddings=True,  # GPT-2 checkpoints always tie
            dtype=_jax_dtype(hf),
        )
        return GPTForCausalLM, cfg, mt
    if mt == "vit":
        from .vit import ViTConfig, ViTForImageClassification
        cfg = ViTConfig(
            image_size=hf.get("image_size", 224),
            patch_size=hf.get("patch_size", 16),
            in_channels=hf.get("num_channels", 3),
            hidden_size=hf["hidden_size"],
            intermediate_size=hf["intermediate_size"],
            num_hidden_layers=hf["num_hidden_layers"],
            num_attention_heads=hf["num_attention_heads"],
            num_classes=len(hf.get("id2label") or {}) or 2,
            layer_norm_eps=hf.get("layer_norm_eps", 1e-12),
            dtype=_jax_dtype(hf),
        )
        return ViTForImageClassification, cfg, mt
    if mt == "resnet":
        from .resnet import ResNet, ResNetConfig
        depths = hf.get("depths", [3, 4, 6, 3])
        bottleneck = hf.get("layer_type", "bottleneck") == "bottleneck"
        exp = 4 if bottleneck else 1
        w = hf.get("embedding_size", 64)
        want = [w * (2 ** i) * exp for i in range(len(depths))]
        if hf.get("hidden_sizes", want) != want:
            raise ValueError(
                f"non-standard ResNet hidden_sizes {hf.get('hidden_sizes')}"
                f" (expected {want}); custom widths are not supported")
        if hf.get("downsample_in_first_stage") or \
                hf.get("downsample_in_bottleneck"):
            raise ValueError("ResNet v1 downsample placement differs from "
                             "our v1.5 ('b') layout")
        cfg = ResNetConfig(
            depth=50 if bottleneck else 18,   # selects the block class
            layers=list(depths),
            num_classes=len(hf.get("id2label") or {}) or 2,
            in_channels=hf.get("num_channels", 3),
            stem_width=w,
            dtype=_jax_dtype(hf),
        )
        return ResNet, cfg, mt
    if mt == "clip":
        from .clip import CLIPConfig, CLIPModel, CLIPTextConfig
        from .vit import ViTConfig
        t, v = hf["text_config"], hf["vision_config"]
        cfg = CLIPConfig(
            text=CLIPTextConfig(
                vocab_size=t["vocab_size"],
                max_position_embeddings=t.get("max_position_embeddings",
                                              77),
                hidden_size=t["hidden_size"],
                intermediate_size=t["intermediate_size"],
                num_hidden_layers=t["num_hidden_layers"],
                num_attention_heads=t["num_attention_heads"],
                layer_norm_eps=t.get("layer_norm_eps", 1e-5),
                eos_token_id=t.get("eos_token_id"),
                hidden_act=t.get("hidden_act", "quick_gelu"),
            ),
            vision=ViTConfig(
                image_size=v.get("image_size", 224),
                patch_size=v.get("patch_size", 32),
                in_channels=v.get("num_channels", 3),
                hidden_size=v["hidden_size"],
                intermediate_size=v["intermediate_size"],
                num_hidden_layers=v["num_hidden_layers"],
                num_attention_heads=v["num_attention_heads"],
                num_classes=0,
                layer_norm_eps=v.get("layer_norm_eps", 1e-5),
                pre_norm=True,             # HF CLIP's pre_layrnorm
                hidden_act=v.get("hidden_act", "quick_gelu"),
                dtype=_jax_dtype(hf),
            ),
            projection_dim=hf.get("projection_dim", 512),
            dtype=_jax_dtype(hf),
        )
        cfg.text.dtype = _jax_dtype(hf)
        return CLIPModel, cfg, mt
    common = dict(
        vocab_size=hf["vocab_size"], hidden_size=hf["hidden_size"],
        num_hidden_layers=hf["num_hidden_layers"],
        num_attention_heads=hf["num_attention_heads"],
    )
    if mt in ("llama", "qwen2", "ernie4_5"):
        from .llama import LlamaConfig, LlamaForCausalLM
        from .qwen2 import Qwen2Config, Qwen2ForCausalLM
        cls, ccls = ((Qwen2ForCausalLM, Qwen2Config) if mt == "qwen2"
                     else (LlamaForCausalLM, LlamaConfig))
        rs_cfg = _rope_scaling_cfg(hf, mt)
        cfg = ccls(
            **common,
            intermediate_size=hf["intermediate_size"],
            num_key_value_heads=hf.get("num_key_value_heads",
                                       hf["num_attention_heads"]),
            max_position_embeddings=hf.get("max_position_embeddings", 8192),
            rms_norm_eps=hf.get("rms_norm_eps", 1e-5),
            rope_theta=hf.get("rope_theta", 10000.0),
            tie_word_embeddings=hf.get("tie_word_embeddings", False),
            attention_bias=hf.get("attention_bias", mt == "qwen2"),
            sliding_window=(hf.get("sliding_window")
                            if hf.get("use_sliding_window") else None),
            max_window_layers=(hf.get("max_window_layers")
                               if hf.get("use_sliding_window") else None),
            rope_scaling=rs_cfg,
            dtype=_jax_dtype(hf),
        )
        return cls, cfg, mt
    if mt in ("qwen2_moe", "ernie4_5_moe"):
        from .ernie import Ernie45MoeConfig, Ernie45MoeForCausalLM
        from .qwen2_moe import Qwen2MoeConfig, Qwen2MoeForCausalLM
        qwen = mt == "qwen2_moe"
        ccls, cls = ((Qwen2MoeConfig, Qwen2MoeForCausalLM) if qwen
                     else (Ernie45MoeConfig, Ernie45MoeForCausalLM))
        if hf.get("decoder_sparse_step", 1) not in (0, 1) or \
                hf.get("mlp_only_layers"):
            raise ValueError(
                "decoder_sparse_step > 1 / mlp_only_layers are not "
                "supported (this build places MoE on every layer past "
                "first_k_dense_replace)")
        rs_cfg = _rope_scaling_cfg(hf, mt)
        n_shared = hf.get("shared_expert_intermediate_size") or 0
        cfg = ccls(
            **common,
            intermediate_size=hf["intermediate_size"],
            num_key_value_heads=hf.get("num_key_value_heads",
                                       hf["num_attention_heads"]),
            max_position_embeddings=hf.get("max_position_embeddings", 8192),
            rms_norm_eps=hf.get("rms_norm_eps", 1e-6),
            rope_theta=hf.get("rope_theta", 1000000.0),
            tie_word_embeddings=hf.get("tie_word_embeddings", False),
            attention_bias=hf.get("attention_bias", qwen),
            num_experts=hf.get("num_experts") or hf.get("moe_num_experts"),
            num_experts_per_tok=hf.get("num_experts_per_tok")
            or hf.get("moe_k", 2),
            moe_intermediate_size=hf.get("moe_intermediate_size", 1408),
            num_shared_experts=(1 if n_shared else
                                hf.get("moe_num_shared_experts", 0)),
            shared_expert_intermediate_size=n_shared or None,
            first_k_dense_replace=hf.get("first_k_dense_replace",
                                         hf.get("moe_layer_start_index", 0)),
            shared_expert_gate=qwen,
            norm_topk_prob=hf.get("norm_topk_prob", False),
            rope_scaling=rs_cfg,
            dtype=_jax_dtype(hf),
        )
        return cls, cfg, mt
    if mt in ("deepseek_v2", "deepseek_v3"):
        from .deepseek_v2 import DeepseekV2Config, DeepseekV2ForCausalLM
        v3 = mt == "deepseek_v3"
        if v3 and hf.get("rope_interleave", True) is False:
            raise ValueError("rope_interleave=False (rotate-half pairing) "
                             "not supported; DeepSeek ships interleaved")
        if not v3 and hf.get("topk_method", "greedy") not in (
                "greedy", "group_limited_greedy"):
            raise ValueError(
                f"topk_method {hf.get('topk_method')!r} not supported")
        if hf.get("moe_layer_freq", 1) != 1:
            raise ValueError("moe_layer_freq != 1 not supported")
        rs_cfg = hf.get("rope_scaling")
        if rs_cfg and rs_cfg.get("rope_type",
                                 rs_cfg.get("type")) not in ("yarn",):
            raise ValueError(
                f"rope_scaling type {rs_cfg!r} not supported (yarn is)")
        cfg = DeepseekV2Config(
            **common,
            intermediate_size=hf["intermediate_size"],
            num_key_value_heads=hf.get("num_key_value_heads",
                                       hf["num_attention_heads"]),
            max_position_embeddings=hf.get("max_position_embeddings", 8192),
            rms_norm_eps=hf.get("rms_norm_eps", 1e-6),
            rope_theta=hf.get("rope_theta", 10000.0),
            attention_bias=hf.get("attention_bias", False),
            q_lora_rank=hf.get("q_lora_rank"),
            kv_lora_rank=hf.get("kv_lora_rank", 512),
            qk_nope_head_dim=hf.get("qk_nope_head_dim", 128),
            qk_rope_head_dim=hf.get("qk_rope_head_dim", 64),
            v_head_dim=hf.get("v_head_dim", 128),
            num_experts=hf.get("n_routed_experts", 64),
            num_experts_per_tok=hf.get("num_experts_per_tok", 6),
            moe_intermediate_size=hf.get("moe_intermediate_size", 1408),
            num_shared_experts=hf.get("n_shared_experts") or 0,
            first_k_dense_replace=hf.get("first_k_dense_replace", 1),
            routed_scaling_factor=hf.get("routed_scaling_factor", 1.0),
            n_group=(hf.get("n_group", 1)
                     if v3 or hf.get("topk_method") ==
                     "group_limited_greedy" else 1),
            topk_group=(hf.get("topk_group", 1)
                        if v3 or hf.get("topk_method") ==
                        "group_limited_greedy" else 1),
            rope_scaling=hf.get("rope_scaling"),
            # V3's sigmoid router APPLIES norm_topk_prob; transformers'
            # V2 gate reads it but never applies it on the greedy path —
            # parity means matching each reference's actual behavior
            norm_topk_prob=hf.get("norm_topk_prob", True) if v3 else False,
            scoring="sigmoid" if v3 else "softmax",
            group_score_mode="top2_sum" if v3 else "max",
            yarn_mscale_all_in_scale=v3,
            aux_loss_weight=0.0 if v3 else 0.001,
            dtype=_jax_dtype(hf),
        )
        return DeepseekV2ForCausalLM, cfg, mt
    if mt in ("bert", "ernie"):
        from .bert import BertConfig, BertForPretraining
        from .ernie import ErnieConfig, ErnieForMaskedLM
        ccls, cls = ((ErnieConfig, ErnieForMaskedLM) if mt == "ernie"
                     else (BertConfig, BertForPretraining))
        kw = dict(
            **common,
            intermediate_size=hf["intermediate_size"],
            max_position_embeddings=hf.get("max_position_embeddings", 512),
            type_vocab_size=hf.get("type_vocab_size", 2),
            layer_norm_eps=hf.get("layer_norm_eps", 1e-12),
            hidden_dropout_prob=hf.get("hidden_dropout_prob", 0.1),
            dtype=_jax_dtype(hf),
        )
        if mt == "ernie":
            kw["task_type_vocab_size"] = hf.get("task_type_vocab_size", 3)
            kw["use_task_id"] = hf.get("use_task_id", True)
        return cls, ccls(**kw), mt
    raise ValueError(f"unsupported model_type {mt!r} in {model_dir}")


def _place(sd: Dict[str, np.ndarray], dtype):
    """Host -> jax arrays, casting floats to the model's compute dtype.
    jnp.issubdtype (not np.issubdtype): bf16 is an ml_dtypes extension
    numpy doesn't recognize as floating."""
    import jax.numpy as jnp
    return {k: (jnp.asarray(v, dtype=dtype)
                if jnp.issubdtype(np.asarray(v).dtype, jnp.floating)
                else jnp.asarray(v))
            for k, v in sd.items()}


def from_pretrained(model_dir: str, dtype: Optional[Any] = None,
                    model_cls=None, strict: bool = True):
    """Build a model from an HF-format checkpoint directory.

    - Unexpected converted keys always raise (converter drift).
    - Missing head keys (``mlm_head.*`` etc. absent from a bare encoder
      checkpoint) stay randomly initialized with a warning; any other
      missing key raises when ``strict``.
    """
    cls, cfg, mt = config_from_hf(model_dir)
    if dtype is not None:
        cfg.dtype = dtype
        for sub in ("text", "vision"):  # CLIP towers read their own dtype
            if hasattr(cfg, sub):
                getattr(cfg, sub).dtype = dtype
    if model_cls is not None:
        cls = model_cls
    model = cls(cfg)

    if mt in ("llama", "qwen2", "ernie4_5"):
        # per-key converter: stream shard-by-shard (host peak = one shard)
        sd: Dict[str, Any] = {}
        for shard in iter_hf_checkpoint_shards(model_dir):
            sd.update(_place(convert_hf_state_dict(shard, cfg, mt), cfg.dtype))
            del shard
    else:
        hf_sd = load_hf_checkpoint(model_dir)
        sd = _place(convert_hf_state_dict(hf_sd, cfg, mt), cfg.dtype)

    missing, unexpected = model.set_state_dict(sd, strict=False)
    if unexpected:
        raise KeyError(f"converted keys not in model: {unexpected[:8]}")
    hard_missing = [k for k in missing
                    if not k.startswith(_OPTIONAL_HEAD_PREFIXES)
                    and not k.endswith(".expert_bias")]  # loss-free-balance
                    # buffer: ours, never in an HF checkpoint
    if hard_missing and strict:
        raise KeyError(f"checkpoint missing model keys: {hard_missing[:8]}")
    # expert_bias is OUR loss-free-balancing buffer, zeros-initialized and
    # mutated online during training; checkpoints without an
    # e_score_correction_bias (e.g. Qwen2-MoE, which balances via aux loss)
    # correctly start it at zero — that is "loaded", not "left at random".
    warn_missing = [k for k in missing if not k.endswith(".expert_bias")]
    if warn_missing:
        warnings.warn(f"{len(warn_missing)} keys left at random init "
                      f"(e.g. {warn_missing[:4]})", stacklevel=2)
    return model
