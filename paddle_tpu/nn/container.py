"""Containers (reference: python/paddle/nn/layer/container.py)."""
from __future__ import annotations

from .layer import Layer, Parameter


class Sequential(Layer):
    def __init__(self, *layers):
        super().__init__()
        if len(layers) == 1 and isinstance(layers[0], (list, tuple)) and \
                layers[0] and isinstance(layers[0][0], tuple):
            for name, layer in layers[0]:
                self.add_sublayer(str(name), layer)
        else:
            for i, layer in enumerate(layers):
                self.add_sublayer(str(i), layer)

    def forward(self, x):
        for layer in self._sub_layers.values():
            x = layer(x)
        return x

    def __getitem__(self, idx):
        if isinstance(idx, slice):
            return Sequential(*list(self._sub_layers.values())[idx])
        return list(self._sub_layers.values())[idx]

    def __len__(self):
        return len(self._sub_layers)

    def __iter__(self):
        return iter(self._sub_layers.values())


class LayerList(Layer):
    def __init__(self, sublayers=None):
        super().__init__()
        for i, layer in enumerate(sublayers or []):
            self.add_sublayer(str(i), layer)

    def append(self, layer):
        self.add_sublayer(str(len(self._sub_layers)), layer)
        return self

    def extend(self, layers):
        for l in layers:
            self.append(l)
        return self

    def insert(self, index, layer):
        existing = list(self._sub_layers.values())
        existing.insert(index, layer)
        self._sub_layers.clear()
        for i, l in enumerate(existing):
            self._sub_layers[str(i)] = l

    def __getitem__(self, idx):
        if isinstance(idx, slice):
            return LayerList(list(self._sub_layers.values())[idx])
        return list(self._sub_layers.values())[idx]

    def __setitem__(self, idx, layer):
        self._sub_layers[str(idx)] = layer

    def __len__(self):
        return len(self._sub_layers)

    def __iter__(self):
        return iter(self._sub_layers.values())


class ParameterList(Layer):
    def __init__(self, parameters=None):
        super().__init__()
        for i, p in enumerate(parameters or []):
            self.add_parameter(str(i), p if isinstance(p, Parameter) else Parameter(p))

    def append(self, p):
        self.add_parameter(str(len(self._parameters)), p if isinstance(p, Parameter) else Parameter(p))
        return self

    def __getitem__(self, idx):
        return self._parameters[str(idx)]

    def __len__(self):
        return len(self._parameters)

    def __iter__(self):
        return iter(self._parameters.values())


class LayerDict(Layer):
    def __init__(self, sublayers=None):
        super().__init__()
        for name, layer in (sublayers or {}).items():
            self.add_sublayer(name, layer)

    def __getitem__(self, key):
        return self._sub_layers[key]

    def __setitem__(self, key, layer):
        self.add_sublayer(key, layer)

    def __contains__(self, key):
        return key in self._sub_layers

    def keys(self):
        return self._sub_layers.keys()

    def values(self):
        return self._sub_layers.values()

    def items(self):
        return self._sub_layers.items()

    def __len__(self):
        return len(self._sub_layers)
