"""paddle.audio parity (reference: python/paddle/audio — functional
window/mel utilities and the Spectrogram/MelSpectrogram/LogMelSpectrogram/
MFCC feature layers).

TPU-native: everything is a pure jnp program over the existing
``paddle_tpu.signal.stft`` — one fused XLA program per feature (frame →
window → rfft → |.|^p → mel matmul → log/DCT), batched over channels, so
feature extraction can live INSIDE a jitted train step (e.g. an audio
classifier consuming raw waveforms) instead of a host-side preprocessing
pass.
"""
from __future__ import annotations

import math
from typing import Optional

import jax.numpy as jnp

from . import signal as _signal
from .nn.layer import Layer

__all__ = [
    "get_window", "hz_to_mel", "mel_to_hz", "mel_frequencies",
    "compute_fbank_matrix", "create_dct", "power_to_db",
    "Spectrogram", "MelSpectrogram", "LogMelSpectrogram", "MFCC",
]


# -------------------------------------------------------------- functional
def get_window(window: str, win_length: int, fftbins: bool = True,
               dtype=jnp.float32):
    """hann/hamming/blackman/ones (reference: paddle.audio.functional
    .get_window). ``fftbins=True`` gives the periodic variant."""
    n = jnp.arange(win_length, dtype=jnp.float32)
    denom = win_length if fftbins else win_length - 1
    if window in ("hann", "hanning"):
        w = 0.5 - 0.5 * jnp.cos(2 * math.pi * n / denom)
    elif window == "hamming":
        w = 0.54 - 0.46 * jnp.cos(2 * math.pi * n / denom)
    elif window == "blackman":
        w = (0.42 - 0.5 * jnp.cos(2 * math.pi * n / denom)
             + 0.08 * jnp.cos(4 * math.pi * n / denom))
    elif window in ("ones", "boxcar", "rectangular"):
        w = jnp.ones((win_length,), jnp.float32)
    else:
        raise ValueError(f"unsupported window {window!r}")
    return w.astype(dtype)


def hz_to_mel(freq, htk: bool = False):
    freq = jnp.asarray(freq, jnp.float32)
    if htk:
        return 2595.0 * jnp.log10(1.0 + freq / 700.0)
    # Slaney: linear below 1 kHz, log above
    f_min, f_sp = 0.0, 200.0 / 3
    mel = (freq - f_min) / f_sp
    min_log_hz = 1000.0
    min_log_mel = (min_log_hz - f_min) / f_sp
    logstep = math.log(6.4) / 27.0
    return jnp.where(freq >= min_log_hz,
                     min_log_mel + jnp.log(freq / min_log_hz) / logstep, mel)


def mel_to_hz(mel, htk: bool = False):
    mel = jnp.asarray(mel, jnp.float32)
    if htk:
        return 700.0 * (10.0 ** (mel / 2595.0) - 1.0)
    f_min, f_sp = 0.0, 200.0 / 3
    freq = f_min + f_sp * mel
    min_log_hz = 1000.0
    min_log_mel = (min_log_hz - f_min) / f_sp
    logstep = math.log(6.4) / 27.0
    return jnp.where(mel >= min_log_mel,
                     min_log_hz * jnp.exp(logstep * (mel - min_log_mel)),
                     freq)


def mel_frequencies(n_mels: int, f_min: float, f_max: float,
                    htk: bool = False):
    mels = jnp.linspace(hz_to_mel(f_min, htk), hz_to_mel(f_max, htk), n_mels)
    return mel_to_hz(mels, htk)


def compute_fbank_matrix(sr: int, n_fft: int, n_mels: int = 64,
                         f_min: float = 0.0, f_max: Optional[float] = None,
                         htk: bool = False, norm: str = "slaney",
                         dtype=jnp.float32):
    """[n_mels, n_fft//2 + 1] triangular mel filterbank (reference:
    paddle.audio.functional.compute_fbank_matrix; librosa-compatible)."""
    f_max = f_max or sr / 2.0
    fft_freqs = jnp.linspace(0.0, sr / 2.0, n_fft // 2 + 1)
    mel_f = mel_frequencies(n_mels + 2, f_min, f_max, htk)
    fdiff = jnp.diff(mel_f)
    ramps = mel_f[:, None] - fft_freqs[None, :]
    lower = -ramps[:-2] / fdiff[:-1, None]
    upper = ramps[2:] / fdiff[1:, None]
    weights = jnp.maximum(0.0, jnp.minimum(lower, upper))
    if norm == "slaney":  # area-normalize each triangle
        enorm = 2.0 / (mel_f[2:n_mels + 2] - mel_f[:n_mels])
        weights = weights * enorm[:, None]
    return weights.astype(dtype)


def create_dct(n_mfcc: int, n_mels: int, norm: Optional[str] = "ortho",
               dtype=jnp.float32):
    """[n_mels, n_mfcc] DCT-II basis (reference: paddle.audio.functional
    .create_dct)."""
    n = jnp.arange(n_mels, dtype=jnp.float32)
    k = jnp.arange(n_mfcc, dtype=jnp.float32)
    dct = jnp.cos(math.pi / n_mels * (n[:, None] + 0.5) * k[None, :]) * 2.0
    if norm == "ortho":
        dct = dct.at[:, 0].multiply(1.0 / math.sqrt(2.0))
        dct = dct * math.sqrt(1.0 / (2.0 * n_mels))
    return dct.astype(dtype)


def power_to_db(spect, ref_value: float = 1.0, amin: float = 1e-10,
                top_db: Optional[float] = 80.0):
    log_spec = 10.0 * jnp.log10(jnp.maximum(amin, spect))
    log_spec = log_spec - 10.0 * math.log10(max(amin, ref_value))
    if top_db is not None:
        log_spec = jnp.maximum(log_spec, log_spec.max() - top_db)
    return log_spec


# ------------------------------------------------------------------ layers
class Spectrogram(Layer):
    """|STFT|^power over frames (reference: paddle.audio.features
    .Spectrogram). Input [..., time] -> [..., freq, frame]."""

    def __init__(self, n_fft: int = 512, hop_length: Optional[int] = None,
                 win_length: Optional[int] = None, window: str = "hann",
                 power: float = 2.0, center: bool = True,
                 pad_mode: str = "reflect", dtype=jnp.float32):
        super().__init__()
        self.n_fft = n_fft
        self.hop_length = hop_length or n_fft // 4
        self.win_length = win_length or n_fft
        self.power = power
        self.center = center
        self.pad_mode = pad_mode
        self.register_buffer(
            "window", get_window(window, self.win_length, dtype=dtype),
            persistable=False)

    def forward(self, x):
        spec = _signal.stft(x, self.n_fft, self.hop_length, self.win_length,
                            self.window, center=self.center,
                            pad_mode=self.pad_mode)
        return jnp.abs(spec) ** self.power


class MelSpectrogram(Layer):
    """Spectrogram -> mel filterbank (reference: paddle.audio.features
    .MelSpectrogram)."""

    def __init__(self, sr: int = 22050, n_fft: int = 512,
                 hop_length: Optional[int] = None,
                 win_length: Optional[int] = None, window: str = "hann",
                 power: float = 2.0, center: bool = True,
                 pad_mode: str = "reflect", n_mels: int = 64,
                 f_min: float = 50.0, f_max: Optional[float] = None,
                 htk: bool = False, norm: str = "slaney", dtype=jnp.float32):
        super().__init__()
        self.spectrogram = Spectrogram(n_fft, hop_length, win_length,
                                       window, power, center, pad_mode,
                                       dtype=dtype)
        self.register_buffer(
            "fbank", compute_fbank_matrix(sr, n_fft, n_mels, f_min, f_max,
                                          htk, norm, dtype),
            persistable=False)

    def forward(self, x):
        return self.fbank @ self.spectrogram(x)


class LogMelSpectrogram(Layer):
    """Mel spectrogram in dB (reference: paddle.audio.features
    .LogMelSpectrogram — positional order matches the reference, so
    paddle code calling (sr, n_fft, hop_length, ...) binds correctly)."""

    def __init__(self, sr: int = 22050, n_fft: int = 512,
                 hop_length: Optional[int] = None,
                 win_length: Optional[int] = None, window: str = "hann",
                 power: float = 2.0, center: bool = True,
                 pad_mode: str = "reflect", n_mels: int = 64,
                 f_min: float = 50.0, f_max: Optional[float] = None,
                 htk: bool = False, norm: str = "slaney",
                 ref_value: float = 1.0, amin: float = 1e-10,
                 top_db: Optional[float] = None, dtype=jnp.float32):
        super().__init__()
        self.mel = MelSpectrogram(sr, n_fft, hop_length, win_length, window,
                                  power, center, pad_mode, n_mels, f_min,
                                  f_max, htk, norm, dtype)
        self.ref_value, self.amin, self.top_db = ref_value, amin, top_db

    def forward(self, x):
        return power_to_db(self.mel(x), self.ref_value, self.amin,
                           self.top_db)


class MFCC(Layer):
    """Mel-frequency cepstral coefficients (reference: paddle.audio
    .features.MFCC): log-mel -> DCT-II."""

    def __init__(self, sr: int = 22050, n_mfcc: int = 40, n_mels: int = 64,
                 **kw):
        super().__init__()
        # kw passes through LogMelSpectrogram's full (reference-ordered)
        # keyword surface: n_fft, hop_length, center, pad_mode, top_db, ...
        self.log_mel = LogMelSpectrogram(sr=sr, n_mels=n_mels, **kw)
        self.register_buffer("dct", create_dct(n_mfcc, n_mels),
                             persistable=False)

    def forward(self, x):
        # [..., n_mels, frames] -> [..., n_mfcc, frames]
        return self.dct.T @ self.log_mel(x)
