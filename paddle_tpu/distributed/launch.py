"""Multi-host launch + elastic bootstrap (reference:
python/paddle/distributed/launch — `python -m paddle.distributed.launch
train.py` spawns/wires one worker per device and restarts on failure;
fleet elastic uses etcd heartbeats).

TPU-native: a TPU pod slice already runs one host process per host, and
ICI/DCN wiring comes from `jax.distributed.initialize` — there is no NCCL
rendezvous to build. So launch here means: (1) initialize the JAX
distributed runtime from the environment (GKE/TPU-pod metadata or explicit
coordinator), (2) install the watchdog + auto-resume hooks that give the
elastic behavior, (3) exec the training script. Single-host invocations
no-op into local mode, so the same entrypoint works everywhere.

Usage:
    python -m paddle_tpu.distributed.launch train.py --args...
or programmatically:
    from paddle_tpu.distributed.launch import init_distributed
    init_distributed()   # before any jax call that touches devices
"""
from __future__ import annotations

import os
import runpy
import sys
from typing import Optional

import jax


def _env(*names, default=None):
    for n in names:
        v = os.environ.get(n)
        if v is not None:
            return v
    return default


def init_distributed(coordinator_address: Optional[str] = None,
                     num_processes: Optional[int] = None,
                     process_id: Optional[int] = None) -> dict:
    """Initialize the JAX distributed runtime for multi-host training.

    Resolution order mirrors the reference launcher's env handling:
    explicit args > PADDLE_TPU_* vars > paddle-compatible PADDLE_* vars >
    TPU-pod auto-detection (jax.distributed.initialize with no args picks
    up Cloud TPU metadata). Returns a summary dict; on a single host with
    no env configured this is a no-op local setup.
    """
    coord = coordinator_address or _env(
        "PADDLE_TPU_COORDINATOR", "COORDINATOR_ADDRESS",
        "PADDLE_MASTER", "MASTER_ADDR")
    nproc = num_processes if num_processes is not None else _env(
        "PADDLE_TPU_NUM_PROCESSES", "PADDLE_TRAINERS_NUM", "WORLD_SIZE")
    pid = process_id if process_id is not None else _env(
        "PADDLE_TPU_PROCESS_ID", "PADDLE_TRAINER_ID", "RANK")

    on_pod = _env("TPU_WORKER_HOSTNAMES",
                  "MEGASCALE_COORDINATOR_ADDRESS") is not None
    explicit = coord is not None and nproc is not None and pid is not None
    if explicit or on_pod:
        try:
            if explicit:
                jax.distributed.initialize(coordinator_address=coord,
                                           num_processes=int(nproc),
                                           process_id=int(pid))
            else:
                jax.distributed.initialize()  # Cloud TPU metadata autodetect
        except RuntimeError as e:
            # initialize() raises this specific error when a jax op already
            # touched the backend (notebook, test session); only THAT case
            # degrades to a warning. Any other failure (coordinator
            # unreachable, barrier timeout, bad world size) must stay fatal
            # or N hosts would silently fan out as independent jobs.
            if "must be called before" not in str(e):
                raise
            import warnings
            warnings.warn(
                f"init_distributed: multi-host setup requested but the XLA "
                f"backend is already initialized ({e}); continuing with the "
                f"EXISTING topology ({jax.process_count()} process(es)). "
                f"Call init_distributed() before any jax operation.",
                RuntimeWarning, stacklevel=2)
    # else: single host — nothing to initialize

    info = {
        "process_index": jax.process_index(),
        "process_count": jax.process_count(),
        "local_devices": len(jax.local_devices()),
        "global_devices": len(jax.devices()),
    }
    os.environ.setdefault("PADDLE_TRAINER_ID", str(info["process_index"]))
    os.environ.setdefault("PADDLE_TRAINERS_NUM", str(info["process_count"]))
    return info


def launch(argv=None):
    """CLI: initialize distributed, then run the target script in-process
    (the reference launcher spawns subprocesses per GPU; on TPU the host
    process IS the per-host worker, so exec is direct)."""
    argv = argv if argv is not None else sys.argv[1:]
    if not argv:
        print("usage: python -m paddle_tpu.distributed.launch "
              "script.py [args...]", file=sys.stderr)
        return 2
    info = init_distributed()
    if info["process_index"] == 0:
        print(f"paddle_tpu.launch: {info['process_count']} process(es), "
              f"{info['global_devices']} device(s)", file=sys.stderr)
    script, *rest = argv
    sys.argv = [script] + rest
    runpy.run_path(script, run_name="__main__")
    return 0


if __name__ == "__main__":
    sys.exit(launch())
