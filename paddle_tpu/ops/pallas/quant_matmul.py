"""Pallas TPU fused dequant-matmul (reference: PHI
``fusion/gpu/weight_only_linear_kernel.cu`` — reimagined for TPU).

Weight-only-quantized decode is HBM-bound: the win is that weights cross
HBM at 1/2 (int8) or 1/4 (int4) the bytes. The XLA path *hopes* the
`dequant -> matmul` chain fuses; this kernel guarantees it: int8/int4
blocks DMA into VMEM, dequantize against their per-(128-row, column)
scales in-register, and feed the MXU — the full-precision weight never
exists outside VMEM.

- grid (out_blocks, in_blocks); in innermost so the fp32 accumulator
  scratch carries partial sums across the contraction.
- activations [m, din] with m padded to the 8-sublane minimum (decode m
  is the batch size).
- int4: two nibbles per int8 byte along the input dim, sign-extended with
  arithmetic shifts in-kernel.
"""
from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

QUANT_BLOCK = 128  # rows per scale group (quantize_blockwise block_size)


from . import interpret_enabled as _interpret


def _pick(total: int, preferred: int, unit: int) -> int:
    b = min(preferred, total)
    b -= b % unit
    while b > unit and total % b:
        b -= unit
    return b if b and total % b == 0 else 0


def _qmm_kernel(x_ref, w_ref, s_ref, o_ref, acc, *, bits, bk, bn, nin):
    ni = pl.program_id(1)

    @pl.when(ni == 0)
    def _init():
        acc[:] = jnp.zeros_like(acc)

    w = w_ref[...].astype(jnp.int32)
    if bits == 4:
        lo = (w << 28) >> 28                       # sign-extend low nibble
        hi = w >> 4                                # arithmetic: signed high
        w = jnp.stack([lo, hi], axis=1).reshape(bk, bn)
    scales = s_ref[0, :bk // QUANT_BLOCK, :]       # drop the 8-sublane pad
    wf = w.astype(jnp.float32).reshape(bk // QUANT_BLOCK, QUANT_BLOCK, bn)
    wf = (wf * scales.astype(jnp.float32)[:, None, :]).reshape(bk, bn)
    acc[:] += lax.dot_general(
        x_ref[...].astype(jnp.float32), wf, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)

    @pl.when(ni == nin - 1)
    def _finalize():
        o_ref[...] = acc[:].astype(o_ref.dtype)


def quant_matmul_pallas(x, qweight, scales, bits: int = 8,
                        block_out: int = 512, block_in: int = 512):
    """x [m, din] @ dequant(qweight, scales) -> [m, dout].

    qweight: int8 [din, dout] (bits=8) or [din/2, dout] (bits=4, packed);
    scales: [din/QUANT_BLOCK, dout]."""
    m, din = x.shape
    dout = qweight.shape[1]
    bk = _pick(din, block_in, QUANT_BLOCK)
    bn = _pick(dout, block_out, 128)
    assert bk and bn, (din, dout)
    nin, nout = din // bk, dout // bn

    mp = max(8, m + (-m) % 8)
    if mp != m:
        x = jnp.pad(x, ((0, mp - m), (0, 0)))

    if bits == 4:
        w_spec = pl.BlockSpec((bk // 2, bn), lambda no, ni: (ni, no))
    else:
        w_spec = pl.BlockSpec((bk, bn), lambda no, ni: (ni, no))

    # Mosaic tiling: a scales block of (bk/128, bn) rows-per-block (often 4)
    # violates the 8-sublane minimum. Regroup to [nin, rows_pad, dout] with
    # the per-block rows padded up to a multiple of 8; the kernel slices the
    # real rows back off. The pad touches only the tiny scales array.
    rows = bk // QUANT_BLOCK
    rows_pad = max(8, rows + (-rows) % 8)
    s3 = scales.reshape(nin, rows, dout)
    if rows_pad != rows:
        s3 = jnp.pad(s3, ((0, 0), (0, rows_pad - rows), (0, 0)))

    kernel = functools.partial(_qmm_kernel, bits=bits, bk=bk, bn=bn, nin=nin)
    out = pl.pallas_call(
        kernel,
        grid=(nout, nin),
        in_specs=[
            pl.BlockSpec((mp, bk), lambda no, ni: (0, ni)),
            w_spec,
            pl.BlockSpec((1, rows_pad, bn), lambda no, ni: (ni, 0, no)),
        ],
        out_specs=pl.BlockSpec((mp, bn), lambda no, ni: (0, no)),
        scratch_shapes=[pltpu.VMEM((mp, bn), jnp.float32)],
        out_shape=jax.ShapeDtypeStruct((mp, dout), x.dtype),
        interpret=_interpret(),
    )(x, qweight, s3)
    return out[:m]


def use_quant_matmul(x2d, qweight, block_size: int) -> bool:
    """The fused kernel targets decode-sized activations (small m) where
    the weight stream dominates; big-m training matmuls go to XLA."""
    m, din = x2d.shape
    dout = qweight.shape[1]
    return (block_size == QUANT_BLOCK and m <= 64
            and _pick(din, 512, QUANT_BLOCK) > 0
            and _pick(dout, 512, 128) > 0)
