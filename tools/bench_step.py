#!/usr/bin/env python
"""Train-step microbenchmark for bisecting `bench.py` regressions on CPU
(ISSUE 4 satellite): synthetic batches through the REAL `Trainer` hot
path — jitted step, AOT warmup, `DevicePrefetcher` — N timed steps, one
JSON line per arm on stdout:

    {"prefetch": "on", "steps": 30, "step_ms": 8.1,
     "tokens_per_sec": 31600.0, "mfu": 1.1e-4, ...}

`--feed-delay-ms` injects a per-batch host-side delay (tokenization /
host-copy stand-in), which is the workload where the async prefetch
pipeline pays: `--prefetch on` overlaps that delay with step compute,
`--prefetch off` serializes it. `--prefetch both` (default) runs the A/B
in one process so a regression bisect is a single command:

    python tools/bench_step.py --steps 30 --feed-delay-ms 5

No TPU tunnel needed — numbers on CPU are meaningless in absolute terms
but the on/off RATIO and step-to-step drift are what a bisect needs.
"""
import argparse
import json
import os
import sys
import tempfile
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)


class SlowFeed:
    """List-backed loader whose every batch costs `delay_ms` of host time
    (sleep, so it overlaps with compute when prefetched — exactly like a
    tokenizer or host copy that releases the GIL)."""

    def __init__(self, batches, delay_ms: float):
        self._batches = batches
        self._delay_s = delay_ms / 1000.0

    def __iter__(self):
        for b in self._batches:
            if self._delay_s:
                time.sleep(self._delay_s)
            yield b

    def __len__(self):
        return len(self._batches)


def run_arm(prefetch_on: bool, ns: argparse.Namespace) -> dict:
    import numpy as np
    import jax.numpy as jnp
    import paddle_tpu as pt
    from paddle_tpu.models import LlamaForCausalLM, llama_tiny
    from paddle_tpu.trainer import Trainer, TrainingArguments

    rng = np.random.RandomState(0)
    batches = [jnp.asarray(rng.randint(0, 256, (ns.batch, ns.seq)))
               for _ in range(8)]
    feed = SlowFeed(batches, ns.feed_delay_ms)
    with tempfile.TemporaryDirectory() as tmp:
        args = TrainingArguments(
            output_dir=tmp, max_steps=ns.steps,
            logging_steps=max(ns.steps // 3, 1),
            resume_from_checkpoint=False, save_steps=0,
            prefetch_depth=ns.depth if prefetch_on else 0,
            aot_warmup=True,   # compile lands before step 0, outside the timer
            compile_cache_dir=ns.compile_cache_dir)
        tr = Trainer(LlamaForCausalLM(llama_tiny()),
                     pt.optimizer.AdamW(learning_rate=1e-4), args,
                     train_dataloader=feed)
        t0 = time.perf_counter()
        tr.train()
        wall_s = time.perf_counter() - t0
        timer = tr.step_timer
        feed_obj = tr._data_feed
        return {
            "prefetch": "on" if prefetch_on else "off",
            "depth": ns.depth if prefetch_on else 0,
            "steps": ns.steps,
            "batch": ns.batch,
            "seq": ns.seq,
            "feed_delay_ms": ns.feed_delay_ms,
            "step_ms": round(timer.avg_step_s * 1e3, 3),
            "tokens_per_sec": round(timer.tokens_per_sec, 1),
            "mfu": timer.mfu,
            "wall_s": round(wall_s, 2),
            "sync_fallbacks": getattr(feed_obj, "sync_fallbacks", 0),
        }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--prefetch", choices=("on", "off", "both"),
                    default="both")
    ap.add_argument("--steps", type=int, default=30)
    ap.add_argument("--depth", type=int, default=2,
                    help="prefetch buffer depth for the `on` arm")
    ap.add_argument("--batch", type=int, default=4)
    ap.add_argument("--seq", type=int, default=16)
    ap.add_argument("--feed-delay-ms", type=float, default=5.0,
                    help="host-side cost per batch (slow-feed workload)")
    ap.add_argument("--compile-cache-dir", default=None,
                    help="persistent XLA cache shared by both arms")
    ns = ap.parse_args(argv)

    # same trick as bench.py: env alone can lose to the image's
    # sitecustomize, an explicit config.update wins
    plat = os.environ.get("PADDLE_TPU_BENCH_PLATFORM")
    if plat:
        import jax
        jax.config.update("jax_platforms", plat)

    arms = {"on": [True], "off": [False], "both": [False, True]}[ns.prefetch]
    results = []
    for on in arms:
        try:
            res = run_arm(on, ns)
        except Exception as e:   # one JSON line even on failure
            res = {"prefetch": "on" if on else "off", "error": repr(e)}
        results.append(res)
        print(json.dumps(res), flush=True)
    if len(results) == 2 and all("error" not in r for r in results):
        off, on_ = results
        print(json.dumps({
            "speedup_on_vs_off": round(
                on_["tokens_per_sec"] / max(off["tokens_per_sec"], 1e-9), 3),
        }), flush=True)
    return 1 if any("error" in r for r in results) else 0


if __name__ == "__main__":
    sys.exit(main())
