"""Checkpointing (reference: python/paddle/framework/io.py paddle.save/load;
distributed checkpoint: python/paddle/distributed/checkpoint/*).

Format: a directory (or single .pdt file) holding an npz of arrays plus a
msgpack-free JSON manifest for non-array state. Distributed sharded
checkpointing and async save live in `paddle_tpu.checkpoint.distributed_ckpt`
(orbax-backed, see C14 in SURVEY.md).
"""
from __future__ import annotations

import json
import os
import pickle
from typing import Any, Dict

import jax
import jax.numpy as jnp
import numpy as np

_ARRAY_KEY = "__paddle_tpu_arrays__"


def _split_state(obj, arrays, prefix=""):
    """Replace arrays in a nested structure with placeholders, collecting
    them into `arrays`."""
    if isinstance(obj, (jax.Array, np.ndarray)):
        key = f"a{len(arrays)}"
        arrays[key] = np.asarray(obj)
        return {_ARRAY_KEY: key}
    if isinstance(obj, dict):
        return {k: _split_state(v, arrays, f"{prefix}.{k}") for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        out = [_split_state(v, arrays, f"{prefix}[{i}]") for i, v in enumerate(obj)]
        return out if isinstance(obj, list) else {"__tuple__": out}
    return obj


def _join_state(obj, arrays):
    if isinstance(obj, dict):
        if _ARRAY_KEY in obj:
            return jnp.asarray(arrays[obj[_ARRAY_KEY]])
        if "__tuple__" in obj:
            return tuple(_join_state(v, arrays) for v in obj["__tuple__"])
        return {k: _join_state(v, arrays) for k, v in obj.items()}
    if isinstance(obj, list):
        return [_join_state(v, arrays) for v in obj]
    return obj


def save(obj: Any, path: str):
    """paddle.save parity: accepts a state_dict (or any nested structure of
    arrays + JSON-able scalars)."""
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    arrays: Dict[str, np.ndarray] = {}
    try:
        structure = _split_state(obj, arrays)
        manifest = json.dumps(structure)
    except TypeError:
        # non-JSON-able python object: pickle fallback (paddle does the same)
        with open(path, "wb") as f:
            pickle.dump(jax.tree.map(np.asarray, obj), f)
        return
    np.savez(path + ".npz" if not path.endswith(".npz") else path,
             __manifest__=np.frombuffer(manifest.encode(), dtype=np.uint8),
             **arrays)


def load(path: str):
    """paddle.load parity."""
    npz_path = path + ".npz" if not path.endswith(".npz") and os.path.exists(path + ".npz") else path
    if os.path.exists(npz_path) and npz_path.endswith(".npz"):
        data = np.load(npz_path)
        manifest = json.loads(bytes(data["__manifest__"]).decode())
        arrays = {k: data[k] for k in data.files if k != "__manifest__"}
        return _join_state(manifest, arrays)
    with open(path, "rb") as f:
        return pickle.load(f)
