"""LoRA/PEFT tests (C30): identity at init, delta math, freeze semantics,
Trainer frozen-subset training, merge/unmerge, adapter save/load, TP
partition derivation (SURVEY.md §4 numerics-first strategy)."""
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import nn
from paddle_tpu.models import LlamaForCausalLM, causal_lm_loss, llama_tiny
from paddle_tpu.peft import (LoRAConfig, LoRAModel, apply_lora, inject_lora,
                             lora_state_dict, merge_lora, unmerge_lora)


def _tiny_model():
    pt.seed(0)
    return LlamaForCausalLM(llama_tiny())


def _ids(b=2, s=16, vocab=256, seed=0):
    return jnp.asarray(np.random.RandomState(seed).randint(0, vocab, (b, s)))


class TestLoRALinear:
    def test_identity_at_init(self):
        """B = 0 at init => adapted forward == base forward exactly."""
        pt.seed(0)
        lin = nn.Linear(16, 32)
        x = jnp.asarray(np.random.RandomState(1).randn(4, 16), jnp.float32)
        y0 = lin(x)
        inject_lora(lin, LoRAConfig(r=4))
        np.testing.assert_allclose(np.asarray(lin(x)), np.asarray(y0))

    def test_delta_math(self):
        pt.seed(0)
        cfg = LoRAConfig(r=4, lora_alpha=8)
        lin = nn.Linear(16, 32)
        x = jnp.asarray(np.random.RandomState(1).randn(4, 16), jnp.float32)
        y0 = lin(x)
        inject_lora(lin, cfg)
        a = jnp.asarray(np.random.RandomState(2).randn(16, 4), jnp.float32)
        b = jnp.asarray(np.random.RandomState(3).randn(4, 32), jnp.float32)
        lin.lora_A, lin.lora_B = a, b
        want = y0 + (x @ a @ b) * cfg.scaling
        np.testing.assert_allclose(np.asarray(lin(x)), np.asarray(want),
                                   rtol=1e-5)

    def test_rslora_scaling(self):
        assert LoRAConfig(r=16, lora_alpha=16).scaling == 1.0
        assert LoRAConfig(r=16, lora_alpha=16, rslora=True).scaling == 4.0

    def test_double_injection_rejected(self):
        lin = nn.Linear(8, 8)
        inject_lora(lin, LoRAConfig(r=2))
        with pytest.raises(ValueError):
            inject_lora(lin, LoRAConfig(r=2))


class TestApplyLoRA:
    def test_targets_and_freeze(self):
        model = _tiny_model()
        hit = apply_lora(model, LoRAConfig(r=4))
        assert all(h.endswith(("q_proj", "v_proj")) for h in hit)
        assert len(hit) == 2 * model.config.num_hidden_layers
        trainable = model.trainable_parameters()
        assert trainable and all(
            k.rsplit(".", 1)[-1] in ("lora_A", "lora_B") for k in trainable)
        # base params frozen, still present in state_dict
        assert "lm_head.weight" in dict(model.named_parameters())

    def test_no_match_raises(self):
        with pytest.raises(ValueError):
            apply_lora(_tiny_model(), LoRAConfig(
                target_modules=[".*nonexistent"]))

    def test_tp_partitions_derived(self):
        model = _tiny_model()
        apply_lora(model, LoRAConfig(r=4, target_modules=
                                     [".*q_proj", ".*o_proj"]))
        meta = model.param_meta()
        # q_proj is column-parallel: A replicated, B sharded on out
        assert meta["model.layers.0.self_attn.q_proj.lora_A"].partition is None
        assert meta["model.layers.0.self_attn.q_proj.lora_B"].partition == \
            (None, "tp")
        # o_proj is row-parallel: A sharded on in, B replicated
        assert meta["model.layers.0.self_attn.o_proj.lora_A"].partition == \
            ("tp", None)
        assert meta["model.layers.0.self_attn.o_proj.lora_B"].partition is None


class TestMerge:
    def test_merge_unmerge_roundtrip(self):
        model = _tiny_model()
        apply_lora(model, LoRAConfig(r=4))
        # give B real values so the merge moves the weights
        for k, v in lora_state_dict(model).items():
            if k.endswith("lora_B"):
                model._set_by_path(
                    k, jnp.full_like(v, 0.01))
        ids = _ids()
        y_adapter = model(ids)
        w0 = np.asarray(model.model.layers[0].self_attn.q_proj.weight).copy()
        merge_lora(model)
        assert not np.allclose(
            np.asarray(model.model.layers[0].self_attn.q_proj.weight), w0)
        np.testing.assert_allclose(np.asarray(model(ids)),
                                   np.asarray(y_adapter), atol=1e-4)
        merge_lora(model)  # idempotent
        unmerge_lora(model)
        np.testing.assert_allclose(
            np.asarray(model.model.layers[0].self_attn.q_proj.weight), w0,
            atol=1e-5)
        np.testing.assert_allclose(np.asarray(model(ids)),
                                   np.asarray(y_adapter), atol=1e-4)


class TestLoRATraining:
    def test_trainer_updates_only_adapters(self, tmp_path):
        from paddle_tpu.trainer import Trainer, TrainingArguments

        model = _tiny_model()
        apply_lora(model, LoRAConfig(r=4, lora_alpha=8))
        base_before = {k: np.asarray(v).copy()
                       for k, v in model.named_parameters()
                       if "lora" not in k}
        loader = [jnp.asarray(
            np.random.RandomState(i).randint(0, 256, (4, 16)))
            for i in range(3)]
        tr = Trainer(
            model,
            pt.optimizer.AdamW(learning_rate=1e-2),
            TrainingArguments(output_dir=str(tmp_path), max_steps=6,
                              logging_steps=2, resume_from_checkpoint=False),
            train_dataloader=loader)
        tr.train()
        # optimizer state exists only for the adapters
        n_lora = len(lora_state_dict(model))
        assert len(tr._opt_state["slots"]) == n_lora
        after = dict(model.named_parameters())
        for k, v in base_before.items():
            np.testing.assert_array_equal(np.asarray(after[k]), v, err_msg=k)
        assert any(np.abs(np.asarray(after[k])).sum() > 0
                   for k in after if k.endswith("lora_B"))

    def test_lora_grad_accum_matches_big_batch(self, tmp_path):
        """accum=2 over half-batches == one full batch step (frozen path)."""
        from paddle_tpu.trainer import Trainer, TrainingArguments

        ids = _ids(4, 16, seed=5)

        def one_step(accum):
            model = _tiny_model()
            apply_lora(model, LoRAConfig(r=4, lora_alpha=8))
            tr = Trainer(
                model, pt.optimizer.SGD(learning_rate=1e-1),
                TrainingArguments(output_dir=str(tmp_path),
                                  gradient_accumulation_steps=accum,
                                  resume_from_checkpoint=False))
            tr._opt_state = tr.optimizer.init(
                {k: tr._params[k] for k in tr._trainable_keys})
            step = tr._build_step()
            batch = tr._prep_batch(ids)
            params, _, _, loss = step(dict(tr._params), tr._opt_state,
                                      None, jnp.int32(0), batch)
            return {k: np.asarray(v) for k, v in params.items()
                    if "lora" in k}, float(loss)

        p1, l1 = one_step(1)
        p2, l2 = one_step(2)
        assert abs(l1 - l2) < 1e-5
        for k in p1:
            np.testing.assert_allclose(p1[k], p2[k], atol=1e-5, err_msg=k)


class TestLoRAModelFacade:
    def test_save_load_adaponly(self, tmp_path):
        pt.seed(0)
        base = LlamaForCausalLM(llama_tiny())
        lm = LoRAModel(base, LoRAConfig(r=4))
        for k, v in lora_state_dict(base).items():
            if k.endswith("lora_B"):
                base._set_by_path(k, jnp.full_like(v, 0.02))
        ids = _ids()
        y = lm(ids)
        path = os.path.join(str(tmp_path), "adapter")
        lm.save_pretrained(path)
        # adapter file holds ONLY lora weights
        from paddle_tpu.checkpoint import load
        saved = load(os.path.join(path, "lora_weights.pdparams"))
        assert set(saved) == set(lora_state_dict(base))

        pt.seed(0)
        fresh = LlamaForCausalLM(llama_tiny())
        lm2 = LoRAModel.from_pretrained(fresh, path)
        assert lm2.lora_config.r == 4
        np.testing.assert_allclose(np.asarray(lm2(ids)), np.asarray(y),
                                   atol=1e-5)

    def test_mismatched_adapter_rejected(self, tmp_path):
        pt.seed(0)
        lm = LoRAModel(LlamaForCausalLM(llama_tiny()), LoRAConfig(r=4))
        path = os.path.join(str(tmp_path), "adapter")
        lm.save_pretrained(path)
        pt.seed(0)
        other = LlamaForCausalLM(llama_tiny())
        # different target set -> different adapter keys -> must NOT load
        cfgpath = os.path.join(path, "lora_config.json")
        import json
        with open(cfgpath) as f:
            cfg = json.load(f)
        cfg["target_modules"] = [".*o_proj"]
        with open(cfgpath, "w") as f:
            json.dump(cfg, f)
        with pytest.raises(KeyError):
            LoRAModel.from_pretrained(other, path)

    def test_facade_survives_deepcopy(self):
        import copy
        pt.seed(0)
        lm = LoRAModel(LlamaForCausalLM(llama_tiny()), LoRAConfig(r=2))
        lm2 = copy.deepcopy(lm)
        assert lm2.lora_config.r == 2
        ids = _ids()
        np.testing.assert_allclose(np.asarray(lm2(ids)),
                                   np.asarray(lm(ids)), atol=1e-6)


class TestLoRADropout:
    def test_dropout_masks_vary_per_step(self, tmp_path):
        """Under the Trainer, stepno-folded keys give a different dropout
        mask (hence different grads) at different step numbers."""
        from paddle_tpu.trainer import Trainer, TrainingArguments

        pt.seed(0)
        model = LlamaForCausalLM(llama_tiny())
        apply_lora(model, LoRAConfig(r=4, lora_alpha=8, lora_dropout=0.5))
        # B=0 at init makes the dropout delta identically zero; give the
        # adapters weight so the mask actually reaches the loss
        for k, v in lora_state_dict(model).items():
            if k.endswith("lora_B"):
                model._set_by_path(k, jnp.full_like(v, 0.05))
        tr = Trainer(model, pt.optimizer.SGD(learning_rate=0.0),
                     TrainingArguments(output_dir=str(tmp_path),
                                       resume_from_checkpoint=False))
        tr._opt_state = tr.optimizer.init(
            {k: tr._params[k] for k in tr._trainable_keys})
        step = tr._build_step()
        ids = _ids(2, 16)
        # lr=0: params are numerically unchanged, so chaining the donated
        # state through the calls keeps every loss comparable
        p, s = dict(tr._params), tr._opt_state
        p, s, _, l0 = step(p, s, None, jnp.int32(0), ids)
        p, s, _, l1 = step(p, s, None, jnp.int32(1), ids)
        p, s, _, l0b = step(p, s, None, jnp.int32(0), ids)
        assert float(l0) != float(l1)       # mask varies across steps
        assert float(l0) == float(l0b)      # ...but is step-deterministic

    def test_evaluate_runs_without_dropout(self, tmp_path):
        """evaluate() traces in eval mode: adapter dropout must be OFF, so
        the eval loss equals the deterministic no-dropout loss."""
        from paddle_tpu.models.llama import causal_lm_loss
        from paddle_tpu.trainer import Trainer, TrainingArguments

        pt.seed(0)
        model = LlamaForCausalLM(llama_tiny())
        apply_lora(model, LoRAConfig(r=4, lora_alpha=8, lora_dropout=0.5))
        for k, v in lora_state_dict(model).items():
            if k.endswith("lora_B"):
                model._set_by_path(k, jnp.full_like(v, 0.05))
        ids = _ids(2, 16)
        tr = Trainer(model, pt.optimizer.SGD(learning_rate=0.0),
                     TrainingArguments(output_dir=str(tmp_path),
                                       resume_from_checkpoint=False),
                     eval_dataloader=[ids])
        got = tr.evaluate()
        model.eval()
        fn, p = model.functional()
        want = float(causal_lm_loss(fn(p, ids), ids))
        model.train()
        assert abs(got - want) < 1e-5
        assert model.training  # restored
