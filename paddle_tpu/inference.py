"""Inference predictor (reference: paddle.inference.Predictor /
paddle/fluid/inference/api — config + predictor over an optimized program;
PaddleNLP's llm/predict/predictor.py for the LLM path).

TPU-native: the "optimized program" is a cached jax.jit of the model's
functional form with donated weights left on device; optional weight-only
quantization at load (C17). One Predictor == one compiled engine per input
shape, the same mental model as the reference's shape-bucketed engines.
"""
from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp


class Config:
    """paddle.inference.Config parity surface (the knobs that matter on
    TPU: dtype, quantization)."""

    def __init__(self, model_path: Optional[str] = None):
        self.model_path = model_path
        self.dtype = None                         # None = keep model dtype
        self.quant_bits: Optional[int] = None     # 8 / 4 / None
        self.quant_skip = ["lm_head", "embed"]

    def enable_weight_only_quant(self, bits: int = 8):
        self.quant_bits = bits
        return self

    def set_dtype(self, dtype):
        self.dtype = dtype
        return self


class Predictor:
    """Wraps a Layer for serving: one jitted engine (jax.jit's own cache
    handles per-shape retraces), optional dtype cast + PTQ at load, state
    kept on device."""

    def __init__(self, model, config: Optional[Config] = None):
        self.config = config or Config()
        self.model = model
        if self.config.dtype is not None:
            model.to(dtype=self.config.dtype)
        if self.config.quant_bits:
            from .quant import quantize_model
            quantize_model(model, bits=self.config.quant_bits,
                           skip=self.config.quant_skip)
        model.eval()
        self._fn, self._params = model.functional()
        # weights live on device once; every run reuses them
        self._params = jax.device_put(self._params)
        self._engine = jax.jit(self._fn)

    def run(self, *inputs):
        """Eager-looking predict: inputs are host arrays; returns device
        outputs (np.asarray them for host use)."""
        args = tuple(jnp.asarray(x) for x in inputs)
        return self._engine(self._params, *args)

    __call__ = run

    def generate(self, input_ids, **kwargs):
        """Autoregressive generation with the model's KV cache path."""
        return self.model.generate(jnp.asarray(input_ids), **kwargs)

    @classmethod
    def from_checkpoint(cls, model_factory: Callable[[], Any], path: str,
                        config: Optional[Config] = None):
        """Build model, load weights (paddle_tpu.load), wrap."""
        from .checkpoint import load
        model = model_factory()
        model.set_state_dict(load(path))
        return cls(model, config)


def create_predictor(config: Config, model=None):
    """paddle.inference.create_predictor parity."""
    if model is None:
        raise ValueError("paddle_tpu predictor needs the model object "
                         "(graph serialization comes via jit.to_static AOT)")
    return Predictor(model, config)
