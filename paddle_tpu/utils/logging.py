"""Metric logging (reference: VisualDL's LogWriter add_scalar API +
PaddleNLP Trainer's logging integration).

TPU-native: a dependency-free JSONL writer (one line per record:
{"step": n, "tag": ..., "value": ...,"wall": t}) that any dashboard can
tail; plus an in-memory scalar history for programmatic access."""
from __future__ import annotations

import json
import os
import time
from collections import defaultdict
from typing import Dict


class LogWriter:
    def __init__(self, logdir: str = "runs", filename: str = "metrics.jsonl"):
        self.logdir = logdir
        os.makedirs(logdir, exist_ok=True)
        self.path = os.path.join(logdir, filename)
        self._fh = open(self.path, "a", buffering=1)  # line-buffered
        self.history: Dict[str, list] = defaultdict(list)

    def add_scalar(self, tag: str, value, step: int):
        value = float(value)
        self.history[tag].append((step, value))
        self._fh.write(json.dumps({"step": int(step), "tag": tag,
                                   "value": value, "wall": time.time()}) + "\n")

    def add_scalars(self, metrics: Dict[str, float], step: int):
        for tag, v in metrics.items():
            self.add_scalar(tag, v, step)

    def close(self):
        self._fh.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


_writers: Dict[str, LogWriter] = {}


def get_logger(logdir: str = "runs") -> LogWriter:
    """Shared writer PER LOGDIR. The old singleton was keyed on nothing,
    so every call after the first silently ignored ``logdir`` and wrote
    into whichever directory happened to be asked for first."""
    key = os.path.abspath(logdir)
    writer = _writers.get(key)
    if writer is None or writer._fh.closed:
        writer = _writers[key] = LogWriter(logdir)
    return writer
