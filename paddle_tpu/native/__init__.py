"""paddle_tpu.native — ctypes bindings for the C++ runtime (reference:
Paddle's C++ core: BlockingQueue, DataLoader workers, pinned staging
allocator; here rebuilt as a small host-side runtime that feeds the TPU).

Build: `make -C paddle_tpu/native` (or it auto-builds on first import if a
compiler is present). Everything degrades to pure-Python fallbacks when the
shared library is unavailable — `available()` reports which path is live.
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading

import numpy as np

_DIR = os.path.dirname(os.path.abspath(__file__))
_SO = os.path.join(_DIR, "libpaddle_tpu_native.so")
_lib = None
_lib_lock = threading.Lock()
_build_attempted = False


def _try_build() -> bool:
    global _build_attempted
    if _build_attempted:
        return os.path.exists(_SO)
    _build_attempted = True
    try:
        subprocess.run(["make", "-C", _DIR], check=True,
                       capture_output=True, timeout=120)
        return True
    except Exception:
        return False


def _bind(lib):
    c = ctypes
    u64, sz, vp = c.c_uint64, c.c_size_t, c.c_void_p
    sigs = {
        "pt_arena_create": ([sz], vp),
        "pt_arena_alloc": ([vp, sz], vp),
        "pt_arena_reset": ([vp], None),
        "pt_arena_used": ([vp], sz),
        "pt_arena_destroy": ([vp], None),
        "pt_pool_create": ([c.c_int], vp),
        "pt_pool_destroy": ([vp], None),
        "pt_pool_size": ([vp], c.c_int),
        "pt_gather_stack": ([vp, c.POINTER(vp), sz, sz, vp], None),
        "pt_gather_pad": ([vp, c.POINTER(vp), c.POINTER(sz), sz, sz, sz,
                           vp, vp], None),
        "pt_ring_create": ([sz], vp),
        "pt_ring_destroy": ([vp], None),
        "pt_ring_push": ([vp, u64, c.c_int], c.c_int),
        "pt_ring_pop": ([vp, c.POINTER(u64), c.c_int], c.c_int),
        "pt_ring_close": ([vp], None),
        "pt_ring_size": ([vp], sz),
        "pt_tok_create": ([c.c_char_p, sz, c.c_int32], vp),
        "pt_tok_destroy": ([vp], None),
        "pt_tok_vocab_size": ([vp], sz),
        "pt_tok_encode": ([vp, c.c_char_p, sz, c.POINTER(c.c_int32), sz], sz),
        "pt_tok_encode_batch": ([vp, vp, c.c_char_p, c.POINTER(sz), sz,
                                 c.POINTER(c.c_int32), sz, c.c_int32,
                                 c.POINTER(sz)], None),
        "pt_bpe_create": ([c.c_int32, c.c_char_p, c.POINTER(c.c_int32),
                           c.POINTER(c.c_int32), c.c_int32, c.c_int32,
                           c.POINTER(c.c_int32), c.POINTER(c.c_int32),
                           c.POINTER(c.c_int32)], vp),
        "pt_bpe_destroy": ([vp], None),
        "pt_bpe_encode_words": ([vp, c.c_char_p, c.POINTER(c.c_int32),
                                 c.c_int32, c.POINTER(c.c_int32),
                                 c.c_int64, c.POINTER(c.c_int32)],
                                c.c_int64),
    }
    for name, (argtypes, restype) in sigs.items():
        fn = getattr(lib, name)
        fn.argtypes = argtypes
        fn.restype = restype
    return lib


def lib():
    """The loaded native library, or None."""
    global _lib
    if _lib is not None:
        return _lib
    with _lib_lock:
        if _lib is not None:
            return _lib
        if not os.path.exists(_SO) and not _try_build():
            return None
        try:
            _lib = _bind(ctypes.CDLL(_SO))
        except AttributeError:
            # stale .so from before a symbol was added (ctypes raises
            # AttributeError for missing symbols): rebuild once, then
            # either bind cleanly or degrade to the Python paths
            global _build_attempted
            _build_attempted = False
            try:
                os.remove(_SO)
            except OSError:
                pass
            if not _try_build():
                return None
            try:
                _lib = _bind(ctypes.CDLL(_SO))
            except (OSError, AttributeError):
                return None
        except OSError:
            return None
    return _lib


def available() -> bool:
    return lib() is not None


class ThreadPool:
    """Native pthread pool handle."""

    def __init__(self, num_threads: int = 0):
        L = lib()
        if L is None:
            raise RuntimeError("native runtime not built")
        self._lib = L
        self._h = L.pt_pool_create(num_threads or (os.cpu_count() or 4))

    def __del__(self):
        if getattr(self, "_h", None):
            self._lib.pt_pool_destroy(self._h)
            self._h = None


class StagingArena:
    """Page-aligned host staging arena; batches assembled here are handed
    straight to jax.device_put (the pinned-buffer analogue on TPU hosts)."""

    def __init__(self, capacity_bytes: int = 1 << 28):
        L = lib()
        if L is None:
            raise RuntimeError("native runtime not built")
        self._lib = L
        self._h = L.pt_arena_create(capacity_bytes)
        if not self._h:
            raise MemoryError(f"arena of {capacity_bytes} bytes")
        self.capacity = capacity_bytes

    def alloc(self, nbytes: int, dtype, shape):
        """Allocate a numpy view inside the arena (no copy on reset)."""
        ptr = self._lib.pt_arena_alloc(self._h, nbytes)
        if not ptr:
            raise MemoryError("staging arena exhausted; call reset()")
        buf = (ctypes.c_char * nbytes).from_address(ptr)
        # the view's base chain holds `buf`; pinning the arena on it keeps
        # the slab alive as long as ANY view exists (prefetch queues hand
        # views to other threads after this thread's locals are gone)
        buf._arena_ref = self
        return np.frombuffer(buf, dtype=dtype).reshape(shape)

    def used(self) -> int:
        return self._lib.pt_arena_used(self._h)

    def reset(self):
        """Recycle the slab (invalidates prior views — only call once the
        previous step's device_put has completed)."""
        self._lib.pt_arena_reset(self._h)

    def __del__(self):
        if getattr(self, "_h", None):
            self._lib.pt_arena_destroy(self._h)
            self._h = None


def gather_stack(pool: ThreadPool, items, arena: StagingArena | None = None):
    """Parallel np.stack of same-shape contiguous arrays via the native
    pool. With an arena, the batch lands in staging memory."""
    items = [np.ascontiguousarray(a) for a in items]
    first = items[0]
    if any(a.shape != first.shape or a.dtype != first.dtype
           for a in items[1:]):
        raise ValueError("gather_stack needs same-shape/dtype items "
                         "(like np.stack)")
    n = len(items)
    out_shape = (n,) + first.shape
    nbytes = first.nbytes * n
    if arena is not None:
        dst = arena.alloc(nbytes, first.dtype, out_shape)
    else:
        dst = np.empty(out_shape, first.dtype)
    srcs = (ctypes.c_void_p * n)(
        *[a.ctypes.data_as(ctypes.c_void_p).value for a in items])
    lib().pt_gather_stack(pool._h, srcs, n, first.nbytes,
                          dst.ctypes.data_as(ctypes.c_void_p))
    return dst


def gather_pad(pool: ThreadPool, seqs, max_len: int, pad_value=0,
               dtype=np.int32, arena: StagingArena | None = None):
    """Ragged int sequences -> padded [n, max_len] batch (LLM collate)."""
    dtype = np.dtype(dtype)
    seqs = [np.ascontiguousarray(s, dtype=dtype) for s in seqs]
    n = len(seqs)
    if arena is not None:
        dst = arena.alloc(n * max_len * dtype.itemsize, dtype, (n, max_len))
    else:
        dst = np.empty((n, max_len), dtype)
    srcs = (ctypes.c_void_p * n)(
        *[s.ctypes.data_as(ctypes.c_void_p).value for s in seqs])
    lens = (ctypes.c_size_t * n)(*[len(s) for s in seqs])
    pad = np.asarray(pad_value, dtype)
    lib().pt_gather_pad(pool._h, srcs, lens, n, max_len, dtype.itemsize,
                        pad.ctypes.data_as(ctypes.c_void_p),
                        dst.ctypes.data_as(ctypes.c_void_p))
    return dst


class Ring:
    """Blocking MPMC ring of u64 handles: prefetch handoff without the
    Python queue's lock contention. Values are opaque (indices into a
    Python-side slot table)."""

    def __init__(self, capacity: int):
        L = lib()
        if L is None:
            raise RuntimeError("native runtime not built")
        self._lib = L
        self._h = L.pt_ring_create(capacity)

    def push(self, value: int, timeout_ms: int = -1) -> bool:
        r = self._lib.pt_ring_push(self._h, value, timeout_ms)
        if r == -1:
            raise TimeoutError("ring push timed out")
        return r == 1

    def pop(self, timeout_ms: int = -1):
        out = ctypes.c_uint64()
        r = self._lib.pt_ring_pop(self._h, ctypes.byref(out), timeout_ms)
        if r == -1:
            raise TimeoutError("ring pop timed out")
        return out.value if r == 1 else None

    def close(self):
        self._lib.pt_ring_close(self._h)

    def __len__(self):
        return self._lib.pt_ring_size(self._h)

    def __del__(self):
        if getattr(self, "_h", None):
            self._lib.pt_ring_destroy(self._h)
            self._h = None


class Tokenizer:
    """Greedy longest-match trie tokenizer over an id-ordered vocab list
    (tokenizer-lite: fast data prep without a Python inner loop)."""

    def __init__(self, vocab, unk_id: int = 0):
        L = lib()
        if L is None:
            raise RuntimeError("native runtime not built")
        self._lib = L
        if isinstance(vocab, (list, tuple)):
            blob = "\n".join(vocab).encode("utf-8")
        else:
            blob = vocab if isinstance(vocab, bytes) else str(vocab).encode()
        self._h = L.pt_tok_create(blob, len(blob), unk_id)
        self.vocab_size = L.pt_tok_vocab_size(self._h)

    def encode(self, text: str, max_len: int = 4096):
        raw = text.encode("utf-8")
        out = np.empty(max_len, np.int32)
        n = self._lib.pt_tok_encode(
            self._h, raw, len(raw),
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)), max_len)
        return out[:n].copy()

    def encode_batch(self, texts, pool: ThreadPool, max_len: int = 512,
                     pad_id: int = 0):
        raws = [t.encode("utf-8") for t in texts]
        blob = b"".join(raws)
        n = len(raws)
        offsets = np.zeros(n + 1, np.uintp)
        np.cumsum([len(r) for r in raws], out=offsets[1:])
        out = np.empty((n, max_len), np.int32)
        lens = np.empty(n, np.uintp)
        self._lib.pt_tok_encode_batch(
            self._h, pool._h, blob,
            offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_size_t)), n,
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)), max_len,
            pad_id, lens.ctypes.data_as(ctypes.POINTER(ctypes.c_size_t)))
        return out, lens.astype(np.int64)

    def __del__(self):
        if getattr(self, "_h", None):
            self._lib.pt_tok_destroy(self._h)
            self._h = None
